"""Fleet worker tracking for the router (docs/FLEET.md).

One background thread polls every worker's ``/healthz`` on an interval
and keeps, per worker:

- **liveness** — a worker is live until ``fail_after`` consecutive
  probe/proxy failures, and rejoins on the first success (the router
  also feeds it in-band results via :meth:`FleetTracker.note_result`,
  so a SIGKILL'd worker leaves the routing set at the first failed
  proxy, not a poll interval later);
- **the warmth ledger** — the ``cache.warm_buckets`` affinity ledger
  (bucket keys the worker has solved) plus the lane-executable view,
  feeding the router's warm-first ranking;
- **cooldowns** — ``Retry-After`` promises the worker made on 503
  sheds, scoped worker-wide (queue_full and friends) or per bucket
  (circuit_open carries its bucket in the shed body), so the router
  honors the backoff contract per worker while other workers absorb
  the traffic.

The tracker never imports jax and tolerates any worker response shape:
a peer running an older build simply reports no ledger and gets pure
rendezvous routing.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request

from ..obs import log as _olog

__all__ = ["WorkerState", "FleetTracker"]

DEFAULT_INTERVAL_S = 2.0
DEFAULT_TIMEOUT_S = 5.0
DEFAULT_FAIL_AFTER = 2


class WorkerState:
    """One worker's live view. All mutation happens under the owning
    tracker's lock."""

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        self.alive = True  # optimistic: route until proven dead
        self.fails = 0
        self.polls = 0
        self.last_ok: float | None = None
        self.warm: set = set()
        self.queue: dict = {}
        self.persistent: dict = {}
        self.identity: dict = {}
        # scope -> unix ts before which this worker must not be sent
        # that scope's traffic; scope None = worker-wide
        self.cooldown: dict = {}

    def cooling_s(self, key, now: float) -> float:
        """Seconds this worker is still honoring a Retry-After for
        ``key`` (bucket tuple or None); 0.0 = ready."""
        until = max(self.cooldown.get(None, 0.0),
                    self.cooldown.get(key, 0.0) if key is not None
                    else 0.0)
        return max(until - now, 0.0)

    def view(self, now: float) -> dict:
        return {
            "url": self.url,
            "alive": self.alive,
            "fails": self.fails,
            "polls": self.polls,
            "age_s": (round(now - self.last_ok, 3)
                      if self.last_ok else None),
            "warm_buckets": sorted(list(k) for k in self.warm),
            "queue": self.queue,
            "persistent_cache": self.persistent,
            "cooldowns": {
                str(k): round(v - now, 3)
                for k, v in self.cooldown.items() if v > now
            },
        }


class FleetTracker:
    """Polls workers' ``/healthz`` and serves the router's routing
    inputs. ``fetch`` is injectable (url -> healthz dict) so tests
    drive membership and warmth without sockets."""

    def __init__(self, urls: list[str], *,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 fail_after: int = DEFAULT_FAIL_AFTER,
                 fetch=None):
        self._lock = threading.Lock()  # kao: guards(_workers, polls_total, poll_errors_total, _thread)
        self._workers = {u.rstrip("/"): WorkerState(u)
                         for u in urls}
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.fail_after = max(1, int(fail_after))
        self._fetch = fetch or self._fetch_http
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.polls_total = 0
        self.poll_errors_total = 0

    # -- membership --------------------------------------------------

    def urls(self) -> list[str]:
        with self._lock:
            return list(self._workers)

    def live(self) -> list[str]:
        """Workers currently routable. When EVERY worker looks dead,
        all of them come back — a wrong 'all dead' verdict (a router-
        side network blip) must degrade to trying anyway, not to
        refusing every request."""
        with self._lock:
            up = [u for u, w in self._workers.items() if w.alive]
            return up or list(self._workers)

    def warm_map(self) -> dict:
        with self._lock:
            return {u: set(w.warm) for u, w in self._workers.items()}

    def state(self, url: str) -> WorkerState | None:
        with self._lock:
            return self._workers.get(url.rstrip("/"))

    # -- in-band evidence from the proxy path ------------------------

    def note_result(self, url: str, ok: bool) -> None:
        """The router reports each proxy attempt: a failure is
        evidence as strong as a failed poll (SIGKILL leaves the set at
        the first failed request), a success instantly rejoins."""
        with self._lock:
            w = self._workers.get(url.rstrip("/"))
            if w is None:
                return
            if ok:
                was_dead = not w.alive
                w.fails, w.alive, w.last_ok = 0, True, time.time()
                if was_dead:
                    _olog.log("router_worker_rejoin", worker=w.url)
            else:
                w.fails += 1
                if w.fails >= self.fail_after and w.alive:
                    w.alive = False
                    _olog.warn("router_worker_down", worker=w.url,
                               fails=w.fails)

    def note_retry_after(self, url: str, seconds: float,
                         bucket=None) -> None:
        """Record a worker's Retry-After promise: worker-wide, or
        scoped to the bucket the shed body named (circuit_open)."""
        with self._lock:
            w = self._workers.get(url.rstrip("/"))
            if w is None:
                return
            scope = tuple(bucket) if bucket is not None else None
            until = time.time() + max(float(seconds), 0.0)
            if until > w.cooldown.get(scope, 0.0):
                w.cooldown[scope] = until

    def cooling_s(self, url: str, key) -> float:
        now = time.time()
        with self._lock:
            w = self._workers.get(url.rstrip("/"))
            return w.cooling_s(key, now) if w is not None else 0.0

    # -- polling -----------------------------------------------------

    def _fetch_http(self, url: str) -> dict:
        # background liveness poll: there is no client request (and so
        # no causal context) to propagate on this hop
        # kao: disable=KAO111 -- read-only health poll, no active request
        with urllib.request.urlopen(
            f"{url}/healthz", timeout=self.timeout_s
        ) as resp:
            return json.loads(resp.read().decode("utf-8", "replace"))

    def poll_once(self) -> None:
        for url in self.urls():
            try:
                hz = self._fetch(url)
            except Exception:
                with self._lock:
                    self.polls_total += 1
                    self.poll_errors_total += 1
                self.note_result(url, ok=False)
                continue
            cache = (hz.get("cache") or {}) if isinstance(hz, dict) \
                else {}
            warm = {
                tuple(int(x) for x in k)
                for k in (cache.get("warm_buckets") or [])
                if isinstance(k, (list, tuple))
            }
            with self._lock:
                self.polls_total += 1
                w = self._workers.get(url)
                if w is None:
                    continue
                w.polls += 1
                w.warm = warm
                w.queue = hz.get("queue") or {}
                w.persistent = cache.get("persistent_cache") or {}
                obs = hz.get("observability") or {}
                w.identity = obs.get("worker") or {}
            self.note_result(url, ok=True)

    def start(self) -> None:
        def run():
            while not self._stop.wait(self.interval_s):
                try:
                    self.poll_once()
                except Exception:  # pragma: no cover - belt only
                    pass

        # check-and-reserve UNDER the lock (KAO116): two racing
        # start() calls both saw None here and spawned two pollers —
        # double poll traffic and double-counted polls_total forever
        with self._lock:
            if self._thread is not None:
                return
            self._thread = thread = threading.Thread(
                target=run, daemon=True, name="kao-router-health",
            )
        # prime OUTSIDE the lock: the synchronous first poll is an
        # HTTP round-trip per worker and must not convoy the routing
        # reads (KAO117's blocking-under-lock class)
        self.poll_once()  # prime synchronously so boot routes warm
        thread.start()

    def stop(self) -> None:
        self._stop.set()

    def snapshot(self) -> dict:
        now = time.time()
        with self._lock:
            return {
                "workers": {
                    u: w.view(now) for u, w in self._workers.items()
                },
                "live": [u for u, w in self._workers.items()
                         if w.alive],
                "polls_total": self.polls_total,
                "poll_errors_total": self.poll_errors_total,
                "interval_s": self.interval_s,
            }
