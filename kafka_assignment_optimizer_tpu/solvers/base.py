"""Solver backend registry.

The reference has exactly one backend — the external native lp_solve MILP
solver (``/root/reference/README.md:135-137``). This build keeps that
*role* as the reference path and adds alternatives behind one interface
(``--solver=...`` per BASELINE.json:5):

- ``milp``     exact 0-1 ILP via scipy/HiGHS (native C++, in-process)
- ``lp_solve`` the reference's solver via subprocess, when installed
- ``native``   bundled C++ branch-and-bound (exact, specialized)
- ``tpu``      JAX/Pallas vmapped annealing engine (the deliverable)
- ``auto``     exact solver for small instances, ``tpu`` at scale
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Protocol

import numpy as np

from ..models.instance import ProblemInstance


@dataclass
class SolveResult:
    """A solved candidate in broker-index space plus solver telemetry."""

    a: np.ndarray  # [P, R] int32 broker indices, slot 0 = leader
    solver: str
    wall_clock_s: float = 0.0
    objective: int | None = None  # preservation weight achieved
    optimal: bool = False  # proven optimal (exact backends)
    stats: dict = field(default_factory=dict)


class Solver(Protocol):
    def __call__(self, inst: ProblemInstance, **kwargs) -> SolveResult: ...


_REGISTRY: dict[str, Callable[..., SolveResult]] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


def available_solvers() -> list[str]:
    _load_all()
    return sorted(_REGISTRY)


def get_solver(name: str) -> Callable[..., SolveResult]:
    _load_all()
    if name == "auto":
        return _auto_solve
    if name not in _REGISTRY:
        detail = ""
        if name in _LOAD_ERRORS:
            detail = f"; backend failed to import:\n{_LOAD_ERRORS[name]}"
        raise KeyError(
            f"unknown solver {name!r}; available: {available_solvers()}{detail}"
        )
    return _REGISTRY[name]


_LOAD_ERRORS: dict[str, str] = {}


def _load_all() -> None:
    # import for registration side effects; optional backends degrade softly
    # but record *why* they are unavailable so errors stay diagnosable
    import importlib
    import traceback

    from . import milp  # noqa: F401

    for name, mod in [
        ("lp_solve", ".lp"),
        ("native", ".native"),
        ("tpu", ".tpu.engine"),
    ]:
        if name in _REGISTRY or name in _LOAD_ERRORS:
            continue
        try:
            importlib.import_module(mod, package=__package__)
        except Exception:
            _LOAD_ERRORS[name] = traceback.format_exc(limit=3)


def resolve_solver(name: str, inst: ProblemInstance) -> str:
    """The concrete registry solver ``name`` will run on ``inst``.

    ``"auto"`` resolves deterministically from the instance size (exact
    ILP when the variable space is small enough to be instant, the TPU
    engine otherwise); every other name passes through. The serving
    path keys its per-bucket gates (circuit breaker, checkpoint
    auto-resume, coalescing, profiling budget) on THIS, not on the
    requested string — a defaulted ``"auto"`` request at production
    scale runs the TPU engine and must get the same per-cluster
    isolation as an explicit ``"solver": "tpu"``."""
    if name != "auto":
        return name
    _load_all()
    nvars = 2 * inst.num_brokers * inst.num_parts
    if nvars <= 20_000 or "tpu" not in _REGISTRY:
        return "milp"
    return "tpu"


def _auto_solve(inst: ProblemInstance, **kw) -> SolveResult:
    """Exact ILP when the variable space is small enough to be instant;
    the TPU engine otherwise (resolution shared with resolve_solver)."""
    return _REGISTRY[resolve_solver("auto", inst)](inst, **kw)


