"""Greedy repair: turn the current assignment into a feasible-ish warm
start for the annealing engine.

The search engine's population is seeded *from the current assignment* so
the zero-move plan (or its nearest feasible neighbour) is in the basin from
step one — the representation-level equivalent of the reference objective's
"more weight to existing assignments" trick
(``/root/reference/README.md:116-120``). Pure numpy, host-side; broker
selection is vectorized so a 256-broker / 10k-partition decommission seeds
in well under a second.

Repairs, in order:
1. fill null slots (removed brokers / RF increase);
2. spread partitions violating rack diversity (``README.md:178-180``);
3. drain brokers above the replica band ceiling / feed below the floor
   (``README.md:158-161``), and the same per rack (``README.md:173-176``);
4. rebalance leadership into the leader band via zero-move leader swaps
   (``README.md:163-166``).

Each unit repair moves one replica (or swaps one leader), choosing the
donor slot with the least preservation weight and the recipient broker
with the least load — keeping the seed near the move-count optimum the
exact backends find. Residual violations (rare, small) are the annealing
engine's job.

Two implementations share this module (docs/CONSTRUCTOR.md, the
swappable constructor interface in ``solvers.tpu.constructor``):

- ``_Repair`` — the ORIGINAL per-partition Python implementation, kept
  verbatim as the parity oracle and the operator's fallback rung
  (``KAO_CONSTRUCTOR=legacy``).
- ``_RepairVec`` — the vectorized default: no per-slot Python set
  bookkeeping (the legacy ``slots_of`` build alone walks P*R slots in
  Python — ~150k iterations at the 50k-partition jumbo), O(1)
  membership tests via a scatter-built count matrix, cached
  lexsort-ordered donor lists for the band-repair relocations, and the
  leader-chain BFS (phase 3) on flat numpy edge arrays instead of a
  per-partition adjacency-dict build per augmentation. Decisions are
  deliberately bit-identical to the legacy path — same donor order,
  same recipient lexsort, same BFS scan order — so the parity pin in
  ``tests/test_constructor_vec.py`` is plan-for-plan, not merely
  rank-for-rank.
"""

from __future__ import annotations

import numpy as np

from ...models.instance import ProblemInstance
from . import constructor as _constructor


class _Repair:
    """Legacy per-partition implementation — the parity oracle. Do not
    optimize in place; speedups belong in :class:`_RepairVec` so this
    path keeps witnessing the original semantics."""

    def __init__(self, inst: ProblemInstance):
        self.inst = inst
        B, K, P, R = inst.num_brokers, inst.num_racks, inst.num_parts, inst.max_rf
        self.B, self.K, self.P, self.R = B, K, P, R
        self.rf = inst.rf
        self.rack = inst.rack_of_broker  # [B+1]
        self.a = inst.a0.copy()
        valid = inst.slot_valid
        self.valid = valid
        flat = np.where(valid, self.a, B)
        self.cnt = np.bincount(flat.ravel(), minlength=B + 1)[:B].astype(np.int64)
        self.lcnt = np.bincount(
            np.where(self.rf > 0, self.a[:, 0], B), minlength=B + 1
        )[:B].astype(np.int64)
        self.rcnt = np.bincount(self.rack[flat].ravel(), minlength=K + 1)[
            :K
        ].astype(np.int64)
        self.prc = np.zeros((P, K), dtype=np.int64)
        rows = np.repeat(np.arange(P), R)
        rk = self.rack[flat].ravel()
        np.add.at(self.prc, (rows[rk < K], rk[rk < K]), 1)
        self._init_slots()

    def _init_slots(self) -> None:
        # replica slots per broker, for donor selection
        self.slots_of: list[set[tuple[int, int]]] = [
            set() for _ in range(self.B)
        ]
        for p in range(self.P):
            for s in range(int(self.rf[p])):
                b = int(self.a[p, s])
                if b < self.B:
                    self.slots_of[b].add((p, s))

    # -- primitives -----------------------------------------------------
    def weight(self, p: int, s: int, b: int) -> int:
        if b >= self.B:
            return 0
        w = self.inst.w_leader if s == 0 else self.inst.w_follower
        return int(w[p, b])

    def set_slot(self, p: int, s: int, b_new: int) -> None:
        b_old = int(self.a[p, s])
        if b_old < self.B:
            self.cnt[b_old] -= 1
            self.rcnt[self.rack[b_old]] -= 1
            self.prc[p, self.rack[b_old]] -= 1
            if s == 0:
                self.lcnt[b_old] -= 1
            self.slots_of[b_old].discard((p, s))
        self.a[p, s] = b_new
        if b_new < self.B:
            self.cnt[b_new] += 1
            self.rcnt[self.rack[b_new]] += 1
            self.prc[p, self.rack[b_new]] += 1
            if s == 0:
                self.lcnt[b_new] += 1
            self.slots_of[b_new].add((p, s))

    def _note_swap(self, p: int, s: int, bl: int, bf: int) -> None:
        """Bookkeeping hook for a leader<->follower swap of partition
        ``p`` slots (0, s): brokers keep their partition membership, only
        the slot indices trade."""
        self.slots_of[bl].discard((p, 0))
        self.slots_of[bl].add((p, s))
        self.slots_of[bf].discard((p, s))
        self.slots_of[bf].add((p, 0))

    def choose_broker(self, p: int, allowed: np.ndarray) -> int:
        """Best recipient among `allowed` (bool mask [B]) for a replica of
        partition p: lexicographically avoid new violations, prefer
        under-floor brokers/racks, then least load, then lowest index."""
        inst, rack = self.inst, self.rack[: self.B]
        if not allowed.any():
            return -1
        div_bad = self.prc[p, rack] + 1 > inst.part_rack_hi[p]
        brk_bad = self.cnt + 1 > inst.broker_hi
        rck_bad = self.rcnt[rack] + 1 > inst.rack_hi[rack]
        brk_under = self.cnt < inst.broker_lo
        rck_under = self.rcnt[rack] < inst.rack_lo[rack]
        order = np.lexsort(
            (
                np.arange(self.B),
                self.cnt,
                ~rck_under,
                ~brk_under,
                rck_bad,
                brk_bad,
                div_bad,
                ~allowed,  # excluded brokers sort last
            )
        )
        best = int(order[0])
        return best if allowed[best] else -1

    def used_mask(self, p: int) -> np.ndarray:
        m = np.zeros(self.B, dtype=bool)
        for s in range(int(self.rf[p])):
            b = int(self.a[p, s])
            if b < self.B:
                m[b] = True
        return m

    # -- repair phases ---------------------------------------------------
    def fill_nulls(self) -> None:
        null_rows = np.flatnonzero(
            (np.where(self.inst.slot_valid, self.a, 0) >= self.B).any(1)
        )
        for p in null_rows:
            for s in range(int(self.rf[p])):
                if int(self.a[p, s]) < self.B:
                    continue
                b = self.choose_broker(p, ~self.used_mask(p))
                if b >= 0:
                    self.set_slot(p, int(s), b)

    def fix_diversity(self) -> None:
        inst, rack = self.inst, self.rack
        bad = np.flatnonzero((self.prc > inst.part_rack_hi[:, None]).any(1))
        for p in bad:
            for _ in range(self.R + 1):
                over = np.flatnonzero(self.prc[p] > inst.part_rack_hi[p])
                if over.size == 0:
                    break
                k = int(over[0])
                slots = [
                    s
                    for s in range(int(self.rf[p]))
                    if int(rack[self.a[p, s]]) == k
                ]
                s = min(slots, key=lambda s: (self.weight(p, s, int(self.a[p, s])), s))
                headroom = self.prc[p, rack[: self.B]] < inst.part_rack_hi[p]
                b = self.choose_broker(p, headroom & ~self.used_mask(p))
                if b < 0:
                    break
                self.set_slot(p, int(s), b)

    def relocate_one(self, src: int, dst_mask: np.ndarray) -> bool:
        """Move the least-weight replica off `src` to the best allowed
        broker. Tries donor slots cheapest-first, and keeps scanning past
        placements that would break per-partition rack diversity, taking
        one only as a last resort."""
        inst, rack = self.inst, self.rack[: self.B]
        slots = sorted(
            self.slots_of[src],
            key=lambda ps: (self.weight(ps[0], ps[1], src), ps),
        )
        fallback: tuple[int, int, int] | None = None
        for p, s in slots:
            b = self.choose_broker(p, dst_mask & ~self.used_mask(p))
            if b < 0:
                continue
            same_rack = rack[b] == rack[src]  # donor replica leaves that rack
            if self.prc[p, rack[b]] + 1 - same_rack <= inst.part_rack_hi[p]:
                self.set_slot(p, s, b)
                return True
            if fallback is None:
                fallback = (p, s, b)
        if fallback is not None:
            self.set_slot(*fallback)
            return True
        return False

    def fix_bands(self, max_repairs: int) -> None:
        inst, B, K = self.inst, self.B, self.K
        rack = self.rack[:B]
        for _ in range(max_repairs):
            over_b = np.flatnonzero(self.cnt > inst.broker_hi)
            under_b = np.flatnonzero(self.cnt < inst.broker_lo)
            over_k = np.flatnonzero(self.rcnt > inst.rack_hi)
            under_k = np.flatnonzero(self.rcnt < inst.rack_lo)
            if not (len(over_b) or len(under_b) or len(over_k) or len(under_k)):
                break
            if len(over_b):
                src = int(over_b[np.argmax(self.cnt[over_b])])
                dst = self.cnt < inst.broker_hi
            elif len(under_b):
                dst = self.cnt < inst.broker_lo
                donors = self.cnt > inst.broker_lo
                if not donors.any():
                    break
                src = int(np.argmax(np.where(donors, self.cnt, -1)))
            elif len(over_k):
                k = int(over_k[0])
                members = rack == k
                src = int(np.argmax(np.where(members, self.cnt, -1)))
                dst = (rack != k) & (self.cnt < inst.broker_hi)
            else:
                k = int(under_k[0])
                dst = (rack == k) & (self.cnt < inst.broker_hi)
                donors = (rack != k) & (self.cnt > inst.broker_lo)
                if not donors.any():
                    break
                src = int(np.argmax(np.where(donors, self.cnt, -1)))
            if not dst.any() or not self.relocate_one(src, dst):
                break  # stuck; the annealer takes it from here

    def _batch_swaps(self, ordered_ps: np.ndarray, s_best: np.ndarray,
                     swap) -> int:
        """Apply the leader swaps for ``ordered_ps`` (best first) whose
        two brokers are untouched so far in this pass, so per-swap deltas
        computed against pass-start counts stay exact. Returns the last
        partition swapped (-1 if none, unreachable for a nonempty
        order)."""
        used = np.zeros(self.B + 1, dtype=bool)
        last = -1
        for p in ordered_ps.tolist():
            bl = int(self.a[p, 0])
            bf = int(self.a[p, int(s_best[p]) + 1])
            if used[bl] or used[bf]:
                continue
            used[bl] = used[bf] = True
            swap(p, int(s_best[p]) + 1)
            last = p
        return last

    def fix_leaders(self, max_repairs: int) -> None:
        inst, B = self.inst, self.B

        def swap(p: int, s: int) -> None:
            bl, bf = int(self.a[p, 0]), int(self.a[p, s])
            self.a[p, 0], self.a[p, s] = bf, bl
            self.lcnt[bl] -= 1
            self.lcnt[bf] += 1
            self._note_swap(p, s, bl, bf)

        # phase 1 — potential descent: repeatedly hand leadership of some
        # partition to its least-leading follower while that strictly
        # decreases sum(lcnt^2) (gain >= 2). Each swap drops the potential
        # by >= 2, so this terminates, and the balanced profile is its
        # global minimum — it walks straight through the multi-hop chains
        # the band-targeted phase below cannot see.
        if self.R > 1:
            foll = self.a[:, 1:]  # [P, R-1]
            foll_valid = (np.arange(1, self.R)[None, :] < self.rf[:, None]) & (
                foll < B
            )
            # batched descent: one swap per pass made the seed the jumbo
            # bottleneck (6.8 s of 11 at 50k partitions — thousands of
            # O(P*R) passes). Each pass now applies every gain>=2 swap
            # whose two brokers are untouched so far in the pass, so the
            # gains (computed against pass-start counts) stay exact and
            # the sum(lcnt^2) potential still strictly drops per swap.
            for _ in range(max_repairs):
                lead = self.a[:, 0]
                safe_lead = np.where(lead < B, lead, 0)
                l_of_lead = np.where(lead < B, self.lcnt[safe_lead], -1)
                f_cnt = np.where(foll_valid, self.lcnt[np.minimum(foll, B - 1)],
                                 np.iinfo(np.int64).max)
                s_best = np.argmin(f_cnt, axis=1)
                f_best = f_cnt[np.arange(self.P), s_best]
                gain = l_of_lead - np.where(f_best < np.iinfo(np.int64).max,
                                            f_best, np.iinfo(np.int64).max)
                cand = np.flatnonzero(gain >= 2)
                if cand.size == 0:
                    break
                cand = cand[np.argsort(-gain[cand], kind="stable")]
                self._batch_swaps(cand, s_best, swap)

        # phase 2 — band-violation descent with bounded neutral chaining:
        # vectorized over partitions, pick the leader<->follower swap with
        # the most negative band-violation delta; when only neutral swaps
        # exist (delta 0), take the one with the largest potential gain —
        # these walk the multi-hop chains (A->B then B->C) a strict descent
        # cannot, with a stall budget so cycles terminate.
        if self.R <= 1:
            return
        lo, hi = inst.leader_lo, inst.leader_hi
        foll = self.a[:, 1:]
        foll_valid = (np.arange(1, self.R)[None, :] < self.rf[:, None]) & (
            foll < B
        )

        def bv(c):
            return np.maximum(c - hi, 0) + np.maximum(lo - c, 0)

        stall = 0
        prev_p = -1  # neutral moves never revisit the partition just swapped
        for _ in range(max_repairs):
            if not (bv(self.lcnt) > 0).any():
                break
            lead = self.a[:, 0]
            safe_lead = np.where(lead < B, lead, 0)
            lc = self.lcnt[safe_lead]
            fc = np.where(
                foll_valid,
                self.lcnt[np.minimum(foll, B - 1)],
                np.iinfo(np.int64).max // 2,
            )
            s_best = np.argmin(fc, axis=1)
            f_best = fc[np.arange(self.P), s_best]
            usable = (lead < B) & (f_best < np.iinfo(np.int64).max // 2)
            # swap delta on total band violation (lead -1, follower +1)
            dviol = np.where(
                usable,
                bv(lc - 1) - bv(lc) + bv(f_best + 1) - bv(f_best),
                np.iinfo(np.int64).max // 2,
            )
            gain = np.where(usable, lc - f_best, np.iinfo(np.int64).min // 2)
            # batch every strictly-improving swap whose brokers are
            # untouched this pass (deltas stay exact; same jumbo-scale
            # reasoning as phase 1). Neutral chain moves remain one per
            # pass — their whole point is re-evaluating after each hop.
            improving = np.flatnonzero(dviol < 0)
            if improving.size:
                improving = improving[
                    np.lexsort((-gain[improving], dviol[improving]))
                ]
                prev_p = self._batch_swaps(improving, s_best, swap)
                stall = 0
                continue
            order = np.lexsort((-gain, dviol))
            p = int(order[0])
            if dviol[p] >= 0 and p == prev_p and self.P > 1:
                p = int(order[1])
            if dviol[p] == 0 and gain[p] >= 1 and stall < 64:
                # short neutral-chain budget: long chains are phase 3's
                # job (exact BFS augmentation); a 4*B budget burned ~7 s
                # of single-step O(P*R) passes at 50k partitions
                stall += 1
            else:
                break
            swap(p, int(s_best[p]) + 1)
            prev_p = p

        # phase 3 — BFS augmenting chains for what descent cannot reach
        # (implementation-swappable: _RepairVec overrides with the
        # flat-edge-array BFS; semantics identical)
        self._augment_leader_chains(max_repairs, lo, hi, swap)

    def _augment_leader_chains(self, max_repairs: int, lo: int, hi: int,
                               swap) -> None:
        """Phase 3 — BFS augmenting chains for what descent cannot reach:
        route one unit of leadership from an over-hi broker to any broker
        with headroom (or from any broker with slack to an under-lo one)
        through a path of leader<->follower swaps. Exact; each
        augmentation reduces total band violation by >= 1."""
        B = self.B
        for _ in range(max_repairs):
            over = np.flatnonzero(self.lcnt > hi)
            under = np.flatnonzero(self.lcnt < lo)
            if not (len(over) or len(under)):
                break
            # edges: leader broker -> (follower broker, partition, slot)
            adj: dict[int, list[tuple[int, int, int]]] = {}
            for p in range(self.P):
                L = int(self.a[p, 0])
                if L >= B:
                    continue
                for s in range(1, int(self.rf[p])):
                    F = int(self.a[p, s])
                    if F < B:
                        adj.setdefault(L, []).append((F, p, s))
            if len(over):
                # shed excess: over-hi broker -> any broker with headroom
                srcs = {int(b) for b in over}
                is_dst = lambda b: self.lcnt[b] < hi  # noqa: E731
            else:
                # feed deficit: any broker with slack -> the under-lo broker
                # (swaps shift leadership forward along the same edges)
                srcs = {b for b in range(B) if self.lcnt[b] > lo}
                dst_set = {int(b) for b in under}
                is_dst = lambda b: b in dst_set  # noqa: E731
            parent: dict[int, tuple[int, int, int]] = {}
            frontier = list(srcs)
            seen = set(srcs)
            goal = -1
            while frontier and goal < 0:
                nxt = []
                for u in frontier:
                    for (v, p, s) in adj.get(u, []):
                        if v in seen:
                            continue
                        seen.add(v)
                        parent[v] = (u, p, s)
                        if is_dst(v):
                            goal = v
                            break
                        nxt.append(v)
                    if goal >= 0:
                        break
                frontier = nxt
            if goal < 0:
                break  # disconnected; annealer's job
            # unwind: swap along the path so leadership shifts one hop per
            # edge. Path nodes (leader brokers) are distinct and each
            # partition has exactly one leader when adj was built, so every
            # edge's swap is still valid at unwind time — the augmentation
            # always applies in full, shifting one leader off the source.
            node = goal
            while node not in srcs:
                u, p, s = parent[node]
                swap(p, s)
                node = u


class _RepairVec(_Repair):
    """Vectorized implementation (the default): identical decisions to
    the legacy path — same donor ordering, same recipient lexsort, same
    BFS scan order — with the per-partition Python loops replaced by
    numpy array work (docs/CONSTRUCTOR.md has the layout)."""

    def _init_slots(self) -> None:
        # membership counts [P, B+1] built with one scatter-add instead
        # of the legacy P*R Python set loop; used_mask and the duplicate
        # guard read rows of this in O(B)
        flat = np.where(self.valid, self.a, self.B)
        self.in_part = np.zeros((self.P, self.B + 1), dtype=np.int16)
        np.add.at(
            self.in_part,
            (np.repeat(np.arange(self.P), self.R), flat.ravel()),
            1,
        )
        # donor lists per broker, built lazily (lexsort over that
        # broker's slots) and invalidated whenever the broker's slot set
        # changes; None marks "not built"
        self._donor_cache: dict[int, list] = {}

    def set_slot(self, p: int, s: int, b_new: int) -> None:
        b_old = int(self.a[p, s])
        if b_old < self.B:
            self.cnt[b_old] -= 1
            self.rcnt[self.rack[b_old]] -= 1
            self.prc[p, self.rack[b_old]] -= 1
            if s == 0:
                self.lcnt[b_old] -= 1
            self.in_part[p, b_old] -= 1
            self._donor_cache.pop(b_old, None)
        self.a[p, s] = b_new
        if b_new < self.B:
            self.cnt[b_new] += 1
            self.rcnt[self.rack[b_new]] += 1
            self.prc[p, self.rack[b_new]] += 1
            if s == 0:
                self.lcnt[b_new] += 1
            self.in_part[p, b_new] += 1
            self._donor_cache.pop(b_new, None)

    def _note_swap(self, p: int, s: int, bl: int, bf: int) -> None:
        # membership counts are slot-order-blind; only the cached donor
        # lists (which carry slot indices) go stale
        self._donor_cache.pop(bl, None)
        self._donor_cache.pop(bf, None)

    def used_mask(self, p: int) -> np.ndarray:
        return self.in_part[p, : self.B] > 0

    def _donor_list(self, src: int) -> list:
        lst = self._donor_cache.get(src)
        if lst is None:
            ps, ss = np.nonzero((self.a == src) & self.valid)
            w = np.where(
                ss == 0,
                self.inst.w_leader[ps, src],
                self.inst.w_follower[ps, src],
            ).astype(np.int64)
            order = np.lexsort((ss, ps, w))  # (weight, p, s) — legacy order
            lst = list(
                zip(ps[order].tolist(), ss[order].tolist())
            )
            self._donor_cache[src] = lst
        return lst

    def relocate_one(self, src: int, dst_mask: np.ndarray) -> bool:
        inst, rack = self.inst, self.rack[: self.B]
        lst = self._donor_list(src)
        fallback: tuple[int, int, int] | None = None
        fallback_i = -1
        for i, (p, s) in enumerate(lst):
            b = self.choose_broker(p, dst_mask & ~self.used_mask(p))
            if b < 0:
                continue
            same_rack = rack[b] == rack[src]
            if self.prc[p, rack[b]] + 1 - same_rack <= inst.part_rack_hi[p]:
                self.set_slot(p, s, b)  # invalidates src's cache...
                lst.pop(i)
                self._donor_cache[src] = lst  # ...which we repair exactly
                return True
            if fallback is None:
                fallback = (p, s, b)
                fallback_i = i
        if fallback is not None:
            p, s, b = fallback
            self.set_slot(p, s, b)
            lst.pop(fallback_i)
            self._donor_cache[src] = lst
            return True
        return False

    def _augment_leader_chains(self, max_repairs: int, lo: int, hi: int,
                               swap) -> None:
        """Phase 3 on flat edge arrays: one ``np.nonzero`` builds every
        leader->follower edge per augmentation (vs the legacy
        per-partition adjacency-dict walk), and each BFS level resolves
        first-visit parents with one lexsort + unique. Scan order —
        (frontier position, edge (p, s) order) — matches the legacy
        dict/list iteration exactly, so the unwound augmenting path is
        the same path and the resulting plan is bit-identical."""
        B = self.B
        for _ in range(max_repairs):
            over = np.flatnonzero(self.lcnt > hi)
            under = np.flatnonzero(self.lcnt < lo)
            if not (len(over) or len(under)):
                break
            mask = self.valid.copy()
            mask[:, 0] = False
            mask &= (self.a[:, [0]] < B) & (self.a < B)
            ep, es = np.nonzero(mask)
            src_b = self.a[ep, 0].astype(np.int64)
            dst_b = self.a[ep, es].astype(np.int64)
            if len(over):
                srcs = {int(b) for b in over}
                dst_ok = self.lcnt < hi
            else:
                srcs = {b for b in range(B) if self.lcnt[b] > lo}
                dst_ok = np.zeros(B, dtype=bool)
                dst_ok[list({int(b) for b in under})] = True
            # frontier built exactly as the legacy set->list conversion,
            # so level-0 scan order (and with it the chosen path) matches
            frontier = list(srcs)
            seen = np.zeros(B, dtype=bool)
            seen[frontier] = True
            parent_edge = np.full(B, -1, dtype=np.int64)
            rank = np.full(B, -1, dtype=np.int64)
            goal = -1
            while frontier and goal < 0:
                rank[:] = -1
                rank[frontier] = np.arange(len(frontier))
                cand = np.flatnonzero((rank[src_b] >= 0) & ~seen[dst_b])
                if cand.size == 0:
                    break
                order = cand[np.lexsort((cand, rank[src_b[cand]]))]
                d_ord = dst_b[order]
                uniq_d, first_idx = np.unique(d_ord, return_index=True)
                scan = np.argsort(first_idx)  # restore scan order
                uniq_d, first_idx = uniq_d[scan], first_idx[scan]
                parent_edge[uniq_d] = order[first_idx]
                seen[uniq_d] = True
                goals = np.flatnonzero(dst_ok[uniq_d])
                if goals.size:
                    goal = int(uniq_d[goals[0]])
                    break
                frontier = uniq_d.tolist()
            if goal < 0:
                break  # disconnected; annealer's job
            src_member = np.zeros(B, dtype=bool)
            src_member[list(srcs)] = True
            node = goal
            while not src_member[node]:
                k = int(parent_edge[node])
                swap(int(ep[k]), int(es[k]))
                node = int(src_b[k])


def greedy_seed(inst: ProblemInstance, max_repairs: int | None = None,
                impl: str | None = None) -> np.ndarray:
    """Greedy repair seed. ``impl`` overrides the process-wide
    constructor implementation (``solvers.tpu.constructor``): ``"vec"``
    (default) or ``"legacy"`` — the oracle the vectorized path is
    parity-pinned against."""
    if max_repairs is None:
        max_repairs = 4 * int(inst.rf.sum()) + 64
    impl = impl or _constructor.active()
    cls = _RepairVec if impl == "vec" else _Repair
    r = cls(inst)
    r.fill_nulls()
    r.fix_diversity()
    r.fix_bands(max_repairs)
    # band repair can occasionally be forced into a diversity-violating
    # placement (every allowed broker's rack full for that partition);
    # one more pass of each usually clears it
    r.fix_diversity()
    r.fix_bands(max_repairs)
    r.fix_leaders(max_repairs)
    return r.a
