"""Sweep-parallel annealing: the large-instance TPU engine.

The chain engine (``.anneal``) applies ONE Metropolis move per sequential
step — O(RF) work per step. That is the right shape for a CPU and a fine
shape for small clusters, but at 10k partitions it needs hundreds of
thousands of *sequential* device steps, and a TPU spends the whole solve
latency-bound at ~0% utilization (the scaling wall SURVEY.md §3.1 notes
for lp_solve, reborn as a dispatch wall).

This engine restructures the loop so per-step work scales with the
problem: every sweep proposes ONE move for EVERY partition of every chain
simultaneously ([N, P] proposals), evaluates all proposal deltas against
the sweep-start histograms as dense gather algebra, Metropolis-accepts
per partition, then **conflict-thins** the accepted set so at most one
move touches any broker's in/out counts (random-priority scatter-max) —
bounding histogram drift to ±1 per broker per sweep while still applying
up to min(P, B) moves in parallel.

Histograms are **delta-accumulated** (r5, VERDICT r4 item 1): the scan
carries exact per-chain (cnt, lcnt, rcnt) and updates them from the kept
moves — a replace moves one (out, in) replica unit, a leader swap one
leadership unit, so the update is a handful of [N, P] one-hot reductions
instead of the full O(N·P·R·B) rescoring kernel the r1-r4 engine ran
every sweep (its measured VPU floor: 0.6% utilization). The updates are
exact integer arithmetic over the thinned move set, so the carried
histograms stay BIT-IDENTICAL to a from-scratch rebuild — asserted
per-sweep in tests/test_sweep.py — and the search trajectory is
unchanged from the full-rescoring engine. A from-scratch **exact resync**
still runs at every snapshot boundary (where the full scorer must run
anyway for best-tracking) and at every chunk entry, so even a
hypothetical drift bug could survive at most ``snapshot_every`` sweeps.

Sequential depth collapses from O(P · sweeps) to O(sweeps): ~300 fused
steps regardless of cluster size. Feasibility and final quality are
enforced downstream (engine: exact rescore + steepest-descent polish +
numpy verification), so the sweep loop is free to be an optimizer, not a
bookkeeper.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax, random

from ...ops.score import moves_batch
from .arrays import (
    SCALE_W,
    ModelArrays,
    band_pen as _band_pen,
    u01 as _u01,
)

P_LSWAP = 0.15  # leadership-only proposals (zero replica movement)
P_RESTORE = 0.5  # replace proposals that re-propose the original broker

# compound 2-move exchange cadence (PR 11, docs/PORTFOLIO.md): every
# COMPOUND_EVERY-th sweep the odd (exchange) slot runs the ATOMIC
# two-replace move instead of the count-invariant pair exchange. The
# cadence divides the snapshot cadence (8) and the chunk parity (even),
# so chunked schedules keep replaying the uncut ladder bit-for-bit.
COMPOUND_EVERY = 4


def _histograms(m: ModelArrays, a: jax.Array):
    """Exact per-chain histograms. a: [N, P, R] -> cnt/lcnt [N, B+1],
    rcnt [N, K+1]."""
    N, P, R = a.shape
    B = m.num_brokers
    K1 = m.rack_lo.shape[0]
    n_idx = jnp.arange(N)[:, None, None]
    flat = jnp.where(m.slot_valid[None], a, B)
    cnt = jnp.zeros((N, B + 1), jnp.int32).at[
        jnp.broadcast_to(n_idx, a.shape), flat
    ].add(1)
    lcnt = jnp.zeros((N, B + 1), jnp.int32).at[
        jnp.arange(N)[:, None], flat[:, :, 0]
    ].add(1)
    racks = m.rack_of[flat]  # [N, P, R]
    rcnt = jnp.zeros((N, K1), jnp.int32).at[
        jnp.broadcast_to(n_idx, a.shape), racks
    ].add(1)
    return flat, racks, cnt, lcnt, rcnt


def _div_overflow(m: ModelArrays, racks: jax.Array) -> jax.Array:
    """C10 penalty without a [N, P, K] table: a slot overflows when its
    within-partition same-rack rank reaches the cap. O(N·P·R²)."""
    R = racks.shape[-1]
    same = racks[..., :, None] == racks[..., None, :]  # [N, P, R, R]
    tri = (jnp.arange(R)[:, None] > jnp.arange(R)[None, :])[None, None]
    rank = (same & tri).sum(-1)  # [N, P, R]
    over = jnp.logical_and(
        m.slot_valid[None], rank >= m.part_rack_hi[None, :, None]
    )
    return over.sum((1, 2)).astype(jnp.int32)  # [N]


def _weight(m: ModelArrays, a: jax.Array) -> jax.Array:
    """Exact preservation weight per chain. [N]."""
    N, P, R = a.shape
    p_idx = jnp.arange(P)[None, :, None]
    wl = m.w_lead[p_idx[..., 0], a[:, :, 0]]  # [N, P]
    w = jnp.where(m.slot_valid[None, :, 0], wl, 0).sum(1)
    if R > 1:
        wf = m.w_foll[jnp.broadcast_to(p_idx, a[..., 1:].shape), a[:, :, 1:]]
        w = w + jnp.where(m.slot_valid[None, :, 1:], wf, 0).sum((1, 2))
    return w.astype(jnp.int32)


def _full_scores_xla(m: ModelArrays, a: jax.Array):
    """(weight [N], penalty [N], cnt, lcnt, rcnt) — exact, from scratch.
    The snapshot/resync scorer of the XLA path: one histogram rebuild
    serves both the score and the delta-engine's carried state."""
    flat, racks, cnt, lcnt, rcnt = _histograms(m, a)
    B = m.num_brokers
    K = m.num_racks
    pen = (
        _band_pen(cnt[:, :B], m.broker_band[0], m.broker_band[1]).sum(1)
        + _band_pen(lcnt[:, :B], m.leader_band[0], m.leader_band[1]).sum(1)
        + _band_pen(rcnt[:, :K], m.rack_lo[None, :K], m.rack_hi[None, :K]).sum(1)
        + _div_overflow(m, racks)
    ).astype(jnp.int32)
    return _weight(m, a), pen, cnt, lcnt, rcnt


def chain_scores(m: ModelArrays, a: jax.Array):
    """(weight [N], penalty [N]) — exact, from scratch."""
    w, pen, _cnt, _lcnt, _rcnt = _full_scores_xla(m, a)
    return w, pen


class ScorerBundle(NamedTuple):
    """The sweep loop's device implementations, resolved per scorer.

    - ``hists(m, a) -> (flat, racks, cnt, lcnt, rcnt)``
    - ``scores(m, a) -> (w [N], pen [N])``
    - ``propose(m, a, bits, temp, hists=...) -> SiteProposals | None``
    - ``halves(...)`` -> exchange half-deltas | None
    - ``full(m, a) -> (w, pen, cnt, lcnt, rcnt)`` — the snapshot scorer
      + exact histogram resync in one pass
    - ``site_step(m, a, cnt, lcnt, rcnt, key, temp)`` -> updated 4-tuple
    - ``exch_step(m, a, cnt, lcnt, rcnt, key, temp)`` -> updated 4-tuple
    - ``comp_step(...)`` — the compound 2-move exchange sweep; one
      shared XLA implementation for every scorer (it runs 1 sweep in
      ``COMPOUND_EVERY``, off the Mosaic hot path, so the Pallas bundle
      executes the identical code CI pins)
    """

    hists: object
    scores: object
    propose: object
    halves: object
    full: object
    site_step: object
    exch_step: object
    comp_step: object


def _make_scorer(scorer: str) -> ScorerBundle:
    """Resolve the sweep loop's device implementations.

    ``"xla"``: scatter-add histograms + gather-based proposal algebra
    (the CPU/CI path).
    ``"pallas"`` / ``"pallas-interpret"``: the Mosaic hot path — the
    tiled one-hot-matmul scoring kernel (``ops.score_pallas``), the
    fused proposal kernel (``ops.propose_pallas``), and the fused
    thinning/apply/delta kernels (``ops.thin_pallas``); interpret mode
    exists so CI can execute the very code paths the TPU runs. Every
    implementation returns bit-identical records (pinned in tests), so
    the sweep trajectory is implementation-independent.
    """
    if scorer == "xla":
        return ScorerBundle(
            _histograms, chain_scores, None, None, _full_scores_xla,
            _site_sweep_delta, _exchange_sweep_delta,
            _compound_sweep_delta,
        )

    import functools

    from ...ops.propose_pallas import (
        exchange_halves_pallas,
        propose_site_pallas,
    )
    from ...ops.score_pallas import score_batch_pallas
    from ...ops.thin_pallas import exchange_step_pallas, site_step_pallas

    interpret = scorer == "pallas-interpret"

    def hists(m: ModelArrays, a: jax.Array):
        B = m.num_brokers
        flat = jnp.where(m.slot_valid[None], a, B)
        racks = m.rack_of[flat]
        s = score_batch_pallas(a, m, interpret=interpret)
        return flat, racks, s.cnt, s.lcnt, s.rcnt

    def scores(m: ModelArrays, a: jax.Array):
        s = score_batch_pallas(a, m, interpret=interpret)
        pen = s.pen_broker + s.pen_leader + s.pen_rack + s.pen_part_rack
        return s.weight, pen.astype(jnp.int32)

    def full(m: ModelArrays, a: jax.Array):
        s = score_batch_pallas(a, m, interpret=interpret)
        pen = s.pen_broker + s.pen_leader + s.pen_rack + s.pen_part_rack
        return s.weight, pen.astype(jnp.int32), s.cnt, s.lcnt, s.rcnt

    propose = functools.partial(propose_site_pallas, interpret=interpret)
    halves = functools.partial(exchange_halves_pallas, interpret=interpret)
    return ScorerBundle(
        hists, scores, propose, halves, full,
        functools.partial(site_step_pallas, interpret=interpret),
        functools.partial(exchange_step_pallas, interpret=interpret),
        _compound_sweep_delta,
    )


def best_key(w: jax.Array, pen: jax.Array) -> jax.Array:
    return jnp.where(pen == 0, w, -pen - 1)


def _make_to_varying(axis_name):
    """Cast replicated leaves to device-varying inside ``shard_map`` —
    required by jax's varying-manual-axes (vma) system. Pre-vma jax
    (0.4.x) has neither ``jax.typeof`` nor ``lax.pcast`` and needs no
    cast (``check_rep=False`` at the shard_map boundary), so the shim
    degrades to identity there.

    ``axis_name`` may be a tuple (docs/MESH.md): the sharded lane paths
    run collectives over ``(mesh_axis, vmap_axis)`` so migration spans
    every logical chain shard regardless of the (chains × lanes) device
    split. Only MESH axes carry vma state — a vmap-introduced axis has
    nothing to pcast over — so tuple members are cast individually and
    names absent from the abstract mesh are skipped."""
    typeof = getattr(jax, "typeof", None)
    pcast = getattr(lax, "pcast", None)
    if typeof is None or pcast is None:
        return lambda x: x
    names = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)

    def _mesh_axes():
        get = getattr(jax.sharding, "get_abstract_mesh", None)
        try:
            return set(get().axis_names) if get is not None else None
        except Exception:
            return None

    def to_varying(x):
        vma = getattr(typeof(x), "vma", frozenset())
        mesh_axes = _mesh_axes()
        for n in names:
            if n in vma:
                continue
            if mesh_axes is not None and n not in mesh_axes:
                continue  # vmap axis: no vma to cast over
            x = pcast(x, n, to="varying")
        return x

    return to_varying


class SiteProposals(NamedTuple):
    """One proposed move per (chain, partition), the unit the conflict
    thinning and apply stages consume. Two move shapes share the record:

    - replace (``is_lsw`` false): slot ``s`` <- ``b_new``; the outgoing
      broker is ``b_at_s``.
    - leader swap (``is_lsw`` true): slot 0 <- ``b_at_s`` (the promotee
      at slot ``s``), slot ``s`` <- ``b_lead``; zero replica movement.

    ``prio`` > 0 iff Metropolis-accepted; thinning keeps a proposal only
    if it owns the priority maps of both brokers whose counts it moves.
    """

    is_lsw: jax.Array  # [N, P] bool
    s: jax.Array       # [N, P] int32 target slot
    b_new: jax.Array   # [N, P] int32 incoming broker (replace)
    b_lead: jax.Array  # [N, P] int32 current leader (slot 0)
    b_at_s: jax.Array  # [N, P] int32 current occupant of slot s
    prio: jax.Array    # [N, P] float32, 0 where rejected


def _rand_idx(u: jax.Array, hi: jax.Array) -> jax.Array:
    """Uniform int in [0, hi) from u ~ U[0,1): floor(u * hi), clamped —
    float32 rounding can land exactly on hi when u is close to 1. This
    (not modulo) is the shared formulation because Mosaic has no vector
    integer division; both the XLA and the Pallas proposal paths use it
    so their trajectories stay bit-identical."""
    hi_f = hi.astype(jnp.float32) if hasattr(hi, "astype") else float(hi)
    return jnp.minimum((u * hi_f).astype(jnp.int32), hi - 1)


def propose_site(m: ModelArrays, a: jax.Array, bits: jax.Array, temp,
                 hists=_histograms) -> SiteProposals:
    """Evaluate one single-site proposal per (chain, partition): pick the
    move, compute its exact score delta against the sweep-start
    histograms, Metropolis-accept, and draw the thinning priority.
    ``bits [N, P, 8] uint32`` supplies all randomness (lane layout shared
    with the Pallas kernel in ``ops.propose_pallas``, which reproduces
    this function bit-for-bit)."""
    N, P, R = a.shape
    B = m.num_brokers

    flat, racks, cnt, lcnt, rcnt = hists(m, a)
    rf = m.rf[None, :]  # [1, P]

    # ---- proposal: slot + move type + incoming broker ----------------
    u_slot = _u01(bits[..., 0])
    s_rep = _rand_idx(u_slot, rf)
    s_lsw = 1 + _rand_idx(u_slot, jnp.maximum(rf - 1, 1))
    is_lsw = jnp.logical_and(_u01(bits[..., 1]) < P_LSWAP, rf > 1)
    s = jnp.where(is_lsw, s_lsw, s_rep)  # [N, P]

    p_idx = jnp.arange(P)[None, :]
    n_idx = jnp.arange(N)[:, None]
    b_lead = a[:, :, 0]
    b_at_s = a[n_idx, p_idx, s]
    # replace moves slot s's occupant out; lswap moves a leadership unit
    # out of the current leader
    b_old = jnp.where(is_lsw, b_lead, b_at_s)
    b_foll = b_at_s  # lswap promotee

    b_uni = _rand_idx(_u01(bits[..., 2]), jnp.int32(B))
    s_orig = _rand_idx(_u01(bits[..., 3]), jnp.int32(R))
    b_orig = m.a0[jnp.broadcast_to(p_idx, s_orig.shape), s_orig]  # [N, P]
    b_new = jnp.where(
        jnp.logical_and(_u01(bits[..., 4]) < P_RESTORE, b_orig < B),
        b_orig,
        b_uni,
    )

    # ---- deltas (replace: a[p, s] <- b_new) --------------------------
    lead_slot = s == 0
    wl_new = m.w_lead[p_idx, b_new]
    wf_new = m.w_foll[p_idx, b_new]
    wl_old = m.w_lead[p_idx, b_old]
    wf_old = m.w_foll[p_idx, b_old]
    dw_rep = jnp.where(lead_slot, wl_new - wl_old, wf_new - wf_old)

    blo, bhi = m.broker_band[0], m.broker_band[1]
    llo, lhi = m.leader_band[0], m.leader_band[1]
    cnt_old = cnt[n_idx, b_old]
    cnt_new = cnt[n_idx, b_new]
    d_cnt = (
        _band_pen(cnt_old - 1, blo, bhi) - _band_pen(cnt_old, blo, bhi)
        + _band_pen(cnt_new + 1, blo, bhi) - _band_pen(cnt_new, blo, bhi)
    )
    lcnt_old = lcnt[n_idx, b_old]
    lcnt_new = lcnt[n_idx, b_new]
    d_lcnt_rep = jnp.where(
        lead_slot,
        _band_pen(lcnt_old - 1, llo, lhi) - _band_pen(lcnt_old, llo, lhi)
        + _band_pen(lcnt_new + 1, llo, lhi) - _band_pen(lcnt_new, llo, lhi),
        0,
    )
    r_old = m.rack_of[b_old]
    r_new = m.rack_of[b_new]
    rc_old = rcnt[n_idx, r_old]
    rc_new = rcnt[n_idx, r_new]
    d_rcnt = (
        _band_pen(rc_old - 1, m.rack_lo[r_old], m.rack_hi[r_old])
        - _band_pen(rc_old, m.rack_lo[r_old], m.rack_hi[r_old])
        + _band_pen(rc_new + 1, m.rack_lo[r_new], m.rack_hi[r_new])
        - _band_pen(rc_new, m.rack_lo[r_new], m.rack_hi[r_new])
    )
    # diversity: within-partition rack counts for the two racks involved
    c_old = (racks == r_old[:, :, None]).sum(-1)
    c_new = (racks == r_new[:, :, None]).sum(-1)
    cap = m.part_rack_hi[None, :]

    def g(c):
        return jnp.maximum(c - cap, 0)

    d_div = g(c_old - 1) - g(c_old) + g(c_new + 1) - g(c_new)
    cross_rack = r_old != r_new
    dpen_rep = d_cnt + d_lcnt_rep + jnp.where(cross_rack, d_rcnt + d_div, 0)
    # b_old == b_new (or b_new already in the row) is illegal
    in_row = jnp.logical_and(
        flat == b_new[:, :, None], m.slot_valid[None]
    ).any(-1)
    legal_rep = ~in_row

    # ---- deltas (lswap: promote slot s to leader) --------------------
    dw_lsw = (
        m.w_lead[p_idx, b_foll] + m.w_foll[p_idx, b_lead]
        - m.w_lead[p_idx, b_lead] - m.w_foll[p_idx, b_foll]
    )
    lc_l = lcnt[n_idx, b_lead]
    lc_f = lcnt[n_idx, b_foll]
    dpen_lsw = (
        _band_pen(lc_l - 1, llo, lhi) - _band_pen(lc_l, llo, lhi)
        + _band_pen(lc_f + 1, llo, lhi) - _band_pen(lc_f, llo, lhi)
    )

    dw = jnp.where(is_lsw, dw_lsw, dw_rep)
    dpen = jnp.where(is_lsw, dpen_lsw, dpen_rep)
    # rf > 0: bucket-padded rows (solvers.tpu.bucket) must never be
    # accepted — their apply is a no-op, but an accepted prio would let
    # them win the conflict-thinning token maps and suppress real moves
    # (measured: a heavily padded tiny instance lost most of its move
    # throughput). All-true on unpadded instances. Mirrored bit-for-bit
    # in ops.propose_pallas.
    legal = jnp.logical_and(
        jnp.where(is_lsw, rf > 1, legal_rep), rf > 0
    )
    # penalty scale as DATA (m.lam, docs/PORTFOLIO.md): the int deltas
    # are exact in float32 (< 2^24), so for the default config this is
    # bit-identical to the historical int `SCALE_W*dw - LAMBDA*dpen`
    delta = (SCALE_W * dw).astype(jnp.float32) - m.lam * dpen.astype(
        jnp.float32
    )

    # ---- Metropolis accept -------------------------------------------
    accept = jnp.logical_and(
        legal,
        jnp.logical_or(
            delta >= 0,
            _u01(bits[..., 5]) < jnp.exp(delta / jnp.maximum(temp, 1e-6)),
        ),
    )

    prio = _u01(bits[..., 6]) + jnp.float32(1e-6)  # > 0
    prio = jnp.where(accept, prio, 0.0)
    return SiteProposals(is_lsw=is_lsw, s=s, b_new=b_new, b_lead=b_lead,
                         b_at_s=b_at_s, prio=prio)


def _thin_keep(m: ModelArrays, p: SiteProposals) -> jax.Array:
    """Conflict-thinning decision: keep an accepted proposal only if it
    owns the random-priority maps of BOTH brokers whose counts it moves.

    Tokens: replace moves an (out=b_at_s, in=b_new) replica unit; lswap
    moves a leadership unit (out=b_lead, in=b_at_s). One shared
    random-priority map per direction bounds every histogram's drift to
    ±1 per broker per sweep."""
    N, P = p.prio.shape
    B = m.num_brokers
    n_idx = jnp.arange(N)[:, None]
    tok_out = jnp.where(p.is_lsw, p.b_lead, p.b_at_s)
    tok_in = jnp.where(p.is_lsw, p.b_at_s, p.b_new)
    m_out = jnp.zeros((N, B + 1), jnp.float32).at[n_idx, tok_out].max(p.prio)
    m_in = jnp.zeros((N, B + 1), jnp.float32).at[n_idx, tok_in].max(p.prio)
    return jnp.logical_and(
        p.prio > 0,
        jnp.logical_and(
            p.prio == m_out[n_idx, tok_out], p.prio == m_in[n_idx, tok_in]
        ),
    )


def _apply_site(m: ModelArrays, a: jax.Array, p: SiteProposals,
                keep: jax.Array) -> jax.Array:
    """Apply the kept proposals (vectorized; one move max per partition)."""
    R = a.shape[2]
    r_iota = jnp.arange(R)[None, None, :]
    s3 = p.s[:, :, None]
    keep3 = keep[:, :, None]
    # replace: slot s <- b_new
    rep_val = jnp.where(r_iota == s3, p.b_new[:, :, None], a)
    # lswap: slot 0 <- promotee (b_at_s), slot s <- old leader
    lsw_val = jnp.where(
        r_iota == 0,
        p.b_at_s[:, :, None],
        jnp.where(r_iota == s3, p.b_lead[:, :, None], a),
    )
    new_a = jnp.where(p.is_lsw[:, :, None], lsw_val, rep_val)
    return jnp.where(keep3, new_a, a)


def thin_apply(m: ModelArrays, a: jax.Array, p: SiteProposals) -> jax.Array:
    """Conflict-thin accepted proposals and apply the winners."""
    return _apply_site(m, a, p, _thin_keep(m, p))


def _hist_delta(tok_out: jax.Array, tok_in: jax.Array,
                width: int) -> jax.Array:
    """Histogram delta from per-(chain, partition) unit moves: +1 at
    ``tok_in``, -1 at ``tok_out``, as a fused one-hot reduction over
    partitions — [N, P] tokens -> [N, width] int32. TPU scatters
    serialize; this compare-subtract-reduce fuses into one VPU pass.
    Token pairs routed to the same bucket (sentinels for not-kept /
    not-applicable moves, or an out-of-range pair) cancel exactly."""
    iota = jnp.arange(width, dtype=jnp.int32)[None, None, :]
    d = (tok_in[:, :, None] == iota).astype(jnp.int32) - (
        tok_out[:, :, None] == iota
    ).astype(jnp.int32)
    return d.sum(1)


def _site_hist_deltas(m: ModelArrays, p: SiteProposals, keep: jax.Array,
                      cnt: jax.Array, lcnt: jax.Array, rcnt: jax.Array):
    """Exact carried-histogram update for one applied site sweep.

    A kept replace moves one replica unit (out=b_at_s, in=b_new) — and,
    when it hits slot 0, one leadership unit with the same tokens; a
    kept lswap moves one leadership unit (out=b_lead, in=b_at_s) and no
    replica unit. Not-kept pairs route both tokens to the null broker B
    (null rack K via ``rack_of[B]``), where the +1/-1 cancel. The ``rf
    > 0`` guard drops proposals on degenerate empty partitions, whose
    apply is a no-op (slot index -1 writes nothing) but whose tokens
    would otherwise corrupt the counts. Integer-exact: the updated
    histograms are bit-identical to a from-scratch rebuild of the
    applied population (pinned in tests/test_sweep.py)."""
    B = m.num_brokers
    live = m.rf[None, :] > 0
    rep = jnp.logical_and(keep, jnp.logical_and(~p.is_lsw, live))
    out_b = jnp.where(rep, p.b_at_s, B)
    in_b = jnp.where(rep, p.b_new, B)
    cnt = cnt + _hist_delta(out_b, in_b, B + 1)
    rcnt = rcnt + _hist_delta(
        m.rack_of[out_b], m.rack_of[in_b], m.rack_lo.shape[0]
    )
    lead_mv = jnp.logical_and(
        keep, jnp.logical_and(jnp.logical_or(p.is_lsw, p.s == 0), live)
    )
    l_out = jnp.where(lead_mv, jnp.where(p.is_lsw, p.b_lead, p.b_at_s), B)
    l_in = jnp.where(lead_mv, jnp.where(p.is_lsw, p.b_at_s, p.b_new), B)
    lcnt = lcnt + _hist_delta(l_out, l_in, B + 1)
    return cnt, lcnt, rcnt


def _site_sweep_delta(m: ModelArrays, a: jax.Array, cnt, lcnt, rcnt,
                      key: jax.Array, temp, propose=None):
    """One site sweep against CARRIED histograms (the delta engine's hot
    path): propose/accept/thin/apply exactly as ``sweep_once``, but the
    sweep-start histograms come from the carry instead of a rebuild, and
    the carry is updated from the kept moves. Because the carried
    histograms are exact, the trajectory is bit-identical to the
    from-scratch formulation."""
    N, P = a.shape[:2]
    bits = random.bits(key, (N, P, 8), jnp.uint32)

    def carried(mm: ModelArrays, aa: jax.Array):
        flat = jnp.where(mm.slot_valid[None], aa, mm.num_brokers)
        return flat, mm.rack_of[flat], cnt, lcnt, rcnt

    prop = (propose or propose_site)(m, a, bits, temp, hists=carried)
    keep = _thin_keep(m, prop)
    a2 = _apply_site(m, a, prop, keep)
    cnt2, lcnt2, rcnt2 = _site_hist_deltas(m, prop, keep, cnt, lcnt, rcnt)
    return a2, cnt2, lcnt2, rcnt2


def sweep_once(m: ModelArrays, a: jax.Array, key: jax.Array, temp,
               hists=_histograms, propose=None):
    """One parallel annealing sweep over all chains and partitions:
    propose everywhere -> Metropolis accept -> conflict-thin -> apply.
    ``hists`` supplies the from-scratch histograms and ``propose`` the
    proposal evaluator (``propose_site`` in XLA by default; the fused
    Pallas kernel on TPU via ``_make_scorer``)."""
    N, P = a.shape[:2]
    bits = random.bits(key, (N, P, 8), jnp.uint32)
    prop = (propose or propose_site)(m, a, bits, temp, hists=hists)
    return thin_apply(m, a, prop)


class ExchangeProposals(NamedTuple):
    """One proposed pair-exchange per (chain, partition), partition-
    aligned: partition p offers its slot-``s`` occupant ``b_own`` and
    receives its partner's ``b_other``. Both halves of a pair carry
    IDENTICAL ``prio`` (the pair's shared draw), so thinning and apply
    reach the same decision on both sides without communication."""

    s: jax.Array        # [N, P] int32 own slot
    b_own: jax.Array    # [N, P] int32 outgoing broker
    b_other: jax.Array  # [N, P] int32 incoming broker
    tok_out: jax.Array  # [N, P] int32 leadership token out (B = none)
    tok_in: jax.Array   # [N, P] int32 leadership token in (B = none)
    prio: jax.Array     # [N, P] float32, 0 where rejected


def _pair_partners(key, N: int, P: int):
    """Involution pairing by random stride: alternating d-blocks pair p
    with p+d (lower blocks) / p-d (upper blocks). The stride d is shared
    by all chains so partner-aligned views are two contiguous rolls
    instead of gathers (XLA TPU gathers cost ~2-5 ms per [N, P] operand;
    rolls are DMA copies); a per-chain random PHASE shifts the block
    boundaries so chains still explore different pair structures
    (ADVICE r1). Over sweeps d varies uniformly, so every pair distance
    is eventually proposed; tail partitions whose partner falls off the
    end sit out for one sweep.

    Returns (d scalar, is_lower [N, P], pair_valid [N, P])."""
    kd, kph = random.split(key)
    # stride capped at P//2: longer distances compose from short strides
    # over sweeps, while d ~ U[1, P-1] would bench ~half the partitions
    # per sweep (pair_valid is false for ~d of P positions)
    d = random.randint(kd, (), 1, max(P // 2, 2))
    phase = random.randint(kph, (N, 1), 0, 2 * d)
    p_idx = jnp.arange(P)[None, :]
    is_lower = ((p_idx + phase) // d) % 2 == 0
    partner = jnp.where(is_lower, p_idx + d, p_idx - d)
    pair_valid = jnp.logical_and(partner >= 0, partner < P)
    return d, is_lower, pair_valid


def _partner_view(x, d, is_lower):
    """x[n, partner(p), ...] for partner = p ± d — two rolls + select,
    no gather. Out-of-range partners wrap; callers mask with
    ``pair_valid``."""
    up = jnp.roll(x, -d, axis=1)      # x[p + d]
    down = jnp.roll(x, d, axis=1)     # x[p - d]
    sel = is_lower
    while sel.ndim < x.ndim:
        sel = sel[..., None]
    return jnp.where(sel, up, down)


def _exchange_halves_xla(m: ModelArrays, a, lcnt, s_own, lead_other,
                         b_other, b_own=None):
    """Per-partition half of a pair-exchange delta, from the OWN row only
    (plus the pair-level leader-count term, identical on both sides).
    The Pallas kernel (``ops.propose_pallas.exchange_halves_pallas``)
    reproduces this bit-for-bit. ``b_own`` (the slot occupant) may be
    passed in when the caller already computed it; the kernel always
    rebuilds it in VMEM where the select is free. Returns (b_own,
    dw_own, ddiv_own, dlcnt_pair, legal_own)."""
    N, P, R = a.shape
    B = m.num_brokers
    p_idx = jnp.arange(P)[None, :]
    n_idx = jnp.arange(N)[:, None]

    if b_own is None:
        r_iota = jnp.arange(R)[None, None, :]
        b_own = (jnp.where(r_iota == s_own[:, :, None], a, 0)).sum(-1)

    # objective half: replace own slot occupant b_own by b_other
    lead_own = s_own == 0
    dw_own = jnp.where(
        lead_own,
        m.w_lead[p_idx, b_other] - m.w_lead[p_idx, b_own],
        m.w_foll[p_idx, b_other] - m.w_foll[p_idx, b_own],
    )

    # leader-count term, pair-level (both sides compute the same value):
    # with exactly one leader slot in the pair, a leadership unit moves
    # from the broker at that slot to the broker arriving into it
    llo, lhi = m.leader_band[0], m.leader_band[1]
    xor = lead_own != lead_other
    l_out = jnp.where(lead_own, b_own, b_other)
    l_in = jnp.where(lead_own, b_other, b_own)
    lo_c = lcnt[n_idx, l_out]
    li_c = lcnt[n_idx, l_in]
    dlcnt = jnp.where(
        xor,
        _band_pen(lo_c - 1, llo, lhi) - _band_pen(lo_c, llo, lhi)
        + _band_pen(li_c + 1, llo, lhi) - _band_pen(li_c, llo, lhi),
        0,
    )

    # diversity half: own row loses rack(b_own), gains rack(b_other)
    flat = jnp.where(m.slot_valid[None], a, B)
    racks = m.rack_of[flat]  # [N, P, R]
    r_out = m.rack_of[b_own]
    r_in = m.rack_of[b_other]
    c_out = (racks == r_out[:, :, None]).sum(-1)
    c_in = (racks == r_in[:, :, None]).sum(-1)
    cap = m.part_rack_hi[None, :]

    def g(c):
        return jnp.maximum(c - cap, 0)

    ddiv_own = jnp.where(
        r_out != r_in,
        g(c_out - 1) - g(c_out) + g(c_in + 1) - g(c_in),
        0,
    )

    # legality half: the incoming broker must not already sit in the row
    in_row = jnp.logical_and(
        flat == b_other[:, :, None], m.slot_valid[None]
    ).any(-1)
    return b_own, dw_own, ddiv_own, dlcnt, ~in_row


def propose_exchange(m: ModelArrays, a, key, temp,
                     halves=None, lcnt=None) -> ExchangeProposals:
    """Evaluate one pair-exchange proposal per (chain, partition). The
    key drives the per-chain stride and a ``bits [N, P, 4]`` tensor
    (lanes: slot-lower, slot-upper, metropolis, prio); the pair's shared
    draws are the LOWER side's bits, so both halves reach identical
    accept/priority decisions. ``lcnt`` may carry the exact leader
    histograms (the delta engine's carry); without it they are rebuilt —
    only leader counts can change under an exchange, so either way no
    full scorer runs."""
    N, P, R = a.shape
    B = m.num_brokers
    if lcnt is None:
        n_idx0 = jnp.arange(N)[:, None]
        lead = jnp.where(m.rf[None, :] > 0, a[:, :, 0], B)
        lcnt = jnp.zeros((N, B + 1), jnp.int32).at[n_idx0, lead].add(1)

    kd, kbits = random.split(key)
    bits = random.bits(kbits, (N, P, 4), jnp.uint32)
    d, is_lower, pair_valid = _pair_partners(kd, N, P)

    bits_low = jnp.where(is_lower[..., None], bits,
                         _partner_view(bits, d, is_lower))
    u0 = _u01(bits_low[..., 0])
    u1 = _u01(bits_low[..., 1])
    rf_own = jnp.broadcast_to(m.rf[None, :], (N, P))
    rf_other = jnp.broadcast_to(
        jnp.where(is_lower, jnp.roll(m.rf, -d)[None, :],
                  jnp.roll(m.rf, d)[None, :]),
        (N, P),
    )
    s_own = _rand_idx(jnp.where(is_lower, u0, u1), rf_own)
    s_other = _rand_idx(jnp.where(is_lower, u1, u0), rf_other)
    lead_other = s_other == 0

    b_probe = (jnp.where(
        jnp.arange(R)[None, None, :] == s_own[:, :, None], a, 0
    )).sum(-1)
    b_other = _partner_view(b_probe, d, is_lower)

    b_own, dw_own, ddiv_own, dlcnt, legal_own = (
        halves or _exchange_halves_xla
    )(m, a, lcnt, s_own, lead_other, b_other, b_own=b_probe)

    # combine the halves (partner-aligned rolls of the packed trio)
    packed = jnp.stack(
        [dw_own, ddiv_own, legal_own.astype(jnp.int32)], axis=-1
    )
    other = _partner_view(packed, d, is_lower)
    dw = dw_own + other[..., 0]
    ddiv = ddiv_own + other[..., 1]
    # both sides must be live partitions: a bucket-padded row (rf == 0,
    # solvers.tpu.bucket) has no slot to give — its apply would be a
    # one-sided write that duplicates a broker into the live partner.
    # All-true on unpadded instances, so trajectories are unchanged.
    pair_live = jnp.logical_and(rf_own > 0, rf_other > 0)
    legal = jnp.logical_and(
        jnp.logical_and(legal_own, other[..., 2] > 0),
        jnp.logical_and(pair_valid, pair_live),
    )
    delta = (SCALE_W * dw).astype(jnp.float32) - m.lam * (
        dlcnt + ddiv
    ).astype(jnp.float32)
    accept = jnp.logical_and(
        legal,
        jnp.logical_or(
            delta >= 0,
            _u01(bits_low[..., 2]) < jnp.exp(
                delta / jnp.maximum(temp, 1e-6)
            ),
        ),
    )
    prio = jnp.where(accept, _u01(bits_low[..., 3]) + jnp.float32(1e-6),
                     0.0)

    lead_own = s_own == 0
    xor = lead_own != lead_other
    hot = jnp.logical_and(prio > 0, xor)  # only leadership moves conflict
    tok_out = jnp.where(hot, jnp.where(lead_own, b_own, b_other), B)
    tok_in = jnp.where(hot, jnp.where(lead_own, b_other, b_own), B)
    return ExchangeProposals(s=s_own, b_own=b_own, b_other=b_other,
                             tok_out=tok_out, tok_in=tok_in, prio=prio)


def exchange_thin_apply(m: ModelArrays, a, p: ExchangeProposals):
    """Thin leadership-moving exchanges to one kept unit per broker per
    direction (token B bypasses the maps — count-invariant swaps are
    conflict-free by the one-pair-per-partition construction), then
    apply: own slot <- incoming broker. Both halves of a pair share
    prio/tokens, so they win or lose together."""
    N, P, R = a.shape
    B = m.num_brokers
    n_idx = jnp.arange(N)[:, None]
    m_out = jnp.zeros((N, B + 1), jnp.float32).at[n_idx, p.tok_out].max(
        p.prio
    )
    m_in = jnp.zeros((N, B + 1), jnp.float32).at[n_idx, p.tok_in].max(
        p.prio
    )
    keep = jnp.logical_and(
        p.prio > 0,
        jnp.logical_and(
            jnp.logical_or(p.tok_out == B,
                           p.prio == m_out[n_idx, p.tok_out]),
            jnp.logical_or(p.tok_in == B,
                           p.prio == m_in[n_idx, p.tok_in]),
        ),
    )
    r_iota = jnp.arange(R)[None, None, :]
    write = jnp.logical_and(keep[:, :, None], r_iota == p.s[:, :, None])
    return jnp.where(write, p.b_other[:, :, None], a)


def exchange_sweep(m: ModelArrays, a: jax.Array, key: jax.Array, temp,
                   halves=None):
    """Cross-partition replica exchange — the count-invariant move.

    Under exact-equality bands (lo == hi on broker/rack totals, common
    when sizes divide evenly) single-site replaces always pass through a
    penalized state and freeze out at every temperature (LAMBDA >> t_hi);
    redistribution then needs swaps that leave every per-broker and
    per-rack total untouched. Each pair proposes swapping one replica
    slot; only leader-count and per-partition diversity penalties can
    change, and both are evaluated exactly — half per side, combined
    with one partner-aligned gather."""
    N, P, _R = a.shape
    if P < 2:
        return a
    prop = propose_exchange(m, a, key, temp, halves=halves)
    return exchange_thin_apply(m, a, prop)


def _exchange_sweep_delta(m: ModelArrays, a: jax.Array, cnt, lcnt, rcnt,
                          key: jax.Array, temp, halves=None):
    """Exchange sweep against the carried leader histograms. Replica and
    rack totals are exchange-invariant by construction (memberships swap
    between two partitions); only leadership units move, and the exact
    lcnt update is the slot-0 diff of the applied population — unchanged
    partitions contribute a cancelling +1/-1 pair."""
    P = a.shape[1]
    if P < 2:
        return a, cnt, lcnt, rcnt
    prop = propose_exchange(m, a, key, temp, halves=halves, lcnt=lcnt)
    a2 = exchange_thin_apply(m, a, prop)
    lcnt = lcnt + _hist_delta(a[:, :, 0], a2[:, :, 0], m.num_brokers + 1)
    return a2, cnt, lcnt, rcnt


class CompoundProposals(NamedTuple):
    """One half of a compound 2-move exchange per (chain, partition),
    partition-aligned like :class:`ExchangeProposals`: partition p
    replaces its slot-``s`` occupant ``b_out`` with a freshly drawn
    ``b_in`` (restore-biased, like a site replace) — and its PAIRED
    partition does the same, atomically. Both halves carry the pair's
    shared ``prio``, so thinning and apply reach one decision."""

    s: jax.Array       # [N, P] int32 own slot
    b_out: jax.Array   # [N, P] int32 outgoing broker (slot occupant)
    b_in: jax.Array    # [N, P] int32 incoming broker (fresh draw)
    lead_mv: jax.Array  # [N, P] bool — own slot is the leader slot
    prio: jax.Array    # [N, P] float32, 0 where rejected


def _pair_pen_delta(hist, outs, ins, lo_of, hi_of):
    """Exact band-penalty delta of a pair's unit moves applied
    ATOMICALLY: ``hist [N, W]``; ``outs``/``ins`` are lists of [N, P]
    token arrays in canonical (lower-side-first) order, identical on
    both sides of a pair. Per token: the NET count change of its bin
    across all four moves, priced once per distinct bin via
    first-occurrence masking — so two moves loading one broker cost
    ``band_pen(c+2) - band_pen(c)``, not twice the single-step delta.
    Sentinel tokens (the null broker/rack) always arrive in matched
    out/in pairs, net to zero, and contribute nothing."""
    toks = list(outs) + list(ins)
    signs = [-1] * len(outs) + [1] * len(ins)
    n_idx = jnp.arange(hist.shape[0])[:, None]
    total = jnp.zeros_like(toks[0])
    for j, tj in enumerate(toks):
        net = jnp.zeros_like(tj)
        for sk, tk in zip(signs, toks):
            net = net + sk * (tk == tj).astype(jnp.int32)
        first = jnp.ones(tj.shape, bool)
        for tk in toks[:j]:
            first = jnp.logical_and(first, tk != tj)
        c = hist[n_idx, tj]
        lo, hi = lo_of(tj), hi_of(tj)
        d = _band_pen(c + net, lo, hi) - _band_pen(c, lo, hi)
        total = total + jnp.where(first, d, 0)
    return total


def propose_compound(m: ModelArrays, a, key, temp, cnt, lcnt, rcnt):
    """Evaluate one compound 2-move exchange per (chain, partition):
    the pair (``_pair_partners`` stride pairing, shared with the plain
    exchange) proposes TWO single-site replaces — each side replaces
    its slot occupant with a fresh restore-biased draw — scored as ONE
    atomic move against the carried histograms, with the cross terms
    between the two halves priced exactly (``_pair_pen_delta``).

    This is the move the exact-band instances need (docs/ANALYSIS.md
    messy[1] triage): each half alone passes through a penalized state
    (accept probability ~e^-lam/t), but the compound delta sees only
    the endpoints, so a relocation or 3-broker rotation that restores
    every band atomically is accepted on its merits. Subsumes neither
    the pair exchange (which stays cheaper per sweep) nor the site
    move — it runs on its own cadence (``COMPOUND_EVERY``).

    A lane whose config disables the move (``m.comp_enable`` = 0,
    docs/PORTFOLIO.md) rejects every proposal — the sweep itself stays
    lane-invariant, so one executable serves every config.

    Returns ``(proposals, d, is_lower)`` — the pairing geometry rides
    along so thinning can align partner decisions."""
    N, P, R = a.shape
    B = m.num_brokers
    kd, kbits = random.split(key)
    bits = random.bits(kbits, (N, P, 7), jnp.uint32)
    d, is_lower, pair_valid = _pair_partners(kd, N, P)

    # pair-shared draws are the LOWER side's bits (slot lanes 0-1,
    # accept lane 5, prio lane 6); the incoming-broker draw (lanes
    # 2-4) is PER SIDE — each half picks its own replacement
    bits_low = jnp.where(is_lower[..., None], bits,
                         _partner_view(bits, d, is_lower))
    u0 = _u01(bits_low[..., 0])
    u1 = _u01(bits_low[..., 1])
    rf_own = jnp.broadcast_to(m.rf[None, :], (N, P))
    rf_other = jnp.broadcast_to(
        jnp.where(is_lower, jnp.roll(m.rf, -d)[None, :],
                  jnp.roll(m.rf, d)[None, :]),
        (N, P),
    )
    s_own = _rand_idx(jnp.where(is_lower, u0, u1), rf_own)

    p_idx = jnp.arange(P)[None, :]
    r_iota = jnp.arange(R)[None, None, :]
    b_out = (jnp.where(r_iota == s_own[:, :, None], a, 0)).sum(-1)

    # incoming broker: restore-biased fresh draw (the site move's
    # proposal shape — the restore path is what walks compound
    # relocations back toward the move-count optimum)
    b_uni = _rand_idx(_u01(bits[..., 2]), jnp.int32(B))
    s_orig = _rand_idx(_u01(bits[..., 3]), jnp.int32(R))
    b_orig = m.a0[jnp.broadcast_to(p_idx, s_orig.shape), s_orig]
    b_in = jnp.where(
        jnp.logical_and(_u01(bits[..., 4]) < P_RESTORE, b_orig < B),
        b_orig,
        b_uni,
    )

    # own-row terms: role-aware weight, diversity, row legality
    lead_own = s_own == 0
    dw_own = jnp.where(
        lead_own,
        m.w_lead[p_idx, b_in] - m.w_lead[p_idx, b_out],
        m.w_foll[p_idx, b_in] - m.w_foll[p_idx, b_out],
    )
    flat = jnp.where(m.slot_valid[None], a, B)
    racks = m.rack_of[flat]
    r_out = m.rack_of[b_out]
    r_in = m.rack_of[b_in]
    c_out = (racks == r_out[:, :, None]).sum(-1)
    c_in = (racks == r_in[:, :, None]).sum(-1)
    cap = m.part_rack_hi[None, :]

    def g(c):
        return jnp.maximum(c - cap, 0)

    ddiv_own = jnp.where(
        r_out != r_in,
        g(c_out - 1) - g(c_out) + g(c_in + 1) - g(c_in),
        0,
    )
    in_row = jnp.logical_and(
        flat == b_in[:, :, None], m.slot_valid[None]
    ).any(-1)
    legal_own = ~in_row  # also rejects the no-op b_in == b_out

    # partner's half via ONE partner-aligned roll of the packed record
    packed = jnp.stack(
        [b_out, b_in, lead_own.astype(jnp.int32), dw_own, ddiv_own,
         legal_own.astype(jnp.int32)],
        axis=-1,
    )
    oth = _partner_view(packed, d, is_lower)
    b_out_o, b_in_o = oth[..., 0], oth[..., 1]
    lead_o = oth[..., 2] > 0

    # canonical (lower-first) token order so both sides price the
    # identical 4-token histogram deltas
    def canon(own, other):
        return (jnp.where(is_lower, own, other),
                jnp.where(is_lower, other, own))

    o_lo, o_up = canon(b_out, b_out_o)
    i_lo, i_up = canon(b_in, b_in_o)
    blo, bhi = m.broker_band[0], m.broker_band[1]
    d_cnt = _pair_pen_delta(
        cnt, [o_lo, o_up], [i_lo, i_up],
        lambda t: blo, lambda t: bhi,
    )
    d_rcnt = _pair_pen_delta(
        rcnt,
        [m.rack_of[o_lo], m.rack_of[o_up]],
        [m.rack_of[i_lo], m.rack_of[i_up]],
        lambda t: m.rack_lo[t], lambda t: m.rack_hi[t],
    )
    led_lo, led_up = canon(lead_own, lead_o)
    llo, lhi = m.leader_band[0], m.leader_band[1]
    d_lcnt = _pair_pen_delta(
        lcnt,
        [jnp.where(led_lo, o_lo, B), jnp.where(led_up, o_up, B)],
        [jnp.where(led_lo, i_lo, B), jnp.where(led_up, i_up, B)],
        lambda t: llo, lambda t: lhi,
    )

    dw = dw_own + oth[..., 3]
    dpen = d_cnt + d_rcnt + d_lcnt + ddiv_own + oth[..., 4]
    pair_live = jnp.logical_and(rf_own > 0, rf_other > 0)
    legal = jnp.logical_and(
        jnp.logical_and(legal_own, oth[..., 5] > 0),
        jnp.logical_and(pair_valid, pair_live),
    )
    # per-lane config gate (docs/PORTFOLIO.md): a disabled lane rejects
    # every compound proposal; the sweep structure stays lane-invariant
    legal = jnp.logical_and(legal, m.comp_enable > 0.5)
    delta = (SCALE_W * dw).astype(jnp.float32) - m.lam * dpen.astype(
        jnp.float32
    )
    accept = jnp.logical_and(
        legal,
        jnp.logical_or(
            delta >= 0,
            _u01(bits_low[..., 5]) < jnp.exp(
                delta / jnp.maximum(temp, 1e-6)
            ),
        ),
    )
    prio = jnp.where(accept, _u01(bits_low[..., 6]) + jnp.float32(1e-6),
                     0.0)
    return (
        CompoundProposals(s=s_own, b_out=b_out, b_in=b_in,
                          lead_mv=lead_own, prio=prio),
        d, is_lower,
    )


def _compound_sweep_delta(m: ModelArrays, a: jax.Array, cnt, lcnt, rcnt,
                          key: jax.Array, temp):
    """One compound 2-move exchange sweep against the carried
    histograms: propose (pair-atomic), conflict-thin, apply, and
    delta-update the carry exactly — each kept half is one replace, so
    the update is :func:`_hist_delta` over the kept tokens, and the
    carried histograms stay bit-identical to a from-scratch rebuild.

    Thinning extends the site rule pair-atomically: a half must own
    the priority maps of both brokers it moves AND its partner half
    must win its own maps — a pair is kept or dropped whole (both
    halves share one prio, so the partner check is one roll). Shared
    by every scorer bundle: compound sweeps are 1-in-COMPOUND_EVERY,
    off the Mosaic hot path by design."""
    N, P = a.shape[:2]
    if P < 2:
        return a, cnt, lcnt, rcnt
    B = m.num_brokers
    prop, d, is_lower = propose_compound(m, a, key, temp, cnt, lcnt,
                                         rcnt)
    n_idx = jnp.arange(N)[:, None]
    m_out = jnp.zeros((N, B + 1), jnp.float32).at[n_idx, prop.b_out].max(
        prop.prio
    )
    m_in = jnp.zeros((N, B + 1), jnp.float32).at[n_idx, prop.b_in].max(
        prop.prio
    )
    win_own = jnp.logical_and(
        prop.prio > 0,
        jnp.logical_and(
            prop.prio == m_out[n_idx, prop.b_out],
            prop.prio == m_in[n_idx, prop.b_in],
        ),
    )
    keep = jnp.logical_and(win_own,
                           _partner_view(win_own, d, is_lower))

    r_iota = jnp.arange(a.shape[2])[None, None, :]
    write = jnp.logical_and(keep[:, :, None],
                            r_iota == prop.s[:, :, None])
    a2 = jnp.where(write, prop.b_in[:, :, None], a)

    out_b = jnp.where(keep, prop.b_out, B)
    in_b = jnp.where(keep, prop.b_in, B)
    cnt = cnt + _hist_delta(out_b, in_b, B + 1)
    rcnt = rcnt + _hist_delta(
        m.rack_of[out_b], m.rack_of[in_b], m.rack_lo.shape[0]
    )
    lead = jnp.logical_and(keep, prop.lead_mv)
    l_out = jnp.where(lead, prop.b_out, B)
    l_in = jnp.where(lead, prop.b_in, B)
    lcnt = lcnt + _hist_delta(l_out, l_in, B + 1)
    return a2, cnt, lcnt, rcnt


def compound_sweep(m: ModelArrays, a: jax.Array, key: jax.Array, temp):
    """From-scratch form of the compound sweep (tests and reference
    loops): rebuild the exact histograms, run one compound 2-move
    exchange sweep, return the applied population."""
    _flat, _racks, cnt, lcnt, rcnt = _histograms(m, a)
    a2, _c, _l, _r = _compound_sweep_delta(m, a, cnt, lcnt, rcnt, key,
                                           temp)
    return a2


def make_sweep_solver_fn(
    n_chains: int,
    snapshot_every: int = 8,
    axis_name: str | None = None,
    scorer: str = "xla",
):
    """Build the jittable sweep-parallel solver for one shard:
    (m, a_seed [P, R], key, temps [sweeps]) -> (best_a [P, R], best_key
    scalar, curve [sweeps]). Interface matches ``anneal.make_solver_fn``
    so ``parallel.mesh`` can host either engine; the temperature ladder
    is a runtime argument so clock-checked chunked solves reuse one
    executable. ``scorer`` selects the bulk-rescoring implementation
    (``_make_scorer``); every scorer yields bit-identical trajectories."""
    stepper = make_sweep_stepper_fn(
        n_chains, snapshot_every, axis_name, scorer
    )
    scores = _make_scorer(scorer).scores  # seed-snapshot scoring only

    def solve(m: ModelArrays, a_seed: jax.Array, key: jax.Array,
              temps: jax.Array):
        P, R = a_seed.shape
        a = jnp.broadcast_to(a_seed.astype(jnp.int32), (n_chains, P, R))
        w0, p0 = scores(m, a)
        # seed snapshot: never return worse than the seed. moves is the
        # lexicographic tie-break: weight tiers alias move counts
        # (keeping one leader == keeping two followers, 4 = 2+2), so
        # equal-objective plans with different move counts exist and
        # Metropolis wanders that plateau (delta >= 0 accepts); ties
        # must prefer fewer moves (the north star).
        state = (a, best_key(w0, p0), moves_batch(a, m), a, key)
        _, top_a, top_k, curve = stepper(m, state, temps)
        return top_a, top_k, curve

    return solve


def make_lane_stepper_fn(
    n_chains: int,
    snapshot_every: int = 8,
    axis_name: str | None = None,
    scorer: str = "xla",
):
    """Batched multi-instance form of :func:`make_sweep_stepper_fn`: L
    independent lanes (one model each, same padded bucket shape) anneal
    concurrently in ONE dispatch. Signature: ``(m_stack [L, ...], state
    [L, ...leaves], temps [sweeps]) -> (state', best_a [L, P, R],
    best_k [L], curve [L, sweeps])``.

    Implementation is literally ``jax.vmap`` of the single-instance
    stepper over the lane axis — every proposal, accept, thinning and
    migration decision is the element-wise computation the unbatched
    stepper runs, so a lane's trajectory is bit-identical to solving it
    alone with the same state and key (pinned in tests/test_lanes.py;
    the temperature ladder and snapshot cadence are lane-invariant, so
    the scan structure — including the ``lax.cond`` snapshot branches —
    stays unbatched under the vmap). The Pallas scorer rides the same
    wrap: ``jax.vmap`` of ``pallas_call`` lifts the lane axis into a
    leading grid dimension, and interpret mode executes the identical
    path on CPU (parity-pinned in CI)."""
    solve = make_sweep_stepper_fn(n_chains, snapshot_every, axis_name,
                                  scorer)
    return jax.vmap(solve, in_axes=(0, 0, None))


def make_sweep_stepper_fn(
    n_chains: int,
    snapshot_every: int = 8,
    axis_name: str | None = None,
    scorer: str = "xla",
):
    """The state-carrying core of the sweep engine: (m, state, temps) ->
    (state', best_a [P, R], best_key scalar, curve [sweeps]), with state
    = (a [N, P, R] current chains, best_k [N], best_mv [N], best_a
    [N, P, R] per-chain snapshots, key). Chunked solves
    (``engine.solve_tpu`` cuts the ladder for certificate checks and
    time limits) thread the FULL state — populations and the RNG key —
    through the boundaries, so as long as the chunk length preserves the
    snapshot cadence and the exchange-sweep parity (engine chunks are a
    multiple of snapshot_every), a chunked run is bit-identical to the
    uncut ladder: chunking changes only where the host may look, never
    the search trajectory.

    Donation contract (docs/PIPELINE.md): every state leaf has an
    identically shaped/dtyped output leaf in ``state'``, which is what
    lets ``parallel.mesh`` mark the state argument donated — XLA then
    aliases the input buffers to the output and a chunk updates the
    populations in HBM in place. Callers must treat a state handed to
    one dispatch as CONSUMED and continue from the returned ``state'``
    only; the runtime enforces this (reuse raises, CPU included)."""
    sc = _make_scorer(scorer)
    hists, full = sc.hists, sc.full
    site_step, exch_step = sc.site_step, sc.exch_step
    comp_step = sc.comp_step

    def solve(m: ModelArrays, state, temps: jax.Array):
        sweeps = temps.shape[0]
        a, best_k, best_mv, best_a, key = state

        if axis_name is not None:
            to_varying = _make_to_varying(axis_name)
            key = to_varying(key)
            a, best_k, best_mv, best_a = jax.tree.map(
                to_varying, (a, best_k, best_mv, best_a)
            )

        # chunk-entry histogram build: the scan below carries exact
        # (cnt, lcnt, rcnt) per chain and delta-updates them from the
        # kept moves, so the per-sweep full rescoring of the r1-r4
        # engine runs only here and at snapshot resyncs
        _flat0, _racks0, cnt, lcnt, rcnt = hists(m, a)

        def body(carry, xs):
            a, cnt, lcnt, rcnt, best_k, best_mv, best_a, key = carry
            temp, do_snap, do_exchange, do_compound = xs
            # per-lane ladder scaling as DATA (docs/PORTFOLIO.md): the
            # shared schedule times m.temp_scale — exact for the
            # default config (x * 1.0 is bit-identical in float32)
            temp = temp * m.temp_scale
            key, sub = random.split(key)
            a, cnt, lcnt, rcnt = lax.cond(
                do_compound,
                lambda ops: comp_step(m, *ops, sub, temp),
                lambda ops: lax.cond(
                    do_exchange,
                    lambda o: exch_step(m, *o, sub, temp),
                    lambda o: site_step(m, *o, sub, temp),
                    ops,
                ),
                (a, cnt, lcnt, rcnt),
            )

            def snap(args):
                a, cnt, lcnt, rcnt, best_k, best_mv, best_a = args
                # exact resync: the snapshot scorer rebuilds the
                # histograms from scratch anyway — overwrite the carry
                # (bit-identical to the delta-updated values; defensive
                # against any drift surviving longer than one cadence)
                w, pen, cnt, lcnt, rcnt = full(m, a)
                k = best_key(w, pen)
                mv = moves_batch(a, m)
                improved = jnp.logical_or(
                    k > best_k, jnp.logical_and(k == best_k, mv < best_mv)
                )
                best_mv = jnp.where(improved, mv, best_mv)
                best_k = jnp.where(improved, k, best_k)
                best_a = jnp.where(improved[:, None, None], a, best_a)
                if axis_name is not None:
                    # ICI best-migration at the snapshot boundary
                    # (VERDICT r1 item 5): locate the globally best
                    # *current* chain (pmax; lowest shard index breaks
                    # ties), broadcast it with a masked psum, and clone
                    # it over this shard's worst chain — the same
                    # owner-broadcast the chain engine runs every round
                    # (anneal.make_round_runner), amortized here to once
                    # per snapshot because a sweep moves every partition.
                    imax = jnp.iinfo(jnp.int32).max
                    local_best = jnp.max(k)
                    global_best = lax.pmax(local_best, axis_name)
                    # lexicographic global winner: highest key, then
                    # fewest moves among the key-tied chains
                    local_mv = jnp.min(
                        jnp.where(k == global_best, mv, imax)
                    )
                    global_mv = lax.pmin(local_mv, axis_name)
                    idx = lax.axis_index(axis_name)
                    am_owner = jnp.logical_and(
                        local_best == global_best, local_mv == global_mv
                    )
                    owner = lax.pmin(
                        jnp.where(am_owner, idx, imax), axis_name
                    )
                    src = jnp.argmin(jnp.where(k == global_best, mv, imax))
                    cand = jnp.where(idx == owner, a[src],
                                     jnp.zeros_like(a[src]))
                    g = lax.psum(cand, axis_name)
                    dst = jnp.argmin(k)
                    a = a.at[dst].set(g)
                    # the migrant's exact histogram rows ride the same
                    # owner-broadcast (a few KB), keeping the carried
                    # counts consistent with the cloned chain
                    def mig_row(h):
                        row = jnp.where(idx == owner, h[src],
                                        jnp.zeros_like(h[src]))
                        return h.at[dst].set(lax.psum(row, axis_name))

                    cnt = mig_row(cnt)
                    lcnt = mig_row(lcnt)
                    rcnt = mig_row(rcnt)
                    # harvest the migrant NOW (its key is global_best by
                    # construction) — waiting for the next snapshot would
                    # make the final sweep's migration dead and leave
                    # short schedules with no propagation at all
                    take = jnp.logical_or(
                        global_best > best_k[dst],
                        jnp.logical_and(global_best == best_k[dst],
                                        global_mv < best_mv[dst]),
                    )
                    best_k = best_k.at[dst].max(global_best)
                    best_mv = best_mv.at[dst].set(
                        jnp.where(take, global_mv, best_mv[dst])
                    )
                    best_a = best_a.at[dst].set(
                        jnp.where(take, g, best_a[dst])
                    )
                return a, cnt, lcnt, rcnt, best_k, best_mv, best_a

            a, cnt, lcnt, rcnt, best_k, best_mv, best_a = lax.cond(
                do_snap, snap, lambda args: args,
                (a, cnt, lcnt, rcnt, best_k, best_mv, best_a)
            )
            return (
                (a, cnt, lcnt, rcnt, best_k, best_mv, best_a, key),
                jnp.max(best_k),
            )

        # snapshot every Nth sweep AND the final one: the coldest sweeps
        # improve the most and must never be discarded
        idx = jnp.arange(sweeps)
        do_snap = jnp.logical_or(
            idx % snapshot_every == snapshot_every - 1, idx == sweeps - 1
        )
        # odd sweeps run the count-invariant pair-exchange move; even
        # sweeps run single-site replace/lswap proposals; every
        # COMPOUND_EVERY-th sweep the exchange slot runs the atomic
        # compound 2-move exchange instead (exact-band tunneling)
        do_exchange = jnp.arange(sweeps) % 2 == 1
        do_compound = jnp.arange(sweeps) % COMPOUND_EVERY == (
            COMPOUND_EVERY - 1
        )
        (a, cnt, lcnt, rcnt, best_k, best_mv, best_a, key), curve = lax.scan(
            body, (a, cnt, lcnt, rcnt, best_k, best_mv, best_a, key),
            (temps, do_snap, do_exchange, do_compound)
        )
        tied = best_k == jnp.max(best_k)
        top = jnp.argmin(
            jnp.where(tied, best_mv, jnp.iinfo(jnp.int32).max)
        )
        return (
            (a, best_k, best_mv, best_a, key),
            best_a[top], best_k[top], curve,
        )

    return solve


# Megachunk fusion (docs/PIPELINE.md): True here and False in
# ``anneal.SUPPORTS_MEGACHUNK`` — the engine resolver consults the
# flag instead of hard-coding engine names.
SUPPORTS_MEGACHUNK = True

# Never-fires early-exit sentinels: a chain qualifies when ``best_k >=
# cert_k AND best_mv <= cert_mv``; no feasible key reaches int32 max
# and no move count is negative, so disarmed groups pass these and the
# armed/disarmed split never forks the executable (runtime scalars,
# one signature).
MEGA_DISARMED_KEY = np.int32(np.iinfo(np.int32).max)
MEGA_DISARMED_MOVES = np.int32(-1)


def make_mega_stepper_fn(
    n_chains: int,
    snapshot_every: int = 8,
    axis_name: str | None = None,
    scorer: str = "xla",
    lane_axis: str | None = None,
):
    """Fuse K consecutive chunk steps into ONE device-resident scan:
    ``(m, state, temps [K, c], active [K] bool, cert_k, cert_mv) ->
    (state', top_a, top_k, cert_a, cert_ok, cert_mv_out, curves [K, c],
    execd [K] bool)``. Each scan step invokes the UNCHANGED
    :func:`make_sweep_stepper_fn` body on the carried state, so an
    executed step is bit-identical to one dispatched chunk — the fused
    run replays the exact accept/decline sequence of the K=1 path and
    the carried state at every step boundary equals the state a chunked
    run would have checkpointed there (pinned in
    tests/test_megachunk_parity.py).

    Early exit: after each step, a chain *qualifies* when ``best_k >=
    cert_k and best_mv <= cert_mv`` — the device-side mirror of the
    engine's boundary-certificate precheck (weight at the proved upper
    bound, moves at the exact lower bound; the host still runs the
    authoritative exact check on ``cert_a``). Any qualifying chain
    anywhere (``lax.pmax`` over the mesh axis, and over the lane axis
    for the vmapped form) sets a carried ``done`` flag and the
    remaining steps become masked no-ops — the PR 1 inert-row
    discipline applied to whole chunks. Disarmed callers pass the
    never-fires sentinels ``cert_k = int32 max, cert_mv = -1`` so ONE
    executable serves armed and disarmed groups. ``active`` masks tail
    steps the same way (a group shorter than K pads ``temps`` and
    clears ``active``), keeping one executable per (bucket, K).

    The host reads ``execd`` to learn how many steps really ran and
    expands ``curves`` back into per-chunk score curves; skipped steps
    emit zero curves that the host discards. ``cert_a`` is this shard's
    best qualifying snapshot (``cert_mv_out`` its move count, int32 max
    when none) — under migration the qualifying chain may live on any
    shard, so the host picks across shards before certifying. Donation
    contract unchanged: every ``state`` leaf has an identically
    shaped/dtyped leaf in ``state'``.

    KAO113 guards the scan body: no host-sync primitive (``.item()``,
    ``device_get``/``np.asarray`` on traced values, Python branches on
    the carry) may appear here — each would force the host round-trip
    this fusion exists to delete."""
    chunk = make_sweep_stepper_fn(n_chains, snapshot_every, axis_name,
                                  scorer)
    imax = jnp.iinfo(jnp.int32).max

    def solve(m: ModelArrays, state, temps: jax.Array,
              active: jax.Array, cert_k: jax.Array, cert_mv: jax.Array):
        def qualify(best_k, best_mv):
            return jnp.logical_and(best_k >= cert_k, best_mv <= cert_mv)

        def body(carry, xs):
            st, done = carry
            temps_j, active_j = xs
            run = jnp.logical_and(active_j, jnp.logical_not(done))

            def go(st):
                st2, _top_a, _top_k, curve = chunk(m, st, temps_j)
                return st2, curve

            def skip(st):
                return st, jnp.zeros((temps_j.shape[0],), jnp.int32)

            st, curve = lax.cond(run, go, skip, st)
            _a, best_k, best_mv, _best_a, _key = st
            hit = jnp.any(qualify(best_k, best_mv)).astype(jnp.int32)
            if axis_name is not None:
                hit = lax.pmax(hit, axis_name)
            if lane_axis is not None:
                hit = lax.pmax(hit, lane_axis)
            done = jnp.logical_or(done, hit > 0)
            return (st, done), (curve, run)

        (state, _done), (curves, execd) = lax.scan(
            body, (state, jnp.asarray(False)), (temps, active)
        )
        a, best_k, best_mv, best_a, key = state
        tied = best_k == jnp.max(best_k)
        top = jnp.argmin(jnp.where(tied, best_mv, imax))
        qual = qualify(best_k, best_mv)
        cert_ok = jnp.any(qual)
        cidx = jnp.argmin(jnp.where(qual, best_mv, imax))
        cert_a = best_a[cidx]
        cert_mv_out = jnp.where(cert_ok, best_mv[cidx], imax)
        return (
            (a, best_k, best_mv, best_a, key),
            best_a[top], best_k[top], cert_a, cert_ok, cert_mv_out,
            curves, execd,
        )

    return solve


def make_mega_lane_stepper_fn(
    n_chains: int,
    snapshot_every: int = 8,
    axis_name: str | None = None,
    scorer: str = "xla",
    mesh_lane_axis: str | None = None,
):
    """Lane-batched :func:`make_mega_stepper_fn` — ``jax.vmap`` over
    the lane axis exactly as :func:`make_lane_stepper_fn` wraps the
    chunk stepper, so a lane's fused trajectory is bit-identical to
    solving it alone. The vmap carries ``axis_name=\"laneblk\"`` so the
    early-exit ``pmax`` also spans lanes: in portfolio mode ANY lane
    certifying stops every lane (first-to-certify, PR 11). When the
    lane axis is additionally split over mesh devices (docs/MESH.md),
    ``mesh_lane_axis`` names that mesh axis and the exit pmax spans
    ``(\"laneblk\", mesh_lane_axis)`` — the vmap block plus its sharded
    complement, i.e. every lane, exactly as before. Under vmap the
    per-step ``lax.cond`` lowers to a select (both branches execute),
    so lanes save dispatches and host round-trips but not per-lane
    device compute after an exit — documented in docs/PIPELINE.md.
    Batch-mode callers always pass the disarmed sentinels (independent
    instances must not share an exit)."""
    lane_axis = ("laneblk" if mesh_lane_axis is None
                 else ("laneblk", mesh_lane_axis))
    solve = make_mega_stepper_fn(n_chains, snapshot_every, axis_name,
                                 scorer, lane_axis=lane_axis)
    return jax.vmap(solve, in_axes=(0, 0, None, None, None, None),
                    axis_name="laneblk")
