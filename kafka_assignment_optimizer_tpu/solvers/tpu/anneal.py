"""Vmapped simulated-annealing kernel — the hot loop of the TPU backend.

Design (SURVEY.md §3.4, §7): a population of N candidate assignments
``A[N, P, R]`` lives in HBM; each candidate runs an independent Metropolis
chain. Every step proposes one of three constraint-aware move types and
evaluates it in **O(RF)** time via incremental count/penalty deltas — no
full rescoring in the loop:

- ``replace``   A[p, s] <- b_new: changes broker/rack/leader counts; the
  move that redistributes load (needs band slack to be accepted cold).
- ``lswap``     swap A[p, 0] <-> A[p, s]: leadership only, zero replica
  moves — the BASELINE.json leader-only-rebalance scenario's workhorse.
- ``xswap``     swap A[p1, s1] <-> A[p2, s2] across partitions: per-broker
  and per-rack totals are *invariant*, so it explores under tight (even
  exact-equality) bands where ``replace`` would always be rejected.

Everything is static-shape, branchless (where-selects), int32 state with
an int64 selection key, inside ``lax.scan`` (steps) nested in ``lax.scan``
(rounds) under one jit. ``vmap`` runs the N chains in lockstep on the VPU;
the candidate axis is what ``shard_map`` shards across the mesh
(``parallel.mesh``). Feasible-best snapshots are taken once per round —
a [N, P, R] select, amortized to nothing — so late high-temperature
wandering can never lose the best feasible plan found.
"""

from __future__ import annotations

from dataclasses import dataclass
import jax
import jax.numpy as jnp
from jax import lax, random

from .arrays import (
    LAMBDA,
    SCALE_W,
    ModelArrays,
    band_pen as _shared_band_pen,
    u01 as _shared_u01,
)

# fused ladder megachunks (docs/PIPELINE.md): the chain engine's
# between-chunk reseed is a HOST data dependency — the global best must
# round-trip to reseed every chain — so its chunks cannot fuse into one
# device-resident scan. The engine checks this flag before resolving
# KAO_MEGACHUNK; sweep.py carries the True side.
SUPPORTS_MEGACHUNK = False

# move-type proposal mix
P_REPLACE = 0.45
P_LSWAP = 0.10  # remainder goes to xswap
# within `replace`: probability of proposing the partition's ORIGINAL
# broker for the slot (a restore) instead of a uniform one — the move that
# claws preservation weight back after high-temperature wandering and
# walks seeds toward the move-count optimum
P_RESTORE = 0.5


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class ChainState:
    """Per-candidate annealing state (leading axis N under vmap)."""

    a: jax.Array  # [P, R] int32
    cnt: jax.Array  # [B+1] int32 replica+leader per broker
    lcnt: jax.Array  # [B+1] int32 leaders per broker
    rcnt: jax.Array  # [K+1] int32 replicas per rack
    pen: jax.Array  # [] int32 total band+diversity violations
    w: jax.Array  # [] int32 preservation weight
    key: jax.Array  # [2] uint32


def chain_score(st: ChainState) -> jax.Array:
    return SCALE_W * st.w - LAMBDA * st.pen


def best_key(st: ChainState) -> jax.Array:
    """int32 ranking: any feasible candidate (weight >= 0) beats any
    infeasible one (strictly negative, ranked by penalty). Weight is
    bounded by ~5 * num_partitions, far inside int32."""
    return jnp.where(st.pen == 0, st.w, -st.pen - 1)


def init_chain(m: ModelArrays, a_seed: jax.Array, key: jax.Array) -> ChainState:
    """Full scoring of the seed — the only non-incremental evaluation."""
    from ...ops.score import score_one

    s = score_one(a_seed, m)
    return ChainState(
        a=a_seed.astype(jnp.int32),
        cnt=s.cnt,
        lcnt=s.lcnt,
        rcnt=s.rcnt,
        pen=s.penalty,
        w=s.weight,
        key=key,
    )


_band_pen = _shared_band_pen


def _delta_band(c_from, c_to, lo, hi):
    """Penalty delta of moving one unit from bucket value c_from to c_to."""
    return (
        _band_pen(c_from - 1, lo, hi)
        - _band_pen(c_from, lo, hi)
        + _band_pen(c_to + 1, lo, hi)
        - _band_pen(c_to, lo, hi)
    )


_u01 = _shared_u01


def _anneal_step(
    m: ModelArrays, st: ChainState, temp: jax.Array, row: jax.Array
) -> ChainState:
    """One Metropolis step for one chain. O(RF) work, all where-selects.

    ``row`` is a [8] uint32 vector of presampled random bits (one
    ``random.bits`` call per ROUND generates all of them — keeping threefry
    key-splitting out of the hot loop is worth ~10x on step latency).
    Modulo bias from ``bits % n`` is negligible for n << 2^32.
    """
    P, R = m.a0.shape
    B, K = m.num_brokers, m.num_racks
    i32 = jnp.int32
    u32 = jnp.uint32

    p = (row[0] % u32(P)).astype(i32)
    rfp = m.rf[p]
    u_type = _u01(row[1])
    is_rep = u_type < P_REPLACE
    is_lsw = jnp.logical_and(u_type >= P_REPLACE, u_type < P_REPLACE + P_LSWAP)
    is_xsw = jnp.logical_not(jnp.logical_or(is_rep, is_lsw))

    # rfp == 0 only on bucket-padded rows (solvers.tpu.bucket): the
    # max() keeps the modulus defined; the rfp > 0 validity guards below
    # reject every move touching such a row, so the clamp never changes
    # a real proposal
    s_raw = (row[2] & u32(0x3FFFFFFF)).astype(i32)
    s_rep = s_raw % jnp.maximum(rfp, 1)
    s_lsw = 1 + s_raw % jnp.maximum(rfp - 1, 1)
    s1 = jnp.where(is_lsw, s_lsw, s_rep)

    row1 = st.a[p]  # [R]
    valid1 = m.slot_valid[p]
    b_old = row1[s1]
    # replace proposal: restore the slot's original broker with prob
    # P_RESTORE (when it exists and is eligible), else uniform
    b_uni = (row[3] % u32(B)).astype(i32)
    s_orig = ((row[7] & u32(0xFFFF)) % u32(R)).astype(i32)
    b_orig = m.a0[p, s_orig]
    b_new_rep = jnp.where(
        jnp.logical_and(_u01(row[7]) < P_RESTORE, b_orig < B), b_orig, b_uni
    )

    # second site for xswap
    p2 = (row[4] % u32(P)).astype(i32)
    rfp2 = m.rf[p2]
    s2 = (row[5] & u32(0x3FFFFFFF)).astype(i32) % jnp.maximum(rfp2, 1)
    row2 = st.a[p2]
    valid2 = m.slot_valid[p2]
    b2 = row2[s2]

    # the broker arriving at (p, s1): replace -> b_new_rep; lswap -> the
    # follower being promoted; xswap -> b2
    b_in = jnp.where(is_rep, b_new_rep, jnp.where(is_lsw, row1[s_lsw], b2))

    # --- validity -----------------------------------------------------
    in_p1 = jnp.logical_and(row1 == b_in, valid1).any()
    in_p2 = jnp.logical_and(row2 == b_old, valid2).any()
    live = rfp > 0  # false only on bucket-padded rows, which are inert
    valid_rep = jnp.logical_and(jnp.logical_not(in_p1), live)
    valid_lsw = rfp >= 2
    valid_xsw = jnp.logical_and(
        jnp.logical_and(jnp.logical_not(in_p1), live),
        jnp.logical_and(
            jnp.logical_and(jnp.logical_not(in_p2), rfp2 > 0), p != p2
        ),
    )
    valid = jnp.where(is_rep, valid_rep, jnp.where(is_lsw, valid_lsw, valid_xsw))

    # --- weight delta -------------------------------------------------
    wl, wf = m.w_lead, m.w_foll
    lead1 = s1 == 0
    lead2 = s2 == 0
    # role-aware weight of broker b at (partition, slot)
    dw_rep = jnp.where(
        lead1, wl[p, b_in] - wl[p, b_old], wf[p, b_in] - wf[p, b_old]
    )
    bl, bf = row1[0], row1[s_lsw]
    dw_lsw = (wl[p, bf] + wf[p, bl]) - (wl[p, bl] + wf[p, bf])
    dw_xsw = (
        jnp.where(lead1, wl[p, b2] - wl[p, b_old], wf[p, b2] - wf[p, b_old])
        + jnp.where(lead2, wl[p2, b_old] - wl[p2, b2], wf[p2, b_old] - wf[p2, b2])
    )
    dw = jnp.where(is_rep, dw_rep, jnp.where(is_lsw, dw_lsw, dw_xsw)).astype(i32)

    # --- penalty deltas ----------------------------------------------
    def f_cnt(b_from, b_to, counts, lo, hi):
        both_real = jnp.logical_and(b_from < B, b_to < B)
        d = _delta_band(counts[b_from], counts[b_to], lo, hi)
        return jnp.where(jnp.logical_and(both_real, b_from != b_to), d, 0)

    # replace: broker totals, leader totals (if leader slot), rack totals
    d_cnt = f_cnt(b_old, b_in, st.cnt, m.broker_band[0], m.broker_band[1])
    d_lead_rep = jnp.where(
        lead1,
        f_cnt(b_old, b_in, st.lcnt, m.leader_band[0], m.leader_band[1]),
        0,
    )
    r_old, r_in = m.rack_of[b_old], m.rack_of[b_in]
    d_rack = jnp.where(
        r_old != r_in,
        _band_pen(st.rcnt[r_old] - 1, m.rack_lo[r_old], m.rack_hi[r_old])
        - _band_pen(st.rcnt[r_old], m.rack_lo[r_old], m.rack_hi[r_old])
        + _band_pen(st.rcnt[r_in] + 1, m.rack_lo[r_in], m.rack_hi[r_in])
        - _band_pen(st.rcnt[r_in], m.rack_lo[r_in], m.rack_hi[r_in]),
        0,
    )

    # partition-rack diversity deltas: local recount over R slots
    racks1 = jnp.where(valid1, m.rack_of[row1], K)

    def div_delta(racks_row, cap, r_from, r_to):
        c_from = (racks_row == r_from).sum()
        c_to = (racks_row == r_to).sum()
        g = lambda c: jnp.maximum(c - cap, 0)
        return jnp.where(
            r_from != r_to,
            g(c_from - 1) - g(c_from) + g(c_to + 1) - g(c_to),
            0,
        )

    d_div1 = div_delta(racks1, m.part_rack_hi[p], r_old, r_in)
    racks2 = jnp.where(valid2, m.rack_of[row2], K)
    r_b2 = m.rack_of[b2]
    d_div2 = div_delta(racks2, m.part_rack_hi[p2], r_b2, r_old)

    # lswap: only leader totals move between the two brokers
    d_lead_lsw = f_cnt(bl, bf, st.lcnt, m.leader_band[0], m.leader_band[1])

    # xswap: cnt/rcnt invariant; lcnt moves only when exactly one of the
    # two slots is a leader slot (both-leader swaps permute leadership,
    # leaving the histogram unchanged)
    lead_xor = jnp.logical_xor(lead1, lead2)
    lsub = jnp.where(lead_xor, jnp.where(lead1, b_old, b2), B)
    ladd = jnp.where(lead_xor, jnp.where(lead1, b2, b_old), B)
    d_lead_xsw = f_cnt(lsub, ladd, st.lcnt, m.leader_band[0], m.leader_band[1])

    dpen_rep = d_cnt + d_lead_rep + d_rack + d_div1
    dpen_lsw = d_lead_lsw
    dpen_xsw = d_div1 + d_div2 + d_lead_xsw
    dpen = jnp.where(
        is_rep, dpen_rep, jnp.where(is_lsw, dpen_lsw, dpen_xsw)
    ).astype(i32)

    # --- accept -------------------------------------------------------
    # penalty scale as data (m.lam, docs/PORTFOLIO.md): exact in
    # float32 for the default config — bit-identical to the historical
    # int `SCALE_W*dw - LAMBDA*dpen`
    delta = (SCALE_W * dw).astype(jnp.float32) - m.lam * dpen.astype(
        jnp.float32
    )
    accept = jnp.logical_and(
        valid,
        jnp.logical_or(
            delta >= 0,
            _u01(row[6]) < jnp.exp(delta / jnp.maximum(temp, 1e-6)),
        ),
    )

    # --- apply (single-element writes; rejected moves write back) -----
    acc_i = accept.astype(i32)
    # site writes: (p, i1) <- v1 ; (pw2, i2) <- v2
    i1 = jnp.where(is_lsw, 0, s1)
    v1 = jnp.where(is_lsw, bf, b_in)
    pw2 = jnp.where(is_xsw, p2, p)
    i2 = jnp.where(is_lsw, s_lsw, jnp.where(is_xsw, s2, s1))
    v2 = jnp.where(is_lsw, bl, jnp.where(is_xsw, b_old, b_in))
    a = st.a
    a = a.at[p, i1].set(jnp.where(accept, v1, a[p, i1]))
    a = a.at[pw2, i2].set(jnp.where(accept, v2, a[pw2, i2]))

    # count updates (replace only for cnt/rcnt)
    upd_c = acc_i * is_rep.astype(i32)
    cnt = st.cnt.at[b_old].add(-upd_c).at[b_in].add(upd_c)
    rcnt = st.rcnt.at[r_old].add(-upd_c).at[r_in].add(upd_c)
    # leader count updates, unified across move types
    l_from = jnp.where(
        is_rep,
        jnp.where(lead1, b_old, B),
        jnp.where(is_lsw, bl, lsub),
    )
    l_to = jnp.where(
        is_rep,
        jnp.where(lead1, b_in, B),
        jnp.where(is_lsw, bf, ladd),
    )
    upd_l = acc_i * jnp.logical_and(l_from < B, l_to < B).astype(i32)
    lcnt = st.lcnt.at[l_from].add(-upd_l).at[l_to].add(upd_l)

    return ChainState(
        a=a,
        cnt=cnt,
        lcnt=lcnt,
        rcnt=rcnt,
        pen=st.pen + jnp.where(accept, dpen, 0),
        w=st.w + jnp.where(accept, dw, 0),
        key=st.key,
    )


def make_round_runner(steps_per_round: int, axis_name: str | None):
    """Build the jittable (m, state, best) -> (state, best) round function:
    `steps_per_round` annealing steps, a feasible-best snapshot, and (on a
    mesh) migration of the global best into each shard's worst chain via
    ICI collectives. ``m`` is an argument (not a closure) so one compiled
    executable serves every same-shape instance."""

    def one_chain_steps(
        m: ModelArrays, st: ChainState, temp: jax.Array
    ) -> ChainState:
        # per-lane ladder scaling as data (docs/PORTFOLIO.md); exact
        # identity for the default config (x * 1.0 in float32)
        temp = temp * m.temp_scale
        key, sub = random.split(st.key)
        bits = random.bits(sub, (steps_per_round, 8), jnp.uint32)

        def body(s, row):
            return _anneal_step(m, s, temp, row), None

        st, _ = lax.scan(body, st, bits)
        return ChainState(
            a=st.a, cnt=st.cnt, lcnt=st.lcnt, rcnt=st.rcnt,
            pen=st.pen, w=st.w, key=key,
        )

    batched_steps = jax.vmap(one_chain_steps, in_axes=(None, 0, None))

    def run_round(m: ModelArrays, state: ChainState, best_k: jax.Array,
                  best_a: jax.Array, temp: jax.Array):
        state = batched_steps(m, state, temp)
        k = best_key(state)  # [N]
        improved = k > best_k
        best_k = jnp.where(improved, k, best_k)
        best_a = jnp.where(improved[:, None, None], state.a, best_a)

        if axis_name is not None:
            # ICI collectives: find the globally best chain this round and
            # clone it over every shard's worst chain (SURVEY.md §3.4)
            local_best = jnp.max(k)
            global_best = lax.pmax(local_best, axis_name)
            idx = jax.lax.axis_index(axis_name)
            am_owner = local_best == global_best
            owner = lax.pmin(jnp.where(am_owner, idx, jnp.iinfo(jnp.int32).max),
                             axis_name)
            is_owner = idx == owner
            src = jnp.argmax(k)
            leaves = (state.a[src], state.cnt[src], state.lcnt[src],
                      state.rcnt[src], state.pen[src], state.w[src])
            zeros = jax.tree.map(jnp.zeros_like, leaves)
            picked = jax.tree.map(
                lambda x, z: jnp.where(is_owner, x, z), leaves, zeros
            )
            ga, gcnt, glcnt, grcnt, gpen, gw = jax.tree.map(
                lambda x: lax.psum(x, axis_name), picked
            )
            dst = jnp.argmin(k)

            def put(arr, val):
                return arr.at[dst].set(val)

            state = ChainState(
                a=put(state.a, ga),
                cnt=put(state.cnt, gcnt),
                lcnt=put(state.lcnt, glcnt),
                rcnt=put(state.rcnt, grcnt),
                pen=put(state.pen, gpen),
                w=put(state.w, gw),
                key=state.key,
            )
        return state, best_k, best_a

    return run_round


def make_lane_solver_fn(
    n_chains: int,
    steps_per_round: int,
    axis_name: str | None = None,
):
    """Batched multi-instance form of :func:`make_solver_fn`: L
    independent lanes (stacked models + seeds + keys, one padded bucket
    shape) anneal in ONE dispatch — ``(m_stack [L, ...], seeds
    [L, P, R], keys [L, 2], temps [rounds]) -> (best_a [L, P, R],
    best_k [L], curve [L, rounds])``. Plain ``jax.vmap`` over the lane
    axis: per-lane trajectories are bit-identical to solving each lane
    alone with the same key (the migration collectives vmap per lane —
    a lane's chains only ever migrate within that lane)."""
    solve = make_solver_fn(n_chains, steps_per_round, axis_name)
    return jax.vmap(solve, in_axes=(0, 0, 0, None))


def make_solver_fn(
    n_chains: int,
    steps_per_round: int,
    axis_name: str | None = None,
):
    """Full anneal as one jittable function: model + seed [P, R] + base key
    + temps [rounds] -> (best_a [P, R], best_key scalar, curve [rounds])
    for this shard. The model AND the temperature ladder are runtime
    arguments, so one compiled executable covers every same-shape instance
    and every schedule segment — which is what lets the engine run the
    anneal in clock-checked chunks (``time_limit_s``) without recompiling
    per chunk."""
    run_round = make_round_runner(steps_per_round, axis_name)

    def solve(m: ModelArrays, a_seed: jax.Array, key: jax.Array,
              temps: jax.Array):
        keys = random.split(key, n_chains)
        state = jax.vmap(lambda k: init_chain(m, a_seed, k))(keys)
        # snapshot the SEED itself before any annealing: high-temperature
        # rounds may never re-reach a good (often near-optimal) warm start,
        # so the final answer must be at least as good as the seed
        best_k = best_key(state)
        best_a = jnp.broadcast_to(
            a_seed.astype(jnp.int32), (n_chains, *a_seed.shape)
        )
        if axis_name is not None:
            # under shard_map the chains are device-varying (their RNG keys
            # are sharded) while seed/model are replicated; the scan carry
            # must be uniformly varying — pcast only the unvarying leaves
            # (identity on pre-vma jax, see sweep._make_to_varying)
            from .sweep import _make_to_varying

            to_varying = _make_to_varying(axis_name)
            state, best_k, best_a = jax.tree.map(
                to_varying, (state, best_k, best_a)
            )

        def body(carry, temp):
            state, bk, ba = carry
            state, bk, ba = run_round(m, state, bk, ba, temp)
            return (state, bk, ba), jnp.max(bk)  # best-score curve point

        (state, best_k, best_a), curve = lax.scan(
            body, (state, best_k, best_a), temps
        )
        top = jnp.argmax(best_k)
        return best_a[top], best_k[top], curve

    return solve
