"""Device-array view of a ProblemInstance (the L1-L3 model lowered to HBM).

This is the host->device boundary of the TPU solve stack (SURVEY.md §3.4):
everything the annealing engine and the scoring kernels need, as a single
pytree of jnp arrays, replicated across the mesh (the *candidates* are
sharded, the *model* is not — it is a few MB even at 256 brokers x 10k
partitions).

Penalty weights: one unit of any constraint violation must always outweigh
the largest single-step objective gain (a weight-4 leader-keep), so the
search orders feasibility strictly above preservation while still letting
high-temperature sweeps tunnel through infeasible states.
"""

from __future__ import annotations

import dataclasses
import os as _os
import threading as _threading
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ...models.instance import ProblemInstance

# score = SCALE_W * weight - LAMBDA * total_violations
SCALE_W = 1
LAMBDA = 64


@dataclass(frozen=True)
class LaneConfig:
    """One lane's search configuration (docs/PORTFOLIO.md) — the host
    grammar for the per-lane config DATA the solver executables consume
    (``ModelArrays.lam`` / ``temp_scale`` / ``comp_enable``). Config is
    array data, never a compile-time constant: every config shares one
    lane-padded executable per bucket (the KAO110 contract — a config
    captured as a Python scalar in a ``make_*`` factory body would
    silently re-specialize the executable per config).

    - ``lam``: penalty scale. The default (``LAMBDA`` = 64) orders
      feasibility strictly above preservation; low-``lam`` lanes tunnel
      through penalized intermediate states tight bands otherwise
      freeze out.
    - ``temp_scale``: multiplier on the shared temperature ladder —
      lanes anneal the same schedule hotter or colder.
    - ``compound``: whether the lane ACCEPTS compound 2-move exchange
      proposals (the sweeps still run lane-invariantly; a disabled
      lane rejects them, keeping the one-executable contract).
    """

    lam: float = float(LAMBDA)
    temp_scale: float = 1.0
    compound: bool = True


DEFAULT_CONFIG = LaneConfig()

# the portfolio ladder (docs/PORTFOLIO.md): lane 0 is ALWAYS the
# default config — a portfolio can never do worse than the solo solve
# it replaces — and the rest trade penalty scale against temperature so
# at least one lane can cross whichever barrier froze the others out.
PORTFOLIO_TABLE = (
    DEFAULT_CONFIG,                        # anchor: the solo config
    LaneConfig(lam=8.0),                   # tunneler: cheap violations
    LaneConfig(temp_scale=4.0),            # hot ladder: wide exploration
    LaneConfig(lam=256.0, temp_scale=0.5),  # strict quench
    LaneConfig(lam=4.0, temp_scale=2.0),   # hot + soft
    LaneConfig(compound=False),            # plain move set (pre-PR-11)
    LaneConfig(lam=16.0, temp_scale=0.25),  # near-greedy cold descent
    LaneConfig(lam=128.0, temp_scale=2.0),  # hot + strict
)


# --------------------------------------------------------------------------
# adaptive portfolio table (ISSUE 12 satellite; the PR-11 follow-on
# named in ROADMAP item 3). kao_portfolio_winner_total is the evidence
# stream: a lane config that NEVER wins is a device slot the diversity
# table should respend. Env-gated — KAO_PORTFOLIO_ADAPT=1 reorders the
# table once enough evidence exists (winners first, never-winners
# demoted toward the tail, where widths below the table length drop
# them); with the gate off the table is PINNED to the static order
# above, bit-for-bit, so default solves stay reproducible.
# --------------------------------------------------------------------------

_ADAPT_LOCK = _threading.Lock()
_ADAPT_WINS = [0] * len(PORTFOLIO_TABLE)
_ADAPT_SOLVES = [0]
# evidence floor: below this many portfolio solves the table never
# reorders, even with the gate on — a single lucky win must not
# reshuffle the race
ADAPT_MIN_SOLVES = 16


def portfolio_adapt_enabled() -> bool:
    return _os.environ.get("KAO_PORTFOLIO_ADAPT", "").lower() not in (
        "", "0", "false", "no",
    )


def note_portfolio_result(winner: LaneConfig | None) -> None:
    """One finished portfolio solve: ``winner`` is the lane config that
    produced the final plan (None when no lane won outright — e.g. the
    constructor's plan was adopted). The engine calls this once per
    portfolio solve, gate on or off, so evidence is already banked when
    an operator flips KAO_PORTFOLIO_ADAPT on."""
    with _ADAPT_LOCK:
        _ADAPT_SOLVES[0] += 1
        if winner is not None:
            try:
                _ADAPT_WINS[PORTFOLIO_TABLE.index(winner)] += 1
            except ValueError:
                pass  # a custom config outside the table: no slot


def reset_portfolio_adapt() -> None:
    with _ADAPT_LOCK:
        _ADAPT_SOLVES[0] = 0
        for i in range(len(_ADAPT_WINS)):
            _ADAPT_WINS[i] = 0


def portfolio_adapt_snapshot() -> dict:
    """The adaptation evidence + the order currently in force
    (serve's /healthz portfolio section)."""
    with _ADAPT_LOCK:
        wins = list(_ADAPT_WINS)
        solves = _ADAPT_SOLVES[0]
    enabled = portfolio_adapt_enabled()
    order = _adapted_order(wins) if (
        enabled and solves >= ADAPT_MIN_SOLVES
    ) else list(range(len(PORTFOLIO_TABLE)))
    return {
        "enabled": enabled,
        "solves": solves,
        "min_solves": ADAPT_MIN_SOLVES,
        "wins": wins,
        "order": order,
        "adapted": order != list(range(len(PORTFOLIO_TABLE))),
    }


def _adapted_order(wins: list[int]) -> list[int]:
    """Lane 0 stays the default config (the portfolio's can-never-lose
    anchor); the rest sort by win count descending, original order
    breaking ties — never-winners sink to the tail and fall out of any
    width below the table length."""
    tail = sorted(range(1, len(PORTFOLIO_TABLE)),
                  key=lambda i: (-wins[i], i))
    return [0] + tail


def portfolio_configs(width: int) -> list[LaneConfig]:
    """The first ``width`` portfolio lane configs (cycling past the
    table, which no default reaches). Lane 0 is the default config.
    With ``KAO_PORTFOLIO_ADAPT`` set and enough evidence banked, the
    table order adapts (winners first, never-winners demoted)."""
    w = max(1, int(width))
    table = PORTFOLIO_TABLE
    if portfolio_adapt_enabled():
        with _ADAPT_LOCK:
            wins = list(_ADAPT_WINS)
            solves = _ADAPT_SOLVES[0]
        if solves >= ADAPT_MIN_SOLVES:
            table = tuple(PORTFOLIO_TABLE[i]
                          for i in _adapted_order(wins))
    return [table[i % len(table)] for i in range(w)]


def band_pen(c, lo, hi):
    """Integer band-violation magnitude of count ``c`` vs [lo, hi] —
    shared by both annealing engines' accept decisions; must match the
    numpy oracle (``ProblemInstance.violations``) exactly."""
    return jnp.maximum(c - hi, 0) + jnp.maximum(lo - c, 0)


def u01(bits):
    """uint32 -> uniform float32 in [0, 1) via the top 24 bits."""
    return (bits >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(
        1.0 / (1 << 24)
    )


def geometric_temps(t_hi: float, t_lo: float, n: int) -> jax.Array:
    """The shared annealing temperature ladder. Built in numpy: each
    eager jnp op here would compile its own tiny executable, and over a
    tunneled TPU every one of those costs a ~0.5 s round-trip to the
    remote compiler — measured r5, the eager setup ops were ~6 s of a
    ~30 s cold solve.

    Computed END TO END in float32: the ladder the device consumes is
    float32, so building it in float64 and rounding at the edge made
    the exact temps depend on the host's float64 `**` — a checkpoint
    resumed under a different numpy could replay a different trajectory.
    """
    f = np.float32
    expo = np.arange(n, dtype=np.float32) / f(max(n - 1, 1))
    ladder = f(t_hi) * (f(t_lo) / f(t_hi)) ** expo
    return jnp.asarray(ladder.astype(np.float32))


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class ModelArrays:
    """Replicated model constants. Shapes: B brokers (+1 null bucket),
    P partitions, R max replication factor, K racks (+1 null rack)."""

    a0: jax.Array  # [P, R] int32 current assignment, null = B
    rf: jax.Array  # [P] int32
    slot_valid: jax.Array  # [P, R] bool
    w_lead: jax.Array  # [P, B+1] int32
    w_foll: jax.Array  # [P, B+1] int32
    rack_of: jax.Array  # [B+1] int32, null broker -> K
    broker_band: jax.Array  # [2] int32 (lo, hi)
    leader_band: jax.Array  # [2] int32 (lo, hi)
    rack_lo: jax.Array  # [K+1] int32 (null rack: 0)
    rack_hi: jax.Array  # [K+1] int32 (null rack: huge)
    part_rack_hi: jax.Array  # [P] int32
    # lane config as DATA (docs/PORTFOLIO.md): scalar leaves, so every
    # config shares one executable per bucket shape — jit keys on
    # shapes, and () float32 is () float32 for every config
    lam: jax.Array  # [] float32 penalty scale (default: LAMBDA)
    temp_scale: jax.Array  # [] float32 temperature-ladder multiplier
    comp_enable: jax.Array  # [] float32 1.0/0.0 compound-exchange gate

    @property
    def num_parts(self) -> int:
        return self.a0.shape[0]

    @property
    def max_rf(self) -> int:
        return self.a0.shape[1]

    @property
    def num_brokers(self) -> int:
        return self.w_lead.shape[1] - 1

    @property
    def num_racks(self) -> int:
        return self.rack_lo.shape[0] - 1


def from_instance(
    inst: ProblemInstance,
    num_parts: int | None = None,
    max_rf: int | None = None,
    config: LaneConfig | None = None,
) -> ModelArrays:
    """Lower an instance to device arrays, optionally padded up to a
    canonical bucket shape (``solvers.tpu.bucket``) so every instance in
    a bucket shares one set of jitted executables.

    Padded partition rows are INERT by the same mechanism that already
    makes short replica lists inert: ``rf = 0`` and ``slot_valid`` all
    false, so their slots null out to broker ``B`` in every histogram,
    their weights are zero, their ``part_rack_hi`` is 0 with zero rack
    counts, and both engines' proposal machinery rejects or no-ops moves
    on them (``rf > 0`` guards). Padded slot columns (``max_rf``) are
    plain invalid slots. The padding is all host-side numpy — one
    ``jnp.asarray`` per field, exactly like the unpadded path, so no
    extra tiny executables compile."""
    B, K = inst.num_brokers, inst.num_racks
    big = np.iinfo(np.int32).max // 4
    rack_lo = np.concatenate([inst.rack_lo, [0]]).astype(np.int32)
    rack_hi = np.concatenate([inst.rack_hi, [big]]).astype(np.int32)
    P, R = inst.num_parts, inst.max_rf
    Pp = P if num_parts is None else max(int(num_parts), P)
    Rp = R if max_rf is None else max(int(max_rf), R)
    a0, rf, slot_valid = inst.a0, inst.rf, inst.slot_valid
    w_leader, w_follower = inst.w_leader, inst.w_follower
    part_rack_hi = inst.part_rack_hi
    if (Pp, Rp) != (P, R):
        a0 = np.full((Pp, Rp), B, np.int32)
        a0[:P, :R] = inst.a0
        rf = np.zeros(Pp, np.int32)
        rf[:P] = inst.rf
        slot_valid = np.zeros((Pp, Rp), bool)
        slot_valid[:P, :R] = inst.slot_valid
        w_leader = np.zeros((Pp, B + 1), np.int32)
        w_leader[:P] = inst.w_leader
        w_follower = np.zeros((Pp, B + 1), np.int32)
        w_follower[:P] = inst.w_follower
        part_rack_hi = np.zeros(Pp, np.int32)
        part_rack_hi[:P] = inst.part_rack_hi
    return ModelArrays(
        a0=jnp.asarray(a0, jnp.int32),
        rf=jnp.asarray(rf, jnp.int32),
        slot_valid=jnp.asarray(slot_valid),
        w_lead=jnp.asarray(w_leader, jnp.int32),
        w_foll=jnp.asarray(w_follower, jnp.int32),
        rack_of=jnp.asarray(inst.rack_of_broker, jnp.int32),
        broker_band=jnp.asarray([inst.broker_lo, inst.broker_hi], jnp.int32),
        leader_band=jnp.asarray([inst.leader_lo, inst.leader_hi], jnp.int32),
        rack_lo=jnp.asarray(rack_lo),
        rack_hi=jnp.asarray(rack_hi),
        part_rack_hi=jnp.asarray(part_rack_hi, jnp.int32),
        **_config_leaves(config or DEFAULT_CONFIG),
    )


def _config_leaves(cfg: LaneConfig) -> dict:
    """The config fields as device scalars — float32 end to end (the
    accept arithmetic is float32; KAO103 discipline)."""
    return {
        "lam": jnp.asarray(np.float32(cfg.lam)),
        "temp_scale": jnp.asarray(np.float32(cfg.temp_scale)),
        "comp_enable": jnp.asarray(np.float32(1.0 if cfg.compound
                                              else 0.0)),
    }


def with_config(m: ModelArrays, cfg: LaneConfig) -> ModelArrays:
    """``m`` with its config leaves replaced — the cheap way to build a
    portfolio stack: the heavy model tables are SHARED across lanes on
    the host (``stack_models`` materializes the lane axis once, on
    device)."""
    return dataclasses.replace(m, **_config_leaves(cfg))


def model_config(m: ModelArrays) -> dict:
    """Host-readable view of a model's config leaves (provenance in
    stats / flight records — docs/PORTFOLIO.md)."""
    return {
        "lam": float(np.asarray(m.lam)),
        "temp_scale": float(np.asarray(m.temp_scale)),
        "compound": bool(float(np.asarray(m.comp_enable)) > 0.5),
    }


def stack_models(models: list[ModelArrays]) -> ModelArrays:
    """Stack L same-shape models along a new leading LANE axis — the
    batched multi-instance form the lane solvers consume (one padded
    bucket shape, L independent instances). Every field gains a leading
    ``[L]`` axis; the result is only meaningful under ``jax.vmap``
    (its shape-derived properties would read the lane axis), so callers
    treat it as an opaque pytree. Raises ValueError on shape skew —
    lanes must already share a bucket (same padded P/R and exact B/K)."""
    if not models:
        raise ValueError("stack_models needs at least one model")
    first = [x.shape for x in jax.tree_util.tree_leaves(models[0])]
    for m in models[1:]:
        got = [x.shape for x in jax.tree_util.tree_leaves(m)]
        if got != first:
            raise ValueError(
                "lane models disagree on shape; pad every instance to a "
                f"common bucket first (expected {first}, got {got})"
            )
    return jax.tree.map(lambda *xs: jnp.stack(xs), *models)


def pad_candidate(a: np.ndarray, m: ModelArrays) -> np.ndarray:
    """Pad a host-side candidate ``[P, R]`` up to a (possibly bucketed)
    model's ``[Pp, Rp]`` with the null broker, so padded rows read as
    empty partitions everywhere (see :func:`from_instance`)."""
    a = np.asarray(a, dtype=np.int32)
    Pp, Rp = m.a0.shape
    if a.shape == (Pp, Rp):
        return a
    out = np.full((Pp, Rp), m.num_brokers, np.int32)
    out[: a.shape[0], : a.shape[1]] = a
    return out


def unpad_candidate(a, inst: ProblemInstance) -> np.ndarray:
    """Slice a (possibly bucket-padded) candidate back to the instance's
    real ``[P, R]`` shape — identity when no padding was applied."""
    return np.asarray(a, dtype=np.int32)[: inst.num_parts, : inst.max_rf]
