"""``--solver=tpu`` — the JAX/TPU combinatorial search backend (C17).

Replaces the reference's external native lp_solve MILP solve
(``/root/reference/README.md:135-137``) with the engine BASELINE.json:5
specifies: a population of candidate assignments annealed in HBM by
vmapped Metropolis chains (``.anneal``), seeded from a greedy host-side
repair of the current assignment (``.seed``), sharded across the device
mesh with ICI best-migration (``parallel.mesh``), and verified against the
exact numpy scorer before the plan is emitted.

North-star target (BASELINE.json): plan quality <= lp_solve's move count,
<5 s wall-clock at 256 brokers / 10k partitions / RF=3 on a v5e-8.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ...analysis import sanitize as _san
from ...models import instance as _instance_mod
from ...models.instance import ProblemInstance
from ...obs import flight as _flight
from ...obs import log as _olog
from ...obs import trace as _otrace
from ...resilience import chaos as _chaos
from ...resilience import ladder as _ladder
from ...resilience.budget import Budget
from ...utils import checkpoint as ckpt
from ..base import SolveResult, register
from . import arrays
from . import constructor as _constructor
from .seed import greedy_seed

# swappable constructor interface (ISSUE 10, docs/CONSTRUCTOR.md): the
# vectorized host constructor is the default; the legacy per-partition
# implementation stays selectable as the oracle / fallback rung
# (KAO_CONSTRUCTOR=legacy, or set_constructor_impl("legacy") in
# process). Re-exported here because the engine is the constructor's
# one orchestration point — every solve enters through these workers.
set_constructor_impl = _constructor.set_impl
constructor_impl = _constructor.active


# partition count at which the sweep-parallel engine takes over from the
# per-move Metropolis chains OFF-TPU: above this, sequential chain steps
# dominate wall-clock (one move per step), while a sweep applies up to
# min(P, B) moves per fused step. On TPU the sweep engine is the default
# at every size (see _defaults).
_SWEEP_THRESHOLD_PARTS = 512

# tokens for AOT compiles running on daemon threads (GIL-atomic set
# ops); a long-lived service consults this before jax.clear_caches()
_PENDING_AOT: set = set()

# how long the solve waits for the LP/MILP plan constructor before
# starting the annealer (seconds); the "big" value applies past the
# aggregation threshold, where the constructor is the only path to a
# certificate and the alternative is a minutes-long first compile.
# Module-level so tests can pin the race deterministically.
_CONSTRUCT_WAIT_S = 5.0
_CONSTRUCT_WAIT_BIG_S = 45.0
# middle tier, greedy+reseat racer ONLY: at tens of thousands of
# members the reseat needs ~4-5 s — just past the snappy cap — and the
# reseat worker always terminates in seconds (greedy + canceller +
# certify, no LP), so the headroom never stalls a solve; missing the
# window would buy a cold process a minutes-long first compile
_CONSTRUCT_WAIT_MID_S = 15.0
_RESEAT_WAIT_MID_MEMBERS = 20_000

# tiny-instance exact race (VERDICT r3 item 7): below these sizes the
# exact MILP solves in milliseconds, so a DEFAULTED solve races it like
# the LP constructor and a cold demo-sized request returns a certified
# optimum without compiling or touching the device. Explicit engine /
# budget knobs opt out — a caller tuning the search wants the search.
_EXACT_RACE_PARTS = 64
_EXACT_RACE_VARS = 20_000  # 2 * brokers * partitions, the MILP var count

# pipelined ladder dispatch (docs/PIPELINE.md): dispatch chunk i+1
# before retiring chunk i, so the host boundary work — curve transfer,
# best-tracking, certificates, checkpointing — overlaps the next
# chunk's device execution instead of leaving the accelerator idle.
# PRNG keys are split in deterministic order up front and the sweep
# state carries its own RNG, so speculation never changes a trajectory.
# Opt out per solve (pipeline=False / --no-pipeline) or process-wide
# via KAO_NO_PIPELINE=1 for A/B runs and debugging. Falsy spellings
# ("0"/"off"/"false"/"none") leave the pipeline ON — same convention
# as KAO_BUCKETS (solvers.tpu.bucket).
_PIPELINE_DEFAULT = os.environ.get("KAO_NO_PIPELINE", "").lower() in (
    "", "off", "0", "none", "false",
)

# portfolio lanes (ISSUE 11, docs/PORTFOLIO.md): a defaulted sweep
# solve races KAO_PORTFOLIO_WIDTH diverse lane configurations —
# distinct penalty scales, temperature-ladder multipliers, and move
# sets (arrays.PORTFOLIO_TABLE) — through the SAME lane-padded
# executable the batched multi-tenant path compiles per bucket (config
# is data: scalar ModelArrays leaves, so no per-config specialization).
# First lane to certify at a chunk boundary retires the remaining
# ladder; otherwise final selection reduces across every lane's
# per-shard winners. Opt out per solve (portfolio=False /
# --no-portfolio) or process-wide via KAO_NO_PORTFOLIO=1; falsy
# spellings leave it ON — same convention as KAO_NO_PIPELINE.
_PORTFOLIO_DEFAULT = os.environ.get("KAO_NO_PORTFOLIO", "").lower() in (
    "", "off", "0", "none", "false",
)


def _env_portfolio_width() -> int:
    """``KAO_PORTFOLIO_WIDTH`` with the same malformed-override
    convention as KAO_BUCKETS/KAO_LANE_BUCKETS (solvers.tpu.bucket):
    unparsable values fall back to the default instead of crashing the
    first engine import. Width 1 is legal and means 'no racing'."""
    raw = os.environ.get("KAO_PORTFOLIO_WIDTH", "").strip()
    if not raw:
        return 8
    try:
        return max(1, int(raw))
    except ValueError:
        return 8


_PORTFOLIO_WIDTH = _env_portfolio_width()


def set_portfolio_default(enabled: bool) -> None:
    """Process-wide default for solves that do not pass ``portfolio=``
    explicitly (serve's ``--no-portfolio`` flag lands here)."""
    global _PORTFOLIO_DEFAULT
    _PORTFOLIO_DEFAULT = bool(enabled)


def portfolio_width_default() -> int:
    """The width a defaulted portfolio solve races (serve /healthz)."""
    return _PORTFOLIO_WIDTH if _PORTFOLIO_DEFAULT else 1


def _resolve_portfolio_width(portfolio) -> int:
    """Resolve the ``portfolio`` knob to a lane count: None defers to
    the process default, booleans toggle the default width, an int >= 2
    names the width directly. 1 (or False) means off."""
    if portfolio is None:
        return _PORTFOLIO_WIDTH if _PORTFOLIO_DEFAULT else 1
    if isinstance(portfolio, bool):
        return _PORTFOLIO_WIDTH if portfolio else 1
    return max(1, int(portfolio))


def _leaves_alive(tree) -> bool:
    """False when any array in ``tree`` was consumed by a donating
    dispatch. The Pallas→XLA retry must not re-dispatch a consumed
    state: a Mosaic error raised at EXECUTION time (after donation)
    leaves nothing to retry on, and the real error should surface
    instead of a confusing "buffer deleted" from the retry. Delegates
    to the mesh layer's donation-liveness predicate (lazily — the
    constructed fast path never imports device-adjacent modules)."""
    if tree is None:
        return True
    from ...parallel.mesh import _args_alive

    return _args_alive(tree)


def set_pipeline_default(enabled: bool) -> None:
    """Process-wide default for solves that do not pass ``pipeline=``
    explicitly (serve's ``--no-pipeline`` flag lands here)."""
    global _PIPELINE_DEFAULT
    _PIPELINE_DEFAULT = bool(enabled)


# ladder megachunks (ISSUE 17, docs/PIPELINE.md): fuse K consecutive
# sweep chunks into ONE device-resident scan dispatch, so the warm
# ladder pays one host round-trip per K chunks instead of per chunk.
# KAO_MEGACHUNK=auto|1|K with the KAO_PORTFOLIO_ADAPT convention:
# unset keeps the per-chunk path (static default — bit-for-bit the
# pre-megachunk ladder), an integer pins the fused width, and "auto"
# opts into the evidence-driven per-bucket chooser below — Automap-
# style measure-then-choose, never a hand-written constant.
def _env_megachunk():
    """Parse KAO_MEGACHUNK: None (unset/off), "auto", or a width >= 1.
    Malformed overrides fall back to unset instead of crashing the
    first engine import (KAO_BUCKETS convention)."""
    raw = os.environ.get("KAO_MEGACHUNK", "").strip().lower()
    if not raw or raw in ("0", "off", "none", "false"):
        return None
    if raw == "auto":
        return "auto"
    try:
        return max(1, int(raw))
    except ValueError:
        return None


_MEGACHUNK_DEFAULT = _env_megachunk()


def set_megachunk_default(value) -> None:
    """Process-wide default for solves that do not pass ``megachunk=``
    explicitly (serve's ``--megachunk`` flag lands here): None/"off"
    keeps the per-chunk ladder, "auto" engages the evidence table, an
    int pins the width."""
    global _MEGACHUNK_DEFAULT
    if isinstance(value, str):
        value = value.strip().lower()
        if value in ("", "0", "off", "none", "false"):
            value = None
        elif value != "auto":
            value = max(1, int(value))
    elif isinstance(value, bool):
        value = "auto" if value else None
    elif value is not None:
        value = max(1, int(value))
    _MEGACHUNK_DEFAULT = value


def megachunk_default():
    """The resolved process default (serve /healthz)."""
    return _MEGACHUNK_DEFAULT


# per-bucket fusion-width evidence (the PR 11/12 note_* style — see
# arrays.note_portfolio_result): every sweep ladder files its measured
# dispatch/device wall split under its executable identity, and the
# "auto" chooser picks the smallest width that makes per-dispatch host
# overhead a <= MEGA_HOST_FRACTION share of a fused group's wall. On
# CPU test meshes dispatch overhead is a rounding error next to chunk
# device time, so auto resolves to 1 and CI trajectories never move.
_MEGA_CANDIDATES = (1, 2, 4, 8)
MEGA_MIN_SOLVES = 16  # evidence quorum before auto departs from 1
MEGA_HOST_FRACTION = 0.05
_MEGA_LOCK = threading.Lock()
_MEGA_EVIDENCE: dict = {}


def note_megachunk_evidence(key: tuple, *, dispatches: int,
                            dispatch_s: float, chunks: int,
                            device_s: float) -> None:
    """File one ladder's measured split under its executable identity
    ``key``. Totals accumulate (means stay stable as solves land);
    the table is process-local, like the portfolio adapt table."""
    if dispatches <= 0 or chunks <= 0:
        return
    with _MEGA_LOCK:
        ev = _MEGA_EVIDENCE.setdefault(key, {
            "solves": 0, "dispatches": 0, "dispatch_s": 0.0,
            "chunks": 0, "device_s": 0.0,
        })
        ev["solves"] += 1
        ev["dispatches"] += int(dispatches)
        ev["dispatch_s"] += float(dispatch_s)
        ev["chunks"] += int(chunks)
        ev["device_s"] += float(device_s)


def choose_megachunk_k(key: tuple) -> int:
    """Evidence-driven width for ``key``: with per-dispatch overhead
    ``o`` and per-chunk device wall ``d``, the smallest candidate K
    holding ``o <= MEGA_HOST_FRACTION * (o + K*d)`` — i.e. fuse just
    enough that the host round-trip stops mattering. Returns 1 until
    MEGA_MIN_SOLVES solves of evidence exist (never guesses)."""
    with _MEGA_LOCK:
        ev = _MEGA_EVIDENCE.get(key)
        if ev is None or ev["solves"] < MEGA_MIN_SOLVES:
            return 1
        o = ev["dispatch_s"] / max(1, ev["dispatches"])
        d = ev["device_s"] / max(1, ev["chunks"])
    for k in _MEGA_CANDIDATES:
        if o <= MEGA_HOST_FRACTION * (o + k * d):
            return k
    return _MEGA_CANDIDATES[-1]


def megachunk_snapshot() -> dict:
    """Evidence-table snapshot for /healthz and tests."""
    with _MEGA_LOCK:
        keys = list(_MEGA_EVIDENCE)
        buckets = {
            repr(k): dict(v) for k, v in _MEGA_EVIDENCE.items()
        }
    return {
        "default": _MEGACHUNK_DEFAULT,
        "min_solves": MEGA_MIN_SOLVES,
        "host_fraction": MEGA_HOST_FRACTION,
        "candidates": list(_MEGA_CANDIDATES),
        "buckets": buckets,
        "chosen": {repr(k): choose_megachunk_k(k) for k in keys},
    }


def reset_megachunk_adapt() -> None:
    """Tests: drop accumulated fusion evidence."""
    with _MEGA_LOCK:
        _MEGA_EVIDENCE.clear()


def _resolve_megachunk(megachunk, engine_mod_supports: bool, multi: bool,
                       n_chunks: int, evidence_key: tuple) -> tuple:
    """Resolve the ``megachunk`` knob to ``(K, mode)``. 1 unless the
    engine supports fusion and the ladder has >1 chunk. Explicit param
    beats the process default; "auto" reads the evidence table — but
    never under multi-controller SPMD, where per-process evidence
    could fork executables across workers and deadlock the pod
    (explicit widths are process-invariant and stay allowed)."""
    if not engine_mod_supports or n_chunks <= 1:
        return 1, "off"
    v = megachunk if megachunk is not None else _MEGACHUNK_DEFAULT
    if isinstance(v, bool):
        v = "auto" if v else None
    if isinstance(v, str) and v.strip().lower() != "auto":
        try:
            v = max(1, int(v))
        except ValueError:
            v = None
    if v is None or v == 1:
        return 1, "off"
    if v == "auto":
        if multi:
            return 1, "off"
        k = choose_megachunk_k(evidence_key)
        return (max(1, min(k, n_chunks)), "auto")
    return max(1, min(int(v), n_chunks)), "static"


class _WarmChunkRegistry:
    """Cross-solve warm per-chunk duration estimates, keyed by the
    executable identity a chunk actually dispatches — (path tag, mesh
    size, chains, budget knobs, bucket shape, chunk length, scorer).
    The batched lane path tags its keys with ``("lanes", L, ...)`` and
    the sequential path with ``("single", ...)``, so a slow first
    batched chunk (L lanes of device work per dispatch) can never
    inflate the sequential path's deadline estimate — and vice versa.
    Values are REPLACED per solve (the latest solve's own warm minimum),
    so a one-off slow solve does not poison the estimate forever."""

    def __init__(self, capacity: int = 64):
        self._cap = capacity
        self._lock = threading.Lock()
        self._d: OrderedDict[tuple, float] = OrderedDict()

    def get(self, key: tuple) -> float | None:
        with self._lock:
            v = self._d.get(key)
            if v is not None:
                self._d.move_to_end(key)
            return v

    def update(self, key: tuple, seconds: float) -> None:
        with self._lock:
            self._d.pop(key, None)
            self._d[key] = float(seconds)
            while len(self._d) > self._cap:
                self._d.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._d.clear()


_WARM_CHUNKS = _WarmChunkRegistry()

# the greedy+reseat racer (r4): on slack-caps instances the greedy seed
# already keeps every keepable member, so the exact leader reseat alone
# often reaches BOTH bounds — a certified optimum in host-side seconds
# with no compile and no device (measured: the 50k-partition adv50k
# default solve drops from ~12 s warm / ~80 s cold to ~5 s either way).
# Module-level so tests can pin the annealer path deterministically.
_RESEAT_RACE = True


def _defaults(inst: ProblemInstance, platform: str, engine: str | None) -> dict:
    """Search-effort defaults for the RESOLVED engine: scale chains with
    the hardware, steps with the problem. CPU (CI) stays small; TPU uses
    the full batch. The engine must be resolved first — each engine's
    budget is meaningless for the other (a chain budget of 256 sweeps
    would leave the chain engine 1000x under-searched and vice versa)."""
    P = inst.num_parts
    on_tpu = platform == "tpu"
    if engine is not None and engine not in ("chain", "sweep"):
        raise ValueError(
            f"unknown tpu engine {engine!r}; expected 'chain' or 'sweep'"
        )
    # TPU always prefers the sweep engine: measured on v5e (r2), even a
    # 10-partition demo solves 10x faster warm through the Mosaic sweep
    # kernels than through the chain engine's sequential Metropolis scan
    # (0.34 s vs 3.6 s; compile 4 s vs 29 s), at equal quality. The
    # chain engine remains the small-instance default off-TPU, where its
    # O(RF) per-step work beats sweeping whole small populations.
    engine = engine or (
        "sweep" if (on_tpu or P >= _SWEEP_THRESHOLD_PARTS) else "chain"
    )
    if engine == "sweep":
        # sweep engine: sequential depth is `rounds` sweeps, flat in P;
        # chain count trades against per-sweep cost (O(chains * P)).
        # Measured on a real v5e chip (r2): per-sweep wall scales ~1:1
        # with chains (the proposal algebra is VPU/gather-bound, already
        # saturated at 8 chains x 10k partitions), so extra chains buy
        # quality only at full wall-clock price; 8 chains x 128 sweeps
        # reaches the provable move lower bound on the 256-broker/10k-
        # partition headline in ~3.5 s warm.
        return {
            "engine": "sweep",
            "batch": 8,
            "rounds": 128 if on_tpu else 64,
            "steps_per_round": 1,
        }
    return {
        "engine": "chain",
        "batch": 512 if on_tpu else 32,
        "rounds": 24,
        "steps_per_round": max(256, min(4 * P, 20_000)),
    }


@register("tpu")
def solve_tpu(inst: ProblemInstance, *args,
              trace: bool | str | None = None, **kwargs) -> SolveResult:
    """Traced entry point: ``trace=True`` (or a trace-ID string) records
    a span-level solve report (``obs.trace``) attached to the result as
    ``stats["solve_report"]`` and registered in the ``/debug/solves``
    ring buffer. Default is untraced — zero telemetry overhead — but an
    AMBIENT trace (the serving path wraps each request in one) still
    collects this solve's phase spans; the trace_id then lands in stats
    so the response can echo it.

    The degradation-rung collector (resilience.ladder) wraps the whole
    call: every rung any layer takes during this solve — mesh AOT
    fallbacks, Pallas→XLA retries, the chain-engine retry's own rungs —
    lands in ``stats["degradations"]`` exactly once, on the outermost
    solve.

    The flight recorder (obs.flight, docs/OBSERVABILITY.md) wraps it
    the same way: the OUTERMOST solve lands one compact cost+quality
    record — the accounting contextvar doubles as the nesting guard,
    so a sweep→chain retry or a batch lane running inside another
    recorded solve feeds the outer record instead of landing its own.
    Precompile (warmup) solves are synthetic and never recorded."""
    nested = _flight.accounting_active()
    acc_tok = None if nested else _flight.start_accounting()
    t0 = time.perf_counter()
    try:
        with _ladder.collect() as _rungs:
            res = _solve_tpu_traced(inst, *args, trace=trace, **kwargs)
            if _rungs:
                res.stats["degradations"] = list(_rungs)
    except BaseException as e:
        acc = (
            _flight.end_accounting(acc_tok) if acc_tok is not None
            else None
        )
        if acc is not None and not kwargs.get("precompile"):
            # a solve that RAISES must still burn the SLO quality
            # budget — an outage of the solve path reading as zero
            # burn would never page (docs/OBSERVABILITY.md)
            _flight.record_failure(inst, acc,
                                   time.perf_counter() - t0, e)
        raise
    acc = (
        _flight.end_accounting(acc_tok) if acc_tok is not None
        else None
    )
    if acc is not None and not kwargs.get("precompile"):
        _flight.record_solve(res, inst, acc,
                             wall_s=time.perf_counter() - t0)
    return res


def _solve_tpu_traced(inst: ProblemInstance, *args,
                      trace: bool | str | None = None,
                      **kwargs) -> SolveResult:
    tr = _otrace.begin(trace, name="solve_tpu")
    if tr is None:
        try:
            res = _solve_tpu(inst, *args, **kwargs)
        except FloatingPointError as e:
            # jax_debug_nans (sanitizer mode) surfaces device NaNs as
            # FloatingPointError at dispatch — count before propagating
            # (once per exception: nested solves share the object)
            _san.note_nan_abort_once(e, "solve_tpu")
            raise
        tid = _otrace.current_trace_id()
        if tid:
            res.stats.setdefault("trace_id", tid)
        return res
    try:
        res = _solve_tpu(inst, *args, **kwargs)
    except BaseException as e:
        if isinstance(e, FloatingPointError):
            _san.note_nan_abort_once(e, "solve_tpu")
        tr.root.set(error=repr(e)[:200])
        _otrace.finish(tr)
        raise
    res.stats["trace_id"] = tr.trace_id
    res.stats["solve_report"] = _otrace.finish(tr)
    return res


def _solve_tpu(
    inst: ProblemInstance,
    seed: int = 0,
    batch: int | None = None,
    rounds: int | None = None,
    sweeps: int | None = None,  # CLI alias for rounds
    steps_per_round: int | None = None,
    t_hi: float | None = None,
    t_lo: float | None = None,
    n_devices: int | None = None,
    engine: str | None = None,
    checkpoint: str | None = None,
    profile_dir: str | None = None,
    time_limit_s: float | None = None,
    cert_min_savings_s: float = 1.0,
    precompile: bool = False,
    pipeline: bool | None = None,
    portfolio: bool | int | None = None,
    warm_start: "np.ndarray | None" = None,
    budget: Budget | None = None,
    decompose: bool | None = None,
    megachunk: "bool | int | str | None" = None,
    **_unused,
) -> SolveResult:
    t0 = time.perf_counter()
    # the solve's ONE deadline/retry budget (resilience.budget): every
    # join, retry and wall-clock gate below asks it for remaining time
    # instead of re-deriving t0 + time_limit_s arithmetic — which is
    # what let a timed-out sweep grant its chain retry the full budget
    # again (satellite fix, ISSUE 6). A CALLER-owned budget (the watch
    # delta path, docs/WATCH.md) is honored instead of a fresh one: its
    # clock already includes queue wait, and cancel()ing it from another
    # thread retires this solve at the next boundary gate.
    if budget is None:
        budget = Budget(time_limit_s, t0=t0)
    # delta-API warm start (docs/WATCH.md): a previous plan adapted to
    # this instance's topology seeds the annealer. Structurally invalid
    # candidates are REJECTED onto the ladder (warm_start_rejected) and
    # the solve proceeds from scratch — never silently trusted.
    warm_start = _validate_warm_start(inst, warm_start)
    # double-buffered ladder dispatch (docs/PIPELINE.md): None defers
    # to the process default (--no-pipeline / KAO_NO_PIPELINE flip it)
    pipeline = _PIPELINE_DEFAULT if pipeline is None else bool(pipeline)
    if _san.enabled():
        # sanitizer mode (KAO_SANITIZE=1): debug_nans + log_compiles +
        # the recompile sentinel / donation guard in parallel.mesh
        _san.install()
    from ...utils.platform import enable_compile_cache, ensure_backend

    # a previous solve on this instance may have cancelled straggling
    # bound workers at its return (or tagged its constructor path);
    # this solve gets a fresh escalation and no stale construct_path to
    # mislabel stats. (The extends-greedy warm-start marker needs no
    # reset: it rides in the worker's RESULT tuple, scoped to this
    # solve's lp_fut — ADVICE r4 closed the cross-solve race a shared
    # instance flag had here.)
    inst._bounds_cancelled = False
    inst._construct_path = None
    # per-solve telemetry: the exact-flow decline counter accumulates
    # inside bound computations, so a repeat solve against the same
    # instance would otherwise report the PREVIOUS solve's declines
    # (advisor r5: stale stats["flow_bound_declines"])
    inst._flow_big_declines = 0
    # the "decomposed" rung of the bucket ladder (docs/DECOMPOSE.md,
    # ROADMAP item 4): AZ/rack-structured instances past the flat
    # ladder's reach (or opted in via --decompose / KAO_DECOMPOSE)
    # solve as map-reduce over per-AZ sub-instances through the
    # lane-padded batch executables, stitched + oracle-verified against
    # THIS flat instance. A failed split/reduce returns None (the
    # decompose_to_flat rung has been noted) and the flat path below
    # proceeds untouched. Precompile solves warm flat executables by
    # contract; the delta-API warm start and checkpoint resume are
    # flat-plan shaped; multi-controller SPMD forbids host-side
    # divergence — all four keep the flat path.
    if (not precompile and warm_start is None and checkpoint is None
            and _process_count() == 1):
        from ...decompose import maybe_decompose, should_decompose

        if should_decompose(inst, decompose):
            dres = maybe_decompose(
                inst, seed=seed, engine=engine,
                time_limit_s=time_limit_s, budget=budget,
                portfolio=portfolio, n_devices=n_devices,
                rounds=rounds or sweeps, t_hi=t_hi, t_lo=t_lo,
            )
            if dres is not None:
                inst.cancel_pending_bounds()
                return dres
    enable_compile_cache()
    # backend init costs ~5 s over a tunneled TPU and the host-side
    # workers below (bounds prefetch, plan constructor) don't need the
    # device at all — run the client init on its own daemon thread so
    # it overlaps them instead of serializing in front (on the
    # constructed path the device may end up never used at all)
    backend_fut = _BoundsTask(ensure_backend)
    # pre-default arguments: the fallback retry must forward what the
    # USER asked for, not this engine's resolved defaults
    engine_arg, batch_arg, t_hi_arg, t_lo_arg = engine, batch, t_hi, t_lo

    # the optimality bounds solve a max-flow + small LP (~1.5 s total at
    # 10k partitions): PREFETCH them on a DAEMON host thread that
    # overlaps the greedy seed and the device sweeps, so certificate
    # checks find them memoized instead of stalling the solve. (Pure
    # numpy/scipy work; no jax calls on the worker thread. A daemon
    # thread — unlike a ThreadPoolExecutor worker — cannot stall
    # interpreter exit if the solve dies while a 50k-partition LP is
    # still grinding.)
    def _bounds_body():
        # sub-phase span (ISSUE 10): the flow/LP bound computation gets
        # its own kao_phase_seconds{phase="bounds_flow"} attribution so
        # flight records can tell the host loop being vectorized apart
        # from the join wait the parent "bounds" span also contains
        with _otrace.span("bounds_flow"):
            return (
                inst.move_lower_bound_exact(),
                inst.weight_upper_bound(),
            )

    bounds_fut = _BoundsTask(_otrace.wrap("bounds", _bounds_body))
    # when balance bands bind, a second worker decodes the kept-replica
    # LP into a plan (solvers.lp_round) — usually the certified global
    # optimum, letting the solve skip annealing (and often compilation)
    # entirely. Small ASYMMETRIC decommission-style instances skip
    # this: their caps are slack, the annealer certifies on its own,
    # and the LP would waste seconds of host CPU. PAST the
    # unaggregated-LP size (~60k members) the constructor runs
    # regardless: the aggregated MILP + leader-aware completion
    # reaches optima the annealer's one-swap moves cannot (the
    # 50k-partition jumbo's exact optimum needs coordinated
    # leader-cascade placement), and at that scale it is CHEAPER than
    # one compile of the sweep executable.
    # the constructor also races on any symmetry-collapsible instance
    # (agg_effective): the aggregated MILP + completion builds the
    # certified optimum of steady-state clusters — the headline
    # decommission included — in ~2 s with no compilation, which is
    # what keeps a cold process inside the 5 s budget.
    # multi-controller SPMD: every worker must make IDENTICAL decisions
    # in front of every collective. Host-side races (the constructor
    # worker, timed boundary certification, wall-clock chunk breaks)
    # resolve at per-process times and would let one worker skip or
    # exit the ladder while another issues the next collective —
    # a pod-wide deadlock. Under multi-process the solve therefore runs
    # the full deterministic ladder with no host-race shortcuts; the
    # final certification (same LP on every host) stays.
    multi = _process_count() > 1
    knobs_set = any(
        v is not None
        for v in (engine, batch, rounds, sweeps, steps_per_round,
                  t_hi, t_lo)
    )
    members = inst._members()[0].size
    big = members > _instance_mod.AGG_MEMBER_THRESHOLD
    worker_fn = None
    worker_path = None
    if precompile:
        # warmup solves (serve /warmup) exist to COMPILE the device
        # path for a bucket shape; a host-side constructor certifying
        # the symmetric synthetic cluster would skip the device — and
        # the compile — entirely, so every race is disabled
        lp_wait_s = 0.0
    elif not multi and (_caps_bind(inst) or big or inst.agg_effective()):
        reseat_ok = _RESEAT_RACE and not knobs_set
        worker_fn = lambda: _construct_worker(inst, bounds_fut,
                                              reseat_fallback=reseat_ok)
        worker_path = "lp"
        # past the aggregation threshold the constructor (agg MILP +
        # completion + exact reseat, ~15-20 s) is far cheaper than the
        # first sweep-executable compile (minutes), so waiting longer
        # is a net win; below it the snappy cap holds (the constructor
        # either lands in ~2 s or the annealer should start — its LP
        # route has no termination guarantee)
        lp_wait_s = _CONSTRUCT_WAIT_BIG_S if big else _CONSTRUCT_WAIT_S
    elif (
        not multi
        and not knobs_set
        and inst.num_parts <= _EXACT_RACE_PARTS
        and 2 * inst.num_brokers * inst.num_parts <= _EXACT_RACE_VARS
    ):
        worker_fn = lambda: _exact_worker(inst, bounds_fut)
        worker_path = "milp"
        lp_wait_s = _CONSTRUCT_WAIT_S
    elif not multi and not knobs_set and _RESEAT_RACE:
        # slack caps, no symmetry, too big for the exact MILP — the
        # adversarial class. Greedy + exact reseat races the annealer:
        # certified it skips the search entirely; uncertified it still
        # hands the ladder a better warm start than the raw greedy
        worker_fn = lambda: _reseat_worker(inst, bounds_fut)
        worker_path = "reseat"
        lp_wait_s = (
            _CONSTRUCT_WAIT_MID_S
            if members > _RESEAT_WAIT_MID_MEMBERS
            else _CONSTRUCT_WAIT_S
        )
    else:
        lp_wait_s = 0.0
    if warm_start is not None and not multi and not precompile:
        # delta-path warm certify (docs/WATCH.md): the adapted previous
        # plan gets first shot at the certificate — when it holds, the
        # solve returns it without an LP decode, a compile, or a single
        # device dispatch. Composed IN FRONT of the class's race worker
        # (the fall-through), not instead of it.
        inner_fn, warm_a = worker_fn, warm_start
        worker_fn = lambda: _warm_certify_worker(inst, bounds_fut,
                                                 warm_a, inner_fn)
        worker_path = f"warm+{worker_path}" if worker_path else "warm"
        lp_wait_s = max(lp_wait_s, _CONSTRUCT_WAIT_S)
    lp_fut = (
        _BoundsTask(_otrace.wrap("construct_worker", worker_fn,
                                 path=worker_path))
        if worker_fn is not None else None
    )
    try:
        res = _solve_tpu_inner(
            inst, seed, batch, rounds, sweeps, steps_per_round, t_hi,
            t_lo, n_devices, engine, checkpoint, profile_dir,
            time_limit_s, backend_fut, t0, bounds_fut,
            cert_min_savings_s, lp_fut, multi, lp_wait_s, pipeline,
            budget, warm_start, portfolio, megachunk,
        )
    except Exception as e:
        # the degradation ladder's last rung (docs/RESILIENCE.md): a
        # fault that makes the DEVICE path unusable must still return a
        # valid, oracle-verified plan — the host greedy/reseat
        # constructor, flagged degraded — instead of failing the
        # request. Deliberately narrow (_degradable): sanitizer trips
        # keep failing loudly, multi-controller workers must not
        # diverge, and precompile solves exist to exercise the device.
        if multi or precompile or not _degradable(e):
            raise
        res = _host_fallback(inst, e, checkpoint, budget, t0,
                             time_limit_s, warm_start=warm_start)
    # robustness net: on TPU the sweep engine is the default at every
    # size, but ultra-tight small instances (exact rack bands + strict
    # per-partition diversity at high RF) can defeat its conflict-
    # thinned parallel moves while the sequential chain engine closes
    # them. When a DEFAULTED sweep ends infeasible on an instance small
    # enough for chains, retry with the chain engine and keep the
    # better-ranked plan.
    if (
        not res.stats["feasible"]
        and engine_arg is None
        and res.stats["engine"] == "sweep"
        and inst.num_parts < _SWEEP_THRESHOLD_PARTS
        # SPMD: workers must agree on retrying; the inner solve ignores
        # the deadline under multi anyway, so only the data-determined
        # conditions above (identical on every worker) may decide
        and (multi or not budget.expired())
    ):
        # the retry runs on what is LEFT of this solve's budget — never
        # the original time_limit_s (a timed-out sweep must not grant
        # the chain retry the full window again)
        remaining = budget.remaining()
        # engine-neutral knobs carry over; the budget knobs
        # (rounds/sweeps/steps_per_round) deliberately do NOT — each
        # engine's budget is meaningless for the other (see _defaults),
        # so the retry runs the chain engine's own defaults. Under an
        # active trace the retry's pipeline spans nest under this
        # "retry" span, keeping the root-level phases exactly-once.
        _ladder.note_rung("sweep_to_chain", parts=inst.num_parts,
                          remaining_s=remaining)
        _olog.warn("engine_fallback_retry", engine="chain",
                   parts=inst.num_parts)
        with _otrace.span("retry", engine="chain"):
            # the CALLER-OWNED budget threads through (not just its
            # remaining seconds): a watch-mode Budget.cancel() landing
            # mid-retry must retire the chain ladder at its next
            # boundary too, not anneal out the whole remaining window
            res2 = solve_tpu(
                inst, seed=seed, engine="chain", n_devices=n_devices,
                batch=batch_arg, t_hi=t_hi_arg, t_lo=t_lo_arg,
                checkpoint=checkpoint, profile_dir=profile_dir,
                time_limit_s=remaining,
                cert_min_savings_s=cert_min_savings_s,
                pipeline=pipeline, warm_start=warm_start,
                budget=budget,
            )
        def rank(r):
            return (
                r.stats["feasible"],
                -r.stats["violations"],
                r.objective,
                -r.stats["moves"],
            )

        if rank(res2) > rank(res):
            res2.stats["engine_fallback"] = (
                "chain after infeasible defaulted sweep"
            )
            res = res2
    # the solve is over: straggling bounds workers (tier-1/2 LPs on
    # daemon threads) must not escalate further and grind host CPU into
    # the next request's wall-clock (ADVICE r2). The flag skips
    # not-yet-started tiers only; post-solve audits use evaluate(),
    # which builds its own instance.
    inst.cancel_pending_bounds()
    return res


def _chaos_chunk_hooks() -> None:
    """The chaos injection points every chunk dispatch fires — host
    side, before anything is traced or donated (docs/RESILIENCE.md): a
    Pallas kernel fault (drained and retried on XLA), a NaN surfacing
    from the chunk (the host-fallback rung when the sanitizer is off),
    and a chunk overrun (exercises the deadline gate). ONE helper so
    the single-solve and batch ladders can never drift apart on which
    faults the chaos soak exercises."""
    _chaos.raise_if("pallas_fault")
    _chaos.raise_if("nan_chunk", FloatingPointError)
    _chaos.sleep_if("chunk_overrun")


def _is_pallas_lowering(e: Exception, scorer: str) -> bool:
    """Only a Mosaic/Pallas lowering failure warrants the XLA retry;
    anything else (OOM, sharding bug, regression) must surface with
    its real traceback. The injected chaos pallas fault qualifies
    regardless of the active scorer, so CPU test meshes exercise the
    same drain-and-retry path real hardware takes."""
    if _chaos.is_pallas_fault(e):
        return True
    msg = f"{type(e).__name__}: {e}"
    return scorer == "pallas" and any(
        s in msg for s in ("Mosaic", "mosaic", "pallas", "Pallas",
                           "lowering", "Lowering")
    )


def _degradable(e: BaseException) -> bool:
    """Faults that warrant the host-fallback rung instead of failing
    the solve: injected chaos faults, and device NaN aborts when the
    sanitizer is NOT armed (armed means the operator asked for loud
    failure — docs/ANALYSIS.md). Everything else (OOM, sharding bugs,
    regressions) must surface with its real traceback."""
    if isinstance(e, _san.SanitizerError):
        return False
    if _chaos.is_fault(e):
        return True
    return isinstance(e, FloatingPointError) and not _san.enabled()


def _host_fallback(inst: ProblemInstance, exc: BaseException,
                   checkpoint: str | None, budget: Budget, t0: float,
                   time_limit_req: float | None,
                   warm_start=None) -> SolveResult:
    """The ladder's terminal rung (``anneal_to_construct``): the device
    search is unusable, so build the best host-side plan — greedy
    repair, displaced by a higher-ranking checkpoint when one exists
    (crash-resume), lifted by the exact leader reseat when feasible —
    verify it against the numpy oracle, and return it FLAGGED
    (``stats["degraded"]``) so callers can tell a degraded plan from a
    searched one. Certification is still attempted (budget permitting):
    on slack-caps instances greedy + exact reseat often IS the proven
    optimum, in which case the degraded plan is also certified."""
    _ladder.note_rung("anneal_to_construct", error=repr(exc)[:200])
    with _otrace.span("greedy"):
        a = np.asarray(greedy_seed(inst), dtype=np.int32)
    resumed = False
    warm_used = False
    if checkpoint:
        a_prev = ckpt.load(checkpoint, inst)
        if a_prev is not None and _seed_rank(inst, a_prev) >= \
                _seed_rank(inst, a):
            a = a_prev
            resumed = True
    # a validated delta-API warm start (docs/WATCH.md) outranking the
    # greedy repair keeps surviving replicas in place even on the
    # degraded path — the last plan must not be forgotten just because
    # the device died
    if warm_start is not None and _seed_rank(inst, warm_start) >= \
            _seed_rank(inst, a):
        a = warm_start
        warm_used = True
    if inst.is_feasible(a) and not budget.expired():
        a = inst.best_leader_assignment(a)
    viol = inst.violations(a)
    feasible = all(v == 0 for v in viol.values())
    weight = inst.preservation_weight(a)
    proved = False
    if feasible and not budget.expired():
        try:
            proved = inst.certify_optimal(a, allow_tight=False)
        except Exception:
            proved = False
    return SolveResult(
        a=a,
        solver="tpu",
        wall_clock_s=time.perf_counter() - t0,
        objective=int(weight),
        optimal=proved,
        stats={
            "engine": "host_fallback",
            "degraded": "anneal_to_construct",
            "fault": repr(exc)[:200],
            "feasible": feasible,
            "violations": sum(viol.values()),
            "moves": int(inst.move_count(a)),
            "seed_moves": int(inst.move_count(a)),
            "proved_optimal": proved,
            "resumed_from_checkpoint": resumed,
            "warm_started": warm_used,
            "time_limit_s": time_limit_req,
            "timed_out": False,
            "early_stopped": False,
            "constructed": True,
            "rounds_run": 0,
        },
    )


def _process_count() -> int:
    """``jax.process_count()`` without forcing backend init: an
    uninitialized ``jax.distributed`` means single-process by
    definition, and asking jax directly would serialize the multi-second
    TPU client init that ``solve_tpu`` deliberately runs on a thread."""
    init = getattr(jax.distributed, "is_initialized", None)
    if callable(init) and not init():
        return 1
    return jax.process_count()


def _caps_bind(inst: ProblemInstance) -> bool:
    """Band-binding signal — now a model method (``caps_bind``) shared
    with the plan constructor's path ordering; thin alias kept for the
    engine's call sites and tests."""
    return inst.caps_bind()


def _reseat_worker(inst: ProblemInstance, bounds_fut) -> tuple:
    """Greedy + exact-reseat racer body: on slack-caps instances the
    greedy repair keeps every keepable member, so replica placement is
    already move- and weight-optimal and the only gap is the leader
    arrangement — which ``best_leader_assignment`` closes EXACTLY (the
    r4 band-repairing cycle canceller handles the greedy seed's
    arbitrary leader counts in well under a second even at 150k
    slots). Joins the bounds prefetch before certifying, like every
    constructor worker, so the two threads never duplicate the bound
    computations. An uncertified result is still returned as a warm
    start — it can only outrank the raw greedy seed it extends.

    Returns ``(plan, certified, extends_greedy)``; the third element
    rides in the result tuple rather than on the shared instance so a
    straggling worker from a PREVIOUS solve can never tag the next
    solve's warm start (ADVICE r4)."""
    with _otrace.span("greedy"):
        a = np.asarray(greedy_seed(inst), dtype=np.int32)
    if not inst.is_feasible(a):
        return None, False, False  # greedy is only near-feasible here
    try:
        bounds_fut.result()
    except Exception:
        pass
    with _otrace.span("reseat"):
        a = inst.best_leader_assignment(a)
    # record the path unconditionally — an uncertified warm start can
    # still win final selection (constructed=True in stats), and its
    # construct_path must then name what actually built it rather
    # than stay None or a stale value from a previous solve
    inst._construct_path = "reseat"
    if inst.certify_optimal(a):
        return a, True, True
    # extends_greedy marks that this warm start IS greedy + exact
    # reseat, so the main path skips recomputing the greedy seed
    # (seconds at 50k partitions) and the rank-vs-greedy comparison
    return a, False, True


def _construct_worker(inst: ProblemInstance, bounds_fut,
                      reseat_fallback: bool = False) -> tuple:
    """Bounds-thread body: decode the kept-replica LP into a plan and
    certify it. Except for the cheap viability pre-check below (which
    may compute the class grouping concurrently with the bounds
    worker — a benign duplicated memo fill, off the main thread), it
    joins the main bounds prefetch first so the two workers never
    duplicate the multi-second bound LPs.

    Like every constructor worker, returns the uniform 3-tuple
    ``(plan, certified, extends_greedy)``."""
    # past the unaggregated-LP size the constructor's only viable path
    # is the aggregated formulation; when THAT will refuse
    # (agg_construct_viable False — e.g. a shuffled 50k-partition
    # cluster with ~1x class collapse) there is no route to a
    # constructed plan in useful time: return at once so the engine's
    # big-instance wait ends immediately instead of stalling 45 s
    # while a ~900 s LP grinds this thread. Checked BEFORE the bounds
    # join, and off the main thread, so solve startup never pays the
    # class grouping.
    if (
        inst._members()[0].size > _instance_mod.AGG_MEMBER_THRESHOLD
        and not inst.agg_construct_viable()
    ):
        if reseat_fallback:
            # the LP/MILP routes refuse, but slack-caps shuffled
            # instances (the adv50k class) usually fall to the greedy
            # + exact-reseat racer — certified with no compile
            return _reseat_worker(inst, bounds_fut)
        return None, False, False
    try:
        bounds_fut.result()
    except Exception:
        pass
    from ..lp_round import construct

    plan = construct(inst)
    if plan is None:
        return None, False, False
    return plan, inst.certify_optimal(plan), False


def _exact_worker(inst: ProblemInstance, bounds_fut) -> tuple:
    """Tiny-instance race body: solve the exact MILP (milliseconds at
    P <= 64) and certify its plan. The proven MILP optimum is itself a
    valid weight upper bound on every feasible plan, so it is recorded
    the same way the aggregated constructor records its optimum —
    certify_optimal then needs no LP ladder, only the move bound.
    Joins the bounds prefetch first (same reason as _construct_worker:
    certify's move bound is memoized there; two threads must not race
    the same computations). Time-limited: losing the race must not
    leave an unkillable HiGHS solve grinding host CPU into the next
    request (the failure class ADVICE r2's cancel closed for bounds).

    Returns the uniform constructor 3-tuple ``(plan, certified,
    extends_greedy)``."""
    try:
        bounds_fut.result()
    except Exception:
        pass
    from ..milp import solve_milp

    r = solve_milp(inst, time_limit_s=2 * _CONSTRUCT_WAIT_S)
    if not r.optimal or r.a is None:
        return None, False, False
    plan = np.asarray(r.a, dtype=np.int32)
    if r.objective is not None:
        inst._agg_weight_ub = int(r.objective)
    if inst.certify_optimal(plan):
        inst._construct_path = "milp"
        return plan, True, False
    # weight-optimal but not provably move-minimal: still a strong
    # warm start for the annealer
    return plan, False, False


def _warm_certify_worker(inst: ProblemInstance, bounds_fut, warm_a,
                         inner=None) -> tuple:
    """Constructor-race body for the delta path (docs/WATCH.md): after
    a cluster event the adapted previous plan is often already the
    optimum — a drain evicts a few replicas and the hole-filling refill
    is move-minimal by construction — so try certifying IT before any
    LP decode. Joins the bounds prefetch like every constructor worker
    (the certify bounds are memoized there); the exact leader reseat is
    metadata-only and closes the one gap adaptation leaves. When the
    warm candidate does not certify, falls through to ``inner`` — the
    race worker this instance class would otherwise have run.

    Returns the uniform 3-tuple ``(plan, certified, extends_greedy)``."""
    try:
        bounds_fut.result()
    except Exception:
        pass
    a = np.ascontiguousarray(warm_a, dtype=np.int32)
    viol = inst.violations(a)
    # adaptation leaves leader counts wherever survival put them; the
    # exact reseat repairs THAT band (transportation problem over fixed
    # replica sets) — so gate only on the families reseat cannot touch
    if all(v == 0 for k, v in viol.items() if k != "leader_balance"):
        try:
            with _otrace.span("reseat"):
                a = inst.best_leader_assignment(a)
        except Exception:
            pass  # infeasible transportation: fall through uncertified
        if inst.certify_optimal(a):
            inst._construct_path = "warm"
            return a, True, False
    if inner is not None:
        return inner()
    return None, False, False


class _BoundsTask:
    """Future-like handle on one bounds computation running on a daemon
    thread (``concurrent.futures`` workers are non-daemon and would
    block interpreter exit for the remainder of a running LP)."""

    def __init__(self, fn):
        import threading

        self._ev = threading.Event()
        self._res = None
        self._exc: BaseException | None = None

        def run():
            try:
                self._res = fn()
            except BaseException as e:  # surfaced on result()
                self._exc = e
            finally:
                self._ev.set()

        threading.Thread(target=run, daemon=True).start()

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: float | None = None):
        if not self._ev.wait(timeout):
            raise TimeoutError("bounds computation still running")
        if self._exc is not None:
            raise self._exc
        return self._res


def _await_constructor(lp_fut, lp_wait_s, checkpoint, budget: Budget):
    """Stage 1 — the constructor race: join the LP/MILP/reseat worker
    for up to ``lp_wait_s``. A certified plan makes annealing — and with
    it the greedy seed, the device model arrays and the schedule —
    unnecessary; skipping that setup is ~1.5 s of a cold process's 5 s
    budget (the constructor certifies steady-state instances, the
    headline decommission included, in ~2 s with zero compilation). If
    the worker is not done in time, annealing starts and the chunk
    boundaries keep watching for it.

    Returns ``(certified_a, lp_warm, lp_warm_extends)``."""
    if lp_fut is None:
        return None, None, False
    if checkpoint:
        # fail fast on an unwritable path BEFORE spending solve time —
        # and before the fast path skips the resume block (stage 2),
        # whose mkdir the end-of-solve ckpt.save relies on
        from pathlib import Path

        Path(checkpoint).parent.mkdir(parents=True, exist_ok=True)
    # per-worker adaptive wait, chosen by solve_tpu when it picked the
    # racer (45 s past the aggregation threshold, a 15 s middle tier
    # for the mid-size reseat racer, 5 s otherwise), capped by the
    # solve budget. Every constructor worker returns the uniform
    # 3-tuple (plan, ok, extends_greedy), so the unpack is strict — a
    # wrong-arity worker is a bug, and the except below turns it into
    # "no constructed plan", never a crash.
    lp_warm_extends = False
    try:
        plan, ok, lp_warm_extends = lp_fut.result(
            timeout=budget.cap(lp_wait_s)
        )
        lp_warm_extends = bool(lp_warm_extends)
    except Exception:
        plan, ok = None, False
    # "adopt" sub-phase (ISSUE 10): the host time spent taking a
    # finished constructor plan into the solve — distinct from the
    # join wait above, which is overlap, not work
    with _otrace.span("adopt", certified=bool(ok),
                      plan=plan is not None):
        if ok:
            return (np.asarray(plan, dtype=np.int32), None,
                    lp_warm_extends)
        if plan is not None:
            # uncertified but complete: candidate warm start, ranked
            # against the greedy seed in stage 2
            return (None, np.asarray(plan, dtype=np.int32),
                    lp_warm_extends)
        return None, None, lp_warm_extends


class _CurveSlice:
    """Per-chunk view over one fused group's async curve transfer:
    ``get()`` slices chunk ``j`` out of the group's ``[..., K, rounds]``
    curve block, so every downstream consumer (per-chunk stats curves,
    the curve materialization at ladder end) sees exactly the arrays
    the unfused ladder produced — one transfer per GROUP feeds K
    per-chunk handles."""

    def __init__(self, h, j: int, axis: int):
        self._h, self._j, self._axis = h, j, axis

    def get(self):
        return np.take(np.asarray(self._h.get()), self._j,
                       axis=self._axis)


@dataclass
class _LadderResult:
    """What the annealing ladder hands to final selection / stats."""

    pop_a: object = None       # per-shard winners (device, mesh-sharded)
    pop_k: object = None
    curves: list = field(default_factory=list)
    rounds_run: int = 0
    timed_out: bool = False
    certified_a: object = None  # boundary- or constructor-certified plan
    constructed: bool = False   # certified_a came from the constructor
    scorer: str = "xla"
    pallas_fallback: str | None = None
    tight_fut: object = None    # in-flight tier-1 LP, reused at the end
    pipelined: bool = False     # speculative double-buffered dispatch ran
    dispatch_s: float = 0.0     # host time enqueueing chunks (incl. compile)
    device_s: float = 0.0       # host time blocked on device results
    boundary_overlap_s: float = 0.0  # boundary work hidden behind device chunks
    winner_lane: int | None = None   # portfolio lane that certified first
    certified_at_s: float | None = None  # solve-relative first-certificate time
    mega_k: int = 1            # fused width this ladder ran at (1 = unfused)
    mega_groups: int = 0       # fused groups dispatched
    dispatches: int = 0        # device dispatches (fused or not)
    chunks_exec: int = 0       # schedule chunks that actually executed
    mega_early_exit: bool = False  # a fused group exited on-device


def _run_ladder(
    inst, m, mesh, chains_per_device, rounds, steps_per_round, engine,
    scorer, chunks, seed_dev, key, sweep_state, lp_fut, bounds_fut,
    multi, cert_min_savings_s, budget, profile_dir,
    polish_starter=None, pipeline=True, warm_key=(), lanes: int = 0,
    mega_k: int = 1,
) -> _LadderResult:
    """Stage 4 — the chunked annealing ladder: dispatch each schedule
    chunk to the mesh, then do the boundary work between chunks — adopt
    a late-finishing constructor plan, try the optimality certificate on
    the top shard winner (adaptive: only when the ladder left to skip
    costs more than certification itself; non-blocking on the bounds
    prefetch — annealing continues while the LPs compute), reseed the
    chain engine from the global best, and honor the wall-clock
    deadline.

    Sweep engine, ``pipeline=True`` (the default): the ladder runs
    DOUBLE-BUFFERED — chunk i+1 is dispatched before chunk i is
    retired, so all of chunk i's boundary work executes while chunk i+1
    runs on device (docs/PIPELINE.md). PRNG keys are pre-split in
    deterministic order (and the sweep state carries its own RNG), so
    the speculative dispatch consumes no host decision and pipelined
    trajectories are bit-identical to synchronous ones. The deadline
    then decides whether to RETIRE the in-flight chunk, not whether to
    dispatch it — abandoning it wastes only speculative device work.

    A Mosaic lowering failure retries the chunk on the XLA scorer and
    records the fallback (pipelined mode drains first: the failed
    speculation is retired synchronously after the current chunk's
    boundary, then the pipeline re-enters); anything else surfaces with
    its real traceback.

    ``lanes`` > 0 is the PORTFOLIO mode (docs/PORTFOLIO.md): ``m`` is a
    lane-stacked model, ``sweep_state`` a lane state, and every chunk
    dispatches through ``solve_lanes`` — the same lane-padded
    executable the batched multi-tenant path uses. Boundary
    certificates then race ACROSS lanes (the per-shard winner pool is
    the flattened [n_dev x lanes] set; only the ``lanes`` real lanes
    are read — padding lanes are inert by masking), and the first lane
    to certify retires the remaining ladder, recording its index as
    ``winner_lane``.

    ``mega_k`` > 1 is the MEGACHUNK mode (ISSUE 17, docs/PIPELINE.md):
    consecutive sweep chunks fuse into one device-resident scan
    dispatch of width ``mega_k`` — one host round-trip retires K
    chunks, with an on-device early-exit certificate test between
    fused steps. Per-lane trajectories stay bit-identical to the
    unfused ladder (the scan body IS the per-chunk step); any fault
    inside a fused group drains to the per-chunk dispatchers via the
    ``megachunk_to_chunked`` rung, re-entering at the first chunk the
    group did not finish."""
    from ...parallel.mesh import (
        fetch_global, fetch_global_async, solve_lanes,
        solve_lanes_megachunk, solve_megachunk, solve_on_mesh,
    )

    r = _LadderResult(scorer=scorer)
    n = len(chunks)
    reseat_tries = 0  # boundary leader-reseat attempts (bounded)
    deadline = budget.deadline

    def _deadline_now():
        """Cancellation-aware deadline read: a Budget.cancel() from
        another thread (a superseded watch-mode solve, docs/WATCH.md)
        moves the effective deadline into the past, so the very next
        boundary gate retires the ladder with its best-so-far plan."""
        if budget.cancelled:
            return time.perf_counter() - 1.0
        return deadline
    # chunk 0's duration is compile-inclusive and a fallback chunk's
    # includes the XLA retry's first compile — both wildly overstate a
    # warm chunk, so neither may feed the warm estimate (a cold solve
    # with budget left would otherwise stop after one chunk). The
    # cross-solve prior for this exact executable identity covers the
    # gap: a warm re-solve can gate from chunk 1 instead of flying
    # blind until two of its own chunks have retired.
    warm_chunk_s: float | None = None
    last_chunk_s: float | None = None
    chunk_len = int(chunks[0].shape[0]) if n else 0

    def _wkey(width: int = 1) -> tuple:
        """Warm-registry key, WIDTH-KEYED (regression-pinned): fused
        measurements are normalized per chunk but lack the per-dispatch
        host overhead an unfused chunk pays, so a K=8 group filed under
        the K=1 key would deflate the per-chunk deadline estimate (and
        vice versa inflate the fused one). Each fused width files and
        reads its own entry."""
        return (*warm_key, chunk_len, width, r.scorer)

    prior_s = _WARM_CHUNKS.get(_wkey())
    # fused-mode measurement track (normalized per chunk, see _wkey)
    mega_warm_s: float | None = None
    mega_prior_s = _WARM_CHUNKS.get(_wkey(mega_k)) if mega_k > 1 else None
    mega_active = False  # True while a fused walker owns the ladder
    handles: list = []  # per-retired-chunk async curve transfers

    def _est_chunk_s() -> float | None:
        """Per-chunk duration estimate for the deadline and
        certificate gates. The fused walkers prefer their own
        normalized measurements; the per-chunk walkers never see a
        fused value (satellite-pinned — widths must not cross-feed)."""
        cands = (
            (mega_warm_s, mega_prior_s, last_chunk_s) if mega_active
            else (warm_chunk_s, prior_s, last_chunk_s)
        )
        for v in cands:
            if v is not None:
                return v
        return None

    # PRNG keys split up front, in exactly the order the sequential
    # loop used to split them — a speculatively dispatched chunk must
    # consume no host-side decision. (The sweep engine ignores these:
    # its RNG rides in the carried state.)
    if n == 1:
        subs = [key]  # bit-identical to the unchunked solve
    else:
        subs, _k = [], key
        for _ in range(n):
            _k, _s = jax.random.split(_k)
            subs.append(_s)

    def dispatch(i, st):
        """Enqueue chunk i on the device; returns without waiting for
        the result (past any compile). Timed internally so a retry
        after a Pallas fallback times the successful dispatch only.
        Chaos injection points fire HERE (_chaos_chunk_hooks)."""
        _chaos_chunk_hooks()
        td = time.perf_counter()
        if lanes:
            out = solve_lanes(
                m, mesh, chains_per_device, chunks[i], state=st,
                engine=engine, steps_per_round=steps_per_round,
                scorer=r.scorer,
            )
        else:
            out = solve_on_mesh(
                m, seed_dev, subs[i], mesh, chains_per_device, rounds,
                steps_per_round, engine=engine, temps=chunks[i],
                scorer=r.scorer, state=st,
            )
        r.dispatches += 1
        if engine == "sweep":
            new_state, pop_a, pop_k, curve = out
        else:
            new_state, (pop_a, pop_k, curve) = None, out
        return new_state, pop_a, pop_k, curve, time.perf_counter() - td

    def _is_lowering(e: Exception) -> bool:
        # r.scorer is read at CALL time: after a fallback flips it to
        # "xla" a second Mosaic-looking failure must surface for real
        return _is_pallas_lowering(e, r.scorer)

    def _note_fallback(i, e) -> None:
        nonlocal warm_chunk_s, prior_s, mega_warm_s, mega_prior_s
        _ladder.note_rung("pallas_to_xla", chunk=i)
        r.pallas_fallback = repr(e)[:500]
        r.scorer = "xla"
        # scorer-pure estimates: Pallas chunks are materially faster
        # than XLA chunks, so measurements from before the fallback
        # must not gate (or be filed for) the XLA executable — restart
        # the warm measurement and re-fetch the prior under the new key
        warm_chunk_s = None
        prior_s = _WARM_CHUNKS.get(_wkey())
        mega_warm_s = None
        mega_prior_s = (
            _WARM_CHUNKS.get(_wkey(mega_k)) if mega_k > 1 else None
        )
        _olog.warn("pallas_fallback", chunk=i, error=repr(e)[:200])

    def dispatch_or_fallback(i, st):
        """Dispatch with the Mosaic→XLA retry. Only legal with the
        pipeline EMPTY: the retry recompiles synchronously. Safe on the
        carried state when the failure is a true lowering error — those
        raise at trace/compile time, before any buffer (donated
        included) is consumed; a Mosaic-worded error raised at
        EXECUTION time has already consumed the donated state, so it
        re-raises instead of retrying on dead buffers. Returns
        ``(dispatch tuple, fell_back)``."""
        try:
            return dispatch(i, st), False
        except Exception as e:
            if not _is_lowering(e) or not _leaves_alive(st):
                raise
            _note_fallback(i, e)
            return dispatch(i, st), True

    def chunk_attrs(sp, i, dispatch_s, device_s, overlap_s, h,
                    scorer_ran) -> None:
        """Per-chunk annealing stats: the best-score curve is the exact
        record the device already returns, so accepts/declines are
        measured at best-curve granularity (rounds that did / did not
        improve the global best) — no extra device outputs, trajectory
        bit-parity untouched. Consuming the async curve handle here is
        free: the copy was started at retire time. ``scorer_ran`` is
        the scorer this chunk actually executed under — a speculative
        dispatch failing mid-boundary flips ``r.scorer`` before the
        current chunk's attrs are recorded."""
        if sp is None:
            return
        t_np = np.asarray(chunks[i])
        # curve is [n_dev, rounds] — or [n_dev, L, rounds] under the
        # portfolio — so reduce over every leading axis
        arr = np.asarray(h.get())
        best = arr.max(axis=tuple(range(arr.ndim - 1)))
        imp = int((np.diff(best) > 0).sum()) if best.size > 1 else 0
        sp.set(
            rounds=int(t_np.shape[0]),
            t_hi=float(t_np[0]),
            t_lo=float(t_np[-1]),
            scorer=scorer_ran,
            dispatch_s=round(dispatch_s, 4),
            device_s=round(device_s, 4),
            boundary_overlap_s=round(overlap_s, 4),
            energy_before=int(best[0]) if best.size else None,
            energy_after=int(best[-1]) if best.size else None,
            accepts=imp,
            declines=max(0, int(best.size) - 1 - imp),
        )

    def boundary(i) -> bool:
        """Between-chunk host work for retired chunk i: constructor
        adoption, the boundary optimality certificate, the chain
        engine's reseed. Returns True when the ladder should stop (a
        certified plan exists). Under the pipelined dispatcher this
        whole block overlaps chunk i+1's device execution."""
        nonlocal seed_dev, reseat_tries
        if i + 1 >= n:
            return False
        # a finished constructor worker short-circuits the rest of the
        # ladder with its certified plan
        if lp_fut is not None and lp_fut.done():
            try:
                plan, ok, _extends = lp_fut.result()
            except Exception:
                plan, ok = None, False
            if ok:
                with _otrace.span("adopt", certified=True,
                                  boundary=i):
                    r.certified_a = np.asarray(plan, dtype=np.int32)
                    r.constructed = True
                return True
        # boundary certificate: if any per-shard winner provably hits
        # the optimum, the remaining chunks cannot improve it. (The
        # sweep engine's populations continue on-device via sweep_state
        # and need no boundary host data until a check actually runs —
        # it skips even the device_get; the chain engine always needs
        # it for the reseed.)
        remaining_s = (n - i - 1) * (_est_chunk_s() or 0.0)
        do_cert = (
            not multi
            and remaining_s > cert_min_savings_s
            and bounds_fut.done()
        )
        if engine != "sweep" or do_cert:
            pa, pk = (
                np.asarray(x)
                for x in fetch_global((r.pop_a, r.pop_k))
            )
            if lanes:
                # portfolio: the candidate pool is every (device, lane)
                # winner — REAL lanes only (padding lanes rerun lane 0
                # and are never read). Flattened row-major, so a flat
                # index j decodes to lane j % lanes.
                pa = pa[:, :lanes].reshape(-1, *pa.shape[2:])
                pk = pk[:, :lanes].reshape(-1)
            # test ONLY the top-ranked shard winner: the key ranks by
            # weight, so a lower-ranked candidate cannot pass a weight
            # bound the top one failed, and repeating the reseat LP per
            # shard per boundary would cost seconds for no new outcome
            for j in np.argsort(-pk)[:1] if do_cert else []:
                # bucket-padded rows are sliced off before any
                # host-side oracle sees the candidate
                cand = arrays.unpad_candidate(pa[j], inst)
                mc = inst.move_count(cand)
                if not inst.is_feasible(cand):
                    continue
                lb_exact, ub0 = bounds_fut.result()
                if mc <= lb_exact:
                    w_cand = inst.preservation_weight(cand)
                    if w_cand < ub0 and reseat_tries < 3:
                        # below the bound: a leader reseat can lift it.
                        # The negative-cycle canceller handles a
                        # near-optimal candidate in well under a second
                        # even at 150k slots (r4), so every size gets
                        # at most 3 boundary tries — the final
                        # certification reseats once regardless
                        reseat_tries += 1
                        cand = inst.best_leader_assignment(cand)
                        w_cand = inst.preservation_weight(cand)
                    if w_cand >= ub0:
                        r.certified_a = cand
                        break
                    # tier 0 failed: evaluate the tight tier-1 LP on a
                    # worker thread — several seconds at 10k
                    # partitions; the devices keep annealing meanwhile
                    if r.tight_fut is None:
                        r.tight_fut = _BoundsTask(
                            lambda: inst.weight_upper_bound(tight=True)
                        )
                    elif r.tight_fut.done() and (
                        w_cand >= r.tight_fut.result()
                    ):
                        r.certified_a = cand
                        break
            if r.certified_a is not None:
                # first-to-certify provenance (docs/PORTFOLIO.md): the
                # flat index `j` that certified decodes to its lane,
                # and the certificate time is solve-relative (the
                # bench's time-to-first-certificate column)
                if lanes:
                    r.winner_lane = int(j % lanes)
                r.certified_at_s = round(
                    time.perf_counter() - budget.t0, 4
                )
                return True
            if do_cert and polish_starter is not None:
                # a certificate check ran and did NOT certify: first
                # evidence this instance may need the steepest-descent
                # polish — start its AOT compile now so it overlaps the
                # remaining chunks. Deferred until here (r5) because
                # the certify-first design means most at-scale solves
                # never polish, and on few-core hosts an eager compile
                # thread STEALS the cpu the main compile needs
                # (measured: the two ~20 s compiles serialize and
                # double the cold start).
                polish_starter()
            if engine != "sweep":
                seed_dev = jnp.asarray(pa[int(np.argmax(pk))])
        return False

    def retire_common(i, pop_a, pop_k, curve, disp_s, device_s,
                      chunk_s, fell_back):
        """Bookkeeping shared by both loop shapes, after chunk i's
        results are on device and synced."""
        nonlocal warm_chunk_s, last_chunk_s
        r.pop_a, r.pop_k = pop_a, pop_k
        r.rounds_run += int(chunks[i].shape[0])
        r.chunks_exec += 1
        r.dispatch_s += disp_s
        r.device_s += device_s
        last_chunk_s = chunk_s
        if i > 0 and not fell_back:
            warm_chunk_s = (
                chunk_s if warm_chunk_s is None
                else min(warm_chunk_s, chunk_s)
            )
        h = fetch_global_async(curve)
        handles.append(h)
        return h

    def run_sync(start: int = 0):
        """One chunk at a time, fully retired before the next dispatch
        (the chain engine — its reseed is a data dependency — and the
        ``--no-pipeline`` escape hatch). ``start`` > 0 is the fused
        walkers' drain re-entry point: resume at the first chunk the
        fused group did not finish."""
        nonlocal sweep_state
        for i in range(start, n):
            dl = _deadline_now()
            if dl is not None and i >= 1:
                est = warm_chunk_s if warm_chunk_s is not None else prior_s
                if time.perf_counter() > dl or (
                    est is not None
                    and dl - time.perf_counter() < est * 0.9
                ):  # cancelled, or the next chunk won't fit
                    r.timed_out = True
                    return
            with _otrace.span("chunk", index=i) as _sp:
                tc = time.perf_counter()
                (new_state, pop_a, pop_k, curve, disp_s), fb = (
                    dispatch_or_fallback(i, sweep_state)
                )
                tw = time.perf_counter()
                jax.block_until_ready(pop_a)
                device_s = time.perf_counter() - tw
                _flight.note_device(device_s)
                if engine == "sweep":
                    # commit only after the sync: a failed dispatch
                    # (e.g. Mosaic lowering, retried on XLA) must not
                    # poison the carried populations
                    sweep_state = new_state
                h = retire_common(i, pop_a, pop_k, curve, disp_s,
                                  device_s, time.perf_counter() - tc, fb)
                chunk_attrs(_sp, i, disp_s, device_s, 0.0, h, r.scorer)
            with _flight.attribute("boundary"):
                stop = boundary(i)
            if stop:
                return
            dl = _deadline_now()
            if dl is not None and time.perf_counter() > dl:
                r.timed_out = i + 1 < n
                return

    def run_pipelined(start: int = 0):
        """Double-buffered sweep dispatch: chunk i+1 enters the device
        queue before chunk i's results are waited on, so every piece of
        chunk i's boundary work (curve transfer, certificates,
        constructor adoption, checkpoint writes in the caller) executes
        while the device is busy. ``start`` > 0 resumes after a fused
        group drained (megachunk_to_chunked)."""
        nonlocal sweep_state
        r.pipelined = True
        t_mark = time.perf_counter()
        pending, pend_fb = dispatch_or_fallback(start, sweep_state)
        i = start
        while True:
            new_state, pop_a, pop_k, curve, disp_s = pending
            # the scorer THIS chunk executed under: a failing
            # speculative dispatch below flips r.scorer before chunk
            # i's attrs are written
            ran_scorer = r.scorer
            nxt = None
            if i + 1 < n:
                # speculative dispatch BEFORE retiring chunk i: the
                # device queue never drains while the host works.
                # Outside chunk i's span, so the mesh-level
                # dispatch/compile sub-spans of chunk i+1 parent under
                # the LADDER span rather than the wrong chunk.
                try:
                    nxt = dispatch(i + 1, new_state)
                except Exception as e:
                    # an execution-time failure has consumed the
                    # donated new_state — nothing left to retry on
                    if not _is_lowering(e) or not _leaves_alive(
                        new_state
                    ):
                        raise
                    # drain-and-retry: retire chunk i with nothing in
                    # flight; the synchronous XLA retry happens once
                    # this boundary's work is done
                    _note_fallback(i + 1, e)
            with _otrace.span("chunk", index=i) as _sp:
                tw = time.perf_counter()
                jax.block_until_ready(pop_a)
                device_s = time.perf_counter() - tw
                _flight.note_device(device_s)
                sweep_state = new_state  # synced: commit
                now = time.perf_counter()
                h = retire_common(i, pop_a, pop_k, curve, disp_s,
                                  device_s, now - t_mark, pend_fb)
                t_mark = now
                tb = time.perf_counter()
                with _flight.attribute("boundary"):
                    stop = boundary(i)
                boundary_s = time.perf_counter() - tb
                overlap = boundary_s if nxt is not None else 0.0
                r.boundary_overlap_s += overlap
                chunk_attrs(_sp, i, disp_s, device_s, overlap, h,
                            ran_scorer)
            if stop or i + 1 >= n:
                # certified (the in-flight speculation, if any, is
                # abandoned — its results are never read) or done
                return
            dl = _deadline_now()
            if dl is not None:
                # pipeline-aware deadline: chunk i+1 is already on the
                # device; the clock decides whether to RETIRE it, not
                # whether to dispatch it. Abandoning costs only
                # speculative device work.
                now = time.perf_counter()
                est = warm_chunk_s if warm_chunk_s is not None else prior_s
                if now > dl or (
                    est is not None and dl - now < est * 0.9
                ):
                    r.timed_out = True
                    return
            if nxt is not None:
                pending, pend_fb = nxt, False
            else:
                # the pipeline drained at a fallback: retry the failed
                # chunk synchronously (compiles the XLA solver — the
                # chunk is warm-estimate-excluded like chunk 0), then
                # speculation resumes from the next iteration
                _ladder.note_rung("pipelined_to_sync", chunk=i + 1)
                pending, _ = dispatch_or_fallback(i + 1, sweep_state)
                pend_fb = True
            i += 1

    # ---------------- fused megachunk walkers (mega_k > 1) ----------------

    def _arm_exit(i) -> tuple | None:
        """Device-side early-exit certificate arming — the exact mirror
        of boundary()'s adaptive gate: arm only when skipping the
        ladder past chunk ``i`` would save more than certification
        costs, and the bounds are already in hand (never block on
        them). Returns ``(cert_k, cert_mv)`` thresholds or None
        (disarmed sentinels)."""
        if multi or not bounds_fut.done():
            return None
        remaining_s = (n - i - 1) * (_est_chunk_s() or 0.0)
        if remaining_s <= cert_min_savings_s:
            return None
        try:
            lb_exact, ub0 = bounds_fut.result()
        except Exception:
            return None
        return int(ub0), int(lb_exact)

    def _mega_degradable(e) -> bool:
        """Any fault inside a fused group drains to the per-chunk
        dispatchers, which own the finer-grained recovery (the
        Pallas→XLA retry, the host-fallback rung); sanitizer trips and
        real regressions surface unchanged."""
        return _degradable(e) or _is_lowering(e)

    def dispatch_mega(i, k, st):
        """Enqueue ONE fused group covering ``chunks[i:i+k]``. Groups
        narrower than ``mega_k`` (the ladder tail, or a drain re-entry
        remainder) pad with repeats of the last chunk under an inactive
        mask — masked steps are inert no-ops, so the executable (keyed
        on the stacked temps shape) never re-specializes on the tail.
        Returns ``(out, armed, dispatch_s)``."""
        _chaos_chunk_hooks()
        _chaos.raise_if("megachunk_fault")
        td = time.perf_counter()
        group = list(chunks[i:i + k])
        active = [True] * k + [False] * (mega_k - k)
        while len(group) < mega_k:
            group.append(group[-1])
        arm = _arm_exit(i + k - 1)
        cert_k, cert_mv = arm if arm is not None else (None, None)
        fn = solve_lanes_megachunk if lanes else solve_megachunk
        out = fn(
            m, mesh, chains_per_device, jnp.stack(group), st,
            active=np.asarray(active), cert_k=cert_k, cert_mv=cert_mv,
            steps_per_round=steps_per_round, scorer=r.scorer,
        )
        r.dispatches += 1
        return out, arm is not None, time.perf_counter() - td

    def _read_exec(execd, k, armed) -> tuple:
        """How many of the group's ``k`` real chunks executed, and
        whether the scan exited early. A DISARMED group runs all ``k``
        by construction, so the answer needs no device transfer and no
        sync — the fused fast path stays one round-trip per group."""
        if not armed:
            return k, False
        e = np.asarray(execd)
        # replicated across shards (pmax) and lanes: row 0 suffices
        n_exec = int(e.reshape(-1, e.shape[-1])[0][:k].sum())
        return n_exec, n_exec < k

    def _certify_exit(cert_a, cert_ok, cert_mvs) -> bool:
        """Host-authoritative check of a device-flagged exit: the scan
        body tested the pure threshold ``best_k >= ub0 and best_mv <=
        lb_exact``; the host re-verifies the snapshot against the real
        oracles (feasibility, exact move count, preservation weight,
        one leader reseat) exactly like boundary() does. Returns True
        when the certificate holds."""
        nonlocal reseat_tries
        ok, ca, mv = (
            np.asarray(x)
            for x in fetch_global((cert_ok, cert_a, cert_mvs))
        )
        if lanes:
            ok = ok[:, :lanes].reshape(-1)
            mv = mv[:, :lanes].reshape(-1)
            ca = ca[:, :lanes].reshape(-1, *ca.shape[2:])
        else:
            ok, mv = ok.reshape(-1), mv.reshape(-1)
            ca = ca.reshape(-1, *ca.shape[1:])
        qual = [j for j in range(ok.shape[0]) if ok[j]]
        if not qual:
            return False
        try:
            lb_exact, ub0 = bounds_fut.result()
        except Exception:
            return False
        # lowest-move-count qualifier first, top candidate only (the
        # same single-candidate discipline as boundary())
        for j in sorted(qual, key=lambda j: int(mv[j]))[:1]:
            cand = arrays.unpad_candidate(ca[j], inst)
            if not inst.is_feasible(cand):
                continue
            if inst.move_count(cand) > lb_exact:
                continue
            w_cand = inst.preservation_weight(cand)
            if w_cand < ub0 and reseat_tries < 3:
                reseat_tries += 1
                cand = inst.best_leader_assignment(cand)
                w_cand = inst.preservation_weight(cand)
            if w_cand >= ub0:
                r.certified_a = cand
                if lanes:
                    r.winner_lane = int(j % lanes)
                r.certified_at_s = round(
                    time.perf_counter() - budget.t0, 4
                )
                r.mega_early_exit = True
                return True
        return False

    def retire_mega(i, k, out, disp_s, armed, group_s):
        """Retire one fused group: sync, commit the carried state,
        account the chunks that executed, and expand the group's single
        curve transfer into per-chunk handles (_CurveSlice). Files the
        warm estimate NORMALIZED per chunk under the fused width's own
        registry key."""
        nonlocal sweep_state, mega_warm_s, last_chunk_s
        (new_state, pop_a, pop_k, cert_a, cert_ok, cert_mv,
         curves, execd) = out
        tw = time.perf_counter()
        jax.block_until_ready(pop_a)
        device_s = time.perf_counter() - tw
        _flight.note_device(device_s)
        sweep_state = new_state
        n_exec, early = _read_exec(execd, k, armed)
        r.pop_a, r.pop_k = pop_a, pop_k
        r.mega_groups += 1
        r.chunks_exec += n_exec
        r.dispatch_s += disp_s
        r.device_s += device_s
        h = fetch_global_async(curves)
        ax = 2 if lanes else 1
        for j in range(n_exec):
            r.rounds_run += int(chunks[i + j].shape[0])
            handles.append(_CurveSlice(h, j, ax))
        per_chunk = group_s / max(1, n_exec)
        last_chunk_s = per_chunk
        if i > 0 and n_exec == k == mega_k:
            # full-width group past the compile-inclusive first one
            mega_warm_s = (
                per_chunk if mega_warm_s is None
                else min(mega_warm_s, per_chunk)
            )
        return (cert_a, cert_ok, cert_mv), n_exec, early, device_s

    def mega_attrs(sp, k, n_exec, armed, early, disp_s,
                   device_s) -> None:
        if sp is None:
            return
        sp.set(width=k, executed=n_exec, armed=armed, early_exit=early,
               dispatch_s=round(disp_s, 4), device_s=round(device_s, 4))

    def _drain(i, e, to_pipelined: bool) -> None:
        """Step down megachunk_to_chunked and re-enter the per-chunk
        ladder at chunk ``i`` — the first chunk no fused group
        finished. Width-keyed estimates mean the re-entry gates on the
        unfused prior, untouched by the fused measurements."""
        nonlocal mega_active
        mega_active = False
        _ladder.note_rung(
            "megachunk_to_chunked", chunk=i,
            **({"error": repr(e)[:200]} if e is not None
               else {"reason": "exit_uncertified"}),
        )
        if to_pipelined:
            run_pipelined(start=i)
        else:
            run_sync(start=i)

    def run_mega_sync():
        """Fused dispatcher, one group at a time: dispatch K chunks,
        sync, boundary — the per-chunk ladder's loop shape at 1/K the
        host round-trips."""
        nonlocal mega_active
        mega_active = True
        r.mega_k = mega_k
        i = 0
        while i < n:
            k = min(mega_k, n - i)
            dl = _deadline_now()
            if dl is not None and i >= 1:
                est = _est_chunk_s()
                if time.perf_counter() > dl or (
                    est is not None
                    and dl - time.perf_counter() < est * k * 0.9
                ):
                    r.timed_out = True
                    return
            with _otrace.span("megachunk", index=r.mega_groups,
                              first_chunk=i, width=k) as _sp:
                tg = time.perf_counter()
                try:
                    out, armed, disp_s = dispatch_mega(i, k, sweep_state)
                    certs, n_exec, early, device_s = retire_mega(
                        i, k, out, disp_s, armed,
                        time.perf_counter() - tg,
                    )
                except Exception as e:
                    if (not _mega_degradable(e)
                            or not _leaves_alive(sweep_state)):
                        raise
                    _drain(i, e, to_pipelined=False)
                    return
                mega_attrs(_sp, k, n_exec, armed, early, disp_s,
                           device_s)
            if early:
                with _flight.attribute("boundary"):
                    certified = _certify_exit(*certs)
                if certified:
                    return
                # the device flagged an exit the host could not
                # certify: the remaining fused groups would flag again
                # every step, so hand the tail to the per-chunk ladder
                # (whose boundary certificates carry the reseat/tight
                # tiers) from the first unexecuted chunk
                _drain(i + n_exec, None, to_pipelined=False)
                return
            with _flight.attribute("boundary"):
                stop = boundary(i + k - 1)
            if stop:
                return
            dl = _deadline_now()
            if dl is not None and time.perf_counter() > dl:
                r.timed_out = i + k < n
                return
            i += k

    def run_mega_pipelined():
        """Double-buffered fused dispatch: group g+1 enters the device
        queue before group g is waited on, so group g's boundary work
        (curve transfer, certificates, constructor adoption) overlaps
        K chunks of device time instead of one.

        Early-exit corner (documented in docs/PIPELINE.md): when group
        g exits early while group g+1 is already in flight, g+1's input
        state was donated at dispatch — there is no live buffer to
        resume from the exact exit point. A certificate that HOLDS
        makes this moot (the speculation is abandoned unread, as the
        per-chunk pipeline abandons its in-flight chunk). A certificate
        that FAILS host-side adopts the in-flight group (its trajectory
        is the full-K continuation — schedule-gap-free relative to its
        own input) and then drains to the per-chunk ladder."""
        nonlocal mega_active
        mega_active = True
        r.mega_k = mega_k
        r.pipelined = True
        t_mark = time.perf_counter()
        i = 0
        k = min(mega_k, n)
        try:
            pending = dispatch_mega(i, k, sweep_state)
        except Exception as e:
            if (not _mega_degradable(e)
                    or not _leaves_alive(sweep_state)):
                raise
            _drain(i, e, to_pipelined=True)
            return
        while True:
            out, armed, disp_s = pending
            new_state = out[0]
            j, k_next = i + k, min(mega_k, n - i - k)
            nxt, drain_exc = None, None
            if k_next > 0:
                try:
                    nxt = dispatch_mega(j, k_next, new_state)
                except Exception as e:
                    if (not _mega_degradable(e)
                            or not _leaves_alive(new_state)):
                        raise
                    drain_exc = e
            with _otrace.span("megachunk", index=r.mega_groups,
                              first_chunk=i, width=k) as _sp:
                now = time.perf_counter()
                certs, n_exec, early, device_s = retire_mega(
                    i, k, out, disp_s, armed, now - t_mark,
                )
                t_mark = time.perf_counter()
                tb = time.perf_counter()
                with _flight.attribute("boundary"):
                    stop = early or boundary(i + k - 1)
                if nxt is not None:
                    r.boundary_overlap_s += time.perf_counter() - tb
                mega_attrs(_sp, k, n_exec, armed, early, disp_s,
                           device_s)
            if early:
                with _flight.attribute("boundary"):
                    certified = _certify_exit(*certs)
                if certified:
                    return  # in-flight speculation abandoned unread
                if nxt is not None:
                    # adopt the in-flight group, then hand the tail to
                    # the per-chunk ladder (see docstring corner)
                    out2, armed2, disp2 = nxt
                    certs2, n2, early2, _dev2 = retire_mega(
                        j, k_next, out2, disp2, armed2,
                        time.perf_counter() - t_mark,
                    )
                    t_mark = time.perf_counter()
                    if early2:
                        with _flight.attribute("boundary"):
                            certified2 = _certify_exit(*certs2)
                        if certified2:
                            return
                    _drain(j + n2, None, to_pipelined=True)
                    return
                _drain(i + n_exec, None, to_pipelined=True)
                return
            if stop or k_next <= 0:
                return
            dl = _deadline_now()
            if dl is not None:
                nowd = time.perf_counter()
                est = _est_chunk_s()
                if nowd > dl or (
                    est is not None and dl - nowd < est * k_next * 0.9
                ):
                    r.timed_out = True
                    return
            if drain_exc is not None:
                _drain(j, drain_exc, to_pipelined=True)
                return
            pending = nxt
            i, k = j, k_next

    prof = (
        jax.profiler.trace(profile_dir)  # SURVEY.md §5 tracing/profiling
        if profile_dir
        else contextlib.nullcontext()
    )
    with prof:
        if engine == "sweep" and mega_k > 1 and n > 1:
            # fused megachunk ladder; faults drain into the per-chunk
            # walkers below via megachunk_to_chunked
            if pipeline:
                run_mega_pipelined()
            else:
                run_mega_sync()
        elif pipeline and engine == "sweep" and n > 1:
            run_pipelined()
        else:
            run_sync()
    # materialize the deferred curve transfers (each copy was started
    # at its chunk's retire — by now they are host-resident; traced
    # solves already consumed them in chunk_attrs, which caches)
    r.curves = [np.asarray(h.get()) for h in handles]
    if warm_chunk_s is not None:
        _WARM_CHUNKS.update(_wkey(), warm_chunk_s)
    if mega_warm_s is not None:
        _WARM_CHUNKS.update(_wkey(mega_k), mega_warm_s)
    return r


def _seed_rank(inst, a) -> tuple:
    """The one candidate rank every seed-selection path shares
    (``_pick_seed`` and ``_host_fallback``): feasibility first, then
    fewest violations, then preservation weight, then fewest moves as
    the tie-break. One definition, so a rank change (the move-count
    tie-break, ISSUE 7) cannot silently apply on one path and not the
    other."""
    pen = sum(inst.violations(a).values())
    return (
        pen == 0, -pen, inst.preservation_weight(a),
        -int(inst.move_count(a)),
    )


def _validate_warm_start(inst, a) -> "np.ndarray | None":
    """Admission check for a delta-API warm-start candidate
    (docs/WATCH.md): shape/dtype/index-range and the hard structural
    families (out-of-range slots, nulls in valid slots, duplicate
    brokers within a partition) must hold — those the annealer's move
    set preserves rather than repairs. Balance-band violations are fine
    (fixing them is the search's job). A candidate whose ONLY violation
    is the leader band gets the exact reseat applied here, at
    admission: adaptation leaves leader counts wherever survival put
    them, the reseat is metadata-only, and every downstream consumer —
    the seed rank in ``_pick_seed``, the certify racer, the host
    fallback — then sees the candidate at its true rank instead of
    discarding a near-optimal plan over a violation the engine repairs
    exactly anyway. A candidate that fails is REJECTED onto the
    degradation ladder (``warm_start_rejected``) and the solve proceeds
    from scratch; returns the validated int32 array or None."""
    if a is None:
        return None
    reason = None
    arr = np.asarray(a)
    if arr.shape != (inst.num_parts, inst.max_rf):
        reason = (
            f"shape {arr.shape} != {(inst.num_parts, inst.max_rf)}"
        )
    elif not np.issubdtype(arr.dtype, np.integer):
        reason = f"non-integer dtype {arr.dtype}"
    else:
        arr = np.ascontiguousarray(arr, dtype=np.int32)
        viol = inst.violations(arr)
        bad = {
            k: viol[k]
            for k in ("slot_out_of_range", "null_in_valid_slot",
                      "duplicate_in_partition")
            if viol[k]
        }
        if bad:
            reason = f"structural violations {bad}"
        elif viol["leader_balance"] and not any(
            v for k, v in viol.items() if k != "leader_balance"
        ):
            try:
                arr = np.ascontiguousarray(
                    inst.best_leader_assignment(arr), dtype=np.int32
                )
            except Exception:
                pass  # infeasible transportation: admit un-reseated
    if reason is not None:
        _ladder.note_rung("warm_start_rejected", reason=reason[:200])
        _olog.warn("warm_start_rejected", reason=reason[:200])
        return None
    return arr


def _pick_seed(inst, lp_warm, lp_warm_extends, checkpoint,
               warm_start=None):
    """Stage 2 — warm-start selection: the host-side greedy repair
    (near-feasible, near-min-move), optionally displaced by a
    higher-ranking checkpoint plan (SURVEY.md §5 resume: the next solve
    can never regress below the last one) or by an uncertified
    constructor plan. When the reseat racer already extended the greedy
    seed (greedy + exact reseat, returned uncertified), reuse it
    directly instead of recomputing the greedy repair — the extension
    can only outrank what it extends.

    A validated delta-API ``warm_start`` candidate (the previous plan
    adapted to the post-event topology, docs/WATCH.md) competes under
    the same rank and wins ties — surviving replicas stay put unless
    the greedy repair is provably better.

    Returns ``(a_seed, resumed_from_checkpoint, warm_started)``."""
    resumed = False
    warm_used = False
    warm_extends = lp_warm is not None and lp_warm_extends
    if warm_extends:
        a_seed = lp_warm
    else:
        with _otrace.span("greedy"):
            a_seed = greedy_seed(inst)
    assert (a_seed[inst.slot_valid] < inst.num_brokers).all(), (
        "seed left unfilled slots"
    )
    if checkpoint:
        # fail fast on an unwritable path BEFORE spending solve time
        from pathlib import Path

        Path(checkpoint).parent.mkdir(parents=True, exist_ok=True)
        a_prev = ckpt.load(checkpoint, inst)
        if a_prev is not None and _seed_rank(inst, a_prev) >= \
                _seed_rank(inst, a_seed):
            a_seed = a_prev
            resumed = True
    if warm_start is not None and _seed_rank(inst, warm_start) >= \
            _seed_rank(inst, a_seed):
        a_seed = warm_start
        warm_used = True
    if lp_warm is not None and not warm_extends:
        def _rank(zz):
            return (
                -sum(inst.violations(zz).values()),
                inst.preservation_weight(zz),
                -inst.move_count(zz),
            )

        if _rank(lp_warm) > _rank(a_seed):
            a_seed = lp_warm
            warm_used = False
    return a_seed, resumed, warm_used


def _build_chunks(inst, engine, rounds, t_hi, t_lo, time_limit_s):
    """Stage 3 — the annealing schedule: one geometric ladder cut into
    equal chunks (one compiled executable — temps is a runtime arg).
    Between chunks the ladder loop (a) checks the wall clock against
    ``time_limit_s`` (VERDICT r1 item 4) and (b) stops early when a
    candidate PROVABLY hits the global optimum. The sweep engine is
    STATEFUL — chain populations thread through chunk boundaries, so
    cutting the ladder changes only where the host may look, not the
    search dynamics — and is therefore always chunked; chunk length
    stays a multiple of the snapshot cadence (8) and even
    (exchange-sweep parity) so the chunked run is bit-identical to the
    uncut ladder. The chain engine restarts its populations from a
    reseed at each boundary (diversity cost), so it is chunked only
    when a time limit demands it. Each boundary costs a dispatch+sync
    round-trip (~0.1 s over a tunneled TPU), so the sweep schedule cuts
    fine (8 chunks) only when boundaries can pay for themselves: under
    a deadline, or at sizes where one chunk dwarfs the certificate work
    and an early stop saves minutes."""
    from .arrays import geometric_temps

    temps_full = geometric_temps(t_hi, t_lo, rounds)
    # host-built floats steer every accept decision; the device-side
    # NaN guard cannot see them until a trajectory is already wrong
    _san.check_host(temps_full, "temperature ladder")
    if engine == "sweep":
        n_chunks = (
            8 if (time_limit_s is not None or inst.num_parts >= 20_000)
            else 2
        )
        c = 8 * max(1, -(-rounds // (8 * n_chunks)))
    elif time_limit_s is not None:
        c = max(1, -(-rounds // 8))
    else:
        c = rounds  # chain engine, no deadline: one uncut ladder
    chunks = [temps_full[i:i + c] for i in range(0, rounds, c)]
    if len(chunks) > 1 and chunks[-1].shape[0] < c:
        # pad the tail chunk with t_lo so every chunk shares one
        # compiled shape (extra cold rounds only ever improve)
        pad = c - chunks[-1].shape[0]
        chunks[-1] = jnp.concatenate(
            [chunks[-1], jnp.full((pad,), t_lo, jnp.float32)]
        )
    return chunks


def _final_selection(
    inst, m, pop_a, polish_jit, polish_fut, bounds_fut, lp_fut,
    budget, multi, lanes: int = 0,
):
    """Stage 5 — final selection: exact-rescore the per-shard winners on
    device (the Pallas kernel on TPU, XLA elsewhere) and rank by
    feasibility, then weight, then fewest moves; certify FIRST, polish
    only on failure (the steepest-descent polish applies ONE move per
    [P, R, B] evaluation — ~a minute at 50k partitions — so paying for
    it when the raw champion, plus at most one exact leader reseat,
    already meets both bounds would put dead weight on every certified
    solve's critical path); finally let an uncertified constructor plan
    outrank the annealed one under the same lexicographic objective.
    Joins block (no .done() polls), so multi-controller workers reach
    identical verdicts.

    Returns ``(best_a, final_cert, lp_plan_won, winner_lane)`` where
    ``final_cert`` names the certify-first outcome ("ok"/"ok_reseat"
    mean the polish was provably unnecessary and was skipped) and
    ``winner_lane`` is the portfolio lane the champion came from (None
    when ``lanes`` is 0 — the DrJAX-style best-feasible reduction over
    the lane axis happens right here, docs/PORTFOLIO.md)."""
    from ...ops.score import moves_batch
    from ...ops.score_pallas import score_batch_auto
    from ...parallel.mesh import fetch_global

    # pop_a comes back mesh-sharded; gather it to one device first (it
    # is n_dev candidates, a few hundred KB) — Mosaic kernels cannot be
    # auto-partitioned
    pop_a = jnp.asarray(fetch_global(pop_a))
    if lanes:
        # portfolio: [n_dev, Lp, P, R] -> the real lanes' winners as
        # one flat pool; flat index j decodes to lane j % lanes. The
        # base (default-config) model scores every lane — scoring is
        # weight/penalty algebra, config-independent by construction.
        pop_a = pop_a[:, :lanes].reshape(-1, *pop_a.shape[2:])
    s = score_batch_auto(pop_a, m)
    moves = moves_batch(pop_a, m)
    # lexicographic in two int32-safe stages (a combined key would
    # overflow int32 at 10k partitions): feasibility/weight first,
    # fewest moves as the tie-break
    primary = jnp.where(s.penalty == 0, s.weight, -s.penalty - 1)
    tied = primary == primary.max()
    top = jnp.argmax(
        jnp.where(tied, -moves, jnp.iinfo(jnp.int32).min)
    )
    cand = pop_a[top]
    winner_lane = int(top) % lanes if lanes else None
    certified_final = None
    final_cert = "budget_spent"  # why the attempt concluded
    left = budget.remaining()
    if left is None or left > 0:
        # cap the pre-polish join so an instance with a straggling
        # bounds ladder AND a real optimality gap keeps the old overlap
        # (polish runs while the LPs finish; the post-polish join below
        # still waits). Under multi-controller SPMD the join must stay
        # unbounded: a wall-clock cap could resolve differently per
        # worker and diverge the control flow.
        join_cap = left if (multi or left is not None) else 15.0
        try:
            lb_exact, ub0 = bounds_fut.result(timeout=join_cap)
        except Exception:
            lb_exact = ub0 = None
        if ub0 is None:
            final_cert = "bounds_unavailable"
        else:
            cand_np = arrays.unpad_candidate(cand, inst)
            if inst.move_count(cand_np) > lb_exact:
                final_cert = "moves_above_lb"
            elif not inst.is_feasible(cand_np):
                final_cert = "infeasible"
            elif inst.preservation_weight(cand_np) >= ub0:
                certified_final = cand_np
                final_cert = "ok"
            else:
                reseated = inst.best_leader_assignment(cand_np)
                if inst.preservation_weight(reseated) >= ub0:
                    # replica sets unchanged by the reseat, so the
                    # move bound still holds
                    certified_final = reseated
                    final_cert = "ok_reseat"
                else:
                    final_cert = "weight_below_ub"
                    # the reseat is >= the raw champion (its internal
                    # rank guard): start the polish from it instead of
                    # discarding the computed work (re-padded so the
                    # polish executable keeps its bucket shape)
                    cand = jnp.asarray(
                        arrays.pad_candidate(reseated, m), jnp.int32
                    )
    if certified_final is not None:
        # the caller's final proof block re-derives the certificate
        # from the (memoized) bounds — no special-casing needed
        return certified_final, final_cert, False, winner_lane
    pol = polish_jit
    if polish_fut is not None:
        # join the ladder-overlapped compile (free when the ladder
        # outlasted it, and never slower than starting a second compile
        # of the same executable here); any AOT mismatch (sharding,
        # aval) falls back to the jitted path below
        try:
            left = budget.remaining()
            pol = polish_fut.result(
                timeout=60.0 if left is None else left
            )
        except Exception:
            pol = polish_jit
    try:
        best_a = pol(m, cand)
    except Exception:
        best_a = polish_jit(m, cand)
    best_a = arrays.unpad_candidate(best_a, inst)
    try:
        # join bounded by the remaining deadline budget: when the
        # ladder outlasted the prefetch (the usual case) this is free,
        # but a timed-out solve must not stall on a straggling LP
        _, ub0 = bounds_fut.result(timeout=budget.remaining())
    except Exception:
        ub0 = None
    if (
        inst.is_feasible(best_a)
        and not budget.expired()  # deadline left
        and (ub0 is None or inst.preservation_weight(best_a) < ub0)
    ):
        # below the weight bound: exact leader reseat (zero replica
        # movement) — weight-improving or a no-op
        best_a = inst.best_leader_assignment(best_a)
    lp_won = False
    if lp_fut is not None:
        # even an uncertified constructed plan may outrank the annealed
        # one — compare under the solve's lexicographic objective
        # (feasible, weight, fewest moves). Re-ask the budget: the
        # bounds join above may have consumed the last of it
        left = budget.remaining()
        try:
            plan, _ok, _extends = lp_fut.result(
                timeout=10.0 if left is None else left
            )
        except Exception:
            plan = None
        if plan is not None:
            def rank(zz):
                return (
                    inst.is_feasible(zz),
                    inst.preservation_weight(zz),
                    -inst.move_count(zz),
                )

            plan = np.asarray(plan, dtype=np.int32)
            if rank(plan) > rank(best_a):
                best_a = plan
                lp_won = True
    return best_a, final_cert, lp_won, (
        None if lp_won else winner_lane
    )


def _solve_tpu_inner(
    inst, seed, batch, rounds, sweeps, steps_per_round, t_hi, t_lo,
    n_devices, engine, checkpoint, profile_dir, time_limit_s,
    backend_fut, t0, bounds_fut, cert_min_savings_s=1.0,
    lp_fut=None, multi=False, lp_wait_s=_CONSTRUCT_WAIT_S,
    pipeline=True, budget: Budget | None = None, warm_start=None,
    portfolio=None, megachunk=None,
) -> SolveResult:
    timed_out = False
    early_stopped = False
    constructed = False
    final_cert = None  # certify-first outcome at final selection
    rounds_run = 0
    if budget is None:
        budget = Budget(time_limit_s, t0=t0)
    # multi-controller SPMD (see solve_tpu): per-process wall-clock
    # budgets would let workers diverge — in front of collectives
    # (deadlock) or at the final bound joins (disagreeing plans) — so
    # the deadline is disabled; the requested value still lands in
    # stats for the operator to see it was not enforced.
    time_limit_req = time_limit_s
    if multi:
        time_limit_s = None
        budget = Budget(None, t0=t0)

    # pipeline phase spans (obs.trace): every stage gets exactly one
    # span on every path — stages that do not run emit a zero-duration
    # span tagged skipped=True, so the span tree's phase vocabulary
    # (bounds/constructor/seed/ladder/polish/verify) is complete in
    # every solve report regardless of which shortcut fired
    with _otrace.span("constructor") as _sp:
        certified_a, lp_warm, lp_warm_extends = _await_constructor(
            lp_fut, lp_wait_s, checkpoint, budget
        )
        if _sp is not None:
            _sp.set(
                skipped=lp_fut is None,
                wait_budget_s=lp_wait_s,
                certified=certified_a is not None,
                warm_start=lp_warm is not None,
            )
    if certified_a is not None:
        early_stopped = True
        constructed = True

    # platform + search-effort defaults are resolved ONLY when the
    # search will actually run: on the constructed path the backend may
    # still be initializing on its thread (or never be needed at all) —
    # joining it would put the multi-second TPU client init back on the
    # critical path the constructor race exists to avoid.
    if certified_a is None:
        platform = backend_fut.result()
        t_backend = time.perf_counter()
        d = _defaults(inst, platform, engine)
        engine = d["engine"]
        batch = batch or d["batch"]
        rounds = rounds or sweeps or d["rounds"]
        steps_per_round_ignored = False
        steps_per_round = steps_per_round or d["steps_per_round"]
        if engine == "sweep" and steps_per_round != 1:
            # the sweep engine has no inner step loop: its sequential
            # budget is `rounds` sweeps, each touching every partition
            # once. An explicit user override has no effect — say so in
            # stats instead of silently eating the knob.
            steps_per_round_ignored = True
            steps_per_round = 1
        if t_hi is None:
            t_hi = 2.0 if engine == "sweep" else 2.5
        if t_lo is None:
            t_lo = 0.02 if engine == "sweep" else 0.05
    else:
        # a dead device must not fail a solve that never needs one:
        # ensure_backend's stored exception (dead tunnel, plugin error)
        # only matters on the search path
        try:
            platform = (
                backend_fut.result(timeout=0.0) if backend_fut.done()
                else "host"
            )
        except Exception:
            platform = "host"
        t_backend = None
        engine = "construct"
        batch = rounds = steps_per_round = 0
        steps_per_round_ignored = False

    # the ledger's host-constructor window: seed selection plus model
    # construction — the host work that must finish before anything can
    # be lowered or dispatched (obs/flight attribution; nested, so any
    # leaf window accrued inside would be netted out, not double-counted)
    with _flight.attribute("constructor"):
        if certified_a is None:
            with _otrace.span("seed") as _sp:
                a_seed, resumed, warm_started = _pick_seed(
                    inst, lp_warm, lp_warm_extends, checkpoint, warm_start
                )
                if _sp is not None:
                    _sp.set(resumed_from_checkpoint=resumed,
                            warm_started=warm_started,
                            warm_start_extends_greedy=bool(lp_warm_extends))
        else:
            _otrace.mark("seed", skipped=True)
            a_seed = certified_a  # never dispatched: the ladder is empty
            resumed = False
            # the delta path's adapted plan can BE the certified plan:
            # the warm-certify race worker tags its win (docs/WATCH.md)
            warm_started = getattr(inst, "_construct_path", None) == "warm"
        # shape bucketing: lower the model padded up to its canonical
        # bucket so every instance in the bucket reuses one set of
        # jitted/AOT executables (solvers.tpu.bucket); padded rows are
        # inert and every host-side oracle below sees plans sliced back
        # to the real shape
        if certified_a is None:
            from . import bucket

            bkt_parts, bkt_rf = bucket.bucket_shape(inst)
            m = arrays.from_instance(inst, num_parts=bkt_parts,
                                     max_rf=bkt_rf)
            bucket.STATS.record_bucket(
                (inst.num_brokers, inst.num_racks, bkt_parts, bkt_rf),
                padded=(bkt_parts, bkt_rf) != (inst.num_parts,
                                               inst.max_rf),
            )
        else:
            m = None
            bkt_parts = bkt_rf = None
    t_seed = time.perf_counter()

    if certified_a is None:
        from ...parallel.mesh import init_sweep_state, make_mesh
        from .polish import polish_jit

        mesh = make_mesh(n_devices)
        n_dev = mesh.devices.size
        chains_per_device = max(1, batch // n_dev)
        key = jax.random.PRNGKey(seed)
    else:
        # the constructed path touches no device at all: mesh creation,
        # PRNG keys and the jax module imports each cost a dispatch /
        # client round-trip (~1 s each over a tunneled TPU in a cold
        # process) for machinery the empty ladder below never uses
        mesh = None
        n_dev = 0
        chains_per_device = 0
        key = None

    if certified_a is not None:
        chunks = []  # the ladder never runs; build no device schedule
    else:
        chunks = _build_chunks(inst, engine, rounds, t_hi, t_lo,
                               time_limit_s)
    moves_lb = inst.move_lower_bound()  # cheap counting bound

    # hot-path scorer (VERDICT r1 items 2-3): on TPU the sweep engine's
    # per-sweep work runs through the Mosaic kernels (one-hot algebra on
    # the VPU/MXU) instead of XLA scatter-adds; if Mosaic fails to lower
    # on this hardware, the ladder falls back to XLA and says so in
    # stats rather than dying
    scorer = "pallas" if (platform == "tpu" and engine == "sweep") else "xla"

    seed_dev = (
        jnp.asarray(arrays.pad_candidate(a_seed, m), jnp.int32)
        if certified_a is None else None
    )
    # portfolio lanes (docs/PORTFOLIO.md): race pw diverse configs in
    # one lane-padded dispatch. Sweep engine only (the chain engine's
    # small-instance niche keeps its sequential shape), single
    # controller only (the early-exit boundary races are host-side and
    # must not desync SPMD workers).
    pw = (
        _resolve_portfolio_width(portfolio)
        if (certified_a is None and engine == "sweep" and not multi)
        else 1
    )
    port_lanes = 0  # padded dispatch width (0 = portfolio off)
    port_cfgs: list = []
    if pw > 1:
        from ...parallel.mesh import init_lane_state
        from . import bucket

        port_cfgs = arrays.portfolio_configs(pw)
        # pad the lane count up the SAME rung ladder the batched
        # multi-tenant path uses, so the portfolio dispatch reuses the
        # one lane-padded executable per bucket (padding lanes rerun
        # lane 0's default config and are masked at selection)
        port_lanes = bucket.lane_bucket(pw)
        port_models = [arrays.with_config(m, c) for c in port_cfgs]
        port_models += [port_models[0]] * (port_lanes - pw)
        m_solver = arrays.stack_models(port_models)
        lane_seeds = np.broadcast_to(
            np.asarray(seed_dev, np.int32),
            (port_lanes, *seed_dev.shape),
        )
        # lane 0 consumes the solo path's key VERBATIM (the width-1
        # parity anchor: a 1-lane portfolio is bit-identical to the
        # solo solve); diversity lanes and padding lanes fold distinct
        # stream ids so no two lanes share a stream
        lane_keys = jnp.stack(
            [key]
            + [jax.random.fold_in(key, i) for i in range(1, pw)]
            + [jax.random.fold_in(key, pw + j)
               for j in range(port_lanes - pw)]
        )
        from ...parallel.mesh import note_lane_serve

        note_lane_serve((inst.num_brokers, inst.num_racks,
                         int(bkt_parts), int(bkt_rf)), pw, port_lanes)
        # the portfolio dispatch rides the shared solve mesh: rebuild
        # with the per-bucket (chains × lanes) split the chooser picked
        # (docs/MESH.md; default chains-only until evidence, same
        # devices either way so n_dev/chains_per_device are unchanged)
        from ...parallel.mesh import make_solve_mesh

        mesh = make_solve_mesh(
            n_devices, lanes=port_lanes,
            bucket_key=(inst.num_brokers, inst.num_racks,
                        int(bkt_parts), int(bkt_rf)),
            engine=engine, multi=multi,
        )
        sweep_state = init_lane_state(
            m_solver, lane_seeds, lane_keys, mesh, chains_per_device
        )
    else:
        m_solver = m
        # sweep engine: full population state (including the per-shard
        # RNG keys) threads through the chunks — the chunked schedule
        # replays exactly the uncut ladder's trajectory
        sweep_state = (
            init_sweep_state(m, seed_dev, key, mesh, chains_per_device)
            if engine == "sweep" and certified_a is None
            else None
        )
    if not chunks:
        polish_jit = None  # device path never imported (certified)
    # the polish AOT compile is LAZY (r5): the certify-first design
    # means most at-scale solves never run the steepest-descent polish,
    # and eagerly compiling its ~20 s executable on a daemon thread
    # stole the cpu the sweep-executable compile needs on few-core
    # hosts (measured: the two compiles serialized and doubled the
    # adversarial cold start, 18 s -> 34 s). The starter fires at the
    # first FAILED boundary certificate — the earliest evidence the
    # polish may actually run — so the compile still overlaps the
    # remaining chunks; a solve whose first check is the final one
    # compiles inline there instead. The AOT handle is joined (not
    # fire-and-forgotten) and the compiled object executed directly;
    # the _PENDING_AOT token lets a long-lived service know a daemon
    # compile may still be in flight before it drops jit caches.
    polish_fut_box: list = []

    def _start_polish_aot():
        if polish_fut_box:
            return  # idempotent: one compile thread at most
        def _aot_polish():
            token = object()
            _PENDING_AOT.add(token)
            try:
                return polish_jit.lower(m, seed_dev).compile()
            finally:
                _PENDING_AOT.discard(token)

        polish_fut_box.append(_BoundsTask(_aot_polish))

    if chunks:
        # warm-chunk estimates are propagated across solves per
        # executable identity; the "single" tag keeps this sequential
        # path's estimates disjoint from the batched lane path's (a
        # batched chunk does L lanes of device work per dispatch). The
        # portfolio path tags itself with the SAME ("lanes", Lp, ...)
        # key space as the multi-tenant batch path — they dispatch the
        # identical lane-padded executable, so they share its estimate.
        if port_lanes:
            from ...parallel.mesh import mesh_spec

            warm_key = ("lanes", port_lanes, engine, n_dev,
                        chains_per_device, steps_per_round,
                        int(bkt_parts), int(bkt_rf))
            # lane-split estimates file under their own identity; the
            # default split keeps the historical key byte-for-byte
            _pdc, _pdl = mesh_spec(mesh)
            if _pdl > 1:
                warm_key = (*warm_key, f"{_pdc}x{_pdl}")
        else:
            warm_key = ("single", engine, n_dev, chains_per_device,
                        steps_per_round, int(bkt_parts), int(bkt_rf))
        # the `portfolio` span (docs/PORTFOLIO.md): zero-duration,
        # attribute-only marker so solve reports carry the racing
        # geometry even when the ladder span is the one timed
        if port_lanes:
            _otrace.mark("portfolio", width=pw, lane_bucket=port_lanes)
        # fused megachunk width (ISSUE 17): resolved per BUCKET — the
        # evidence key is the warm-chunk identity, so "auto" tunes K
        # from this executable family's own measured host/device split
        if engine == "sweep":
            from . import sweep as _sweep_mod

            _mega_sup = getattr(_sweep_mod, "SUPPORTS_MEGACHUNK", False)
        else:
            _mega_sup = False
        mega_k, mega_mode = _resolve_megachunk(
            megachunk, _mega_sup, multi, len(chunks),
            (*warm_key, int(chunks[0].shape[0]), scorer),
        )
        marks0 = _flight.ledger_marks()
        with _otrace.span("ladder", engine=engine,
                          chunks=len(chunks)) as _sp:
            lad = _run_ladder(
                inst, m_solver, mesh, chains_per_device, rounds,
                steps_per_round, engine, scorer, chunks, seed_dev, key,
                sweep_state, lp_fut, bounds_fut, multi,
                cert_min_savings_s, budget, profile_dir,
                polish_starter=_start_polish_aot, pipeline=pipeline,
                warm_key=warm_key, lanes=pw if port_lanes else 0,
                mega_k=mega_k,
            )
            if _sp is not None:
                _sp.set(rounds_run=lad.rounds_run,
                        timed_out=lad.timed_out, scorer=lad.scorer,
                        pipelined=lad.pipelined,
                        dispatch_s=round(lad.dispatch_s, 4),
                        device_s=round(lad.device_s, 4),
                        boundary_overlap_s=round(
                            lad.boundary_overlap_s, 4),
                        boundary_certified=lad.certified_a is not None,
                        portfolio_width=pw if port_lanes else None,
                        dispatches=lad.dispatches,
                        megachunk_k=lad.mega_k)
        if engine == "sweep" and lad.dispatches:
            # feed the fusion evidence table (KAO_MEGACHUNK=auto) from
            # the attribution funnel's measured windows — the SAME
            # dispatch/device leaves the solve ledger lands, differenced
            # around the ladder, so the evidence table and the ledger
            # can never disagree. Compile time is its own leaf, so the
            # per-dispatch overhead here is compile-exclusive (the warm
            # steady state fusion actually tunes for). Falls back to
            # the ladder's own tallies when accounting is inactive.
            marks1 = _flight.ledger_marks()
            ev_n = marks1["dispatches"] - marks0["dispatches"]
            if ev_n > 0:
                ev_disp = marks1["dispatch_s"] - marks0["dispatch_s"]
                ev_dev = marks1["device_s"] - marks0["device_s"]
            else:
                ev_n, ev_disp, ev_dev = (
                    lad.dispatches, lad.dispatch_s, lad.device_s
                )
            note_megachunk_evidence(
                (*warm_key, int(chunks[0].shape[0]), lad.scorer),
                dispatches=ev_n, dispatch_s=ev_disp,
                chunks=lad.chunks_exec, device_s=ev_dev,
            )
            if port_lanes:
                # sharding evidence rides the same funnel (docs/MESH.md)
                from ...parallel.mesh import (
                    mesh_spec, note_sharding_evidence,
                )

                note_sharding_evidence(
                    (inst.num_brokers, inst.num_racks, int(bkt_parts),
                     int(bkt_rf)), mesh_spec(mesh),
                    lanes=port_lanes, solves=ev_n, device_s=ev_dev,
                )
    else:
        # constructed fast path: the ladder never runs, and calling into
        # it would import device-adjacent modules this path avoids
        _otrace.mark("ladder", skipped=True)
        lad = _LadderResult(scorer=scorer)
        mega_mode = "off"
    polish_fut = polish_fut_box[0] if polish_fut_box else None
    pop_a, pop_k = lad.pop_a, lad.pop_k
    scorer, pallas_fallback = lad.scorer, lad.pallas_fallback
    tight_fut = lad.tight_fut
    rounds_run += lad.rounds_run
    timed_out = timed_out or lad.timed_out
    if lad.timed_out:
        # deadline rung: the ladder returned best-so-far early — a
        # degradation in search depth, recorded like every other rung
        _ladder.note_rung("deadline_truncated",
                          rounds_run=lad.rounds_run)
    if lad.certified_a is not None:
        certified_a = lad.certified_a
        early_stopped = True
        constructed = constructed or lad.constructed
    t_solve = time.perf_counter()
    curve = (
        np.concatenate(lad.curves, axis=-1) if lad.curves
        else np.zeros((1, 0), dtype=np.int64)
    )
    # best-score trajectory (max over shards — and over lanes on the
    # portfolio path): stats' score_curve and the solve report's
    # annealing summary share one computation
    curve = np.asarray(jax.device_get(curve))
    best_curve = curve.max(axis=tuple(range(curve.ndim - 1)))
    if _otrace.active():
        _imp = (
            int((np.diff(best_curve) > 0).sum())
            if best_curve.size > 1 else 0
        )
        _otrace.set_trajectory(
            engine=engine,
            rounds=int(best_curve.size),
            energy_curve=_downsample(best_curve, 64),
            improved_rounds=_imp,
            plateau_rounds=max(0, int(best_curve.size) - 1 - _imp),
        )

    winner_lane = lad.winner_lane
    if certified_a is not None:
        # a chunk-boundary candidate already carries the optimality
        # certificate — selection and polish cannot improve a proven
        # global optimum
        _otrace.mark("polish", skipped=True)
        best_a = np.asarray(certified_a, dtype=np.int32)
    else:
        # the "polish" phase span covers all of final selection: the
        # device rescore, the certify-first attempt, and (only on
        # certificate failure) the steepest-descent polish itself —
        # final_cert names which of those actually ran
        with _otrace.span("polish") as _sp:
            best_a, final_cert, lp_won, winner_lane = _final_selection(
                inst, m, pop_a, polish_jit, polish_fut, bounds_fut, lp_fut,
                budget, multi, lanes=pw if port_lanes else 0,
            )
            if _sp is not None:
                _sp.set(final_cert=final_cert, lp_plan_won=lp_won)
        constructed = constructed or lp_won
    t_polish = time.perf_counter()

    # host-side exact verification (SURVEY.md §4.3 property): the engine's
    # incremental scores must agree with the numpy oracle
    with _otrace.span("verify") as _sp:
        viol = inst.violations(best_a)
        weight = inst.preservation_weight(best_a)
        feasible = all(v == 0 for v in viol.values())
        moves_final = int(inst.move_count(best_a))
        if checkpoint:
            # persist BEFORE the certification joins below: with no
            # deadline they may block on a straggling LP, and a solve
            # killed in that window must not lose its plan. A write
            # FAILURE (disk full, permissions, the chaos injection
            # point) degrades to checkpoint-skipped — the solve already
            # holds a verified plan and must return it, not die on
            # persistence (docs/RESILIENCE.md)
            try:
                _chaos.raise_if("checkpoint_write", OSError)
                ckpt.save(
                    checkpoint,
                    inst,
                    best_a,
                    meta={
                        "objective": int(weight),
                        "feasible": feasible,
                        "moves": moves_final,
                        "engine": engine,
                    },
                )
            except Exception as e:
                _ladder.note_rung("checkpoint_skipped",
                                  error=repr(e)[:200])
        # optimality certificate: when the final plan meets both bounds
        # it is a PROVEN global optimum (weight is the primary
        # objective, moves the tie-break, and no feasible plan can beat
        # either bound). A boundary-certified plan already holds the
        # proof; otherwise join the prefetched bounds — bounded by any
        # remaining deadline budget so a timed-out solve is not stalled
        # by a straggling LP — and re-derive it. The synchronous tier-1
        # escalation inside certify_optimal is allowed only when no
        # deadline is in play.
        if certified_a is not None:
            proved_optimal = True
        else:
            try:
                timeout = budget.remaining()
                bounds_fut.result(timeout=timeout)
                if tight_fut is not None:
                    # a tier-1 LP is already running on the worker: join
                    # it (budget-bounded) rather than letting
                    # certify_optimal recompute the same multi-second LP
                    # on this thread
                    tight_fut.result(timeout=timeout)
                proved_optimal = inst.certify_optimal(
                    best_a,
                    allow_tight=(
                        time_limit_s is None or tight_fut is not None
                    ),
                )
            except Exception:
                proved_optimal = False
        if _sp is not None:
            _sp.set(feasible=feasible, violations=sum(viol.values()),
                    moves=moves_final, proved_optimal=proved_optimal)

    if port_lanes:
        # adaptive-portfolio evidence (docs/PORTFOLIO.md): which config
        # actually produced the winning plan — the stream the
        # KAO_PORTFOLIO_ADAPT table reordering reads (pinned static
        # table when the gate is off)
        arrays.note_portfolio_result(
            port_cfgs[winner_lane] if winner_lane is not None else None
        )

    return SolveResult(
        a=best_a,
        solver="tpu",
        wall_clock_s=time.perf_counter() - t0,
        objective=int(weight),
        optimal=proved_optimal,
        stats={
            "platform": platform,
            "engine": engine,
            "devices": n_dev,
            "chains_per_device": chains_per_device,
            "rounds": rounds,
            "rounds_run": rounds_run,
            "timed_out": timed_out,
            "early_stopped": early_stopped,
            # True when the plan came from the LP-rounding constructor
            # (solvers.lp_round) rather than annealing, and which of
            # its paths built it (aggregated MILP vs exact LP vertex)
            "constructed": constructed,
            "construct_path": (
                getattr(inst, "_construct_path", None)
                if constructed else None
            ),
            # which constructor implementation served this solve
            # (docs/CONSTRUCTOR.md): "vec" by default, "legacy" when
            # the oracle/fallback rung was selected
            "constructor_impl": _constructor.active(),
            # best known lower bound: the LP sharpening when it was
            # (lazily) evaluated, else the counting bound
            "moves_lb": (
                moves_lb
                if getattr(inst, "_move_lb_memo", None) is None
                else inst._move_lb_memo
            ),
            # present only when the lazy LP bound was actually evaluated
            "weight_ub": inst.best_known_weight_ub(),
            # times the exact leader-cap flow tier declined (BIG over
            # int32 arc-cost range) and fell back to the LP — a silent
            # bound-tightness loss at scale unless surfaced here
            "flow_bound_declines": getattr(
                inst, "_flow_big_declines", 0
            ),
            "proved_optimal": proved_optimal,
            # shape bucketing (solvers.tpu.bucket): the canonical padded
            # shape this solve's executables were keyed on (absent on
            # the constructed path, which never lowers the model)
            **({"bucket_parts": int(bkt_parts), "bucket_rf": int(bkt_rf)}
               if bkt_parts is not None else {}),
            "time_limit_s": time_limit_req,
            "steps_per_round": steps_per_round,
            "steps_per_round_ignored": steps_per_round_ignored,
            "scorer": scorer,
            # double-buffered ladder dispatch (docs/PIPELINE.md): True
            # when speculative dispatch actually ran, plus the overlap
            # accounting — boundary host work hidden behind device
            # chunks, and the host-side enqueue vs device-wait split
            "pipeline": lad.pipelined,
            "dispatch_s": round(lad.dispatch_s, 4),
            "device_s": round(lad.device_s, 4),
            "boundary_overlap_s": round(lad.boundary_overlap_s, 4),
            # device dispatches the ladder issued (fused or not) — the
            # megachunk headline metric is this divided by chunks run
            "dispatches": lad.dispatches,
            # fused-ladder provenance (ISSUE 17, docs/PIPELINE.md):
            # resolved width, how it was chosen, group/chunk counts,
            # and whether an on-device certificate retired the scan
            **({"megachunk": {
                "k": lad.mega_k,
                "mode": mega_mode,
                "groups": lad.mega_groups,
                "dispatches": lad.dispatches,
                "chunks": lad.chunks_exec,
                "early_exit": lad.mega_early_exit,
            }} if engine == "sweep" and chunks else {}),
            **({"pallas_fallback": pallas_fallback} if pallas_fallback
               else {}),
            # portfolio provenance (docs/PORTFOLIO.md): the racing
            # geometry, the winning lane and its config, and — when a
            # boundary certificate retired the ladder — the
            # solve-relative time-to-first-certificate
            **({"portfolio": {
                "width": pw,
                "lane_bucket": port_lanes,
                "winner_lane": winner_lane,
                "winner_config": (
                    dataclasses.asdict(port_cfgs[winner_lane])
                    if winner_lane is not None else None
                ),
                # a LANE certificate retired the ladder — a boundary
                # adoption of the constructor's plan is an early stop
                # too, but not a portfolio win, and must not skew the
                # first-to-certify metrics
                "early_exit": (
                    lad.certified_a is not None and not lad.constructed
                ),
                **({"certified_at_s": lad.certified_at_s}
                   if lad.certified_at_s is not None else {}),
            }} if port_lanes else {}),
            # certify-first outcome at final selection (None when a
            # boundary/constructor certificate made it moot): "ok" /
            # "ok_reseat" mean the polish was provably unnecessary and
            # was skipped; anything else names the failed check
            **({"final_cert": final_cert} if final_cert else {}),
            # chain: Metropolis steps per chain; sweep: every sweep
            # proposes one move per partition
            "total_steps": rounds_run * steps_per_round
            if engine == "chain"
            else rounds_run * inst.num_parts,
            # backend client init (seconds over a tunneled TPU) split
            # from the actual greedy-seed work
            "backend_init_s": round(
                (t_backend or t0) - t0, 4
            ),
            "seed_s": round(t_seed - (t_backend or t0), 4),
            "anneal_s": round(t_solve - t_seed, 4),
            "polish_s": round(t_polish - t_solve, 4),
            "seed_moves": int(inst.move_count(a_seed)),
            "moves": moves_final,
            "feasible": feasible,
            "violations": sum(viol.values()),
            "resumed_from_checkpoint": resumed,
            # delta-API warm start (docs/WATCH.md): True when the
            # adapted previous plan actually seeded this solve
            "warm_started": warm_started,
            # best-score trajectory (max over shards, downsampled): the
            # convergence record SURVEY.md §5 calls for
            "score_curve": _downsample(best_curve, 32),
        },
    )


def solve_tpu_batch(*args, **kwargs) -> list[SolveResult]:
    """Batched entry point — see :func:`_solve_tpu_batch_impl` for the
    full contract. Wraps the implementation in the degradation-rung
    collector (resilience.ladder): rungs taken by the SHARED batched
    dispatch apply to every lane, while a lane's own sequential
    fallback (collected lane-scoped inside the impl) lands on that
    lane's ``stats["degradations"]`` only — seven clean lanes must not
    read as degraded because the eighth fell back.

    Flight records: ONE record per lane, kind ``"lane"`` (obs.flight).
    The accounting accumulator is shared by the whole dispatch, so a
    lane record's compile/cache columns describe the batch's one
    dispatch, not the lane alone; the per-lane quality columns are the
    lane's own. The accumulator also suppresses the per-lane
    ``solve_tpu`` records on the unstackable-fallback path — every
    lane lands exactly one record either way.

    ``precompile=True`` (serve's lane warmup, ISSUE 10) marks the batch
    synthetic: like the single path's precompile solves it is never
    flight-recorded — a warmup must not burn SLO budget or skew the
    lane-latency histograms."""
    precompile = bool(kwargs.get("precompile"))
    nested = _flight.accounting_active()
    acc_tok = None if nested else _flight.start_accounting()
    t0 = time.perf_counter()
    insts = args[0] if args else kwargs.get("insts", ())
    try:
        with _ladder.collect() as _rungs:
            results = _solve_tpu_batch_impl(*args, **kwargs)
            for r in results:
                combined = list(_rungs or ()) + r.stats.get(
                    "degradations", [])
                if combined:
                    r.stats["degradations"] = combined
    except BaseException as e:
        acc = (
            _flight.end_accounting(acc_tok) if acc_tok is not None
            else None
        )
        if acc is not None and not precompile:
            # the whole batched dispatch failed: one failure record
            # per lane, same accounting as the success path
            for inst in insts:
                _flight.record_failure(inst, acc,
                                       time.perf_counter() - t0, e,
                                       kind="lane")
        raise
    acc = (
        _flight.end_accounting(acc_tok) if acc_tok is not None
        else None
    )
    if acc is not None and not precompile:
        for inst, r in zip(insts, results):
            _flight.record_solve(r, inst, acc, kind="lane")
    return results


def _solve_tpu_batch_impl(
    insts: list,
    seeds: int | list[int] = 0,
    *,
    engine: str | None = None,
    batch: int | None = None,
    rounds: int | None = None,
    sweeps: int | None = None,
    t_hi: float | None = None,
    t_lo: float | None = None,
    n_devices: int | None = None,
    time_limit_s: float | None = None,
    certify: bool = False,
    trace: bool | str | None = None,
    pipeline: bool | None = None,
    portfolio: bool | int | None = None,
    megachunk: "bool | int | str | None" = None,
    precompile: bool = False,  # consumed by the solve_tpu_batch wrapper
) -> list[SolveResult]:
    """Solve L independent instances in ONE batched device dispatch —
    the multi-tenant throughput path (serve's coalescing dispatcher and
    the bench throughput scenario). Every instance is padded up to one
    COMMON bucket shape (the max of the lanes' bucket rungs) and lowered
    into a lane-stacked model; the vmapped lane solver then anneals all
    L lanes concurrently, chains sharded over the mesh, so the sweep's
    VPU work scales with L at near-constant dispatch depth — the
    measured ~15% HBM / ~4% compute roofline headroom (BENCH_r05) is
    exactly what the extra lanes soak up.

    Deliberately simpler than :func:`solve_tpu`: no host-side
    constructor races, no chunk-boundary certificates, no polish — the
    batch path exists for warm same-bucket throughput, where those
    host-side stages would serialize L times on the critical path.
    Per-lane results ARE exactly verified against the numpy oracle, and
    ``certify=True`` additionally runs the per-lane optimality
    certificate (bound LPs: seconds per lane at scale — bench evidence,
    not a serving default).

    ``seeds`` is one int (lane i gets ``seed + i``) or a per-lane list.
    Instances whose broker/rack axes differ cannot stack (those axes
    are never padded — see ``solvers.tpu.bucket``); such calls fall
    back to sequential :func:`solve_tpu` solves, tagged
    ``stats["lane_fallback"]``.

    ``time_limit_s`` is enforced the same way the single path enforces
    it: the ladder is cut into chunks (``_build_chunks`` — multiples of
    the snapshot cadence, so a chunked sweep run is bit-identical to
    the uncut ladder) and the wall clock is checked between chunks; a
    batch out of budget stops early with ``stats["timed_out"]`` and
    returns the per-lane bests found so far (never worse than each
    lane's seed).

    ``trace`` records ONE span-level solve report for the whole batch
    (obs.trace): every lane's stats carry the shared ``trace_id`` and
    ``solve_report``, and the report registers in the /debug/solves
    ring buffer.

    ``pipeline`` controls the double-buffered ladder dispatch exactly
    as in :func:`solve_tpu` (docs/PIPELINE.md): the sweep engine's
    chunk i+1 is dispatched before chunk i is retired. None defers to
    the process default.

    ``portfolio`` is accepted for option symmetry with
    :func:`solve_tpu` (serve's batchable ``options.portfolio``) but
    the BATCHED dispatch deliberately ignores it: multi-tenant lanes
    already occupy the lane-padded width the portfolio would race —
    the idle roofline is spent either way (docs/PORTFOLIO.md). The
    unstackable sequential fallback honors it per lane."""
    t0 = time.perf_counter()
    pipeline = _PIPELINE_DEFAULT if pipeline is None else bool(pipeline)
    if _san.enabled():
        _san.install()
    if not insts:
        return []
    if isinstance(seeds, int):
        seeds = [seeds + i for i in range(len(insts))]
    if len(seeds) != len(insts):
        raise ValueError(
            f"got {len(seeds)} seeds for {len(insts)} instances"
        )
    L = len(insts)
    axes = {(i.num_brokers, i.num_racks) for i in insts}

    # one trace covers the whole call — batched dispatch or the
    # unstackable sequential fallback alike — so trace=True always
    # honors the documented contract: a shared report/trace_id on
    # every lane's stats
    tr = _otrace.begin(trace, name="solve_tpu_batch", lanes=L)
    try:
        if len(axes) > 1:
            results = []
            for i, (inst, s) in enumerate(zip(insts, seeds)):
                # each sequential solve's pipeline spans nest under a
                # per-lane span, keeping the shared report readable
                with _otrace.span("lane", index=i):
                    # lane-scoped rung collection: THIS lane's
                    # fallbacks must not flag its siblings' stats
                    with _ladder.collect_lane() as lane_rungs:
                        r = solve_tpu(inst, seed=s, engine=engine,
                                      batch=batch, rounds=rounds,
                                      sweeps=sweeps, t_hi=t_hi,
                                      t_lo=t_lo, n_devices=n_devices,
                                      time_limit_s=time_limit_s,
                                      pipeline=pipeline,
                                      portfolio=portfolio,
                                      megachunk=megachunk)
                if lane_rungs:
                    r.stats["degradations"] = list(lane_rungs)
                r.stats["lane_fallback"] = (
                    "brokers/racks differ across lanes"
                )
                results.append(r)
        else:
            from ...parallel.mesh import (
                fetch_global, make_mesh, solve_lanes,
            )
            from ...utils.platform import (
                enable_compile_cache, ensure_backend,
            )
            from . import bucket

            results = _solve_batch_body(
                insts, seeds, engine, batch, rounds, sweeps, t_hi, t_lo,
                n_devices, time_limit_s, certify, t0, L,
                fetch_global, make_mesh, solve_lanes,
                enable_compile_cache, ensure_backend, bucket, pipeline,
                megachunk,
            )
    except BaseException as e:
        if isinstance(e, FloatingPointError):
            _san.note_nan_abort_once(e, "solve_tpu_batch")
        if tr is not None:
            tr.root.set(error=repr(e)[:200])
            _otrace.finish(tr)
        raise
    if tr is not None:
        rep = _otrace.finish(tr)
        for r in results:
            r.stats["trace_id"] = tr.trace_id
            r.stats["solve_report"] = rep
    else:
        tid = _otrace.current_trace_id()
        if tid:
            for r in results:
                r.stats.setdefault("trace_id", tid)
    return results


def _solve_batch_body(
    insts, seeds, engine, batch, rounds, sweeps, t_hi, t_lo, n_devices,
    time_limit_s, certify, t0, L, fetch_global, make_mesh, solve_lanes,
    enable_compile_cache, ensure_backend, bucket, pipeline=True,
    megachunk=None,
) -> list[SolveResult]:
    for inst in insts:
        inst._bounds_cancelled = False
        inst._construct_path = None
        inst._flow_big_declines = 0
    enable_compile_cache()
    platform = ensure_backend()
    # search-effort defaults follow the LARGEST lane (same bucket ⇒ same
    # executable cost); the engine must resolve before the budget knobs
    # mean anything (see _defaults)
    biggest = max(insts, key=lambda i: i.num_parts)
    d = _defaults(biggest, platform, engine)
    engine = d["engine"]
    batch = batch or d["batch"]
    rounds = rounds or sweeps or d["rounds"]
    steps_per_round = d["steps_per_round"]
    if t_hi is None:
        t_hi = 2.0 if engine == "sweep" else 2.5
    if t_lo is None:
        t_lo = 0.02 if engine == "sweep" else 0.05

    # the batch path deliberately runs no bounds prefetch, constructor
    # race, or polish (see the docstring) — the skipped marks keep the
    # span tree's phase vocabulary uniform with the single-solve path
    _otrace.mark("bounds", skipped=True)
    _otrace.mark("constructor", skipped=True)
    _otrace.mark("polish", skipped=True)
    # one COMMON bucket for the whole batch: the max rung over lanes, so
    # every lane's arrays share one padded shape (the stacking invariant)
    bkt_parts = max(bucket.part_bucket(i.num_parts) for i in insts)
    bkt_rf = max(bucket.rf_bucket(i.max_rf) for i in insts)
    B, K = insts[0].num_brokers, insts[0].num_racks
    # lane consolidation (ISSUE 10): pad the batch width up its own
    # ladder rung so ONE lane-padded executable per bucket serves every
    # L in 2..Lmax — previously each distinct L compiled its own
    # executable on first contact. Padded lanes anneal a COPY of lane 0
    # (distinct per-lane RNG keys, so real-lane trajectories are
    # untouched by vmap width — the B=1 bit-parity anchor generalizes)
    # and are inert by masking: selection below iterates the REAL
    # instances only, so a padded lane's results are never read.
    Lp = bucket.lane_bucket(L)
    pad_lanes = Lp - L
    from ...parallel.mesh import note_lane_serve

    note_lane_serve((B, K, bkt_parts, bkt_rf), L, Lp)
    models = []
    lane_seeds = np.empty((Lp, bkt_parts, bkt_rf), np.int32)
    with _otrace.span("seed", lanes=L):
        for i, inst in enumerate(insts):
            bucket.STATS.record_bucket(
                (B, K, bkt_parts, bkt_rf),
                padded=(
                    (bkt_parts, bkt_rf)
                    != (inst.num_parts, inst.max_rf)
                ),
            )
            m = arrays.from_instance(inst, num_parts=bkt_parts,
                                     max_rf=bkt_rf)
            models.append(m)
            # the greedy sub-phase wraps ONLY the repair itself: array
            # packing/padding must not inflate construct_host_s's
            # greedy attribution (sub-phase contract,
            # docs/OBSERVABILITY.md); per-lane spans sum in the roll-up
            with _otrace.span("greedy", lane=i):
                a_seed = np.asarray(greedy_seed(inst), dtype=np.int32)
            assert (
                a_seed[inst.slot_valid] < inst.num_brokers
            ).all(), "seed left unfilled slots"
            lane_seeds[i] = arrays.pad_candidate(a_seed, m)
        for i in range(pad_lanes):
            models.append(models[0])
            lane_seeds[L + i] = lane_seeds[0]
        m_stack = arrays.stack_models(models)
        seed_moves = [int(inst.move_count(arrays.unpad_candidate(
            lane_seeds[i], inst))) for i, inst in enumerate(insts)]

    # the lane dispatches below ride the shared solve mesh: the
    # (chains × lanes) split is the per-bucket chooser's call
    # (docs/MESH.md) — default chains-only until evidence says a lane
    # split wins this bucket; trajectories are split-invariant
    from ...parallel.mesh import make_solve_mesh, mesh_spec

    mesh = make_solve_mesh(
        n_devices, lanes=Lp, bucket_key=(B, K, bkt_parts, bkt_rf),
        engine=engine,
    )
    n_dev = mesh.devices.size
    chains_per_device = max(1, batch // n_dev)
    # padded lanes get derived keys so no two lanes ever consume one
    # stream (their results are discarded either way)
    pad_keys = [
        jax.random.fold_in(jax.random.PRNGKey(seeds[0]), 1 + i)
        for i in range(pad_lanes)
    ]
    keys = jnp.stack(
        [jax.random.PRNGKey(s) for s in seeds] + pad_keys
    )
    scorer = "pallas" if (platform == "tpu" and engine == "sweep") else "xla"

    # chunked ladder + between-chunk clock checks — the same deadline
    # mechanism the single path runs (sweep chunks thread the full lane
    # state, so a chunked schedule is bit-identical to the uncut one;
    # the chain engine reseeds each lane from its best-so-far at the
    # boundary, exactly like the single path's reseed)
    from ...parallel.mesh import (
        fetch_global_async, init_lane_state, solve_lanes_megachunk,
    )

    deadline = Budget(time_limit_s, t0=t0).deadline
    chunks = _build_chunks(biggest, engine, rounds, t_hi, t_lo,
                           time_limit_s)
    n = len(chunks)
    state = None
    cur_seeds, cur_keys = lane_seeds, keys
    handles: list = []  # per-chunk async curve transfers
    rounds_run = 0
    timed_out = False
    pop_a = pop_k = None
    pallas_fallback = None
    pipelined = False
    # warm-chunk estimate: per-solve measurement (chunk 0 and fallback
    # chunks excluded — compile-inclusive) plus the cross-solve prior.
    # The "lanes" tag + the PADDED width keep this key space disjoint
    # from the sequential path's: a slow first batched chunk must never
    # inflate solve_tpu's deadline estimate, and vice versa. Lp (not L)
    # because the executable — and with it the chunk duration — is the
    # padded one: every width sharing a lane bucket shares the estimate.
    warm_chunk_s: float | None = None
    chunk_len = int(chunks[0].shape[0]) if n else 0
    warm_key = ("lanes", Lp, engine, n_dev, chains_per_device,
                steps_per_round, int(bkt_parts), int(bkt_rf))
    # a lane-split mesh changes the per-dispatch cost profile, so its
    # estimates and fusion evidence file under their own identity; the
    # default split keeps the historical key byte-for-byte
    _mesh_dc, _mesh_dl = mesh_spec(mesh)
    if _mesh_dl > 1:
        warm_key = (*warm_key, f"{_mesh_dc}x{_mesh_dl}")

    def _wkey(width: int = 1):
        # width-keyed like the single path's registry: fused and
        # unfused measurements must never cross-feed (regression-pinned)
        return (*warm_key, chunk_len, width, scorer)

    prior_s = _WARM_CHUNKS.get(_wkey())

    # fused megachunk width (ISSUE 17): batch lanes are independent
    # instances, so fused groups always run DISARMED — no shared early
    # exit — and the fusion saves dispatches/host round-trips only
    if engine == "sweep":
        from . import sweep as _sweep_mod

        _mega_sup = getattr(_sweep_mod, "SUPPORTS_MEGACHUNK", False)
    else:
        _mega_sup = False
    mega_k, mega_mode = _resolve_megachunk(
        megachunk, _mega_sup, False, n,
        (*warm_key, chunk_len, scorer),
    )
    mega_warm_s: float | None = None
    mega_prior_s = _WARM_CHUNKS.get(_wkey(mega_k)) if mega_k > 1 else None
    mega_groups = 0
    dispatches = 0
    chunks_exec = 0
    dispatch_s_total = 0.0
    device_s_total = 0.0

    def _mega_est():
        for v in (mega_warm_s, mega_prior_s):
            if v is not None:
                return v
        return None

    def dispatch(ci, st):
        """Enqueue chunk ci (no wait); timed internally so a fallback
        retry times the successful dispatch only. Same chaos points as
        the single path (_chaos_chunk_hooks: host side, never traced)."""
        nonlocal dispatches
        _chaos_chunk_hooks()
        td = time.perf_counter()
        out = solve_lanes(
            m_stack, mesh, chains_per_device, chunks[ci], state=st,
            lane_seeds=cur_seeds, keys=cur_keys, engine=engine,
            steps_per_round=steps_per_round, scorer=scorer,
        )
        dispatches += 1
        if engine == "sweep":
            new_state, pa, pk, cv = out
        else:
            new_state, (pa, pk, cv) = None, out
        return new_state, pa, pk, cv, time.perf_counter() - td

    def _is_lowering(e):
        # scorer is read at CALL time (see the single path's note)
        return _is_pallas_lowering(e, scorer)

    def _note_fb(ci, e):
        nonlocal scorer, pallas_fallback, warm_chunk_s, prior_s
        _ladder.note_rung("pallas_to_xla", chunk=ci)
        pallas_fallback = repr(e)[:500]
        scorer = "xla"
        # restart the warm measurement under the new scorer key (see
        # the single path's _note_fallback)
        warm_chunk_s = None
        prior_s = _WARM_CHUNKS.get(_wkey())
        _olog.warn("pallas_fallback", chunk=ci, error=repr(e)[:200])

    def dispatch_or_fallback(ci, st):
        try:
            return dispatch(ci, st), False
        except Exception as e:
            # execution-time failures have consumed the donated state;
            # only trace/compile-time lowering errors may retry
            if not _is_lowering(e) or not _leaves_alive(st):
                raise
            _note_fb(ci, e)
            return dispatch(ci, st), True

    def retire(ci, pa, pk, cv, disp_s, device_s, chunk_s, fb, sp,
               overlap_s, scorer_ran=None):
        nonlocal pop_a, pop_k, rounds_run, warm_chunk_s
        nonlocal chunks_exec, dispatch_s_total, device_s_total
        pop_a, pop_k = pa, pk
        rounds_run += int(chunks[ci].shape[0])
        chunks_exec += 1
        dispatch_s_total += disp_s
        device_s_total += device_s
        handles.append(fetch_global_async(cv))
        if ci > 0 and not fb:
            warm_chunk_s = (
                chunk_s if warm_chunk_s is None
                else min(warm_chunk_s, chunk_s)
            )
        if sp is not None:
            t_np = np.asarray(chunks[ci])
            sp.set(rounds=int(t_np.shape[0]), t_hi=float(t_np[0]),
                   t_lo=float(t_np[-1]),
                   scorer=scorer if scorer_ran is None else scorer_ran,
                   dispatch_s=round(disp_s, 4),
                   device_s=round(device_s, 4),
                   boundary_overlap_s=round(overlap_s, 4))

    def run_sync(start: int = 0):
        nonlocal state, cur_seeds, cur_keys, timed_out
        for ci in range(start, n):
            if deadline is not None and ci >= 1:
                est = (warm_chunk_s if warm_chunk_s is not None
                       else prior_s)
                if est is not None and (
                    deadline - time.perf_counter() < est * 0.9
                ):
                    timed_out = True
                    return
            tc = time.perf_counter()
            with _otrace.span("chunk", index=ci) as _sp:
                (new_state, pa, pk, cv, disp_s), fb = (
                    dispatch_or_fallback(ci, state)
                )
                tw = time.perf_counter()
                jax.block_until_ready(pa)
                device_s = time.perf_counter() - tw
                _flight.note_device(device_s)
                state = new_state
                retire(ci, pa, pk, cv, disp_s, device_s,
                       time.perf_counter() - tc, fb, _sp, 0.0)
            over = (deadline is not None
                    and time.perf_counter() > deadline)
            if engine != "sweep" and ci + 1 < n and not over:
                # chain boundary reseed: each lane continues from its
                # best shard winner with a fresh per-lane key stream
                # (padded lanes included — their state must keep the
                # stacked shape even though their results are masked)
                pa_np = np.asarray(fetch_global(pop_a))
                pk_np = np.asarray(fetch_global(pop_k))
                top = pk_np.argmax(axis=0)  # [Lp]
                cur_seeds = np.stack(
                    [pa_np[top[i], i] for i in range(Lp)]
                ).astype(np.int32)
                cur_keys = jax.vmap(jax.random.split)(cur_keys)[:, 1]
            if over:
                timed_out = ci + 1 < n
                return

    def run_pipelined(start: int = 0):
        """Sweep lanes, double-buffered: chunk ci+1 enters the device
        queue before chunk ci's results are waited on — same dispatch
        discipline as the single path (docs/PIPELINE.md); the per-lane
        state is donated, so each chunk updates HBM in place. ``start``
        > 0 is the fused walkers' drain re-entry point."""
        nonlocal state, timed_out, pipelined
        pipelined = True
        t_mark = time.perf_counter()
        pending, pend_fb = dispatch_or_fallback(start, state)
        ci = start
        while True:
            new_state, pa, pk, cv, disp_s = pending
            ran_scorer = scorer  # before a speculation failure flips it
            nxt = None
            if ci + 1 < n:
                # outside chunk ci's span — see the single path's
                # run_pipelined for the span-parenting rationale
                try:
                    nxt = dispatch(ci + 1, new_state)
                except Exception as e:
                    if not _is_lowering(e) or not _leaves_alive(
                        new_state
                    ):
                        raise
                    # drain-and-retry: retire chunk ci with nothing
                    # in flight; the XLA retry happens below
                    _note_fb(ci + 1, e)
            with _otrace.span("chunk", index=ci) as _sp:
                tw = time.perf_counter()
                jax.block_until_ready(pa)
                device_s = time.perf_counter() - tw
                _flight.note_device(device_s)
                state = new_state
                now = time.perf_counter()
                retire(ci, pa, pk, cv, disp_s, device_s, now - t_mark,
                       pend_fb, _sp, 0.0, scorer_ran=ran_scorer)
                if nxt is not None and _sp is not None:
                    # the retire's host work (async curve start, span
                    # attrs) ran while chunk ci+1 was on the device
                    _sp.set(boundary_overlap_s=round(
                        time.perf_counter() - now, 4))
                t_mark = now
            if ci + 1 >= n:
                return
            if deadline is not None:
                # pipeline-aware deadline: decide whether to RETIRE
                # the in-flight chunk, not whether to dispatch it
                now = time.perf_counter()
                est = (warm_chunk_s if warm_chunk_s is not None
                       else prior_s)
                if now > deadline or (
                    est is not None and deadline - now < est * 0.9
                ):
                    timed_out = True
                    return
            if nxt is not None:
                pending, pend_fb = nxt, False
            else:
                # drained at a Pallas fallback: synchronous XLA retry,
                # then the pipeline re-enters
                _ladder.note_rung("pipelined_to_sync", chunk=ci + 1)
                pending, _ = dispatch_or_fallback(ci + 1, state)
                pend_fb = True
            ci += 1

    # ------------- fused megachunk walkers (mega_k > 1, sweep) -------------
    # Independent lanes never share an early exit, so batch groups run
    # DISARMED: every group executes all its chunks, no device transfer
    # decides anything, and the fusion saves host round-trips only.

    def dispatch_mega(ci, k, st):
        nonlocal dispatches
        _chaos_chunk_hooks()
        _chaos.raise_if("megachunk_fault")
        td = time.perf_counter()
        group = list(chunks[ci:ci + k])
        active = [True] * k + [False] * (mega_k - k)
        while len(group) < mega_k:
            group.append(group[-1])
        out = solve_lanes_megachunk(
            m_stack, mesh, chains_per_device, jnp.stack(group), st,
            active=np.asarray(active), steps_per_round=steps_per_round,
            scorer=scorer,
        )
        dispatches += 1
        return out, time.perf_counter() - td

    def retire_mega(ci, k, out, disp_s, group_s, sp):
        nonlocal state, pop_a, pop_k, rounds_run, mega_warm_s
        nonlocal mega_groups, chunks_exec, dispatch_s_total
        nonlocal device_s_total
        (new_state, pa, pk, _ca, _cok, _cmv, cv, _ex) = out
        tw = time.perf_counter()
        jax.block_until_ready(pa)
        device_s = time.perf_counter() - tw
        _flight.note_device(device_s)
        state = new_state
        pop_a, pop_k = pa, pk
        mega_groups += 1
        chunks_exec += k
        dispatch_s_total += disp_s
        device_s_total += device_s
        h = fetch_global_async(cv)
        for j in range(k):
            rounds_run += int(chunks[ci + j].shape[0])
            handles.append(_CurveSlice(h, j, 2))  # [n_dev, L, K, c]
        per_chunk = group_s / max(1, k)
        if ci > 0 and k == mega_k:
            mega_warm_s = (
                per_chunk if mega_warm_s is None
                else min(mega_warm_s, per_chunk)
            )
        if sp is not None:
            sp.set(width=k, dispatch_s=round(disp_s, 4),
                   device_s=round(device_s, 4))

    def _mega_degradable(e) -> bool:
        return _degradable(e) or _is_lowering(e)

    def _drain(ci, e) -> None:
        """megachunk_to_chunked: re-enter the per-chunk batch ladder at
        the first chunk no fused group finished."""
        _ladder.note_rung("megachunk_to_chunked", chunk=ci,
                          error=repr(e)[:200])
        if pipeline:
            run_pipelined(start=ci)
        else:
            run_sync(start=ci)

    def run_mega_sync():
        nonlocal timed_out
        ci = 0
        while ci < n:
            k = min(mega_k, n - ci)
            if deadline is not None and ci >= 1:
                est = _mega_est()
                if est is not None and (
                    deadline - time.perf_counter() < est * k * 0.9
                ):
                    timed_out = True
                    return
            with _otrace.span("megachunk", index=mega_groups,
                              first_chunk=ci, width=k) as _sp:
                tg = time.perf_counter()
                try:
                    out, disp_s = dispatch_mega(ci, k, state)
                    retire_mega(ci, k, out, disp_s,
                                time.perf_counter() - tg, _sp)
                except Exception as e:
                    if (not _mega_degradable(e)
                            or not _leaves_alive(state)):
                        raise
                    _drain(ci, e)
                    return
            if deadline is not None and time.perf_counter() > deadline:
                timed_out = ci + k < n
                return
            ci += k

    def run_mega_pipelined():
        nonlocal timed_out, pipelined
        pipelined = True
        t_mark = time.perf_counter()
        ci, k = 0, min(mega_k, n)
        try:
            pending = dispatch_mega(ci, k, state)
        except Exception as e:
            if not _mega_degradable(e) or not _leaves_alive(state):
                raise
            _drain(ci, e)
            return
        while True:
            out, disp_s = pending
            new_state = out[0]
            cj, k_next = ci + k, min(mega_k, n - ci - k)
            nxt, drain_exc = None, None
            if k_next > 0:
                try:
                    nxt = dispatch_mega(cj, k_next, new_state)
                except Exception as e:
                    if (not _mega_degradable(e)
                            or not _leaves_alive(new_state)):
                        raise
                    drain_exc = e
            with _otrace.span("megachunk", index=mega_groups,
                              first_chunk=ci, width=k) as _sp:
                retire_mega(ci, k, out, disp_s,
                            time.perf_counter() - t_mark, _sp)
                t_mark = time.perf_counter()
            if k_next <= 0:
                return
            if deadline is not None:
                nowd = time.perf_counter()
                est = _mega_est()
                if nowd > deadline or (
                    est is not None
                    and deadline - nowd < est * k_next * 0.9
                ):
                    timed_out = True
                    return
            if drain_exc is not None:
                _drain(cj, drain_exc)
                return
            pending = nxt
            ci, k = cj, k_next

    marks0 = _flight.ledger_marks()
    with _otrace.span("ladder", engine=engine,
                      chunks=len(chunks)) as _lsp:
        if engine == "sweep" and mega_k > 1 and n > 1:
            if state is None:
                # the per-chunk path lets solve_lanes build this from
                # (lane_seeds, keys) on first dispatch; the fused
                # dispatchers take state only — same init, same values
                state = init_lane_state(
                    m_stack, cur_seeds, cur_keys, mesh,
                    chains_per_device,
                )
            if pipeline:
                run_mega_pipelined()
            else:
                run_mega_sync()
        elif pipeline and engine == "sweep" and n > 1:
            run_pipelined()
        else:
            run_sync()
        if _lsp is not None:
            _lsp.set(rounds_run=rounds_run, timed_out=timed_out,
                     scorer=scorer, pipelined=pipelined,
                     dispatches=dispatches, megachunk_k=mega_k)
    if timed_out:
        _ladder.note_rung("deadline_truncated", rounds_run=rounds_run)
    if warm_chunk_s is not None:
        _WARM_CHUNKS.update(_wkey(), warm_chunk_s)
    if mega_warm_s is not None:
        _WARM_CHUNKS.update(_wkey(mega_k), mega_warm_s)
    if engine == "sweep" and dispatches:
        # one accounting funnel (see the single path): the evidence
        # table eats the ledger's own dispatch/device leaves differenced
        # around the batch ladder, falling back to the ladder tallies
        # when accounting is inactive
        marks1 = _flight.ledger_marks()
        ev_n = marks1["dispatches"] - marks0["dispatches"]
        if ev_n > 0:
            ev_disp = marks1["dispatch_s"] - marks0["dispatch_s"]
            ev_dev = marks1["device_s"] - marks0["device_s"]
        else:
            ev_n, ev_disp, ev_dev = (
                dispatches, dispatch_s_total, device_s_total
            )
        note_megachunk_evidence(
            (*warm_key, chunk_len, scorer),
            dispatches=ev_n, dispatch_s=ev_disp,
            chunks=chunks_exec, device_s=ev_dev,
        )
        # sharding evidence rides the same funnel: production batches
        # keep the table honest about the split they actually ran
        from ...parallel.mesh import note_sharding_evidence

        note_sharding_evidence(
            (B, K, bkt_parts, bkt_rf), (_mesh_dc, _mesh_dl),
            lanes=Lp, solves=ev_n, device_s=ev_dev,
        )
    t_solve = time.perf_counter()

    # per-lane final selection on the host: rank each lane's per-shard
    # winners under the solve's lexicographic objective via the exact
    # numpy oracle (n_dev candidates per lane, a few hundred KB total)
    pa = np.asarray(fetch_global(pop_a))  # [n_dev, L, P, R]
    curve_np = np.concatenate(
        [np.asarray(h.get()) for h in handles], axis=2
    )  # [n_dev, L, rounds_run]
    wall = time.perf_counter() - t0
    with _otrace.span("verify", lanes=L) as _vsp:
        results = _select_lanes(
            insts, pa, curve_np, n_dev, certify, wall, t_solve, t0,
            platform, engine, L, chains_per_device, rounds, rounds_run,
            timed_out, bkt_parts, bkt_rf, scorer, pallas_fallback,
            time_limit_s, seed_moves, pipelined, lane_bucket=Lp,
            dispatches=dispatches,
            mega={"k": mega_k, "mode": mega_mode, "groups": mega_groups,
                  "dispatches": dispatches, "chunks": chunks_exec,
                  "early_exit": False} if engine == "sweep" and n
            else None,
        )
        if _vsp is not None:
            _vsp.set(lanes_feasible=sum(
                1 for r in results if r.stats["feasible"]))
    return results


def _select_lanes(
    insts, pa, curve_np, n_dev, certify, wall, t_solve, t0, platform,
    engine, L, chains_per_device, rounds, rounds_run, timed_out,
    bkt_parts, bkt_rf, scorer, pallas_fallback, time_limit_s, seed_moves,
    pipelined=False, lane_bucket=None, dispatches=None, mega=None,
) -> list[SolveResult]:
    """Per-lane final selection + oracle verification (the batch path's
    "verify" phase body). Iterates the REAL instances only — this loop
    IS the inert-lane mask: a lane-padded dispatch's padding lanes
    (indices >= len(insts)) are simply never read."""
    results = []
    for i, inst in enumerate(insts):
        best_a = None
        best_rank = None
        for dev in range(n_dev):
            cand = arrays.unpad_candidate(pa[dev, i], inst)
            pen = sum(inst.violations(cand).values())
            r = (pen == 0, -pen, inst.preservation_weight(cand),
                 -inst.move_count(cand))
            if best_rank is None or r > best_rank:
                best_rank, best_a = r, cand
        viol = inst.violations(best_a)
        weight = inst.preservation_weight(best_a)
        feasible = all(v == 0 for v in viol.values())
        proved = bool(certify and feasible and inst.certify_optimal(best_a))
        results.append(SolveResult(
            a=best_a,
            solver="tpu",
            wall_clock_s=wall,
            objective=int(weight),
            optimal=proved,
            stats={
                "platform": platform,
                "engine": engine,
                "lanes": L,
                "lane": i,
                # padded dispatch width (lane consolidation, ISSUE 10):
                # the executable that served this batch was compiled
                # for lane_bucket lanes, shared by every L it covers
                **({"lane_bucket": int(lane_bucket)}
                   if lane_bucket is not None else {}),
                "devices": n_dev,
                "chains_per_device": chains_per_device,
                "rounds": rounds,
                "rounds_run": rounds_run,
                "timed_out": timed_out,
                "bucket_parts": int(bkt_parts),
                "bucket_rf": int(bkt_rf),
                "scorer": scorer,
                "pipeline": pipelined,
                # shared-dispatch accounting: the batch's ONE ladder
                # served every lane, so these columns describe the
                # batch dispatch, not this lane alone
                **({"dispatches": int(dispatches)}
                   if dispatches is not None else {}),
                **({"megachunk": dict(mega)} if mega else {}),
                **({"pallas_fallback": pallas_fallback}
                   if pallas_fallback else {}),
                "proved_optimal": proved,
                "time_limit_s": time_limit_s,
                "seed_moves": seed_moves[i],
                "moves": int(inst.move_count(best_a)),
                "feasible": feasible,
                "violations": sum(viol.values()),
                "anneal_s": round(t_solve - t0, 4),
                "batch_wall_s": round(wall, 4),
                "score_curve": _downsample(curve_np[:, i].max(axis=0), 32),
            },
        ))
    return results


def _downsample(x: np.ndarray, n: int) -> list[int]:
    if len(x) <= n:
        return [int(v) for v in x]
    idx = np.linspace(0, len(x) - 1, n).round().astype(int)
    return [int(x[i]) for i in idx]
