"""Swappable constructor implementation registry (ISSUE 10).

The host-bound constructor path — greedy placement (``seed.py``), the
aggregated-MILP disaggregation (``solvers.lp_round``), and their shared
repair machinery — exists in two implementations:

- ``"vec"`` (the default): the per-partition Python loops rewritten as
  vectorized numpy over the same padded arrays the annealer uses
  (docs/CONSTRUCTOR.md). This is the production path.
- ``"legacy"``: the original per-partition Python implementation, kept
  verbatim as the ORACLE — ``tests/test_constructor_vec.py`` pins the
  vectorized path against it plan-for-plan (or rank-for-rank where the
  algorithms legitimately tie-break differently), and it remains the
  operator's fallback rung when a vectorization bug ships
  (``KAO_CONSTRUCTOR=legacy``, no redeploy needed).

The registry is deliberately tiny and dependency-free: ``seed.py`` and
``lp_round.py`` consult :func:`active` at call time, and the engine
re-exports :func:`set_impl` so tests and the serve layer can flip the
implementation per process. The env var is read once at import; the
setter wins afterwards.
"""

from __future__ import annotations

import os

IMPLS = ("vec", "legacy")

_DEFAULT = os.environ.get("KAO_CONSTRUCTOR", "vec").strip().lower()
if _DEFAULT not in IMPLS:
    # a typo'd override must not SILENTLY select an implementation the
    # operator did not ask for: the whole point of the env var is the
    # no-redeploy fallback rung, and a misspelled "legacy" quietly
    # running "vec" would defeat it. Same loud-decline convention as
    # the chaos spec parser (docs/RESILIENCE.md) — logged, then the
    # default proceeds (raising here would brick every entry point on
    # an env typo).
    from ...obs import log as _olog

    _olog.warn("kao_constructor_invalid",
               value=os.environ.get("KAO_CONSTRUCTOR", ""),
               expected="|".join(IMPLS), using="vec")
    _DEFAULT = "vec"

_ACTIVE = _DEFAULT


def active() -> str:
    """The currently selected constructor implementation name."""
    return _ACTIVE


def set_impl(name: str) -> str:
    """Select the constructor implementation process-wide. Returns the
    previous value so tests can restore it."""
    global _ACTIVE
    if name not in IMPLS:
        raise ValueError(
            f"unknown constructor impl {name!r}; expected one of {IMPLS}"
        )
    prev = _ACTIVE
    _ACTIVE = name
    return prev


def use_vectorized() -> bool:
    return _ACTIVE == "vec"
