"""Shape bucketing: canonical padded shapes + cache counters.

Every jitted stage executable (sweep stepper, chain solver, polish,
scorers) is keyed on the shapes of its arguments, and the dominant shape
axis is the partition count: real fleets hold a stable broker/rack
topology while topics — and with them partition counts — churn
constantly. Without bucketing, every distinct (partitions, max-RF) pair
pays a full XLA compile on first contact (BENCH_r05: 26-68 s cold vs
4-9 s warm on the adversarial rows); with it, instance arrays are padded
up a small geometric ladder of canonical partition counts so every
instance inside a bucket reuses one set of executables.

Padded rows are inert by construction (``arrays.from_instance``): rf=0,
slot_valid false, zero weights, zero diversity caps — both engines'
proposal machinery rejects or no-ops moves on them, so the padded solve
explores exactly the real instance's search space and the returned plan
is sliced back to the real shape before any host-side oracle sees it.

The broker and rack axes are deliberately NOT padded: their band
penalties are global scalars (a padded broker at count 0 would violate
``broker_lo`` and poison feasibility), they are stable per fleet, and
the Mosaic kernels bake them into tile layouts. The bucket key is
therefore (brokers, racks, rf-bucket, partition-bucket) with the first
two exact.

Config:

- ``KAO_BUCKETS=off``           disable bucketing (raw shapes).
- ``KAO_BUCKETS=64,1024,16384`` override the partition ladder with an
  explicit comma list (values are sorted; instances above the largest
  rung fall back to their raw partition count).

Counters feed ``serve.py``'s ``/metrics`` + ``/healthz`` and the bench
JSON; they are process-wide and thread-safe.
"""

from __future__ import annotations

import os
import threading

# partition ladder: geometric ("power-of-two-ish") from 32 up, rounded
# to sublane multiples of 8 so padded tiles stay aligned. Growth is
# graduated — 1.5x while buckets are small (padding a 90-partition
# cluster to 112 costs microseconds), 1.25x from 1024 up (at-scale
# sweeps pay per-partition work on padded rows, so the worst-case
# padding overhead is capped at ~25% where it matters), and 256-aligned
# above 4096 (the Pallas scoring kernel's partition tile is 256). ~40
# rungs cover 32 .. >1M partitions — a long-lived service compiles a
# handful of them for any real traffic mix.
_LADDER_BASE = 32
_LADDER_GROWTH_SMALL = 1.5
_LADDER_GROWTH_BIG = 1.25
_LADDER_BIG_AT = 1024
_LADDER_ALIGN = 8
_LADDER_TILE_AT = 4096
_LADDER_TILE = 256

# max-RF ladder: RF is tiny and coarse in practice; one rung per common
# value, then multiples of 4. Padded slots are ordinary invalid slots.
_RF_LADDER = (1, 2, 3, 4, 5, 6, 8)

# lane ladder (ISSUE 10): batched multi-instance solves pad their lane
# count up to a rung so ONE lane-padded executable per bucket serves
# every batch width L in 2..Lmax — without it, each distinct L compiled
# its own executable on first contact (the per-(bucket, lane-count)
# executable zoo) and burned an exec-cache slot per width. Rung 1 is
# deliberate: the B=1 path stays raw so single-lane dispatches (and the
# bench's sequential-baseline arm) never pay padded-lane device work.
# Padded lanes are inert by masking at selection: they anneal a copy of
# lane 0's instance and their results are never read.
#
# ``KAO_LANE_BUCKETS=off`` disables (raw lane counts);
# ``KAO_LANE_BUCKETS=2,4,8`` overrides the rung list. Batches above the
# top rung stay raw, exactly like the partition ladder.
_LANE_LADDER = (1, 8)


def _round_up(v: int, align: int) -> int:
    return -(-int(v) // align) * align


def enabled() -> bool:
    return os.environ.get("KAO_BUCKETS", "").lower() not in (
        "off", "0", "none", "false",
    )


def _custom_ladder() -> list[int] | None:
    """Explicit partition ladder from ``KAO_BUCKETS``, or None."""
    raw = os.environ.get("KAO_BUCKETS", "")
    if not raw or raw.lower() in ("on", "1", "true"):
        return None
    try:
        rungs = sorted({int(x) for x in raw.split(",") if x.strip()})
    except ValueError:
        return None  # malformed override: fall back to the default ladder
    return rungs or None


def _next_rung(v: int) -> int:
    growth = (
        _LADDER_GROWTH_SMALL if v < _LADDER_BIG_AT else _LADDER_GROWTH_BIG
    )
    align = _LADDER_TILE if v >= _LADDER_TILE_AT else _LADDER_ALIGN
    return _round_up(v * growth, align)


def part_bucket(num_parts: int) -> int:
    """Smallest ladder rung >= num_parts (identity when bucketing is
    disabled; instances above a custom ladder's top rung stay raw)."""
    p = int(num_parts)
    if not enabled():
        return p
    custom = _custom_ladder()
    if custom is not None:
        for rung in custom:
            if rung >= p:
                return rung
        return p
    v = _LADDER_BASE
    while v < p:
        v = _next_rung(v)
    return v


def _lane_ladder() -> tuple[int, ...] | None:
    """The active lane ladder, or None when lane padding is off."""
    raw = os.environ.get("KAO_LANE_BUCKETS", "")
    if raw.lower() in ("off", "0", "none", "false"):
        return None
    if not raw or raw.lower() in ("on", "1", "true"):
        return _LANE_LADDER
    try:
        rungs = sorted({int(x) for x in raw.split(",") if x.strip()})
    except ValueError:
        return _LANE_LADDER  # malformed override: default ladder
    return tuple(rungs) or _LANE_LADDER


def lane_bucket(lanes: int) -> int:
    """Smallest lane-ladder rung >= lanes (identity when lane padding
    is disabled, or above the top rung)."""
    n = int(lanes)
    ladder = _lane_ladder()
    if ladder is None:
        return n
    for rung in ladder:
        if rung >= n:
            return rung
    return n


def lane_ladder() -> list[int]:
    """The ACTIVE lane ladder rungs (for /healthz and docs); empty when
    lane padding is disabled."""
    ladder = _lane_ladder()
    return [] if ladder is None else list(ladder)


def rf_bucket(max_rf: int) -> int:
    r = int(max_rf)
    if not enabled():
        return r
    for rung in _RF_LADDER:
        if rung >= r:
            return rung
    return _round_up(r, 4)


def ladder(n: int = 16) -> list[int]:
    """The first ``n`` rungs of the active partition ladder (for
    /healthz and docs)."""
    custom = _custom_ladder()
    if custom is not None:
        return custom[:n]
    out, v = [], _LADDER_BASE
    for _ in range(n):
        out.append(v)
        v = _next_rung(v)
    return out


def bucket_shape(inst) -> tuple[int, int]:
    """(partition-bucket, rf-bucket) for a ProblemInstance."""
    return part_bucket(inst.num_parts), rf_bucket(inst.max_rf)


class CacheStats:
    """Process-wide cache counters: bucket reuse (instance shape ->
    bucket already seen), executable-cache hits/misses, and compile
    wall-clock. One instance (``STATS``) is shared by the engine, the
    mesh executable cache, the HTTP service, and the bench harness."""

    def __init__(self):
        self._lock = threading.Lock()
        self._seen_buckets: set = set()
        self._c = {
            "bucket_hits": 0,        # solve mapped to an already-seen bucket
            "bucket_misses": 0,      # first solve for this bucket key
            "padded_solves": 0,      # solves whose arrays were padded
            "exec_hits": 0,          # executable served from the LRU
            "exec_misses": 0,        # executable had to be built
            "compiles_total": 0,     # XLA compiles actually performed
            "compile_seconds_total": 0.0,
            "exec_fallbacks": 0,     # AOT path failed; jit dispatch used
        }

    def record_bucket(self, key: tuple, padded: bool) -> bool:
        """Record one solve's bucket key; returns True on a bucket hit."""
        with self._lock:
            hit = key in self._seen_buckets
            self._seen_buckets.add(key)
            self._c["bucket_hits" if hit else "bucket_misses"] += 1
            if padded:
                self._c["padded_solves"] += 1
        return hit

    def record_exec(self, hit: bool, compile_s: float = 0.0,
                    fallback: bool = False) -> None:
        with self._lock:
            self._c["exec_hits" if hit else "exec_misses"] += 1
            if not hit and not fallback:
                self._c["compiles_total"] += 1
                self._c["compile_seconds_total"] += float(compile_s)
            if fallback:
                self._c["exec_fallbacks"] += 1

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self._c)
            out["buckets_seen"] = len(self._seen_buckets)
        out["compile_seconds_total"] = round(
            out["compile_seconds_total"], 4
        )
        return out

    def seen(self) -> list:
        """The bucket keys this process has solved — the affinity
        ledger the fleet router reads from /healthz "cache" (a seen
        bucket's executables are warm in-process, modulo the periodic
        maintenance cache clear, which the next solve re-warms from
        the persistent disk cache)."""
        with self._lock:
            return sorted(
                list(k) for k in self._seen_buckets
                if isinstance(k, tuple)
            )


STATS = CacheStats()
