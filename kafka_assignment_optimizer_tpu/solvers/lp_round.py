"""LP-rounding plan constructor — decode the kept-replica LP's vertex
into an actual reassignment plan.

The level-2 weight bound (``ProblemInstance._kept_weight_lp``) is a
transportation-structured LP whose optimum is (almost always) an
INTEGRAL vertex: x/y say exactly which current members stay and in which
role, z says how many new replicas each broker absorbs, u how many
leaderships land on non-kept leaders. When the caps genuinely bind —
scale-outs over-filling old brokers, leader-skew rebalances — local
search burns its whole ladder approaching that structure from below;
this module instead materializes it directly:

1. round x/y/z (bail to None on a fractional vertex),
2. place the kept members,
3. complete the vacant slots with new replicas via one max-flow
   (partitions -> (partition, rack) diversity nodes -> brokers with
   z-quota), so every band and diversity cap holds by construction,
4. reseat leaders exactly (``best_leader_assignment``).

If the result is feasible and meets the weight bound it IS a proven
global optimum and the engine can skip annealing entirely; otherwise it
still seeds the population at (or near) the LP structure. Returns None
whenever any step cannot complete — callers always have the greedy seed
to fall back on.

No counterpart in the reference (its lp_solve run IS the exact solve,
``/root/reference/README.md:135-137``); this is the TPU build's bridge
between the search engine and exact optimality.
"""

from __future__ import annotations

import numpy as np

from ..models import instance as _instance_mod
from ..models.instance import ProblemInstance


def construct(inst: ProblemInstance) -> np.ndarray | None:
    """Decode the kept-replica LP into a full plan, or None.

    Past ~60k members the unaggregated LP is intractable; the
    symmetry-aggregated MILP (``_kept_weight_agg``) is solved instead
    and its per-class kept counts are realized into per-partition
    choices (``_disaggregate``) — partitions within a class are
    exchangeable, so any realization of the counts is optimal. The
    aggregated path also serves any instance whose symmetry is
    effective (``agg_effective``): on the 10k-partition headline it
    builds the certified optimum in ~2 s with no compilation, which is
    what keeps a cold process inside the 5 s budget.

    The aggregated realization (greedy disaggregation + flow
    completion) could historically be LOSSY on instances with binding
    caps (r4 observed -14 weight on the 8k-partition scale-out with
    the blind completion), so the caps-bind family used to solve the
    full unaggregated LP first — 2+ s of HiGHS at 8k partitions where
    the aggregated MILP takes ~0.2 s. The leader-aware MCMF completion
    has since made the aggregated realization lossless on the whole
    caps-bind benchmark family (scale_out, leader_only: weight == the
    recorded bound, verified each run by the lossless check below), so
    symmetry-effective instances now try the CHEAP aggregated path
    first even when caps bind (ISSUE 10 — this is most of the
    scale-out/leader-only cold-path win); a lossy realization still
    falls through to the exact LP vertex exactly as before, so the r4
    failure mode costs one cheap MILP attempt, never quality. Only
    caps-bind instances WITHOUT effective symmetry keep the
    exact-vertex-first order."""
    members = inst._members()[0].size
    big = members > _instance_mod.AGG_MEMBER_THRESHOLD
    lp_first = not big and inst.caps_bind() and not inst.agg_effective()
    plan_lp = plan_agg = None
    if lp_first:
        plan_lp, vertex_w = _unagg_plan(inst, with_weight=True)
        if plan_lp is not None and (
            vertex_w is None
            or inst.preservation_weight(plan_lp) >= vertex_w
        ):
            inst._construct_path = "lp"
            return plan_lp  # realized the vertex losslessly: optimal
        # lossy realization (e.g. the blind max-flow completion when
        # the MCMF kernel is unavailable): let the aggregated path
        # compete below instead of short-circuiting past it
    if big or inst.agg_effective():
        try:
            agg = inst._kept_weight_agg(integer=True,
                                        return_solution=True)
        except Exception:
            agg = None
        d = _disaggregate(inst, agg) if isinstance(agg, dict) else None
        if d is not None:
            plan_agg = _realize(
                inst, d["x"], d["y"], agg["z"].astype(np.int64),
                d["mrows"], d["mcols"],
            )
            ub = getattr(inst, "_agg_weight_ub", None)
            if (
                plan_agg is not None
                and ub is not None
                and inst.preservation_weight(plan_agg) >= ub
            ):
                inst._construct_path = "agg"
                return plan_agg  # lossless realization: weight-optimal
        if big:
            if plan_agg is not None:
                inst._construct_path = "agg"
            return plan_agg  # nothing cheaper exists past the threshold
    if not lp_first:
        plan_lp, _ = _unagg_plan(inst, with_weight=True)
    if plan_agg is None:
        if plan_lp is not None:
            inst._construct_path = "lp"
        return plan_lp
    if plan_lp is None:
        inst._construct_path = "agg"
        return plan_agg
    best = max(
        (plan_lp, plan_agg),
        key=lambda p: (inst.preservation_weight(p),
                       -inst.move_count(p)),
    )
    inst._construct_path = "agg" if best is plan_agg else "lp"
    return best


def _unagg_plan(inst: ProblemInstance, with_weight: bool = False):
    """The exact-vertex path: solve the unaggregated kept-replica LP
    and realize its integral vertex (None on fractional vertices or
    any realization failure). With ``with_weight`` returns
    ``(plan, vertex_weight)`` — the kept weight the vertex itself
    attains, so callers can tell a lossless realization from a
    degraded one (completion fallbacks can demote kept leaders)."""
    empty = (None, None) if with_weight else None
    try:
        sol = inst._kept_weight_lp(return_solution=True)
    except Exception:
        return empty
    if not isinstance(sol, dict):
        return empty
    x, y = np.asarray(sol["x"]), np.asarray(sol["y"])
    z = np.asarray(sol["z"])

    # integral vertex required: kept roles and new-replica quotas must
    # be whole (transportation structure makes this the common case)
    if (
        np.abs(x - np.rint(x)).max(initial=0) > 1e-6
        or np.abs(y - np.rint(y)).max(initial=0) > 1e-6
        or np.abs(z - np.rint(z)).max(initial=0) > 1e-6
    ):
        return empty
    xi = np.rint(x).astype(bool)
    yi = np.rint(y).astype(bool)
    mrows, mcols = sol["mrows"], sol["mcols"]
    wl = inst.w_leader[mrows, mcols]
    wf = np.maximum(inst.w_follower[mrows, mcols], 0)
    vertex_w = int((wf * xi).sum() + (wl * yi).sum())
    # the weight part of the lexicographic LP optimum is a valid upper
    # bound on ANY feasible plan's weight (every plan maps into the
    # polytope and scale > any kept count — the same argument, and the
    # same recording convention, as the aggregated MILP's
    # ``_agg_weight_ub`` in models.bounds._kept_weight_agg). Recording
    # it lets certify_optimal skip the bound-ladder LPs entirely for a
    # losslessly realized vertex — previously the scale-out /
    # leader-only certify path re-solved the SAME kept-replica LP a
    # second time just to restate this number (ISSUE 10, the duplicated
    # multi-second LP on the construct critical path). Min-merged: both
    # recorders hold valid bounds, so the tighter one wins.
    prev = getattr(inst, "_agg_weight_ub", None)
    inst._agg_weight_ub = (
        vertex_w if prev is None else min(prev, vertex_w)
    )
    plan = _realize(
        inst, xi, yi, np.rint(z).astype(np.int64),
        mrows, mcols,
    )
    if not with_weight:
        return plan
    return plan, vertex_w


def _realize(inst, xi, yi, quota, mrows, mcols) -> np.ndarray | None:
    """Place the kept roles, complete the vacancies, reseat leaders —
    the shared tail of both construct paths. Returns a feasible plan
    or None."""
    P, R = inst.num_parts, inst.max_rf
    B, K = inst.num_brokers, inst.num_racks
    rf = inst.rf.astype(np.int64)
    valid = inst.slot_valid

    # place kept members sequentially per partition — slot ORDER is
    # irrelevant here because the final exact leader reseat permutes
    # each row anyway
    keep = xi | yi
    kr, kb = mrows[keep], mcols[keep]
    order = np.argsort(kr, kind="stable")
    kr, kb = kr[order], kb[order]
    first = np.r_[True, kr[1:] != kr[:-1]] if kr.size else np.array([], bool)
    start = np.maximum.accumulate(
        np.where(first, np.arange(kr.size), 0)
    ) if kr.size else kr
    rank = np.arange(kr.size) - start
    if kr.size and (rank >= rf[kr]).any():
        return None  # vertex kept more slots than the partition has
    a = np.full((P, R), B, dtype=np.int64)
    a[kr, rank] = kb

    kept_cnt = (a != B).sum(axis=1)
    vac = rf - kept_cnt  # >= 0: the rank check above caps keeps at rf
    need = int(vac.sum())
    if need != int(quota.sum()):
        return None
    if need > 0:
        # leader-aware completion first: partitions left without a kept
        # leader must receive one of their new replicas on a broker
        # with leadership headroom, or the final exact reseat is forced
        # to demote kept leaders elsewhere (observed: -67 weight on the
        # 50k-partition jumbo with the blind completion). Min-cost
        # max-flow places every vacancy AND maximizes lead-capable
        # coverage jointly; the plain max-flow remains the fallback
        # when the native kernel is unavailable.
        has_lead = np.zeros(P, dtype=bool)
        has_lead[mrows[yi]] = True
        leaderless = (~has_lead) & (inst.rf > 0)
        lead_cnt = np.bincount(mcols[yi], minlength=B + 1)[:B]
        lead_quota = np.maximum(inst.leader_hi - lead_cnt, 0)
        assign = None
        if leaderless.any():
            assign = _complete_mcmf(
                inst, a, vac, leaderless, lead_quota
            )
        if assign is None:
            flow = _complete_maxflow(inst, a, vac, quota)
            if flow is not None:
                ap, ab = flow
                assign = (ap, ab, np.zeros(ap.size, dtype=bool))
        if assign is None:
            return None
        # vectorized vacancy fill (ISSUE 10): the per-assignment Python
        # loop re-scanned each row for its first vacant slot — O(need)
        # interpreter iterations on the jumbo completion. Identical
        # result by construction: assignments grouped per partition in
        # list order (stable sort) land on that partition's vacant
        # slots in ascending slot order, exactly the order the
        # one-at-a-time ``vac_slots[0]`` loop produced.
        ap, ab, alead = assign
        ordr = np.argsort(ap, kind="stable")
        ap_s, ab_s = ap[ordr], ab[ordr]
        first = np.r_[True, ap_s[1:] != ap_s[:-1]] if ap_s.size else \
            np.array([], bool)
        start = (
            np.maximum.accumulate(
                np.where(first, np.arange(ap_s.size), 0)
            ) if ap_s.size else ap_s
        )
        rank = np.arange(ap_s.size) - start
        vr, vc = np.nonzero((a == B) & valid)  # row-major: slots ascend
        v_start = np.searchsorted(vr, ap_s)
        pos = v_start + rank
        if pos.size and (
            (pos >= vr.size) | (vr[np.minimum(pos, vr.size - 1)] != ap_s)
        ).any():
            return None  # more placements than vacancies on some row
        a[ap_s, vc[pos] if pos.size else pos] = ab_s
    else:
        assign = (
            np.zeros(0, np.int64), np.zeros(0, np.int64),
            np.zeros(0, dtype=bool),
        )
    if ((a == B) & valid).any():
        return None

    # pre-seat slot 0 before the exact reseat: the kept leaders (y —
    # the LP/MILP's own leader choice, in-band by its leader rows) plus
    # the completion's lead-channel placements. Slot order was
    # arbitrary up to here, so without this the reseat sees random
    # leader counts, its fast cycle-canceller declines (out-of-band
    # input), and every constructed solve pays the full transportation
    # LP instead — measured 3.9 s of the jumbo's 16 s wall (r4).
    ap, ab, alead = assign
    lead_b_of = np.full(P, -1, dtype=np.int64)
    # duplicate partitions keep the LAST entry, matching the loop the
    # scatter replaces (numpy fancy assignment is last-wins)
    lead_b_of[ap[alead]] = ab[alead]
    lead_b_of[mrows[yi]] = mcols[yi]  # kept leaders win over coverage
    prows = np.flatnonzero(lead_b_of >= 0)
    if prows.size:
        hit = a[prows] == lead_b_of[prows, None]
        s0 = hit.argmax(axis=1)
        ok = hit[np.arange(prows.size), s0]
        prows, s0 = prows[ok], s0[ok]
        lead_vals = a[prows, s0].copy()
        a[prows, s0] = a[prows, 0]
        a[prows, 0] = lead_vals

    a = a.astype(np.int32)
    a = inst.best_leader_assignment(a)
    if not inst.is_feasible(a):
        return None
    return a


def _disaggregate(inst, agg):
    """Realize the aggregated MILP's per-(class, member) kept counts as
    per-partition selections.

    Partitions within a class are exchangeable (identical members,
    weights, rf, caps), so ANY realization of the counts has the same
    objective; the greedy spreads each member's remaining demand
    most-constrained-first, giving at most one leader per partition and
    respecting the per-rack diversity cap. The aggregate rows guarantee
    per-partition feasibility on average; the greedy can in principle
    strand demand on adversarial instances — the caller verifies the
    final plan and falls back, so a stranded realization costs nothing
    but the attempt (it returns the partial keeps, still a valid warm
    start).

    Dispatches on the swappable constructor implementation
    (``solvers.tpu.constructor``, docs/CONSTRUCTOR.md): the vectorized
    default realizes each class with array ops (~0.85 s of per-
    partition Python at the 50k-partition jumbo before ISSUE 10); the
    legacy per-partition greedy stays as the parity oracle. Both
    realize the SAME counts, so any valid realization has identical
    weight, kept-slot count, and move count — which is exactly what
    ``tests/test_constructor_vec.py`` pins."""
    from .tpu import constructor as _constructor

    if _constructor.use_vectorized():
        return _disaggregate_vec(inst, agg)
    return _disaggregate_legacy(inst, agg)


def _disaggregate_vec(inst, agg):
    """Vectorized realization: one pass per (class, member) — numpy
    masks over the class's partition block replace the per-partition
    Python loop with its per-partition sorts and Counters. Leaders are
    laid out count-descending over the class's partitions; each
    follower member then takes its ``X_j`` keeps on the first eligible
    partitions (rf headroom, rack-diversity headroom, not already this
    partition's leader)."""
    mrows, mcols = inst._members()
    n = mrows.size
    B, K = inst.num_brokers, inst.num_racks
    # member lookup: (p, b) -> flat member index, via binary search on
    # the row-major (p, b) keys np.nonzero already emits sorted
    keys = mrows.astype(np.int64) * (B + 1) + mcols.astype(np.int64)
    x = np.zeros(n, dtype=bool)
    y = np.zeros(n, dtype=bool)
    rack_of = inst.rack_of_broker
    cm_cls = np.asarray(agg["cm_cls"], np.int64)
    cm_broker = np.asarray(agg["cm_broker"], np.int64)
    X = np.asarray(agg["X"], np.int64)
    Y = np.asarray(agg["Y"], np.int64)
    n_cls = len(agg["cls_parts"])
    order = np.argsort(cm_cls, kind="stable")
    splits = np.cumsum(np.bincount(cm_cls, minlength=n_cls))[:-1]
    by_cls = np.split(order, splits)
    out_p: list[np.ndarray] = []
    out_b: list[np.ndarray] = []
    out_lead: list[np.ndarray] = []
    for ci, parts in enumerate(agg["cls_parts"]):
        cms = by_cls[ci]
        if cms.size == 0:
            continue
        parts_a = np.asarray(parts, dtype=np.int64)
        nP = parts_a.size
        rf_c = int(agg["cls_rf"][ci])
        prh = int(agg["cls_prh"][ci])
        placed = np.zeros(nP, dtype=np.int64)
        rack_load = np.zeros((nP, K), dtype=np.int64)
        lead_of_part = np.full(nP, -1, dtype=np.int64)
        ysort = cms[np.argsort(-Y[cms], kind="stable")]
        lead_members = np.repeat(ysort, Y[ysort])
        if lead_members.size:
            # sum(Y) <= n_c is an aggregate constraint row, so the
            # truncation below is defensive, not load-bearing
            lead_members = lead_members[:nP]
            nl = lead_members.size
            lead_of_part[:nl] = lead_members
            placed[:nl] = 1
            lead_rk = rack_of[cm_broker[lead_members]]
            rack_load[np.arange(nl), lead_rk] += 1
            out_p.append(parts_a[:nl])
            out_b.append(cm_broker[lead_members])
            out_lead.append(np.ones(nl, dtype=bool))
        for j in cms[np.argsort(-X[cms], kind="stable")].tolist():
            xj = int(X[j])
            if xj <= 0:
                continue
            rk = int(rack_of[cm_broker[j]])
            elig = (
                (placed < rf_c)
                & (rack_load[:, rk] < prh)
                & (lead_of_part != j)
            )
            idx = np.flatnonzero(elig)[:xj]
            if idx.size == 0:
                continue  # stranded demand: caller verifies, like legacy
            placed[idx] += 1
            rack_load[idx, rk] += 1
            out_p.append(parts_a[idx])
            out_b.append(np.full(idx.size, cm_broker[j], np.int64))
            out_lead.append(np.zeros(idx.size, dtype=bool))
    if out_p:
        pp = np.concatenate(out_p)
        bb = np.concatenate(out_b)
        ll = np.concatenate(out_lead)
        want = pp * (B + 1) + bb
        pos = np.searchsorted(keys, want)
        if n == 0 or (pos >= n).any() or (
            keys[np.minimum(pos, n - 1)] != want
        ).any():
            return None  # a counted member is not a member: refuse
        y[pos[ll]] = True
        x[pos[~ll]] = True
    return {"x": x, "y": y, "mrows": mrows, "mcols": mcols}


def _disaggregate_legacy(inst, agg):
    """The original per-partition greedy realization — the parity
    oracle for ``_disaggregate_vec`` (``KAO_CONSTRUCTOR=legacy``)."""
    import collections

    mrows, mcols = inst._members()
    idx_of = {}
    for i, (r, c) in enumerate(zip(mrows.tolist(), mcols.tolist())):
        idx_of[(r, c)] = i
    x = np.zeros(mrows.size, dtype=bool)
    y = np.zeros(mrows.size, dtype=bool)
    cm_by_cls = collections.defaultdict(list)
    for j in range(agg["cm_cls"].size):
        cm_by_cls[int(agg["cm_cls"][j])].append(j)
    rack_of = inst.rack_of_broker
    cm_broker = agg["cm_broker"]
    X, Y = agg["X"], agg["Y"]
    for ci, parts in enumerate(agg["cls_parts"]):
        cms = cm_by_cls[ci]
        xr = {j: int(X[j]) for j in cms}
        yr = {j: int(Y[j]) for j in cms}
        prh = int(agg["cls_prh"][ci])
        rf_c = int(agg["cls_rf"][ci])
        for p in parts:
            rack_load: collections.Counter = collections.Counter()
            placed = 0
            lead_j = None
            cands = sorted(cms, key=lambda j: -yr[j])
            if cands and yr[cands[0]] > 0:
                lead_j = cands[0]
                rack_load[int(rack_of[cm_broker[lead_j]])] += 1
                i = idx_of.get((p, int(cm_broker[lead_j])))
                if i is None:
                    return None
                y[i] = True
                yr[lead_j] -= 1
                placed = 1
            for j in sorted(cms, key=lambda j: -xr[j]):
                # rf cap: front-loading a class's keep counts into its
                # early partitions must not exceed any partition's rf
                # (RF-shrink classes have more members than rf)
                if placed >= rf_c:
                    break
                if j == lead_j or xr[j] <= 0:
                    continue
                rk = int(rack_of[cm_broker[j]])
                if rack_load[rk] >= prh:
                    continue
                i = idx_of.get((p, int(cm_broker[j])))
                if i is None:
                    return None
                x[i] = True
                xr[j] -= 1
                rack_load[rk] += 1
                placed += 1
    return {"x": x, "y": y, "mrows": mrows, "mcols": mcols}


def _complete_mcmf(inst, a, vac, leaderless, lead_quota):
    """Leader-aware completion: one min-cost max-flow placing every
    vacancy directly against the BAND SLACK (per-broker and per-rack
    capacity left by the keeps) rather than a fixed per-broker quota —
    the LP's z quotas satisfy the bands but cannot see the (partition,
    broker) pairing, and a blind realization of them strands lead
    coverage (observed: -9 weight on the jumbo instance).

    Cost structure (min-cost at max flow):
    - arcs giving a LEADERLESS partition a new replica on a broker with
      leadership headroom (capped per broker by ``lead_quota`` through
      a gateway node) carry cost -1 -> coverage is maximized, so the
      final exact reseat is not forced to demote kept leaders; each
      such candidate also has a parallel cost-0 bypass so a plain
      placement never consumes lead quota (binding gates must reduce
      the reward, not the max flow);
    - the first ``broker_lo - kept`` / ``rack_lo - kept`` units into a
      below-floor broker/rack carry cost -1000 -> band deficits are
      filled with absolute priority (a completion that leaves a floor
      unmet is infeasible anyway).

    Returns [(p, broker, through_lead_channel)] or None; the caller
    verifies the final plan, so any shortfall here only costs the
    attempt. The lead flag marks placements the flow routed through a
    broker's lead quota — the caller's slot-0 pre-seat uses them so the
    exact reseat starts from in-band leader counts."""
    try:
        from ..native import mcmf
    except Exception:
        return None
    P, R = a.shape
    B, K = inst.num_brokers, inst.num_racks
    rack_of = inst.rack_of_broker[:B].astype(np.int64)
    filled = a != B
    kept_b = np.bincount(
        a[filled].astype(np.int64), minlength=B + 1
    )[:B]
    cap_b = np.maximum(inst.broker_hi - kept_b, 0)
    deficit_b = np.minimum(
        np.maximum(inst.broker_lo - kept_b, 0), cap_b
    )
    kept_k = np.bincount(
        inst.rack_of_broker[a[filled]], minlength=K + 1
    )[:K]
    cap_k = np.maximum(inst.rack_hi - kept_k, 0)
    deficit_k = np.minimum(np.maximum(inst.rack_lo - kept_k, 0), cap_k)
    qb = np.flatnonzero(cap_b > 0)
    pv = np.flatnonzero(vac > 0)
    if qb.size == 0 or pv.size == 0:
        return None
    # one bincount over the flattened (partition, rack) key: np.add.at
    # pays per-element scatter cost (~0.3 s at 50k partitions) on the
    # completion path (ISSUE 10)
    kept_rack = np.bincount(
        np.arange(P, dtype=np.int64)[:, None].repeat(R, 1)[filled]
        * (K + 1)
        + inst.rack_of_broker[a[filled]],
        minlength=P * (K + 1),
    ).reshape(P, K + 1)
    rem = inst.part_rack_hi[:, None] - kept_rack[:, :K]
    qr = np.unique(rack_of[qb])
    grid_p = np.repeat(pv, qr.size)
    grid_k = np.tile(qr, pv.size)
    keep = rem[grid_p, grid_k] > 0
    pk_p, pk_k = grid_p[keep], grid_k[keep]
    U = pk_p.size
    if U == 0:
        return None
    pair_of = np.full(P * K, -1, dtype=np.int64)
    pair_of[pk_p * K + pk_k] = np.arange(U)
    in_part = np.zeros((P, B + 1), dtype=bool)
    rows_f, cols_f = np.nonzero(filled)
    in_part[rows_f, a[rows_f, cols_f]] = True

    # candidate (p, b) edges
    eb_p = np.repeat(pv, qb.size)
    eb_b = np.tile(qb, pv.size)
    pid = pair_of[eb_p * K + rack_of[eb_b]]
    ok_e = (pid >= 0) & ~in_part[eb_p, eb_b]
    eb_p, eb_b, pid = eb_p[ok_e], eb_b[ok_e], pid[ok_e]
    # lead-channel candidates get a per-(p, b) intermediate node with
    # TWO outgoing arcs: the gated lead arc (cost -1, shares the
    # broker's lead-quota capacity) AND a parallel cost-0 direct arc.
    # The intermediate's unit in-capacity keeps per-(p, b) uniqueness,
    # and the direct arc means a plain placement never consumes lead
    # quota — without it, binding gates push max flow below the
    # vacancy count and abort the whole leader-aware completion to the
    # blind fallback.
    lead_e = leaderless[eb_p] & (lead_quota[eb_b] > 0)
    n_lead = int(lead_e.sum())
    # node ids: 0 source | parts | pairs | lead gateways | brokers |
    # racks | per-(p, b) lead intermediates | sink
    o_part, o_pair = 1, 1 + P
    o_gate = o_pair + U
    o_brok = o_gate + B
    o_rack = o_brok + B
    o_mid = o_rack + K
    t = o_mid + n_lead
    DEFICIT_REWARD = 1000
    b_idx = np.arange(B)
    k_idx = np.arange(K)
    m_idx = np.arange(n_lead)
    src = [
        np.zeros(pv.size, np.int64),        # s -> p
        o_part + pk_p,                      # p -> (p, k)
        o_pair + pid[~lead_e],              # (p, k) -> b   (plain)
        o_pair + pid[lead_e],               # (p, k) -> mid (lead cand)
        o_mid + m_idx,                      # mid -> gate (lead channel)
        o_mid + m_idx,                      # mid -> b     (plain bypass)
        o_gate + b_idx,                     # gate -> b
        o_brok + qb,                        # b -> rack: deficit channel
        o_brok + qb,                        # b -> rack: remaining slack
        o_rack + k_idx,                     # rack -> t: deficit channel
        o_rack + k_idx,                     # rack -> t: remaining slack
    ]
    dst = [
        o_part + pv,
        o_pair + np.arange(U),
        o_brok + eb_b[~lead_e],
        o_mid + m_idx,
        o_gate + eb_b[lead_e],
        o_brok + eb_b[lead_e],
        o_brok + b_idx,
        o_rack + rack_of[qb],
        o_rack + rack_of[qb],
        np.full(K, t, np.int64),
        np.full(K, t, np.int64),
    ]
    cap = [
        vac[pv],
        np.minimum(rem[pk_p, pk_k], vac[pk_p]),
        np.ones(int((~lead_e).sum()), np.int64),
        np.ones(n_lead, np.int64),
        np.ones(n_lead, np.int64),
        np.ones(n_lead, np.int64),
        np.minimum(lead_quota, cap_b),
        deficit_b[qb],
        (cap_b - deficit_b)[qb],
        deficit_k,
        cap_k - deficit_k,
    ]
    cost = [
        np.zeros(pv.size, np.int64),
        np.zeros(U, np.int64),
        np.zeros(int((~lead_e).sum()), np.int64),
        np.zeros(n_lead, np.int64),
        -np.ones(n_lead, np.int64),
        np.zeros(n_lead, np.int64),
        np.zeros(B, np.int64),
        np.full(qb.size, -DEFICIT_REWARD, np.int64),
        np.zeros(qb.size, np.int64),
        np.full(K, -DEFICIT_REWARD, np.int64),
        np.zeros(K, np.int64),
    ]
    src = np.concatenate(src)
    dst = np.concatenate(dst)
    cap = np.concatenate(cap)
    cost = np.concatenate(cost)
    try:
        flow, _cost, arc_flow = mcmf(src, dst, cap, cost, 0, t, t + 1)
    except Exception:
        return None
    if flow != int(vac.sum()):
        return None
    n0 = pv.size + U
    n_plain = int((~lead_e).sum())
    p_pl, b_pl = eb_p[~lead_e], eb_b[~lead_e]
    p_ld, b_ld = eb_p[lead_e], eb_b[lead_e]
    pf = np.asarray(arc_flow[n0:n0 + n_plain], np.int64)
    # a lead candidate is placed iff its (p, k) -> mid arc carries flow;
    # it consumed lead quota iff the mid -> gate channel carried it
    # (the bypass is a plain placement). Assignments returned as flat
    # (partition, broker, via-lead-channel) arrays — np.repeat over the
    # arc flows instead of the per-unit Python list build (ISSUE 10).
    lf = np.asarray(arc_flow[n0 + n_plain:n0 + n_plain + n_lead],
                    np.int64)
    gf = np.asarray(
        arc_flow[n0 + n_plain + n_lead:n0 + n_plain + 2 * n_lead],
        np.int64,
    )
    ap = np.concatenate([
        np.repeat(p_pl, pf), np.repeat(p_ld, lf),
    ]).astype(np.int64)
    ab = np.concatenate([
        np.repeat(b_pl, pf), np.repeat(b_ld, lf),
    ]).astype(np.int64)
    alead = np.concatenate([
        np.zeros(int(pf.sum()), dtype=bool),
        np.repeat(gf > 0, lf),
    ])
    return ap, ab, alead


def _complete_maxflow(inst, a, vac, quota):
    """Assign each vacancy a (partition, broker) pair: max-flow over
    partitions -> (p, rack) diversity nodes -> quota brokers. Returns
    [(p, broker)] or None if the vacancies cannot all be placed."""
    try:
        import scipy.sparse as sp
        from scipy.sparse.csgraph import maximum_flow
    except Exception:
        return None
    P, R = a.shape
    B, K = inst.num_brokers, inst.num_racks
    rack_of = inst.rack_of_broker[:B].astype(np.int64)
    qb = np.flatnonzero(quota > 0)
    if qb.size == 0:
        return None
    # per-(p, rack) remaining diversity allowance
    filled = a != B
    # one bincount over the flattened (partition, rack) key: np.add.at
    # pays per-element scatter cost (~0.3 s at 50k partitions) on the
    # completion path (ISSUE 10)
    kept_rack = np.bincount(
        np.arange(P, dtype=np.int64)[:, None].repeat(R, 1)[filled]
        * (K + 1)
        + inst.rack_of_broker[a[filled]],
        minlength=P * (K + 1),
    ).reshape(P, K + 1)
    rem = inst.part_rack_hi[:, None] - kept_rack[:, :K]  # [P, K]

    # sparse (p, k) pair nodes: only racks holding quota brokers, only
    # partitions with vacancies and remaining allowance. Fully
    # vectorized — at 50k partitions x 100 quota brokers the Python
    # per-edge version costs seconds of host CPU.
    qr = np.unique(rack_of[qb])
    pv = np.flatnonzero(vac > 0)
    if pv.size == 0 or qr.size == 0:
        return None
    grid_p = np.repeat(pv, qr.size)
    grid_k = np.tile(qr, pv.size)
    keep = rem[grid_p, grid_k] > 0
    pk_p, pk_k = grid_p[keep], grid_k[keep]
    U = pk_p.size
    if U == 0:
        return None
    # pair lookup: index into the dense (p, k) grid
    pair_of = np.full(P * K, -1, dtype=np.int64)
    pair_of[pk_p * K + pk_k] = np.arange(U)

    # membership mask to forbid brokers already in the partition
    in_part = np.zeros((P, B + 1), dtype=bool)
    rows_f, cols_f = np.nonzero(filled)
    in_part[rows_f, a[rows_f, cols_f]] = True

    o_part, o_pair = 1, 1 + P
    o_brok = 1 + P + U
    t = o_brok + B
    src, dst, cap = [], [], []
    # s -> partition
    src.append(np.zeros(pv.size, np.int64))
    dst.append(o_part + pv)
    cap.append(vac[pv])
    # partition -> pair
    src.append(o_part + pk_p)
    dst.append(o_pair + np.arange(U))
    cap.append(np.minimum(rem[pk_p, pk_k], vac[pk_p]))
    # pair -> broker (cap 1 per (p, b); skip members already in p):
    # cross every quota broker with every pair node of its rack
    eb_p = np.repeat(pv, qb.size)        # candidate partition
    eb_b = np.tile(qb, pv.size)          # candidate broker
    pid = pair_of[eb_p * K + rack_of[eb_b]]
    ok_e = (pid >= 0) & ~in_part[eb_p, eb_b]
    if not ok_e.any():
        return None
    src.append(o_pair + pid[ok_e])
    dst.append(o_brok + eb_b[ok_e])
    cap.append(np.ones(int(ok_e.sum()), np.int64))
    # broker -> t
    src.append(o_brok + qb)
    dst.append(np.full(qb.size, t, np.int64))
    cap.append(quota[qb])

    src = np.concatenate(src)
    dst = np.concatenate(dst)
    cap = np.concatenate(cap).astype(np.int32)
    g = sp.csr_matrix((cap, (src, dst)), shape=(t + 1, t + 1))
    res = maximum_flow(g, 0, t)
    if res.flow_value != int(vac.sum()):
        return None
    flow = res.flow.tocoo()
    # vectorized extraction of the pair -> broker arcs (the per-edge
    # Python loop walked every arc of the flow matrix; ISSUE 10)
    fi = np.asarray(flow.row, np.int64)
    fj = np.asarray(flow.col, np.int64)
    fd = np.asarray(flow.data, np.int64)
    keep = (
        (fd > 0) & (fi >= o_pair) & (fi < o_brok)
        & (fj >= o_brok) & (fj < t)
    )
    fi, fj, fd = fi[keep], fj[keep], fd[keep]
    ap = np.repeat(pk_p[fi - o_pair], fd).astype(np.int64)
    ab = np.repeat(fj - o_brok, fd).astype(np.int64)
    return ap, ab
