"""LP-rounding plan constructor — decode the kept-replica LP's vertex
into an actual reassignment plan.

The level-2 weight bound (``ProblemInstance._kept_weight_lp``) is a
transportation-structured LP whose optimum is (almost always) an
INTEGRAL vertex: x/y say exactly which current members stay and in which
role, z says how many new replicas each broker absorbs, u how many
leaderships land on non-kept leaders. When the caps genuinely bind —
scale-outs over-filling old brokers, leader-skew rebalances — local
search burns its whole ladder approaching that structure from below;
this module instead materializes it directly:

1. round x/y/z (bail to None on a fractional vertex),
2. place the kept members,
3. complete the vacant slots with new replicas via one max-flow
   (partitions -> (partition, rack) diversity nodes -> brokers with
   z-quota), so every band and diversity cap holds by construction,
4. reseat leaders exactly (``best_leader_assignment``).

If the result is feasible and meets the weight bound it IS a proven
global optimum and the engine can skip annealing entirely; otherwise it
still seeds the population at (or near) the LP structure. Returns None
whenever any step cannot complete — callers always have the greedy seed
to fall back on.

No counterpart in the reference (its lp_solve run IS the exact solve,
``/root/reference/README.md:135-137``); this is the TPU build's bridge
between the search engine and exact optimality.
"""

from __future__ import annotations

import numpy as np

from ..models.instance import ProblemInstance


def construct(inst: ProblemInstance) -> np.ndarray | None:
    """Decode the kept-replica LP into a full plan, or None."""
    try:
        sol = inst._kept_weight_lp(return_solution=True)
    except Exception:
        return None
    if not isinstance(sol, dict):
        return None
    x, y = np.asarray(sol["x"]), np.asarray(sol["y"])
    z = np.asarray(sol["z"])
    mrows, mcols = sol["mrows"], sol["mcols"]

    # integral vertex required: kept roles and new-replica quotas must
    # be whole (transportation structure makes this the common case)
    if (
        np.abs(x - np.rint(x)).max(initial=0) > 1e-6
        or np.abs(y - np.rint(y)).max(initial=0) > 1e-6
        or np.abs(z - np.rint(z)).max(initial=0) > 1e-6
    ):
        return None
    xi = np.rint(x).astype(bool)
    yi = np.rint(y).astype(bool)
    quota = np.rint(z).astype(np.int64)

    P, R = inst.num_parts, inst.max_rf
    B, K = inst.num_brokers, inst.num_racks
    rf = inst.rf.astype(np.int64)
    valid = inst.slot_valid

    # place kept members sequentially per partition — slot ORDER is
    # irrelevant here because the final exact leader reseat permutes
    # each row anyway
    keep = xi | yi
    kr, kb = mrows[keep], mcols[keep]
    order = np.argsort(kr, kind="stable")
    kr, kb = kr[order], kb[order]
    first = np.r_[True, kr[1:] != kr[:-1]] if kr.size else np.array([], bool)
    start = np.maximum.accumulate(
        np.where(first, np.arange(kr.size), 0)
    ) if kr.size else kr
    rank = np.arange(kr.size) - start
    if kr.size and (rank >= rf[kr]).any():
        return None  # vertex kept more slots than the partition has
    a = np.full((P, R), B, dtype=np.int64)
    a[kr, rank] = kb

    kept_cnt = (a != B).sum(axis=1)
    vac = rf - kept_cnt  # >= 0: the rank check above caps keeps at rf
    need = int(vac.sum())
    if need != int(quota.sum()):
        return None
    if need > 0:
        assign = _complete_maxflow(inst, a, vac, quota)
        if assign is None:
            return None
        for p, b in assign:
            row = a[p]
            vac_slots = np.flatnonzero((row == B) & valid[p])
            a[p, vac_slots[0]] = b
    if ((a == B) & valid).any():
        return None

    a = a.astype(np.int32)
    a = inst.best_leader_assignment(a)
    if not inst.is_feasible(a):
        return None
    return a


def _complete_maxflow(inst, a, vac, quota):
    """Assign each vacancy a (partition, broker) pair: max-flow over
    partitions -> (p, rack) diversity nodes -> quota brokers. Returns
    [(p, broker)] or None if the vacancies cannot all be placed."""
    try:
        import scipy.sparse as sp
        from scipy.sparse.csgraph import maximum_flow
    except Exception:
        return None
    P, R = a.shape
    B, K = inst.num_brokers, inst.num_racks
    rack_of = inst.rack_of_broker[:B].astype(np.int64)
    qb = np.flatnonzero(quota > 0)
    if qb.size == 0:
        return None
    # per-(p, rack) remaining diversity allowance
    kept_rack = np.zeros((P, K + 1), dtype=np.int64)
    filled = a != B
    np.add.at(
        kept_rack,
        (np.arange(P)[:, None].repeat(R, 1)[filled],
         inst.rack_of_broker[a[filled]]),
        1,
    )
    rem = inst.part_rack_hi[:, None] - kept_rack[:, :K]  # [P, K]

    # sparse (p, k) pair nodes: only racks holding quota brokers, only
    # partitions with vacancies and remaining allowance. Fully
    # vectorized — at 50k partitions x 100 quota brokers the Python
    # per-edge version costs seconds of host CPU.
    qr = np.unique(rack_of[qb])
    pv = np.flatnonzero(vac > 0)
    if pv.size == 0 or qr.size == 0:
        return None
    grid_p = np.repeat(pv, qr.size)
    grid_k = np.tile(qr, pv.size)
    keep = rem[grid_p, grid_k] > 0
    pk_p, pk_k = grid_p[keep], grid_k[keep]
    U = pk_p.size
    if U == 0:
        return None
    # pair lookup: index into the dense (p, k) grid
    pair_of = np.full(P * K, -1, dtype=np.int64)
    pair_of[pk_p * K + pk_k] = np.arange(U)

    # membership mask to forbid brokers already in the partition
    in_part = np.zeros((P, B + 1), dtype=bool)
    rows_f, cols_f = np.nonzero(filled)
    in_part[rows_f, a[rows_f, cols_f]] = True

    o_part, o_pair = 1, 1 + P
    o_brok = 1 + P + U
    t = o_brok + B
    src, dst, cap = [], [], []
    # s -> partition
    src.append(np.zeros(pv.size, np.int64))
    dst.append(o_part + pv)
    cap.append(vac[pv])
    # partition -> pair
    src.append(o_part + pk_p)
    dst.append(o_pair + np.arange(U))
    cap.append(np.minimum(rem[pk_p, pk_k], vac[pk_p]))
    # pair -> broker (cap 1 per (p, b); skip members already in p):
    # cross every quota broker with every pair node of its rack
    eb_p = np.repeat(pv, qb.size)        # candidate partition
    eb_b = np.tile(qb, pv.size)          # candidate broker
    pid = pair_of[eb_p * K + rack_of[eb_b]]
    ok_e = (pid >= 0) & ~in_part[eb_p, eb_b]
    if not ok_e.any():
        return None
    src.append(o_pair + pid[ok_e])
    dst.append(o_brok + eb_b[ok_e])
    cap.append(np.ones(int(ok_e.sum()), np.int64))
    # broker -> t
    src.append(o_brok + qb)
    dst.append(np.full(qb.size, t, np.int64))
    cap.append(quota[qb])

    src = np.concatenate(src)
    dst = np.concatenate(dst)
    cap = np.concatenate(cap).astype(np.int32)
    g = sp.csr_matrix((cap, (src, dst)), shape=(t + 1, t + 1))
    res = maximum_flow(g, 0, t)
    if res.flow_value != int(vac.sum()):
        return None
    flow = res.flow.tocoo()
    out = []
    for i, j, f in zip(flow.row, flow.col, flow.data):
        if f > 0 and o_pair <= i < o_brok and o_brok <= j < t:
            p = int(pk_p[i - o_pair])
            b = int(j - o_brok)
            out.extend([(p, b)] * int(f))
    return out
