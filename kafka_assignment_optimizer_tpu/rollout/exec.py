"""The rollout executor (docs/ROLLOUT.md): waves through the watch
channel.

One :class:`RolloutManager` rides a :class:`~..watch.manager.
WatchRegistry`. Commands (``start``/``advance``/``pause``/``rollback``)
mutate the epoch-fenced :class:`~.state.RolloutRecord` under a
per-cluster rollout lock, persist it to the plan store BEFORE the
in-memory commit (the watch manager's crash contract), and emit each
wave as upstream-compatible Kafka reassignment JSON. Every transition
lands simultaneously on four surfaces: the plan store (durable record),
a ``rollout`` trace span in the solve-report ring, a ``kind="rollout"``
flight record, and the ``kao_rollout_*`` counters the serve layer
renders.

Ground-truth discipline: while a rollout is active the registry's
commit does NOT fold a delta solve's plan into the cluster assignment
(the cluster is mid-move; the truth advances wave by wave via
:meth:`~..watch.manager.WatchRegistry.commit_assignment`). A
mid-rollout cluster event (``broker_remove``, ``rack_fail``) therefore
solves against the PARTIALLY-MOVED assignment, and the committed plan
flows back here through the registry's replan hook: the REMAINING
waves are re-packed against the partially-moved truth, epochs stay
monotone, and yesterday's "storm" — a reassignment fighting the
optimizer — becomes one coalesced rollout.
"""

from __future__ import annotations

import threading
import time

from ..models.cluster import Assignment
from ..obs import flight as _oflight
from ..obs import log as _olog
from ..obs import trace as _otrace
from .state import (
    TERMINAL,
    RolloutConflict,
    RolloutError,
    RolloutFenced,
    RolloutRecord,
    validate_epoch,
)
from .waves import (
    DEFAULT_BROKER_CAP,
    DEFAULT_LANES,
    DEFAULT_RACK_CAP,
    WaveCaps,
    WavePlan,
    pack_waves,
)

__all__ = ["RolloutManager", "wave_json"]


def wave_json(wave) -> dict:
    """One wave as upstream-compatible reassignment JSON
    (``README.md:52-78``): the byte dialect ``kafka-reassign-
    partitions --execute`` accepts. Partition order is the wave's
    application order — data moves first, leader-changing moves last —
    NOT the sorted order ``Assignment.to_dict`` emits: the order is
    part of the wave contract."""
    return {
        "version": 1,
        "partitions": [
            {"topic": t, "partition": p, "replicas": list(r)}
            for t, p, r in wave.targets()
        ],
    }


def _counter_dict() -> dict:
    return {
        "started_total": 0,        # rollouts created (start admitted)
        "commands_total": 0,       # admitted (post-fence) commands
        "fenced_total": 0,         # stale rollout epochs rejected
        "waves_emitted_total": 0,  # wave JSONs handed to the operator
        "waves_applied_total": 0,  # waves folded into ground truth
        "canary_fail_total": 0,    # canary verdicts that rolled back
        "rollbacks_total": 0,      # rollback commands (incl. canary)
        "replans_total": 0,        # mid-rollout remaining-wave re-plans
        "completed_total": 0,      # rollouts that reached done
    }


class RolloutManager:
    """Per-cluster rollout execution over one watch registry."""

    def __init__(self, registry, store=None, *,
                 broker_cap: int = DEFAULT_BROKER_CAP,
                 rack_cap: int = DEFAULT_RACK_CAP,
                 packer: str = "greedy",
                 lanes: int = DEFAULT_LANES,
                 trace: bool = True):
        self.registry = registry
        self.store = store if store is not None else registry.store
        self.default_caps = WaveCaps(broker=int(broker_cap),
                                     rack=int(rack_cap))
        self.packer = packer
        self.lanes = int(lanes)
        self.trace = trace
        self._lock = threading.Lock()
        self._locks: dict[str, threading.Lock] = {}
        self._records: dict[str, RolloutRecord] = {}
        self._counters = _counter_dict()
        # the watch-channel replan hook (docs/ROLLOUT.md): every plan
        # committed while a rollout holds the ground truth (the
        # registry's rollout_hold, raised by begin_execution) is
        # offered here for a remaining-wave re-plan
        registry.replan_fn = self.on_replan

    # -- bookkeeping ----------------------------------------------------

    def _count(self, **updates) -> None:
        with self._lock:
            for k, v in updates.items():
                self._counters[k] += v

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self._counters)
            recs = list(self._records.values())
        out["active"] = sum(1 for r in recs if r.active)
        out["packer"] = self.packer
        out["broker_cap"] = self.default_caps.broker
        out["rack_cap"] = self.default_caps.rack
        out["durable"] = int(self.store is not None)
        return out

    def _cluster_lock(self, cluster_id: str) -> threading.Lock:
        with self._lock:
            lk = self._locks.get(cluster_id)
            if lk is None:
                lk = self._locks[cluster_id] = threading.Lock()
        return lk

    def _load(self, cluster_id: str) -> RolloutRecord | None:
        """The in-memory record, lazily restored from the durable store
        (first touch after a restart resumes at the persisted wave and
        epoch). Caller holds the cluster's rollout lock."""
        rec = self._records.get(cluster_id)
        if rec is None and self.store is not None:
            payload = self.store.load_rollout(cluster_id)
            if payload is not None:
                try:
                    rec = RolloutRecord.from_dict(payload)
                except (KeyError, TypeError, ValueError) as e:
                    _olog.error("rollout_record_unreadable",
                                cluster=cluster_id,
                                error=repr(e)[:200])
                    rec = None
            if rec is not None:
                self._records[cluster_id] = rec
        return rec

    def _persist(self, rec: RolloutRecord) -> None:
        """Durably save BEFORE the in-memory commit (the watch
        manager's crash contract): a save that raises leaves memory and
        disk agreeing, so the client's retried command is admitted, not
        fenced on an epoch that was never recorded."""
        if self.store is not None:
            self.store.save_rollout(rec.cluster_id, rec.to_dict())

    # -- observability: every transition on all four surfaces -----------

    def _observe(self, cmd: str, rec: RolloutRecord, wall_s: float,
                 **extra) -> str | None:
        # the "start" transition IS the rollout's root trace (its ID
        # is the durable rec.trace_id the plan store carries); every
        # later transition gets its own ID linked back via
        # rollout_root, the same link the mid-rollout delta re-solve
        # traces record (ISSUE 15, docs/ROLLOUT.md)
        tid = None
        if self.trace:
            tid = (rec.trace_id if cmd == "start" and rec.trace_id
                   else _otrace.new_trace_id())
        tr = _otrace.begin(tid, name="rollout", cluster=rec.cluster_id,
                           command=cmd)
        if tr is not None:
            if rec.trace_id and tid != rec.trace_id:
                tr.root.set(rollout_root=rec.trace_id)
            tr.root.set(status=rec.status, wave=rec.wave_index,
                        waves=len(rec.plan.waves),
                        applied=len(rec.applied),
                        rollout_epoch=rec.rollout_epoch, **extra)
            _otrace.finish(tr)
        _oflight.record({
            "ts": round(time.time(), 3),
            "kind": "rollout",
            "trace_id": tid,
            "cluster": rec.cluster_id,
            "command": cmd,
            "status": rec.status,
            "wave": rec.wave_index,
            "waves": len(rec.plan.waves),
            "applied": len(rec.applied),
            "rollout_epoch": rec.rollout_epoch,
            "wall_s": round(wall_s, 4),
            # a rollout transition is a control action, not a solve:
            # quality is "did the state machine accept it", which it
            # did by the time this record lands
            "quality": {"feasible": True, "certified": False,
                        "degraded": False},
            **({"rollout_root": rec.trace_id}
               if rec.trace_id and tid != rec.trace_id else {}),
            **extra,
        })
        _olog.log("rollout", cluster=rec.cluster_id, command=cmd,
                  status=rec.status, wave=rec.wave_index,
                  applied=len(rec.applied), epoch=rec.rollout_epoch)
        return tid

    # -- read surface ---------------------------------------------------

    def get(self, cluster_id: str) -> dict | None:
        with self._cluster_lock(cluster_id):
            rec = self._load(cluster_id)
            if rec is None:
                return None
            return self._view(rec)

    def active_trace_root(self, cluster_id: str) -> str | None:
        """The ACTIVE rollout's durable root trace ID for
        ``cluster_id`` (None when no rollout owns the cluster) — what
        serve's delta re-solve traces link to (ISSUE 15). Safe from
        the solve path: the caller holds no manager locks there (the
        watch registry runs solves outside its commit lock), and the
        rollout→cluster lock order never reverses."""
        with self._cluster_lock(cluster_id):
            rec = self._load(cluster_id)
            if rec is None or not rec.active:
                return None
            return rec.trace_id

    def _view(self, rec: RolloutRecord) -> dict:
        plan = rec.plan
        current = None
        if rec.active and rec.status != "planned" \
                and rec.wave_index < len(plan.waves):
            current = wave_json(plan.waves[rec.wave_index])
        return {
            "cluster_id": rec.cluster_id,
            "status": rec.status,
            "trace_id": rec.trace_id,
            "rollout_epoch": rec.rollout_epoch,
            "plan_epoch": rec.plan_epoch,
            "wave_index": rec.wave_index,
            "waves": len(plan.waves),
            "applied": list(rec.applied),
            "remaining": rec.remaining,
            "replans": rec.replans,
            "caps": plan.caps.to_dict(),
            "packer": plan.packer,
            "wave_summary": [
                {
                    "index": w.index,
                    "moves": len(w.moves),
                    "data_units": w.data_units,
                    "peak_broker": w.peak_broker,
                    "peak_rack": w.peak_rack,
                    "cross_rack": w.cross_rack,
                    "applied": w.index in set(rec.applied),
                }
                for w in plan.waves
            ],
            "current_wave": current,
        }

    # -- commands -------------------------------------------------------

    def command(self, cluster_id: str, cmd: str, payload: dict,
                budget=None) -> dict:
        """Apply one fenced rollout command; returns the response body.
        Raises :class:`RolloutError` (400), :class:`RolloutConflict` /
        :class:`RolloutFenced` (409), or :class:`~..watch.events.
        EventError` for an unknown cluster."""
        if cmd not in ("start", "advance", "pause", "rollback"):
            raise RolloutError(
                f"unknown rollout command {cmd!r}; want start, "
                "advance, pause, or rollback"
            )
        if not isinstance(payload, dict):
            raise RolloutError("rollout command body must be a JSON "
                               "object")
        t0 = time.perf_counter()
        with self._cluster_lock(cluster_id):
            try:
                rec = self._load(cluster_id)
                if cmd == "start":
                    out = self._start(cluster_id, rec, payload, budget)
                else:
                    if rec is None:
                        raise RolloutConflict(
                            f"no rollout for cluster {cluster_id!r}; "
                            "POST .../rollout/start first"
                        )
                    self._check_generation(rec)
                    epoch = rec.fence(payload.get("epoch"))
                    # mutate a WORKING COPY and swap it in only after
                    # its persist succeeded: a failed save must leave
                    # memory and disk agreeing, so the client's RETRY
                    # of the same epoch is admitted, never fenced on a
                    # command that was not durably recorded. (A wave
                    # whose ground-truth commit landed before the
                    # failed save re-applies idempotently on retry —
                    # commit_assignment sets the same replica lists.)
                    work = RolloutRecord.from_dict(rec.to_dict())
                    if cmd == "advance":
                        out = self._advance(work, epoch, payload)
                    elif cmd == "pause":
                        out = self._pause(work, epoch)
                    else:
                        out = self._rollback(work, epoch,
                                             reason="command")
                    self._records[cluster_id] = work
            except RolloutFenced as e:
                # the fence is provable from the counters: fenced moves,
                # commands/waves do not, and the store was not written
                self._count(fenced_total=1)
                _olog.warn("rollout_epoch_fenced", cluster=cluster_id,
                           got=e.got, current=e.current)
                raise
            self._count(commands_total=1)
        self._observe(cmd, self._records[cluster_id],
                      time.perf_counter() - t0)
        return out

    def _check_generation(self, rec: RolloutRecord) -> None:
        """A re-bootstrap re-declared the cluster's ground truth: a
        rollout recorded against an older generation describes a dead
        world and must refuse every further command (start a fresh
        one)."""
        info = self.registry.plan_info(rec.cluster_id)
        if info is not None and rec.active \
                and info["generation"] != rec.generation:
            raise RolloutConflict(
                f"rollout for {rec.cluster_id!r} predates a "
                "re-bootstrap (generation "
                f"{rec.generation} != {info['generation']}); start a "
                "new rollout"
            )

    def _start(self, cluster_id: str, rec: RolloutRecord | None,
               payload: dict, budget) -> dict:
        if rec is not None and rec.active:
            # a record from a dead generation does not block a fresh
            # start — the re-bootstrap already invalidated it
            info = self.registry.plan_info(cluster_id)
            if info is None or info["generation"] == rec.generation:
                raise RolloutConflict(
                    f"cluster {cluster_id!r} already has an active "
                    f"rollout ({rec.status!r}, wave {rec.wave_index}); "
                    "rollback or complete it first"
                )
        epoch = validate_epoch(payload.get("epoch"))
        if rec is not None and epoch <= rec.rollout_epoch:
            raise RolloutFenced(cluster_id, epoch, rec.rollout_epoch)
        info = self.registry.plan_info(cluster_id)
        if info is None:
            from ..watch.events import EventError

            raise EventError(
                f"unknown cluster {cluster_id!r}; bootstrap it with "
                "POST /clusters/<id>/events first"
            )
        if info.get("plan") is None:
            raise RolloutConflict(
                f"cluster {cluster_id!r} has no certified plan yet; "
                "a rollout executes the plan the watch channel solved"
            )
        try:
            caps = WaveCaps(
                broker=int(payload.get("broker_cap",
                                       self.default_caps.broker)),
                rack=int(payload.get("rack_cap",
                                     self.default_caps.rack)),
            )
        except (TypeError, ValueError) as e:
            # malformed caps are the documented 400, never a 422
            raise RolloutError(
                f"'broker_cap'/'rack_cap' must be ints >= 1: {e}"
            ) from e
        if caps.broker < 1 or caps.rack < 1:
            raise RolloutError("'broker_cap'/'rack_cap' must be >= 1")
        packer = payload.get("packer", self.packer)
        # the plan is a DESTINATION: rewind the ground truth to the
        # pre-plan assignment (the registry kept it at merge time) so
        # the waves execute the actual copy work the plan implies
        base_dict = self.registry.begin_execution(cluster_id)
        try:
            current = Assignment.from_dict(base_dict)
            target = Assignment.from_dict(info["plan"])
            topo = self.registry.topology_of(cluster_id)
            try:
                plan = pack_waves(current, target, topo, caps=caps,
                                  packer=packer, lanes=self.lanes,
                                  budget=budget)
            except ValueError as e:
                raise RolloutError(str(e)) from e
            status = "planned" if plan.waves else "done"
            new = RolloutRecord(
                cluster_id=cluster_id,
                rollout_epoch=epoch,
                plan_epoch=info.get("plan_epoch"),
                status=status,
                wave_index=0,
                plan=plan,
                base=current.to_dict(),
                target=target.to_dict(),
                generation=info["generation"],
                # the durable root trace ID every transition and
                # mid-rollout re-solve links to (ISSUE 15)
                trace_id=(_otrace.new_trace_id() if self.trace
                          else None),
            )
            self._persist(new)
        except BaseException:
            # NOTHING was durably created: release the hold
            # begin_execution raised (bad packer spec, unparsable plan,
            # a failed save — disk full) or the cluster would stop
            # merging plans forever with no record to drive it
            self.registry.end_execution(cluster_id)
            raise
        self._records[cluster_id] = new
        if status == "done":
            # nothing to execute: release the hold begin_execution
            # raised — the plan IS the truth already
            self.registry.end_execution(cluster_id)
        self._count(started_total=1,
                    completed_total=int(status == "done"))
        return self._view(new)

    def _advance(self, rec: RolloutRecord, epoch: int,
                 payload: dict) -> dict:
        rec.require_status("planned", "canary", "advancing", "paused")
        if rec.status == "planned":
            # emit the canary wave; nothing is applied until verified
            rec.rollout_epoch = epoch
            rec.status = "canary"
            self._persist(rec)
            self._count(waves_emitted_total=1)
            return self._view(rec)
        if rec.status == "paused":
            rec.rollout_epoch = epoch
            rec.status = rec.resumed_status or "advancing"
            rec.resumed_status = None
            self._persist(rec)
            return self._view(rec)
        if rec.status == "canary":
            ok = payload.get("canary_ok")
            if not isinstance(ok, bool):
                raise RolloutError(
                    "advancing past the canary wave requires "
                    "'canary_ok': true|false — the operator's verdict "
                    "on the canary reassignment (docs/ROLLOUT.md)"
                )
            if not ok:
                self._count(canary_fail_total=1)
                return self._rollback(rec, epoch, reason="canary_fail")
        # canary verified, or mid-rollout: apply the current wave to
        # the ground truth, then emit the next (or finish)
        return self._apply_wave(rec, epoch)

    def _apply_wave(self, rec: RolloutRecord, epoch: int) -> dict:
        wave = rec.plan.waves[rec.wave_index]
        # the wave becomes ground truth THROUGH the watch channel: the
        # registry persists the new assignment before committing it,
        # so the plan store, the next delta solve, and the rollout
        # record all agree on the partially-moved cluster
        self.registry.commit_assignment(rec.cluster_id, wave.targets())
        rec.applied.append(rec.wave_index)
        rec.wave_index += 1
        rec.rollout_epoch = epoch
        done = rec.wave_index >= len(rec.plan.waves)
        rec.status = "done" if done else "advancing"
        self._persist(rec)
        if done:
            self.registry.end_execution(rec.cluster_id)
        self._count(waves_applied_total=1,
                    waves_emitted_total=int(not done),
                    completed_total=int(done))
        return self._view(rec)

    def _pause(self, rec: RolloutRecord, epoch: int) -> dict:
        rec.require_status("planned", "canary", "advancing")
        rec.resumed_status = rec.status
        rec.status = "paused"
        rec.rollout_epoch = epoch
        self._persist(rec)
        return self._view(rec)

    def _rollback(self, rec: RolloutRecord, epoch: int, *,
                  reason: str) -> dict:
        rec.require_status("planned", "canary", "advancing", "paused")
        # replay the inverse waves in reverse order: every applied
        # wave's partitions return to their BASE replica lists, so the
        # pre-rollout assignment is restored bit-exactly (partitions no
        # wave touched were never changed by the rollout). A partition
        # the base does NOT know was created mid-rollout
        # (partition_growth) and placed by a post-replan wave: its
        # pre-rollout truth is the empty replica list growth declared,
        # so its inverse is un-placement, not survival
        base_by = {
            (p["topic"], p["partition"]): p["replicas"]
            for p in rec.base["partitions"]
        }
        inverse = []
        for idx in reversed(rec.applied):
            wave = rec.plan.waves[idx]
            targets = [
                (t, p, list(base_by.get((t, p), [])))
                for t, p, _ in wave.targets()
            ]
            if targets:
                self.registry.commit_assignment(rec.cluster_id, targets)
            inverse.append({
                "index": idx,
                "reassignment": {
                    "version": 1,
                    "partitions": [
                        {"topic": t, "partition": p, "replicas": r}
                        for t, p, r in targets
                    ],
                },
            })
        rec.status = "rolled_back"
        rec.rollout_epoch = epoch
        rec.resumed_status = None
        self._persist(rec)
        self.registry.end_execution(rec.cluster_id)
        self._count(rollbacks_total=1)
        out = self._view(rec)
        out["rollback_reason"] = reason
        out["inverse_waves"] = inverse
        return out

    # -- the watch-channel replan hook ----------------------------------

    def on_replan(self, cluster_id: str, plan_dict: dict,
                  plan_epoch: int) -> None:
        """Called by the registry AFTER a delta solve commits while a
        rollout is active: the cluster changed mid-rollout
        (broker_remove, rack_fail, growth...), the watch channel
        re-solved against the PARTIALLY-MOVED ground truth, and the
        remaining waves must now chase the new plan. Applied waves are
        history and keep their indices; waves from ``wave_index`` on
        are re-packed. Never raises into the solve path."""
        try:
            t0 = time.perf_counter()
            with self._cluster_lock(cluster_id):
                rec = self._load(cluster_id)
                if rec is None or not rec.active:
                    return
                truth = self.registry.assignment_of(cluster_id)
                if truth is None:
                    return
                current = Assignment.from_dict(truth)
                target = Assignment.from_dict(plan_dict)
                topo = self.registry.topology_of(cluster_id)
                fresh = pack_waves(
                    current, target, topo, caps=rec.plan.caps,
                    packer=rec.plan.packer, lanes=self.lanes,
                )
                # working-copy discipline (same as command()): a
                # failed save must not leave memory ahead of disk
                work = RolloutRecord.from_dict(rec.to_dict())
                kept = work.plan.waves[: work.wave_index]
                for i, w in enumerate(fresh.waves):
                    w.index = work.wave_index + i
                work.plan = WavePlan(
                    waves=kept + fresh.waves, caps=fresh.caps,
                    packer=fresh.packer,
                    lanes_raced=fresh.lanes_raced,
                    winner_lane=fresh.winner_lane,
                )
                work.target = target.to_dict()
                work.plan_epoch = plan_epoch
                work.replans += 1
                done = not fresh.waves
                if done:
                    # the new plan IS the partially-moved truth: the
                    # event undid the remaining work (e.g. the target
                    # brokers failed) — the rollout completes here.
                    # (A regenerated canary keeps status "canary": it
                    # is re-emitted and re-verified against the new
                    # plan.)
                    work.status = "done"
                self._persist(work)
                self._records[cluster_id] = work
                if done:
                    self.registry.end_execution(cluster_id)
                    self._count(completed_total=1)
            self._count(replans_total=1)
            self._observe("replan", work, time.perf_counter() - t0,
                          plan_epoch=plan_epoch)
        except Exception as e:  # the solve path must never pay for this
            _olog.error("rollout_replan_failed", cluster=cluster_id,
                        error=repr(e)[:200])
