"""Streaming plan rollout (docs/ROLLOUT.md): turn a certified plan
into an executed, supervised reassignment.

- :mod:`waves` decomposes the move diff into bandwidth-budgeted waves
  (move-graph scheduling; no broker or rack exceeds a per-wave
  transfer cap).
- :mod:`state` is the epoch-fenced rollout record and its wave state
  machine (``planned -> canary -> advancing -> done | rolled_back``),
  persisted in the PR-7 plan store.
- :mod:`exec` drives the waves through the watch channel: each
  wave emits upstream-compatible reassignment JSON, canary
  verification gates advancement, rollback replays the inverse waves,
  and mid-rollout cluster events re-plan the REMAINING waves against
  the partially-moved ground truth.
"""

from .state import RolloutConflict, RolloutError, RolloutFenced, RolloutRecord
from .waves import Move, Wave, WaveCaps, WavePlan, moves_of, pack_waves

__all__ = [
    "Move", "Wave", "WaveCaps", "WavePlan", "moves_of", "pack_waves",
    "RolloutRecord", "RolloutError", "RolloutConflict", "RolloutFenced",
]
