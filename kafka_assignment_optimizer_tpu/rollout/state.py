"""Rollout records and the wave state machine (docs/ROLLOUT.md).

One :class:`RolloutRecord` per cluster, persisted in the PR-7 plan
store (``watch.store.PlanStore.save_rollout``: atomic write-rename +
fingerprint, kill-9-safe) so a restarted process resumes at the same
wave with the same epoch. The record is the single source of truth the
executor (:mod:`exec`) mutates under the cluster's rollout lock.

State machine::

    planned --start--> (record exists, nothing emitted)
    planned --advance--> canary      (wave 0 emitted, NOT applied)
    canary  --advance{canary_ok:true}--> advancing   (wave 0 applied)
    canary  --advance{canary_ok:false}--> rolled_back
    advancing --advance--> advancing ... --> done    (last wave applied)
    canary|advancing --pause--> paused --advance--> (resumes prior)
    any non-terminal --rollback--> rolled_back

Epoch fencing mirrors the watch channel's contract: every rollout
command carries a client ``epoch`` that must be STRICTLY greater than
the record's ``rollout_epoch``; a stale or replayed command raises
:class:`RolloutFenced` BEFORE any state change and provably without
touching the store. Rollout epochs are their own per-cluster monotone
sequence, independent of the cluster-event epochs.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .waves import WavePlan

__all__ = [
    "RolloutRecord", "RolloutError", "RolloutConflict", "RolloutFenced",
    "STATES", "TERMINAL", "COMMANDS",
]

STATES = ("planned", "canary", "advancing", "paused", "done",
          "rolled_back")
TERMINAL = frozenset({"done", "rolled_back"})
COMMANDS = ("start", "advance", "pause", "rollback")


class RolloutError(ValueError):
    """A malformed rollout command (missing/mistyped field) — the
    serve layer's 400."""


class RolloutConflict(Exception):
    """A well-formed command the current rollout state cannot accept
    (advance on a terminal rollout, start over an active one) — the
    serve layer's 409 ``bad_state``."""


class RolloutFenced(Exception):
    """A stale or replayed rollout epoch hit the fence: nothing was
    applied, nothing was persisted."""

    def __init__(self, cluster_id: str, got: int, current: int):
        super().__init__(
            f"rollout epoch {got} is not newer than cluster "
            f"{cluster_id!r}'s current rollout epoch {current}"
        )
        self.cluster_id = cluster_id
        self.got = got
        self.current = current


@dataclass
class RolloutRecord:
    """One cluster's rollout: the packed wave schedule, where it
    stands, and everything rollback needs (the pre-rollout base
    assignment, bit-exact)."""

    cluster_id: str
    rollout_epoch: int          # last accepted command epoch (fence)
    plan_epoch: int | None      # the watch plan this rollout executes
    status: str                 # one of STATES
    wave_index: int             # next wave to emit/apply
    plan: WavePlan              # the wave schedule (applied + remaining)
    base: dict                  # pre-rollout assignment (bit-exact)
    target: dict                # the certified plan being executed
    resumed_status: str | None = None   # what pause interrupted
    replans: int = 0            # mid-rollout re-plans of remaining waves
    applied: list[int] = field(default_factory=list)
    # the cluster generation this rollout was started against: a
    # re-bootstrap bumps it, and a rollout recorded against an older
    # generation refuses every further command (dead world)
    generation: int = 0
    # the rollout's ROOT trace ID (docs/OBSERVABILITY.md "Distributed
    # traces"): assigned at start, persisted with the record, and
    # linked from every transition trace AND every mid-rollout delta
    # re-solve trace (rollout_root attr) — the one ID a wave story
    # joins under
    trace_id: str | None = None

    @property
    def active(self) -> bool:
        return self.status not in TERMINAL

    @property
    def remaining(self) -> int:
        return max(len(self.plan.waves) - len(self.applied), 0)

    def require_status(self, *allowed: str) -> None:
        if self.status not in allowed:
            raise RolloutConflict(
                f"rollout for {self.cluster_id!r} is {self.status!r}; "
                f"this command needs one of {sorted(allowed)}"
            )

    def fence(self, epoch) -> int:
        """Validate + admit one command epoch (strictly monotone).
        Raises :class:`RolloutError` on a malformed epoch and
        :class:`RolloutFenced` on a stale one; the caller persists the
        record AFTER mutating it, so a fenced command provably never
        touches the store."""
        epoch = validate_epoch(epoch)
        if epoch <= self.rollout_epoch:
            raise RolloutFenced(self.cluster_id, epoch,
                                self.rollout_epoch)
        return epoch

    def to_dict(self) -> dict:
        return {
            "cluster_id": self.cluster_id,
            "rollout_epoch": self.rollout_epoch,
            "plan_epoch": self.plan_epoch,
            "status": self.status,
            "wave_index": self.wave_index,
            "plan": self.plan.to_dict(),
            "base": self.base,
            "target": self.target,
            "resumed_status": self.resumed_status,
            "replans": self.replans,
            "applied": list(self.applied),
            "generation": self.generation,
            "trace_id": self.trace_id,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "RolloutRecord":
        status = str(d["status"])
        if status not in STATES:
            raise ValueError(f"unknown rollout status {status!r}")
        return cls(
            cluster_id=str(d["cluster_id"]),
            rollout_epoch=int(d["rollout_epoch"]),
            plan_epoch=(None if d.get("plan_epoch") is None
                        else int(d["plan_epoch"])),
            status=status,
            wave_index=int(d["wave_index"]),
            plan=WavePlan.from_dict(d["plan"]),
            base=dict(d["base"]),
            target=dict(d["target"]),
            resumed_status=d.get("resumed_status"),
            replans=int(d.get("replans", 0)),
            applied=[int(i) for i in d.get("applied", [])],
            generation=int(d.get("generation", 0)),
            # absent on pre-ISSUE-15 records: the link simply reads
            # unassigned, never an error
            trace_id=(None if d.get("trace_id") is None
                      else str(d["trace_id"])),
        )


def validate_epoch(epoch) -> int:
    if isinstance(epoch, bool) or not isinstance(epoch, int) \
            or epoch < 0:
        raise RolloutError(
            "rollout commands need an 'epoch': a non-negative int, "
            "strictly greater than the rollout's current epoch"
        )
    return int(epoch)
