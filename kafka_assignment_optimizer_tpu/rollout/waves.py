"""Bandwidth-budgeted move waves (docs/ROLLOUT.md).

A certified plan is a *destination*; the cluster has to copy real data
to get there. This module decomposes the move diff between the current
assignment and the plan into ordered **waves** — partial reassignments
applied one at a time — such that within any single wave no broker and
no rack exceeds a per-wave transfer cap. Wave packing is itself an
assignment problem over the move graph (moves are nodes, shared
brokers/racks are capacity edges), the same structure the lane engine
scores as energies, so two packers share one accounting model:

- ``greedy`` — the host reference packer: first-fit-decreasing over the
  move list, deterministic, always available;
- ``scored`` — opt-in (``packer="scored"`` / ``KAO_ROLLOUT_PACKER``):
  races ``lanes`` diverse move orderings through the same first-fit
  core (the portfolio-lane idiom applied host-side) and keeps the
  packing minimizing ``makespan x peak per-wave cross-rack traffic``.
  Lane 0 is always the greedy order, so the scored packer can never do
  worse than the reference it replaces.

Transfer model (the bandwidth-cap contract, docs/ROLLOUT.md): one
**transfer unit** is one replica copy of one partition. A replica added
to broker ``b`` charges 1 inbound unit to ``b`` (and to ``b``'s rack)
and 1 outbound unit to the move's **source** — the partition's current
leader, which streams the copy. A partition with an empty current
replica list (declared but never placed: ``partition_growth``) has no
source; its initial copies charge inbound only. Replica removals and
leader-only changes are metadata, zero units. Broker load is
``inbound + outbound`` (NICs are full-duplex but the replication
fetcher pool is not); rack load counts inbound units only.

Caps are **fields of the plan** (:class:`WaveCaps`), never module
constants: every wave records the caps it was packed under, and a cap
below the largest single move's own demand is raised to it (recorded
as ``raised``) — a single partition's copy can never be split across
waves.

Within a wave, moves that change the partition's leader are ordered
LAST: the data copies land first, leadership flips at the tail, so a
wave aborted midway has moved bytes but not traffic leadership.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np

from ..models.cluster import Assignment, Topology

__all__ = [
    "Move", "Wave", "WaveCaps", "WavePlan", "moves_of", "pack_waves",
    "DEFAULT_BROKER_CAP", "DEFAULT_RACK_CAP", "DEFAULT_LANES",
]

DEFAULT_BROKER_CAP = 4
DEFAULT_RACK_CAP = 16
DEFAULT_LANES = 8


@dataclass(frozen=True)
class Move:
    """One partition's transition from its current replica list to the
    plan's. ``adds`` are the replica copies the cluster must stream
    (the transfer units); ``source`` is the current leader that streams
    them (None for an initial placement)."""

    topic: str
    partition: int
    old: tuple[int, ...]
    new: tuple[int, ...]
    adds: tuple[int, ...]
    source: int | None
    leader_changed: bool

    @property
    def cost(self) -> int:
        return len(self.adds)

    def to_dict(self) -> dict:
        return {
            "topic": self.topic, "partition": self.partition,
            "old": list(self.old), "new": list(self.new),
            "adds": list(self.adds), "source": self.source,
            "leader_changed": self.leader_changed,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Move":
        return cls(
            topic=str(d["topic"]), partition=int(d["partition"]),
            old=tuple(int(b) for b in d["old"]),
            new=tuple(int(b) for b in d["new"]),
            adds=tuple(int(b) for b in d["adds"]),
            source=(None if d.get("source") is None
                    else int(d["source"])),
            leader_changed=bool(d["leader_changed"]),
        )


@dataclass(frozen=True)
class WaveCaps:
    """Per-wave transfer caps, in transfer units (replica copies).
    Carried as plan fields so every wave records the contract it was
    packed under; ``raised`` notes the caps were lifted to admit the
    largest single move."""

    broker: int = DEFAULT_BROKER_CAP
    rack: int = DEFAULT_RACK_CAP
    raised: bool = False

    def to_dict(self) -> dict:
        return {"broker": self.broker, "rack": self.rack,
                "raised": self.raised}

    @classmethod
    def from_dict(cls, d: dict) -> "WaveCaps":
        return cls(broker=int(d["broker"]), rack=int(d["rack"]),
                   raised=bool(d.get("raised", False)))


@dataclass
class Wave:
    """One wave: the moves it applies (data moves first, leader-
    changing moves last) and its transfer accounting."""

    index: int
    moves: list[Move] = field(default_factory=list)
    broker_load: dict[int, int] = field(default_factory=dict)
    rack_load: dict[str, int] = field(default_factory=dict)
    cross_rack: int = 0

    @property
    def peak_broker(self) -> int:
        return max(self.broker_load.values(), default=0)

    @property
    def peak_rack(self) -> int:
        return max(self.rack_load.values(), default=0)

    @property
    def data_units(self) -> int:
        return sum(m.cost for m in self.moves)

    def ordered_moves(self) -> list[Move]:
        """Leader moves LAST within the wave (stable otherwise)."""
        return sorted(self.moves,
                      key=lambda m: (bool(m.leader_changed),))

    def targets(self) -> list[tuple[str, int, list[int]]]:
        """(topic, partition, target replicas) in emission order."""
        return [(m.topic, m.partition, list(m.new))
                for m in self.ordered_moves()]

    def to_dict(self) -> dict:
        return {
            "index": self.index,
            "moves": [m.to_dict() for m in self.ordered_moves()],
            "broker_load": {str(b): n
                            for b, n in sorted(self.broker_load.items())},
            "rack_load": dict(sorted(self.rack_load.items())),
            "cross_rack": self.cross_rack,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Wave":
        return cls(
            index=int(d["index"]),
            moves=[Move.from_dict(m) for m in d["moves"]],
            broker_load={int(b): int(n)
                         for b, n in d.get("broker_load", {}).items()},
            rack_load={str(r): int(n)
                       for r, n in d.get("rack_load", {}).items()},
            cross_rack=int(d.get("cross_rack", 0)),
        )


@dataclass
class WavePlan:
    """The packed schedule: waves in application order, the caps they
    honor, and the packer's provenance."""

    waves: list[Wave]
    caps: WaveCaps
    packer: str = "greedy"
    lanes_raced: int = 1
    winner_lane: int = 0

    @property
    def makespan(self) -> int:
        return len(self.waves)

    @property
    def peak_broker(self) -> int:
        return max((w.peak_broker for w in self.waves), default=0)

    @property
    def peak_rack(self) -> int:
        return max((w.peak_rack for w in self.waves), default=0)

    @property
    def peak_cross_rack(self) -> int:
        return max((w.cross_rack for w in self.waves), default=0)

    @property
    def score(self) -> int:
        """makespan x peak per-wave cross-rack traffic (the scored
        packer's objective; total cross-rack units are invariant to the
        packing — the PEAK is what saturates inter-rack links)."""
        return self.makespan * max(self.peak_cross_rack, 1)

    def to_dict(self) -> dict:
        return {
            "waves": [w.to_dict() for w in self.waves],
            "caps": self.caps.to_dict(),
            "packer": self.packer,
            "lanes_raced": self.lanes_raced,
            "winner_lane": self.winner_lane,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "WavePlan":
        return cls(
            waves=[Wave.from_dict(w) for w in d["waves"]],
            caps=WaveCaps.from_dict(d["caps"]),
            packer=str(d.get("packer", "greedy")),
            lanes_raced=int(d.get("lanes_raced", 1)),
            winner_lane=int(d.get("winner_lane", 0)),
        )


def moves_of(current: Assignment, target: Assignment) -> list[Move]:
    """The move list between two assignments, sorted by key. Partitions
    only the plan knows are initial placements (empty ``old``);
    partitions only the current assignment knows are left alone (the
    plan does not speak for them)."""
    cur_by = current.by_key()
    out: list[Move] = []
    for p in sorted(target.partitions, key=lambda x: (x.topic, x.partition)):
        olds = cur_by.get(p.key)
        old = tuple(olds.replicas) if olds else ()
        new = tuple(p.replicas)
        if old == new:
            continue
        adds = tuple(b for b in new if b not in set(old))
        out.append(Move(
            topic=p.topic, partition=p.partition, old=old, new=new,
            adds=adds, source=(old[0] if old else None),
            leader_changed=bool(old and new and old[0] != new[0]),
        ))
    return out


def _move_demand(m: Move, rack_of) -> tuple[dict, dict, int]:
    """One move's own (broker_load, rack_load, cross_rack) demand."""
    bl: dict[int, int] = {}
    rl: dict[str, int] = {}
    cross = 0
    for b in m.adds:
        bl[b] = bl.get(b, 0) + 1
        r = rack_of(b)
        rl[r] = rl.get(r, 0) + 1
        if m.source is not None:
            bl[m.source] = bl.get(m.source, 0) + 1
            if rack_of(m.source) != r:
                cross += 1
    return bl, rl, cross


def _fits(wave: Wave, bl: dict, rl: dict, caps: WaveCaps) -> bool:
    return all(
        wave.broker_load.get(b, 0) + n <= caps.broker
        for b, n in bl.items()
    ) and all(
        wave.rack_load.get(r, 0) + n <= caps.rack for r, n in rl.items()
    )


def _first_fit(moves: list[Move], caps: WaveCaps, rack_of) -> list[Wave]:
    """First-fit over ``moves`` in the given order: each data move
    lands in the earliest wave whose caps still admit its demand.
    Zero-cost (leader-only / remove-only) moves ride the LAST wave —
    they are metadata and must not open waves of their own."""
    waves: list[Wave] = []
    meta: list[Move] = []
    for m in moves:
        bl, rl, cross = _move_demand(m, rack_of)
        if not bl:
            meta.append(m)
            continue
        placed = False
        for w in waves:
            if _fits(w, bl, rl, caps):
                placed = True
                break
        if not placed:
            w = Wave(index=len(waves))
            waves.append(w)
        w.moves.append(m)
        for b, n in bl.items():
            w.broker_load[b] = w.broker_load.get(b, 0) + n
        for r, n in rl.items():
            w.rack_load[r] = w.rack_load.get(r, 0) + n
        w.cross_rack += cross
    if meta:
        if not waves:
            waves.append(Wave(index=0))
        waves[-1].moves.extend(meta)
    return waves


def _orderings(moves: list[Move], lanes: int, seed: int,
               rack_of) -> list[tuple[str, list[Move]]]:
    """The scored packer's lane orderings. Lane 0 is the greedy
    reference order (cost-descending first fit), so the race can never
    lose to the packer it replaces; the rest spread sources, front-load
    cross-rack copies, and explore seeded shuffles."""
    idx = list(range(len(moves)))
    ffd = sorted(idx, key=lambda i: (-moves[i].cost, moves[i].topic,
                                     moves[i].partition))
    lanes_out: list[tuple[str, list[Move]]] = [
        ("greedy", [moves[i] for i in ffd]),
    ]
    if lanes > 1:
        cross_first = sorted(idx, key=lambda i: (
            -_move_demand(moves[i], rack_of)[2], -moves[i].cost,
            moves[i].topic, moves[i].partition,
        ))
        lanes_out.append(("cross_first", [moves[i] for i in cross_first]))
    if lanes > 2:
        # round-robin over source brokers: consecutive moves never
        # share a source, so first fit spreads outbound load
        by_src: dict = {}
        for i in ffd:
            by_src.setdefault(moves[i].source, []).append(i)
        rr: list[int] = []
        queues = [by_src[k] for k in sorted(
            by_src, key=lambda s: (s is None, s))]
        while queues:
            nxt = []
            for q in queues:
                rr.append(q.pop(0))
                if q:
                    nxt.append(q)
            queues = nxt
        lanes_out.append(("source_rr", [moves[i] for i in rr]))
    rng = np.random.default_rng(seed)
    for j in range(len(lanes_out), lanes):
        perm = rng.permutation(len(moves))
        lanes_out.append((f"shuffle{j}", [moves[i] for i in perm]))
    return lanes_out[:max(lanes, 1)]


def _effective_caps(moves: list[Move], caps: WaveCaps,
                    rack_of) -> WaveCaps:
    """Caps below the largest single move's own demand are raised to it
    — a single partition's copy cannot be split across waves, so the
    floor is the packing's feasibility condition."""
    need_b = need_r = 0
    for m in moves:
        bl, rl, _ = _move_demand(m, rack_of)
        need_b = max(need_b, max(bl.values(), default=0))
        need_r = max(need_r, max(rl.values(), default=0))
    b = max(int(caps.broker), 1)
    r = max(int(caps.rack), 1)
    if need_b > b or need_r > r:
        return WaveCaps(broker=max(b, need_b), rack=max(r, need_r),
                        raised=True)
    return WaveCaps(broker=b, rack=r, raised=False)


def pack_waves(current: Assignment, target: Assignment,
               topology: Topology | None = None, *,
               caps: WaveCaps | None = None,
               packer: str | None = None,
               lanes: int = DEFAULT_LANES,
               seed: int = 0,
               budget=None) -> WavePlan:
    """Decompose ``current -> target`` into a capped wave schedule.

    ``packer``: ``"greedy"`` (default) or ``"scored"`` (opt-in, also
    via ``KAO_ROLLOUT_PACKER``). ``budget`` is an optional
    :class:`~..resilience.budget.Budget`: the scored race stops early
    when it expires, keeping the best candidate packed so far (lane 0
    — the greedy reference — always completes)."""
    caps = caps or WaveCaps()
    packer = packer or os.environ.get("KAO_ROLLOUT_PACKER") or "greedy"
    if packer not in ("greedy", "scored"):
        raise ValueError(
            f"unknown wave packer {packer!r}; want 'greedy' or 'scored'"
        )
    rack_of = (topology.rack if topology is not None
               else (lambda b: "r0"))
    moves = moves_of(current, target)
    eff = _effective_caps(moves, caps, rack_of)
    if not moves:
        return WavePlan(waves=[], caps=eff, packer=packer)
    if packer == "greedy":
        order = sorted(moves, key=lambda m: (-m.cost, m.topic,
                                             m.partition))
        return WavePlan(waves=_first_fit(order, eff, rack_of), caps=eff,
                        packer="greedy")
    best: WavePlan | None = None
    orderings = _orderings(moves, max(int(lanes), 1), seed, rack_of)
    for lane, (label, order) in enumerate(orderings):
        cand = WavePlan(waves=_first_fit(order, eff, rack_of), caps=eff,
                        packer="scored", lanes_raced=len(orderings),
                        winner_lane=lane)
        if best is None or cand.score < best.score:
            best = cand
        if lane > 0 and budget is not None:
            left = budget.remaining()
            if left is not None and left <= 0.0:
                break  # keep the best candidate packed so far
    return best


def verify_caps(plan: WavePlan) -> bool:
    """Every wave within the plan's caps (the invariant tests assert
    straight off the move graph)."""
    return all(
        w.peak_broker <= plan.caps.broker
        and w.peak_rack <= plan.caps.rack
        for w in plan.waves
    )
