"""Bound ladder + flow/LP machinery for :class:`ProblemInstance`.

Moved out of ``models.instance`` in r5 (VERDICT r4 item 7): the data
model keeps thin delegating methods with an unchanged public surface —
callers (and tests that monkeypatch ``ProblemInstance`` attributes)
see no difference — while the certificate machinery lives here:

- the weight upper-bound ladder (levels 0-2: kept-replica max-flow,
  leader-cap LP/flow tiers, the symmetry-aggregated LP/MILP) feeding
  ``certify_optimal`` (docs/OPTIMALITY.md has the soundness argument);
- the exact move lower bound (``move_lower_bound_exact``);
- the partition-symmetry aggregation (``_member_classes``) that keeps
  every tier tractable at 50k partitions.

Every function takes the instance as its first argument and stores its
memos on the instance (under ``inst._memo_lock()``), exactly as the
methods did; cross-calls go through ``inst.<method>`` so class-level
monkeypatching keeps intercepting them.
"""

from __future__ import annotations

import logging

import numpy as np

from . import instance as _inst_mod

_log = logging.getLogger(__name__)

def _safe_floor_ub(neg_fun: float) -> int:
    """Floor an LP maximum into a still-valid integer upper bound.

    The slack must dominate the solver's possible objective undershoot
    (termination tolerances are RELATIVE, so a fixed absolute epsilon
    fails at large objective scales); 1e-6 relative can at worst loosen
    a razor-edge bound by 1, never tighten it below the true optimum."""
    v = -neg_fun
    return int(np.floor(v + 1e-6 * max(1.0, abs(v))))


def _dual_repair_max_ub(c, a_ub, b_ub, a_eq, b_eq, lo, hi, res):
    """Certified upper bound on ``max -c'x`` from an (approximate) LP
    solve, via dual-feasibility repair — sound even when the primal
    iterate undershoots the true optimum (e.g. ``highs-ipm`` without
    crossover, whose termination tolerance is all that protects the
    primal value).

    Takes the solver's constraint marginals as a *starting point* for
    the dual (lam = -ineq marginals clamped >= 0, mu = -eq marginals),
    then restores exact dual stationarity by absorbing the residual
    ``r = c + A_ub' lam + A_eq' mu`` into the variable-bound duals
    (alpha = max(r, 0) on x >= lo, beta = max(-r, 0) on x <= hi). Any
    such (lam, mu, alpha, beta) is dual feasible, so by weak duality

        min c'x  >=  -lam'b_ub - mu'b_eq + alpha'lo - beta'hi

    and ``max -c'x <= -that``. Returns the float bound, or None when
    the solve carried no marginals (then the caller falls back to the
    primal value, which is exact for simplex/crossover methods)."""
    try:
        m_ub = getattr(res.ineqlin, "marginals", None)
        m_eq = getattr(res.eqlin, "marginals", None)
        if m_ub is None or m_eq is None:
            return None
        lam = np.maximum(-np.asarray(m_ub, dtype=np.float64), 0.0)
        mu = -np.asarray(m_eq, dtype=np.float64)
        r = np.asarray(c, dtype=np.float64)
        if lam.size:
            r = r + a_ub.T @ lam
        if mu.size:
            r = r + a_eq.T @ mu
        alpha = np.maximum(r, 0.0)
        beta = np.maximum(-r, 0.0)
        dual = (
            -(lam @ b_ub if lam.size else 0.0)
            - (mu @ b_eq if mu.size else 0.0)
            + alpha @ lo
            - beta @ hi
        )
        return float(-dual)
    except Exception:
        return None


def _leader_vals(inst):
    """Per-(partition, candidate-leader) optimum of the preservation
    weight, vectorized on a padded sparse member view. Returns
    ``(val [P, M], s_rm1 [P], ids [P, M])`` — ``val[p, m]`` is the
    best weight of partition p when member ``ids[p, m]`` leads (its
    leader weight plus the best rf-1 positive follower weights among
    the rest), ``s_rm1`` the best weight under a non-member (zero
    weight) leader, padding columns carry ids of -1 and val ==
    s_rm1. None when no weights exist at all."""
    P, B = inst.num_parts, inst.num_brokers
    if P == 0:
        return None
    wl_full = inst.w_leader[:, :B]
    wf_full = inst.w_follower[:, :B]
    # weights are sparse (only current members carry any): gather the
    # nonzero (partition, broker) pairs into a padded [P, M] view so
    # the per-leader formula runs on M ~ rf columns, not B
    rows, cols = np.nonzero((wl_full > 0) | (wf_full > 0))
    if rows.size == 0:
        return None
    cnt = np.bincount(rows, minlength=P)
    M = int(cnt.max())
    offs = np.zeros(P + 1, np.int64)
    np.cumsum(cnt, out=offs[1:])
    pos = np.arange(rows.size) - offs[rows]  # rank within its row
    wl = np.zeros((P, M), np.int64)
    wf = np.zeros((P, M), np.int64)
    ids = np.full((P, M), -1, np.int64)
    wl[rows, pos] = wl_full[rows, cols]
    wf[rows, pos] = np.maximum(wf_full[rows, cols], 0)
    ids[rows, pos] = cols
    rf = inst.rf.astype(np.int64)
    k = M
    top = -np.sort(-wf, axis=1)  # [P, M] desc
    csum = np.concatenate(
        [np.zeros((P, 1), np.int64), np.cumsum(top, axis=1)], axis=1
    )
    prow = np.arange(P)
    s_rm1 = csum[prow, np.minimum(rf - 1, k)]  # sum of top rf-1
    # with v_1 >= v_2 >= ... the clipped-positive follower weights and
    # s_k their prefix sums, leader m scores wl[m] + (s_{rf-1} - v(m)
    # + v_rf if v(m) >= v_{rf-1} else s_{rf-1}) — removing one
    # instance of m's follower value from the top set and backfilling
    # with the next-best; only values matter, so ties need no
    # identity tracking. v_edge = v_{rf-1} (the weakest kept
    # follower), v_next = v_rf (the backfill).
    v_edge = top[prow, np.clip(rf - 2, 0, k - 1)]
    v_next = np.where(
        rf - 1 < k, top[prow, np.clip(rf - 1, 0, k - 1)], 0
    )
    in_top = (wf >= v_edge[:, None]) & (rf[:, None] >= 2)
    foll_sum = np.where(
        in_top,
        s_rm1[:, None] - wf + v_next[:, None],
        s_rm1[:, None],
    )
    return wl + foll_sum, s_rm1, ids


def weight_upper_bound(inst, tight: bool = False, level: int = 0
                       ) -> int:
    """A constraint-aware upper bound on any feasible plan's
    preservation weight — ``max_weight`` tightened by the balance
    constraints that couple partitions through the objective.

    Leveled by cost, each level memoized, callers escalate only
    when the cheaper level fails to certify:

    - level 0 (``tight=False``, cheap): ``max_weight`` refined by
      the leader-cap transportation LP — leadership gains under the
      per-broker ``leader_hi`` cap (integral polytope, HiGHS via
      scipy, ~1 s at 10k partitions). Tight whenever lower bands
      and follower caps don't bind (demo, decommission, rf_change).
    - level 1: the same LP with per-broker zero-gain-lead slacks,
      the leader band's LOWER side, and the total-leads equality —
      needed when under-leading brokers are FORCED to take
      leaderships (leader-skew rebalances).
    - level 2 (``tight=True``): the joint kept-replica LP
      (``_kept_weight_lp``), which also bands follower keeps and
      forced new replicas per broker/rack — needed when brokers are
      over-full (scale-out). Seconds at 10k partitions, so only on
      explicit request (the engine runs it on a worker thread).
      Past ~60k members the unaggregated LP is intractable (the
      50k-partition jumbo times it out at 900 s) and the tier
      switches to the SYMMETRY-AGGREGATED formulation
      (``_kept_weight_agg``) — the exact same LP optimum at
      ~#classes/#partitions of the cost.
    - level 3: the aggregated kept-replica MILP's branch-and-bound
      dual bound (``_kept_weight_agg(integer=True)``) — integer
      aggregation is a valid relaxation of the true MILP, so this
      can only tighten level 2; time-limited, any size with few
      classes.

    ``certify_optimal`` escalates 0 -> 1 -> 2 -> 3.

    Thread-safe: the tier ladder runs under a per-instance lock
    (the engine prefetches bounds on worker threads while the main
    thread certifies — without the lock both would solve the same
    multi-second LPs). A caller that no longer needs tighter tiers
    (a finished solve with straggling workers) sets
    ``_bounds_cancelled``; not-yet-memoized tiers are then skipped
    WITHOUT memoizing, so the cancellation can never poison a later
    legitimate escalation."""
    level = 2 if tight else level
    with inst._memo_lock():
        memo = getattr(inst, "_wub_memo", None)
        if memo is None:
            memo = {}
            inst._wub_memo = memo
        if 0 not in memo:
            lead = inst._leader_cap_lp(with_lower=False)
            mw = inst.max_weight()
            memo[0] = mw if lead is None else min(mw, lead)
        # LP cost grows superlinearly in member count; past the
        # aggregation threshold the level-1 LP sticks with the
        # cheaper bound and level 2 switches to the aggregated
        # formulation (exact; see _kept_weight_agg). Level 2 also
        # prefers the aggregated LP whenever symmetry is effective
        # (generated and steady-state round-robin clusters): same
        # bound or tighter, at a fraction of the unaggregated cost.
        big = (
            level >= 1
            and inst._members()[0].size > _inst_mod.AGG_MEMBER_THRESHOLD
        )
        if level >= 1 and 1 not in memo:
            if getattr(inst, "_bounds_cancelled", False):
                return memo[0]
            # past the threshold the scipy LP is off the table, but
            # the r4 flow fast path stays cheap at any size — so
            # big instances attempt level 1 flow-only instead of
            # skipping the tier outright
            lead = inst._leader_cap_lp(with_lower=True,
                                       flow_only=big)
            memo[1] = memo[0] if lead is None else min(memo[0], lead)
        if level >= 2 and 2 not in memo:
            if getattr(inst, "_bounds_cancelled", False):
                return memo[1]
            kept = (
                inst._kept_weight_agg()
                if big or inst.agg_effective() else None
            )
            if kept is None and not big:
                # aggregation unavailable or refused (solver
                # failure, deadline): the unaggregated LP is still
                # tractable here — don't silently degrade the
                # certificate to the level-1 bound
                kept = inst._kept_weight_lp()
            memo[2] = memo[1] if kept is None else min(memo[1], kept)
        if level >= 3 and 3 not in memo:
            if getattr(inst, "_bounds_cancelled", False):
                return memo[2]
            kept = inst._kept_weight_agg(integer=True)
            memo[3] = memo[2] if kept is None else min(memo[2], kept)
        return memo[level]


def move_lower_bound_exact(inst) -> int:
    """Max-flow sharpening of ``move_lower_bound``: moves >=
    total_replicas - maxflow, where the flow network models the kept
    caps JOINTLY (the counting bound takes their min):

        source -(rf_p)-> partition -(part_rack_hi_p)-> (p, rack)
               -(1 per member)-> broker -(broker_hi)-> rack
               -(rack_hi_k)-> sink

    Max integral flow == the most slots ANY feasible plan can keep.
    Never weaker than ``move_lower_bound``; memoized; milliseconds
    even at 50k partitions (scipy's C Dinic)."""
    cached = getattr(inst, "_move_lb_memo", None)
    if cached is None:
        kept = inst._kept_maxflow()
        cheap = inst.move_lower_bound()
        cached = cheap if kept is None else max(
            cheap, inst.total_replicas - kept
        )
        inst._move_lb_memo = cached
    return cached


def _kept_maxflow(inst) -> int | None:
    """Max number of kept slots over all feasible plans (see
    ``move_lower_bound_exact``)."""
    try:
        import scipy.sparse as sp
        from scipy.sparse.csgraph import maximum_flow
    except Exception:
        return None
    mrows, mcols = inst._members()
    n = mrows.size
    if n == 0:
        return 0
    try:
        B, K, P = inst.num_brokers, inst.num_racks, inst.num_parts
        rack = inst.rack_of_broker[mcols].astype(np.int64)
        pair_key = mrows.astype(np.int64) * K + rack
        pairs, pair_idx = np.unique(pair_key, return_inverse=True)
        U = pairs.size
        # node ids: 0 source | 1..P parts | pairs | brokers | racks | sink
        o_part, o_pair = 1, 1 + P
        o_brok, o_rack = 1 + P + U, 1 + P + U + B
        t = o_rack + K
        live = np.flatnonzero(inst.rf > 0)
        src = np.concatenate([
            np.zeros(live.size, np.int64),       # s -> p
            o_part + pairs // K,                 # p -> (p,k)
            o_pair + pair_idx,                   # (p,k) -> b
            np.full(B, 0) + o_brok + np.arange(B),  # b -> rack
            o_rack + np.arange(K),               # rack -> t
        ])
        dst = np.concatenate([
            o_part + live,
            o_pair + np.arange(U),
            o_brok + mcols,
            o_rack + inst.rack_of_broker[:B].astype(np.int64),
            np.full(K, t),
        ])
        cap = np.concatenate([
            inst.rf[live].astype(np.int64),
            inst.part_rack_hi[(pairs // K)].astype(np.int64),
            np.ones(n, np.int64),
            np.full(B, int(inst.broker_hi), np.int64),
            inst.rack_hi.astype(np.int64),
        ])
        g = sp.csr_matrix(
            (cap.astype(np.int32), (src, dst)), shape=(t + 1, t + 1)
        )
        return int(maximum_flow(g, 0, t).flow_value)
    except Exception:
        return None


def _flow_prologue(inst, gain, rows, cols, ids):
    """Shared guards + arc extraction for the leader-bound flow
    fast paths. Returns ``(mcmf, g_int, b_of, nP, pidx)`` or None
    when the native kernel is unavailable, the bounds deadline is
    spent, or the gains are non-integral — callers fall back to
    the scipy LP in every case."""
    try:
        from ..native import mcmf
    except Exception:
        return None
    if inst._lp_options() is None:  # bounds deadline already spent
        return None
    g = gain[rows, cols]
    g_int = np.asarray(g, np.int64)
    if not np.array_equal(g_int, g):
        return None
    b_of = ids[rows, cols].astype(np.int64)
    up, pidx = np.unique(rows, return_inverse=True)
    return mcmf, g_int, b_of, up.size, pidx


def _leader_cap_flow(inst, gain, rows, cols, ids, base) -> int | None:
    """Exact cap-only leader bound on the native min-cost-flow
    kernel (the fast path of ``_leader_cap_lp``): the transportation
    polytope is integral, so integer flows reach the identical LP
    optimum. Returns None (caller falls back to the LP) when the
    shared prologue declines."""
    pro = inst._flow_prologue(gain, rows, cols, ids)
    if pro is None:
        return None
    mcmf, g_int, b_of, nP, pidx = pro
    ub, bidx = np.unique(b_of, return_inverse=True)
    nB, n = ub.size, rows.size
    o_b = 1 + nP
    t = o_b + nB
    src = np.concatenate([
        np.zeros(nP, np.int64),      # s -> p
        1 + pidx,                    # p -> broker (gain arcs)
        1 + np.arange(nP),           # p -> t (zero-cost disposal)
        o_b + np.arange(nB),         # broker -> t
    ])
    dst = np.concatenate([
        1 + np.arange(nP),
        o_b + bidx,
        np.full(nP, t, np.int64),
        np.full(nB, t, np.int64),
    ])
    cap = np.concatenate([
        np.ones(nP, np.int64),
        np.ones(n, np.int64),
        np.ones(nP, np.int64),
        np.full(nB, int(inst.leader_hi), np.int64),
    ])
    cost = np.concatenate([
        np.zeros(nP, np.int64),
        -g_int,
        np.zeros(nP, np.int64),
        np.zeros(nB, np.int64),
    ])
    try:
        _f, c, _af = mcmf(src, dst, cap, cost, 0, t, t + 1)
    except Exception:
        return None
    return base + int(-c)


def _leader_cap_flow_lower(inst, gain, rows, cols, ids, base,
                           p_active) -> int | None:
    """Exact LEVEL-1 leader bound on the native min-cost-flow
    kernel (the fast path of ``_leader_cap_lp(with_lower=True)``).
    The slack formulation is still a network: the per-broker
    zero-gain lead slack y_b is a POOL node any partition (or the
    source directly, for partitions with no gainful arc) can dump
    into and that feeds every broker at cost 0; the leader band's
    lower side becomes a rewarded broker->sink arc of capacity
    ``leader_lo`` at cost -BIG (BIG > total possible gain, so
    floors fill with absolute priority), the upper side the
    residual ``leader_hi - leader_lo`` at cost 0; the total-leads
    equality is the forced max flow of exactly ``p_active``. The
    polytope is integral, so the integer flow optimum IS the LP
    optimum — with none of the IPM-undershoot dual-repair the LP
    path needs. Returns None (caller falls back to the LP) when
    the shared prologue declines, the flow comes up short of
    ``p_active``, or any floor arc goes unsaturated
    (band-infeasible: the LP verdict decides)."""
    pro = inst._flow_prologue(gain, rows, cols, ids)
    if pro is None:
        return None
    mcmf, g_int, b_of, nP, pidx = pro
    B = inst.num_brokers
    lo_b = int(inst.leader_lo)
    hi_b = int(inst.leader_hi)
    big = int(g_int.sum()) + 1
    if big > np.iinfo(np.int32).max:
        # the floor-priority cost -BIG would overflow the kernel's
        # int32 arc costs; the wrapper would raise, the except
        # below would swallow it, and past the flow_only threshold
        # the level-1 tier would SILENTLY degrade to the weaker
        # level-0 bound. Decline loudly instead (ADVICE r4): count
        # it on the instance and log, so a tightness loss at scale
        # is visible in telemetry rather than inferred from bounds.
        inst._flow_big_declines = getattr(
            inst, "_flow_big_declines", 0
        ) + 1
        _log.debug(
            "leader-cap flow bound declined: BIG=%d exceeds int32 "
            "arc-cost range (falling back to the LP tier)", big,
        )
        return None
    n = rows.size
    o_pool = 1 + nP
    o_b = o_pool + 1
    t = o_b + B
    rest = int(p_active) - nP  # partitions with no gainful arc
    if rest < 0:
        return None  # inconsistent inputs; let the LP decide
    src = np.concatenate([
        np.zeros(nP, np.int64),          # s -> p
        1 + pidx,                        # p -> broker (gain arcs)
        1 + np.arange(nP),               # p -> pool (zero-gain)
        np.zeros(1, np.int64),           # s -> pool (gainless parts)
        np.full(B, o_pool, np.int64),    # pool -> broker
        o_b + np.arange(B),              # broker -> t (floor, -BIG)
        o_b + np.arange(B),              # broker -> t (residual)
    ])
    dst = np.concatenate([
        1 + np.arange(nP),
        o_b + b_of,
        np.full(nP, o_pool, np.int64),
        np.full(1, o_pool, np.int64),
        o_b + np.arange(B),
        np.full(B, t, np.int64),
        np.full(B, t, np.int64),
    ])
    cap = np.concatenate([
        np.ones(nP, np.int64),
        np.ones(n, np.int64),
        np.ones(nP, np.int64),
        np.full(1, rest, np.int64),
        np.full(B, int(p_active), np.int64),
        np.full(B, lo_b, np.int64),
        np.full(B, hi_b - lo_b, np.int64),
    ])
    cost = np.concatenate([
        np.zeros(nP, np.int64),
        -g_int,
        np.zeros(nP, np.int64),
        np.zeros(1, np.int64),
        np.zeros(B, np.int64),
        np.full(B, -big, np.int64),
        np.zeros(B, np.int64),
    ])
    try:
        f, c, af = mcmf(src, dst, cap, cost, 0, t, t + 1)
    except Exception:
        return None
    if f != int(p_active):
        return None  # band-infeasible or degenerate: LP decides
    floor_arcs = af[nP + n + nP + 1 + B:nP + n + nP + 1 + 2 * B]
    filled = int(floor_arcs.sum())
    if filled != B * lo_b:
        return None  # a floor went unmet: LP decides
    return base + int(-(c + big * filled))


def _leader_cap_lp(inst, with_lower: bool = False,
                   flow_only: bool = False) -> int | None:
    """max_weight with the per-broker leadership constraints modeled
    exactly. Each partition either hands leadership to a member m
    (gain = val[p,m] - s_rm1 over the non-member-leader optimum) or
    to some zero-gain leader; each broker accepts at most
    ``leader_hi`` — a transportation LP (integral).

    ``with_lower`` additionally introduces per-broker slack
    variables y_b counting the zero-gain leads, the band's LOWER
    side, and the total-leads equality. The lower band matters for
    leader-skew rebalances: under-leading brokers are FORCED to
    take leaderships away from gainful keeps, a loss the cap-only
    model cannot see — but the slack formulation solves ~3x slower,
    so it is a separate, lazier bound level.

    ``flow_only`` skips the scipy-LP fallback when the native flow
    fast path declines — for instances past the aggregation
    threshold, where the LP would grind for minutes but the flow
    stays sub-second at any size."""
    r = inst._leader_vals()
    if r is None:
        return 0
    val, s_rm1, ids = r
    active = inst.rf > 0
    p_active = int(active.sum())
    base = int(s_rm1[active].sum())
    gain = np.where(
        (ids >= 0) & active[:, None],
        np.maximum(val - s_rm1[:, None], 0), 0,
    )
    rows, cols = np.nonzero(gain > 0)
    if rows.size == 0:
        return base
    if inst.leader_hi <= 0:
        return base
    if not with_lower:
        # the cap-only model is a pure transportation problem:
        # source -> partition (cap 1) -> gainful member's broker
        # (cost -gain) -> sink (cap leader_hi), plus a zero-cost
        # partition -> sink disposal arc so the forced max flow
        # never routes a positive-cost path. Integer flows solve
        # the SAME integral polytope the LP does, on the native
        # min-cost-flow kernel — 5.3 s of HiGHS IPM -> ~0.3 s at
        # the 50k-partition adv50k size (measured r4), and this
        # bound sits on the certificate critical path of every
        # annealed solve. The LP below stays as the fallback.
        b = inst._leader_cap_flow(gain, rows, cols, ids, base)
        if b is not None:
            return b
    else:
        # the slack formulation is a network too (pool node +
        # floor-priority arcs); same exactness argument, ~25x the
        # LP's speed at 50k partitions
        b = inst._leader_cap_flow_lower(
            gain, rows, cols, ids, base, p_active
        )
        if b is not None:
            return b
    if flow_only:
        return None  # caller ruled the scipy LP out at this size
    try:
        import scipy.sparse as sp
        from scipy.optimize import linprog

        B = inst.num_brokers
        g = gain[rows, cols].astype(np.float64)
        b_of = ids[rows, cols]
        n = rows.size
        var = np.arange(n)
        opts = inst._lp_options()
        if opts is None:  # bounds deadline already spent
            return None
        per_part = sp.csr_matrix(  # one leading member each
            (np.ones(n), (rows, var)), shape=(inst.num_parts, n)
        )
        cap = sp.csr_matrix((np.ones(n), (b_of, var)), shape=(B, n))
        if not with_lower:
            c = -g
            a_ub = sp.vstack([per_part, cap], format="csr")
            b_ub = np.concatenate(
                [np.ones(inst.num_parts),
                 np.full(B, float(inst.leader_hi))]
            )
            a_eq, b_eq = None, None
            lo, hi = np.zeros(n), np.ones(n)
            res = linprog(
                c, A_ub=a_ub, b_ub=b_ub, bounds=(0, 1),
                method="highs-ipm", options=opts,
            )
        else:
            # columns: x (gainful member leads) then y (per-broker
            # zero-gain lead slack)
            led_of_b = sp.hstack(
                [cap, sp.eye(B, format="csr")], format="csr"
            )
            a_ub = sp.vstack(
                [
                    sp.hstack(
                        [per_part,
                         sp.csr_matrix((inst.num_parts, B))],
                        format="csr",
                    ),
                    led_of_b,        # <= leader_hi
                    -led_of_b,       # >= leader_lo
                ],
                format="csr",
            )
            b_ub = np.concatenate(
                [
                    np.ones(inst.num_parts),
                    np.full(B, float(inst.leader_hi)),
                    np.full(B, -float(inst.leader_lo)),
                ]
            )
            c = -np.concatenate([g, np.zeros(B)])
            # every live partition has exactly one leader
            a_eq = sp.csr_matrix(np.ones((1, n + B)))
            b_eq = np.array([float(p_active)])
            lo = np.zeros(n + B)
            hi = np.concatenate(
                [np.ones(n), np.full(B, float(p_active))]
            )
            # variable bounds as one [n+B, 2] array: building the
            # equivalent Python list of tuples walks every variable in
            # the interpreter — dead host time at 150k members
            # (ISSUE 10); identical values, so the LP (and with it the
            # certified bound) is bit-equal
            res = linprog(
                c, A_ub=a_ub, b_ub=b_ub, A_eq=a_eq, b_eq=b_eq,
                bounds=np.stack([lo, hi], axis=1),
                method="highs-ipm", options=opts,
            )
        if not res.success:
            return None
        # certificate-critical: the repaired dual bound is valid
        # regardless of primal tolerance, so when marginals exist it
        # is the ONLY sound choice — a loose repair weakens the
        # verdict, never the soundness. The max with the primal
        # value guards fp noise in the repair arithmetic (a feasible
        # iterate's value never exceeds the true optimum, so the max
        # is still an upper bound). Primal fallback only when the
        # solve carried no marginals at all.
        ub = _dual_repair_max_ub(c, a_ub, b_ub, a_eq, b_eq, lo, hi, res)
        if ub is None:
            return base + _safe_floor_ub(res.fun)
        return base + _safe_floor_ub(-max(ub, -res.fun))
    except Exception:
        return None


def _kept_weight_lp(inst, return_solution: bool = False):
    """Level-2 bound: max preservation weight of kept slots under
    ALL band families jointly, BOTH sides (see
    ``weight_upper_bound``). Variables: x_{p,b} (member kept as
    follower, weight w_follower) / y_{p,b} (member kept as leader,
    weight w_leader) per current eligible member, plus zero-weight
    slacks u_b (partitions broker b leads through a non-kept
    leader) and z_b (new, non-kept replicas broker b hosts):

        x + y <= 1                    per member (one role)
        sum_b y <= 1                  per partition (C5)
        sum_b (x+y) <= rf_p           per partition (C4)
        sum_{b in k} (x+y) <= part_rack_hi_p   per (p, rack) (C10)
        leader_lo <= sum_p y->b + u_b <= leader_hi   per broker (C7)
        broker_lo <= sum (x+y)->b + z_b <= broker_hi per broker (C6)
        rack_lo_k <= sum_{b in k} [(x+y)->b + z_b] <= rack_hi_k (C9)
        sum y + sum u = #live partitions       (one leader each)
        sum (x+y) + sum z = total_replicas     (every slot filled)

    Every feasible plan maps into this region (kept roles -> x/y,
    its remaining leads/replicas -> u/z), so the optimum is a valid
    upper bound; the slacks let the LOWER bands and totals bind —
    an under-leading broker must absorb leaderships and a
    below-floor broker/rack must absorb new replicas, losses the
    cap-only levels cannot see."""
    try:
        import scipy.sparse as sp
        from scipy.optimize import linprog
    except Exception:
        return None
    mrows, mcols = inst._members()
    n = mrows.size
    if n == 0:
        return None if return_solution else 0
    # deadline check BEFORE model build: assembling the sparse
    # matrices costs seconds at 10k partitions (and holds the serve
    # solve lock) — an expired budget must not pay it
    opts = inst._lp_options()
    if opts is None:
        return None
    try:
        B, K, P = inst.num_brokers, inst.num_racks, inst.num_parts
        rack = inst.rack_of_broker[mcols]
        var = np.arange(n)
        one = np.ones(n)
        pair_key = mrows.astype(np.int64) * K + rack
        pairs, pair_idx = np.unique(pair_key, return_inverse=True)
        p_active = int((inst.rf > 0).sum())
        r_total = float(inst.total_replicas)
        # column layout: x (kept follower) 0..n-1 | y (kept leader)
        # n..2n-1 | u (non-kept lead per broker) 2n..2n+B-1 | z (new
        # replica per broker) 2n+B..2n+2B-1. The slack columns let
        # the LOWER bands and the totals bind: an under-leading
        # broker must take leads (losing 4->2 keeps elsewhere), new
        # replicas forced by broker/rack floors consume cap the
        # kept slots then cannot use.
        ncols = 2 * n + 2 * B
        u_off, z_off = 2 * n, 2 * n + B

        def block(r, c, shape0):
            return sp.csr_matrix(
                (np.ones(len(c)), (r, c)), shape=(shape0, ncols)
            )

        def both(r, shape0):  # rows over x+y
            return block(
                np.concatenate([r, r]),
                np.concatenate([var, var + n]),
                shape0,
            )

        def y_only(r, shape0):
            return block(r, var + n, shape0)

        b_idx = np.arange(B)
        lead_of_b = y_only(mcols, B) + block(
            b_idx, u_off + b_idx, B
        )
        repl_of_b = both(mcols, B) + block(b_idx, z_off + b_idx, B)
        rack_rows = both(rack, K) + block(
            inst.rack_of_broker[:B], z_off + b_idx, K
        )
        a_ub = sp.vstack(
            [
                both(var, n),          # x + y <= 1 per member
                y_only(mrows, P),      # one kept leader per part
                both(mrows, P),        # <= rf per part
                both(pair_idx, pairs.size),  # diversity per (p,k)
                lead_of_b,             # <= leader_hi per broker
                -lead_of_b,            # >= leader_lo per broker
                repl_of_b,             # <= broker_hi per broker
                -repl_of_b,            # >= broker_lo per broker
                rack_rows,             # <= rack_hi per rack
                -rack_rows,            # >= rack_lo per rack
            ],
            format="csr",
        )
        b_ub = np.concatenate(
            [
                np.ones(n),
                np.ones(P),
                inst.rf.astype(np.float64),
                inst.part_rack_hi[(pairs // K)].astype(np.float64),
                np.full(B, float(inst.leader_hi)),
                np.full(B, -float(inst.leader_lo)),
                np.full(B, float(inst.broker_hi)),
                np.full(B, -float(inst.broker_lo)),
                inst.rack_hi.astype(np.float64),
                -inst.rack_lo.astype(np.float64),
            ]
        )
        # totals: every live partition has one leader; every valid
        # slot is kept or new
        a_eq = sp.vstack(
            [
                block(
                    np.zeros(n + B, np.int64),
                    np.concatenate([var + n, u_off + b_idx]),
                    1,
                ),
                block(
                    np.zeros(2 * n + B, np.int64),
                    np.concatenate([var, var + n, z_off + b_idx]),
                    1,
                ),
            ],
            format="csr",
        )
        b_eq = np.array([float(p_active), r_total])
        wl = inst.w_leader[:, :B][mrows, mcols].astype(np.float64)
        wf = np.maximum(
            inst.w_follower[:, :B][mrows, mcols], 0
        ).astype(np.float64)
        # variable bounds as arrays (see _leader_cap_lp): the tuple
        # list walked 2n+2B variables in the interpreter per solve —
        # at the 50k-partition jumbo that is ~300k dead Python
        # iterations on the constructor's critical path (ISSUE 10)
        lo = np.zeros(ncols)
        hi = np.concatenate([
            np.ones(2 * n),
            np.full(B, float(p_active)),
            np.full(B, r_total),
        ])
        bounds = np.stack([lo, hi], axis=1)
        if return_solution:
            # one composite solve: weight lexicographically above
            # the kept-slot count (kept < n+1, so the scaled weight
            # term dominates) — among weight-optimal vertices, pick
            # a move-minimal one for the constructor to decode. The
            # decoded plan's weight/moves are recomputed from the
            # ROUNDED integers, so composite-objective fp noise
            # cannot leak into any certificate.
            scale = float(n + 1)
            c = -np.concatenate(
                [scale * wf + 1, scale * wl + 1, np.zeros(2 * B)]
            )
        else:
            c = -np.concatenate([wf, wl, np.zeros(2 * B)])
        res = linprog(
            c,
            A_ub=a_ub, b_ub=b_ub, A_eq=a_eq, b_eq=b_eq,
            bounds=bounds, method="highs",
            options=opts,
        )
        if not res.success:
            return None
        if return_solution:
            sol = res.x
            return {
                "x": sol[:n],
                "y": sol[n:2 * n],
                "z": sol[z_off:z_off + B],
                "mrows": mrows,
                "mcols": mcols,
            }
        # certificate-critical: when marginals exist the repaired
        # dual bound is the only sound choice (see _leader_cap_lp);
        # max with the primal value guards repair fp noise
        ub = _dual_repair_max_ub(c, a_ub, b_ub, a_eq, b_eq, lo, hi, res)
        if ub is None:
            return _safe_floor_ub(res.fun)
        return _safe_floor_ub(-max(ub, -res.fun))
    except Exception:
        return None


def _member_classes(inst):
    """Partition-symmetry classes for the aggregated kept-weight
    bound: partitions are interchangeable in the level-2 LP when
    they share (rf, part_rack_hi, sorted member (broker, w_leader,
    w_follower) triples). Generated clusters — and real round-robin
    Kafka clusters — have FAR fewer classes than partitions (the
    50k-partition jumbo instance has 543), which is what makes the
    level-2 bound affordable at any size.

    Returns (cls_parts, cls_rf, cls_prh, cm_cls, cm_broker, cm_wl,
    cm_wf): per-class partition lists and rf/prh, plus flattened
    class-member arrays. Memoized."""
    cached = getattr(inst, "_member_classes_memo", None)
    if cached is not None:
        return cached

    mrows, mcols = inst._members()
    wl = inst.w_leader[mrows, mcols].astype(np.int64)
    wf = np.maximum(inst.w_follower[mrows, mcols], 0).astype(np.int64)
    P = inst.num_parts
    # vectorized grouping: encode each member as one int64, lay the
    # per-partition sorted member lists into a padded signature
    # matrix [P, 2 + maxM], and let np.unique(axis=0) find the
    # classes — the Python-dict version costs ~0.6 s at jumbo
    # scale, squarely on the constructor's critical path
    if (
        0 <= wl.min(initial=0)
        and wl.max(initial=0) < (1 << 12)
        and wf.max(initial=0) < (1 << 12)
        and inst.num_brokers < (1 << 24)
    ):
        enc = (mcols.astype(np.int64) << 24) | (wl << 12) | wf
        cnt = np.bincount(mrows, minlength=P)
        starts = np.concatenate([[0], np.cumsum(cnt)[:-1]])
        order = np.lexsort((enc, mrows))
        r_s, e_s = mrows[order], enc[order]
        pos = np.arange(r_s.size) - starts[r_s]
        maxm = int(cnt.max(initial=0))
        sig = np.full((P, 2 + maxm), -1, np.int64)
        sig[:, 0] = inst.rf
        sig[:, 1] = inst.part_rack_hi
        sig[r_s, 2 + pos] = e_s
        uniq, inv = np.unique(sig, axis=0, return_inverse=True)
        by_cls = np.argsort(inv, kind="stable")
        splits = np.cumsum(np.bincount(inv))[:-1]
        cls_parts = [p.tolist() for p in np.split(by_cls, splits)]
        cls_rf = uniq[:, 0].copy()
        cls_prh = uniq[:, 1].copy()
        mem = uniq[:, 2:]
        ci, mj = np.nonzero(mem != -1)
        me = mem[ci, mj]
        out = (
            cls_parts,
            cls_rf,
            cls_prh,
            ci.astype(np.int64),
            (me >> 24).astype(np.int64),
            ((me >> 12) & 0xFFF).astype(np.int64),
            (me & 0xFFF).astype(np.int64),
        )
        inst._member_classes_memo = out
        return out

    # fallback for out-of-range weights/broker ids (never hit by
    # the README tier rule, which caps weights at 4)
    import collections

    per = collections.defaultdict(list)
    for r, c, a, b in zip(mrows.tolist(), mcols.tolist(),
                          wl.tolist(), wf.tolist()):
        per[r].append((c, a, b))
    groups: dict = collections.defaultdict(list)
    rf_l = inst.rf.tolist()
    prh_l = inst.part_rack_hi.tolist()
    for p in range(inst.num_parts):  # kao: disable=KAO109 -- out-of-range-weight fallback only; the vectorized np.unique grouping above serves every README-tier instance (weights <= 4)
        key = (rf_l[p], prh_l[p], tuple(sorted(per[p])))
        groups[key].append(p)
    cls_parts, cls_rf, cls_prh = [], [], []
    cm_cls, cm_broker, cm_wl, cm_wf = [], [], [], []
    for ci, (key, parts) in enumerate(groups.items()):
        rff, prh, members = key
        cls_parts.append(parts)
        cls_rf.append(rff)
        cls_prh.append(prh)
        for (b, a, f) in members:
            cm_cls.append(ci)
            cm_broker.append(b)
            cm_wl.append(a)
            cm_wf.append(f)
    out = (
        cls_parts,
        np.array(cls_rf, np.int64),
        np.array(cls_prh, np.int64),
        np.array(cm_cls, np.int64),
        np.array(cm_broker, np.int64),
        np.array(cm_wl, np.int64),
        np.array(cm_wf, np.int64),
    )
    inst._member_classes_memo = out
    return out


def _kept_weight_agg(inst, integer: bool = False,
                     return_solution: bool = False):
    """The level-2 kept-weight bound on the SYMMETRY-AGGREGATED
    model — exactly the same polytope as ``_kept_weight_lp`` but
    with one variable per (class, member) instead of per
    (partition, member).

    Exactness: the LP optimum is invariant under aggregation —
    averaging any optimum over a class's partitions (they have
    identical members, weights, rf and caps) is feasible with the
    same objective, and symmetric solutions biject with the
    aggregated ones (every aggregated row is the sum of the
    partition rows it replaces). So this IS the level-2 LP bound,
    at ~#classes/#partitions of the cost — 0.5 s where the
    unaggregated LP times out at 900 s (50k-partition jumbo).

    ``integer=True`` solves the aggregated MILP instead: integer
    symmetrization is only into (every real plan maps to an integer
    aggregate; not every integer aggregate is realizable), so its
    optimum — or its dual bound under a time limit — is a still-
    valid, potentially TIGHTER upper bound than the LP (the
    ``weight_upper_bound`` level-3 tier).

    ``return_solution`` (with ``integer=True``) returns the raw
    aggregated solution for the plan constructor
    (``solvers.lp_round``): a dict with per-class-member kept
    counts X/Y, per-broker new-replica quotas z and non-kept-leader
    quotas u, plus the class arrays to disaggregate with."""
    try:
        import scipy.sparse as sp
        from scipy.optimize import linprog
    except Exception:
        return None
    (cls_parts, cls_rf, cls_prh, cm_cls, cm_broker, cm_wl, cm_wf
     ) = inst._member_classes()
    n_cm = cm_broker.size
    if n_cm == 0:
        return None if return_solution else 0
    # the formulation only pays off when symmetry actually shrinks
    # the problem: on clusters with near-distinct per-partition
    # weights (#classes ~ #partitions) this would be a full-size
    # MILP burning its whole time limit to restate the level-2
    # verdict — refuse instead of grinding (certify_optimal and the
    # serve audit run these tiers synchronously)
    if not inst.agg_construct_viable():
        return None
    opts = inst._lp_options()
    if opts is None:  # bounds deadline already spent
        return None
    try:
        B, K = inst.num_brokers, inst.num_racks
        C = len(cls_parts)
        cls_n = np.array([len(p) for p in cls_parts], np.float64)
        cm_n = cls_n[cm_cls]
        rack = inst.rack_of_broker[cm_broker]
        p_active = float((inst.rf > 0).sum())
        r_total = float(inst.total_replicas)
        ncols = 2 * n_cm + 2 * B
        u_off, z_off = 2 * n_cm, 2 * n_cm + B
        var = np.arange(n_cm)

        def block(r, c, nrows):
            return sp.csr_matrix(
                (np.ones(len(c)), (r, c)), shape=(nrows, ncols)
            )

        def both(r, nrows):
            return block(
                np.concatenate([r, r]),
                np.concatenate([var, var + n_cm]),
                nrows,
            )

        b_idx = np.arange(B)
        pk = cm_cls * K + rack
        pairs, pair_idx = np.unique(pk, return_inverse=True)
        lead_b = block(cm_broker, var + n_cm, B) + block(
            b_idx, u_off + b_idx, B
        )
        repl_b = both(cm_broker, B) + block(b_idx, z_off + b_idx, B)
        rack_rows = both(rack, K) + block(
            inst.rack_of_broker[:B], z_off + b_idx, K
        )
        # u_b <= z_b: a lead through a non-kept leader sits on one
        # of that broker's NEW replicas (valid for every real plan;
        # tightens the aggregate against phantom leaderships)
        uz = sp.csr_matrix(
            (np.concatenate([np.ones(B), -np.ones(B)]),
             (np.concatenate([b_idx, b_idx]),
              np.concatenate([u_off + b_idx, z_off + b_idx]))),
            shape=(B, ncols),
        )
        a_ub = sp.vstack(
            [
                both(var, n_cm),              # X+Y <= n_c per member
                block(cm_cls, var + n_cm, C),  # sum Y <= n_c
                both(cm_cls, C),              # sum(X+Y) <= n_c rf
                both(pair_idx, pairs.size),   # diversity pairs
                block(cm_cls, var, C),        # sum X <= n_c (rf-1):
                # a fully-kept partition keeps its leader, so kept
                # FOLLOWERS never exceed rf-1
                lead_b, -lead_b,
                repl_b, -repl_b,
                rack_rows, -rack_rows,
                uz,
            ],
            format="csr",
        )
        b_ub = np.concatenate(
            [
                cm_n,
                cls_n,
                cls_n * cls_rf,
                (cls_n * cls_prh)[(pairs // K)],
                cls_n * np.maximum(cls_rf - 1, 0),
                np.full(B, float(inst.leader_hi)),
                np.full(B, -float(inst.leader_lo)),
                np.full(B, float(inst.broker_hi)),
                np.full(B, -float(inst.broker_lo)),
                inst.rack_hi.astype(np.float64),
                -inst.rack_lo.astype(np.float64),
                np.zeros(B),
            ]
        )
        a_eq = sp.vstack(
            [
                block(
                    np.zeros(n_cm + B, np.int64),
                    np.concatenate([var + n_cm, u_off + b_idx]),
                    1,
                ),
                block(
                    np.zeros(2 * n_cm + B, np.int64),
                    np.concatenate(
                        [var, var + n_cm, z_off + b_idx]
                    ),
                    1,
                ),
            ],
            format="csr",
        )
        b_eq = np.array([p_active, r_total])
        if return_solution:
            # lexicographic: weight dominant, kept count tie-break
            scale = float(inst.total_replicas + 1)
            c = -np.concatenate(
                [scale * cm_wf + 1, scale * cm_wl + 1,
                 np.zeros(2 * B)]
            )
        else:
            c = -np.concatenate(
                [cm_wf.astype(np.float64), cm_wl.astype(np.float64),
                 np.zeros(2 * B)]
            )
        lo = np.zeros(ncols)
        hi = np.concatenate(
            [cm_n, cm_n, np.full(B, p_active), np.full(B, r_total)]
        )
        if integer:
            from scipy.optimize import (
                Bounds, LinearConstraint, milp,
            )

            res = milp(
                c,
                constraints=[
                    LinearConstraint(a_ub, -np.inf, b_ub),
                    LinearConstraint(a_eq, b_eq, b_eq),
                ],
                bounds=Bounds(lo, hi),
                integrality=np.ones(ncols),
                options={"time_limit": opts["time_limit"],
                         "mip_rel_gap": 0.0},
            )
            if return_solution:
                # scipy.milp: success is True ONLY at proven
                # optimality (status 0) — a time-limit incumbent
                # reports success=False — so everything below,
                # including the recorded weight bound, rests on a
                # solved-to-optimality aggregate
                if not res.success or res.x is None:
                    return None
                sol = np.rint(res.x)
                if np.abs(res.x - sol).max(initial=0) > 1e-6:
                    return None
                # the pure-weight part of the lexicographic optimum
                # is a valid upper bound on ANY feasible plan's
                # weight: scale > every kept count, so a plan with
                # higher weight would map to an aggregate beating
                # the composite optimum. Recording it lets
                # certify_optimal skip the bound-ladder LPs for
                # constructor-built plans.
                xs = sol[:n_cm]
                ys = sol[n_cm:2 * n_cm]
                agg_w = int((cm_wf * xs).sum() + (cm_wl * ys).sum())
                # min-merged with any bound the unaggregated LP vertex
                # already recorded (solvers.lp_round._unagg_plan): both
                # are valid upper bounds, the tighter one certifies more
                prev = getattr(inst, "_agg_weight_ub", None)
                inst._agg_weight_ub = (
                    agg_w if prev is None else min(prev, agg_w)
                )
                return {
                    "X": sol[:n_cm].astype(np.int64),
                    "Y": sol[n_cm:2 * n_cm].astype(np.int64),
                    "u": sol[u_off:u_off + B].astype(np.int64),
                    "z": sol[z_off:z_off + B].astype(np.int64),
                    "cls_parts": cls_parts,
                    "cls_rf": cls_rf,
                    "cls_prh": cls_prh,
                    "cm_cls": cm_cls,
                    "cm_broker": cm_broker,
                    "cm_wl": cm_wl,
                    "cm_wf": cm_wf,
                }
            # branch-and-bound dual bound: valid even on timeout
            db = getattr(res, "mip_dual_bound", None)
            if db is None or not np.isfinite(db):
                return None
            return _safe_floor_ub(db)
        res = linprog(
            c, A_ub=a_ub, b_ub=b_ub, A_eq=a_eq, b_eq=b_eq,
            bounds=np.stack([lo, hi], axis=1), method="highs",
            options=opts,
        )
        if not res.success:
            return None
        ub = _dual_repair_max_ub(c, a_ub, b_ub, a_eq, b_eq, lo, hi,
                                 res)
        if ub is None:
            return _safe_floor_ub(res.fun)
        return _safe_floor_ub(-max(ub, -res.fun))
    except Exception:
        return None


def certify_optimal(inst, a: np.ndarray, allow_tight: bool = True
                    ) -> bool:
    """True iff ``a`` is PROVABLY a global optimum: feasible, its
    preservation weight meets the unconstrained upper bound
    (``max_weight``), and its move count meets ``move_lower_bound``.
    Search engines use this to stop early with ``optimal=True``; a
    False return proves nothing (the bounds may simply not be tight
    for this instance)."""
    if not inst.is_feasible(a):
        return False
    mc = inst.move_count(a)
    if mc > inst.move_lower_bound() and (
        mc > inst.move_lower_bound_exact()
    ):
        return False
    w = inst.preservation_weight(a)
    # fast path: an aggregated-MILP optimum recorded by the plan
    # constructor is already a valid upper bound on every feasible
    # plan's weight (see _kept_weight_agg) — meeting it needs no LP
    agg_ub = getattr(inst, "_agg_weight_ub", None)
    if agg_ub is not None and w >= agg_ub:
        return True
    if w >= inst.weight_upper_bound(level=0):
        return True
    # the higher levels solve multi-second LPs at 10k partitions;
    # deadline-sensitive callers (the engine under time_limit_s)
    # disable the synchronous escalation
    if not allow_tight:
        return False
    return (
        w >= inst.weight_upper_bound(level=1)
        or w >= inst.weight_upper_bound(level=2)
        or w >= inst.weight_upper_bound(level=3)
    )

