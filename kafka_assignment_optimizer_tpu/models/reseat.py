"""Exact leader reseating for :class:`ProblemInstance`.

Moved out of ``models.instance`` in r5 (VERDICT r4 item 7), same
delegation contract as ``models.bounds``. Given a plan with its replica
SETS fixed, these compute the weight-optimal leader arrangement (zero
replica movement — the reference's leader-preservation objective,
``/root/reference/README.md:131-133``): the band-repairing
negative-cycle canceller as the fast path, the assignment-polytope LP
as the oracle/fallback.
"""

from __future__ import annotations

import numpy as np

def best_leader_assignment(inst, a: np.ndarray) -> np.ndarray:
    """Exact optimal leader choice for FIXED replica sets: permute
    each partition's slots so the leader (slot 0) maximizes the total
    preservation weight subject to the per-broker leader band.

    With replica sets fixed, total weight = const + sum_p
    (w_lead - w_foll)[p, leader_p], one leader per partition, each
    broker leading within [leader_lo, leader_hi] — a transportation
    problem (integral polytope). Closes the gap one-swap-at-a-time
    local search cannot: chains of leader reseats through near-cap
    brokers (the reference's "preferred leader has more weight"
    objective, ``/root/reference/README.md:131-133``, optimized
    exactly). The other constraint families only see replica sets,
    so feasibility is untouched. Returns ``a`` unchanged on any
    failure.

    Solved by incremental negative-cycle canceling on the broker
    lead-move graph (``_reseat_cycle_cancel``) — the engine hands
    this an annealed candidate whose leadership is already
    near-optimal, so a handful of O(B^3) Bellman-Ford passes beat
    re-solving the 150k-variable transportation LP from scratch by
    ~2 orders of magnitude (58 s -> <1 s at the 50k-partition
    adv50k scale, measured r4). Out-of-band leadership counts are
    repaired first by cheapest lead-shift paths (same arc
    machinery), so constructed plans and scrambled inputs stay on
    the fast path too; the HiGHS LP remains as the exact fallback
    for the rare inputs the canceller still declines (repair
    budget or iteration cap tripped)."""
    a = np.asarray(a)
    P, R = a.shape
    if P == 0 or R == 0:
        return a
    try:
        out = inst._reseat_cycle_cancel(a)
        if out is None:
            out = inst._best_leader_lp(a)
        if out is None:
            return a
        # exactness guard against round-off / edge cases in either
        # path: keep the better plan under (fewest violations, then
        # weight). A feasible input can only improve; an
        # infeasible-leadership input is legitimately repaired at a
        # weight cost.
        def rank(z):
            return (
                -sum(inst.violations(z).values()),
                inst.preservation_weight(z),
            )

        return out if rank(out) >= rank(a) else a
    except Exception:
        # the documented contract: a malformed input degrades to
        # "no reseat", never to a crashed solve
        return a


def _best_leader_lp(inst, a: np.ndarray) -> np.ndarray | None:
    """Transportation-LP formulation of the exact leader reseat
    (see ``best_leader_assignment``), solved with HiGHS via scipy.
    Returns the reseated plan or None on solver failure."""
    P, R = a.shape
    B = inst.num_brokers
    valid = inst.slot_valid
    try:
        import scipy.sparse as sp
        from scipy.optimize import linprog

        prow = np.arange(P)[:, None]
        gain = np.where(
            valid,
            inst.w_leader[prow, a] - inst.w_follower[prow, a],
            0,
        ).astype(np.float64)
        rows, cols = np.nonzero(valid & (inst.rf[:, None] > 0))
        n = rows.size
        if n == 0:
            return a
        g = gain[rows, cols]
        b_of = a[rows, cols]
        var = np.arange(n)
        a_eq = sp.csr_matrix(  # exactly one leader per partition
            (np.ones(n), (rows, var)),
            shape=(P, n),
        )
        keep = inst.rf > 0
        a_eq = a_eq[keep]
        lead_of_b = sp.csr_matrix(
            (np.ones(n), (b_of, var)), shape=(B, n)
        )
        res = linprog(
            -g,
            A_eq=a_eq,
            b_eq=np.ones(int(keep.sum())),
            A_ub=sp.vstack([lead_of_b, -lead_of_b], format="csr"),
            b_ub=np.concatenate(
                [
                    np.full(B, float(inst.leader_hi)),
                    np.full(B, -float(inst.leader_lo)),
                ]
            ),
            bounds=(0, 1),
            # measured at 150k slots (r4): HiGHS simplex 58 s, IPM
            # (with its default crossover to a basic solution,
            # which the argmax decode below needs) 3.3 s
            method="highs-ipm",
        )
        if not res.success:
            return None
        x = np.zeros((P, R))
        x[rows, cols] = res.x
        chosen = np.argmax(x, axis=1)  # integral LP: one ~1.0 per row
        out = a.copy()
        rng = np.arange(P)
        lead = out[rng, chosen]
        out[rng, chosen] = out[:, 0]
        out[:, 0] = np.where(keep, lead, out[:, 0])
        return out
    except Exception:
        return None


def _reseat_cycle_cancel(inst, a: np.ndarray) -> np.ndarray | None:
    """Exact leader reseat by negative-cycle canceling (the fast
    path of ``best_leader_assignment``).

    View a leader arrangement as a flow on the broker lead-move
    graph: reseating partition p from its current leader (broker
    ``b = a[p, 0]``) to the member in slot s (broker
    ``c = a[p, s]``) is an arc b -> c with integer cost
    ``gain(p, 0) - gain(p, s)`` where ``gain = w_lead - w_foll`` of
    the occupying broker; it shifts one lead from b to c. Any two
    band-feasible arrangements of the same replica sets differ by a
    set of broker-space cycles (lead counts unchanged) plus paths
    (endpoints shift by one, still inside the band) — so an
    arrangement with no negative cycle in the dense min-cost arc
    matrix (paths modeled via a virtual node with zero-cost arcs to
    brokers that can shed a lead and from brokers that can absorb
    one) is globally optimal: the standard min-cost-flow optimality
    argument on an integral transportation polytope.

    Each Bellman-Ford pass is a vectorized [B+1, B+1] min-plus
    sweep; every applied cycle raises the exact integer objective
    by >= 1, so termination is bounded by the optimality gap of the
    input — a handful of iterations for the near-optimal candidates
    the engine feeds here, independent of partition count (the only
    O(P) work per iteration is rebuilding the arc mins).

    Returns the optimal reseat, or None to decline: the band-repair
    budget or iteration cap tripped (guards, not budgets — neither
    has been observed on engine-fed candidates)."""
    P, R = a.shape
    B = inst.num_brokers
    valid = inst.slot_valid
    keep = inst.rf > 0
    if (keep & (a[:, 0] >= B)).any():
        return None  # live partition with no in-range leader
    lcnt = np.bincount(a[keep, 0], minlength=B)[:B]
    prow = np.arange(P)[:, None]
    # candidate arcs: (p, s>=1) valid follower slots of live
    # partitions; arc out[p,0] -> out[p,s] at cost
    # gain[p,0]-gain[p,s] (gain = lead-over-follow weight of the
    # occupying broker; slot-keyed, so recomputed after each
    # applied cycle's swaps)
    arc_mask = valid.copy()
    arc_mask[:, 0] = False
    arc_mask &= keep[:, None] & (a < B)
    p_arc, s_arc = np.nonzero(arc_mask)
    in_band = (
        (lcnt >= inst.leader_lo).all()
        and (lcnt <= inst.leader_hi).all()
    )
    if p_arc.size == 0:
        # no alternative leaders anywhere: a is optimal as-is when
        # in band (the LP could not change anything either — its
        # only choice is which valid slot leads); out of band it is
        # unrepairable by lead permutation
        return a.copy() if in_band else None
    out = a.copy()
    INF = np.int64(1) << 40
    N = B + 1  # + virtual node for band-shifting paths

    def arc_views():
        """(gain, b_from, b_to, cost) over the CURRENT ``out``.
        The single definition both phases share: the witness
        lookup below matches on ``cost == C[b, c]``, which is only
        sound while every consumer computes costs identically."""
        gain = np.where(
            valid & (out < B),
            inst.w_leader[prow, out] - inst.w_follower[prow, out],
            0,
        ).astype(np.int64)
        return (
            gain,
            out[p_arc, 0],
            out[p_arc, s_arc],
            gain[p_arc, 0] - gain[p_arc, s_arc],
        )

    def refresh_row(p, gain, b_from, b_to, cost):
        """Fold one partition's swap into the arc views in
        O(R + arcs_of_p) — a full rebuild per applied edge is
        O(P*R) and turns the repair of a scrambled 50k-partition
        input into seconds of dead numpy."""
        row = out[p]
        gain[p] = np.where(
            valid[p] & (row < B),
            inst.w_leader[p, row] - inst.w_follower[p, row],
            0,
        )
        lo_i = np.searchsorted(p_arc, p)
        hi_i = np.searchsorted(p_arc, p + 1)
        b_from[lo_i:hi_i] = row[0]
        b_to[lo_i:hi_i] = row[s_arc[lo_i:hi_i]]
        cost[lo_i:hi_i] = gain[p, 0] - gain[p, s_arc[lo_i:hi_i]]

    if not in_band:
        # --- band-repair phase (r4): out-of-band inputs used to
        # decline to the transportation LP (seconds at 50k
        # partitions). Each repair unit shifts one lead along the
        # cheapest broker path from a shed source to an absorbing
        # sink, reducing total band violation by exactly one; a
        # path always exists while violations remain, because the
        # difference to ANY band-feasible arrangement of the same
        # replica sets decomposes into lead-shift paths whose arcs
        # are all present in the current arrangement. Optimality
        # is NOT needed here — the cycle-canceling phase below
        # restores it from any feasible point — so path costs are
        # shifted non-negative and searched with plain
        # Bellman-Ford (the raw arc matrix can hold negative
        # cycles before canceling).
        viol = int(
            np.maximum(lcnt - inst.leader_hi, 0).sum()
            + np.maximum(inst.leader_lo - lcnt, 0).sum()
        )
        if viol > 2 * N + 16:
            return None  # grossly out of band: let the LP repair
        gain = b_from = b_to = cost = None
        for _unit in range(viol):
            surplus = lcnt > inst.leader_hi
            deficit = lcnt < inst.leader_lo
            if not surplus.any() and not deficit.any():
                break
            if gain is None:  # per-edge refreshes keep them current
                gain, b_from, b_to, cost = arc_views()
            C = np.full((B, B), INF, dtype=np.int64)
            np.minimum.at(C, (b_from, b_to), cost)
            np.fill_diagonal(C, INF)
            finite = C < INF
            if not finite.any():
                return None
            shift = max(0, -int(C[finite].min()))
            Cn = np.where(finite, C + shift, INF)
            if surplus.any():
                src_mask = surplus
                dst_mask = lcnt + 1 <= inst.leader_hi
            else:
                src_mask = lcnt - 1 >= inst.leader_lo
                dst_mask = deficit
            dist = np.where(src_mask, np.int64(0), INF)
            parent = np.full(B, -1, dtype=np.int64)
            for _sweep in range(B):
                cand = dist[:, None] + Cn
                nb = cand.argmin(axis=0)
                nd = cand[nb, np.arange(B)]
                better = nd < dist
                if not better.any():
                    break
                dist = np.where(better, nd, dist)
                parent = np.where(better, nb, parent)
            sinks = np.flatnonzero(dst_mask & (dist < INF))
            if sinks.size == 0:
                return None  # unreachable: decline, LP decides
            v = int(sinks[np.argmin(dist[sinks])])
            path = [v]
            while not src_mask[path[-1]]:
                u = int(parent[path[-1]])
                if u < 0 or len(path) > B:
                    return None
                path.append(u)
            path.reverse()  # source ... sink
            for b, c in zip(path, path[1:]):
                hit = np.flatnonzero(
                    (b_from == b) & (b_to == c) & (cost == C[b, c])
                )
                if hit.size == 0:
                    return None  # stale witness: decline
                k = int(hit[0])
                p, s = int(p_arc[k]), int(s_arc[k])
                out[p, 0], out[p, s] = out[p, s], out[p, 0]
                lcnt[b] -= 1
                lcnt[c] += 1
                # refresh the swapped row's arc views so the
                # path's later edges see this swap (their
                # witnesses stay valid: a shift INTO an
                # intermediate broker never removes a partition
                # from its led set)
                refresh_row(p, gain, b_from, b_to, cost)
        if (lcnt < inst.leader_lo).any() or (
            lcnt > inst.leader_hi
        ).any():
            return None  # repair fell short: decline, LP decides
    for _ in range(256):  # cap >> any observed cycle count
        gain, b_from, b_to, cost = arc_views()
        C = np.full((N, N), INF, dtype=np.int64)
        np.minimum.at(C, (b_from, b_to), cost)
        np.fill_diagonal(C, INF)  # self-loop arcs are no-ops
        C[:B, B] = np.where(lcnt + 1 <= inst.leader_hi, 0, INF)
        C[B, :B] = np.where(lcnt - 1 >= inst.leader_lo, 0, INF)
        # all-source Bellman-Ford: dist starts at 0 everywhere, so
        # any relaxation still possible after N sweeps lies on a
        # negative cycle reachable through the parent chain. The
        # engine's candidates are near-optimal, so their cancel
        # cycles are SHORT — probe the parent chain of one improved
        # node every sweep and stop at the first revisit, instead
        # of paying all N min-plus sweeps per cycle (the difference
        # between ~25 ms and ~0.6 s per canceled cycle at B=511)
        dist = np.zeros(N, dtype=np.int64)
        parent = np.full(N, -1, dtype=np.int64)

        def cycle_edges(v):
            """Simple parent cycle through v (which must lie ON the
            cycle) as forward arcs, or None if the walk leaves the
            parent graph / exceeds N steps (v was not on a cycle
            after all) or the total cost is not negative —
            mid-flux (Jacobi) parent graphs can transiently hold
            non-improving cycles, which must not be applied."""
            cyc = [v]
            u = int(parent[v])
            while u != v:
                if u < 0 or len(cyc) > N:
                    return None
                cyc.append(u)
                u = int(parent[u])
            cyc.reverse()  # parent chain is reversed arc order
            edges = list(zip(cyc, cyc[1:] + cyc[:1]))
            if sum(int(C[b, c]) for b, c in edges) >= 0:
                return None
            return edges

        edges = None
        for _sweep in range(N):
            cand = dist[:, None] + C
            nb = cand.argmin(axis=0)
            nd = cand[nb, np.arange(N)]
            better = nd < dist
            if not better.any():
                break
            dist = np.where(better, nd, dist)
            parent = np.where(better, nb, parent)
            u = int(np.flatnonzero(better)[0])
            seen = np.full(N, False)
            for _step in range(N + 1):
                if u < 0:
                    break
                if seen[u]:
                    edges = cycle_edges(u)
                    break
                seen[u] = True
                u = int(parent[u])
            if edges is not None:
                break
        else:
            # N sweeps still improving: a negative cycle certainly
            # exists; walk N parents from an improving node to land
            # on one (guarding the walk — Jacobi parent chains can
            # terminate at a never-improved root)
            v = int(np.flatnonzero(better)[0])
            for _step in range(N):
                nxt = int(parent[v])
                if nxt < 0:
                    return None  # chain left the parent graph
                v = nxt
            edges = cycle_edges(v)
            if edges is None:
                return None  # non-negative parent cycle: LP decides
        if edges is None:
            break  # no negative cycle: optimal
        # apply: for each arc b -> c on the cycle (skipping the
        # virtual node), reseat one witness partition achieving the
        # arc's min cost. Cycle nodes are distinct brokers, so the
        # witnesses are distinct partitions (one current leader
        # broker each).
        applied = False
        for b, c in edges:
            if b == B or c == B:
                continue  # virtual-node legs carry no reseat
            hit = np.flatnonzero(
                (b_from == b) & (b_to == c) & (cost == C[b, c])
            )
            if hit.size == 0:
                return None  # stale witness: decline, LP decides
            k = int(hit[0])
            p, s = int(p_arc[k]), int(s_arc[k])
            out[p, 0], out[p, s] = out[p, s], out[p, 0]
            lcnt[b] -= 1
            lcnt[c] += 1
            applied = True
        if not applied:
            break
    else:
        return None  # iteration cap: decline rather than loop
    return out

