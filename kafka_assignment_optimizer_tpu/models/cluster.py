"""Cluster / topology / assignment model (ingest + emit + move diff).

TPU-native rebuild of the reference's L0/L6 layers:

- Kafka reassignment-JSON parse/emit — the dialect shown in the reference
  demo (``/root/reference/README.md:50-78``): ``{"version": 1, "partitions":
  [{"topic": ..., "partition": ..., "replicas": [brokerIds]}]}`` with the
  leader first in every replica list (``README.md:52-78``).
- Broker list + broker->rack topology ingest (``README.md:27-29, 46-48``).
- Move diff / plan-minimality report (``README.md:83-91``): the whole point
  of the optimizer is that the emitted plan moves as few replicas as
  possible.

Everything here is plain Python + numpy; device arrays only appear once a
:class:`~kafka_assignment_optimizer_tpu.models.instance.ProblemInstance` is
built from these objects.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Iterable, Mapping, Sequence


@dataclass(frozen=True, order=True)
class PartitionKey:
    """Identity of one partition: (topic name, partition id)."""

    topic: str
    partition: int


@dataclass
class PartitionAssignment:
    """One partition's replica list; ``replicas[0]`` is the preferred leader
    (reference demo convention, ``README.md:52-78``)."""

    topic: str
    partition: int
    replicas: list[int]

    @property
    def key(self) -> PartitionKey:
        return PartitionKey(self.topic, self.partition)

    @property
    def leader(self) -> int:
        if not self.replicas:
            raise ValueError(f"{self.topic}-{self.partition} has no replicas")
        return self.replicas[0]


@dataclass
class Assignment:
    """A full current/proposed assignment in Kafka's reassignment-JSON
    dialect (``README.md:50-63``)."""

    partitions: list[PartitionAssignment] = field(default_factory=list)
    version: int = 1

    # -- ingest ---------------------------------------------------------
    @classmethod
    def from_json(cls, text: str | bytes) -> "Assignment":
        data = json.loads(text)
        return cls.from_dict(data)

    @classmethod
    def from_dict(cls, data: Mapping) -> "Assignment":
        if "partitions" not in data:
            raise ValueError("reassignment JSON must contain 'partitions'")
        parts = [
            PartitionAssignment(
                topic=str(p["topic"]),
                partition=int(p["partition"]),
                replicas=[int(b) for b in p["replicas"]],
            )
            for p in data["partitions"]
        ]
        return cls(partitions=parts, version=int(data.get("version", 1)))

    # -- emit -----------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "partitions": [
                {
                    "topic": p.topic,
                    "partition": p.partition,
                    "replicas": list(p.replicas),
                }
                for p in sorted(self.partitions, key=lambda x: (x.topic, x.partition))
            ],
        }

    def to_json(self, indent: int | None = None) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    # -- views ----------------------------------------------------------
    def by_key(self) -> dict[PartitionKey, PartitionAssignment]:
        return {p.key: p for p in self.partitions}

    def topics(self) -> list[str]:
        seen: dict[str, None] = {}
        for p in self.partitions:
            seen.setdefault(p.topic, None)
        return list(seen)

    def broker_ids(self) -> list[int]:
        ids: set[int] = set()
        for p in self.partitions:
            ids.update(p.replicas)
        return sorted(ids)


@dataclass
class Topology:
    """Broker -> rack (or AZ / top-of-rack switch) mapping.

    The reference demo's topology is "odd brokers in AZ b, even in AZ a"
    (``README.md:27-29``); the LP sample names racks like ``tor02``
    (``README.md:173``). A missing topology means one implicit rack.
    """

    rack_of: dict[int, str] = field(default_factory=dict)

    @classmethod
    def from_json(cls, text: str | bytes) -> "Topology":
        data = json.loads(text)
        return cls.from_dict(data)

    @classmethod
    def from_dict(cls, data: Mapping) -> "Topology":
        # accepted forms: {"0": "a", "1": "b"} or
        # {"racks": {"a": [0, 2], "b": [1, 3]}}
        if "racks" in data:
            rack_of: dict[int, str] = {}
            for rack, brokers in data["racks"].items():
                for b in brokers:
                    rack_of[int(b)] = str(rack)
            return cls(rack_of=rack_of)
        return cls(rack_of={int(k): str(v) for k, v in data.items()})

    @classmethod
    def even_odd(cls, broker_ids: Iterable[int], even: str = "a", odd: str = "b") -> "Topology":
        """The reference demo topology (``README.md:27-29``)."""
        return cls(rack_of={b: (even if b % 2 == 0 else odd) for b in broker_ids})

    @classmethod
    def single_rack(cls, broker_ids: Iterable[int], rack: str = "r0") -> "Topology":
        return cls(rack_of={b: rack for b in broker_ids})

    def to_dict(self) -> dict:
        return {str(b): r for b, r in sorted(self.rack_of.items())}

    def racks(self) -> list[str]:
        seen: dict[str, None] = {}
        for b in sorted(self.rack_of):
            seen.setdefault(self.rack_of[b], None)
        return list(seen)

    def rack(self, broker: int, default: str = "r0") -> str:
        return self.rack_of.get(broker, default)


def parse_broker_list(text: str) -> list[int]:
    """Parse ``--broker-list 0,1,2,...,18`` style input (``README.md:48``).

    Supports comma-separated ids and inclusive ranges (``0-18``).
    """
    out: list[int] = []
    for tok in text.replace(" ", "").split(","):
        if not tok:
            continue
        if "-" in tok and not tok.startswith("-"):
            lo, hi = tok.split("-", 1)
            out.extend(range(int(lo), int(hi) + 1))
        else:
            out.append(int(tok))
    seen: dict[int, None] = {}
    for b in out:
        seen.setdefault(b, None)
    return list(seen)


# ---------------------------------------------------------------------------
# Move diff (C15): plan minimality report
# ---------------------------------------------------------------------------


@dataclass
class MoveReport:
    """Diff between two assignments, counting real data movement.

    A *replica move* is a (partition, broker) pair present in the new plan
    but absent from the old one — each such pair implies copying the whole
    partition over the network, the cost the optimizer minimizes
    (``README.md:8-18``). Leader changes that keep the replica set intact
    are metadata-only and counted separately.
    """

    replica_moves: int
    leader_changes: int
    changed: list[PartitionKey]
    added: dict[PartitionKey, list[int]]
    removed: dict[PartitionKey, list[int]]

    def to_dict(self) -> dict:
        return {
            "replica_moves": self.replica_moves,
            "leader_changes": self.leader_changes,
            "changed_partitions": [
                {"topic": k.topic, "partition": k.partition} for k in self.changed
            ],
        }


def move_diff(old: Assignment, new: Assignment) -> MoveReport:
    old_by = old.by_key()
    new_by = new.by_key()
    replica_moves = 0
    leader_changes = 0
    changed: list[PartitionKey] = []
    added: dict[PartitionKey, list[int]] = {}
    removed: dict[PartitionKey, list[int]] = {}
    for key in sorted(set(old_by) | set(new_by)):
        olds = old_by.get(key)
        news = new_by.get(key)
        if olds is not None and news is not None \
                and olds.replicas == news.replicas:
            # identical replica list: no adds, no removes, no leader
            # change — skip the set algebra. On a 50k-partition
            # decommission ~49.7k partitions take this path, which is
            # most of move_diff's 0.7 s of host time (ISSUE 10).
            continue
        old_set = set(olds.replicas) if olds else set()
        new_set = set(news.replicas) if news else set()
        add = sorted(new_set - old_set)
        rem = sorted(old_set - new_set)
        # a partition with an empty replica list (declared but not yet
        # placed — the delta API's partition_growth) has no leader to
        # change: its initial placement is charged as replica moves
        lead_changed = bool(
            olds and news and olds.replicas and news.replicas
            and olds.replicas[0] != news.replicas[0]
        )
        if add or rem or lead_changed:
            changed.append(key)
        if add:
            added[key] = add
        if rem:
            removed[key] = rem
        replica_moves += len(add)
        leader_changes += int(lead_changed)
    return MoveReport(
        replica_moves=replica_moves,
        leader_changes=leader_changes,
        changed=changed,
        added=added,
        removed=removed,
    )


def demo_assignment() -> Assignment:
    """The reference demo's current assignment (``README.md:52-63``):
    20 brokers / 2 AZs, topic ``x.y.z.t`` with 10 partitions, RF=2."""
    replicas = [
        [7, 18], [8, 19], [9, 10], [0, 11], [1, 12],
        [2, 13], [3, 14], [4, 15], [5, 16], [6, 17],
    ]
    return Assignment(
        partitions=[
            PartitionAssignment("x.y.z.t", i, r) for i, r in enumerate(replicas)
        ]
    )


def demo_broker_list() -> list[int]:
    """Target broker list of the demo: drop broker 19 (``README.md:46-48``)."""
    return list(range(19))


def demo_topology() -> Topology:
    """Odd brokers on AZ ``b``, even on ``a`` (``README.md:27-29``)."""
    return Topology.even_odd(range(20))
