"""Solver-neutral optimization model (the reference's L1-L3 layers).

Builds, from (current assignment, target broker list, topology, target RF),
the single :class:`ProblemInstance` that *every* solver backend consumes —
the LP emitter, the MILP oracle, the native C++ branch-and-bound, and the
JAX/TPU annealing engine. Mirrors the reference's model-builder stage
(``/root/reference/README.md:106-133``) but uses dense index arrays rather
than named LP variables; the ``t{t}b{b}p{p}[_l]`` naming survives only in
the LP emitter.

Key representation decision (TPU-first): candidates are *replica-slot*
arrays ``A[P, R] : int`` of broker **indices** with slot 0 = leader —
matching the reference's leader-first JSON convention
(``README.md:52-78``). This hard-encodes the equality constraints
(replication factor ``README.md:148-151``, one leader ``README.md:153-156``,
per-broker uniqueness ``README.md:168-171``) by construction, leaving only
the inequality families as penalty terms for the search backends.

Constraint families and their bound arithmetic (derived from the worked LP
sample, ``README.md:144-185``):

- replicas/broker  in [floor(R_tot/B), ceil(R_tot/B)]   (``README.md:158-161``)
  NOTE: the reference sample shows ``>= 1`` in a 32-broker/20-replica
  cluster where floor(20/32)=0 — the sample is elided/illustrative and
  underdetermines the exact rule; floor/ceil is the self-consistent choice
  and reproduces the demo optimum (golden test).
- leaders/broker   in [floor(P/B),     ceil(P/B)]       (``README.md:163-166``)
- replicas/rack    in [floor(R_tot*B_k/B), ceil(R_tot*B_k/B)] per rack k with
  B_k brokers — proportional form; reduces to the sample's exact R_tot/K
  when racks are equal-sized (``README.md:173-176``)
- replicas of one partition per rack <= ceil(RF/K)      (``README.md:178-180``)

Objective weights (observed data points ``README.md:146``; ordering rule
"leader-keep > follower-keep > new" per ``README.md:116-133``):

- current preferred leader broker: leader-role weight 4, follower-role 2
- current follower broker:         leader-role weight 2, follower-role 1
- any other broker: 0

This exact rule reproduces every coefficient shown in the reference sample
and the demo's 1-move optimum (golden test).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

# guards creation of per-instance memo locks (instances are dataclasses;
# the lock attribute is created lazily on first bound computation)
_MEMO_GUARD = threading.Lock()

# member count past which the UNaggregated kept-replica LP is considered
# intractable (the 50k-partition jumbo's ~150k members time out at 900 s)
# and the symmetry-aggregated formulation takes over — in the bound
# ladder and in the plan constructor (solvers.lp_round)
AGG_MEMBER_THRESHOLD = 60_000

from .cluster import Assignment, PartitionAssignment, Topology

# Objective weight tiers (README.md:146 observed values).
W_LEADER_KEEP = 4  # current leader stays leader
W_LEADER_DEMOTE = 2  # current leader stays as follower
W_FOLLOWER_PROMOTE = 2  # current follower becomes leader
W_FOLLOWER_KEEP = 1  # current follower stays follower



@dataclass
class ProblemInstance:
    """Dense, index-based optimization model.

    Broker axis is *eligible brokers only* (the target ``--broker-list``);
    ``broker_ids[i]`` maps index -> Kafka broker id. Index ``B`` (one past
    the end) is the shared "null bucket" used for padded replica slots, so
    histograms can be built with scatter-adds without branching.
    """

    # topology / broker axis
    broker_ids: np.ndarray  # [B] int32, sorted eligible Kafka broker ids
    rack_of_broker: np.ndarray  # [B+1] int32 rack index; null bucket -> K
    rack_names: list[str]
    # partition axis (all topics flattened)
    topics: list[str]
    topic_of_part: np.ndarray  # [P] int32 topic index
    part_id: np.ndarray  # [P] int32 kafka partition id within topic
    rf: np.ndarray  # [P] int32 target replication factor
    # current assignment, in broker-*index* space, -? see below
    # A0[p, s] = broker index of current replica in slot s (slot 0 leader),
    #            B (null) if slot unused or broker not eligible.
    a0: np.ndarray  # [P, R] int32
    # current assignment in raw broker-id space (for diffs / weights incl.
    # ineligible brokers)
    current: Assignment = field(repr=False, default=None)
    # objective weights, [P, B+1] int32 (null bucket column always 0)
    w_leader: np.ndarray = field(repr=False, default=None)
    w_follower: np.ndarray = field(repr=False, default=None)
    # inequality-constraint bounds
    broker_lo: int = 0
    broker_hi: int = 0
    leader_lo: int = 0
    leader_hi: int = 0
    rack_lo: np.ndarray = None  # [K] int32
    rack_hi: np.ndarray = None  # [K] int32
    part_rack_hi: np.ndarray = None  # [P] int32: ceil(rf/K)

    # -- sizes ----------------------------------------------------------
    @property
    def num_brokers(self) -> int:
        return int(self.broker_ids.shape[0])

    @property
    def num_parts(self) -> int:
        return int(self.topic_of_part.shape[0])

    @property
    def num_racks(self) -> int:
        return len(self.rack_names)

    @property
    def max_rf(self) -> int:
        return int(self.a0.shape[1])

    @property
    def total_replicas(self) -> int:
        return int(self.rf.sum())

    @property
    def slot_valid(self) -> np.ndarray:
        """[P, R] bool — slot s is a real replica slot for partition p."""
        return np.arange(self.max_rf)[None, :] < self.rf[:, None]

    # -- decode ---------------------------------------------------------
    def decode(self, a: np.ndarray) -> Assignment:
        """Map a candidate ``A[P, R]`` of broker indices back to
        reassignment JSON (leader = slot 0 = ``replicas[0]``,
        ``README.md:65-78``). One vectorized id translation; the Python
        loop only assembles the output objects (at 10k partitions the
        per-element indexing version cost ~0.1 s of the warm solve)."""
        valid = self.slot_valid
        ids = self.broker_ids[np.where(valid, a, 0)].tolist()
        rfs = self.rf.tolist()
        topic_names = [self.topics[t] for t in self.topic_of_part.tolist()]
        pids = self.part_id.tolist()
        parts = [
            PartitionAssignment(
                topic=topic_names[p],
                partition=pids[p],
                replicas=ids[p][: rfs[p]],
            )
            for p in range(self.num_parts)
        ]
        return Assignment(partitions=parts)

    def encode(self, plan: Assignment) -> np.ndarray:
        """Inverse of :meth:`decode`: map a plan in reassignment-JSON
        form onto this instance's index space ``A[P, R]`` (slot 0 =
        ``replicas[0]`` = leader). The plan must cover exactly this
        instance's (topic, partition) set, with each replica list no
        longer than the partition's target RF (the index space cannot
        represent extra replicas, and silently truncating them would
        let an over-replicated plan audit as feasible) — structural
        mismatches raise. Everything representable is ENCODED rather
        than judged: ineligible brokers map to the null bucket ``B``
        (surfacing as ``null_in_valid_slot`` violations), duplicated
        brokers land in their slots (``duplicate_in_partition``), and
        short replica lists leave null slots — so external plans, e.g.
        ``kafka-reassign-partitions`` output, get scored and certified
        by the same oracle as every solver's."""
        B = self.num_brokers
        by_key: dict[tuple[str, int], list[int]] = {}
        for p in plan.partitions:
            key = (p.topic, p.partition)
            if key in by_key:
                # last-wins dict building would silently dedupe a
                # malformed plan listing the same partition twice (with
                # possibly conflicting replica lists) — a structural
                # mismatch, so it raises like the others
                raise ValueError(
                    f"plan lists partition {key[0]}/{key[1]} more than once"
                )
            by_key[key] = p.replicas
        idx_of_broker = {int(b): i for i, b in enumerate(self.broker_ids)}
        a = np.full((self.num_parts, self.max_rf), B, dtype=np.int32)
        topic_names = [self.topics[t] for t in self.topic_of_part.tolist()]
        pids = self.part_id.tolist()
        rfs = self.rf.tolist()
        seen = set()
        for p in range(self.num_parts):
            key = (topic_names[p], pids[p])
            if key not in by_key:
                raise ValueError(
                    f"plan is missing partition {key[0]}/{key[1]}"
                )
            seen.add(key)
            reps = by_key[key]
            if len(reps) > rfs[p]:
                raise ValueError(
                    f"plan has {len(reps)} replicas for "
                    f"{key[0]}/{key[1]} but the target RF is {rfs[p]} "
                    "(pass target_rf / --rf to audit at a different RF)"
                )
            for s, broker in enumerate(reps):
                a[p, s] = idx_of_broker.get(int(broker), B)
        extra = set(by_key) - seen
        if extra:
            raise ValueError(
                f"plan contains unknown partitions: {sorted(extra)[:3]}"
            )
        return a

    # -- feasibility / scoring (numpy reference; oracle for all backends) --
    def violations(self, a: np.ndarray) -> dict[str, int]:
        """Exact integer violation counts of the inequality families for a
        candidate in index space. All zeros == feasible. Also validates the
        hard-encoded families (rf/leader/uniqueness) defensively."""
        B, K, P, R = self.num_brokers, self.num_racks, self.num_parts, self.max_rf
        valid = self.slot_valid
        a = np.asarray(a)
        flat = np.where(valid, a, B)
        # per-broker totals (replica+leader vars together, README.md:158-161)
        cnt = np.bincount(flat.ravel(), minlength=B + 1)[:B]
        lead = np.bincount(np.where(self.rf > 0, a[:, 0], B), minlength=B + 1)[:B]
        rk = self.rack_of_broker[flat]  # [P, R], null -> K
        rcnt = np.bincount(rk.ravel(), minlength=K + 1)[:K]
        # per (partition, rack) counts via one bincount over the
        # flattened (partition, rack) key — np.add.at's per-element
        # scatter cost ~0.3 s per call at 50k partitions, and this
        # oracle runs several times per solve (ISSUE 10)
        pr = np.bincount(
            (np.arange(P, dtype=np.int64)[:, None] * (K + 1)
             + rk).ravel(),
            minlength=P * (K + 1),
        ).reshape(P, K + 1)
        pr = pr[:, :K]

        def band(x, lo, hi):
            return int(np.maximum(x - hi, 0).sum() + np.maximum(lo - x, 0).sum())

        srt = np.sort(flat, axis=1)
        dup = int(
            ((srt[:, 1:] == srt[:, :-1]) & (srt[:, 1:] < B)).sum()
        )
        return {
            "broker_balance": band(cnt, self.broker_lo, self.broker_hi),
            "leader_balance": band(lead, self.leader_lo, self.leader_hi),
            "rack_balance": band(rcnt, self.rack_lo, self.rack_hi),
            "part_rack_diversity": int(
                np.maximum(pr - self.part_rack_hi[:, None], 0).sum()
            ),
            # hard-encoded families, checked defensively:
            "slot_out_of_range": int(((flat < 0) | (flat > B)).sum()),
            "null_in_valid_slot": int((flat[valid] >= B).sum()),
            "duplicate_in_partition": dup,
        }

    def is_feasible(self, a: np.ndarray) -> bool:
        return all(v == 0 for v in self.violations(a).values())

    def preservation_weight(self, a: np.ndarray) -> int:
        """Objective value (maximized): sum of kept-assignment weights."""
        P = self.num_parts
        a = np.asarray(a)
        valid = self.slot_valid
        rows = np.arange(P)
        w = int(self.w_leader[rows, a[:, 0]][self.rf > 0].sum())
        if self.max_rf > 1:
            foll = self.w_follower[rows[:, None], a[:, 1:]]
            w += int(foll[valid[:, 1:]].sum())
        return w

    def max_weight(self) -> int:
        """Exact unconstrained per-partition optimum of the preservation
        weight (ignoring the balance constraints): for each partition, the
        best choice of leader among weighted brokers (or an unweighted
        one) plus the best rf-1 positive follower weights among the rest.
        A true upper bound on any feasible plan's objective.

        Vectorized over partitions (it sits on the warm solve path via
        ``certify_optimal``): with v_1 >= v_2 >= ... the clipped-positive
        follower weights of partition p and s_k their prefix sums, leader
        b scores  w_lead[b] + (s_{rf-1} - v(b) + v_rf  if v(b) >= v_{rf-1}
        else s_{rf-1})  — removing one instance of b's follower value from
        the top set and backfilling with the next-best; only values
        matter, so ties need no identity tracking."""
        r = self._leader_vals()
        if r is None:
            return 0
        val, s_rm1, _ = r
        best = np.maximum(val.max(axis=1), s_rm1)
        return int(best[self.rf > 0].sum())

    def _leader_vals(self, *a, **k):
        """Delegates to ``models.bounds._leader_vals`` (the bound/
        reseat machinery moved out of the data model, r5)."""
        from . import bounds
        return bounds._leader_vals(self, *a, **k)

    def weight_upper_bound(self, *a, **k):
        """Delegates to ``models.bounds.weight_upper_bound`` (the bound/
        reseat machinery moved out of the data model, r5)."""
        from . import bounds
        return bounds.weight_upper_bound(self, *a, **k)

    def _memo_lock(self) -> threading.Lock:
        lock = getattr(self, "_bounds_memo_lock", None)
        if lock is None:
            with _MEMO_GUARD:
                lock = getattr(self, "_bounds_memo_lock", None)
                if lock is None:
                    lock = threading.Lock()
                    self._bounds_memo_lock = lock
        return lock

    def cancel_pending_bounds(self) -> None:
        """Tell straggling bound workers to stop escalating: tiers not
        yet memoized are skipped (un-memoized) on the next check. The
        in-flight HiGHS solve still runs to its time limit — scipy
        cannot be interrupted — but no NEW tier starts. Engines call
        this when their solve returns so a daemon bounds thread cannot
        grind multi-second LPs into the next request's wall-clock."""
        self._bounds_cancelled = True

    def set_bounds_deadline(self, budget_s: float | None) -> None:
        """Give the bound LPs a wall-clock budget: each subsequent LP
        gets ``min(30 s, time remaining)`` as its HiGHS time limit, and
        LPs starting after the deadline are skipped outright (the bound
        ladder then falls back to the cheapest computed level — looser,
        never unsound). Used by deadline-sensitive callers: the serve
        audit endpoint (``--max-solve-s``) and the engine's bounds
        worker."""
        self._bounds_deadline = (
            None if budget_s is None else time.perf_counter() + budget_s
        )

    def _lp_options(self, default_limit: float = 30.0) -> dict | None:
        """HiGHS options for one bound LP under the instance deadline;
        None when the deadline has already passed (caller skips)."""
        d = getattr(self, "_bounds_deadline", None)
        if d is None:
            return {"time_limit": default_limit}
        remaining = d - time.perf_counter()
        if remaining <= 0.05:
            return None
        return {"time_limit": min(default_limit, remaining)}

    def best_known_weight_ub(self) -> int | None:
        """The tightest weight upper bound evaluated so far (for
        reports), or None if none has been."""
        memo = getattr(self, "_wub_memo", None)
        if not memo:
            return None
        # .copy() is atomic under the GIL; a bounds worker thread may be
        # inserting a tier concurrently
        return min(memo.copy().values())

    def move_lower_bound_exact(self, *a, **k):
        """Delegates to ``models.bounds.move_lower_bound_exact`` (the bound/
        reseat machinery moved out of the data model, r5)."""
        from . import bounds
        return bounds.move_lower_bound_exact(self, *a, **k)

    def _members(self):
        """(mrows, mcols): the (partition, broker) pairs whose slot could
        be *kept* — current eligible members of live partitions.
        Memoized: the bound ladder, the plan constructor and the
        disaggregator each re-derive it, and the nonzero scan costs
        ~0.12 s at the 50k-partition jumbo — repeated four times that
        was a measurable slice of the construct path (ISSUE 10). The
        weight matrices are immutable after build, so the memo can
        never go stale; a concurrent double fill is benign (identical
        value)."""
        cached = getattr(self, "_members_memo", None)
        if cached is None:
            B = self.num_brokers
            cached = np.nonzero(
                ((self.w_leader[:, :B] > 0) | (self.w_follower[:, :B] > 0))
                & (self.rf[:, None] > 0)
            )
            self._members_memo = cached
        return cached

    def _kept_maxflow(self, *a, **k):
        """Delegates to ``models.bounds._kept_maxflow`` (the bound/
        reseat machinery moved out of the data model, r5)."""
        from . import bounds
        return bounds._kept_maxflow(self, *a, **k)

    def _flow_prologue(self, *a, **k):
        """Delegates to ``models.bounds._flow_prologue`` (the bound/
        reseat machinery moved out of the data model, r5)."""
        from . import bounds
        return bounds._flow_prologue(self, *a, **k)

    def _leader_cap_flow(self, *a, **k):
        """Delegates to ``models.bounds._leader_cap_flow`` (the bound/
        reseat machinery moved out of the data model, r5)."""
        from . import bounds
        return bounds._leader_cap_flow(self, *a, **k)

    def _leader_cap_flow_lower(self, *a, **k):
        """Delegates to ``models.bounds._leader_cap_flow_lower`` (the bound/
        reseat machinery moved out of the data model, r5)."""
        from . import bounds
        return bounds._leader_cap_flow_lower(self, *a, **k)

    def _leader_cap_lp(self, *a, **k):
        """Delegates to ``models.bounds._leader_cap_lp`` (the bound/
        reseat machinery moved out of the data model, r5)."""
        from . import bounds
        return bounds._leader_cap_lp(self, *a, **k)

    def _kept_weight_lp(self, *a, **k):
        """Delegates to ``models.bounds._kept_weight_lp`` (the bound/
        reseat machinery moved out of the data model, r5)."""
        from . import bounds
        return bounds._kept_weight_lp(self, *a, **k)

    def _member_classes(self, *a, **k):
        """Delegates to ``models.bounds._member_classes`` (the bound/
        reseat machinery moved out of the data model, r5)."""
        from . import bounds
        return bounds._member_classes(self, *a, **k)

    def agg_effective(self) -> bool:
        """True when partition symmetry collapses the member space
        enough that the AGGREGATED kept-replica formulation (LP and
        MILP) is cheap — the gate for preferring it over the
        unaggregated LP in the bound ladder and for racing the
        aggregated plan constructor on any instance, not just the
        over-threshold ones. Steady-state round-robin clusters (the
        benchmark family, and real Kafka clusters after a balanced
        tool pass) collapse by 50-500x; adversarial distinct-weight
        clusters do not, and this returns False. The gate is a pure
        collapse RATIO (>= 8x) — no absolute floor — so small or
        asymmetric instances keep the annealer path (and its CI
        coverage) instead of degenerating into a host MILP solve."""
        members = self._members()[0].size
        if members == 0:
            return False
        n_cm = self._member_classes()[3].size
        return n_cm * 8 <= members

    def agg_construct_viable(self) -> bool:
        """True when the AGGREGATED kept-weight formulation would
        accept this instance rather than refuse: small enough to grind
        regardless (<= 20k members), or class collapse of at least 4x.
        ``_kept_weight_agg``'s refusal and the engine's constructor-race
        gate share this predicate so the two can never drift — past the
        unaggregated-LP size a refusal here means the constructor has
        NO viable path and racing it only delays the annealer."""
        members = self._members()[0].size
        if members <= 20_000:
            return True
        # n_cm <= members // 4 for integers — the refusal's complement
        return self._member_classes()[3].size * 4 <= members

    def _kept_weight_agg(self, *a, **k):
        """Delegates to ``models.bounds._kept_weight_agg`` (the bound/
        reseat machinery moved out of the data model, r5)."""
        from . import bounds
        return bounds._kept_weight_agg(self, *a, **k)

    def best_leader_assignment(self, *a, **k):
        """Delegates to ``models.reseat.best_leader_assignment`` (the bound/
        reseat machinery moved out of the data model, r5)."""
        from . import reseat
        return reseat.best_leader_assignment(self, *a, **k)

    def _best_leader_lp(self, *a, **k):
        """Delegates to ``models.reseat._best_leader_lp`` (the bound/
        reseat machinery moved out of the data model, r5)."""
        from . import reseat
        return reseat._best_leader_lp(self, *a, **k)

    def _reseat_cycle_cancel(self, *a, **k):
        """Delegates to ``models.reseat._reseat_cycle_cancel`` (the bound/
        reseat machinery moved out of the data model, r5)."""
        from . import reseat
        return reseat._reseat_cycle_cancel(self, *a, **k)

    def move_count(self, a: np.ndarray) -> int:
        """Replica moves vs the current assignment: count of valid slots
        whose broker is not in the partition's current (eligible) replica
        set. Membership test uses the weight matrices: every currently
        assigned eligible broker carries nonzero leader weight."""
        a = np.asarray(a)
        member = self.w_leader[np.arange(self.num_parts)[:, None], a] > 0
        return int((~member & self.slot_valid).sum())

    def move_lower_bound(self) -> int:
        """Provable lower bound on ``move_count`` over ALL feasible plans,
        from a counting relaxation of "how many slots can possibly be
        kept": a kept slot holds a current eligible member of its
        partition, each partition keeps at most min(rf, |members|) of them
        (at most ``part_rack_hi`` per rack), each broker hosts at most
        ``broker_hi`` total and appears in at most m_b = |{p : b member}|
        partitions, each rack holds at most ``rack_hi`` total. Every
        non-kept valid slot is one move, so

            moves >= total_replicas - min(A, B, C)

        with A/B/C the per-partition / per-broker / per-rack kept caps.
        Arrival counting gives two more bounds: a broker below
        ``broker_lo`` needs (lo - m_b) incoming moves, a rack below its
        ``rack_lo`` likewise. The max of all bounds is returned. It
        reproduces the hand-derived bounds of every benchmark scenario
        (``utils/gen.py``): decommission (slots on the removed broker),
        rf_change (new slots have no members), scale_out (empty brokers
        must absorb floor(R/B) each), leader_only (0)."""
        B, K = self.num_brokers, self.num_racks
        member = self.w_leader > 0  # [P, B+?]; columns past B are unused
        member = member[:, :B]
        m_b = member.sum(axis=0).astype(np.int64)  # [B]
        rack = self.rack_of_broker[:B]  # [B] rack index of each broker

        # A: per-partition kept cap, rack-diversity aware. Per-rack
        # column-group sums via reduceat over rack-sorted columns: the
        # np.add.at scatter this replaces cost ~0.3 s at 50k
        # partitions, on the bounds_flow critical path (ISSUE 10).
        # Racks are nonempty by construction (rack_names derive from
        # the brokers), so no reduceat empty-segment edge case.
        order = np.argsort(rack, kind="stable")
        starts = np.searchsorted(rack[order], np.arange(K))
        mem_rack = np.add.reduceat(
            member[:, order].astype(np.int64), starts, axis=1
        )
        per_part = np.minimum(mem_rack, self.part_rack_hi[:, None]).sum(1)
        a_cap = int(np.minimum(self.rf, per_part).sum())

        # B: per-broker kept cap;  C: per-rack kept cap
        capped_b = np.minimum(m_b, self.broker_hi)
        b_cap = int(capped_b.sum())
        per_rack = np.bincount(rack, weights=capped_b, minlength=K)[:K]
        c_cap = int(np.minimum(per_rack, self.rack_hi).sum())

        lb_kept = self.total_replicas - min(a_cap, b_cap, c_cap)
        # arrival bounds (each move lands exactly one replica somewhere)
        lb_broker_in = int(np.maximum(self.broker_lo - m_b, 0).sum())
        mk = np.bincount(rack, weights=m_b, minlength=K)[:K]
        lb_rack_in = int(np.maximum(self.rack_lo - mk, 0).sum())
        return max(lb_kept, lb_broker_in, lb_rack_in, 0)

    def caps_bind(self) -> bool:
        """True when balance bands bind against the CURRENT assignment —
        over-full or under-floor brokers for either replicas or
        leaderships. These are exactly the instances where (a) local
        search must trade keeps against bands and plateaus epsilon below
        the optimum, and (b) the LP-rounding constructor
        (``solvers.lp_round``) tends to produce a certified optimum
        outright: scale-outs, leader-skew rebalances, RF changes. A
        plain decommission triggers neither side."""
        B = self.num_brokers
        m_b = (self.w_leader[:, :B] > 0).sum(axis=0)
        lead = self.a0[:, 0]
        ok = (
            (self.rf > 0)
            & (lead >= 0)
            & (lead < B)
            & (self.w_leader[np.arange(self.num_parts),
                             np.clip(lead, 0, B - 1)] > 0)
        )
        lcnt = np.bincount(lead[ok], minlength=B)[:B]
        return bool(
            (m_b > self.broker_hi).any()
            or (m_b < self.broker_lo).any()
            or (lcnt > self.leader_hi).any()
            or (lcnt < self.leader_lo).any()
        )

    def certify_optimal(self, *a, **k):
        """Delegates to ``models.bounds.certify_optimal`` (the bound/
        reseat machinery moved out of the data model, r5)."""
        from . import bounds
        return bounds.certify_optimal(self, *a, **k)

def build_instance(
    current: Assignment,
    broker_list: Sequence[int],
    topology: Topology | None = None,
    target_rf: int | dict[str, int] | None = None,
) -> ProblemInstance:
    """Build the solver-neutral model from raw inputs (reference L0->L1-L3,
    ``README.md:46-63, 106-133``)."""
    broker_ids = np.array(sorted(set(int(b) for b in broker_list)), dtype=np.int32)
    B = len(broker_ids)
    if B == 0:
        raise ValueError("empty broker list")

    if topology is None:
        topology = Topology.single_rack(broker_ids.tolist())
    rack_names = sorted({topology.rack(int(b)) for b in broker_ids})
    rack_idx = {r: i for i, r in enumerate(rack_names)}
    K = len(rack_names)
    rack_of_broker = np.full(B + 1, K, dtype=np.int32)
    for i, b in enumerate(broker_ids):
        rack_of_broker[i] = rack_idx[topology.rack(int(b))]

    parts = sorted(current.partitions, key=lambda p: (p.topic, p.partition))
    topics = []
    topic_idx: dict[str, int] = {}
    for p in parts:
        if p.topic not in topic_idx:
            topic_idx[p.topic] = len(topics)
            topics.append(p.topic)
    P = len(parts)

    if isinstance(target_rf, dict):
        # a typo'd topic would otherwise be silently ignored and the
        # operator would apply a plan believing RF was raised
        unknown = sorted(set(target_rf) - set(topic_idx))
        if unknown:
            raise ValueError(
                f"target_rf names unknown topic(s) {unknown}; "
                f"assignment has {sorted(topic_idx)}"
            )

    def rf_for(p: PartitionAssignment) -> int:
        if target_rf is None:
            return len(p.replicas)
        if isinstance(target_rf, dict):
            return int(target_rf.get(p.topic, len(p.replicas)))
        return int(target_rf)

    rf = np.array([rf_for(p) for p in parts], dtype=np.int32)
    if (rf <= 0).any():
        raise ValueError("replication factor must be >= 1")
    if (rf > B).any():
        raise ValueError("replication factor exceeds broker count")
    R = int(rf.max())

    topic_of_part = np.array([topic_idx[p.topic] for p in parts], dtype=np.int32)
    part_id = np.array([p.partition for p in parts], dtype=np.int32)

    # current assignment -> index space; ineligible brokers -> null
    # bucket B. Vectorized over one flattened (partition, slot, broker)
    # view (ISSUE 10): the per-partition Python fills cost ~0.35 s at
    # the 50k-partition jumbo, on every solve's cold path. Broker-id ->
    # index translation is a searchsorted over the (sorted) broker_ids.
    rep_counts = np.fromiter(
        (len(p.replicas) for p in parts), np.int64, count=P
    )
    n_flat = int(rep_counts.sum())
    flat_b = np.fromiter(
        (int(b) for p in parts for b in p.replicas), np.int64,
        count=n_flat,
    )
    rows = np.repeat(np.arange(P, dtype=np.int64), rep_counts)
    starts = np.concatenate([[0], np.cumsum(rep_counts)[:-1]]) \
        if P else np.zeros(0, np.int64)
    slots = np.arange(n_flat, dtype=np.int64) - starts[rows] \
        if n_flat else np.zeros(0, np.int64)
    pos = np.searchsorted(broker_ids, flat_b)
    eligible = (pos < B) & (
        broker_ids[np.minimum(pos, B - 1)] == flat_b
    )
    idx = np.where(eligible, pos, B).astype(np.int32)
    a0 = np.full((P, R), B, dtype=np.int32)
    in_range = slots < R
    a0[rows[in_range], slots[in_range]] = idx[in_range]

    # objective weights (README.md:116-133, 146): see module docstring.
    # Follower tiers first (duplicate scatters write the same constant,
    # so last-wins assignment equals the legacy max), then the leader
    # tier overwrites — reproducing the legacy slot-order semantics
    # where a broker appearing as both leader and follower keeps the
    # leader weights.
    w_leader = np.zeros((P, B + 1), dtype=np.int32)
    w_follower = np.zeros((P, B + 1), dtype=np.int32)
    foll = eligible & (slots > 0)
    w_leader[rows[foll], idx[foll]] = W_FOLLOWER_PROMOTE
    w_follower[rows[foll], idx[foll]] = W_FOLLOWER_KEEP
    lead = eligible & (slots == 0)
    w_leader[rows[lead], idx[lead]] = W_LEADER_KEEP
    w_follower[rows[lead], idx[lead]] = W_LEADER_DEMOTE

    # bound arithmetic (README.md:158-180; SURVEY §2 rules)
    r_tot = int(rf.sum())
    broker_lo, broker_hi = r_tot // B, -(-r_tot // B)
    leader_lo, leader_hi = P // B, -(-P // B)
    rack_sizes = np.array(
        [int((rack_of_broker[:B] == k).sum()) for k in range(K)], dtype=np.int64
    )
    rack_lo = (r_tot * rack_sizes) // B
    rack_hi = -((-r_tot * rack_sizes) // B)
    part_rack_hi = -(-rf // K)

    # --- satisfiability repair (balance bands are preferences: they must
    # never make the instance infeasible). Equal-size racks satisfy every
    # condition below as-is and reproduce the reference sample's exact
    # bounds unchanged (README.md:173-176); lopsided topologies (found by
    # the r2 property fuzz: a 1-broker rack + diversity caps can make the
    # proportional ceilings under-supply r_tot, which the exact MILP
    # reports as infeasible) get the minimal relaxation that admits a
    # plan. Steps:
    #   1. per-partition: the diversity cap c_p must allow rf_p replicas
    #      across racks given each rack's broker count (uniqueness).
    #   2. per-rack: tighten the band to the true implied extremes
    #      [m_k, M_k] (no semantic change), then
    #   3. jointly: relax ceilings/floors until sum(hi) covers r_tot and
    #      sum(lo) <= r_tot.
    #   4. broker bands: every rack's brokers must supply its floor, and
    #      the global per-broker supply must cover r_tot under the rack
    #      ceilings.
    cap_pk = np.minimum(part_rack_hi[:, None], rack_sizes[None, :])
    short = rf - cap_pk.sum(1)
    while (short > 0).any():  # step 1 (terminates: B >= rf checked)
        part_rack_hi = part_rack_hi + (short > 0)
        cap_pk = np.minimum(part_rack_hi[:, None], rack_sizes[None, :])
        short = rf - cap_pk.sum(1)
    M = cap_pk.sum(0)  # [K] true max replicas rack k can hold
    m = np.maximum(  # [K] forced minimum (others at their caps)
        rf[:, None] - (cap_pk.sum(1)[:, None] - cap_pk), 0
    ).sum(0)
    rack_hi = np.maximum(np.minimum(rack_hi, M), m)  # step 2 (m <= M, so
    rack_lo = np.maximum(np.minimum(rack_lo, rack_hi), m)  # lo <= hi holds)
    # steps 3a/3b converge in <= K+1 passes: every non-final pass clips
    # at least one rack at its extreme
    for _ in range(K + 1):  # step 3a: raise ceilings toward M
        deficit = r_tot - int(rack_hi.sum())
        head = M - rack_hi
        if deficit <= 0 or not (head > 0).any():
            break
        add = -(-deficit // max(int((head > 0).sum()), 1))
        rack_hi = np.minimum(rack_hi + np.where(head > 0, add, 0), M)
    for _ in range(K + 1):  # step 3b: lower floors toward m
        excess = int(rack_lo.sum()) - r_tot
        slack = rack_lo - m
        if excess <= 0 or not (slack > 0).any():
            break
        sub = -(-excess // max(int((slack > 0).sum()), 1))
        rack_lo = np.maximum(rack_lo - np.where(slack > 0, sub, 0), m)
    # step 4: per-broker band vs rack floors/ceilings
    broker_hi = max(broker_hi, int(np.max(-(-rack_lo // rack_sizes))))
    supply = lambda h: int(np.minimum(rack_sizes * h, rack_hi).sum())  # noqa: E731
    while supply(broker_hi) < r_tot and broker_hi < r_tot:
        broker_hi += 1
    broker_lo = min(broker_lo, int(np.min(rack_hi // rack_sizes)))

    inst = ProblemInstance(
        broker_ids=broker_ids,
        rack_of_broker=rack_of_broker,
        rack_names=rack_names,
        topics=topics,
        topic_of_part=topic_of_part,
        part_id=part_id,
        rf=rf,
        a0=a0,
        current=current,
        w_leader=w_leader,
        w_follower=w_follower,
        broker_lo=int(broker_lo),
        broker_hi=int(broker_hi),
        leader_lo=int(leader_lo),
        leader_hi=int(leader_hi),
        rack_lo=rack_lo.astype(np.int32),
        rack_hi=rack_hi.astype(np.int32),
        part_rack_hi=part_rack_hi.astype(np.int32),
    )
    return inst
