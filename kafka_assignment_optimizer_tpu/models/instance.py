"""Solver-neutral optimization model (the reference's L1-L3 layers).

Builds, from (current assignment, target broker list, topology, target RF),
the single :class:`ProblemInstance` that *every* solver backend consumes —
the LP emitter, the MILP oracle, the native C++ branch-and-bound, and the
JAX/TPU annealing engine. Mirrors the reference's model-builder stage
(``/root/reference/README.md:106-133``) but uses dense index arrays rather
than named LP variables; the ``t{t}b{b}p{p}[_l]`` naming survives only in
the LP emitter.

Key representation decision (TPU-first): candidates are *replica-slot*
arrays ``A[P, R] : int`` of broker **indices** with slot 0 = leader —
matching the reference's leader-first JSON convention
(``README.md:52-78``). This hard-encodes the equality constraints
(replication factor ``README.md:148-151``, one leader ``README.md:153-156``,
per-broker uniqueness ``README.md:168-171``) by construction, leaving only
the inequality families as penalty terms for the search backends.

Constraint families and their bound arithmetic (derived from the worked LP
sample, ``README.md:144-185``):

- replicas/broker  in [floor(R_tot/B), ceil(R_tot/B)]   (``README.md:158-161``)
  NOTE: the reference sample shows ``>= 1`` in a 32-broker/20-replica
  cluster where floor(20/32)=0 — the sample is elided/illustrative and
  underdetermines the exact rule; floor/ceil is the self-consistent choice
  and reproduces the demo optimum (golden test).
- leaders/broker   in [floor(P/B),     ceil(P/B)]       (``README.md:163-166``)
- replicas/rack    in [floor(R_tot*B_k/B), ceil(R_tot*B_k/B)] per rack k with
  B_k brokers — proportional form; reduces to the sample's exact R_tot/K
  when racks are equal-sized (``README.md:173-176``)
- replicas of one partition per rack <= ceil(RF/K)      (``README.md:178-180``)

Objective weights (observed data points ``README.md:146``; ordering rule
"leader-keep > follower-keep > new" per ``README.md:116-133``):

- current preferred leader broker: leader-role weight 4, follower-role 2
- current follower broker:         leader-role weight 2, follower-role 1
- any other broker: 0

This exact rule reproduces every coefficient shown in the reference sample
and the demo's 1-move optimum (golden test).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

_log = logging.getLogger(__name__)

# guards creation of per-instance memo locks (instances are dataclasses;
# the lock attribute is created lazily on first bound computation)
_MEMO_GUARD = threading.Lock()

# member count past which the UNaggregated kept-replica LP is considered
# intractable (the 50k-partition jumbo's ~150k members time out at 900 s)
# and the symmetry-aggregated formulation takes over — in the bound
# ladder and in the plan constructor (solvers.lp_round)
AGG_MEMBER_THRESHOLD = 60_000

from .cluster import Assignment, PartitionAssignment, Topology

# Objective weight tiers (README.md:146 observed values).
W_LEADER_KEEP = 4  # current leader stays leader
W_LEADER_DEMOTE = 2  # current leader stays as follower
W_FOLLOWER_PROMOTE = 2  # current follower becomes leader
W_FOLLOWER_KEEP = 1  # current follower stays follower



def _safe_floor_ub(neg_fun: float) -> int:
    """Floor an LP maximum into a still-valid integer upper bound.

    The slack must dominate the solver's possible objective undershoot
    (termination tolerances are RELATIVE, so a fixed absolute epsilon
    fails at large objective scales); 1e-6 relative can at worst loosen
    a razor-edge bound by 1, never tighten it below the true optimum."""
    v = -neg_fun
    return int(np.floor(v + 1e-6 * max(1.0, abs(v))))


def _dual_repair_max_ub(c, a_ub, b_ub, a_eq, b_eq, lo, hi, res):
    """Certified upper bound on ``max -c'x`` from an (approximate) LP
    solve, via dual-feasibility repair — sound even when the primal
    iterate undershoots the true optimum (e.g. ``highs-ipm`` without
    crossover, whose termination tolerance is all that protects the
    primal value).

    Takes the solver's constraint marginals as a *starting point* for
    the dual (lam = -ineq marginals clamped >= 0, mu = -eq marginals),
    then restores exact dual stationarity by absorbing the residual
    ``r = c + A_ub' lam + A_eq' mu`` into the variable-bound duals
    (alpha = max(r, 0) on x >= lo, beta = max(-r, 0) on x <= hi). Any
    such (lam, mu, alpha, beta) is dual feasible, so by weak duality

        min c'x  >=  -lam'b_ub - mu'b_eq + alpha'lo - beta'hi

    and ``max -c'x <= -that``. Returns the float bound, or None when
    the solve carried no marginals (then the caller falls back to the
    primal value, which is exact for simplex/crossover methods)."""
    try:
        m_ub = getattr(res.ineqlin, "marginals", None)
        m_eq = getattr(res.eqlin, "marginals", None)
        if m_ub is None or m_eq is None:
            return None
        lam = np.maximum(-np.asarray(m_ub, dtype=np.float64), 0.0)
        mu = -np.asarray(m_eq, dtype=np.float64)
        r = np.asarray(c, dtype=np.float64)
        if lam.size:
            r = r + a_ub.T @ lam
        if mu.size:
            r = r + a_eq.T @ mu
        alpha = np.maximum(r, 0.0)
        beta = np.maximum(-r, 0.0)
        dual = (
            -(lam @ b_ub if lam.size else 0.0)
            - (mu @ b_eq if mu.size else 0.0)
            + alpha @ lo
            - beta @ hi
        )
        return float(-dual)
    except Exception:
        return None


@dataclass
class ProblemInstance:
    """Dense, index-based optimization model.

    Broker axis is *eligible brokers only* (the target ``--broker-list``);
    ``broker_ids[i]`` maps index -> Kafka broker id. Index ``B`` (one past
    the end) is the shared "null bucket" used for padded replica slots, so
    histograms can be built with scatter-adds without branching.
    """

    # topology / broker axis
    broker_ids: np.ndarray  # [B] int32, sorted eligible Kafka broker ids
    rack_of_broker: np.ndarray  # [B+1] int32 rack index; null bucket -> K
    rack_names: list[str]
    # partition axis (all topics flattened)
    topics: list[str]
    topic_of_part: np.ndarray  # [P] int32 topic index
    part_id: np.ndarray  # [P] int32 kafka partition id within topic
    rf: np.ndarray  # [P] int32 target replication factor
    # current assignment, in broker-*index* space, -? see below
    # A0[p, s] = broker index of current replica in slot s (slot 0 leader),
    #            B (null) if slot unused or broker not eligible.
    a0: np.ndarray  # [P, R] int32
    # current assignment in raw broker-id space (for diffs / weights incl.
    # ineligible brokers)
    current: Assignment = field(repr=False, default=None)
    # objective weights, [P, B+1] int32 (null bucket column always 0)
    w_leader: np.ndarray = field(repr=False, default=None)
    w_follower: np.ndarray = field(repr=False, default=None)
    # inequality-constraint bounds
    broker_lo: int = 0
    broker_hi: int = 0
    leader_lo: int = 0
    leader_hi: int = 0
    rack_lo: np.ndarray = None  # [K] int32
    rack_hi: np.ndarray = None  # [K] int32
    part_rack_hi: np.ndarray = None  # [P] int32: ceil(rf/K)

    # -- sizes ----------------------------------------------------------
    @property
    def num_brokers(self) -> int:
        return int(self.broker_ids.shape[0])

    @property
    def num_parts(self) -> int:
        return int(self.topic_of_part.shape[0])

    @property
    def num_racks(self) -> int:
        return len(self.rack_names)

    @property
    def max_rf(self) -> int:
        return int(self.a0.shape[1])

    @property
    def total_replicas(self) -> int:
        return int(self.rf.sum())

    @property
    def slot_valid(self) -> np.ndarray:
        """[P, R] bool — slot s is a real replica slot for partition p."""
        return np.arange(self.max_rf)[None, :] < self.rf[:, None]

    # -- decode ---------------------------------------------------------
    def decode(self, a: np.ndarray) -> Assignment:
        """Map a candidate ``A[P, R]`` of broker indices back to
        reassignment JSON (leader = slot 0 = ``replicas[0]``,
        ``README.md:65-78``). One vectorized id translation; the Python
        loop only assembles the output objects (at 10k partitions the
        per-element indexing version cost ~0.1 s of the warm solve)."""
        valid = self.slot_valid
        ids = self.broker_ids[np.where(valid, a, 0)].tolist()
        rfs = self.rf.tolist()
        topic_names = [self.topics[t] for t in self.topic_of_part.tolist()]
        pids = self.part_id.tolist()
        parts = [
            PartitionAssignment(
                topic=topic_names[p],
                partition=pids[p],
                replicas=ids[p][: rfs[p]],
            )
            for p in range(self.num_parts)
        ]
        return Assignment(partitions=parts)

    def encode(self, plan: Assignment) -> np.ndarray:
        """Inverse of :meth:`decode`: map a plan in reassignment-JSON
        form onto this instance's index space ``A[P, R]`` (slot 0 =
        ``replicas[0]`` = leader). The plan must cover exactly this
        instance's (topic, partition) set, with each replica list no
        longer than the partition's target RF (the index space cannot
        represent extra replicas, and silently truncating them would
        let an over-replicated plan audit as feasible) — structural
        mismatches raise. Everything representable is ENCODED rather
        than judged: ineligible brokers map to the null bucket ``B``
        (surfacing as ``null_in_valid_slot`` violations), duplicated
        brokers land in their slots (``duplicate_in_partition``), and
        short replica lists leave null slots — so external plans, e.g.
        ``kafka-reassign-partitions`` output, get scored and certified
        by the same oracle as every solver's."""
        B = self.num_brokers
        by_key: dict[tuple[str, int], list[int]] = {}
        for p in plan.partitions:
            key = (p.topic, p.partition)
            if key in by_key:
                # last-wins dict building would silently dedupe a
                # malformed plan listing the same partition twice (with
                # possibly conflicting replica lists) — a structural
                # mismatch, so it raises like the others
                raise ValueError(
                    f"plan lists partition {key[0]}/{key[1]} more than once"
                )
            by_key[key] = p.replicas
        idx_of_broker = {int(b): i for i, b in enumerate(self.broker_ids)}
        a = np.full((self.num_parts, self.max_rf), B, dtype=np.int32)
        topic_names = [self.topics[t] for t in self.topic_of_part.tolist()]
        pids = self.part_id.tolist()
        rfs = self.rf.tolist()
        seen = set()
        for p in range(self.num_parts):
            key = (topic_names[p], pids[p])
            if key not in by_key:
                raise ValueError(
                    f"plan is missing partition {key[0]}/{key[1]}"
                )
            seen.add(key)
            reps = by_key[key]
            if len(reps) > rfs[p]:
                raise ValueError(
                    f"plan has {len(reps)} replicas for "
                    f"{key[0]}/{key[1]} but the target RF is {rfs[p]} "
                    "(pass target_rf / --rf to audit at a different RF)"
                )
            for s, broker in enumerate(reps):
                a[p, s] = idx_of_broker.get(int(broker), B)
        extra = set(by_key) - seen
        if extra:
            raise ValueError(
                f"plan contains unknown partitions: {sorted(extra)[:3]}"
            )
        return a

    # -- feasibility / scoring (numpy reference; oracle for all backends) --
    def violations(self, a: np.ndarray) -> dict[str, int]:
        """Exact integer violation counts of the inequality families for a
        candidate in index space. All zeros == feasible. Also validates the
        hard-encoded families (rf/leader/uniqueness) defensively."""
        B, K, P, R = self.num_brokers, self.num_racks, self.num_parts, self.max_rf
        valid = self.slot_valid
        a = np.asarray(a)
        flat = np.where(valid, a, B)
        # per-broker totals (replica+leader vars together, README.md:158-161)
        cnt = np.bincount(flat.ravel(), minlength=B + 1)[:B]
        lead = np.bincount(np.where(self.rf > 0, a[:, 0], B), minlength=B + 1)[:B]
        rk = self.rack_of_broker[flat]  # [P, R], null -> K
        rcnt = np.bincount(rk.ravel(), minlength=K + 1)[:K]
        # per (partition, rack) counts
        pr = np.zeros((P, K + 1), dtype=np.int64)
        np.add.at(pr, (np.arange(P)[:, None].repeat(R, 1), rk), 1)
        pr = pr[:, :K]

        def band(x, lo, hi):
            return int(np.maximum(x - hi, 0).sum() + np.maximum(lo - x, 0).sum())

        srt = np.sort(flat, axis=1)
        dup = int(
            ((srt[:, 1:] == srt[:, :-1]) & (srt[:, 1:] < B)).sum()
        )
        return {
            "broker_balance": band(cnt, self.broker_lo, self.broker_hi),
            "leader_balance": band(lead, self.leader_lo, self.leader_hi),
            "rack_balance": band(rcnt, self.rack_lo, self.rack_hi),
            "part_rack_diversity": int(
                np.maximum(pr - self.part_rack_hi[:, None], 0).sum()
            ),
            # hard-encoded families, checked defensively:
            "slot_out_of_range": int(((flat < 0) | (flat > B)).sum()),
            "null_in_valid_slot": int((flat[valid] >= B).sum()),
            "duplicate_in_partition": dup,
        }

    def is_feasible(self, a: np.ndarray) -> bool:
        return all(v == 0 for v in self.violations(a).values())

    def preservation_weight(self, a: np.ndarray) -> int:
        """Objective value (maximized): sum of kept-assignment weights."""
        P = self.num_parts
        a = np.asarray(a)
        valid = self.slot_valid
        rows = np.arange(P)
        w = int(self.w_leader[rows, a[:, 0]][self.rf > 0].sum())
        if self.max_rf > 1:
            foll = self.w_follower[rows[:, None], a[:, 1:]]
            w += int(foll[valid[:, 1:]].sum())
        return w

    def max_weight(self) -> int:
        """Exact unconstrained per-partition optimum of the preservation
        weight (ignoring the balance constraints): for each partition, the
        best choice of leader among weighted brokers (or an unweighted
        one) plus the best rf-1 positive follower weights among the rest.
        A true upper bound on any feasible plan's objective.

        Vectorized over partitions (it sits on the warm solve path via
        ``certify_optimal``): with v_1 >= v_2 >= ... the clipped-positive
        follower weights of partition p and s_k their prefix sums, leader
        b scores  w_lead[b] + (s_{rf-1} - v(b) + v_rf  if v(b) >= v_{rf-1}
        else s_{rf-1})  — removing one instance of b's follower value from
        the top set and backfilling with the next-best; only values
        matter, so ties need no identity tracking."""
        r = self._leader_vals()
        if r is None:
            return 0
        val, s_rm1, _ = r
        best = np.maximum(val.max(axis=1), s_rm1)
        return int(best[self.rf > 0].sum())

    def _leader_vals(self):
        """Per-(partition, candidate-leader) optimum of the preservation
        weight, vectorized on a padded sparse member view. Returns
        ``(val [P, M], s_rm1 [P], ids [P, M])`` — ``val[p, m]`` is the
        best weight of partition p when member ``ids[p, m]`` leads (its
        leader weight plus the best rf-1 positive follower weights among
        the rest), ``s_rm1`` the best weight under a non-member (zero
        weight) leader, padding columns carry ids of -1 and val ==
        s_rm1. None when no weights exist at all."""
        P, B = self.num_parts, self.num_brokers
        if P == 0:
            return None
        wl_full = self.w_leader[:, :B]
        wf_full = self.w_follower[:, :B]
        # weights are sparse (only current members carry any): gather the
        # nonzero (partition, broker) pairs into a padded [P, M] view so
        # the per-leader formula runs on M ~ rf columns, not B
        rows, cols = np.nonzero((wl_full > 0) | (wf_full > 0))
        if rows.size == 0:
            return None
        cnt = np.bincount(rows, minlength=P)
        M = int(cnt.max())
        offs = np.zeros(P + 1, np.int64)
        np.cumsum(cnt, out=offs[1:])
        pos = np.arange(rows.size) - offs[rows]  # rank within its row
        wl = np.zeros((P, M), np.int64)
        wf = np.zeros((P, M), np.int64)
        ids = np.full((P, M), -1, np.int64)
        wl[rows, pos] = wl_full[rows, cols]
        wf[rows, pos] = np.maximum(wf_full[rows, cols], 0)
        ids[rows, pos] = cols
        rf = self.rf.astype(np.int64)
        k = M
        top = -np.sort(-wf, axis=1)  # [P, M] desc
        csum = np.concatenate(
            [np.zeros((P, 1), np.int64), np.cumsum(top, axis=1)], axis=1
        )
        prow = np.arange(P)
        s_rm1 = csum[prow, np.minimum(rf - 1, k)]  # sum of top rf-1
        # with v_1 >= v_2 >= ... the clipped-positive follower weights and
        # s_k their prefix sums, leader m scores wl[m] + (s_{rf-1} - v(m)
        # + v_rf if v(m) >= v_{rf-1} else s_{rf-1}) — removing one
        # instance of m's follower value from the top set and backfilling
        # with the next-best; only values matter, so ties need no
        # identity tracking. v_edge = v_{rf-1} (the weakest kept
        # follower), v_next = v_rf (the backfill).
        v_edge = top[prow, np.clip(rf - 2, 0, k - 1)]
        v_next = np.where(
            rf - 1 < k, top[prow, np.clip(rf - 1, 0, k - 1)], 0
        )
        in_top = (wf >= v_edge[:, None]) & (rf[:, None] >= 2)
        foll_sum = np.where(
            in_top,
            s_rm1[:, None] - wf + v_next[:, None],
            s_rm1[:, None],
        )
        return wl + foll_sum, s_rm1, ids

    def weight_upper_bound(self, tight: bool = False, level: int = 0
                           ) -> int:
        """A constraint-aware upper bound on any feasible plan's
        preservation weight — ``max_weight`` tightened by the balance
        constraints that couple partitions through the objective.

        Leveled by cost, each level memoized, callers escalate only
        when the cheaper level fails to certify:

        - level 0 (``tight=False``, cheap): ``max_weight`` refined by
          the leader-cap transportation LP — leadership gains under the
          per-broker ``leader_hi`` cap (integral polytope, HiGHS via
          scipy, ~1 s at 10k partitions). Tight whenever lower bands
          and follower caps don't bind (demo, decommission, rf_change).
        - level 1: the same LP with per-broker zero-gain-lead slacks,
          the leader band's LOWER side, and the total-leads equality —
          needed when under-leading brokers are FORCED to take
          leaderships (leader-skew rebalances).
        - level 2 (``tight=True``): the joint kept-replica LP
          (``_kept_weight_lp``), which also bands follower keeps and
          forced new replicas per broker/rack — needed when brokers are
          over-full (scale-out). Seconds at 10k partitions, so only on
          explicit request (the engine runs it on a worker thread).
          Past ~60k members the unaggregated LP is intractable (the
          50k-partition jumbo times it out at 900 s) and the tier
          switches to the SYMMETRY-AGGREGATED formulation
          (``_kept_weight_agg``) — the exact same LP optimum at
          ~#classes/#partitions of the cost.
        - level 3: the aggregated kept-replica MILP's branch-and-bound
          dual bound (``_kept_weight_agg(integer=True)``) — integer
          aggregation is a valid relaxation of the true MILP, so this
          can only tighten level 2; time-limited, any size with few
          classes.

        ``certify_optimal`` escalates 0 -> 1 -> 2 -> 3.

        Thread-safe: the tier ladder runs under a per-instance lock
        (the engine prefetches bounds on worker threads while the main
        thread certifies — without the lock both would solve the same
        multi-second LPs). A caller that no longer needs tighter tiers
        (a finished solve with straggling workers) sets
        ``_bounds_cancelled``; not-yet-memoized tiers are then skipped
        WITHOUT memoizing, so the cancellation can never poison a later
        legitimate escalation."""
        level = 2 if tight else level
        with self._memo_lock():
            memo = getattr(self, "_wub_memo", None)
            if memo is None:
                memo = {}
                self._wub_memo = memo
            if 0 not in memo:
                lead = self._leader_cap_lp(with_lower=False)
                mw = self.max_weight()
                memo[0] = mw if lead is None else min(mw, lead)
            # LP cost grows superlinearly in member count; past the
            # aggregation threshold the level-1 LP sticks with the
            # cheaper bound and level 2 switches to the aggregated
            # formulation (exact; see _kept_weight_agg). Level 2 also
            # prefers the aggregated LP whenever symmetry is effective
            # (generated and steady-state round-robin clusters): same
            # bound or tighter, at a fraction of the unaggregated cost.
            big = (
                level >= 1
                and self._members()[0].size > AGG_MEMBER_THRESHOLD
            )
            if level >= 1 and 1 not in memo:
                if getattr(self, "_bounds_cancelled", False):
                    return memo[0]
                # past the threshold the scipy LP is off the table, but
                # the r4 flow fast path stays cheap at any size — so
                # big instances attempt level 1 flow-only instead of
                # skipping the tier outright
                lead = self._leader_cap_lp(with_lower=True,
                                           flow_only=big)
                memo[1] = memo[0] if lead is None else min(memo[0], lead)
            if level >= 2 and 2 not in memo:
                if getattr(self, "_bounds_cancelled", False):
                    return memo[1]
                kept = (
                    self._kept_weight_agg()
                    if big or self.agg_effective() else None
                )
                if kept is None and not big:
                    # aggregation unavailable or refused (solver
                    # failure, deadline): the unaggregated LP is still
                    # tractable here — don't silently degrade the
                    # certificate to the level-1 bound
                    kept = self._kept_weight_lp()
                memo[2] = memo[1] if kept is None else min(memo[1], kept)
            if level >= 3 and 3 not in memo:
                if getattr(self, "_bounds_cancelled", False):
                    return memo[2]
                kept = self._kept_weight_agg(integer=True)
                memo[3] = memo[2] if kept is None else min(memo[2], kept)
            return memo[level]

    def _memo_lock(self) -> threading.Lock:
        lock = getattr(self, "_bounds_memo_lock", None)
        if lock is None:
            with _MEMO_GUARD:
                lock = getattr(self, "_bounds_memo_lock", None)
                if lock is None:
                    lock = threading.Lock()
                    self._bounds_memo_lock = lock
        return lock

    def cancel_pending_bounds(self) -> None:
        """Tell straggling bound workers to stop escalating: tiers not
        yet memoized are skipped (un-memoized) on the next check. The
        in-flight HiGHS solve still runs to its time limit — scipy
        cannot be interrupted — but no NEW tier starts. Engines call
        this when their solve returns so a daemon bounds thread cannot
        grind multi-second LPs into the next request's wall-clock."""
        self._bounds_cancelled = True

    def set_bounds_deadline(self, budget_s: float | None) -> None:
        """Give the bound LPs a wall-clock budget: each subsequent LP
        gets ``min(30 s, time remaining)`` as its HiGHS time limit, and
        LPs starting after the deadline are skipped outright (the bound
        ladder then falls back to the cheapest computed level — looser,
        never unsound). Used by deadline-sensitive callers: the serve
        audit endpoint (``--max-solve-s``) and the engine's bounds
        worker."""
        self._bounds_deadline = (
            None if budget_s is None else time.perf_counter() + budget_s
        )

    def _lp_options(self, default_limit: float = 30.0) -> dict | None:
        """HiGHS options for one bound LP under the instance deadline;
        None when the deadline has already passed (caller skips)."""
        d = getattr(self, "_bounds_deadline", None)
        if d is None:
            return {"time_limit": default_limit}
        remaining = d - time.perf_counter()
        if remaining <= 0.05:
            return None
        return {"time_limit": min(default_limit, remaining)}

    def best_known_weight_ub(self) -> int | None:
        """The tightest weight upper bound evaluated so far (for
        reports), or None if none has been."""
        memo = getattr(self, "_wub_memo", None)
        if not memo:
            return None
        # .copy() is atomic under the GIL; a bounds worker thread may be
        # inserting a tier concurrently
        return min(memo.copy().values())

    def move_lower_bound_exact(self) -> int:
        """Max-flow sharpening of ``move_lower_bound``: moves >=
        total_replicas - maxflow, where the flow network models the kept
        caps JOINTLY (the counting bound takes their min):

            source -(rf_p)-> partition -(part_rack_hi_p)-> (p, rack)
                   -(1 per member)-> broker -(broker_hi)-> rack
                   -(rack_hi_k)-> sink

        Max integral flow == the most slots ANY feasible plan can keep.
        Never weaker than ``move_lower_bound``; memoized; milliseconds
        even at 50k partitions (scipy's C Dinic)."""
        cached = getattr(self, "_move_lb_memo", None)
        if cached is None:
            kept = self._kept_maxflow()
            cheap = self.move_lower_bound()
            cached = cheap if kept is None else max(
                cheap, self.total_replicas - kept
            )
            self._move_lb_memo = cached
        return cached

    def _members(self):
        """(mrows, mcols): the (partition, broker) pairs whose slot could
        be *kept* — current eligible members of live partitions."""
        B = self.num_brokers
        return np.nonzero(
            ((self.w_leader[:, :B] > 0) | (self.w_follower[:, :B] > 0))
            & (self.rf[:, None] > 0)
        )

    def _kept_maxflow(self) -> int | None:
        """Max number of kept slots over all feasible plans (see
        ``move_lower_bound_exact``)."""
        try:
            import scipy.sparse as sp
            from scipy.sparse.csgraph import maximum_flow
        except Exception:
            return None
        mrows, mcols = self._members()
        n = mrows.size
        if n == 0:
            return 0
        try:
            B, K, P = self.num_brokers, self.num_racks, self.num_parts
            rack = self.rack_of_broker[mcols].astype(np.int64)
            pair_key = mrows.astype(np.int64) * K + rack
            pairs, pair_idx = np.unique(pair_key, return_inverse=True)
            U = pairs.size
            # node ids: 0 source | 1..P parts | pairs | brokers | racks | sink
            o_part, o_pair = 1, 1 + P
            o_brok, o_rack = 1 + P + U, 1 + P + U + B
            t = o_rack + K
            live = np.flatnonzero(self.rf > 0)
            src = np.concatenate([
                np.zeros(live.size, np.int64),       # s -> p
                o_part + pairs // K,                 # p -> (p,k)
                o_pair + pair_idx,                   # (p,k) -> b
                np.full(B, 0) + o_brok + np.arange(B),  # b -> rack
                o_rack + np.arange(K),               # rack -> t
            ])
            dst = np.concatenate([
                o_part + live,
                o_pair + np.arange(U),
                o_brok + mcols,
                o_rack + self.rack_of_broker[:B].astype(np.int64),
                np.full(K, t),
            ])
            cap = np.concatenate([
                self.rf[live].astype(np.int64),
                self.part_rack_hi[(pairs // K)].astype(np.int64),
                np.ones(n, np.int64),
                np.full(B, int(self.broker_hi), np.int64),
                self.rack_hi.astype(np.int64),
            ])
            g = sp.csr_matrix(
                (cap.astype(np.int32), (src, dst)), shape=(t + 1, t + 1)
            )
            return int(maximum_flow(g, 0, t).flow_value)
        except Exception:
            return None

    def _flow_prologue(self, gain, rows, cols, ids):
        """Shared guards + arc extraction for the leader-bound flow
        fast paths. Returns ``(mcmf, g_int, b_of, nP, pidx)`` or None
        when the native kernel is unavailable, the bounds deadline is
        spent, or the gains are non-integral — callers fall back to
        the scipy LP in every case."""
        try:
            from ..native import mcmf
        except Exception:
            return None
        if self._lp_options() is None:  # bounds deadline already spent
            return None
        g = gain[rows, cols]
        g_int = np.asarray(g, np.int64)
        if not np.array_equal(g_int, g):
            return None
        b_of = ids[rows, cols].astype(np.int64)
        up, pidx = np.unique(rows, return_inverse=True)
        return mcmf, g_int, b_of, up.size, pidx

    def _leader_cap_flow(self, gain, rows, cols, ids, base) -> int | None:
        """Exact cap-only leader bound on the native min-cost-flow
        kernel (the fast path of ``_leader_cap_lp``): the transportation
        polytope is integral, so integer flows reach the identical LP
        optimum. Returns None (caller falls back to the LP) when the
        shared prologue declines."""
        pro = self._flow_prologue(gain, rows, cols, ids)
        if pro is None:
            return None
        mcmf, g_int, b_of, nP, pidx = pro
        ub, bidx = np.unique(b_of, return_inverse=True)
        nB, n = ub.size, rows.size
        o_b = 1 + nP
        t = o_b + nB
        src = np.concatenate([
            np.zeros(nP, np.int64),      # s -> p
            1 + pidx,                    # p -> broker (gain arcs)
            1 + np.arange(nP),           # p -> t (zero-cost disposal)
            o_b + np.arange(nB),         # broker -> t
        ])
        dst = np.concatenate([
            1 + np.arange(nP),
            o_b + bidx,
            np.full(nP, t, np.int64),
            np.full(nB, t, np.int64),
        ])
        cap = np.concatenate([
            np.ones(nP, np.int64),
            np.ones(n, np.int64),
            np.ones(nP, np.int64),
            np.full(nB, int(self.leader_hi), np.int64),
        ])
        cost = np.concatenate([
            np.zeros(nP, np.int64),
            -g_int,
            np.zeros(nP, np.int64),
            np.zeros(nB, np.int64),
        ])
        try:
            _f, c, _af = mcmf(src, dst, cap, cost, 0, t, t + 1)
        except Exception:
            return None
        return base + int(-c)

    def _leader_cap_flow_lower(self, gain, rows, cols, ids, base,
                               p_active) -> int | None:
        """Exact LEVEL-1 leader bound on the native min-cost-flow
        kernel (the fast path of ``_leader_cap_lp(with_lower=True)``).
        The slack formulation is still a network: the per-broker
        zero-gain lead slack y_b is a POOL node any partition (or the
        source directly, for partitions with no gainful arc) can dump
        into and that feeds every broker at cost 0; the leader band's
        lower side becomes a rewarded broker->sink arc of capacity
        ``leader_lo`` at cost -BIG (BIG > total possible gain, so
        floors fill with absolute priority), the upper side the
        residual ``leader_hi - leader_lo`` at cost 0; the total-leads
        equality is the forced max flow of exactly ``p_active``. The
        polytope is integral, so the integer flow optimum IS the LP
        optimum — with none of the IPM-undershoot dual-repair the LP
        path needs. Returns None (caller falls back to the LP) when
        the shared prologue declines, the flow comes up short of
        ``p_active``, or any floor arc goes unsaturated
        (band-infeasible: the LP verdict decides)."""
        pro = self._flow_prologue(gain, rows, cols, ids)
        if pro is None:
            return None
        mcmf, g_int, b_of, nP, pidx = pro
        B = self.num_brokers
        lo_b = int(self.leader_lo)
        hi_b = int(self.leader_hi)
        big = int(g_int.sum()) + 1
        if big > np.iinfo(np.int32).max:
            # the floor-priority cost -BIG would overflow the kernel's
            # int32 arc costs; the wrapper would raise, the except
            # below would swallow it, and past the flow_only threshold
            # the level-1 tier would SILENTLY degrade to the weaker
            # level-0 bound. Decline loudly instead (ADVICE r4): count
            # it on the instance and log, so a tightness loss at scale
            # is visible in telemetry rather than inferred from bounds.
            self._flow_big_declines = getattr(
                self, "_flow_big_declines", 0
            ) + 1
            _log.debug(
                "leader-cap flow bound declined: BIG=%d exceeds int32 "
                "arc-cost range (falling back to the LP tier)", big,
            )
            return None
        n = rows.size
        o_pool = 1 + nP
        o_b = o_pool + 1
        t = o_b + B
        rest = int(p_active) - nP  # partitions with no gainful arc
        if rest < 0:
            return None  # inconsistent inputs; let the LP decide
        src = np.concatenate([
            np.zeros(nP, np.int64),          # s -> p
            1 + pidx,                        # p -> broker (gain arcs)
            1 + np.arange(nP),               # p -> pool (zero-gain)
            np.zeros(1, np.int64),           # s -> pool (gainless parts)
            np.full(B, o_pool, np.int64),    # pool -> broker
            o_b + np.arange(B),              # broker -> t (floor, -BIG)
            o_b + np.arange(B),              # broker -> t (residual)
        ])
        dst = np.concatenate([
            1 + np.arange(nP),
            o_b + b_of,
            np.full(nP, o_pool, np.int64),
            np.full(1, o_pool, np.int64),
            o_b + np.arange(B),
            np.full(B, t, np.int64),
            np.full(B, t, np.int64),
        ])
        cap = np.concatenate([
            np.ones(nP, np.int64),
            np.ones(n, np.int64),
            np.ones(nP, np.int64),
            np.full(1, rest, np.int64),
            np.full(B, int(p_active), np.int64),
            np.full(B, lo_b, np.int64),
            np.full(B, hi_b - lo_b, np.int64),
        ])
        cost = np.concatenate([
            np.zeros(nP, np.int64),
            -g_int,
            np.zeros(nP, np.int64),
            np.zeros(1, np.int64),
            np.zeros(B, np.int64),
            np.full(B, -big, np.int64),
            np.zeros(B, np.int64),
        ])
        try:
            f, c, af = mcmf(src, dst, cap, cost, 0, t, t + 1)
        except Exception:
            return None
        if f != int(p_active):
            return None  # band-infeasible or degenerate: LP decides
        floor_arcs = af[nP + n + nP + 1 + B:nP + n + nP + 1 + 2 * B]
        filled = int(floor_arcs.sum())
        if filled != B * lo_b:
            return None  # a floor went unmet: LP decides
        return base + int(-(c + big * filled))

    def _leader_cap_lp(self, with_lower: bool = False,
                       flow_only: bool = False) -> int | None:
        """max_weight with the per-broker leadership constraints modeled
        exactly. Each partition either hands leadership to a member m
        (gain = val[p,m] - s_rm1 over the non-member-leader optimum) or
        to some zero-gain leader; each broker accepts at most
        ``leader_hi`` — a transportation LP (integral).

        ``with_lower`` additionally introduces per-broker slack
        variables y_b counting the zero-gain leads, the band's LOWER
        side, and the total-leads equality. The lower band matters for
        leader-skew rebalances: under-leading brokers are FORCED to
        take leaderships away from gainful keeps, a loss the cap-only
        model cannot see — but the slack formulation solves ~3x slower,
        so it is a separate, lazier bound level.

        ``flow_only`` skips the scipy-LP fallback when the native flow
        fast path declines — for instances past the aggregation
        threshold, where the LP would grind for minutes but the flow
        stays sub-second at any size."""
        r = self._leader_vals()
        if r is None:
            return 0
        val, s_rm1, ids = r
        active = self.rf > 0
        p_active = int(active.sum())
        base = int(s_rm1[active].sum())
        gain = np.where(
            (ids >= 0) & active[:, None],
            np.maximum(val - s_rm1[:, None], 0), 0,
        )
        rows, cols = np.nonzero(gain > 0)
        if rows.size == 0:
            return base
        if self.leader_hi <= 0:
            return base
        if not with_lower:
            # the cap-only model is a pure transportation problem:
            # source -> partition (cap 1) -> gainful member's broker
            # (cost -gain) -> sink (cap leader_hi), plus a zero-cost
            # partition -> sink disposal arc so the forced max flow
            # never routes a positive-cost path. Integer flows solve
            # the SAME integral polytope the LP does, on the native
            # min-cost-flow kernel — 5.3 s of HiGHS IPM -> ~0.3 s at
            # the 50k-partition adv50k size (measured r4), and this
            # bound sits on the certificate critical path of every
            # annealed solve. The LP below stays as the fallback.
            b = self._leader_cap_flow(gain, rows, cols, ids, base)
            if b is not None:
                return b
        else:
            # the slack formulation is a network too (pool node +
            # floor-priority arcs); same exactness argument, ~25x the
            # LP's speed at 50k partitions
            b = self._leader_cap_flow_lower(
                gain, rows, cols, ids, base, p_active
            )
            if b is not None:
                return b
        if flow_only:
            return None  # caller ruled the scipy LP out at this size
        try:
            import scipy.sparse as sp
            from scipy.optimize import linprog

            B = self.num_brokers
            g = gain[rows, cols].astype(np.float64)
            b_of = ids[rows, cols]
            n = rows.size
            var = np.arange(n)
            opts = self._lp_options()
            if opts is None:  # bounds deadline already spent
                return None
            per_part = sp.csr_matrix(  # one leading member each
                (np.ones(n), (rows, var)), shape=(self.num_parts, n)
            )
            cap = sp.csr_matrix((np.ones(n), (b_of, var)), shape=(B, n))
            if not with_lower:
                c = -g
                a_ub = sp.vstack([per_part, cap], format="csr")
                b_ub = np.concatenate(
                    [np.ones(self.num_parts),
                     np.full(B, float(self.leader_hi))]
                )
                a_eq, b_eq = None, None
                lo, hi = np.zeros(n), np.ones(n)
                res = linprog(
                    c, A_ub=a_ub, b_ub=b_ub, bounds=(0, 1),
                    method="highs-ipm", options=opts,
                )
            else:
                # columns: x (gainful member leads) then y (per-broker
                # zero-gain lead slack)
                led_of_b = sp.hstack(
                    [cap, sp.eye(B, format="csr")], format="csr"
                )
                a_ub = sp.vstack(
                    [
                        sp.hstack(
                            [per_part,
                             sp.csr_matrix((self.num_parts, B))],
                            format="csr",
                        ),
                        led_of_b,        # <= leader_hi
                        -led_of_b,       # >= leader_lo
                    ],
                    format="csr",
                )
                b_ub = np.concatenate(
                    [
                        np.ones(self.num_parts),
                        np.full(B, float(self.leader_hi)),
                        np.full(B, -float(self.leader_lo)),
                    ]
                )
                c = -np.concatenate([g, np.zeros(B)])
                # every live partition has exactly one leader
                a_eq = sp.csr_matrix(np.ones((1, n + B)))
                b_eq = np.array([float(p_active)])
                lo = np.zeros(n + B)
                hi = np.concatenate(
                    [np.ones(n), np.full(B, float(p_active))]
                )
                res = linprog(
                    c, A_ub=a_ub, b_ub=b_ub, A_eq=a_eq, b_eq=b_eq,
                    bounds=[(0, 1)] * n + [(0, float(p_active))] * B,
                    method="highs-ipm", options=opts,
                )
            if not res.success:
                return None
            # certificate-critical: the repaired dual bound is valid
            # regardless of primal tolerance, so when marginals exist it
            # is the ONLY sound choice — a loose repair weakens the
            # verdict, never the soundness. The max with the primal
            # value guards fp noise in the repair arithmetic (a feasible
            # iterate's value never exceeds the true optimum, so the max
            # is still an upper bound). Primal fallback only when the
            # solve carried no marginals at all.
            ub = _dual_repair_max_ub(c, a_ub, b_ub, a_eq, b_eq, lo, hi, res)
            if ub is None:
                return base + _safe_floor_ub(res.fun)
            return base + _safe_floor_ub(-max(ub, -res.fun))
        except Exception:
            return None

    def _kept_weight_lp(self, return_solution: bool = False):
        """Level-2 bound: max preservation weight of kept slots under
        ALL band families jointly, BOTH sides (see
        ``weight_upper_bound``). Variables: x_{p,b} (member kept as
        follower, weight w_follower) / y_{p,b} (member kept as leader,
        weight w_leader) per current eligible member, plus zero-weight
        slacks u_b (partitions broker b leads through a non-kept
        leader) and z_b (new, non-kept replicas broker b hosts):

            x + y <= 1                    per member (one role)
            sum_b y <= 1                  per partition (C5)
            sum_b (x+y) <= rf_p           per partition (C4)
            sum_{b in k} (x+y) <= part_rack_hi_p   per (p, rack) (C10)
            leader_lo <= sum_p y->b + u_b <= leader_hi   per broker (C7)
            broker_lo <= sum (x+y)->b + z_b <= broker_hi per broker (C6)
            rack_lo_k <= sum_{b in k} [(x+y)->b + z_b] <= rack_hi_k (C9)
            sum y + sum u = #live partitions       (one leader each)
            sum (x+y) + sum z = total_replicas     (every slot filled)

        Every feasible plan maps into this region (kept roles -> x/y,
        its remaining leads/replicas -> u/z), so the optimum is a valid
        upper bound; the slacks let the LOWER bands and totals bind —
        an under-leading broker must absorb leaderships and a
        below-floor broker/rack must absorb new replicas, losses the
        cap-only levels cannot see."""
        try:
            import scipy.sparse as sp
            from scipy.optimize import linprog
        except Exception:
            return None
        mrows, mcols = self._members()
        n = mrows.size
        if n == 0:
            return None if return_solution else 0
        # deadline check BEFORE model build: assembling the sparse
        # matrices costs seconds at 10k partitions (and holds the serve
        # solve lock) — an expired budget must not pay it
        opts = self._lp_options()
        if opts is None:
            return None
        try:
            B, K, P = self.num_brokers, self.num_racks, self.num_parts
            rack = self.rack_of_broker[mcols]
            var = np.arange(n)
            one = np.ones(n)
            pair_key = mrows.astype(np.int64) * K + rack
            pairs, pair_idx = np.unique(pair_key, return_inverse=True)
            p_active = int((self.rf > 0).sum())
            r_total = float(self.total_replicas)
            # column layout: x (kept follower) 0..n-1 | y (kept leader)
            # n..2n-1 | u (non-kept lead per broker) 2n..2n+B-1 | z (new
            # replica per broker) 2n+B..2n+2B-1. The slack columns let
            # the LOWER bands and the totals bind: an under-leading
            # broker must take leads (losing 4->2 keeps elsewhere), new
            # replicas forced by broker/rack floors consume cap the
            # kept slots then cannot use.
            ncols = 2 * n + 2 * B
            u_off, z_off = 2 * n, 2 * n + B

            def block(r, c, shape0):
                return sp.csr_matrix(
                    (np.ones(len(c)), (r, c)), shape=(shape0, ncols)
                )

            def both(r, shape0):  # rows over x+y
                return block(
                    np.concatenate([r, r]),
                    np.concatenate([var, var + n]),
                    shape0,
                )

            def y_only(r, shape0):
                return block(r, var + n, shape0)

            b_idx = np.arange(B)
            lead_of_b = y_only(mcols, B) + block(
                b_idx, u_off + b_idx, B
            )
            repl_of_b = both(mcols, B) + block(b_idx, z_off + b_idx, B)
            rack_rows = both(rack, K) + block(
                self.rack_of_broker[:B], z_off + b_idx, K
            )
            a_ub = sp.vstack(
                [
                    both(var, n),          # x + y <= 1 per member
                    y_only(mrows, P),      # one kept leader per part
                    both(mrows, P),        # <= rf per part
                    both(pair_idx, pairs.size),  # diversity per (p,k)
                    lead_of_b,             # <= leader_hi per broker
                    -lead_of_b,            # >= leader_lo per broker
                    repl_of_b,             # <= broker_hi per broker
                    -repl_of_b,            # >= broker_lo per broker
                    rack_rows,             # <= rack_hi per rack
                    -rack_rows,            # >= rack_lo per rack
                ],
                format="csr",
            )
            b_ub = np.concatenate(
                [
                    np.ones(n),
                    np.ones(P),
                    self.rf.astype(np.float64),
                    self.part_rack_hi[(pairs // K)].astype(np.float64),
                    np.full(B, float(self.leader_hi)),
                    np.full(B, -float(self.leader_lo)),
                    np.full(B, float(self.broker_hi)),
                    np.full(B, -float(self.broker_lo)),
                    self.rack_hi.astype(np.float64),
                    -self.rack_lo.astype(np.float64),
                ]
            )
            # totals: every live partition has one leader; every valid
            # slot is kept or new
            a_eq = sp.vstack(
                [
                    block(
                        np.zeros(n + B, np.int64),
                        np.concatenate([var + n, u_off + b_idx]),
                        1,
                    ),
                    block(
                        np.zeros(2 * n + B, np.int64),
                        np.concatenate([var, var + n, z_off + b_idx]),
                        1,
                    ),
                ],
                format="csr",
            )
            b_eq = np.array([float(p_active), r_total])
            wl = self.w_leader[:, :B][mrows, mcols].astype(np.float64)
            wf = np.maximum(
                self.w_follower[:, :B][mrows, mcols], 0
            ).astype(np.float64)
            bounds = (
                [(0, 1)] * (2 * n)
                + [(0, float(p_active))] * B
                + [(0, r_total)] * B
            )
            if return_solution:
                # one composite solve: weight lexicographically above
                # the kept-slot count (kept < n+1, so the scaled weight
                # term dominates) — among weight-optimal vertices, pick
                # a move-minimal one for the constructor to decode. The
                # decoded plan's weight/moves are recomputed from the
                # ROUNDED integers, so composite-objective fp noise
                # cannot leak into any certificate.
                scale = float(n + 1)
                c = -np.concatenate(
                    [scale * wf + 1, scale * wl + 1, np.zeros(2 * B)]
                )
            else:
                c = -np.concatenate([wf, wl, np.zeros(2 * B)])
            res = linprog(
                c,
                A_ub=a_ub, b_ub=b_ub, A_eq=a_eq, b_eq=b_eq,
                bounds=bounds, method="highs",
                options=opts,
            )
            if not res.success:
                return None
            if return_solution:
                sol = res.x
                return {
                    "x": sol[:n],
                    "y": sol[n:2 * n],
                    "z": sol[z_off:z_off + B],
                    "mrows": mrows,
                    "mcols": mcols,
                }
            # certificate-critical: when marginals exist the repaired
            # dual bound is the only sound choice (see _leader_cap_lp);
            # max with the primal value guards repair fp noise
            lo = np.array([b[0] for b in bounds], dtype=np.float64)
            hi = np.array([b[1] for b in bounds], dtype=np.float64)
            ub = _dual_repair_max_ub(c, a_ub, b_ub, a_eq, b_eq, lo, hi, res)
            if ub is None:
                return _safe_floor_ub(res.fun)
            return _safe_floor_ub(-max(ub, -res.fun))
        except Exception:
            return None

    def _member_classes(self):
        """Partition-symmetry classes for the aggregated kept-weight
        bound: partitions are interchangeable in the level-2 LP when
        they share (rf, part_rack_hi, sorted member (broker, w_leader,
        w_follower) triples). Generated clusters — and real round-robin
        Kafka clusters — have FAR fewer classes than partitions (the
        50k-partition jumbo instance has 543), which is what makes the
        level-2 bound affordable at any size.

        Returns (cls_parts, cls_rf, cls_prh, cm_cls, cm_broker, cm_wl,
        cm_wf): per-class partition lists and rf/prh, plus flattened
        class-member arrays. Memoized."""
        cached = getattr(self, "_member_classes_memo", None)
        if cached is not None:
            return cached

        mrows, mcols = self._members()
        wl = self.w_leader[mrows, mcols].astype(np.int64)
        wf = np.maximum(self.w_follower[mrows, mcols], 0).astype(np.int64)
        P = self.num_parts
        # vectorized grouping: encode each member as one int64, lay the
        # per-partition sorted member lists into a padded signature
        # matrix [P, 2 + maxM], and let np.unique(axis=0) find the
        # classes — the Python-dict version costs ~0.6 s at jumbo
        # scale, squarely on the constructor's critical path
        if (
            0 <= wl.min(initial=0)
            and wl.max(initial=0) < (1 << 12)
            and wf.max(initial=0) < (1 << 12)
            and self.num_brokers < (1 << 24)
        ):
            enc = (mcols.astype(np.int64) << 24) | (wl << 12) | wf
            cnt = np.bincount(mrows, minlength=P)
            starts = np.concatenate([[0], np.cumsum(cnt)[:-1]])
            order = np.lexsort((enc, mrows))
            r_s, e_s = mrows[order], enc[order]
            pos = np.arange(r_s.size) - starts[r_s]
            maxm = int(cnt.max(initial=0))
            sig = np.full((P, 2 + maxm), -1, np.int64)
            sig[:, 0] = self.rf
            sig[:, 1] = self.part_rack_hi
            sig[r_s, 2 + pos] = e_s
            uniq, inv = np.unique(sig, axis=0, return_inverse=True)
            by_cls = np.argsort(inv, kind="stable")
            splits = np.cumsum(np.bincount(inv))[:-1]
            cls_parts = [p.tolist() for p in np.split(by_cls, splits)]
            cls_rf = uniq[:, 0].copy()
            cls_prh = uniq[:, 1].copy()
            mem = uniq[:, 2:]
            ci, mj = np.nonzero(mem != -1)
            me = mem[ci, mj]
            out = (
                cls_parts,
                cls_rf,
                cls_prh,
                ci.astype(np.int64),
                (me >> 24).astype(np.int64),
                ((me >> 12) & 0xFFF).astype(np.int64),
                (me & 0xFFF).astype(np.int64),
            )
            self._member_classes_memo = out
            return out

        # fallback for out-of-range weights/broker ids (never hit by
        # the README tier rule, which caps weights at 4)
        import collections

        per = collections.defaultdict(list)
        for r, c, a, b in zip(mrows.tolist(), mcols.tolist(),
                              wl.tolist(), wf.tolist()):
            per[r].append((c, a, b))
        groups: dict = collections.defaultdict(list)
        rf_l = self.rf.tolist()
        prh_l = self.part_rack_hi.tolist()
        for p in range(self.num_parts):
            key = (rf_l[p], prh_l[p], tuple(sorted(per[p])))
            groups[key].append(p)
        cls_parts, cls_rf, cls_prh = [], [], []
        cm_cls, cm_broker, cm_wl, cm_wf = [], [], [], []
        for ci, (key, parts) in enumerate(groups.items()):
            rff, prh, members = key
            cls_parts.append(parts)
            cls_rf.append(rff)
            cls_prh.append(prh)
            for (b, a, f) in members:
                cm_cls.append(ci)
                cm_broker.append(b)
                cm_wl.append(a)
                cm_wf.append(f)
        out = (
            cls_parts,
            np.array(cls_rf, np.int64),
            np.array(cls_prh, np.int64),
            np.array(cm_cls, np.int64),
            np.array(cm_broker, np.int64),
            np.array(cm_wl, np.int64),
            np.array(cm_wf, np.int64),
        )
        self._member_classes_memo = out
        return out

    def agg_effective(self) -> bool:
        """True when partition symmetry collapses the member space
        enough that the AGGREGATED kept-replica formulation (LP and
        MILP) is cheap — the gate for preferring it over the
        unaggregated LP in the bound ladder and for racing the
        aggregated plan constructor on any instance, not just the
        over-threshold ones. Steady-state round-robin clusters (the
        benchmark family, and real Kafka clusters after a balanced
        tool pass) collapse by 50-500x; adversarial distinct-weight
        clusters do not, and this returns False. The gate is a pure
        collapse RATIO (>= 8x) — no absolute floor — so small or
        asymmetric instances keep the annealer path (and its CI
        coverage) instead of degenerating into a host MILP solve."""
        members = self._members()[0].size
        if members == 0:
            return False
        n_cm = self._member_classes()[3].size
        return n_cm * 8 <= members

    def agg_construct_viable(self) -> bool:
        """True when the AGGREGATED kept-weight formulation would
        accept this instance rather than refuse: small enough to grind
        regardless (<= 20k members), or class collapse of at least 4x.
        ``_kept_weight_agg``'s refusal and the engine's constructor-race
        gate share this predicate so the two can never drift — past the
        unaggregated-LP size a refusal here means the constructor has
        NO viable path and racing it only delays the annealer."""
        members = self._members()[0].size
        if members <= 20_000:
            return True
        # n_cm <= members // 4 for integers — the refusal's complement
        return self._member_classes()[3].size * 4 <= members

    def _kept_weight_agg(self, integer: bool = False,
                         return_solution: bool = False):
        """The level-2 kept-weight bound on the SYMMETRY-AGGREGATED
        model — exactly the same polytope as ``_kept_weight_lp`` but
        with one variable per (class, member) instead of per
        (partition, member).

        Exactness: the LP optimum is invariant under aggregation —
        averaging any optimum over a class's partitions (they have
        identical members, weights, rf and caps) is feasible with the
        same objective, and symmetric solutions biject with the
        aggregated ones (every aggregated row is the sum of the
        partition rows it replaces). So this IS the level-2 LP bound,
        at ~#classes/#partitions of the cost — 0.5 s where the
        unaggregated LP times out at 900 s (50k-partition jumbo).

        ``integer=True`` solves the aggregated MILP instead: integer
        symmetrization is only into (every real plan maps to an integer
        aggregate; not every integer aggregate is realizable), so its
        optimum — or its dual bound under a time limit — is a still-
        valid, potentially TIGHTER upper bound than the LP (the
        ``weight_upper_bound`` level-3 tier).

        ``return_solution`` (with ``integer=True``) returns the raw
        aggregated solution for the plan constructor
        (``solvers.lp_round``): a dict with per-class-member kept
        counts X/Y, per-broker new-replica quotas z and non-kept-leader
        quotas u, plus the class arrays to disaggregate with."""
        try:
            import scipy.sparse as sp
            from scipy.optimize import linprog
        except Exception:
            return None
        (cls_parts, cls_rf, cls_prh, cm_cls, cm_broker, cm_wl, cm_wf
         ) = self._member_classes()
        n_cm = cm_broker.size
        if n_cm == 0:
            return None if return_solution else 0
        # the formulation only pays off when symmetry actually shrinks
        # the problem: on clusters with near-distinct per-partition
        # weights (#classes ~ #partitions) this would be a full-size
        # MILP burning its whole time limit to restate the level-2
        # verdict — refuse instead of grinding (certify_optimal and the
        # serve audit run these tiers synchronously)
        if not self.agg_construct_viable():
            return None
        opts = self._lp_options()
        if opts is None:  # bounds deadline already spent
            return None
        try:
            B, K = self.num_brokers, self.num_racks
            C = len(cls_parts)
            cls_n = np.array([len(p) for p in cls_parts], np.float64)
            cm_n = cls_n[cm_cls]
            rack = self.rack_of_broker[cm_broker]
            p_active = float((self.rf > 0).sum())
            r_total = float(self.total_replicas)
            ncols = 2 * n_cm + 2 * B
            u_off, z_off = 2 * n_cm, 2 * n_cm + B
            var = np.arange(n_cm)

            def block(r, c, nrows):
                return sp.csr_matrix(
                    (np.ones(len(c)), (r, c)), shape=(nrows, ncols)
                )

            def both(r, nrows):
                return block(
                    np.concatenate([r, r]),
                    np.concatenate([var, var + n_cm]),
                    nrows,
                )

            b_idx = np.arange(B)
            pk = cm_cls * K + rack
            pairs, pair_idx = np.unique(pk, return_inverse=True)
            lead_b = block(cm_broker, var + n_cm, B) + block(
                b_idx, u_off + b_idx, B
            )
            repl_b = both(cm_broker, B) + block(b_idx, z_off + b_idx, B)
            rack_rows = both(rack, K) + block(
                self.rack_of_broker[:B], z_off + b_idx, K
            )
            # u_b <= z_b: a lead through a non-kept leader sits on one
            # of that broker's NEW replicas (valid for every real plan;
            # tightens the aggregate against phantom leaderships)
            uz = sp.csr_matrix(
                (np.concatenate([np.ones(B), -np.ones(B)]),
                 (np.concatenate([b_idx, b_idx]),
                  np.concatenate([u_off + b_idx, z_off + b_idx]))),
                shape=(B, ncols),
            )
            a_ub = sp.vstack(
                [
                    both(var, n_cm),              # X+Y <= n_c per member
                    block(cm_cls, var + n_cm, C),  # sum Y <= n_c
                    both(cm_cls, C),              # sum(X+Y) <= n_c rf
                    both(pair_idx, pairs.size),   # diversity pairs
                    block(cm_cls, var, C),        # sum X <= n_c (rf-1):
                    # a fully-kept partition keeps its leader, so kept
                    # FOLLOWERS never exceed rf-1
                    lead_b, -lead_b,
                    repl_b, -repl_b,
                    rack_rows, -rack_rows,
                    uz,
                ],
                format="csr",
            )
            b_ub = np.concatenate(
                [
                    cm_n,
                    cls_n,
                    cls_n * cls_rf,
                    (cls_n * cls_prh)[(pairs // K)],
                    cls_n * np.maximum(cls_rf - 1, 0),
                    np.full(B, float(self.leader_hi)),
                    np.full(B, -float(self.leader_lo)),
                    np.full(B, float(self.broker_hi)),
                    np.full(B, -float(self.broker_lo)),
                    self.rack_hi.astype(np.float64),
                    -self.rack_lo.astype(np.float64),
                    np.zeros(B),
                ]
            )
            a_eq = sp.vstack(
                [
                    block(
                        np.zeros(n_cm + B, np.int64),
                        np.concatenate([var + n_cm, u_off + b_idx]),
                        1,
                    ),
                    block(
                        np.zeros(2 * n_cm + B, np.int64),
                        np.concatenate(
                            [var, var + n_cm, z_off + b_idx]
                        ),
                        1,
                    ),
                ],
                format="csr",
            )
            b_eq = np.array([p_active, r_total])
            if return_solution:
                # lexicographic: weight dominant, kept count tie-break
                scale = float(self.total_replicas + 1)
                c = -np.concatenate(
                    [scale * cm_wf + 1, scale * cm_wl + 1,
                     np.zeros(2 * B)]
                )
            else:
                c = -np.concatenate(
                    [cm_wf.astype(np.float64), cm_wl.astype(np.float64),
                     np.zeros(2 * B)]
                )
            lo = np.zeros(ncols)
            hi = np.concatenate(
                [cm_n, cm_n, np.full(B, p_active), np.full(B, r_total)]
            )
            if integer:
                from scipy.optimize import (
                    Bounds, LinearConstraint, milp,
                )

                res = milp(
                    c,
                    constraints=[
                        LinearConstraint(a_ub, -np.inf, b_ub),
                        LinearConstraint(a_eq, b_eq, b_eq),
                    ],
                    bounds=Bounds(lo, hi),
                    integrality=np.ones(ncols),
                    options={"time_limit": opts["time_limit"],
                             "mip_rel_gap": 0.0},
                )
                if return_solution:
                    # scipy.milp: success is True ONLY at proven
                    # optimality (status 0) — a time-limit incumbent
                    # reports success=False — so everything below,
                    # including the recorded weight bound, rests on a
                    # solved-to-optimality aggregate
                    if not res.success or res.x is None:
                        return None
                    sol = np.rint(res.x)
                    if np.abs(res.x - sol).max(initial=0) > 1e-6:
                        return None
                    # the pure-weight part of the lexicographic optimum
                    # is a valid upper bound on ANY feasible plan's
                    # weight: scale > every kept count, so a plan with
                    # higher weight would map to an aggregate beating
                    # the composite optimum. Recording it lets
                    # certify_optimal skip the bound-ladder LPs for
                    # constructor-built plans.
                    xs = sol[:n_cm]
                    ys = sol[n_cm:2 * n_cm]
                    self._agg_weight_ub = int(
                        (cm_wf * xs).sum() + (cm_wl * ys).sum()
                    )
                    return {
                        "X": sol[:n_cm].astype(np.int64),
                        "Y": sol[n_cm:2 * n_cm].astype(np.int64),
                        "u": sol[u_off:u_off + B].astype(np.int64),
                        "z": sol[z_off:z_off + B].astype(np.int64),
                        "cls_parts": cls_parts,
                        "cls_rf": cls_rf,
                        "cls_prh": cls_prh,
                        "cm_cls": cm_cls,
                        "cm_broker": cm_broker,
                        "cm_wl": cm_wl,
                        "cm_wf": cm_wf,
                    }
                # branch-and-bound dual bound: valid even on timeout
                db = getattr(res, "mip_dual_bound", None)
                if db is None or not np.isfinite(db):
                    return None
                return _safe_floor_ub(db)
            res = linprog(
                c, A_ub=a_ub, b_ub=b_ub, A_eq=a_eq, b_eq=b_eq,
                bounds=np.stack([lo, hi], axis=1), method="highs",
                options=opts,
            )
            if not res.success:
                return None
            ub = _dual_repair_max_ub(c, a_ub, b_ub, a_eq, b_eq, lo, hi,
                                     res)
            if ub is None:
                return _safe_floor_ub(res.fun)
            return _safe_floor_ub(-max(ub, -res.fun))
        except Exception:
            return None

    def best_leader_assignment(self, a: np.ndarray) -> np.ndarray:
        """Exact optimal leader choice for FIXED replica sets: permute
        each partition's slots so the leader (slot 0) maximizes the total
        preservation weight subject to the per-broker leader band.

        With replica sets fixed, total weight = const + sum_p
        (w_lead - w_foll)[p, leader_p], one leader per partition, each
        broker leading within [leader_lo, leader_hi] — a transportation
        problem (integral polytope). Closes the gap one-swap-at-a-time
        local search cannot: chains of leader reseats through near-cap
        brokers (the reference's "preferred leader has more weight"
        objective, ``/root/reference/README.md:131-133``, optimized
        exactly). The other constraint families only see replica sets,
        so feasibility is untouched. Returns ``a`` unchanged on any
        failure.

        Solved by incremental negative-cycle canceling on the broker
        lead-move graph (``_reseat_cycle_cancel``) — the engine hands
        this an annealed candidate whose leadership is already
        near-optimal, so a handful of O(B^3) Bellman-Ford passes beat
        re-solving the 150k-variable transportation LP from scratch by
        ~2 orders of magnitude (58 s -> <1 s at the 50k-partition
        adv50k scale, measured r4). Out-of-band leadership counts are
        repaired first by cheapest lead-shift paths (same arc
        machinery), so constructed plans and scrambled inputs stay on
        the fast path too; the HiGHS LP remains as the exact fallback
        for the rare inputs the canceller still declines (repair
        budget or iteration cap tripped)."""
        a = np.asarray(a)
        P, R = a.shape
        if P == 0 or R == 0:
            return a
        try:
            out = self._reseat_cycle_cancel(a)
            if out is None:
                out = self._best_leader_lp(a)
            if out is None:
                return a
            # exactness guard against round-off / edge cases in either
            # path: keep the better plan under (fewest violations, then
            # weight). A feasible input can only improve; an
            # infeasible-leadership input is legitimately repaired at a
            # weight cost.
            def rank(z):
                return (
                    -sum(self.violations(z).values()),
                    self.preservation_weight(z),
                )

            return out if rank(out) >= rank(a) else a
        except Exception:
            # the documented contract: a malformed input degrades to
            # "no reseat", never to a crashed solve
            return a

    def _best_leader_lp(self, a: np.ndarray) -> np.ndarray | None:
        """Transportation-LP formulation of the exact leader reseat
        (see ``best_leader_assignment``), solved with HiGHS via scipy.
        Returns the reseated plan or None on solver failure."""
        P, R = a.shape
        B = self.num_brokers
        valid = self.slot_valid
        try:
            import scipy.sparse as sp
            from scipy.optimize import linprog

            prow = np.arange(P)[:, None]
            gain = np.where(
                valid,
                self.w_leader[prow, a] - self.w_follower[prow, a],
                0,
            ).astype(np.float64)
            rows, cols = np.nonzero(valid & (self.rf[:, None] > 0))
            n = rows.size
            if n == 0:
                return a
            g = gain[rows, cols]
            b_of = a[rows, cols]
            var = np.arange(n)
            a_eq = sp.csr_matrix(  # exactly one leader per partition
                (np.ones(n), (rows, var)),
                shape=(P, n),
            )
            keep = self.rf > 0
            a_eq = a_eq[keep]
            lead_of_b = sp.csr_matrix(
                (np.ones(n), (b_of, var)), shape=(B, n)
            )
            res = linprog(
                -g,
                A_eq=a_eq,
                b_eq=np.ones(int(keep.sum())),
                A_ub=sp.vstack([lead_of_b, -lead_of_b], format="csr"),
                b_ub=np.concatenate(
                    [
                        np.full(B, float(self.leader_hi)),
                        np.full(B, -float(self.leader_lo)),
                    ]
                ),
                bounds=(0, 1),
                # measured at 150k slots (r4): HiGHS simplex 58 s, IPM
                # (with its default crossover to a basic solution,
                # which the argmax decode below needs) 3.3 s
                method="highs-ipm",
            )
            if not res.success:
                return None
            x = np.zeros((P, R))
            x[rows, cols] = res.x
            chosen = np.argmax(x, axis=1)  # integral LP: one ~1.0 per row
            out = a.copy()
            rng = np.arange(P)
            lead = out[rng, chosen]
            out[rng, chosen] = out[:, 0]
            out[:, 0] = np.where(keep, lead, out[:, 0])
            return out
        except Exception:
            return None

    def _reseat_cycle_cancel(self, a: np.ndarray) -> np.ndarray | None:
        """Exact leader reseat by negative-cycle canceling (the fast
        path of ``best_leader_assignment``).

        View a leader arrangement as a flow on the broker lead-move
        graph: reseating partition p from its current leader (broker
        ``b = a[p, 0]``) to the member in slot s (broker
        ``c = a[p, s]``) is an arc b -> c with integer cost
        ``gain(p, 0) - gain(p, s)`` where ``gain = w_lead - w_foll`` of
        the occupying broker; it shifts one lead from b to c. Any two
        band-feasible arrangements of the same replica sets differ by a
        set of broker-space cycles (lead counts unchanged) plus paths
        (endpoints shift by one, still inside the band) — so an
        arrangement with no negative cycle in the dense min-cost arc
        matrix (paths modeled via a virtual node with zero-cost arcs to
        brokers that can shed a lead and from brokers that can absorb
        one) is globally optimal: the standard min-cost-flow optimality
        argument on an integral transportation polytope.

        Each Bellman-Ford pass is a vectorized [B+1, B+1] min-plus
        sweep; every applied cycle raises the exact integer objective
        by >= 1, so termination is bounded by the optimality gap of the
        input — a handful of iterations for the near-optimal candidates
        the engine feeds here, independent of partition count (the only
        O(P) work per iteration is rebuilding the arc mins).

        Returns the optimal reseat, or None to decline: the band-repair
        budget or iteration cap tripped (guards, not budgets — neither
        has been observed on engine-fed candidates)."""
        P, R = a.shape
        B = self.num_brokers
        valid = self.slot_valid
        keep = self.rf > 0
        if (keep & (a[:, 0] >= B)).any():
            return None  # live partition with no in-range leader
        lcnt = np.bincount(a[keep, 0], minlength=B)[:B]
        prow = np.arange(P)[:, None]
        # candidate arcs: (p, s>=1) valid follower slots of live
        # partitions; arc out[p,0] -> out[p,s] at cost
        # gain[p,0]-gain[p,s] (gain = lead-over-follow weight of the
        # occupying broker; slot-keyed, so recomputed after each
        # applied cycle's swaps)
        arc_mask = valid.copy()
        arc_mask[:, 0] = False
        arc_mask &= keep[:, None] & (a < B)
        p_arc, s_arc = np.nonzero(arc_mask)
        in_band = (
            (lcnt >= self.leader_lo).all()
            and (lcnt <= self.leader_hi).all()
        )
        if p_arc.size == 0:
            # no alternative leaders anywhere: a is optimal as-is when
            # in band (the LP could not change anything either — its
            # only choice is which valid slot leads); out of band it is
            # unrepairable by lead permutation
            return a.copy() if in_band else None
        out = a.copy()
        INF = np.int64(1) << 40
        N = B + 1  # + virtual node for band-shifting paths

        def arc_views():
            """(gain, b_from, b_to, cost) over the CURRENT ``out``.
            The single definition both phases share: the witness
            lookup below matches on ``cost == C[b, c]``, which is only
            sound while every consumer computes costs identically."""
            gain = np.where(
                valid & (out < B),
                self.w_leader[prow, out] - self.w_follower[prow, out],
                0,
            ).astype(np.int64)
            return (
                gain,
                out[p_arc, 0],
                out[p_arc, s_arc],
                gain[p_arc, 0] - gain[p_arc, s_arc],
            )

        def refresh_row(p, gain, b_from, b_to, cost):
            """Fold one partition's swap into the arc views in
            O(R + arcs_of_p) — a full rebuild per applied edge is
            O(P*R) and turns the repair of a scrambled 50k-partition
            input into seconds of dead numpy."""
            row = out[p]
            gain[p] = np.where(
                valid[p] & (row < B),
                self.w_leader[p, row] - self.w_follower[p, row],
                0,
            )
            lo_i = np.searchsorted(p_arc, p)
            hi_i = np.searchsorted(p_arc, p + 1)
            b_from[lo_i:hi_i] = row[0]
            b_to[lo_i:hi_i] = row[s_arc[lo_i:hi_i]]
            cost[lo_i:hi_i] = gain[p, 0] - gain[p, s_arc[lo_i:hi_i]]

        if not in_band:
            # --- band-repair phase (r4): out-of-band inputs used to
            # decline to the transportation LP (seconds at 50k
            # partitions). Each repair unit shifts one lead along the
            # cheapest broker path from a shed source to an absorbing
            # sink, reducing total band violation by exactly one; a
            # path always exists while violations remain, because the
            # difference to ANY band-feasible arrangement of the same
            # replica sets decomposes into lead-shift paths whose arcs
            # are all present in the current arrangement. Optimality
            # is NOT needed here — the cycle-canceling phase below
            # restores it from any feasible point — so path costs are
            # shifted non-negative and searched with plain
            # Bellman-Ford (the raw arc matrix can hold negative
            # cycles before canceling).
            viol = int(
                np.maximum(lcnt - self.leader_hi, 0).sum()
                + np.maximum(self.leader_lo - lcnt, 0).sum()
            )
            if viol > 2 * N + 16:
                return None  # grossly out of band: let the LP repair
            gain = b_from = b_to = cost = None
            for _unit in range(viol):
                surplus = lcnt > self.leader_hi
                deficit = lcnt < self.leader_lo
                if not surplus.any() and not deficit.any():
                    break
                if gain is None:  # per-edge refreshes keep them current
                    gain, b_from, b_to, cost = arc_views()
                C = np.full((B, B), INF, dtype=np.int64)
                np.minimum.at(C, (b_from, b_to), cost)
                np.fill_diagonal(C, INF)
                finite = C < INF
                if not finite.any():
                    return None
                shift = max(0, -int(C[finite].min()))
                Cn = np.where(finite, C + shift, INF)
                if surplus.any():
                    src_mask = surplus
                    dst_mask = lcnt + 1 <= self.leader_hi
                else:
                    src_mask = lcnt - 1 >= self.leader_lo
                    dst_mask = deficit
                dist = np.where(src_mask, np.int64(0), INF)
                parent = np.full(B, -1, dtype=np.int64)
                for _sweep in range(B):
                    cand = dist[:, None] + Cn
                    nb = cand.argmin(axis=0)
                    nd = cand[nb, np.arange(B)]
                    better = nd < dist
                    if not better.any():
                        break
                    dist = np.where(better, nd, dist)
                    parent = np.where(better, nb, parent)
                sinks = np.flatnonzero(dst_mask & (dist < INF))
                if sinks.size == 0:
                    return None  # unreachable: decline, LP decides
                v = int(sinks[np.argmin(dist[sinks])])
                path = [v]
                while not src_mask[path[-1]]:
                    u = int(parent[path[-1]])
                    if u < 0 or len(path) > B:
                        return None
                    path.append(u)
                path.reverse()  # source ... sink
                for b, c in zip(path, path[1:]):
                    hit = np.flatnonzero(
                        (b_from == b) & (b_to == c) & (cost == C[b, c])
                    )
                    if hit.size == 0:
                        return None  # stale witness: decline
                    k = int(hit[0])
                    p, s = int(p_arc[k]), int(s_arc[k])
                    out[p, 0], out[p, s] = out[p, s], out[p, 0]
                    lcnt[b] -= 1
                    lcnt[c] += 1
                    # refresh the swapped row's arc views so the
                    # path's later edges see this swap (their
                    # witnesses stay valid: a shift INTO an
                    # intermediate broker never removes a partition
                    # from its led set)
                    refresh_row(p, gain, b_from, b_to, cost)
            if (lcnt < self.leader_lo).any() or (
                lcnt > self.leader_hi
            ).any():
                return None  # repair fell short: decline, LP decides
        for _ in range(256):  # cap >> any observed cycle count
            gain, b_from, b_to, cost = arc_views()
            C = np.full((N, N), INF, dtype=np.int64)
            np.minimum.at(C, (b_from, b_to), cost)
            np.fill_diagonal(C, INF)  # self-arcs are no-ops
            C[:B, B] = np.where(lcnt + 1 <= self.leader_hi, 0, INF)
            C[B, :B] = np.where(lcnt - 1 >= self.leader_lo, 0, INF)
            # all-source Bellman-Ford: dist starts at 0 everywhere, so
            # any relaxation still possible after N sweeps lies on a
            # negative cycle reachable through the parent chain. The
            # engine's candidates are near-optimal, so their cancel
            # cycles are SHORT — probe the parent chain of one improved
            # node every sweep and stop at the first revisit, instead
            # of paying all N min-plus sweeps per cycle (the difference
            # between ~25 ms and ~0.6 s per canceled cycle at B=511)
            dist = np.zeros(N, dtype=np.int64)
            parent = np.full(N, -1, dtype=np.int64)

            def cycle_edges(v):
                """Simple parent cycle through v (which must lie ON the
                cycle) as forward arcs, or None if the walk leaves the
                parent graph / exceeds N steps (v was not on a cycle
                after all) or the total cost is not negative —
                mid-flux (Jacobi) parent graphs can transiently hold
                non-improving cycles, which must not be applied."""
                cyc = [v]
                u = int(parent[v])
                while u != v:
                    if u < 0 or len(cyc) > N:
                        return None
                    cyc.append(u)
                    u = int(parent[u])
                cyc.reverse()  # parent chain is reversed arc order
                edges = list(zip(cyc, cyc[1:] + cyc[:1]))
                if sum(int(C[b, c]) for b, c in edges) >= 0:
                    return None
                return edges

            edges = None
            for _sweep in range(N):
                cand = dist[:, None] + C
                nb = cand.argmin(axis=0)
                nd = cand[nb, np.arange(N)]
                better = nd < dist
                if not better.any():
                    break
                dist = np.where(better, nd, dist)
                parent = np.where(better, nb, parent)
                u = int(np.flatnonzero(better)[0])
                seen = np.full(N, False)
                for _step in range(N + 1):
                    if u < 0:
                        break
                    if seen[u]:
                        edges = cycle_edges(u)
                        break
                    seen[u] = True
                    u = int(parent[u])
                if edges is not None:
                    break
            else:
                # N sweeps still improving: a negative cycle certainly
                # exists; walk N parents from an improving node to land
                # on one (guarding the walk — Jacobi parent chains can
                # terminate at a never-improved root)
                v = int(np.flatnonzero(better)[0])
                for _step in range(N):
                    nxt = int(parent[v])
                    if nxt < 0:
                        return None  # chain left the parent graph
                    v = nxt
                edges = cycle_edges(v)
                if edges is None:
                    return None  # non-negative parent cycle: LP decides
            if edges is None:
                break  # no negative cycle: optimal
            # apply: for each arc b -> c on the cycle (skipping the
            # virtual node), reseat one witness partition achieving the
            # arc's min cost. Cycle nodes are distinct brokers, so the
            # witnesses are distinct partitions (one current leader
            # broker each).
            applied = False
            for b, c in edges:
                if b == B or c == B:
                    continue  # virtual-node legs carry no reseat
                hit = np.flatnonzero(
                    (b_from == b) & (b_to == c) & (cost == C[b, c])
                )
                if hit.size == 0:
                    return None  # stale witness: decline, LP decides
                k = int(hit[0])
                p, s = int(p_arc[k]), int(s_arc[k])
                out[p, 0], out[p, s] = out[p, s], out[p, 0]
                lcnt[b] -= 1
                lcnt[c] += 1
                applied = True
            if not applied:
                break
        else:
            return None  # iteration cap: decline rather than loop
        return out

    def move_count(self, a: np.ndarray) -> int:
        """Replica moves vs the current assignment: count of valid slots
        whose broker is not in the partition's current (eligible) replica
        set. Membership test uses the weight matrices: every currently
        assigned eligible broker carries nonzero leader weight."""
        a = np.asarray(a)
        member = self.w_leader[np.arange(self.num_parts)[:, None], a] > 0
        return int((~member & self.slot_valid).sum())

    def move_lower_bound(self) -> int:
        """Provable lower bound on ``move_count`` over ALL feasible plans,
        from a counting relaxation of "how many slots can possibly be
        kept": a kept slot holds a current eligible member of its
        partition, each partition keeps at most min(rf, |members|) of them
        (at most ``part_rack_hi`` per rack), each broker hosts at most
        ``broker_hi`` total and appears in at most m_b = |{p : b member}|
        partitions, each rack holds at most ``rack_hi`` total. Every
        non-kept valid slot is one move, so

            moves >= total_replicas - min(A, B, C)

        with A/B/C the per-partition / per-broker / per-rack kept caps.
        Arrival counting gives two more bounds: a broker below
        ``broker_lo`` needs (lo - m_b) incoming moves, a rack below its
        ``rack_lo`` likewise. The max of all bounds is returned. It
        reproduces the hand-derived bounds of every benchmark scenario
        (``utils/gen.py``): decommission (slots on the removed broker),
        rf_change (new slots have no members), scale_out (empty brokers
        must absorb floor(R/B) each), leader_only (0)."""
        B, K = self.num_brokers, self.num_racks
        member = self.w_leader > 0  # [P, B+?]; columns past B are unused
        member = member[:, :B]
        m_b = member.sum(axis=0).astype(np.int64)  # [B]
        rack = self.rack_of_broker[:B]  # [B] rack index of each broker

        # A: per-partition kept cap, rack-diversity aware
        mem_rack = np.zeros((self.num_parts, K), dtype=np.int64)
        np.add.at(mem_rack.T, rack, member.T.astype(np.int64))
        per_part = np.minimum(mem_rack, self.part_rack_hi[:, None]).sum(1)
        a_cap = int(np.minimum(self.rf, per_part).sum())

        # B: per-broker kept cap;  C: per-rack kept cap
        capped_b = np.minimum(m_b, self.broker_hi)
        b_cap = int(capped_b.sum())
        per_rack = np.bincount(rack, weights=capped_b, minlength=K)[:K]
        c_cap = int(np.minimum(per_rack, self.rack_hi).sum())

        lb_kept = self.total_replicas - min(a_cap, b_cap, c_cap)
        # arrival bounds (each move lands exactly one replica somewhere)
        lb_broker_in = int(np.maximum(self.broker_lo - m_b, 0).sum())
        mk = np.bincount(rack, weights=m_b, minlength=K)[:K]
        lb_rack_in = int(np.maximum(self.rack_lo - mk, 0).sum())
        return max(lb_kept, lb_broker_in, lb_rack_in, 0)

    def caps_bind(self) -> bool:
        """True when balance bands bind against the CURRENT assignment —
        over-full or under-floor brokers for either replicas or
        leaderships. These are exactly the instances where (a) local
        search must trade keeps against bands and plateaus epsilon below
        the optimum, and (b) the LP-rounding constructor
        (``solvers.lp_round``) tends to produce a certified optimum
        outright: scale-outs, leader-skew rebalances, RF changes. A
        plain decommission triggers neither side."""
        B = self.num_brokers
        m_b = (self.w_leader[:, :B] > 0).sum(axis=0)
        lead = self.a0[:, 0]
        ok = (
            (self.rf > 0)
            & (lead >= 0)
            & (lead < B)
            & (self.w_leader[np.arange(self.num_parts),
                             np.clip(lead, 0, B - 1)] > 0)
        )
        lcnt = np.bincount(lead[ok], minlength=B)[:B]
        return bool(
            (m_b > self.broker_hi).any()
            or (m_b < self.broker_lo).any()
            or (lcnt > self.leader_hi).any()
            or (lcnt < self.leader_lo).any()
        )

    def certify_optimal(self, a: np.ndarray, allow_tight: bool = True
                        ) -> bool:
        """True iff ``a`` is PROVABLY a global optimum: feasible, its
        preservation weight meets the unconstrained upper bound
        (``max_weight``), and its move count meets ``move_lower_bound``.
        Search engines use this to stop early with ``optimal=True``; a
        False return proves nothing (the bounds may simply not be tight
        for this instance)."""
        if not self.is_feasible(a):
            return False
        mc = self.move_count(a)
        if mc > self.move_lower_bound() and (
            mc > self.move_lower_bound_exact()
        ):
            return False
        w = self.preservation_weight(a)
        # fast path: an aggregated-MILP optimum recorded by the plan
        # constructor is already a valid upper bound on every feasible
        # plan's weight (see _kept_weight_agg) — meeting it needs no LP
        agg_ub = getattr(self, "_agg_weight_ub", None)
        if agg_ub is not None and w >= agg_ub:
            return True
        if w >= self.weight_upper_bound(level=0):
            return True
        # the higher levels solve multi-second LPs at 10k partitions;
        # deadline-sensitive callers (the engine under time_limit_s)
        # disable the synchronous escalation
        if not allow_tight:
            return False
        return (
            w >= self.weight_upper_bound(level=1)
            or w >= self.weight_upper_bound(level=2)
            or w >= self.weight_upper_bound(level=3)
        )



def build_instance(
    current: Assignment,
    broker_list: Sequence[int],
    topology: Topology | None = None,
    target_rf: int | dict[str, int] | None = None,
) -> ProblemInstance:
    """Build the solver-neutral model from raw inputs (reference L0->L1-L3,
    ``README.md:46-63, 106-133``)."""
    broker_ids = np.array(sorted(set(int(b) for b in broker_list)), dtype=np.int32)
    B = len(broker_ids)
    if B == 0:
        raise ValueError("empty broker list")
    idx_of_broker = {int(b): i for i, b in enumerate(broker_ids)}

    if topology is None:
        topology = Topology.single_rack(broker_ids.tolist())
    rack_names = sorted({topology.rack(int(b)) for b in broker_ids})
    rack_idx = {r: i for i, r in enumerate(rack_names)}
    K = len(rack_names)
    rack_of_broker = np.full(B + 1, K, dtype=np.int32)
    for i, b in enumerate(broker_ids):
        rack_of_broker[i] = rack_idx[topology.rack(int(b))]

    parts = sorted(current.partitions, key=lambda p: (p.topic, p.partition))
    topics = []
    topic_idx: dict[str, int] = {}
    for p in parts:
        if p.topic not in topic_idx:
            topic_idx[p.topic] = len(topics)
            topics.append(p.topic)
    P = len(parts)

    if isinstance(target_rf, dict):
        # a typo'd topic would otherwise be silently ignored and the
        # operator would apply a plan believing RF was raised
        unknown = sorted(set(target_rf) - set(topic_idx))
        if unknown:
            raise ValueError(
                f"target_rf names unknown topic(s) {unknown}; "
                f"assignment has {sorted(topic_idx)}"
            )

    def rf_for(p: PartitionAssignment) -> int:
        if target_rf is None:
            return len(p.replicas)
        if isinstance(target_rf, dict):
            return int(target_rf.get(p.topic, len(p.replicas)))
        return int(target_rf)

    rf = np.array([rf_for(p) for p in parts], dtype=np.int32)
    if (rf <= 0).any():
        raise ValueError("replication factor must be >= 1")
    if (rf > B).any():
        raise ValueError("replication factor exceeds broker count")
    R = int(rf.max())

    topic_of_part = np.array([topic_idx[p.topic] for p in parts], dtype=np.int32)
    part_id = np.array([p.partition for p in parts], dtype=np.int32)

    # current assignment -> index space; ineligible brokers -> null bucket B
    a0 = np.full((P, R), B, dtype=np.int32)
    for pi, p in enumerate(parts):
        for s, b in enumerate(p.replicas[:R]):
            a0[pi, s] = idx_of_broker.get(int(b), B)

    # objective weights (README.md:116-133, 146): see module docstring
    w_leader = np.zeros((P, B + 1), dtype=np.int32)
    w_follower = np.zeros((P, B + 1), dtype=np.int32)
    for pi, p in enumerate(parts):
        for s, b in enumerate(p.replicas):
            bi = idx_of_broker.get(int(b))
            if bi is None:
                continue  # broker being removed: no preservation reward
            if s == 0:
                w_leader[pi, bi] = W_LEADER_KEEP
                w_follower[pi, bi] = W_LEADER_DEMOTE
            else:
                w_leader[pi, bi] = max(w_leader[pi, bi], W_FOLLOWER_PROMOTE)
                w_follower[pi, bi] = max(w_follower[pi, bi], W_FOLLOWER_KEEP)

    # bound arithmetic (README.md:158-180; SURVEY §2 rules)
    r_tot = int(rf.sum())
    broker_lo, broker_hi = r_tot // B, -(-r_tot // B)
    leader_lo, leader_hi = P // B, -(-P // B)
    rack_sizes = np.array(
        [int((rack_of_broker[:B] == k).sum()) for k in range(K)], dtype=np.int64
    )
    rack_lo = (r_tot * rack_sizes) // B
    rack_hi = -((-r_tot * rack_sizes) // B)
    part_rack_hi = -(-rf // K)

    # --- satisfiability repair (balance bands are preferences: they must
    # never make the instance infeasible). Equal-size racks satisfy every
    # condition below as-is and reproduce the reference sample's exact
    # bounds unchanged (README.md:173-176); lopsided topologies (found by
    # the r2 property fuzz: a 1-broker rack + diversity caps can make the
    # proportional ceilings under-supply r_tot, which the exact MILP
    # reports as infeasible) get the minimal relaxation that admits a
    # plan. Steps:
    #   1. per-partition: the diversity cap c_p must allow rf_p replicas
    #      across racks given each rack's broker count (uniqueness).
    #   2. per-rack: tighten the band to the true implied extremes
    #      [m_k, M_k] (no semantic change), then
    #   3. jointly: relax ceilings/floors until sum(hi) covers r_tot and
    #      sum(lo) <= r_tot.
    #   4. broker bands: every rack's brokers must supply its floor, and
    #      the global per-broker supply must cover r_tot under the rack
    #      ceilings.
    cap_pk = np.minimum(part_rack_hi[:, None], rack_sizes[None, :])
    short = rf - cap_pk.sum(1)
    while (short > 0).any():  # step 1 (terminates: B >= rf checked)
        part_rack_hi = part_rack_hi + (short > 0)
        cap_pk = np.minimum(part_rack_hi[:, None], rack_sizes[None, :])
        short = rf - cap_pk.sum(1)
    M = cap_pk.sum(0)  # [K] true max replicas rack k can hold
    m = np.maximum(  # [K] forced minimum (others at their caps)
        rf[:, None] - (cap_pk.sum(1)[:, None] - cap_pk), 0
    ).sum(0)
    rack_hi = np.maximum(np.minimum(rack_hi, M), m)  # step 2 (m <= M, so
    rack_lo = np.maximum(np.minimum(rack_lo, rack_hi), m)  # lo <= hi holds)
    # steps 3a/3b converge in <= K+1 passes: every non-final pass clips
    # at least one rack at its extreme
    for _ in range(K + 1):  # step 3a: raise ceilings toward M
        deficit = r_tot - int(rack_hi.sum())
        head = M - rack_hi
        if deficit <= 0 or not (head > 0).any():
            break
        add = -(-deficit // max(int((head > 0).sum()), 1))
        rack_hi = np.minimum(rack_hi + np.where(head > 0, add, 0), M)
    for _ in range(K + 1):  # step 3b: lower floors toward m
        excess = int(rack_lo.sum()) - r_tot
        slack = rack_lo - m
        if excess <= 0 or not (slack > 0).any():
            break
        sub = -(-excess // max(int((slack > 0).sum()), 1))
        rack_lo = np.maximum(rack_lo - np.where(slack > 0, sub, 0), m)
    # step 4: per-broker band vs rack floors/ceilings
    broker_hi = max(broker_hi, int(np.max(-(-rack_lo // rack_sizes))))
    supply = lambda h: int(np.minimum(rack_sizes * h, rack_hi).sum())  # noqa: E731
    while supply(broker_hi) < r_tot and broker_hi < r_tot:
        broker_hi += 1
    broker_lo = min(broker_lo, int(np.min(rack_hi // rack_sizes)))

    inst = ProblemInstance(
        broker_ids=broker_ids,
        rack_of_broker=rack_of_broker,
        rack_names=rack_names,
        topics=topics,
        topic_of_part=topic_of_part,
        part_id=part_id,
        rf=rf,
        a0=a0,
        current=current,
        w_leader=w_leader,
        w_follower=w_follower,
        broker_lo=int(broker_lo),
        broker_hi=int(broker_hi),
        leader_lo=int(leader_lo),
        leader_hi=int(leader_hi),
        rack_lo=rack_lo.astype(np.int32),
        rack_hi=rack_hi.astype(np.int32),
        part_rack_hi=part_rack_hi.astype(np.int32),
    )
    return inst
