"""Synthetic cluster/scenario generators — the benchmark suite's inputs.

The reference ships exactly one worked scenario (the 20-broker demo,
``/root/reference/README.md:27-91``); the five configurations below are
the new build's benchmark suite (BASELINE.json "configs", SURVEY.md §4.6):

1. ``demo``          the README example (golden acceptance case)
2. ``scale_out``     64 brokers / 4 racks / 200 topics x 40 parts RF=3, add 8
3. ``decommission``  256 brokers / 8 racks / 10k parts RF=3, drop one broker
4. ``rf_change``     RF 2->3 across 1k partitions, strict rack diversity
5. ``leader_only``   128 brokers / 5k parts, fix leader skew, 0 replica moves

Placement scheme: brokers are ordered round-robin by rack
(r0b0, r1b0, ..., rK-1b0, r0b1, ...), and partition ``p`` takes the window
``ordered[(p + s) % B]`` for slot ``s``. Consecutive window entries sit in
distinct racks whenever RF <= K, so the generated *current* assignments are
rack-diverse and per-broker/per-rack balanced by construction — realistic
steady-state clusters, which is exactly what a reassignment starts from.
"""

from __future__ import annotations


from dataclasses import dataclass, replace

from ..models.cluster import (
    Assignment,
    PartitionAssignment,
    Topology,
    demo_assignment,
    demo_broker_list,
    demo_topology,
)


@dataclass
class Scenario:
    """One benchmark configuration: the optimizer's full input tuple plus
    bookkeeping the harness uses to judge plan quality."""

    name: str
    current: Assignment
    broker_list: list[int]
    topology: Topology
    target_rf: int | None = None
    # a provable lower bound on replica moves for any feasible plan; when
    # ``lb_tight`` the bound is known achievable, so the harness's quality
    # gate requires moves == min_moves_lb (e.g. leader_only: exactly 0)
    min_moves_lb: int = 0
    lb_tight: bool = False
    notes: str = ""

    @property
    def kwargs(self) -> dict:
        return dict(
            current=self.current,
            broker_list=self.broker_list,
            topology=self.topology,
            target_rf=self.target_rf,
        )


def _rack_interleaved(broker_ids: list[int], topology: Topology) -> list[int]:
    """Order brokers round-robin across racks."""
    by_rack: dict[str, list[int]] = {}
    for b in broker_ids:
        by_rack.setdefault(topology.rack(b), []).append(b)
    lanes = [sorted(v) for _, v in sorted(by_rack.items())]
    out: list[int] = []
    i = 0
    while len(out) < len(broker_ids):
        for lane in lanes:
            if i < len(lane):
                out.append(lane[i])
        i += 1
    return out


def balanced_assignment(
    broker_ids: list[int],
    topology: Topology,
    topics: dict[str, int],
    rf: int,
) -> Assignment:
    """Rack-diverse, balanced placement (see module docstring).

    Replica slots are filled *sequentially* through the rack-interleaved
    order — replica g lands on ``order[g % B]`` — so per-broker totals are
    exactly floor/ceil(R_tot/B) and per-rack totals exactly proportional,
    whatever P and B are. The leader of each partition is then chosen
    greedily as its least-leading replica, keeping leader counts inside
    the floor/ceil band too: the generated current assignments are fully
    feasible steady states."""
    order = _rack_interleaved(broker_ids, topology)
    B = len(order)
    lcnt = {b: 0 for b in broker_ids}
    parts = []
    g = 0
    for topic, n_parts in topics.items():
        for p in range(n_parts):
            reps = [order[(g + s) % B] for s in range(rf)]
            g += rf
            lead = min(reps, key=lambda b: (lcnt[b], b))
            lcnt[lead] += 1
            reps = [lead] + [b for b in reps if b != lead]
            parts.append(
                PartitionAssignment(topic=topic, partition=p, replicas=reps)
            )
    return Assignment(partitions=parts)


def _mod_topology(broker_ids: list[int], n_racks: int) -> Topology:
    return Topology.from_dict(
        {str(b): f"rack{b % n_racks}" for b in broker_ids}
    )


def demo() -> Scenario:
    """BASELINE config 1 — the reference's worked example
    (``README.md:27-91``): 20 brokers, even/odd AZs, 10 partitions RF=2,
    decommission broker 19. Known optimum: exactly 1 replica move."""
    return Scenario(
        name="demo",
        current=demo_assignment(),
        broker_list=demo_broker_list(),  # 0..18 (19 removed)
        topology=demo_topology(),
        min_moves_lb=1,
        lb_tight=True,
        notes="golden: optimal plan moves exactly 1 replica (README.md:85-91)",
    )


def scale_out(
    n_old: int = 56, n_new: int = 64, n_racks: int = 4,
    n_topics: int = 200, parts_per_topic: int = 40, rf: int = 3,
) -> Scenario:
    """BASELINE config 2 — scale-out rebalance: cluster grew from 56 to 64
    brokers; rebalance so the 8 empty brokers take their share."""
    new_list = list(range(n_new))
    topo = _mod_topology(new_list, n_racks)
    current = balanced_assignment(
        list(range(n_old)), topo, {f"t{i}": parts_per_topic for i in range(n_topics)}, rf
    )
    # every replica the new brokers must absorb is one unavoidable move:
    # any feasible plan gives each broker >= floor(R/B) replicas
    r_tot = n_topics * parts_per_topic * rf
    lb = (n_new - n_old) * (r_tot // n_new)
    return Scenario(
        name="scale_out",
        current=current,
        broker_list=new_list,
        topology=topo,
        min_moves_lb=lb,
        notes=f"add {n_new - n_old} brokers; each must reach floor(R/B) replicas",
    )


def decommission(
    n_brokers: int = 256, n_racks: int = 8,
    n_topics: int = 100, parts_per_topic: int = 100, rf: int = 3,
    remove: int | None = None,
) -> Scenario:
    """BASELINE config 3 — the headline/north-star scenario: 256 brokers,
    8 racks, 10k partitions RF=3, single-broker decommission. Minimum moves
    = the replicas hosted on the removed broker (each must land somewhere
    else; nothing else is forced to move since remaining-broker bands stay
    satisfiable)."""
    all_brokers = list(range(n_brokers))
    remove = n_brokers - 1 if remove is None else remove
    topo = _mod_topology(all_brokers, n_racks)
    current = balanced_assignment(
        all_brokers, topo, {f"t{i}": parts_per_topic for i in range(n_topics)}, rf
    )
    lb = sum(
        1 for p in current.partitions for b in p.replicas if b == remove
    )
    return Scenario(
        name="decommission",
        current=current,
        broker_list=[b for b in all_brokers if b != remove],
        topology=topo,
        min_moves_lb=lb,
        lb_tight=True,
        notes=f"drop broker {remove}; it hosts {lb} replicas -> min {lb} moves",
    )


def rf_change(
    n_brokers: int = 32, n_racks: int = 4,
    n_topics: int = 10, parts_per_topic: int = 100, rf_old: int = 2, rf_new: int = 3,
) -> Scenario:
    """BASELINE config 4 — replication-factor increase 2->3 under strict
    rack diversity (the reference's RF-change use case, README.md:8-10).
    Every partition gains rf_new - rf_old replicas; each is a move."""
    brokers = list(range(n_brokers))
    topo = _mod_topology(brokers, n_racks)
    current = balanced_assignment(
        brokers, topo, {f"t{i}": parts_per_topic for i in range(n_topics)}, rf_old
    )
    n_parts = n_topics * parts_per_topic
    return Scenario(
        name="rf_change",
        current=current,
        broker_list=brokers,
        topology=topo,
        target_rf=rf_new,
        min_moves_lb=n_parts * (rf_new - rf_old),
        lb_tight=True,
        notes="each partition must gain one replica on a new broker",
    )


def leader_only(
    n_brokers: int = 128, n_racks: int = 8,
    n_topics: int = 50, parts_per_topic: int = 100, rf: int = 3,
) -> Scenario:
    """BASELINE config 5 — leader-only rebalance: replicas are perfectly
    placed but leadership is skewed onto a subset of brokers. The optimal
    plan fixes leader balance with in-place leader swaps: ZERO replica
    moves. Exercises the engine's lswap move type in isolation."""
    brokers = list(range(n_brokers))
    topo = _mod_topology(brokers, n_racks)
    base = balanced_assignment(
        brokers, topo, {f"t{i}": parts_per_topic for i in range(n_topics)}, rf
    )
    # skew leadership: make the replica with the smallest (id mod 16)
    # residue the leader — leaders pile onto low-residue brokers while the
    # replica *sets* stay balanced and rack-diverse
    parts = []
    for p in base.partitions:
        reps = sorted(p.replicas, key=lambda b: (b % 16, b))
        parts.append(
            PartitionAssignment(topic=p.topic, partition=p.partition, replicas=reps)
        )
    return Scenario(
        name="leader_only",
        current=Assignment(partitions=parts),
        broker_list=brokers,
        topology=topo,
        min_moves_lb=0,
        lb_tight=True,
        notes="optimal plan has 0 replica moves, only leader swaps",
    )


def _scatter_assignment(
    broker_ids: list[int],
    topology: Topology,
    topic_rf: list[tuple[str, int, int]],
    rng,
) -> Assignment:
    """Exactly balanced but SHUFFLED placement: per-broker totals are
    floor/ceil(R/B) and every partition is rack-diverse, yet member
    sets are drawn by seeded shuffle so essentially every partition is
    its own symmetry class (the opposite of ``balanced_assignment``'s
    round-robin windows, which collapse 50-500x under
    ``_member_classes``). ``topic_rf`` is [(topic, n_parts, rf)]."""
    B = len(broker_ids)
    rack = {b: topology.rack(b) for b in broker_ids}
    rfs = [rf for _, n, rf in topic_rf for _ in range(n)]
    R = sum(rfs)
    lo, n_hi = R // B, R % B
    counts = {b: lo for b in broker_ids}
    # ceil brokers spread rack-interleaved so rack totals stay balanced
    for b in _rack_interleaved(broker_ids, topology)[:n_hi]:
        counts[b] += 1
    supply = [b for b in broker_ids for _ in range(counts[b])]
    rng.shuffle(supply)
    starts = [0]
    for r in rfs:
        starts.append(starts[-1] + r)
    # forward repair: ensure each partition's slots are distinct
    # brokers in distinct racks, swapping offenders with any later slot
    # that fits (later partitions are untouched regions, so a forward
    # swap can only be re-examined, never silently corrupted)
    n_slots = len(supply)
    for p in range(len(rfs)):
        s, e = starts[p], starts[p + 1]
        for j in range(s, e):
            used_b = set(supply[s:j])
            used_r = {rack[x] for x in supply[s:j]}
            if supply[j] not in used_b and rack[supply[j]] not in used_r:
                continue
            for k in range(e, n_slots):
                if (supply[k] not in used_b
                        and rack[supply[k]] not in used_r):
                    supply[j], supply[k] = supply[k], supply[j]
                    break
            else:
                # tail starvation: trade with an earlier partition where
                # both stay valid (rare; seeded, so exercised in tests)
                if not _backward_slot_trade(
                    supply, starts, rfs, rack, p, j
                ):
                    raise RuntimeError(
                        "scatter repair failed; change the seed"
                    )
    # leaders: greedy least-loaded, then rebalanced into the band that
    # is valid BOTH before and after any single-broker removal
    n_p = len(rfs)
    reps = [supply[starts[p]:starts[p + 1]] for p in range(n_p)]
    lcnt = {b: 0 for b in broker_ids}
    leads = []
    for rr in reps:
        ld = min(rr, key=lambda b: (lcnt[b], b))
        lcnt[ld] += 1
        leads.append(ld)
    if B > 1:
        # the surviving-cluster floor is the stricter target, but it is
        # only reachable when B brokers can all carry it
        lo_t = n_p // (B - 1)
        if lo_t * B > n_p:
            lo_t = n_p // B
        # ceil(n_p/B) is the stricter (pre-removal) ceiling of the two
        hi_t = max(-(-n_p // B), lo_t)
    else:
        lo_t = hi_t = n_p
    def promote(p, nb):
        lcnt[leads[p]] -= 1
        lcnt[nb] += 1
        leads[p] = nb

    for _ in range(4 * n_p):
        if all(lo_t <= lcnt[b] <= hi_t for b in broker_ids):
            break
        changed = False
        for p, rr in enumerate(reps):
            ld = leads[p]
            if lcnt[ld] > hi_t:
                cand = [b for b in rr if lcnt[b] < hi_t]
            elif lcnt[ld] > lo_t:
                cand = [b for b in rr if lcnt[b] < lo_t]
            else:
                continue
            if cand:
                promote(p, min(cand, key=lambda b: (lcnt[b], b)))
                changed = True
        if all(lo_t <= lcnt[b] <= hi_t for b in broker_ids):
            break
        if changed:
            continue
        # single promotions are stuck: augment through an at-bound
        # intermediary W (U gains via A, W compensates via B — the
        # 2-hop chains some seeds need when every deficit broker only
        # appears in partitions whose leaders sit exactly on a bound)
        contains: dict[int, list[int]] = {b: [] for b in broker_ids}
        for p, rr in enumerate(reps):
            for b in rr:
                contains[b].append(p)
        for U in [b for b in broker_ids if lcnt[b] < lo_t]:
            done = False
            for A in contains[U]:
                W = leads[A]
                for Bp in contains[W]:
                    V = leads[Bp]
                    if V != W and lcnt[V] > lo_t:
                        promote(Bp, W)  # W compensates first
                        promote(A, U)
                        done = changed = True
                        break
                if done:
                    break
        for V in [b for b in broker_ids if lcnt[b] > hi_t]:
            done = False
            for Bp in [p for p in range(n_p) if leads[p] == V]:
                for W in reps[Bp]:
                    if W == V:
                        continue
                    for A in [p for p in contains[W] if leads[p] == W]:
                        X = [b for b in reps[A]
                             if b != W and lcnt[b] < hi_t]
                        if X:
                            promote(A, min(X))  # W sheds first
                            promote(Bp, W)
                            done = changed = True
                            break
                    if done:
                        break
                if done:
                    break
        if not changed:
            raise RuntimeError(
                "leader rebalance stalled; change the seed"
            )
    if not all(lo_t <= lcnt[b] <= hi_t for b in broker_ids):
        raise RuntimeError("leader rebalance did not converge")
    parts = []
    i = 0
    for topic, n, _rf in topic_rf:
        for p in range(n):
            rr = reps[i]
            ld = leads[i]
            parts.append(PartitionAssignment(
                topic=topic, partition=p,
                replicas=[ld] + [b for b in rr if b != ld],
            ))
            i += 1
    return Assignment(partitions=parts)


def _backward_slot_trade(supply, starts, rfs, rack, p, j) -> bool:
    """Swap ``supply[j]`` with a slot of an earlier partition such that
    both partitions end up valid. Returns True on success."""
    s, e = starts[p], starts[p + 1]
    used_b = set(supply[s:j])
    used_r = {rack[x] for x in supply[s:j]}
    for q in range(p):
        qs, qe = starts[q], starts[q + 1]
        for k in range(qs, qe):
            cand = supply[k]
            if cand in used_b or rack[cand] in used_r:
                continue
            q_others = [supply[x] for x in range(qs, qe) if x != k]
            give = supply[j]
            if give in q_others:
                continue
            if rack[give] in {rack[x] for x in q_others}:
                continue
            supply[j], supply[k] = supply[k], supply[j]
            return True
    return False


def adversarial(
    n_brokers: int = 256, n_racks: int = 8,
    n_topics_low: int = 50, n_topics_high: int = 50,
    parts_per_topic: int = 100, rf_low: int = 2, rf_high: int = 4,
    seed: int = 7,
) -> Scenario:
    """Constructor-proof decommission at headline scale (VERDICT r3
    item 2): same 256 brokers / 8 racks / 10k partitions as the
    headline, but with per-partition RF asymmetry (half the topics RF=2,
    half RF=4) and seeded-shuffled member sets, so every partition is
    its own symmetry class. ``agg_effective()`` is False (the
    aggregated MILP refuses), caps stay slack (no LP constructor race:
    the default totals keep floor/ceil(R/B) unchanged by the removal),
    and the TPU sweep annealer has to close to the bound ladder
    on-chip — this row is the at-scale proof of the search engine the
    framework is named for, not of the host constructor."""
    import numpy as _np

    all_brokers = list(range(n_brokers))
    remove = n_brokers - 1
    topo = _mod_topology(all_brokers, n_racks)
    topic_rf = (
        [(f"lo{i}", parts_per_topic, rf_low)
         for i in range(n_topics_low)]
        + [(f"hi{i}", parts_per_topic, rf_high)
           for i in range(n_topics_high)]
    )
    current = _scatter_assignment(
        all_brokers, topo, topic_rf, _np.random.default_rng(seed)
    )
    lb = sum(
        1 for p in current.partitions for b in p.replicas if b == remove
    )
    return Scenario(
        name="adversarial",
        current=current,
        broker_list=[b for b in all_brokers if b != remove],
        topology=topo,
        min_moves_lb=lb,
        lb_tight=True,
        notes=(
            f"shuffled mixed-RF decommission of broker {remove} "
            f"({lb} replicas): every partition its own symmetry class, "
            "caps slack -> annealer must close to the bound on-chip"
        ),
    )


def jumbo(
    n_brokers: int = 512, n_racks: int = 16,
    n_topics: int = 250, parts_per_topic: int = 200, rf: int = 3,
) -> Scenario:
    """Beyond the north star: 512 brokers / 16 racks / 50k partitions
    RF=3 decommission — 5x the headline's partition count (150k replica
    slots). No BASELINE counterpart; exists to demonstrate the sweep
    engine's scaling headroom past the size that motivated the rebuild
    (per-sweep work is O(chains * partitions); sequential depth stays
    flat)."""
    sc = decommission(n_brokers=n_brokers, n_racks=n_racks,
                      n_topics=n_topics, parts_per_topic=parts_per_topic,
                      rf=rf)
    return replace(
        sc, name="jumbo",
        notes=f"{n_brokers}b/{n_topics * parts_per_topic}-part "
              f"decommission; {sc.notes}",
    )


def adv50k(
    n_brokers: int = 512, n_racks: int = 16,
    n_topics_low: int = 126, n_topics_high: int = 124,
    parts_per_topic: int = 200, seed: int = 7,
) -> Scenario:
    """Constructor-proof at JUMBO scale: the adversarial shuffled
    mixed-RF decommission grown to 512 brokers / 16 racks / 50k
    partitions (149,600 replica slots — jumbo's size with adversarial's
    asymmetry). The 126/124 topic split keeps the broker bands
    removal-invariant ([292, 292] both sides; leaders [97, 98] both),
    so caps stay slack. ~147k symmetry classes over ~149k members, so
    the aggregated MILP refuses, and the sweep annealer must close to
    the bound ladder on-chip at 5x the headline scale — the proof that
    the search engine's flat sequential depth survives where the host
    constructors cannot follow."""
    sc = adversarial(
        n_brokers=n_brokers, n_racks=n_racks,
        n_topics_low=n_topics_low, n_topics_high=n_topics_high,
        parts_per_topic=parts_per_topic, seed=seed,
    )
    return replace(
        sc, name="adv50k",
        notes=(f"{n_brokers}b/"
               f"{(n_topics_low + n_topics_high) * parts_per_topic}-part "
               f"shuffled mixed-RF decommission; {sc.notes}"),
    )


def ultra_jumbo(
    n_az: int = 4, racks_per_az: int = 4, base_brokers: int = 8,
    partitions: int = 200_000, rf: int = 3, cross_frac: float = 0.02,
    seed: int = 0,
) -> Scenario:
    """ROADMAP item 4's instance family: an AZ/rack-structured
    decommission sized past any flat bucket (default 200k partitions,
    600k replica slots). Racks are heterogeneous (``base + r`` brokers
    for rack ``r``) but the rack-size *multiset is identical across
    AZs*, and the decommission removes one rack-0 broker per AZ — so
    every AZ keeps the same (brokers, racks) shape and the decomposed
    map phase can stack all AZ sub-instances into ONE lane-padded
    executable (docs/DECOMPOSE.md). Most partitions live entirely
    inside one AZ (per-AZ balanced topic blocks); a ``cross_frac``
    sliver of partitions is placed with each replica in a *different*
    AZ — the boundary family the reduce phase must reconcile."""
    if rf > n_az:
        raise ValueError(f"ultra_jumbo needs rf <= n_az ({rf} > {n_az})")
    if racks_per_az <= rf:
        # ceil(rf/K) pins part_rack_hi at 1 for big K: a group with
        # only rf racks would force every partition onto ALL of them,
        # colliding with the proportional rack bands
        raise ValueError(
            f"ultra_jumbo needs racks_per_az > rf "
            f"({racks_per_az} <= {rf})")
    # heterogeneous but FLAT rack sizes (base+0..base+racks-1): per-AZ
    # rack-band admissibility needs the largest rack <= B_az/rf once
    # part_rack_hi == 1 (docs/DECOMPOSE.md "split criteria")
    rack_sizes = [base_brokers + r for r in range(racks_per_az)]
    rack_of: dict[str, str] = {}
    az_brokers: list[list[int]] = []
    removed: list[int] = []
    bid = 0
    for g in range(n_az):
        mine: list[int] = []
        for r, sz in enumerate(rack_sizes):
            for _ in range(sz):
                rack_of[str(bid)] = f"az{g}-rack{r}"
                mine.append(bid)
                bid += 1
        az_brokers.append(mine)
        removed.append(mine[base_brokers - 1])  # last rack-0 broker
    topo = Topology.from_dict(rack_of)
    all_brokers = [b for mine in az_brokers for b in mine]

    cross = int(partitions * cross_frac)
    per_az = (partitions - cross) // n_az
    cross = partitions - per_az * n_az  # exact total
    parts: list[PartitionAssignment] = []
    for g in range(n_az):
        blk = balanced_assignment(
            az_brokers[g], topo, {f"az{g}": per_az}, rf
        )
        parts.extend(blk.partitions)
    # boundary family: replica j of cross partition p lives in AZ
    # (seed + p + j) % n_az, walking each AZ's rack-interleaved order —
    # every replica a distinct AZ (hence a distinct rack)
    orders = [_rack_interleaved(mine, topo) for mine in az_brokers]
    for p in range(cross):
        reps = [
            orders[(seed + p + j) % n_az][(p * rf + j) % len(orders[0])]
            for j in range(rf)
        ]
        parts.append(
            PartitionAssignment(topic="xaz", partition=p, replicas=reps)
        )
    current = Assignment(partitions=parts)
    gone = set(removed)
    lb = sum(1 for pa in current.partitions for b in pa.replicas
             if b in gone)
    return Scenario(
        name="ultra_jumbo",
        current=current,
        broker_list=[b for b in all_brokers if b not in gone],
        topology=topo,
        min_moves_lb=lb,
        notes=(
            f"{len(all_brokers)}b/{n_az}az/{partitions}-part AZ-structured "
            f"decommission of one broker per AZ ({lb} replicas), "
            f"{cross} cross-AZ boundary partitions"
        ),
    )


def ultra_jumbo_case(seed: int = 0, partitions: int = 200_000) -> Scenario:
    """The ISSUE 16 entry point: the AZ-structured ultra-jumbo
    decommission at the requested size, seeded for reproducible
    boundary placement. Tests and bench both consume this wrapper so
    the decomposed path is always measured on the same family."""
    return ultra_jumbo(partitions=partitions, seed=seed)


def messy_cluster(rng):
    """One deliberately irregular worst-case cluster (the property
    fuzz's messy family, docs/ANALYSIS.md): several topics with
    different partition counts and RFs, a lopsided rack map (rack 0
    holds ~half the brokers; exact bands with single-broker racks are
    common), and a broker list that may both drop and add brokers.
    THE one generator — tests/test_property_fuzz.py and the bench
    portfolio A/B both consume it, so the 'messy[1] was the tier-1
    xfail' correspondence can never silently desynchronize. Returns
    ``(current, broker_list, topology, target_rf)``."""
    n_brokers = int(rng.integers(6, 16))
    n_topics = int(rng.integers(1, 4))
    parts = []
    for t in range(n_topics):
        rf = int(rng.integers(1, min(4, n_brokers) + 1))
        for p in range(int(rng.integers(2, 9))):
            reps = rng.choice(n_brokers, size=rf, replace=False)
            parts.append(PartitionAssignment(
                f"topic-{t}", p, [int(b) for b in reps]
            ))
    n_racks = int(rng.integers(1, 4))
    add = int(rng.integers(0, 3))
    all_ids = list(range(n_brokers + add))
    rack_of = {
        b: f"rack{0 if b % 4 < 2 else (b % n_racks)}" for b in all_ids
    }
    drop = int(rng.integers(0, 2))
    brokers = all_ids[drop:]
    target_rf = None
    if rng.random() < 0.3:
        target_rf = int(rng.integers(1, 4))
    return (Assignment(partitions=parts), brokers,
            Topology(rack_of=rack_of), target_rf)


def messy_case(seed: int = 0):
    """The sweep-test family's seeding of :func:`messy_cluster`
    (``default_rng(2000 + seed)``): ``messy_case(1)`` IS the instance
    ``test_sweep_engine_on_messy_clusters[1]`` pins — the exact-band
    tier-1 xfail the portfolio lanes closed (docs/PORTFOLIO.md)."""
    import numpy as _np

    return messy_cluster(_np.random.default_rng(2000 + int(seed)))


SCENARIOS = {
    "demo": demo,
    "scale_out": scale_out,
    "decommission": decommission,
    "rf_change": rf_change,
    "leader_only": leader_only,
    "adversarial": adversarial,
    "adv50k": adv50k,
    "jumbo": jumbo,
    "ultra_jumbo": ultra_jumbo,
}

# shrunk per-scenario kwargs for quick CPU smoke runs: the single source of
# truth shared by bench.py (--smoke) and ops.bench_kernel, so the scenario
# solve and the embedded kernel micro-bench always measure the same instance
SMOKE_KWARGS = {
    "demo": dict(),
    "scale_out": dict(n_old=12, n_new=16, n_topics=8, parts_per_topic=10),
    "decommission": dict(n_brokers=32, n_topics=8, parts_per_topic=25),
    "rf_change": dict(n_brokers=16, n_topics=4, parts_per_topic=25),
    "leader_only": dict(n_brokers=32, n_topics=8, parts_per_topic=25),
    "adversarial": dict(n_brokers=32, n_topics_low=11, n_topics_high=9,
                        parts_per_topic=10),
    "adv50k": dict(n_brokers=48, n_topics_low=6, n_topics_high=6,
                   parts_per_topic=10),
    "jumbo": dict(n_brokers=48, n_topics=10, parts_per_topic=40),
    "ultra_jumbo": dict(n_az=3, racks_per_az=4, partitions=600),
}
