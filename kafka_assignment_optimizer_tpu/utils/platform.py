"""Platform pinning: make ``JAX_PLATFORMS`` authoritative.

Site plugins can force-register an accelerator platform and win over the
environment variable (tests/conftest.py documents the same issue for the
CPU test mesh). Entry points (CLI, HTTP service, bench) call
:func:`pin_platform` before any JAX backend initializes so an operator's
``JAX_PLATFORMS=cpu`` (or ``tpu``) is always honored.
"""

from __future__ import annotations

import os


def pin_platform(platform: str | None = None) -> None:
    """Pin JAX to ``platform`` (default: the ``JAX_PLATFORMS`` env var).
    No-op when neither is set. Must run before backend initialization."""
    want = platform or os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    if jax.config.jax_platforms != want:
        jax.config.update("jax_platforms", want)


def enable_compile_cache() -> None:
    """Turn on JAX's persistent compilation cache (idempotent).

    Measured on the r2 TPU host: the headline sweep executable costs
    ~25 s to compile in a fresh process and ~4 s with a warm disk cache —
    and the bench harness, the CLI, and the HTTP service each solve in
    fresh processes, so cross-process reuse is the difference between a
    60 s and a ~15 s cold start. Opt out with ``KAO_JIT_CACHE=off``;
    override the location with ``KAO_JIT_CACHE=/path``."""
    want = os.environ.get("KAO_JIT_CACHE", "")
    if want.lower() in ("off", "0", "none"):
        return
    path = want or os.path.join(
        os.environ.get("XDG_CACHE_HOME",
                       os.path.expanduser("~/.cache")),
        "kafka_assignment_optimizer_tpu", "jit",
    )
    import jax

    if jax.config.jax_compilation_cache_dir != path:
        try:
            os.makedirs(path, exist_ok=True)
        except OSError as e:
            # the cache is an optimization, never a precondition: a
            # read-only $HOME (containerized service) must not fail solves
            from ..obs import log as _olog

            _olog.warn("compile_cache_disabled", error=str(e))
            return
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def ensure_backend() -> str:
    """Initialize a JAX backend, surviving a broken accelerator plugin.

    Round-1 postmortem: the site TPU plugin can fail init with
    ``RuntimeError: Unable to initialize backend 'axon': UNAVAILABLE``,
    which killed every solve before a single op ran. Attempt order:
    current config, then ``jax_platforms=''`` (automatic choice, which
    tolerates plugin failure), then ``cpu``. Returns the platform of the
    default device. Must be called before any device arrays exist —
    recovery resets the backend registry (``clear_backends``).

    (A *hanging* plugin cannot be recovered in-process; ``bench.py``
    handles that case with subprocess probes under a timeout.)
    """
    import jax

    last: Exception | None = None
    for override in (None, "", "cpu"):
        try:
            if override is not None:
                from jax.extend.backend import clear_backends

                jax.config.update("jax_platforms", override)
                clear_backends()
            return jax.devices()[0].platform
        except RuntimeError as e:  # backend init failure
            last = e
    raise RuntimeError(f"no usable JAX backend: {last}")  # pragma: no cover
