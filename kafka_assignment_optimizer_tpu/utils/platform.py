"""Platform pinning: make ``JAX_PLATFORMS`` authoritative.

Site plugins can force-register an accelerator platform and win over the
environment variable (tests/conftest.py documents the same issue for the
CPU test mesh). Entry points (CLI, HTTP service, bench) call
:func:`pin_platform` before any JAX backend initializes so an operator's
``JAX_PLATFORMS=cpu`` (or ``tpu``) is always honored.
"""

from __future__ import annotations

import os
import threading


def pin_platform(platform: str | None = None) -> None:
    """Pin JAX to ``platform`` (default: the ``JAX_PLATFORMS`` env var).
    No-op when neither is set. Must run before backend initialization."""
    want = platform or os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    if jax.config.jax_platforms != want:
        jax.config.update("jax_platforms", want)


# persistent-cache traffic counters (ISSUE 14 satellite): jax reports
# disk-cache hits/misses as jax.monitoring events, and a fleet sharing
# one KAO_COMPILE_CACHE dir needs them to PROVE a non-owner worker's
# warmup compiled nothing fresh (every .compile() call looks the same
# from bucket.STATS — only the miss counter separates a cold XLA
# compile from a disk hit). Counted here, surfaced in /healthz "cache"
# and the /warmup per-shape rows.
_CACHE_STATS_LOCK = threading.Lock()
_CACHE_STATS = {"hits": 0, "misses": 0}
_CACHE_LISTENER_ON = False


def _cache_event(name: str, **kw) -> None:
    if name == "/jax/compilation_cache/cache_hits":
        with _CACHE_STATS_LOCK:
            _CACHE_STATS["hits"] += 1
    elif name == "/jax/compilation_cache/cache_misses":
        with _CACHE_STATS_LOCK:
            _CACHE_STATS["misses"] += 1


def compile_cache_stats() -> dict:
    """Persistent compile-cache state: the configured dir (None while
    disabled or before the first solve armed it) and the hit/miss
    traffic this process has generated against it. Reads the already-
    imported jax module only — a /healthz or router probe must never be
    the thing that pays the jax import."""
    import sys

    jax = sys.modules.get("jax")
    d = None
    if jax is not None:
        try:
            d = jax.config.jax_compilation_cache_dir
        except Exception:
            d = None
    with _CACHE_STATS_LOCK:
        return {"dir": d, "enabled": bool(d), **_CACHE_STATS}


def compile_cache_dir() -> str | None:
    """The directory :func:`enable_compile_cache` would use (without
    importing jax or touching the filesystem); None when disabled."""
    want = os.environ.get("KAO_COMPILE_CACHE",
                          os.environ.get("KAO_JIT_CACHE", ""))
    if want.lower() in ("off", "0", "none"):
        return None
    return want or os.path.join(
        os.environ.get("XDG_CACHE_HOME",
                       os.path.expanduser("~/.cache")),
        "kafka_assignment_optimizer_tpu", "jit",
    )


def enable_compile_cache() -> None:
    """Turn on JAX's persistent compilation cache (idempotent).

    Measured on the r2 TPU host: the headline sweep executable costs
    ~25 s to compile in a fresh process and ~4 s with a warm disk cache —
    and the bench harness, the CLI, and the HTTP service each solve in
    fresh processes, so cross-process reuse is the difference between a
    60 s and a ~15 s cold start. A serving FLEET points every worker at
    ONE shared dir (``KAO_COMPILE_CACHE``, docs/FLEET.md) so one
    worker's cold compile becomes every other worker's disk hit.

    Opt out with ``KAO_COMPILE_CACHE=off``; override the location with
    ``KAO_COMPILE_CACHE=/path`` (``KAO_JIT_CACHE`` is the legacy
    spelling and still honored). ``KAO_COMPILE_CACHE_MIN_S`` lowers the
    persist threshold (default 0.5 s) so small-bucket fleets — whose
    executables compile fast but still cost a first-contact stall —
    share warmth too."""
    path = compile_cache_dir()
    if path is None:
        return
    import jax

    global _CACHE_LISTENER_ON
    if not _CACHE_LISTENER_ON:
        _CACHE_LISTENER_ON = True
        try:
            from jax import monitoring as _mon

            _mon.register_event_listener(_cache_event)
        except Exception:  # pragma: no cover - monitoring API moved
            pass
    if jax.config.jax_compilation_cache_dir != path:
        try:
            os.makedirs(path, exist_ok=True)
        except OSError as e:
            # the cache is an optimization, never a precondition: a
            # read-only $HOME (containerized service) must not fail solves
            from ..obs import log as _olog

            _olog.warn("compile_cache_disabled", error=str(e))
            return
        try:
            min_s = float(os.environ.get("KAO_COMPILE_CACHE_MIN_S", 0.5))
        except ValueError:
            min_s = 0.5
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_s)


def ensure_backend() -> str:
    """Initialize a JAX backend, surviving a broken accelerator plugin.

    Round-1 postmortem: the site TPU plugin can fail init with
    ``RuntimeError: Unable to initialize backend 'axon': UNAVAILABLE``,
    which killed every solve before a single op ran. Attempt order:
    current config, then ``jax_platforms=''`` (automatic choice, which
    tolerates plugin failure), then ``cpu``. Returns the platform of the
    default device. Must be called before any device arrays exist —
    recovery resets the backend registry (``clear_backends``).

    (A *hanging* plugin cannot be recovered in-process; ``bench.py``
    handles that case with subprocess probes under a timeout.)
    """
    import jax

    last: Exception | None = None
    for override in (None, "", "cpu"):
        try:
            if override is not None:
                from jax.extend.backend import clear_backends

                jax.config.update("jax_platforms", override)
                clear_backends()
            return jax.devices()[0].platform
        except RuntimeError as e:  # backend init failure
            last = e
    raise RuntimeError(f"no usable JAX backend: {last}")  # pragma: no cover
