"""Platform pinning: make ``JAX_PLATFORMS`` authoritative.

Site plugins can force-register an accelerator platform and win over the
environment variable (tests/conftest.py documents the same issue for the
CPU test mesh). Entry points (CLI, HTTP service, bench) call
:func:`pin_platform` before any JAX backend initializes so an operator's
``JAX_PLATFORMS=cpu`` (or ``tpu``) is always honored.
"""

from __future__ import annotations

import os


def pin_platform(platform: str | None = None) -> None:
    """Pin JAX to ``platform`` (default: the ``JAX_PLATFORMS`` env var).
    No-op when neither is set. Must run before backend initialization."""
    want = platform or os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    if jax.config.jax_platforms != want:
        jax.config.update("jax_platforms", want)
