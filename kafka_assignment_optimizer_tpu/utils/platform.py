"""Platform pinning: make ``JAX_PLATFORMS`` authoritative.

Site plugins can force-register an accelerator platform and win over the
environment variable (tests/conftest.py documents the same issue for the
CPU test mesh). Entry points (CLI, HTTP service, bench) call
:func:`pin_platform` before any JAX backend initializes so an operator's
``JAX_PLATFORMS=cpu`` (or ``tpu``) is always honored.
"""

from __future__ import annotations

import os


def pin_platform(platform: str | None = None) -> None:
    """Pin JAX to ``platform`` (default: the ``JAX_PLATFORMS`` env var).
    No-op when neither is set. Must run before backend initialization."""
    want = platform or os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    if jax.config.jax_platforms != want:
        jax.config.update("jax_platforms", want)


def ensure_backend() -> str:
    """Initialize a JAX backend, surviving a broken accelerator plugin.

    Round-1 postmortem: the site TPU plugin can fail init with
    ``RuntimeError: Unable to initialize backend 'axon': UNAVAILABLE``,
    which killed every solve before a single op ran. Attempt order:
    current config, then ``jax_platforms=''`` (automatic choice, which
    tolerates plugin failure), then ``cpu``. Returns the platform of the
    default device. Must be called before any device arrays exist —
    recovery resets the backend registry (``clear_backends``).

    (A *hanging* plugin cannot be recovered in-process; ``bench.py``
    handles that case with subprocess probes under a timeout.)
    """
    import jax

    last: Exception | None = None
    for override in (None, "", "cpu"):
        try:
            if override is not None:
                from jax.extend.backend import clear_backends

                jax.config.update("jax_platforms", override)
                clear_backends()
            return jax.devices()[0].platform
        except RuntimeError as e:  # backend init failure
            last = e
    raise RuntimeError(f"no usable JAX backend: {last}")  # pragma: no cover
