// lp_solve-compatible command-line solver (bundled work-alike).
//
// Role: the reference's entire solve path is "lp_solve is used behind the
// scene to solve the generated linear equation"
// (/root/reference/README.md:135-137, 200) — an external C binary reading
// LP-format text and printing the optimal 0/1 assignment. That binary is
// not installable in this environment (no network egress), so this file
// provides a genuine stand-in: it PARSES the same LP-format dialect the
// emitter produces (solvers/lp.py, mirroring README.md:144-185), solves
// the 0-1 integer program exactly with branch-and-bound + activity-bound
// propagation, and prints output in the `lp_solve -S4` layout the adapter
// parses. The subprocess path (emit -> exec -> parse) therefore executes
// for real, end to end, against a binary that is NOT the in-process
// HiGHS/B&B code paths it is used to cross-check.
//
// Supported input subset (everything the reference sample uses):
//   // line comments, /* block comments */
//   max: | min:  objective with integer coefficients;
//   [name:] rows of `c v + c v ...  <= | >= | = | < | >  rhs;`
//   bin | int declarations (all variables are treated as 0/1 regardless);
//   statements may span lines; ';' terminates.
//
// Flags: -S<n> verbosity accepted and ignored (output is always the -S4
// shape), -timeout <sec> caps the search (best-so-far printed, marked
// suboptimal). Last non-flag argument is the model file; '-' reads stdin.
//
// Exit codes follow lp_solve 5.5: 0 optimal, 1 suboptimal (timeout with
// an incumbent), 2 infeasible, 7 timeout before any incumbent,
// 255 parse/usage error.

#include <algorithm>
#include <cctype>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <iostream>
#include <sstream>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

constexpr int64_t kInf = INT64_C(1) << 60;

struct Term {
  int64_t coef;
  int var;
};

struct Row {
  std::vector<Term> terms;
  int64_t lo = -kInf;  // lo <= sum <= hi
  int64_t hi = kInf;
};

struct Model {
  bool maximize = true;
  std::vector<std::string> names;
  std::vector<int64_t> obj;  // per variable
  std::vector<Row> rows;
};

// ---------------------------------------------------------------- lexer --

struct Lexer {
  std::string text;
  size_t pos = 0;

  void skip_ws() {
    for (;;) {
      while (pos < text.size() && std::isspace((unsigned char)text[pos]))
        ++pos;
      if (pos + 1 < text.size() && text[pos] == '/' && text[pos + 1] == '/') {
        while (pos < text.size() && text[pos] != '\n') ++pos;
        continue;
      }
      if (pos + 1 < text.size() && text[pos] == '/' && text[pos + 1] == '*') {
        pos += 2;
        while (pos + 1 < text.size() &&
               !(text[pos] == '*' && text[pos + 1] == '/'))
          ++pos;
        pos = std::min(pos + 2, text.size());
        continue;
      }
      break;
    }
  }

  bool eof() {
    skip_ws();
    return pos >= text.size();
  }

  char peek() {
    skip_ws();
    return pos < text.size() ? text[pos] : '\0';
  }

  bool eat(char c) {
    skip_ws();
    if (pos < text.size() && text[pos] == c) {
      ++pos;
      return true;
    }
    return false;
  }

  // identifier: letter/_ then alnum/_ (the t{t}b{b}p{p}[_l] names and any
  // other lp-format identifier)
  std::string ident() {
    skip_ws();
    size_t s = pos;
    if (pos < text.size() &&
        (std::isalpha((unsigned char)text[pos]) || text[pos] == '_')) {
      ++pos;
      while (pos < text.size() && (std::isalnum((unsigned char)text[pos]) ||
                                   text[pos] == '_'))
        ++pos;
    }
    return text.substr(s, pos - s);
  }

  bool number(int64_t *out) {
    skip_ws();
    size_t s = pos;
    if (pos < text.size() && (text[pos] == '+' || text[pos] == '-')) ++pos;
    size_t d = pos;
    while (pos < text.size() && std::isdigit((unsigned char)text[pos])) ++pos;
    if (pos == d) {
      pos = s;
      return false;
    }
    // LP format allows decimals; the model family is integral, so reject
    // a fractional part loudly rather than mis-solving
    if (pos < text.size() && text[pos] == '.') {
      std::fprintf(stderr, "lp_cli: non-integer coefficient at offset %zu\n",
                   s);
      std::exit(255);
    }
    *out = std::strtoll(text.c_str() + s, nullptr, 10);
    return true;
  }
};

// --------------------------------------------------------------- parser --

struct Parser {
  Lexer lx;
  Model m;
  std::unordered_map<std::string, int> by_name;

  int var_id(const std::string &name) {
    auto it = by_name.find(name);
    if (it != by_name.end()) return it->second;
    int id = (int)m.names.size();
    by_name.emplace(name, id);
    m.names.push_back(name);
    m.obj.push_back(0);
    return id;
  }

  [[noreturn]] void fail(const std::string &what) {
    std::fprintf(stderr, "lp_cli: parse error: %s (near offset %zu)\n",
                 what.c_str(), lx.pos);
    std::exit(255);
  }

  // `c v + c v - v ...` until an operator/semicolon; returns terms
  std::vector<Term> linear_expr() {
    std::vector<Term> terms;
    int sign = 1;
    for (;;) {
      char c = lx.peek();
      if (c == '+') {
        lx.eat('+');
        sign = 1;
        continue;
      }
      if (c == '-') {
        lx.eat('-');
        sign = -1;
        continue;
      }
      int64_t coef = 1;
      bool had_num = lx.number(&coef);
      std::string v = lx.ident();
      if (v.empty()) {
        if (had_num) fail("coefficient without variable");
        break;
      }
      terms.push_back({sign * coef, var_id(v)});
      sign = 1;
    }
    return terms;
  }

  void parse(const std::string &text) {
    lx.text = text;
    bool saw_objective = false;
    while (!lx.eof()) {
      size_t save = lx.pos;
      std::string head = lx.ident();
      if (!saw_objective &&
          (head == "max" || head == "min" || head == "maximize" ||
           head == "minimize" || head == "maximise" || head == "minimise")) {
        if (!lx.eat(':')) fail("expected ':' after objective keyword");
        m.maximize = (head[0] == 'm' && head[1] == 'a');
        for (const Term &t : linear_expr()) m.obj[t.var] += t.coef;
        if (!lx.eat(';')) fail("expected ';' after objective");
        saw_objective = true;
        continue;
      }
      if (head == "bin" || head == "int" || head == "sec" || head == "sin") {
        // declarations: register names, treat everything as binary
        for (;;) {
          std::string v = lx.ident();
          if (v.empty()) break;
          var_id(v);
          if (!lx.eat(',')) break;
        }
        if (!lx.eat(';')) fail("expected ';' after declaration list");
        continue;
      }
      // optional row label `name:` — `head` may already be the first var
      if (!head.empty() && lx.eat(':')) {
        // it was a label; fall through to parse the row body
      } else {
        lx.pos = save;  // re-parse from the start of the row
      }
      Row row;
      row.terms = linear_expr();
      if (row.terms.empty()) fail("empty constraint row");
      std::string op;
      while (lx.peek() == '<' || lx.peek() == '>' || lx.peek() == '=') {
        op += lx.text[lx.pos];
        ++lx.pos;
      }
      int64_t rhs;
      if (!lx.number(&rhs)) fail("expected integer right-hand side");
      if (op == "<=" || op == "=<" || op == "<")
        row.hi = rhs;
      else if (op == ">=" || op == "=>" || op == ">")
        row.lo = rhs;
      else if (op == "=")
        row.lo = row.hi = rhs;
      else
        fail("unknown comparison operator '" + op + "'");
      if (!lx.eat(';')) fail("expected ';' after constraint");
      m.rows.push_back(std::move(row));
    }
    if (!saw_objective) fail("no objective found");
  }
};

// --------------------------------------------------------------- solver --
//
// Exact DFS branch-and-bound over 0/1 variables with activity-bound
// propagation per row (lo <= activity <= hi). Branch order: descending
// |objective| (the move-minimization weights concentrate on few vars),
// preferred value first (1 for positive weight under max).

struct Solver {
  const Model &m;
  int n;
  std::vector<int8_t> val;      // -1 unfixed, 0/1 fixed
  std::vector<int64_t> act_lo;  // row activity given fixed vars
  std::vector<int64_t> act_hi;
  std::vector<std::vector<std::pair<int, int64_t>>> var_rows;  // var -> (row, coef)
  std::vector<int> order;
  std::vector<int64_t> pos_suffix;  // max extra objective from order[i:]
  // cover bound: every positive-weight var is claimed by its tightest
  // finite-capacity row; a group of claimed vars can add at most the sum
  // of its top-(hi - current ones) weights. For the reassignment family
  // this caps each partition's leader gain at one var (C5 rows, hi=1)
  // and each partition's total gain at RF vars (C4 rows) — orders of
  // magnitude tighter than the plain positive-weight suffix.
  std::vector<int> group_row;                // group -> row
  std::vector<std::vector<int>> group_vars;  // weight-sorted claimed vars
  std::vector<int> ungrouped;                // positive vars in no finite row
  int64_t cur_obj = 0;
  int64_t best_obj = -kInf;
  std::vector<int8_t> best;
  bool have_best = false;
  uint64_t nodes = 0;
  double timeout_s;
  Clock::time_point t0 = Clock::now();
  bool timed_out = false;

  explicit Solver(const Model &model, double timeout)
      : m(model), n((int)model.names.size()), val(n, -1),
        var_rows(n), timeout_s(timeout) {
    act_lo.assign(m.rows.size(), 0);
    act_hi.assign(m.rows.size(), 0);
    for (size_t r = 0; r < m.rows.size(); ++r)
      for (const Term &t : m.rows[r].terms) {
        var_rows[t.var].push_back({(int)r, t.coef});
        if (t.coef > 0)
          act_hi[r] += t.coef;
        else
          act_lo[r] += t.coef;
      }
    // branch order: all weighted vars first (descending |weight|) — the
    // cover bound can then prune the zero-weight tail wholesale. (A
    // complete-one-partition-block-at-a-time order was tried and is far
    // worse: it front-loads unweighted branching before the bound bites.)
    order.resize(n);
    for (int i = 0; i < n; ++i) order[i] = i;
    std::stable_sort(order.begin(), order.end(), [&](int a, int b) {
      return std::llabs(m.obj[a]) > std::llabs(m.obj[b]);
    });
    pos_suffix.assign(n + 1, 0);
    for (int i = n - 1; i >= 0; --i)
      pos_suffix[i] =
          pos_suffix[i + 1] + std::max<int64_t>(0, signed_obj(order[i]));

    std::unordered_map<int, int> row_to_group;
    for (int v = 0; v < n; ++v) {
      if (signed_obj(v) <= 0) continue;
      int best_r = -1;
      for (auto [r, c] : var_rows[v]) {
        if (c <= 0 || m.rows[r].hi >= kInf) continue;
        if (best_r == -1 || m.rows[r].hi < m.rows[best_r].hi) best_r = r;
      }
      if (best_r == -1) {
        ungrouped.push_back(v);
        continue;
      }
      auto [it, added] =
          row_to_group.emplace(best_r, (int)group_row.size());
      if (added) {
        group_row.push_back(best_r);
        group_vars.emplace_back();
      }
      group_vars[it->second].push_back(v);
    }
    for (auto &g : group_vars)
      std::sort(g.begin(), g.end(), [&](int a, int b) {
        return signed_obj(a) > signed_obj(b);
      });
  }

  // admissible overestimate of the objective still reachable from here
  int64_t bound_extra() const {
    int64_t extra = 0;
    for (size_t gi = 0; gi < group_row.size(); ++gi) {
      int r = group_row[gi];
      // coefficient-1 rows: act_lo is exactly the count of 1-fixed vars
      int64_t cap = m.rows[r].hi - act_lo[r];
      if (cap <= 0) continue;
      int64_t taken = 0;
      for (int v : group_vars[gi]) {
        if (taken >= cap) break;
        if (val[v] == -1) {
          extra += signed_obj(v);
          ++taken;
        }
      }
    }
    for (int v : ungrouped)
      if (val[v] == -1) extra += signed_obj(v);
    return extra;
  }

  // objective in "maximize" orientation
  int64_t signed_obj(int v) const { return m.maximize ? m.obj[v] : -m.obj[v]; }

  bool out_of_time() {
    if (timeout_s <= 0) return false;
    if ((nodes & 1023) == 0) {
      double el = std::chrono::duration<double>(Clock::now() - t0).count();
      if (el > timeout_s) timed_out = true;
    }
    return timed_out;
  }

  struct Trail {
    std::vector<int> fixed;  // vars fixed during this node (for undo)
  };

  // fix var to v, update activities; false on row violation. ALWAYS
  // applies every row update before reporting a violation — undo()
  // reverses all of them, so a partial update would corrupt activities.
  bool assign(int var, int8_t v, Trail &tr, std::vector<int> &dirty) {
    val[var] = v;
    tr.fixed.push_back(var);
    cur_obj += v ? signed_obj(var) : 0;
    bool ok = true;
    for (auto [r, c] : var_rows[var]) {
      // removing the unfixed contribution, adding the fixed one
      if (c > 0) {
        if (v)
          act_lo[r] += c;
        else
          act_hi[r] -= c;
      } else {
        if (v)
          act_hi[r] += c;
        else
          act_lo[r] -= c;
      }
      if (act_lo[r] > m.rows[r].hi || act_hi[r] < m.rows[r].lo) {
        if (ok) fail_row = r;  // first culprit: restart weighting
        ok = false;
      }
      dirty.push_back(r);
    }
    return ok;
  }

  // conflict weighting (dom/wdeg-lite): rows that keep killing dives
  // rise to the front of later restarts' demand order
  int fail_row = -1;
  std::vector<uint64_t> row_weight;

  void bump_fail_row() {
    if (fail_row < 0) return;
    if (row_weight.size() != m.rows.size())
      row_weight.assign(m.rows.size(), 0);
    ++row_weight[fail_row];
    fail_row = -1;
  }

  void undo(Trail &tr) {
    for (auto it = tr.fixed.rbegin(); it != tr.fixed.rend(); ++it) {
      int var = *it;
      int8_t v = val[var];
      cur_obj -= v ? signed_obj(var) : 0;
      for (auto [r, c] : var_rows[var]) {
        if (c > 0) {
          if (v)
            act_lo[r] -= c;
          else
            act_hi[r] += c;
        } else {
          if (v)
            act_hi[r] -= c;
          else
            act_lo[r] += c;
        }
      }
      val[var] = -1;
    }
    tr.fixed.clear();
  }

  // unit-style propagation over a worklist of dirty rows: a row whose
  // slack forces a remaining var to one value fixes it and enqueues that
  // var's rows in turn. Coefficient-1 rows (this model family) are
  // handled exactly; general coefs use the same activity-bound logic.
  // reused across propagate() calls (twice per node on the hot path):
  // generation-stamped dedup instead of an O(rows) memset per call
  std::vector<uint32_t> queued_gen_;
  uint32_t gen_ = 0;
  std::vector<int> dirty_buf_;

  bool propagate(Trail &tr, std::vector<int> &work) {
    if (queued_gen_.size() != m.rows.size())
      queued_gen_.assign(m.rows.size(), 0);
    ++gen_;
    auto queued = [&](int r) { return queued_gen_[r] == gen_; };
    auto mark = [&](int r) { queued_gen_[r] = gen_; };
    for (int r : work) mark(r);
    std::vector<int> &dirty = dirty_buf_;
    while (!work.empty()) {
      int r = work.back();
      work.pop_back();
      queued_gen_[r] = gen_ - 1;  // unmark
      const Row &row = m.rows[r];
      for (const Term &t : row.terms) {
        if (val[t.var] != -1) continue;
        // forcing test: would fixing this var to 1 (resp. 0) make the
        // row's reachable activity interval miss [lo, hi]? (act_lo
        // already counts negative coefs of unfixed vars, act_hi the
        // positive ones)
        int64_t c = t.coef, lo1, hi1, lo0, hi0;
        if (c > 0) {
          lo1 = act_lo[r] + c; hi1 = act_hi[r];
          lo0 = act_lo[r];     hi0 = act_hi[r] - c;
        } else {
          lo1 = act_lo[r];     hi1 = act_hi[r] + c;
          lo0 = act_lo[r] - c; hi0 = act_hi[r];
        }
        int8_t force = -1;
        if (lo1 > row.hi || hi1 < row.lo) force = 0;       // can't be 1
        else if (lo0 > row.hi || hi0 < row.lo) force = 1;  // can't be 0
        if (force != -1) {
          dirty.clear();
          if (!assign(t.var, force, tr, dirty)) return false;
          for (int d : dirty)
            if (!queued(d)) {
              mark(d);
              work.push_back(d);
            }
        }
      }
    }
    return true;
  }

  int next_unfixed(int from) const {
    while (from < n && val[order[from]] != -1) ++from;
    return from;
  }

  // Phase-1 search controls: stop at the first feasible leaf, and cap
  // the node budget so a hopeless dive hands over to the exact phase.
  bool first_feasible_only = false;
  bool phase_aborted = false;
  uint64_t node_cap = 0;
  std::vector<int> feas_rows;  // demand rows (lo > 0), variant order
  uint64_t rng_state = 1;

  uint64_t rnd() {  // splitmix64: deterministic per-variant stream
    uint64_t z = (rng_state += 0x9E3779B97F4A7C15ull);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    return z ^ (z >> 31);
  }

  void recompute_suffix() {
    for (int i = n - 1; i >= 0; --i)
      pos_suffix[i] =
          pos_suffix[i + 1] + std::max<int64_t>(0, signed_obj(order[i]));
  }

  // Feasibility-first variable order: complete one demand row (lo > 0
  // — the RF / one-leader equalities of this model family) at a time.
  // Propagation then keeps each dive's backtracking local to a
  // partition block. The objective-major order is the right one for
  // PRUNING but can thrash for hours on tight capacity bands before
  // reaching ANY feasible leaf (fuzz-found: RF=4 clusters with
  // 1-broker racks gave rc=7 at 120 s while the incumbent-seeded
  // search proves optimality in milliseconds).
  //
  // A single row order is not enough on extreme exact-band instances
  // (perfect-packing feasibility problems the generator produces):
  // whichever fixed order is chosen, some instance packs the early
  // rows in a way no completion can finish, and chronological
  // backtracking cannot climb back out within any node budget. run()
  // therefore tries a LADDER of orders until one lands an incumbent:
  //   variant 0: demand rows in file order (fast common case)
  //   variant 1: tightest band first (hi-lo asc; exact rows lead)
  //   variant 2: widest demand first (reverse of 1)
  //   variant 3+: deterministic shuffles (splitmix64-seeded)
  void use_feasibility_order(int variant = 0) {
    std::vector<int> rows_idx;
    for (size_t r = 0; r < m.rows.size(); ++r)
      if (m.rows[r].lo > 0) rows_idx.push_back((int)r);
    if (variant == -1 && !row_weight.empty()) {
      // conflict-weighted: the rows that killed previous dives lead
      std::stable_sort(rows_idx.begin(), rows_idx.end(),
                       [&](int a, int b) {
                         if (row_weight[a] != row_weight[b])
                           return row_weight[a] > row_weight[b];
                         return (m.rows[a].hi - m.rows[a].lo) <
                                (m.rows[b].hi - m.rows[b].lo);
                       });
    } else if (variant == 1 || variant == 2) {
      std::stable_sort(rows_idx.begin(), rows_idx.end(),
                       [&](int a, int b) {
                         int64_t sa = m.rows[a].hi - m.rows[a].lo;
                         int64_t sb = m.rows[b].hi - m.rows[b].lo;
                         if (sa != sb)
                           return variant == 1 ? sa < sb : sa > sb;
                         return a < b;
                       });
    } else if (variant >= 3) {
      // rng_state is seeded per variant by run(): the shuffle stream
      // is deterministic and distinct per restart
      for (size_t i = rows_idx.size(); i > 1; --i)
        std::swap(rows_idx[i - 1], rows_idx[rnd() % i]);
    }
    std::vector<int> neworder;
    neworder.reserve(n);
    std::vector<uint8_t> seen(n, 0);
    for (int r : rows_idx)
      for (const Term &t : m.rows[r].terms)
        if (!seen[t.var]) {
          seen[t.var] = 1;
          neworder.push_back(t.var);
        }
    for (int v = 0; v < n; ++v)
      if (!seen[v]) neworder.push_back(v);
    order = std::move(neworder);
    feas_rows = std::move(rows_idx);
    recompute_suffix();
  }

  // Dynamic least-constraining dive: the fixed-order dives above pack
  // early demand rows greedily and chronological backtracking cannot
  // climb out of a bad early packing — tiny (300-var) exact-band
  // instances timed out down EVERY fixed order (fuzz round 4). This
  // dive instead walks the demand rows and, inside the first
  // unsatisfied one, sets the variable whose tightest remaining
  // capacity row has the MOST slack (least-constraining value,
  // randomized tie-break per variant). Once every demand row is met,
  // remaining variables zero-fill under propagation.
  int pick_feas_var() {
    for (int r : feas_rows) {
      if (act_lo[r] >= m.rows[r].lo) continue;
      int best = -1;
      uint64_t best_key = 0;
      for (const Term &t : m.rows[r].terms) {
        if (t.coef <= 0 || val[t.var] != -1) continue;
        int64_t slack = kInf;
        for (auto [r2, c2] : var_rows[t.var]) {
          if (c2 <= 0 || m.rows[r2].hi >= kInf) continue;
          slack = std::min(slack, m.rows[r2].hi - act_lo[r2]);
        }
        if (slack > (int64_t)1e6) slack = (int64_t)1e6;
        if (slack < 0) slack = 0;
        uint64_t key = ((uint64_t)slack << 4) | (rnd() & 15);
        if (best == -1 || key > best_key) {
          best = t.var;
          best_key = key;
        }
      }
      if (best != -1) return best;
    }
    return -1;  // every demand row satisfied
  }

  int dive_depth = 0;
  // stack guard: one frame per assigned variable, each holding a Trail
  // and a dirty vector — tens of thousands of frames approach the
  // default 8 MB stack. Abort the phase (the exact dfs takes over)
  // instead of letting a huge aggregated instance kill the process.
  static constexpr int kMaxDiveDepth = 20000;

  void dive() {
    if (out_of_time() || have_best) return;
    if (node_cap && nodes >= node_cap) {
      phase_aborted = true;
      return;
    }
    if (dive_depth >= kMaxDiveDepth) {
      phase_aborted = true;
      return;
    }
    ++nodes;
    int var = pick_feas_var();
    if (var == -1) {
      // demands met: zero-fill the rest (propagation may force 1s
      // for remaining lower bands; any violation unwinds the fill)
      Trail tr;
      bool ok = true;
      for (int v = 0; v < n && ok; ++v) {
        if (val[v] != -1) continue;
        std::vector<int> dirty;
        ok = assign(v, 0, tr, dirty) && propagate(tr, dirty);
      }
      if (ok)
        record_if_better();
      else
        bump_fail_row();
      undo(tr);
      return;
    }
    for (int8_t v : {(int8_t)1, (int8_t)0}) {
      Trail tr;
      std::vector<int> dirty;
      if (assign(var, v, tr, dirty) && propagate(tr, dirty)) {
        ++dive_depth;
        dive();
        --dive_depth;
      } else {
        bump_fail_row();
      }
      undo(tr);
      if (timed_out || phase_aborted || have_best) return;
    }
  }

  void record_if_better() {
    if (cur_obj > best_obj) {
      best_obj = cur_obj;
      best.assign(val.begin(), val.end());
      have_best = true;
    }
  }

  void dfs(int depth) {
    if (out_of_time()) return;
    if (first_feasible_only && have_best) return;
    if (node_cap && nodes >= node_cap) {
      phase_aborted = true;
      return;
    }
    ++nodes;
    // bound: cheap suffix first, then the row-capacity cover bound
    if (have_best && cur_obj + pos_suffix[depth] <= best_obj) return;
    if (have_best && cur_obj + bound_extra() <= best_obj) return;
    int i = next_unfixed(depth);
    if (i >= n) {
      record_if_better();
      return;
    }
    int var = order[i];
    // prefer keeping weighted (currently-assigned) vars and LEAVING OUT
    // unweighted ones — flooding zero-weight vars with 1s only violates
    // capacity bands and thrashes the feasibility search. In the
    // feasibility phase the preference is demand-driven instead: a var
    // that can still lift an unsatisfied >=-row (a leader/replica
    // lower band) goes in — without this, lower-band violations
    // surface only at the bottom of the dive, where chronological
    // backtracking cannot escape them (fuzz-found: exact rack bands +
    // per-broker leader floors).
    int8_t pref = signed_obj(var) > 0 ? 1 : 0;
    if (first_feasible_only && pref == 0) {
      for (auto [r, c] : var_rows[var])
        if (c > 0 && act_lo[r] < m.rows[r].lo) {
          pref = 1;
          break;
        }
    }
    for (int8_t v : {pref, (int8_t)(1 - pref)}) {
      Trail tr;
      std::vector<int> dirty;
      if (assign(var, v, tr, dirty) && propagate(tr, dirty)) dfs(i + 1);
      undo(tr);
      if (timed_out || phase_aborted ||
          (first_feasible_only && have_best))
        return;
    }
  }

  // returns lp_solve-style exit code
  int run() {
    Trail root;
    std::vector<int> all(m.rows.size());
    for (size_t r = 0; r < m.rows.size(); ++r) all[r] = (int)r;
    if (!propagate(root, all)) return 2;  // infeasible at the root
    // phase 1: feasibility dives to seed an incumbent (node-capped;
    // root-propagation fixes persist, each dive's trail unwinds
    // fully). A ladder of row orders runs until one lands a feasible
    // leaf — a single fixed order leaves rc=7 holes on exact-band
    // perfect-packing instances (see use_feasibility_order). Phase 2
    // re-proves/improves the incumbent exactly, so a failed dive
    // costs nothing but its node budget.
    const std::vector<int> obj_order = order;
    first_feasible_only = true;
    for (int variant = 0; variant < 24 && !have_best && !out_of_time();
         ++variant) {
      phase_aborted = false;
      rng_state = 0x9E3779B97F4A7C15ull * (uint64_t)(variant + 1);
      if (variant < 2) {
        // fixed-order dives: instant on the common case
        use_feasibility_order(variant);
        node_cap = nodes + (variant == 0 ? 1000000 : 200000);
        dfs(0);
      } else {
        // dynamic least-constraining dives over varied row orders —
        // tightest-band-first (exact rack totals lead), widest,
        // shuffles, alternating with conflict-weighted restarts
        // (rows that killed earlier dives lead); small caps with many
        // restarts beat one deep dive on perfect-packing instances
        use_feasibility_order(
            variant >= 4 && variant % 2 == 0 ? -1 : variant - 1
        );
        node_cap = nodes + 200000;
        dive();
      }
    }
    first_feasible_only = false;
    phase_aborted = false;
    node_cap = 0;
    order = obj_order;
    recompute_suffix();
    // phase 2: exact objective-major branch-and-bound
    if (!timed_out) dfs(0);
    if (!have_best) return timed_out ? 7 : 2;  // 7: no incumbent in time
    return timed_out ? 1 : 0;
  }
};

}  // namespace

int main(int argc, char **argv) {
  std::string path;
  double timeout = 0;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    if (a == "-timeout" && i + 1 < argc) {
      timeout = std::atof(argv[++i]);
    } else if (!a.empty() && a[0] == '-' && a != "-") {
      // -S4 etc: verbosity flags accepted and ignored
    } else {
      path = a;
    }
  }
  if (path.empty()) {
    std::fprintf(stderr, "usage: lp_cli [-S4] [-timeout sec] model.lp\n");
    return 255;
  }
  std::string text;
  if (path == "-") {
    std::ostringstream ss;
    ss << std::cin.rdbuf();
    text = ss.str();
  } else {
    std::ifstream f(path);
    if (!f) {
      std::fprintf(stderr, "lp_cli: cannot open %s\n", path.c_str());
      return 255;
    }
    std::ostringstream ss;
    ss << f.rdbuf();
    text = ss.str();
  }

  Parser parser;
  parser.parse(text);
  Solver solver(parser.m, timeout);
  int rc = solver.run();
  if (rc == 2) {
    std::printf("\nThis problem is infeasible\n");
    return 2;
  }
  if (rc == 7) {
    std::printf("\nTimeout before any integer solution was found\n");
    return 7;
  }
  // lp_solve -S4 output layout (the adapter's parser reads the
  // name/value pairs; the objective line matches lp_solve's phrasing)
  int64_t printed_obj =
      parser.m.maximize ? solver.best_obj : -solver.best_obj;
  std::printf("\nValue of objective function: %lld\n\n",
              (long long)printed_obj);
  std::printf("Actual values of the variables:\n");
  for (int v = 0; v < (int)parser.m.names.size(); ++v)
    std::printf("%-24s%15d\n", parser.m.names[v].c_str(),
                (int)solver.best[v]);
  return rc;
}
