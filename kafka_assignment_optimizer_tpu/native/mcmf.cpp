// Min-cost max-flow (primal-dual / successive shortest paths with
// Johnson potentials) with a plain C ABI for ctypes binding.
//
// Native runtime component of the TPU build (the reference's only native
// piece is the external lp_solve C solver it shells out to,
// /root/reference/README.md:135-137). Used by the plan constructor
// (solvers/lp_round.py) for LEADER-AWARE completion: placing new
// replicas is a transportation problem, and partitions left without a
// kept leader must receive one of their new replicas on a broker with
// leadership headroom — encoded as negative-cost arcs, so the min-cost
// max-flow simultaneously (a) places every vacancy and (b) maximizes
// the number of lead-capable placements. Two sequential max-flows
// cannot do this: the first stage's blind choices strand the second
// (observed: 3 of 197 vacancies unplaceable on the 50k-partition jumbo
// instance).
//
// Algorithm: ONE initial SPFA pass absorbs the negative input costs
// into node potentials (and carries the defensive negative-cycle
// guard); every subsequent augmentation runs Dijkstra on the reduced
// costs (cost + pi[u] - pi[v] >= 0, the standard primal-dual
// invariant — reverse arcs created by an augmentation have reduced
// cost exactly 0, and nodes unreachable from s stay unreachable, so
// their stale potentials are never read from a settled node).
// SPFA-per-augmentation was the previous implementation; with ~300
// negative-cost augmentations over ~1.7e5 arcs its requeue-heavy
// passes cost 2.6 s of the 50k-partition jumbo's constructor wall
// (measured r4) — the heap-based reruns settle each node once.

#include <algorithm>
#include <cstddef>
#include <cstdint>
#include <queue>
#include <utility>
#include <vector>

namespace {

struct Arc {
    int32_t to;      // head node
    int32_t next;    // next arc out of the same tail (linked list)
    int32_t cap;     // residual capacity
    int32_t cost;    // per-unit cost
};

struct Graph {
    std::vector<Arc> arcs;        // paired: arc i ^ 1 is the reverse
    std::vector<int32_t> head;    // head[v] = first arc index of v, -1 end

    explicit Graph(int n) : head(n, -1) {}

    void add(int32_t u, int32_t v, int32_t cap, int32_t cost) {
        arcs.push_back({v, head[u], cap, cost});
        head[u] = static_cast<int32_t>(arcs.size()) - 1;
        arcs.push_back({u, head[v], 0, -cost});
        head[v] = static_cast<int32_t>(arcs.size()) - 1;
    }
};

}  // namespace

extern "C" {

// Computes min-cost max-flow from s to t.
//
//   n_nodes, n_arcs: graph size; arcs given as parallel arrays
//   src/dst/cap/cost (int32). s, t: terminal node ids.
//   out_arc_flow[i]: flow pushed on input arc i (int32).
//   out_flow/out_cost: totals (int64).
//
// Returns 0 on success, -1 on invalid input, -2 when a negative-cost
// cycle is reachable in the residual graph (successive shortest paths
// is undefined there; the caller's networks are DAG-layered so this is
// purely a defensive guard — without it SPFA never settles and the
// queue grows until the process aborts).
int kao_mcmf(int32_t n_nodes, int32_t n_arcs,
             const int32_t* src, const int32_t* dst,
             const int32_t* cap, const int32_t* cost,
             int32_t s, int32_t t,
             int32_t* out_arc_flow,
             int64_t* out_flow, int64_t* out_cost) {
    if (n_nodes <= 0 || n_arcs < 0 || s < 0 || s >= n_nodes || t < 0 ||
        t >= n_nodes || s == t) {
        return -1;
    }
    Graph g(n_nodes);
    g.arcs.reserve(static_cast<size_t>(n_arcs) * 2);
    for (int32_t i = 0; i < n_arcs; ++i) {
        if (src[i] < 0 || src[i] >= n_nodes || dst[i] < 0 ||
            dst[i] >= n_nodes || cap[i] < 0) {
            return -1;
        }
        g.add(src[i], dst[i], cap[i], cost[i]);
    }

    const int64_t INF = INT64_C(0x3fffffffffffffff);
    std::vector<int64_t> dist(n_nodes);
    std::vector<int64_t> pi(n_nodes, 0);  // Johnson potentials
    // initial SPFA: absorbs the negative input costs into pi and keeps
    // the defensive negative-cycle guard (the caller's networks are
    // DAG-layered, so the guard should never fire)
    {
        std::vector<uint8_t> in_queue(n_nodes, 0);
        std::vector<int32_t> enq(n_nodes, 0);
        std::vector<int32_t> queue;
        queue.reserve(n_nodes);
        std::fill(dist.begin(), dist.end(), INF);
        dist[s] = 0;
        queue.push_back(s);
        in_queue[s] = 1;
        for (size_t qi = 0; qi < queue.size(); ++qi) {
            int32_t u = queue[qi];
            in_queue[u] = 0;
            for (int32_t e = g.head[u]; e != -1; e = g.arcs[e].next) {
                const Arc& a = g.arcs[e];
                if (a.cap <= 0) continue;
                int64_t nd = dist[u] + a.cost;
                if (nd < dist[a.to]) {
                    dist[a.to] = nd;
                    if (!in_queue[a.to]) {
                        // a node settling > n_nodes times means a
                        // negative cycle is relaxing forever
                        if (++enq[a.to] > n_nodes) return -2;
                        queue.push_back(a.to);
                        in_queue[a.to] = 1;
                    }
                }
            }
        }
        for (int32_t v = 0; v < n_nodes; ++v) {
            if (dist[v] < INF) pi[v] = dist[v];
        }
    }

    using HeapItem = std::pair<int64_t, int32_t>;  // (dist, node)
    std::priority_queue<HeapItem, std::vector<HeapItem>,
                        std::greater<HeapItem>> heap;
    std::vector<uint8_t> reached(n_nodes);
    std::vector<uint8_t> dead(n_nodes);     // DFS-retreated this round
    std::vector<uint8_t> onpath(n_nodes);   // on the current DFS stack
    std::vector<int32_t> cur(n_nodes);      // current-arc pointers
    std::vector<int32_t> path_arc;          // DFS stack (arc into node)
    path_arc.reserve(n_nodes);

    int64_t total_flow = 0, total_cost = 0;
    for (;;) {
        // full Dijkstra on reduced costs (lazy-deletion heap): settle
        // every reachable node — the whole zero-reduced-cost DAG is
        // needed below, so there is no early exit at t
        std::fill(dist.begin(), dist.end(), INF);
        std::fill(reached.begin(), reached.end(), 0);
        dist[s] = 0;
        heap = {};
        heap.push({0, s});
        while (!heap.empty()) {
            auto [du, u] = heap.top();
            heap.pop();
            if (reached[u]) continue;
            reached[u] = 1;
            for (int32_t e = g.head[u]; e != -1; e = g.arcs[e].next) {
                const Arc& a = g.arcs[e];
                if (a.cap <= 0 || reached[a.to]) continue;
                int64_t nd = du + a.cost + pi[u] - pi[a.to];
                if (nd < dist[a.to]) {
                    dist[a.to] = nd;
                    heap.push({nd, a.to});
                }
            }
        }
        if (!reached[t]) break;  // no augmenting path left
        // fold the distances into the potentials; unreachable nodes
        // keep their stale pi (they stay unreachable in later rounds —
        // augmentations never add residual capacity out of the
        // reachable set — so no settled node ever reads them)
        for (int32_t v = 0; v < n_nodes; ++v) {
            if (reached[v]) pi[v] += dist[v];
        }
        // blocking flow over the admissible arcs (cap > 0 and reduced
        // cost 0 under the updated pi): every augmenting path through
        // them costs exactly pi[t] - pi[s] = pi[t], so the costs of
        // {0, -1, -1000} collapse the run into a handful of Dijkstra
        // rounds — one per DISTINCT path cost — instead of one per
        // augmentation (measured r4: 2.6 s -> the SPFA floor of ~0.2 s
        // on the 50k-partition jumbo completion). DFS with current-arc
        // pointers; a zero-cost cycle cannot trap it because retreat
        // marks the node dead for the rest of the round.
        const int64_t round_cost = pi[t];
        std::copy(g.head.begin(), g.head.end(), cur.begin());
        std::fill(dead.begin(), dead.end(), 0);
        std::fill(onpath.begin(), onpath.end(), 0);
        for (;;) {
            // one DFS descent from s with persistent arc pointers; the
            // onpath guard keeps zero-cost cycles (admissible reverse
            // arcs) from revisiting the stack
            path_arc.clear();
            int32_t v = s;
            onpath[s] = 1;
            bool found = false;
            for (;;) {
                if (v == t) {
                    found = true;
                    break;
                }
                int32_t e = cur[v];
                for (; e != -1; e = g.arcs[e].next) {
                    const Arc& a = g.arcs[e];
                    if (a.cap <= 0 || dead[a.to] || onpath[a.to] ||
                        !reached[a.to]) {
                        continue;
                    }
                    if (a.cost + pi[v] - pi[a.to] != 0) continue;
                    break;
                }
                cur[v] = e;
                if (e == -1) {
                    // no admissible way forward: retreat
                    onpath[v] = 0;
                    if (v == s) break;  // blocking flow complete
                    dead[v] = 1;
                    v = g.arcs[path_arc.back() ^ 1].to;
                    path_arc.pop_back();
                } else {
                    path_arc.push_back(e);
                    v = g.arcs[e].to;
                    onpath[v] = 1;
                }
            }
            if (!found) break;
            int32_t push = INT32_MAX;
            for (int32_t e : path_arc) {
                push = std::min(push, g.arcs[e].cap);
            }
            for (int32_t e : path_arc) {
                g.arcs[e].cap -= push;
                g.arcs[e ^ 1].cap += push;
            }
            total_flow += push;
            total_cost += static_cast<int64_t>(push) * round_cost;
            // next descent restarts from s with the SAME cur pointers:
            // exhausted arcs stay skipped, saturated arcs fail the cap
            // check and advance their tail's pointer. Clear the path
            // markers (the onpath guard is per-descent).
            onpath[s] = 0;
            for (int32_t e : path_arc) onpath[g.arcs[e].to] = 0;
        }
    }

    for (int32_t i = 0; i < n_arcs; ++i) {
        // forward arc 2i: flow = reverse residual
        out_arc_flow[i] = g.arcs[2 * i + 1].cap;
    }
    *out_flow = total_flow;
    *out_cost = total_cost;
    return 0;
}

}  // extern "C"
