// Min-cost max-flow (successive shortest augmenting paths, SPFA) with a
// plain C ABI for ctypes binding.
//
// Native runtime component of the TPU build (the reference's only native
// piece is the external lp_solve C solver it shells out to,
// /root/reference/README.md:135-137). Used by the plan constructor
// (solvers/lp_round.py) for LEADER-AWARE completion: placing new
// replicas is a transportation problem, and partitions left without a
// kept leader must receive one of their new replicas on a broker with
// leadership headroom — encoded as negative-cost arcs, so the min-cost
// max-flow simultaneously (a) places every vacancy and (b) maximizes
// the number of lead-capable placements. Two sequential max-flows
// cannot do this: the first stage's blind choices strand the second
// (observed: 3 of 197 vacancies unplaceable on the 50k-partition jumbo
// instance).
//
// Algorithm: Bellman-Ford/SPFA-based successive shortest paths on the
// residual graph, augmenting by bottleneck capacity. Handles negative
// arc costs (no negative cycles by construction: every negative-cost
// arc leaves a source-side node of a DAG-layered network). Complexity
// O(F * E) worst case with F = total flow — completions move a few
// hundred units over ~1e5 arcs, far under a millisecond-budget.

#include <algorithm>
#include <cstddef>
#include <cstdint>
#include <vector>

namespace {

struct Arc {
    int32_t to;      // head node
    int32_t next;    // next arc out of the same tail (linked list)
    int32_t cap;     // residual capacity
    int32_t cost;    // per-unit cost
};

struct Graph {
    std::vector<Arc> arcs;        // paired: arc i ^ 1 is the reverse
    std::vector<int32_t> head;    // head[v] = first arc index of v, -1 end

    explicit Graph(int n) : head(n, -1) {}

    void add(int32_t u, int32_t v, int32_t cap, int32_t cost) {
        arcs.push_back({v, head[u], cap, cost});
        head[u] = static_cast<int32_t>(arcs.size()) - 1;
        arcs.push_back({u, head[v], 0, -cost});
        head[v] = static_cast<int32_t>(arcs.size()) - 1;
    }
};

}  // namespace

extern "C" {

// Computes min-cost max-flow from s to t.
//
//   n_nodes, n_arcs: graph size; arcs given as parallel arrays
//   src/dst/cap/cost (int32). s, t: terminal node ids.
//   out_arc_flow[i]: flow pushed on input arc i (int32).
//   out_flow/out_cost: totals (int64).
//
// Returns 0 on success, -1 on invalid input, -2 when a negative-cost
// cycle is reachable in the residual graph (successive shortest paths
// is undefined there; the caller's networks are DAG-layered so this is
// purely a defensive guard — without it SPFA never settles and the
// queue grows until the process aborts).
int kao_mcmf(int32_t n_nodes, int32_t n_arcs,
             const int32_t* src, const int32_t* dst,
             const int32_t* cap, const int32_t* cost,
             int32_t s, int32_t t,
             int32_t* out_arc_flow,
             int64_t* out_flow, int64_t* out_cost) {
    if (n_nodes <= 0 || n_arcs < 0 || s < 0 || s >= n_nodes || t < 0 ||
        t >= n_nodes || s == t) {
        return -1;
    }
    Graph g(n_nodes);
    g.arcs.reserve(static_cast<size_t>(n_arcs) * 2);
    for (int32_t i = 0; i < n_arcs; ++i) {
        if (src[i] < 0 || src[i] >= n_nodes || dst[i] < 0 ||
            dst[i] >= n_nodes || cap[i] < 0) {
            return -1;
        }
        g.add(src[i], dst[i], cap[i], cost[i]);
    }

    const int64_t INF = INT64_C(0x3fffffffffffffff);
    std::vector<int64_t> dist(n_nodes);
    std::vector<int32_t> in_arc(n_nodes);
    std::vector<uint8_t> in_queue(n_nodes);
    std::vector<int32_t> enq(n_nodes);
    std::vector<int32_t> queue;
    queue.reserve(n_nodes);

    int64_t total_flow = 0, total_cost = 0;
    for (;;) {
        // SPFA shortest path s -> t on the residual graph
        std::fill(dist.begin(), dist.end(), INF);
        std::fill(in_queue.begin(), in_queue.end(), 0);
        std::fill(enq.begin(), enq.end(), 0);
        dist[s] = 0;
        queue.clear();
        queue.push_back(s);
        in_queue[s] = 1;
        for (size_t qi = 0; qi < queue.size(); ++qi) {
            int32_t u = queue[qi];
            in_queue[u] = 0;
            for (int32_t e = g.head[u]; e != -1; e = g.arcs[e].next) {
                const Arc& a = g.arcs[e];
                if (a.cap <= 0) continue;
                int64_t nd = dist[u] + a.cost;
                if (nd < dist[a.to]) {
                    dist[a.to] = nd;
                    in_arc[a.to] = e;
                    if (!in_queue[a.to]) {
                        // a node settling > n_nodes times means a
                        // negative cycle is relaxing forever
                        if (++enq[a.to] > n_nodes) return -2;
                        queue.push_back(a.to);
                        in_queue[a.to] = 1;
                    }
                }
            }
        }
        if (dist[t] >= INF) break;  // no augmenting path left
        // bottleneck along the path
        int32_t push = INT32_MAX;
        for (int32_t v = t; v != s; v = g.arcs[in_arc[v] ^ 1].to) {
            push = std::min(push, g.arcs[in_arc[v]].cap);
        }
        for (int32_t v = t; v != s; v = g.arcs[in_arc[v] ^ 1].to) {
            g.arcs[in_arc[v]].cap -= push;
            g.arcs[in_arc[v] ^ 1].cap += push;
        }
        total_flow += push;
        total_cost += static_cast<int64_t>(push) * dist[t];
    }

    for (int32_t i = 0; i < n_arcs; ++i) {
        // forward arc 2i: flow = reverse residual
        out_arc_flow[i] = g.arcs[2 * i + 1].cap;
    }
    *out_flow = total_flow;
    *out_cost = total_cost;
    return 0;
}

}  // extern "C"
