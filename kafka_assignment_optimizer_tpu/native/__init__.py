"""Native (C++) runtime components and their build/loading machinery.

The reference's native component is the external lp_solve 5.5 C solver it
shells out to (``/root/reference/README.md:135-137``). This package bundles
the equivalent *in-process*: ``bb.cpp`` — a specialized exact
branch-and-bound for the reassignment model — compiled on first use with
the system ``g++`` into a cached shared library and bound via ctypes
(no pybind11 dependency).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from pathlib import Path

_SRC = Path(__file__).with_name("bb.cpp")


def _build_dir() -> Path:
    d = Path(__file__).with_name("_build")
    d.mkdir(exist_ok=True)
    return d


def lib_path() -> Path:
    """Content-addressed artifact path: a source edit changes the hash, so
    stale libraries are never loaded and parallel builds never collide."""
    digest = hashlib.sha256(_SRC.read_bytes()).hexdigest()[:16]
    return _build_dir() / f"libkao_{digest}.so"


def _compile(src: Path, out: Path, extra_flags: list[str],
             verbose: bool = False) -> Path:
    """Compile ``src`` to ``out`` with g++ if not already present:
    content-addressed artifact names make staleness impossible, a
    tempdir + ``os.replace`` makes concurrent builds publish atomically."""
    if out.exists():
        return out
    with tempfile.TemporaryDirectory(dir=_build_dir()) as td:
        tmp = Path(td) / out.name
        cmd = [
            "g++", "-std=c++17", "-Wall", "-Wextra", *extra_flags,
            str(src), "-o", str(tmp),
        ]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native build failed:\n{' '.join(cmd)}\n{proc.stderr}"
            )
        if verbose and proc.stderr:
            from ..obs import log as _olog

            _olog.warn("native_build_warnings", stderr=proc.stderr)
        os.replace(tmp, out)  # atomic publish
    return out


def build(verbose: bool = False) -> Path:
    return _compile(_SRC, lib_path(), ["-O3", "-shared", "-fPIC"], verbose)


_LIB: ctypes.CDLL | None = None


def load() -> ctypes.CDLL:
    global _LIB
    if _LIB is None:
        lib = ctypes.CDLL(str(build()))
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.kao_solve.restype = ctypes.c_int
        lib.kao_solve.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,  # P B K R
            i32p, i32p, i32p, i32p,  # rf rack_of w_leader w_follower
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,  # bands
            i32p, i32p, i32p,  # rack_lo rack_hi part_rack_hi
            i32p, ctypes.c_int64, ctypes.c_int,  # seed_a seed_w has_seed
            ctypes.c_double,  # time limit
            i32p, i64p, i64p,  # out_a out_objective out_nodes
        ]
        _LIB = lib
    return _LIB


# ---------------------------------------------------------------------------
# min-cost max-flow kernel (mcmf.cpp) — leader-aware plan completion

_MCMF_SRC = Path(__file__).with_name("mcmf.cpp")


def mcmf_lib_path() -> Path:
    digest = hashlib.sha256(_MCMF_SRC.read_bytes()).hexdigest()[:16]
    return _build_dir() / f"libkao_mcmf_{digest}.so"


_MCMF_LIB: ctypes.CDLL | None = None


def load_mcmf() -> ctypes.CDLL:
    global _MCMF_LIB
    if _MCMF_LIB is None:
        path = _compile(_MCMF_SRC, mcmf_lib_path(),
                        ["-O3", "-shared", "-fPIC"])
        lib = ctypes.CDLL(str(path))
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.kao_mcmf.restype = ctypes.c_int
        lib.kao_mcmf.argtypes = [
            ctypes.c_int32, ctypes.c_int32,  # n_nodes n_arcs
            i32p, i32p, i32p, i32p,          # src dst cap cost
            ctypes.c_int32, ctypes.c_int32,  # s t
            i32p, i64p, i64p,                # out_arc_flow out_flow out_cost
        ]
        _MCMF_LIB = lib
    return _MCMF_LIB


def mcmf(src, dst, cap, cost, s: int, t: int, n_nodes: int):
    """Min-cost max-flow via the native kernel. Returns
    (total_flow, total_cost, per_arc_flow) or raises RuntimeError —
    rc=-1 for malformed input, rc=-2 when a negative-cost cycle is
    reachable (outside the successive-shortest-paths contract; the
    completion networks are DAG-layered so this never fires there)."""
    import numpy as np

    # range-check BEFORE the int32 cast: np.ascontiguousarray wraps
    # silently, and a wrapped cost would make a caller's bound
    # arithmetic (computed python-side with the unwrapped value)
    # quietly unsound — the callers all catch and fall back to an
    # exact LP, so raising here is the safe failure
    for name, arr in (("cap", np.asarray(cap)), ("cost", np.asarray(cost))):
        if arr.size and (
            int(arr.max(initial=0)) > np.iinfo(np.int32).max
            or int(arr.min(initial=0)) < np.iinfo(np.int32).min
        ):
            raise ValueError(f"{name} exceeds the kernel's int32 range")
    src = np.ascontiguousarray(src, dtype=np.int32)
    dst = np.ascontiguousarray(dst, dtype=np.int32)
    cap = np.ascontiguousarray(cap, dtype=np.int32)
    cost = np.ascontiguousarray(cost, dtype=np.int32)
    n_arcs = src.size
    if not (dst.size == cap.size == cost.size == n_arcs):
        raise ValueError("arc arrays must have equal length")
    flow_out = np.zeros(n_arcs, dtype=np.int32)
    tf = ctypes.c_int64()
    tc = ctypes.c_int64()
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib = load_mcmf()
    rc = lib.kao_mcmf(
        ctypes.c_int32(n_nodes), ctypes.c_int32(n_arcs),
        src.ctypes.data_as(i32p), dst.ctypes.data_as(i32p),
        cap.ctypes.data_as(i32p), cost.ctypes.data_as(i32p),
        ctypes.c_int32(s), ctypes.c_int32(t),
        flow_out.ctypes.data_as(i32p),
        ctypes.byref(tf), ctypes.byref(tc),
    )
    if rc != 0:
        raise RuntimeError(f"kao_mcmf rejected the input (rc={rc})")
    return int(tf.value), int(tc.value), flow_out


# ---------------------------------------------------------------------------
# bundled lp_solve work-alike CLI (lp_cli.cpp)

_LP_SRC = Path(__file__).with_name("lp_cli.cpp")


def lp_cli_path() -> Path:
    digest = hashlib.sha256(_LP_SRC.read_bytes()).hexdigest()[:16]
    return _build_dir() / f"lp_cli_{digest}"


def build_lp_cli() -> Path:
    """Compile the bundled lp_solve-compatible CLI (LP-format parser +
    exact 0-1 branch-and-bound, ``lp_cli.cpp``) on first use."""
    return _compile(_LP_SRC, lp_cli_path(), ["-O2"])
