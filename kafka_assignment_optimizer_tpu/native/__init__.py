"""Native (C++) runtime components and their build/loading machinery.

The reference's native component is the external lp_solve 5.5 C solver it
shells out to (``/root/reference/README.md:135-137``). This package bundles
the equivalent *in-process*: ``bb.cpp`` — a specialized exact
branch-and-bound for the reassignment model — compiled on first use with
the system ``g++`` into a cached shared library and bound via ctypes
(no pybind11 dependency).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from pathlib import Path

_SRC = Path(__file__).with_name("bb.cpp")


def _build_dir() -> Path:
    d = Path(__file__).with_name("_build")
    d.mkdir(exist_ok=True)
    return d


def lib_path() -> Path:
    """Content-addressed artifact path: a source edit changes the hash, so
    stale libraries are never loaded and parallel builds never collide."""
    digest = hashlib.sha256(_SRC.read_bytes()).hexdigest()[:16]
    return _build_dir() / f"libkao_{digest}.so"


def build(verbose: bool = False) -> Path:
    out = lib_path()
    if out.exists():
        return out
    with tempfile.TemporaryDirectory(dir=_build_dir()) as td:
        tmp = Path(td) / out.name
        cmd = [
            "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
            "-Wall", "-Wextra",
            str(_SRC), "-o", str(tmp),
        ]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native build failed:\n{' '.join(cmd)}\n{proc.stderr}"
            )
        if verbose and proc.stderr:
            print(proc.stderr)
        os.replace(tmp, out)  # atomic publish
    return out


_LIB: ctypes.CDLL | None = None


def load() -> ctypes.CDLL:
    global _LIB
    if _LIB is None:
        lib = ctypes.CDLL(str(build()))
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.kao_solve.restype = ctypes.c_int
        lib.kao_solve.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,  # P B K R
            i32p, i32p, i32p, i32p,  # rf rack_of w_leader w_follower
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,  # bands
            i32p, i32p, i32p,  # rack_lo rack_hi part_rack_hi
            i32p, ctypes.c_int64, ctypes.c_int,  # seed_a seed_w has_seed
            ctypes.c_double,  # time limit
            i32p, i64p, i64p,  # out_a out_objective out_nodes
        ]
        _LIB = lib
    return _LIB
