"""Model-layer tests: ingest/emit round trips, move diff, bound arithmetic
(SURVEY.md §2 rules), weight rule (README.md:146 data points)."""

import json

import numpy as np
import pytest

from kafka_assignment_optimizer_tpu import (
    Assignment,
    Topology,
    build_instance,
    move_diff,
    parse_broker_list,
)
from kafka_assignment_optimizer_tpu.models.cluster import (
    demo_assignment,
    demo_broker_list,
    demo_topology,
)
from kafka_assignment_optimizer_tpu.models.instance import (
    W_FOLLOWER_KEEP,
    W_FOLLOWER_PROMOTE,
    W_LEADER_DEMOTE,
    W_LEADER_KEEP,
)


def test_json_round_trip():
    a = demo_assignment()
    b = Assignment.from_json(a.to_json())
    assert b.to_dict() == a.to_dict()
    assert b.partitions[1].replicas == [8, 19]
    assert b.partitions[1].leader == 8


def test_parse_broker_list():
    assert parse_broker_list("0,1,2") == [0, 1, 2]
    assert parse_broker_list("0-3,7") == [0, 1, 2, 3, 7]
    assert parse_broker_list("1,1,2") == [1, 2]


def test_topology_forms():
    t1 = Topology.from_dict({"0": "a", "1": "b"})
    t2 = Topology.from_dict({"racks": {"a": [0], "b": [1]}})
    assert t1.to_dict() == t2.to_dict()
    demo = demo_topology()
    assert demo.rack(19) == "b" and demo.rack(18) == "a"
    assert demo.racks() == ["a", "b"]


def test_move_diff_counts_replica_moves():
    old = demo_assignment()
    new = Assignment.from_dict(old.to_dict())
    new.by_key()  # no-op
    # the demo's known-optimal single edit: partition 1 [8,19] -> [8,1]
    for p in new.partitions:
        if p.partition == 1:
            p.replicas = [8, 1]
    d = move_diff(old, new)
    assert d.replica_moves == 1
    assert d.leader_changes == 0
    assert [k.partition for k in d.changed] == [1]


def test_move_diff_leader_only():
    old = demo_assignment()
    new = Assignment.from_dict(old.to_dict())
    for p in new.partitions:
        if p.partition == 0:
            p.replicas = [18, 7]  # swap leader, same replica set
    d = move_diff(old, new)
    assert d.replica_moves == 0
    assert d.leader_changes == 1


def test_instance_shapes_and_bounds_demo():
    inst = build_instance(demo_assignment(), demo_broker_list(), demo_topology())
    # demo: 19 eligible brokers, 10 partitions, RF 2, 2 racks
    assert inst.num_brokers == 19
    assert inst.num_parts == 10
    assert inst.num_racks == 2
    assert inst.max_rf == 2
    assert inst.total_replicas == 20
    # README.md:158-161 -> replicas/broker in [1, 2] (20 replicas / 19 brokers)
    assert (inst.broker_lo, inst.broker_hi) == (1, 2)
    # README.md:163-166 -> leaders/broker in [0, 1]
    assert (inst.leader_lo, inst.leader_hi) == (0, 1)
    # rack sizes: even 'a' has 10 brokers (0..18 even), odd 'b' has 9
    np.testing.assert_array_equal(
        np.sort(np.bincount(inst.rack_of_broker[:19])), [9, 10]
    )
    # proportional bounds tightened to the diversity-implied extremes:
    # the per-partition cap of 1 bounds each rack at P = 10 total AND
    # forces >= 1 replica per partition in each rack (the other rack is
    # capped), so both bands collapse to exactly [10, 10] — the same
    # exact-band shape the reference sample shows for its equal-rack
    # case (README.md:173-176)
    a_idx = inst.rack_names.index("a")
    b_idx = inst.rack_names.index("b")
    assert (inst.rack_lo[a_idx], inst.rack_hi[a_idx]) == (10, 10)
    assert (inst.rack_lo[b_idx], inst.rack_hi[b_idx]) == (10, 10)
    # README.md:178-180 -> per-partition per-rack <= ceil(2/2) = 1
    assert (inst.part_rack_hi == 1).all()


def test_equal_rack_bounds_match_reference_sample():
    # the reference LP sample pins rack totals exactly when racks are equal:
    # 20 replicas / 2 racks -> [10, 10] (README.md:173-176)
    current = demo_assignment()
    topo = Topology.even_odd(range(20))
    inst = build_instance(current, list(range(20)), topo)
    np.testing.assert_array_equal(inst.rack_lo, [10, 10])
    np.testing.assert_array_equal(inst.rack_hi, [10, 10])


def test_weight_rule_matches_observed_tiers():
    inst = build_instance(demo_assignment(), demo_broker_list(), demo_topology())
    # partition 0: replicas [7, 18], leader 7
    p0 = 0
    b7 = int(np.searchsorted(inst.broker_ids, 7))
    b18 = int(np.searchsorted(inst.broker_ids, 18))
    assert inst.w_leader[p0, b7] == W_LEADER_KEEP == 4
    assert inst.w_follower[p0, b7] == W_LEADER_DEMOTE == 2
    assert inst.w_leader[p0, b18] == W_FOLLOWER_PROMOTE == 2
    assert inst.w_follower[p0, b18] == W_FOLLOWER_KEEP == 1
    # ineligible broker (19, being removed) earns no preservation weight
    p1 = 1  # replicas [8, 19]
    assert inst.w_leader[p1].sum() == W_LEADER_KEEP + 0
    assert inst.w_follower[p1].sum() == W_LEADER_DEMOTE


def test_identity_candidate_scores_upper_bound_when_no_broker_removed():
    current = demo_assignment()
    inst = build_instance(current, list(range(20)), Topology.even_odd(range(20)))
    assert inst.preservation_weight(inst.a0) == inst.max_weight()
    assert inst.move_count(inst.a0) == 0
    assert inst.is_feasible(inst.a0)


def test_violations_flag_imbalance():
    current = demo_assignment()
    inst = build_instance(current, list(range(20)), Topology.even_odd(range(20)))
    a = inst.a0.copy()
    # pile everything onto broker 0: breaks broker band + rack band + dup
    a[:, :] = 0
    v = inst.violations(a)
    assert v["broker_balance"] > 0
    assert v["duplicate_in_partition"] > 0


def test_rf_change_instance():
    inst = build_instance(
        demo_assignment(), list(range(20)), Topology.even_odd(range(20)), target_rf=3
    )
    assert inst.max_rf == 3
    assert inst.total_replicas == 30
    # current a0 pads the third slot with the null bucket
    assert (inst.a0[:, 2] == inst.num_brokers).all()
    # per-partition per-rack cap: ceil(3/2) = 2
    assert (inst.part_rack_hi == 2).all()


def test_rf_exceeding_brokers_rejected():
    with pytest.raises(ValueError):
        build_instance(demo_assignment(), [0, 1], None, target_rf=3)
