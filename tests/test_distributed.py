"""Multi-host initialization surface (``parallel.distributed``).

Real multi-process launches cannot run inside one CI process; what CAN
be pinned is the contract that makes the flag safe to leave on in
launch scripts: single-host no-op via jax's own cluster resolution
(fast ValueError, no coordinator timeout), idempotence, and that the
mesh the engines build covers the global device view either way.
"""

from __future__ import annotations

import jax

from kafka_assignment_optimizer_tpu.parallel.distributed import (
    init_distributed,
)
from kafka_assignment_optimizer_tpu.parallel.mesh import make_mesh


def test_single_host_is_noop(monkeypatch, capsys):
    """Without a cluster environment, jax's spec resolution raises
    ValueError inside initialize() and init_distributed treats it as a
    single-host launch: instant return, stderr note, no hang."""
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    idx, cnt = init_distributed()
    assert (idx, cnt) == (jax.process_index(), jax.process_count())
    assert cnt == 1  # the test env is single-process
    # and it is idempotent
    assert init_distributed() == (idx, cnt)


def test_explicit_misconfig_raises(monkeypatch):
    """A ValueError out of an EXPLICITLY configured launch (args or
    JAX_COORDINATOR_ADDRESS) is a malformed spec, not 'no cluster' —
    it must raise rather than let N workers silently solve alone."""
    import pytest

    import kafka_assignment_optimizer_tpu.parallel.distributed as dist

    monkeypatch.setattr(
        jax.distributed, "is_initialized", lambda: False, raising=False
    )

    def boom(**kw):
        raise ValueError("malformed spec")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    with pytest.raises(ValueError):
        dist.init_distributed(coordinator_address="nonsense:0",
                              num_processes=2, process_id=0)
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "nonsense:0")
    with pytest.raises(ValueError):
        dist.init_distributed()
    # truly unconfigured: same ValueError downgrades to single-host
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS")
    assert dist.init_distributed() == (
        jax.process_index(), jax.process_count()
    )


def test_mesh_spans_global_devices():
    """make_mesh builds over jax.devices() — the view that becomes
    global after a real distributed init — so multi-host needs no mesh
    code changes."""
    mesh = make_mesh()
    assert list(mesh.devices.flat) == jax.devices()


def test_cli_flag_exists_and_serve_has_none():
    """--distributed exists on the CLI (multi-controller SPMD: same
    program on every worker). serve deliberately has NO such flag —
    independent per-host HTTP request streams cannot drive matching
    collectives."""
    from kafka_assignment_optimizer_tpu.cli import build_parser

    args = build_parser().parse_args(["--broker-list", "0-2",
                                      "--distributed"])
    assert args.distributed
    args = build_parser().parse_args(["--broker-list", "0-2"])
    assert not args.distributed

    import kafka_assignment_optimizer_tpu.serve as serve_mod
    import inspect

    assert "--distributed" not in inspect.getsource(serve_mod)
