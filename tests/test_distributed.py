"""Multi-host initialization surface (``parallel.distributed``).

Real multi-process launches cannot run inside one CI process; what CAN
be pinned is the contract that makes the flag safe to leave on in
launch scripts: single-host no-op via jax's own cluster resolution
(fast ValueError, no coordinator timeout), idempotence, and that the
mesh the engines build covers the global device view either way.
"""

from __future__ import annotations

import jax
import pytest

from kafka_assignment_optimizer_tpu.parallel.distributed import (
    init_distributed,
)
from kafka_assignment_optimizer_tpu.parallel.mesh import make_mesh


def test_single_host_is_noop(monkeypatch, capsys):
    """Without a cluster environment, jax's spec resolution raises
    ValueError inside initialize() and init_distributed treats it as a
    single-host launch: instant return, stderr note, no hang."""
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    idx, cnt = init_distributed()
    assert (idx, cnt) == (jax.process_index(), jax.process_count())
    assert cnt == 1  # the test env is single-process
    # and it is idempotent
    assert init_distributed() == (idx, cnt)


def test_explicit_misconfig_raises(monkeypatch):
    """A ValueError out of an EXPLICITLY configured launch (args or
    JAX_COORDINATOR_ADDRESS) is a malformed spec, not 'no cluster' —
    it must raise rather than let N workers silently solve alone."""
    import pytest

    import kafka_assignment_optimizer_tpu.parallel.distributed as dist

    monkeypatch.setattr(
        jax.distributed, "is_initialized", lambda: False, raising=False
    )

    def boom(**kw):
        raise ValueError("malformed spec")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    with pytest.raises(ValueError):
        dist.init_distributed(coordinator_address="nonsense:0",
                              num_processes=2, process_id=0)
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "nonsense:0")
    with pytest.raises(ValueError):
        dist.init_distributed()
    # truly unconfigured: same ValueError downgrades to single-host
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS")
    assert dist.init_distributed() == (
        jax.process_index(), jax.process_count()
    )


def test_mesh_spans_global_devices():
    """make_mesh builds over jax.devices() — the view that becomes
    global after a real distributed init — so multi-host needs no mesh
    code changes."""
    mesh = make_mesh()
    assert list(mesh.devices.flat) == jax.devices()


@pytest.mark.soak
def test_two_process_distributed_solve_matches_single_process():
    """VERDICT r3 item 4: actually EXECUTE the multi-host path. Two
    local processes form a real jax.distributed cluster (CPU backend,
    4 forced devices each -> one global 8-device mesh) and run the
    sharded sweep solve end to end through the CLI's ``--distributed``;
    worker 0's plan must match the single-process 8-device solve.

    Gated on a backend capability probe (ISSUE 14 satellite, per the
    ROADMAP item-1 note): instead of a blanket ``xfail``, a real
    2-process collective probe decides — a build that supports
    multi-process CPU collectives runs the full test, one that does
    not skips with the probe's own finding as the reason, and a jax
    upgrade that fixes the limitation starts running this end to end
    with no test edit."""
    from kafka_assignment_optimizer_tpu.parallel.distributed import (
        probe_multiprocess_cpu,
    )

    supported, finding = probe_multiprocess_cpu()
    if not supported:
        pytest.skip(
            "this jax build cannot run multi-process CPU collectives "
            f"(capability probe: {finding})"
        )
    import json
    import os
    import socket
    import subprocess
    import sys
    import tempfile

    from kafka_assignment_optimizer_tpu.models.cluster import (
        demo_assignment,
    )

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    with tempfile.TemporaryDirectory() as td:
        inp = os.path.join(td, "current.json")
        with open(inp, "w") as f:
            f.write(demo_assignment().to_json())
        cmd = [
            sys.executable, "-m", "kafka_assignment_optimizer_tpu",
            "--input", inp, "--broker-list", "0-18",
            "--topology", "even-odd", "--solver", "tpu",
            "--seed", "0", "--engine", "sweep", "--distributed",
        ]

        def env_for(pid, n_dev):
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = (
                f"--xla_force_host_platform_device_count={n_dev}"
            )
            if pid is not None:
                env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
                env["JAX_NUM_PROCESSES"] = "2"
                env["JAX_PROCESS_ID"] = str(pid)
            return env

        procs = [
            subprocess.Popen(
                cmd, env=env_for(pid, 4), stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
            )
            for pid in (0, 1)
        ]
        outs = []
        try:
            for p in procs:
                out, err = p.communicate(timeout=300)
                outs.append((p.returncode, out, err))
        finally:
            for p in procs:
                p.kill()
        for rc, out, err in outs:
            assert rc == 0, f"worker failed rc={rc}: {err[-800:]}"

        def plan_of(out):
            # the gloo CPU collective backend chats on stdout
            # ("[Gloo] Rank 0 is connected ..."); the plan JSON is the
            # object that follows
            return json.loads(out[out.index("{"):])

        # every worker computed the same plan (SPMD: identical program,
        # identical global mesh) — the operator reads worker 0's
        plans = [plan_of(out) for _, out, _ in outs]
        assert plans[0] == plans[1]

        # single-process reference on the same 8-device global view
        r = subprocess.run(
            cmd[:-1], env=env_for(None, 8), timeout=300,
            capture_output=True, text=True,
        )
        assert r.returncode == 0, r.stderr[-800:]
        assert plan_of(r.stdout) == plans[0]


def test_cli_flag_exists_and_serve_has_none():
    """--distributed exists on the CLI (multi-controller SPMD: same
    program on every worker). serve deliberately has NO such flag —
    independent per-host HTTP request streams cannot drive matching
    collectives."""
    from kafka_assignment_optimizer_tpu.cli import build_parser

    args = build_parser().parse_args(["--broker-list", "0-2",
                                      "--distributed"])
    assert args.distributed
    args = build_parser().parse_args(["--broker-list", "0-2"])
    assert not args.distributed

    import kafka_assignment_optimizer_tpu.serve as serve_mod
    import inspect

    assert "--distributed" not in inspect.getsource(serve_mod)
