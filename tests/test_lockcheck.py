"""Lock-discipline coverage: the KAO116–119 static rules
(analysis/concurrency.py), the KAO_LSAN runtime sanitizer
(analysis/lsan.py), and the findings-ratchet baseline
(analysis/baseline.py + the CLI flags). docs/ANALYSIS.md is the
user-facing catalog; these tests pin the semantics it documents.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from kafka_assignment_optimizer_tpu.analysis import lsan
from kafka_assignment_optimizer_tpu.analysis.baseline import (
    compare,
    load,
    save,
)
from kafka_assignment_optimizer_tpu.analysis.findings import Finding
from kafka_assignment_optimizer_tpu.analysis.rules_ast import lint_source


def _lint(snippet: str, rel: str = "obs/fixture.py"):
    # default rel sits OUTSIDE the serve/fleet scope markers so the
    # lock fixtures exercise only the concurrency rules (urlopen under
    # a serving rel would also trip KAO111's trace-injection contract)
    return lint_source(textwrap.dedent(snippet), "fixture.py", rel=rel)


def _rules(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------- KAO116

SEEDED_UNGUARDED = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0

        def bump(self):
            with self._lock:
                self.n += 1

        def race(self):
            self.n += 1  # the seeded race
"""


def test_kao116_unguarded_write_flagged():
    found = _lint(SEEDED_UNGUARDED)
    assert _rules(found) == ["KAO116"]
    assert "race()" in found[0].message


def test_kao116_ctor_writes_exempt():
    # __init__ runs before the object is shared: not a race
    found = _lint("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self.n += 1
    """)
    assert found == []


def test_kao116_guards_comment_declares_discipline():
    # the declaration flags an unguarded write even with NO inferable
    # second write site — evidence-free discipline, explicitly stated
    found = _lint("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()  # kao: guards(n)
                self.n = 0

            def race(self):
                self.n = 5
    """)
    assert _rules(found) == ["KAO116"]


def test_kao116_locked_suffix_method_assumed_under_lock():
    # the *_locked naming convention: callers hold the lock
    found = _lint("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self._bump_locked()

            def _bump_locked(self):
                self.n += 1
    """)
    assert found == []


def test_kao116_module_global_main_exempt():
    # main() mutates config globals before any thread starts
    found = _lint("""
        import threading

        _LOCK = threading.Lock()
        CFG = {}

        def handler():
            with _LOCK:
                CFG["x"] = 1

        def main():
            CFG["boot"] = True
    """)
    assert found == []


# ---------------------------------------------------------------- KAO117

def test_kao117_blocking_call_under_lock():
    found = _lint("""
        import threading
        import urllib.request

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def fetch(self):
                with self._lock:
                    urllib.request.urlopen("http://x")
    """)
    assert _rules(found) == ["KAO117"]
    assert "urlopen" in found[0].message


def test_kao117_blocking_call_outside_lock_ok():
    found = _lint("""
        import threading
        import urllib.request

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def fetch(self):
                with self._lock:
                    pass
                urllib.request.urlopen("http://x")
    """)
    assert found == []


def test_kao117_condition_wait_exempt():
    # cv.wait RELEASES the lock while blocking — the one sanctioned
    # blocking call under a lock
    found = _lint("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)

            def drain(self):
                with self._cv:
                    self._cv.wait(timeout=1.0)
    """)
    assert found == []


# ---------------------------------------------------------------- KAO118

SEEDED_INVERSION = """
    import threading

    class Pair:
        def __init__(self):
            self.a = threading.Lock()
            self.b = threading.Lock()

        def fwd(self):
            with self.a:
                with self.b:
                    pass

        def rev(self):
            with self.b:
                with self.a:
                    pass
"""


def test_kao118_static_inversion_flagged():
    found = _lint(SEEDED_INVERSION)
    assert _rules(found) == ["KAO118"]
    assert "deadlock" in found[0].message


def test_kao118_consistent_order_silent():
    found = _lint("""
        import threading

        class Pair:
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()

            def one(self):
                with self.a:
                    with self.b:
                        pass

            def two(self):
                with self.a:
                    with self.b:
                        pass
    """)
    assert found == []


# ---------------------------------------------------------------- KAO119

def test_kao119_orphan_thread_in_serving_module():
    found = _lint("""
        import threading

        def spawn():
            threading.Thread(target=print).start()
    """, rel="serve.py")
    assert _rules(found) == ["KAO119"]


def test_kao119_daemon_thread_ok_and_nonserving_exempt():
    daemon = """
        import threading

        def spawn():
            threading.Thread(target=print, daemon=True).start()
    """
    assert _lint(daemon, rel="serve.py") == []
    orphan = """
        import threading

        def spawn():
            threading.Thread(target=print).start()
    """
    # same code outside the serving plane: out of scope
    assert _lint(orphan, rel="solvers/tpu/sweep.py") == []


# ------------------------------------------------------- runtime sanitizer

def test_lsan_inversion_trips_deterministically():
    """The seeded inversion from SEEDED_INVERSION, executed for real:
    A→B then B→A on the SAME thread — no timing, no second thread, the
    order graph alone trips it every run."""
    a = lsan.wrap(site="pair.a")
    b = lsan.wrap(site="pair.b")
    with lsan.scope() as sc:
        with a:
            with b:
                pass
        with pytest.raises(lsan.LockOrderInversion) as ei:
            with b:
                with a:
                    pass
        assert "pair.a" in str(ei.value) and "pair.b" in str(ei.value)
        assert [v.kind for v in sc.violations] == ["inversion"]
    # a tripped acquisition must not leak the inner lock (the raise
    # escapes __enter__, so __exit__ never runs)
    assert not a._inner.locked() and not b._inner.locked()
    # deliberate trips stay out of the session ledger
    assert all(v.site_a != "pair.a" for v in lsan.violations())


def test_lsan_record_only_mode(monkeypatch):
    monkeypatch.setenv("KAO_LSAN_RAISE", "0")
    a = lsan.wrap(site="ro.a")
    b = lsan.wrap(site="ro.b")
    with lsan.scope() as sc:
        with a:
            with b:
                pass
        with b:
            with a:  # recorded, not raised
                pass
        assert [v.kind for v in sc.violations] == ["inversion"]


def test_lsan_hold_budget_recorded_on_release():
    old = lsan._HOLD_BUDGET[0]
    lsan._HOLD_BUDGET[0] = 0.01
    try:
        lock = lsan.wrap(site="hold.x")
        with lsan.scope() as sc:
            with lock:
                time.sleep(0.05)
            assert [v.kind for v in sc.violations] == ["hold_budget"]
    finally:
        lsan._HOLD_BUDGET[0] = old


def test_lsan_rlock_reentry_is_not_an_edge():
    r = lsan.wrap(threading.RLock(), site="re.r", reentrant=True)
    inner = lsan.wrap(site="re.inner")
    with lsan.scope() as sc:
        with r:
            with r:  # re-entry: no self-edge, no double hold window
                with inner:
                    pass
        assert sc.violations == []


def test_lsan_condition_integration():
    cv = threading.Condition(lsan.wrap(site="cv.lock"))
    hits = []

    def waiter():
        with cv:
            while not hits:
                cv.wait(timeout=2.0)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cv:
        hits.append(1)
        cv.notify_all()
    t.join(timeout=5.0)
    assert not t.is_alive()


def test_lsan_install_wraps_only_package_locks():
    lsan.install()
    try:
        raw = threading.Lock()  # this test module is OUTSIDE the pkg
        assert type(raw).__name__ != "_LsanLock"
        # re-import a serving module so its locks bind post-install
        for m in list(sys.modules):
            if m.startswith("kafka_assignment_optimizer_tpu.fleet"):
                del sys.modules[m]
        from kafka_assignment_optimizer_tpu.fleet import health

        t = health.FleetTracker([], fetch=lambda u: {})
        assert type(t._lock).__name__ == "_LsanLock"
        t.poll_once()
        t.snapshot()
    finally:
        lsan.uninstall()
        for m in list(sys.modules):
            if m.startswith("kafka_assignment_optimizer_tpu.fleet"):
                del sys.modules[m]


def test_lsan_overhead_smoke():
    """The serve-plane contract: wrapped acquire/release must stay
    cheap enough that KAO_LSAN=1 tier-1 is viable. Relative timing on
    shared CI is noise, so the bound is absolute and generous: 50k
    uncontended lock round-trips through the proxy in under 2s
    (~40µs/op ceiling vs ~1µs typical) — an accidental O(edges) or
    syscall per acquisition blows straight through it."""
    lock = lsan.wrap(site="perf.x")
    n = 50_000
    t0 = time.monotonic()
    for _ in range(n):
        with lock:
            pass
    elapsed = time.monotonic() - t0
    assert elapsed < 2.0, f"{n} wrapped round-trips took {elapsed:.2f}s"


# ------------------------------------------------------- baseline ratchet

def _f(rule, path, line, msg):
    return Finding(rule, path, line, msg)


def test_baseline_compare_three_way():
    cur = [_f("KAO116", "a.py", 10, "m1"), _f("KAO117", "a.py", 20, "m2")]
    entries = [
        {"rule": "KAO116", "path": "a.py", "line": 99, "message": "m1"},
        {"rule": "KAO118", "path": "b.py", "line": 5, "message": "gone"},
    ]
    r = compare(cur, entries)
    # line drift (10 vs 99) still matches; m2 is new; 'gone' is stale
    assert [f.message for f in r.known] == ["m1"]
    assert [f.message for f in r.new] == ["m2"]
    assert [e["message"] for e in r.stale] == ["gone"]
    assert not r.clean


def test_baseline_duplicate_findings_counted():
    # two identical findings vs ONE baseline entry: the second is new
    cur = [_f("KAO116", "a.py", 1, "m"), _f("KAO116", "a.py", 2, "m")]
    entries = [{"rule": "KAO116", "path": "a.py", "line": 1,
                "message": "m"}]
    r = compare(cur, entries)
    assert len(r.known) == 1 and len(r.new) == 1


def test_baseline_round_trip(tmp_path):
    p = tmp_path / "base.json"
    save(str(p), [_f("KAO117", "x.py", 3, "blocking")])
    entries = load(str(p))
    assert entries == [{"rule": "KAO117", "path": "x.py", "line": 3,
                        "message": "blocking"}]
    assert compare([_f("KAO117", "x.py", 30, "blocking")],
                   entries).clean


def _cli(*argv, timeout=120):
    return subprocess.run(
        [sys.executable, "-m",
         "kafka_assignment_optimizer_tpu.analysis", *argv],
        capture_output=True, text=True, timeout=timeout,
    )


def test_ratchet_round_trip_cli(tmp_path):
    """The full workflow docs/ANALYSIS.md describes: seeded findings
    fail → --update-baseline accepts them → tolerated run exits 0 →
    fixing the code makes the stale entries fail → --update-baseline
    shrinks the baseline back to empty."""
    bad = tmp_path / "seeded.py"
    bad.write_text(textwrap.dedent(SEEDED_UNGUARDED))
    base = tmp_path / "base.json"

    r = _cli("--no-contracts", str(bad))
    assert r.returncode == 1 and "KAO116" in r.stdout

    r = _cli("--no-contracts", str(bad), "--baseline", str(base),
             "--update-baseline")
    assert r.returncode == 0, r.stdout + r.stderr

    r = _cli("--no-contracts", str(bad), "--baseline", str(base))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "1 baselined" in r.stdout

    bad.write_text(textwrap.dedent(SEEDED_UNGUARDED).replace(
        "self.n += 1  # the seeded race",
        "with self._lock:\n            self.n += 1"))
    r = _cli("--no-contracts", str(bad), "--baseline", str(base))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "stale baseline entry" in r.stdout

    r = _cli("--no-contracts", str(bad), "--baseline", str(base),
             "--update-baseline")
    assert r.returncode == 0
    assert load(str(base)) == []
    r = _cli("--no-contracts", str(bad), "--baseline", str(base))
    assert r.returncode == 0, r.stdout + r.stderr


def test_update_baseline_requires_baseline_flag():
    r = _cli("--no-contracts", "--update-baseline")
    assert r.returncode == 2
    assert "requires --baseline" in r.stderr


def test_sarif_output_marks_baselined_suppressed(tmp_path):
    bad = tmp_path / "seeded.py"
    bad.write_text(textwrap.dedent(SEEDED_INVERSION))
    base = tmp_path / "base.json"
    r = _cli("--no-contracts", str(bad), "--baseline", str(base),
             "--update-baseline")
    assert r.returncode == 0

    r = _cli("--no-contracts", str(bad), "--baseline", str(base),
             "--format", "sarif")
    doc = json.loads(r.stdout)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    ids = {rule["id"] for rule in run["tool"]["driver"]["rules"]}
    assert {"KAO116", "KAO117", "KAO118", "KAO119"} <= ids
    results = run["results"]
    assert [res["ruleId"] for res in results] == ["KAO118"]
    assert results[0]["suppressions"][0]["kind"] == "external"
    # baselined-only run is clean, so the gate passes
    assert r.returncode == 0

    r = _cli("--no-contracts", str(bad), "--format", "sarif")
    doc = json.loads(r.stdout)
    assert "suppressions" not in doc["runs"][0]["results"][0]
    assert r.returncode == 1


def test_repo_baseline_is_clean():
    """The committed analysis_baseline.json holds zero findings (the
    two serve-plane races the rules caught were FIXED, not baselined)
    and the repo passes its own ratchet."""
    import pathlib

    root = pathlib.Path(__file__).resolve().parents[1]
    base = root / "analysis_baseline.json"
    assert json.loads(base.read_text())["findings"] == []
    r = _cli("--no-contracts", "--baseline", str(base), timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
