"""Fused ladder megachunk bit-parity (ISSUE 17, docs/PIPELINE.md).

A megachunk stacks K consecutive sweep chunks into one device-resident
``lax.scan`` dispatch. Fusion is pure scheduling: the scan body is the
per-chunk step, the carried state (population, best snapshots, PRNG
keys) is the same state the chunked ladder hands between dispatches, so
every fused width must reproduce the K=1 chunked trajectory BIT FOR BIT
— final plan, curve, move count, checkpoint contents — while issuing
~K× fewer dispatches. These tests pin that contract at the optimize
level (XLA scorer), at the mesh level (XLA and Pallas-interpret, the
code path TPU compiles via Mosaic), across a checkpoint-resume, through
the device-side early-exit certificate, and through executable-cache
warmth (a re-solve at the same (bucket, K) compiles nothing).

Boundary/early-exit certificates are pinned OFF (``cert_min_savings_s=
1e9``) in the strict-parity tests and ON (negative threshold) only in
the early-exit tests, for the reasons test_pipeline_parity.py's module
docstring gives: whether a certificate check runs is wall-clock
adaptive by design.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafka_assignment_optimizer_tpu import build_instance
from kafka_assignment_optimizer_tpu.api import optimize
from kafka_assignment_optimizer_tpu.models.cluster import (
    Assignment,
    PartitionAssignment,
    Topology,
)

NO_DEADLINE = 3600.0


def random_cluster(rng, n_brokers, n_parts, rf, n_racks, drop=0):
    parts = []
    for p in range(n_parts):
        reps = rng.choice(n_brokers, size=rf, replace=False).tolist()
        parts.append(PartitionAssignment("t", p, [int(b) for b in reps]))
    topo = Topology(rack_of={b: f"r{b % n_racks}" for b in range(n_brokers)})
    brokers = list(range(n_brokers - drop))
    return Assignment(partitions=parts), brokers, topo


def _solve(cluster, megachunk, pipeline=False, checkpoint=None, **kw):
    # precompile=True + cert_min_savings_s=1e9: the deterministic knobs
    # (see test_pipeline_parity.py) — fusion parity must not depend on
    # constructor-race or certificate timing accidents. rounds=32 under
    # a never-binding deadline forces the 4-piece chunk schedule.
    current, brokers, topo = cluster
    return optimize(
        current, brokers, topo, solver="tpu", engine="sweep", seed=0,
        batch=8, pipeline=pipeline, time_limit_s=NO_DEADLINE,
        cert_min_savings_s=1e9, precompile=True, rounds=32,
        checkpoint=checkpoint, megachunk=megachunk, **kw,
    )


def _assert_parity(r_mega, r_base):
    s_m, s_b = r_mega.solve.stats, r_base.solve.stats
    assert np.array_equal(r_mega.solve.a, r_base.solve.a)
    assert r_mega.solve.objective == r_base.solve.objective
    assert s_m["moves"] == s_b["moves"]
    assert s_m["rounds_run"] == s_b["rounds_run"]
    assert s_m["score_curve"] == s_b["score_curve"]
    assert s_m["feasible"] is True


def test_megachunk_bit_identical_to_chunked(rng):
    """The tentpole acceptance: K∈{2,8}, sync and pipelined, all four
    fused trajectories equal the unfused chunked solve exactly, with
    fewer device dispatches; K=1 restores the per-chunk path with an
    identical dispatch count."""
    cluster = random_cluster(rng, 12, 48, 3, 3, drop=1)
    base = _solve(cluster, None)
    s_b = base.solve.stats
    n_chunks = s_b["dispatches"]  # chunked: one dispatch per chunk
    assert n_chunks > 1
    for pipeline in (False, True):
        for k in (2, 8):
            r = _solve(cluster, k, pipeline=pipeline)
            mg = r.solve.stats["megachunk"]
            _assert_parity(r, base)
            # the resolved width is the request capped at the ladder
            assert mg["k"] == min(k, n_chunks)
            assert mg["mode"] == "static"
            assert mg["chunks"] == n_chunks
            assert mg["dispatches"] == -(-n_chunks // mg["k"])  # ceil
            assert r.solve.stats["dispatches"] < n_chunks
    r1 = _solve(cluster, 1, pipeline=True)
    _assert_parity(r1, base)
    assert r1.solve.stats["megachunk"]["k"] == 1
    assert r1.solve.stats["dispatches"] == n_chunks


@pytest.mark.soak
@pytest.mark.slow  # ~30 s; nightly. Tier-1 keeps fused-vs-chunked
# parity at the optimize level (test_megachunk_bit_identical_to_chunked)
# and sharded megachunk parity (test_mesh_sharding.py).
def test_megachunk_mesh_parity_xla_and_interpret(rng):
    """Mesh-level: one fused solve_megachunk dispatch over K=4 chunk
    steps replays the 4-dispatch chunked loop bit-for-bit — final
    state, champion, per-chunk curves — under BOTH the XLA scorer and
    the Pallas kernel in interpret mode (the code path TPU compiles)."""
    from kafka_assignment_optimizer_tpu.parallel import mesh as pm
    from kafka_assignment_optimizer_tpu.solvers.tpu import arrays
    from kafka_assignment_optimizer_tpu.solvers.tpu.seed import greedy_seed

    current, brokers, topo = random_cluster(rng, 10, 16, 2, 2, drop=1)
    inst = build_instance(current, brokers, topo)
    m = arrays.from_instance(inst)
    seed = jnp.asarray(greedy_seed(inst), jnp.int32)
    mesh = pm.make_mesh()
    temps = arrays.geometric_temps(2.0, 0.05, 16)
    segs = [temps[i * 4:(i + 1) * 4] for i in range(4)]
    outs = {}
    for scorer in ("xla", "pallas-interpret"):
        # chunked reference: 4 sequential stateful dispatches
        st = pm.init_sweep_state(m, seed, jax.random.PRNGKey(3), mesh, 2)
        curves = []
        for seg in segs:
            st, ba, bk, cv = pm.solve_on_mesh(
                m, seed, jax.random.PRNGKey(3), mesh, 2, rounds=4,
                steps_per_round=2, engine="sweep", temps=seg,
                scorer=scorer, state=st,
            )
            curves.append(np.asarray(cv))
        chunked = (np.asarray(ba), np.asarray(bk),
                   np.stack(curves, axis=1))
        # fused: ONE dispatch, disarmed (all 4 steps execute)
        st2 = pm.init_sweep_state(m, seed, jax.random.PRNGKey(3), mesh, 2)
        (_st3, top_a, top_k, _ca, _ok, _mv, mcurves, execd
         ) = pm.solve_megachunk(
            m, mesh, 2, jnp.stack(segs), st2, steps_per_round=2,
            scorer=scorer,
        )
        assert np.asarray(execd).all()  # disarmed: every step executed
        np.testing.assert_array_equal(chunked[0], np.asarray(top_a))
        np.testing.assert_array_equal(chunked[1], np.asarray(top_k))
        np.testing.assert_array_equal(chunked[2], np.asarray(mcurves))
        outs[scorer] = chunked
    # and the two scorers agree with each other (Mosaic-path anchor)
    for a, b in zip(outs["xla"], outs["pallas-interpret"]):
        np.testing.assert_array_equal(a, b)


def test_megachunk_mesh_forced_certificate_exits_deterministically(rng):
    """Forced on-device certificate: thresholds every chain satisfies
    from the seed make the scan exit after step 0 — execd masks steps
    1..3 as never-executed, the certificate snapshot is flagged, and a
    replay is bit-identical (the early exit is pure device arithmetic,
    no host wall-clock in the loop)."""
    from kafka_assignment_optimizer_tpu.parallel import mesh as pm
    from kafka_assignment_optimizer_tpu.solvers.tpu import arrays
    from kafka_assignment_optimizer_tpu.solvers.tpu.seed import greedy_seed

    current, brokers, topo = random_cluster(rng, 10, 16, 2, 2, drop=1)
    inst = build_instance(current, brokers, topo)
    m = arrays.from_instance(inst)
    seed = jnp.asarray(greedy_seed(inst), jnp.int32)
    mesh = pm.make_mesh()
    segs = jnp.stack(
        [arrays.geometric_temps(2.0, 0.05, 16)[i * 4:(i + 1) * 4]
         for i in range(4)]
    )

    def run():
        st = pm.init_sweep_state(m, seed, jax.random.PRNGKey(3), mesh, 2)
        out = pm.solve_megachunk(
            m, mesh, 2, segs, st, steps_per_round=2, scorer="xla",
            cert_k=-(2 ** 31) + 1, cert_mv=2 ** 31 - 1,
        )
        (_st, top_a, top_k, cert_a, cert_ok, cert_mv, _cv, execd) = out
        return (np.asarray(top_a), np.asarray(top_k),
                np.asarray(cert_a), np.asarray(cert_ok),
                np.asarray(cert_mv), np.asarray(execd))

    first, again = run(), run()
    execd = first[5].reshape(-1, 4)
    assert execd[:, 0].all() and not execd[:, 1:].any(), execd
    assert first[3].all()  # every shard flagged the certificate
    for a, b in zip(first, again):
        np.testing.assert_array_equal(a, b)


def test_megachunk_early_exit_certifies_at_engine_level(monkeypatch):
    """With bounds prewarmed, the constructor neutralized, certificate
    economics disabled (negative threshold) and the weight bound forced
    to a value every chain reaches, the fused ladder arms the
    device-side exit, the scan retires after one chunk of four, and the
    host certifies the snapshot — deterministically across a warm
    replay. (The REAL decommission weight bound is only reached after
    the host-side leader reseat, which the raw device threshold
    deliberately excludes — so the forced bound is what makes the
    device exit itself, not the boundary certificate, the thing under
    test.)"""
    from kafka_assignment_optimizer_tpu.solvers.tpu import engine as eng
    from kafka_assignment_optimizer_tpu.utils import gen

    monkeypatch.setattr(
        eng, "_construct_worker", lambda *a, **k: (None, False, False)
    )
    sc = gen.SCENARIOS["decommission"](**gen.SMOKE_KWARGS["decommission"])
    inst = build_instance(
        sc.current, sc.broker_list, sc.topology, target_rf=sc.target_rf
    )
    lb = inst.move_lower_bound_exact()  # prewarm: the exact move bound
    monkeypatch.setattr(inst, "weight_upper_bound", lambda *a, **k: 1)
    kw = dict(seed=0, engine="sweep", batch=8, rounds=32,
              time_limit_s=NO_DEADLINE, cert_min_savings_s=-1.0,
              megachunk=4)
    res = eng.solve_tpu(inst, **kw)
    s = res.stats
    assert s["feasible"]
    assert s["moves"] == lb  # the move-count leg of the test is real
    mg = s["megachunk"]
    assert mg["k"] == 4
    assert mg["early_exit"] is True
    assert mg["chunks"] < 4  # the scan retired before the group's end
    assert s["rounds_run"] < s["rounds"]
    # warm replay: the early exit is device arithmetic, so the retired
    # chunk count and the certified plan replay exactly
    res2 = eng.solve_tpu(inst, **kw)
    assert np.array_equal(res2.a, res.a)
    assert res2.stats["megachunk"] == mg
    assert res2.stats["rounds_run"] == s["rounds_run"]


def test_megachunk_checkpoint_resume_across_boundary(rng, tmp_path):
    """Fused and chunked solves write identical checkpoints, and a
    resume from the fused solve's checkpoint — which was filed at a
    MEGACHUNK boundary — replays to the chunked answer again."""
    from kafka_assignment_optimizer_tpu.models.instance import (
        build_instance as _bi,
    )
    from kafka_assignment_optimizer_tpu.utils import checkpoint as ckpt

    cluster = random_cluster(rng, 12, 48, 3, 3, drop=1)
    ck_m = str(tmp_path / "mega" / "ck.npz")
    ck_b = str(tmp_path / "base" / "ck.npz")
    r_mega = _solve(cluster, 2, checkpoint=ck_m)
    r_base = _solve(cluster, None, checkpoint=ck_b)
    _assert_parity(r_mega, r_base)
    inst = _bi(*cluster)
    a_m, a_b = ckpt.load(ck_m, inst), ckpt.load(ck_b, inst)
    assert a_m is not None and np.array_equal(a_m, a_b)
    r_mega2 = _solve(cluster, 2, checkpoint=ck_m)
    r_base2 = _solve(cluster, None, checkpoint=ck_b)
    assert r_mega2.solve.stats["resumed_from_checkpoint"] is True
    assert r_base2.solve.stats["resumed_from_checkpoint"] is True
    _assert_parity(r_mega2, r_base2)
    assert np.array_equal(r_mega2.solve.a, r_mega.solve.a)


def test_megachunk_warm_resolve_compiles_nothing(rng, monkeypatch):
    """One executable per (bucket, K): a warm re-solve at the same
    fused width compiles NOTHING and — the donation round-trip — the
    donated carry left no corrupted buffers behind, so the answer is
    identical. Compiles counted via the lowering hook
    (tests/test_bucketing.py idiom)."""
    from kafka_assignment_optimizer_tpu.parallel import mesh

    cluster = random_cluster(rng, 12, 48, 3, 3, drop=1)
    compiles: list = []
    real = mesh._lower_and_compile

    def counting(fn, args):
        compiles.append(mesh._arg_signature(args))
        return real(fn, args)

    monkeypatch.setattr(mesh, "_lower_and_compile", counting)
    r1 = _solve(cluster, 8)
    after_first = len(compiles)
    r2 = _solve(cluster, 8)
    assert len(compiles) == after_first, (
        f"warm same-(bucket,K) re-solve recompiled: "
        f"{compiles[after_first:]}"
    )
    assert np.array_equal(r1.solve.a, r2.solve.a)
    assert r1.solve.stats["score_curve"] == r2.solve.stats["score_curve"]
    assert r2.solve.stats["megachunk"]["k"] > 1


def test_megachunk_warm_estimate_is_width_keyed(rng):
    """Satellite pin: fused measurements file under their own width key
    — a K=2 solve must not move the K=1 warm estimate the per-chunk
    deadline gates read (a fused group amortizes per-dispatch host
    overhead the unfused chunk pays, so cross-feeding would deflate the
    chunked estimate and inflate the fused one)."""
    from kafka_assignment_optimizer_tpu.solvers.tpu.engine import (
        _WARM_CHUNKS,
    )

    cluster = random_cluster(rng, 12, 48, 3, 3, drop=1)
    _WARM_CHUNKS.clear()
    _solve(cluster, None)
    before = dict(_WARM_CHUNKS._d)
    assert before, "chunked solve filed no warm estimate"
    # the registry key is (*warm_key, chunk_len, width, scorer)
    assert all(k[-2] == 1 for k in before)
    _solve(cluster, 2)
    after = dict(_WARM_CHUNKS._d)
    for k, v in before.items():
        assert after[k] == v, f"fused solve moved the width-1 entry {k}"
    mega_keys = [k for k in after if k[-2] == 2]
    assert mega_keys, "fused solve filed no width-keyed estimate"
