"""HTTP service tests (reference C16, ``README.md:187-195``): a real
ThreadingHTTPServer on an ephemeral port, exercised with urllib — the
golden demo through POST /submit, schema error paths, and /healthz."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from kafka_assignment_optimizer_tpu.models.cluster import demo_assignment
from kafka_assignment_optimizer_tpu.serve import ApiError, handle_submit, make_server


@pytest.fixture(scope="module")
def server_url():
    srv = make_server(port=0)  # ephemeral port
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()
    srv.server_close()


def post(url, payload):
    return post_to(url, "/submit", payload)


def test_submit_demo_golden(server_url):
    status, body = post(server_url, {
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
        "topology": "even-odd",
        "solver": "milp",
    })
    assert status == 200, body
    rep = body["report"]
    assert rep["replica_moves"] == 1 and rep["feasible"]
    plan = {p["partition"]: p["replicas"] for p in body["assignment"]["partitions"]}
    assert plan[1][0] == 8 and plan[1][1] % 2 == 1  # leader kept, odd AZ


def test_submit_solver_options(server_url):
    status, body = post(server_url, {
        "assignment": demo_assignment().to_dict(),
        "brokers": list(range(19)),
        "topology": "even-odd",
        "solver": "tpu",
        "options": {"batch": 8, "rounds": 4, "steps_per_round": 100},
    })
    assert status == 200, body
    assert body["report"]["feasible"]


@pytest.mark.parametrize("payload,want", [
    ({}, 400),
    ({"assignment": {"version": 1, "partitions": []}}, 400),  # no brokers
    ({"assignment": "nope", "brokers": "0-3"}, 400),
    ({"assignment": {"version": 1, "partitions": []}, "brokers": "x"}, 400),
    ({"assignment": demo_assignment().to_dict(), "brokers": "0-18",
      "rf": "three"}, 400),
    ({"assignment": demo_assignment().to_dict(), "brokers": "0-18",
      "solver": "unknown-backend"}, 400),
    ({"assignment": demo_assignment().to_dict(), "brokers": "0,1",
      "rf": 5}, 422),  # RF > broker count
])
def test_submit_error_paths(server_url, payload, want):
    status, body = post(server_url, payload)
    assert status == want, body
    assert "error" in body


def test_submit_rejects_invalid_json(server_url):
    req = urllib.request.Request(
        server_url + "/submit", data=b"{not json", method="POST"
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            status = resp.status
    except urllib.error.HTTPError as e:
        status = e.code
    assert status == 400


def test_healthz_and_404(server_url):
    with urllib.request.urlopen(server_url + "/healthz", timeout=30) as resp:
        body = json.loads(resp.read())
    assert body["status"] == "ok"
    assert "milp" in body["solvers"] and "tpu" in body["solvers"]
    try:
        urllib.request.urlopen(server_url + "/nope", timeout=30)
        status = 200
    except urllib.error.HTTPError as e:
        status = e.code
    assert status == 404


def test_handler_unit_surface():
    """handle_submit is callable without a socket (embedding surface)."""
    out = handle_submit({
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
        "topology": "even-odd",
        "solver": "milp",
    })
    assert out["report"]["replica_moves"] == 1
    with pytest.raises(ApiError) as ei:
        handle_submit({"brokers": "0-3"})
    assert ei.value.status == 400


def test_submit_rejects_path_valued_options(server_url):
    """ADVICE r1 (medium): a remote client must not be able to forward
    path-valued solver kwargs (checkpoint/profile_dir) — or any kwarg
    outside the search-knob allowlist — through POST /submit."""
    base = {
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
        "solver": "milp",
    }
    for bad in ({"checkpoint": "/tmp/evil.npz"},
                {"profile_dir": "/tmp/evil"},
                {"nonsense_knob": 1}):
        status, body = post(server_url, {**base, "options": bad})
        assert status == 400, (bad, body)
        assert "unsupported option" in body["error"]


def _saturated_queue(srv_mod):
    """A 1-worker/depth-1 solve queue whose worker and slot are both
    pinned by blocking jobs; returns (queue, release_event)."""
    gate = threading.Event()
    q = srv_mod._SolveQueue(workers=1, depth=1)
    q.submit(lambda: True, wait_s=1.0, budget_s=1.0)  # start the worker
    blocker = srv_mod._QueueItem(lambda: gate.wait(30))
    q._q.put(blocker, timeout=5)  # occupies the worker
    time.sleep(0.1)
    filler = srv_mod._QueueItem(lambda: True)
    q._q.put(filler, timeout=5)  # occupies the only queue slot
    return q, gate


def test_submit_busy_returns_503(monkeypatch):
    """VERDICT r1 item 9, queue edition: with every worker busy and the
    bounded queue full, a new request must shed with 503 after its wait
    budget — and succeed again once capacity frees up."""
    from kafka_assignment_optimizer_tpu import serve as srv_mod

    payload = {
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
        "solver": "milp",
    }
    q, gate = _saturated_queue(srv_mod)
    monkeypatch.setattr(srv_mod, "_SOLVES", q)
    try:
        with pytest.raises(ApiError) as ei:
            handle_submit(payload, lock_wait_s=0.2)
        assert ei.value.status == 503
    finally:
        gate.set()
    time.sleep(0.3)  # worker drains the blocker + filler
    out = handle_submit(payload, lock_wait_s=5.0)
    assert out["report"]["feasible"]


def test_submit_concurrent_requests_both_complete():
    """Acceptance: overlapping submits must not serialize on a global
    lock — two concurrent requests both complete with consistent
    metrics counters."""
    from kafka_assignment_optimizer_tpu import serve as srv_mod

    payload = {
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
        "topology": "even-odd",
        "solver": "milp",
    }
    with srv_mod._METRICS_LOCK:
        solves_before = srv_mod._METRICS["solves_total"]
    results: list = [None, None]

    def run(i):
        results[i] = handle_submit(dict(payload), lock_wait_s=30.0)

    threads = [threading.Thread(target=run, args=(i,)) for i in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "concurrent submit deadlocked"
    for out in results:
        assert out is not None and out["report"]["feasible"]
        assert out["report"]["replica_moves"] == 1
    with srv_mod._METRICS_LOCK:
        assert srv_mod._METRICS["solves_total"] == solves_before + 2


def test_evaluate_succeeds_while_solver_saturated(monkeypatch):
    """VERDICT r4 item 8: audits are host-only and hold their own lock,
    so a saturated solve queue must not 503 an /evaluate — and a
    saturated auditor still sheds."""
    from kafka_assignment_optimizer_tpu import serve as srv_mod
    from kafka_assignment_optimizer_tpu.serve import handle_evaluate

    payload = {
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
        "topology": "even-odd",
        "plan": demo_assignment().to_dict(),
    }
    q, gate = _saturated_queue(srv_mod)
    monkeypatch.setattr(srv_mod, "_SOLVES", q)
    try:
        out = handle_evaluate(payload, lock_wait_s=0.2)
        assert out["feasible"] is False  # references removed broker 19
    finally:
        gate.set()
    # the audit lock itself still saturates with 503
    assert srv_mod._AUDIT_LOCK.acquire(timeout=5)
    try:
        with pytest.raises(ApiError) as ei:
            handle_evaluate(payload, lock_wait_s=0.2)
        assert ei.value.status == 503
    finally:
        srv_mod._AUDIT_LOCK.release()


def test_submit_server_caps_time_limit():
    """The service injects its max solve budget; a client may tighten
    the limit but never exceed it."""
    payload = {
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
        "solver": "tpu",
        "options": {"batch": 8, "rounds": 4, "steps_per_round": 100,
                    "time_limit_s": 9999.0},
    }
    out = handle_submit(payload, max_solve_s=60.0)
    assert out["report"]["solver_time_limit_s"] == 60.0
    payload["options"]["time_limit_s"] = 30.0
    out = handle_submit(payload, max_solve_s=60.0)
    assert out["report"]["solver_time_limit_s"] == 30.0


def test_submit_time_limit_validation_and_no_mutation():
    payload = {
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
        "solver": "milp",
        "options": {"time_limit_s": "30"},
    }
    with pytest.raises(ApiError) as ei:
        handle_submit(payload)
    assert ei.value.status == 400
    # the caller's dict is never mutated by the cap injection
    payload["options"] = {}
    handle_submit(payload, max_solve_s=60.0)
    assert payload["options"] == {}


def test_metrics_endpoint(server_url):
    """GET /metrics: Prometheus text counters that actually move."""
    import urllib.request

    def scrape():
        with urllib.request.urlopen(server_url + "/metrics") as r:
            assert r.status == 200
            return {
                line.split()[0]: float(line.split()[1])
                for line in r.read().decode().splitlines()
                if line and not line.startswith("#")
            }

    before = scrape()
    status, _ = post(server_url, {
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
        "solver": "milp",
    })
    assert status == 200
    after = scrape()
    assert after["kao_requests_total"] == before["kao_requests_total"] + 1
    assert after["kao_solves_total"] == before["kao_solves_total"] + 1
    assert after["kao_last_solve_seconds"] > 0
    # an invalid request bumps the error counter
    status, _ = post(server_url, {"brokers": "0-3"})
    assert status == 400
    final = scrape()
    assert final["kao_errors_total"] == after["kao_errors_total"] + 1


def post_to(url, path, payload):
    req = urllib.request.Request(
        url + path,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=120) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_evaluate_endpoint_audits_plans(server_url):
    """POST /evaluate: certify the optimal plan, flag a stale one."""
    status, body = post(server_url, {
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
        "topology": "even-odd",
        "solver": "milp",
    })
    assert status == 200, body
    status, rep = post_to(server_url, "/evaluate", {
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
        "topology": "even-odd",
        "plan": body["assignment"],
    })
    assert status == 200, rep
    assert rep["feasible"] and rep["proven_optimal"]
    assert rep["replica_moves"] == 1 == rep["min_moves_lower_bound"]

    # the unmodified current assignment references removed broker 19
    status, rep = post_to(server_url, "/evaluate", {
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
        "topology": "even-odd",
        "plan": demo_assignment().to_dict(),
    })
    assert status == 200
    assert not rep["feasible"] and not rep["proven_optimal"]

    # missing plan field
    status, rep = post_to(server_url, "/evaluate", {
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
    })
    assert status == 400


def test_submit_malformed_topology_returns_400(server_url):
    """Satellite fix: malformed topology/rf specs must come back as
    structured 400 JSON, not bubble into a 500 (non-int broker keys and
    non-iterable rack lists both used to escape the parse try)."""
    base = {
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
        "solver": "milp",
    }
    for bad_topo in ({"not_an_int": "rackA"},
                     {"racks": {"a": 5}},
                     {"racks": {"a": [None]}},
                     ["rackA", "rackB"]):
        status, body = post(server_url, {**base, "topology": bad_topo})
        assert status == 400, (bad_topo, body)
        assert "error" in body
    for bad_rf in ({"t": "three"}, {"t": True}, True):
        status, body = post(server_url, {**base, "rf": bad_rf})
        assert status == 400, (bad_rf, body)
    status, body = post(server_url, {**base, "brokers": [0, True, 2]})
    assert status == 400, body


def test_healthz_cache_and_queue_sections(server_url):
    with urllib.request.urlopen(server_url + "/healthz", timeout=30) as r:
        body = json.loads(r.read())
    cache = body["cache"]
    assert isinstance(cache["bucketing_enabled"], bool)
    assert cache["part_ladder_head"][0] >= 1
    for key in ("bucket_hits", "bucket_misses", "exec_hits",
                "exec_misses", "compiles_total", "compile_seconds_total"):
        assert key in cache
    # lane consolidation (ISSUE 10): the active padding rungs plus
    # executables reported by bucket with the raw batch widths served
    assert isinstance(cache["lane_ladder"], list)
    assert cache["lane_ladder"] == [] or cache["lane_ladder"][-1] >= 2
    assert isinstance(cache["lane_executables"], dict)
    for row in cache["lane_executables"].values():
        assert set(row) >= {"lane_buckets", "served_lane_counts",
                            "dispatches"}
    q = body["queue"]
    assert q["workers"] >= 1 and q["queue_depth"] >= 0


def test_warmup_endpoint_precompiles_bucket(server_url):
    """POST /warmup compiles a bucket's executables once — including
    the CONSOLIDATED lane-padded batch executable, once per bucket, not
    once per lane count (ISSUE 10) — and a second warmup of the same
    bucket reports already_warm with zero compiles on both rows (the
    acceptance signal: same-bucket solves, batched at any width, never
    see XLA compile)."""
    shape = {"brokers": 8, "partitions": 24, "rf": 2, "racks": 2}
    status, out = post_to(server_url, "/warmup",
                          {"shapes": [shape], "engine": "sweep"})
    assert status == 200, out
    row = out["warmed"][0]
    assert row["bucket_parts"] >= shape["partitions"]
    assert row["wall_s"] > 0
    # lane warmup ran by default and reports its own compile delta
    assert "lane_error" not in row, row
    assert row["lane_bucket"] >= 2
    assert row["lane_wall_s"] > 0
    status, out2 = post_to(server_url, "/warmup",
                           {"shapes": [shape], "engine": "sweep"})
    assert status == 200, out2
    row2 = out2["warmed"][0]
    assert row2["already_warm"] is True
    assert row2["compiles"] == 0 and row2["compile_s"] == 0
    assert row2.get("lanes_already_warm") is True, row2
    assert row2.get("lane_compiles") == 0, row2
    # "lanes": false opts the lane precompile out (and stays fast)
    status, out3 = post_to(server_url, "/warmup",
                           {"shapes": [shape], "lanes": False})
    assert status == 200, out3
    assert "lane_bucket" not in out3["warmed"][0]
    # malformed warmup bodies are structured 400s
    for bad in ({}, {"shapes": []}, {"shapes": ["x"]},
                {"shapes": [{"brokers": 2, "partitions": 4, "rf": 3}]},
                {"shapes": [[8, 24]], "engine": "bogus"},
                {"shapes": [[8, 24]], "lanes": "yes"},
                {"shapes": [[8, 24]], "decompose": "yes"},
                {"shapes": [[8, 24]], "decompose": 99}):
        status, body = post_to(server_url, "/warmup", bad)
        assert status == 400, (bad, body)


@pytest.mark.soak
@pytest.mark.slow  # ~21 s (a real /warmup decompose compile); nightly.
# Tier-1 keeps the warmup-endpoint compile pin and the /healthz
# malformed-body 400s.
def test_healthz_decompose_section_and_warmup(server_url):
    """PR 16 satellite: /healthz carries the decompose config/counters
    and /warmup {"decompose": true} precompiles the map-lane shape."""
    with urllib.request.urlopen(server_url + "/healthz", timeout=30) as r:
        body = json.loads(r.read())
    dec = body["decompose"]
    assert dec["mode"] in ("auto", "on", "off")
    assert dec["auto_parts"] >= 1 and dec["max_iters"] >= 1
    assert isinstance(dec["sub_bucket_ladder"], list)
    assert isinstance(dec["map_lane_warm"], bool)
    for k in ("solves", "fallback", "unsplittable"):
        assert k in dec["counters"], dec
    # decompose warmup rides the shape rows: sub-shapes derived from
    # the flat shape, solved through the REAL batch path as one
    # lane-padded precompile
    shape = {"brokers": 12, "partitions": 60, "rf": 2, "racks": 3}
    status, out = post_to(server_url, "/warmup",
                          {"shapes": [shape], "lanes": False,
                           "decompose": True})
    assert status == 200, out
    row = out["warmed"][0]
    assert row["decompose_groups"] == 2
    assert row["decompose_lane_bucket"] >= 2
    assert row["decompose_wall_s"] > 0
    # a second decompose warmup of the same shape is all cache hits
    status, out2 = post_to(server_url, "/warmup",
                           {"shapes": [shape], "lanes": False,
                            "decompose": True})
    assert status == 200, out2
    row2 = out2["warmed"][0]
    assert row2.get("decompose_already_warm") is True, row2
    assert row2.get("decompose_compiles") == 0, row2


def test_landing_page_front_door(server_url):
    """GET /: the human-usable landing page (reference hosted-instance
    UX, README.md:189-195) — HTML with the worked example, the live
    form, and links to the machine surfaces."""
    with urllib.request.urlopen(server_url + "/", timeout=30) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/html")
        html = resp.read().decode()
    assert "POST /submit" in html and "/evaluate" in html
    assert "x.y.z.t" in html  # prefilled demo assignment
    for link in ("/healthz", "/metrics", "/schema"):
        assert link in html


def test_landing_content_negotiation_and_schema(server_url):
    """JSON clients on / get the schema; GET /schema always does."""
    req = urllib.request.Request(
        server_url + "/", headers={"Accept": "application/json"}
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        body = json.loads(resp.read())
    assert "POST /submit" in body["endpoints"]
    with urllib.request.urlopen(server_url + "/schema", timeout=30) as resp:
        schema = json.loads(resp.read())
    assert schema["endpoints"] == body["endpoints"]
    # the embedded example is itself a valid /submit payload
    ex = schema["example"]
    status, out = post(server_url, dict(ex, solver="milp"))
    assert status == 200 and out["report"]["replica_moves"] == 1


# --------------------------------------------------------------------------
# request coalescing (PR-2: batched multi-instance solve lanes in serve)
# --------------------------------------------------------------------------


def _tpu_payload(topic_prefix=""):
    d = demo_assignment().to_dict()
    if topic_prefix:
        for p in d["partitions"]:
            p["topic"] = topic_prefix + p["topic"]
    return {
        "assignment": d,
        "brokers": "0-18",
        "topology": "even-odd",
        "solver": "tpu",
        "options": {"rounds": 2, "batch": 4},
    }


def test_submit_coalesces_concurrent_same_bucket(monkeypatch):
    """Acceptance: concurrent same-bucket TPU requests are grouped into
    ONE batched lane solve (batch-size histogram shows >1) and each
    request gets ITS OWN plan back (demux correlation pinned via
    distinct topic names)."""
    from kafka_assignment_optimizer_tpu import serve as srv_mod

    # force the coalescing branch (the pool is idle in tests) and keep
    # the window short; restore via monkeypatch teardown
    monkeypatch.setattr(srv_mod._Coalescer, "should_bypass",
                        lambda self, key: False)
    monkeypatch.setattr(srv_mod._COALESCER, "window_s", 0.25)
    monkeypatch.setattr(srv_mod._COALESCER, "max_batch", 4)

    with srv_mod._METRICS_LOCK:
        before = dict(srv_mod._METRICS)
        sizes_before = dict(srv_mod._BATCH_SIZES)
    prefixes = ["", "zz.", "yy."]
    results: list = [None] * len(prefixes)

    def run(i):
        payload = _tpu_payload(prefixes[i])
        payload["options"] = dict(payload["options"], seed=i)
        results[i] = handle_submit(payload, lock_wait_s=30.0)

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(len(prefixes))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "coalesced submit deadlocked"
    for i, out in enumerate(results):
        assert out is not None and out["report"]["feasible"], out
        topics = {p["topic"] for p in out["assignment"]["partitions"]}
        assert all(t.startswith(prefixes[i]) for t in topics), (
            "demux returned another request's plan"
        )
        if prefixes[i]:
            assert any(t.startswith(prefixes[i]) for t in topics)
    with srv_mod._METRICS_LOCK:
        after = dict(srv_mod._METRICS)
        sizes_after = dict(srv_mod._BATCH_SIZES)
    assert after["batch_solves_total"] == before["batch_solves_total"] + 1
    assert (after["batched_requests_total"]
            == before["batched_requests_total"] + 3)
    assert after["batch_lanes_feasible_total"] >= (
        before["batch_lanes_feasible_total"] + 3
    )
    assert sizes_after.get(3, 0) == sizes_before.get(3, 0) + 1
    # the histogram renders as a labeled counter family in /metrics
    text = srv_mod.render_metrics()
    assert 'kao_batch_size_total{size="3"}' in text


def test_submit_sparse_request_bypasses_window():
    """Acceptance: a single request finding free capacity skips the
    coalescing window entirely — it runs the full single-solve path
    (no batch dispatch recorded) and bumps the bypass counter."""
    from kafka_assignment_optimizer_tpu import serve as srv_mod

    with srv_mod._METRICS_LOCK:
        before = dict(srv_mod._METRICS)
    out = handle_submit(_tpu_payload(), lock_wait_s=30.0)
    assert out["report"]["feasible"]
    with srv_mod._METRICS_LOCK:
        after = dict(srv_mod._METRICS)
    assert after["batch_bypass_total"] == before["batch_bypass_total"] + 1
    assert after["batch_solves_total"] == before["batch_solves_total"]
    assert after["solves_total"] == before["solves_total"] + 1


def test_submit_max_batch_flushes_without_window(monkeypatch):
    """A group hitting --max-batch dispatches immediately instead of
    waiting out the window (the window only bounds the wait, it is not
    a fixed tax)."""
    from kafka_assignment_optimizer_tpu import serve as srv_mod

    monkeypatch.setattr(srv_mod._Coalescer, "should_bypass",
                        lambda self, key: False)
    monkeypatch.setattr(srv_mod._COALESCER, "window_s", 30.0)
    monkeypatch.setattr(srv_mod._COALESCER, "max_batch", 2)
    results: list = [None, None]

    def run(i):
        payload = _tpu_payload()
        payload["options"] = dict(payload["options"], seed=i)
        results[i] = handle_submit(payload, lock_wait_s=30.0)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=run, args=(i,)) for i in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive()
    assert time.perf_counter() - t0 < 25.0, (
        "max-batch flush waited out the 30s window"
    )
    for out in results:
        assert out is not None and out["report"]["feasible"]


def test_healthz_reports_coalescing_config(server_url):
    with urllib.request.urlopen(server_url + "/healthz", timeout=30) as r:
        body = json.loads(r.read())
    co = body["coalescing"]
    assert set(co) == {"enabled", "window_ms", "max_batch"}
    assert co["max_batch"] >= 1


# --------------------------------------------------------------------------
# solve-trace telemetry (ISSUE 3: trace IDs + /debug/solves)
# --------------------------------------------------------------------------


def _span_names(span_dict, acc=None):
    acc = [] if acc is None else acc
    acc.append(span_dict["name"])
    for c in span_dict.get("spans", []):
        _span_names(c, acc)
    return acc


def test_submit_echoes_trace_id_and_debug_endpoint(server_url):
    """Acceptance (ISSUE 3): the solve response echoes a request-scoped
    trace_id, and the same solve report — phase spans included — is
    retrievable from the running server via GET /debug/solves/<id>."""
    status, body = post(server_url, _tpu_payload("tr."))
    assert status == 200, body
    tid = body.get("trace_id")
    assert tid, body
    assert body["report"].get("solver_trace_id") == tid
    with urllib.request.urlopen(
        server_url + f"/debug/solves/{tid}", timeout=30
    ) as r:
        rep = json.loads(r.read())
    assert rep["trace_id"] == tid
    names = set(_span_names(rep["spans"]))
    assert {"bounds", "constructor", "seed", "ladder", "polish",
            "verify"} <= names, names
    assert rep["wall_s"] > 0 and rep["phases"]
    # the listing surfaces it, newest first
    with urllib.request.urlopen(
        server_url + "/debug/solves", timeout=30
    ) as r:
        ids = json.loads(r.read())["trace_ids"]
    assert tid in ids
    # unknown IDs are a structured 404
    try:
        urllib.request.urlopen(
            server_url + "/debug/solves/nosuchtrace", timeout=30
        )
        status = 200
    except urllib.error.HTTPError as e:
        status = e.code
    assert status == 404


def test_submit_non_tpu_solver_also_traced(server_url):
    """Request traces are solver-agnostic: a milp solve still gets a
    trace_id and a retrievable (engine-phase-free) report."""
    status, body = post(server_url, {
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
        "solver": "milp",
    })
    assert status == 200, body
    tid = body.get("trace_id")
    assert tid
    with urllib.request.urlopen(
        server_url + f"/debug/solves/{tid}", timeout=30
    ) as r:
        rep = json.loads(r.read())
    assert rep["spans"]["attrs"]["solver"] == "milp"


def test_submit_no_trace_when_disabled(monkeypatch):
    from kafka_assignment_optimizer_tpu import serve as srv_mod

    monkeypatch.setitem(srv_mod.OBS, "trace", False)
    out = handle_submit({
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
        "solver": "milp",
    })
    assert "trace_id" not in out
    assert "solver_trace_id" not in out["report"]


def test_coalesced_batch_members_keep_own_traces(monkeypatch):
    """ISSUE 15 satellite (the PR 3 shared-ID fix): every member of a
    coalesced dispatch echoes its OWN trace_id, each ID resolves in
    the report ring as a stub linking to the shared batch report via
    coalesced_into, and the batch report (its own fresh ID) carries
    the real span tree — so a router-propagated trace never aliases
    two clients onto one trace."""
    from kafka_assignment_optimizer_tpu import serve as srv_mod

    monkeypatch.setattr(srv_mod._Coalescer, "should_bypass",
                        lambda self, key: False)
    monkeypatch.setattr(srv_mod._COALESCER, "window_s", 0.25)
    monkeypatch.setattr(srv_mod._COALESCER, "max_batch", 4)
    results: list = [None, None]

    def run(i):
        payload = _tpu_payload()
        payload["options"] = dict(payload["options"], seed=i)
        results[i] = handle_submit(payload, lock_wait_s=30.0)

    threads = [threading.Thread(target=run, args=(i,)) for i in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive()
    tids = {out.get("trace_id") for out in results}
    assert len(tids) == 2 and None not in tids, tids
    batch_ids = {out.get("coalesced_into") for out in results}
    assert len(batch_ids) == 1 and None not in batch_ids
    batch_id = batch_ids.pop()
    assert batch_id not in tids  # the batch trace has its OWN id
    from kafka_assignment_optimizer_tpu.obs import trace as otrace

    # the batch report carries the real span tree + the member links
    rep = otrace.RECENT.get(batch_id)
    assert rep is not None and rep["name"] == "request_batch"
    names = set(_span_names(rep["spans"]))
    assert {"seed", "ladder", "verify"} <= names, names
    members = set(
        rep["spans"]["attrs"]["coalesced_members"].split(","))
    assert members == tids
    # every member's OWN id resolves to a stub linking back
    for out in results:
        stub = otrace.RECENT.get(out["trace_id"])
        assert stub is not None, out["trace_id"]
        assert stub["coalesced_into"] == batch_id
        assert stub["spans"]["attrs"]["coalesced_into"] == batch_id


def test_healthz_observability_section(server_url):
    with urllib.request.urlopen(server_url + "/healthz", timeout=30) as r:
        body = json.loads(r.read())
    obs = body["observability"]
    assert obs["trace_enabled"] is True
    assert obs["report_ring_capacity"] >= 1
    assert obs["solve_reports_held"] >= 0


def test_metrics_phase_histogram_renders(server_url):
    """After a traced solve, /metrics carries the per-phase latency
    histogram family with HELP/TYPE pairs."""
    status, _ = post(server_url, _tpu_payload())
    assert status == 200
    with urllib.request.urlopen(server_url + "/metrics", timeout=30) as r:
        text = r.read().decode()
    assert "# TYPE kao_phase_seconds histogram" in text
    assert 'kao_phase_seconds_bucket{phase="ladder"' in text or (
        'kao_phase_seconds_bucket{phase="constructor"' in text
    )
    assert "# HELP kao_requests_total" in text
