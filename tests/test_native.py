"""Native C++ branch-and-bound backend tests (SURVEY.md §4.4 parity).

The native solver plays lp_solve's role for the reference
(``/root/reference/README.md:135-137``): the exact solve. Exactness is
asserted against the independent HiGHS MILP oracle — same objective on the
demo and on random clusters — plus golden move count and time-limit
behavior."""

import numpy as np
import pytest

from kafka_assignment_optimizer_tpu import build_instance, optimize
from kafka_assignment_optimizer_tpu.solvers.base import get_solver

from tests.test_tpu_engine import random_cluster


def test_native_demo_golden(demo):
    current, brokers, topo = demo
    res = optimize(current, brokers, topo, solver="native")
    rep = res.report()
    assert rep["feasible"], rep
    assert res.solve.optimal
    assert res.replica_moves == 1  # README.md:85-91 known optimum
    assert res.solve.objective == res.instance.max_weight()


@pytest.mark.parametrize("case", [
    dict(n_brokers=8, n_parts=12, rf=2, n_racks=2, drop=1),
    dict(n_brokers=9, n_parts=10, rf=3, n_racks=3, drop=0),
    dict(n_brokers=12, n_parts=18, rf=2, n_racks=4, drop=2),
    dict(n_brokers=6, n_parts=8, rf=1, n_racks=2, drop=1),  # RF=1 edge
    dict(n_brokers=10, n_parts=7, rf=4, n_racks=2, drop=1),
])
def test_native_matches_milp_oracle(case, rng):
    """Exactness: independent exact backends must agree on the optimum."""
    current, brokers, topo = random_cluster(rng, **case)
    inst = build_instance(current, brokers, topo)
    nat = get_solver("native")(inst)
    ilp = get_solver("milp")(inst)
    assert nat.optimal and ilp.optimal
    assert inst.is_feasible(nat.a), inst.violations(nat.a)
    assert nat.objective == inst.preservation_weight(nat.a)
    assert nat.objective == ilp.objective


def test_native_objective_is_exact_recount(rng):
    current, brokers, topo = random_cluster(rng, 8, 10, 2, 2, drop=1)
    inst = build_instance(current, brokers, topo)
    res = get_solver("native")(inst)
    assert res.objective == inst.preservation_weight(res.a)
    assert res.a.shape == (inst.num_parts, inst.max_rf)
    assert res.a.dtype == np.int32


def test_native_time_limit(rng):
    """A too-small budget must return cleanly: either a (possibly
    suboptimal) incumbent or a diagnosable no-solution error."""
    current, brokers, topo = random_cluster(rng, 24, 120, 3, 4, drop=2)
    inst = build_instance(current, brokers, topo)
    try:
        res = get_solver("native")(inst, time_limit_s=0.05)
    except RuntimeError as e:
        assert "no solution" in str(e)
    else:
        assert inst.is_feasible(res.a) or not res.optimal
