"""Fleet telemetry plane (ISSUE 13, docs/OBSERVABILITY.md "Fleet
plane"): worker identity + per-worker seq stamping, the live
/debug/stream fan-out with slow-client shedding, the kao-fleet merge
(ordering, dedup-on-(worker,seq), torn tails, mid-merge rotation,
fleet burn-rate equality with the single-stream engine), the
rotation-surviving --follow tail, the device-occupancy sampler's
overhead budget, and the EWMA/Page-Hinkley drift alarms."""

import json
import subprocess
import sys
import threading
import time
import urllib.request
from pathlib import Path

import pytest

from kafka_assignment_optimizer_tpu.obs import drift as odrift
from kafka_assignment_optimizer_tpu.obs import fleet as ofleet
from kafka_assignment_optimizer_tpu.obs import flight as oflight
from kafka_assignment_optimizer_tpu.obs import sampler as osampler
from kafka_assignment_optimizer_tpu.obs import slo as oslo
from kafka_assignment_optimizer_tpu.obs import trace as otrace

REPO = Path(__file__).resolve().parent.parent


def _rec(worker: str, seq: int, ts: float, wall_s: float = 0.1,
         certified: bool = True, kind: str = "solve") -> dict:
    return {
        "ts": ts, "kind": kind, "wall_s": wall_s, "seq": seq,
        "worker": {"host": worker, "pid": 1, "port": 8787,
                   "boot": worker},
        "quality": {"feasible": True, "certified": certified},
    }


# --------------------------------------------------------------------------
# worker identity + seq stamping (satellite 1)
# --------------------------------------------------------------------------


def test_records_stamped_with_worker_identity_and_monotonic_seq():
    oflight.reset_recent()
    oflight.record({"ts": time.time(), "kind": "solve", "wall_s": 0.1,
                    "quality": {"feasible": True, "certified": True}})
    oflight.record({"ts": time.time(), "kind": "solve", "wall_s": 0.1,
                    "quality": {"feasible": True, "certified": True}})
    a, b = oflight.recent()[-2:]
    for r in (a, b):
        w = r["worker"]
        assert w["host"] and isinstance(w["pid"], int) and w["boot"]
        assert "port" in w  # None until serve binds; key always present
    assert b["seq"] == a["seq"] + 1
    # the merge key is stable and boot-scoped
    assert oflight.worker_key(a) == oflight.worker_key(b)
    assert oflight.worker_key({}) == "legacy"


def test_failure_records_carry_worker_and_seq_too():
    # record_failure funnels through record(), so an outage burns the
    # fleet ledger with the same merge key as healthy records
    oflight.reset_recent()
    rec = oflight.record_failure(None, None, 0.5, RuntimeError("boom"))
    assert rec["worker"]["host"] and isinstance(rec["seq"], int)
    assert rec["quality"]["feasible"] is False


# --------------------------------------------------------------------------
# live-stream fan-out (tentpole 1)
# --------------------------------------------------------------------------


def test_stream_subscriber_bounded_queue_sheds_slow_client():
    client = oflight.subscribe(maxlen=3)
    try:
        before = oflight.stream_stats()["dropped_total"]
        for i in range(8):
            oflight.record({"ts": time.time(), "kind": "solve",
                            "wall_s": 0.1, "i": i,
                            "quality": {"feasible": True,
                                        "certified": True}})
        # the slow client keeps the OLDEST 3 it could queue; the rest
        # dropped for it alone and counted
        assert client.dropped_total == 5
        assert oflight.stream_stats()["dropped_total"] - before == 5
        got = [client.get(timeout=1.0)["i"] for _ in range(3)]
        assert got == [0, 1, 2]
    finally:
        oflight.unsubscribe(client)
    assert oflight.stream_stats()["clients"] == 0


def test_stream_subscriber_cap():
    clients = [oflight.subscribe() for _ in range(
        oflight.MAX_STREAM_CLIENTS - oflight.stream_stats()["clients"]
    )]
    try:
        with pytest.raises(RuntimeError):
            oflight.subscribe()
    finally:
        for c in clients:
            oflight.unsubscribe(c)


def test_http_stream_snapshot_follow_and_fleet_endpoint():
    """/debug/stream serves NDJSON (snapshot + live follow, with the
    tail/live dedup), and /debug/fleet serves the merged view."""
    from kafka_assignment_optimizer_tpu.serve import make_server

    oflight.reset_recent()
    s = make_server(port=0)
    t = threading.Thread(target=s.serve_forever, daemon=True)
    t.start()
    port = s.server_address[1]
    base = f"http://127.0.0.1:{port}"
    try:
        for i in range(5):
            oflight.record({"ts": time.time(), "kind": "solve",
                            "wall_s": 0.1, "i": i,
                            "quality": {"feasible": True,
                                        "certified": True}})
        # snapshot mode: dump the tail, close, correct content type
        with urllib.request.urlopen(
            base + "/debug/stream?follow=0&tail=512", timeout=30
        ) as resp:
            assert resp.headers.get("Content-Type") == \
                "application/x-ndjson"
            lines = [json.loads(x)
                     for x in resp.read().decode().splitlines()
                     if x.strip()]
        assert [r["i"] for r in lines] == [0, 1, 2, 3, 4]
        assert all(isinstance(r.get("seq"), int) for r in lines)
        # live follow: every record a concurrent "solve" lands arrives
        got: list = []
        started = threading.Event()

        def reader():
            req = urllib.request.urlopen(
                base + "/debug/stream", timeout=30
            )
            started.set()
            for raw in req:
                line = raw.decode().strip()
                if not line:
                    continue  # heartbeat
                got.append(json.loads(line))
                if len(got) >= 3:
                    req.close()
                    return

        rt = threading.Thread(target=reader, daemon=True)
        rt.start()
        assert started.wait(10)
        time.sleep(0.2)  # let the subscriber register server-side
        for i in range(3):
            oflight.record({"ts": time.time(), "kind": "solve",
                            "wall_s": 0.1, "live": i,
                            "quality": {"feasible": True,
                                        "certified": True}})
        rt.join(timeout=15)
        assert [r["live"] for r in got] == [0, 1, 2]
        # the merged self-view: one worker, all eight records
        with urllib.request.urlopen(base + "/debug/fleet",
                                    timeout=30) as resp:
            assert resp.headers.get("Content-Type") == "application/json"
            view = json.loads(resp.read())
        assert view["workers"] == 1
        assert view["records"] == 8
        assert view["peers"] == []
        wkey = next(iter(view["per_worker"]))
        assert view["per_worker"][wkey]["seq_gaps"] == 0
    finally:
        s.shutdown()
        s.server_close()


# --------------------------------------------------------------------------
# fleet merge (tentpole 2 + satellite test coverage)
# --------------------------------------------------------------------------


def _write_worker_dir(tmp_path, name: str, records: list,
                      max_bytes: int = 1 << 20) -> str:
    d = str(tmp_path / name)
    rec = oflight.FlightRecorder()
    rec.configure(d, max_bytes=max_bytes, max_files=64)
    for r in records:
        rec.write(r)
    return d


def test_fleet_merge_three_dirs_interleaved_torn_and_duplicated(
        tmp_path):
    """3 synthetic worker dirs with interleaved (and skewed)
    timestamps, one torn kill-9 tail, and duplicated (worker, seq)
    rows: the merge orders per-worker by seq, across workers by ts,
    dedups, and reports per-worker coverage."""
    # worker a: healthy, ts interleaves with b's
    a = [_rec("a", i + 1, 100.0 + 2 * i) for i in range(10)]
    # worker b: clock skewed BACKWARD mid-stream (seq must still rule
    # within the worker)
    b = [_rec("b", i + 1, 101.0 + 2 * i) for i in range(10)]
    b[6]["ts"] = b[4]["ts"] - 0.5  # skew: older ts, newer seq
    # worker c: will get a torn tail
    c = [_rec("c", i + 1, 150.0 + i) for i in range(5)]
    da = _write_worker_dir(tmp_path, "a", a)
    db = _write_worker_dir(tmp_path, "b", b)
    dc = _write_worker_dir(tmp_path, "c", c)
    with open(Path(dc) / "flight.jsonl", "a") as fh:
        fh.write('{"ts": 999, "seq": 6, "torn')  # the kill -9 tail
    # a duplicated source: worker a's dir read twice (live snapshot +
    # archive overlap is the production shape) — dedup on (worker, seq)
    sources = [
        (da, list(oflight.iter_records(da))),
        (db, list(oflight.iter_records(db))),
        (dc, list(oflight.iter_records(dc))),
        (da + "-again", list(oflight.iter_records(da))),
    ]
    merged, per_worker, dups = ofleet.merge_sources(sources)
    assert len(merged) == 25  # 10 + 10 + 5; torn tail skipped
    assert dups == 10         # the duplicated a-dir fully deduped
    assert set(per_worker) == {"a:1:a", "b:1:b", "c:1:c"}
    for info in per_worker.values():
        assert info["seq_gaps"] == 0
    # per-worker seq order survives the skew: b's records appear in
    # seq order even though b[6].ts < b[5].ts
    b_seqs = [r["seq"] for r in merged
              if oflight.worker_key(r) == "b:1:b"]
    assert b_seqs == list(range(1, 11))
    # cross-worker ordering approximates ts: the merged stream's ts is
    # sorted up to the one deliberate intra-worker skew
    ts = [r["ts"] for r in merged]
    unsorted_pairs = sum(1 for x, y in zip(ts, ts[1:]) if y < x)
    assert unsorted_pairs <= 2


def test_fleet_burn_rates_equal_single_engine_on_concatenated_input(
        tmp_path):
    """Acceptance: kao-fleet's fleet-wide burn rates over >= 2 worker
    dirs reproduce the single-process SLO engine's numbers on the
    concatenated input, class for class and window for window."""
    now = 10_000.0
    recs = []
    for w in ("w1", "w2", "w3"):
        for i in range(20):
            # a mix of fast/slow and certified/not, spread so the tail
            # lands inside the 5m window and everything inside 1h —
            # both burn windows exercise real counts
            wall = 8.0 if (i % 5 == 0 and w == "w2") else 0.2
            certified = not (i % 7 == 0 and w == "w3")
            kind = "delta" if i % 3 == 0 else "solve"
            recs.append(_rec(w, i + 1,
                             now - 3500 + i * 180.0
                             + {"w1": 0, "w2": 0.3, "w3": 0.7}[w],
                             wall_s=wall, certified=certified,
                             kind=kind))
    dirs = {}
    for w in ("w1", "w2", "w3"):
        dirs[w] = _write_worker_dir(
            tmp_path, w,
            [r for r in recs if r["worker"]["host"] == w])
    # reference: ONE engine fed the concatenated input
    ref = oslo.SLOEngine()
    for r in recs:
        ref.observe_record(r)
    ref_snap = ref.snapshot(now=now)
    view = ofleet.build_view(
        [(d, list(oflight.iter_records(d))) for d in dirs.values()],
        now=now,
    )
    assert view["workers"] == 3
    fleet_snap = view["slo"]
    assert fleet_snap["classes"].keys() == ref_snap["classes"].keys()
    for cls, ref_cls in ref_snap["classes"].items():
        got_cls = fleet_snap["classes"][cls]
        assert got_cls["events_total"] == ref_cls["events_total"]
        assert (got_cls["latency_breaches_total"]
                == ref_cls["latency_breaches_total"])
        assert (got_cls["quality_breaches_total"]
                == ref_cls["quality_breaches_total"])
        assert got_cls["status"] == ref_cls["status"]
        for win, ref_w in ref_cls["windows"].items():
            assert got_cls["windows"][win] == ref_w, (cls, win)


def test_fleet_merge_tolerates_mid_merge_rotation(tmp_path):
    """A merge racing the writer's rotation path: every record lands
    exactly once in the final merge, across several rotations."""
    d = str(tmp_path / "w")
    rec = oflight.FlightRecorder()
    rec.configure(d, max_bytes=4096, max_files=64)
    stop = threading.Event()
    mid_merges = []

    def merge_loop():
        while not stop.is_set():
            mid_merges.append(
                ofleet.merge_sources([(d, oflight.iter_records(d))])
            )
            time.sleep(0.01)

    t = threading.Thread(target=merge_loop, daemon=True)
    t.start()
    for i in range(200):
        rec.write(_rec("w", i + 1, 100.0 + i, wall_s=0.1))
    stop.set()
    t.join(timeout=10)
    assert rec.snapshot()["rotations_total"] >= 2
    merged, per_worker, dups = ofleet.merge_sources(
        [(d, oflight.iter_records(d))]
    )
    assert [r["seq"] for r in merged] == list(range(1, 201))
    assert dups == 0
    assert per_worker["w:1:w"]["seq_gaps"] == 0
    # every mid-rotation merge saw an internally consistent prefix:
    # no duplicates, seqs strictly increasing
    for m_recs, _pw, m_dups in mid_merges:
        seqs = [r["seq"] for r in m_recs]
        assert m_dups == 0
        assert seqs == sorted(set(seqs))


def test_kao_fleet_cli_json_and_metrics(tmp_path):
    """The kao-fleet console entry over real dirs: the JSON view and
    an exposition-valid metrics rendering (kao_fleet_* + kao_slo_* +
    kao_drift_*)."""
    from tests.test_metrics_format import validate_prometheus

    now = time.time()
    d1 = _write_worker_dir(
        tmp_path, "w1", [_rec("w1", i + 1, now - 60 + i)
                         for i in range(12)])
    d2 = _write_worker_dir(
        tmp_path, "w2", [_rec("w2", i + 1, now - 59.5 + i)
                         for i in range(12)])
    r = subprocess.run(
        [sys.executable, "-m",
         "kafka_assignment_optimizer_tpu.obs.fleet", d1, d2,
         "--format", "json"],
        capture_output=True, text=True, timeout=120, cwd=str(REPO),
    )
    assert r.returncode == 0, r.stderr
    view = json.loads(r.stdout)
    assert view["workers"] == 2
    assert view["records"] == 24
    assert view["duplicates_dropped"] == 0
    r = subprocess.run(
        [sys.executable, "-m",
         "kafka_assignment_optimizer_tpu.obs.fleet", d1, d2,
         "--format", "metrics"],
        capture_output=True, text=True, timeout=120, cwd=str(REPO),
    )
    assert r.returncode == 0, r.stderr
    samples = validate_prometheus(r.stdout)
    names = {n for n, _ in samples}
    assert {"kao_fleet_workers", "kao_fleet_records",
            "kao_slo_events_total", "kao_slo_burn_rate",
            "kao_drift_alarms_total"} <= names
    assert ("kao_fleet_workers", ()) in samples
    workers = next(ln for ln in r.stdout.splitlines()
                   if ln.startswith("kao_fleet_workers "))
    assert workers.endswith(" 2")
    # an unreadable source is an error + exit 3 when nothing merges
    r = subprocess.run(
        [sys.executable, "-m",
         "kafka_assignment_optimizer_tpu.obs.fleet",
         str(tmp_path / "nope")],
        capture_output=True, text=True, timeout=120, cwd=str(REPO),
    )
    assert r.returncode == 3


# --------------------------------------------------------------------------
# kao-trace flight --follow (satellite 2)
# --------------------------------------------------------------------------


def test_follow_records_survives_rotation_never_double_reads(tmp_path):
    d = str(tmp_path)
    rec = oflight.FlightRecorder()
    rec.configure(d, max_bytes=4096, max_files=64)
    got: list = []
    stop = threading.Event()

    def run():
        for r in oflight.follow_records(d, poll_s=0.01,
                                        stop=stop.is_set):
            got.append(r["i"])

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(0.2)
    for i in range(300):
        rec.write({"i": i, "pad": "x" * 60})
    deadline = time.time() + 60
    while len(got) < 300 and time.time() < deadline:
        time.sleep(0.05)
    stop.set()
    t.join(timeout=10)
    assert rec.snapshot()["rotations_total"] >= 2
    # exactly once, in order, across every rotation
    assert got == list(range(300))


def test_follow_buffers_torn_partial_line(tmp_path):
    live = tmp_path / "flight.jsonl"
    live.write_text("")
    got: list = []
    stop = threading.Event()

    def run():
        for r in oflight.follow_records(str(tmp_path), poll_s=0.01,
                                        stop=stop.is_set):
            got.append(r)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(0.1)
    with open(live, "a") as fh:
        fh.write('{"i": 1}\n{"i": 2, "pa')  # torn mid-record
        fh.flush()
        time.sleep(0.3)
        assert [r["i"] for r in got] == [1]  # the torn half waits
        fh.write('d": "x"}\n')               # the newline lands
        fh.flush()
    deadline = time.time() + 10
    while len(got) < 2 and time.time() < deadline:
        time.sleep(0.02)
    stop.set()
    t.join(timeout=5)
    assert [r["i"] for r in got] == [1, 2]


def test_snapshot_then_follow_is_gap_free_across_rotation(tmp_path):
    """The --tail --follow handoff: records landing BETWEEN the
    snapshot and the follow's first read — including across a rotation
    in that window — are delivered exactly once."""
    d = str(tmp_path)
    rec = oflight.FlightRecorder()
    rec.configure(d, max_bytes=4096, max_files=64)
    for i in range(120):  # history spanning at least one rotation
        rec.write({"i": i, "pad": "x" * 60})
    assert rec.snapshot()["rotations_total"] >= 1
    history, resume = oflight.snapshot_records(d)
    assert [r["i"] for r in history] == list(range(120))
    # the gap window: more records land (forcing another rotation)
    # BEFORE the follow starts
    for i in range(120, 240):
        rec.write({"i": i, "pad": "x" * 60})
    got: list = []
    stop = threading.Event()

    def run():
        for r in oflight.follow_records(d, poll_s=0.01,
                                        stop=stop.is_set,
                                        resume=resume):
            got.append(r["i"])

    t = threading.Thread(target=run, daemon=True)
    t.start()
    for i in range(240, 300):  # and more while following
        rec.write({"i": i, "pad": "x" * 60})
    deadline = time.time() + 60
    while len(got) < 180 and time.time() < deadline:
        time.sleep(0.05)
    stop.set()
    t.join(timeout=10)
    # exactly the post-snapshot records, in order, none twice
    assert got == list(range(120, 300))


def test_kao_trace_flight_follow_cli(tmp_path):
    """kao-trace flight --follow --max N: prints records (with their
    worker/seq stamps) as they land, exits after N."""
    d = str(tmp_path)
    rec = oflight.FlightRecorder()
    rec.configure(d)
    rec.write(_rec("pre", 1, 1.0))  # history: must NOT print (tail -f)
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "kafka_assignment_optimizer_tpu.obs.trace_cli", "flight", d,
         "--follow", "--max", "3"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=str(REPO),
    )
    try:
        # keep landing records until the follower has seen its 3 and
        # exited — robust to slow subprocess startup on this container
        seq = 2
        deadline = time.time() + 120
        while proc.poll() is None and time.time() < deadline:
            rec.write(_rec("w", seq, float(seq)))
            seq += 1
            time.sleep(0.2)
        out, err = proc.communicate(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, err
    lines = [json.loads(x) for x in out.splitlines() if x.strip()]
    assert len(lines) == 3  # --max honored
    seqs = [r["seq"] for r in lines]
    # strictly increasing, never the pre-follow history record
    assert seqs == sorted(set(seqs)) and seqs[0] >= 2
    # the worker identity stamp prints with each record (satellite 1)
    assert all(r["worker"]["host"] == "w" for r in lines)


# --------------------------------------------------------------------------
# drift alarms (tentpole 4)
# --------------------------------------------------------------------------


def test_drift_trips_on_sustained_p99_step_not_on_stable_stream():
    mon = odrift.DriftMonitor()
    tripped = []
    for i in range(60):
        tripped += mon.observe_record(
            _rec("w", i + 1, float(i), wall_s=0.1))
    assert tripped == []  # stable stream: silent
    for i in range(60):
        tripped += mon.observe_record(
            _rec("w", 61 + i, 60.0 + i, wall_s=1.0))
    assert "p99" in tripped  # a 10x sustained step trips
    snap = mon.snapshot()
    assert snap["alarms_total"] >= 1
    alarm = snap["classes"]["solve"]["p99"]["last_alarm"]
    assert alarm["value"] == pytest.approx(1.0)


def test_drift_single_outlier_immunity():
    """One 2x outlier rides the rolling p99 for a full window but must
    NOT trip — the strided updates bound its contribution below lam."""
    mon = odrift.DriftMonitor()
    tripped = []
    for i in range(64):
        wall = 0.2 if i != 40 else 0.4  # one 2x outlier
        tripped += mon.observe_record(
            _rec("w", i + 1, float(i), wall_s=wall))
    assert tripped == []
    assert mon.snapshot()["alarms_total"] == 0


def test_drift_trips_on_certify_rate_drop():
    mon = odrift.DriftMonitor()
    tripped = []
    for i in range(60):
        tripped += mon.observe_record(
            _rec("w", i + 1, float(i), certified=True))
    assert tripped == []
    for i in range(60):
        tripped += mon.observe_record(
            _rec("w", 61 + i, 60.0 + i, certified=False))
    assert "certify_rate" in tripped
    # the latency signal stayed silent: walls never moved
    assert "p99" not in tripped


def test_drift_mark_lands_in_active_trace_and_rearms():
    mon = odrift.DriftMonitor()
    for i in range(40):
        mon.observe_record(_rec("w", i + 1, float(i), wall_s=0.1))
    tr = otrace.begin(True, name="drift_probe")
    try:
        tripped = []
        for i in range(60):
            tripped += mon.observe_record(
                _rec("w", 41 + i, 40.0 + i, wall_s=2.0))
        assert "p99" in tripped
    finally:
        rep = otrace.finish(tr)
    marks = [s for s in rep["spans"]["spans"]
             if s["name"] == "drift"]
    assert marks and marks[0]["attrs"]["signal"] == "p99"
    assert marks[0]["wall_s"] == 0.0  # zero-duration mark
    # after the alarm the detector re-baselines at the new level: the
    # SAME level does not re-trip (one regression = one alarm)
    before = mon.snapshot()["alarms_total"]
    for i in range(40):
        mon.observe_record(_rec("w", 101 + i, 100.0 + i, wall_s=2.0))
    assert mon.snapshot()["alarms_total"] == before


def test_drift_families_on_metrics_and_debug_slo():
    from kafka_assignment_optimizer_tpu import serve as srv
    from tests.test_metrics_format import validate_prometheus

    # drive the PROCESS monitor through the real record funnel
    odrift.MONITOR.reset()
    for i in range(40):
        oflight.record({"ts": time.time(), "kind": "solve",
                        "wall_s": 0.1,
                        "quality": {"feasible": True,
                                    "certified": True}})
    text = srv.render_metrics()
    samples = validate_prometheus(text)
    names = {n for n, _ in samples}
    assert {"kao_drift_alarms_total", "kao_drift_ph",
            "kao_stream_clients", "kao_stream_dropped_total",
            "kao_device_duty_cycle",
            "kao_device_sampler_samples_total"} <= names
    assert any(
        n == "kao_drift_alarms_total"
        and ("class", "solve") in labels and ("signal", "p99") in labels
        for n, labels in samples
    )
    slo = srv.handle_debug_slo()
    assert "drift" in slo
    assert "solve" in slo["drift"]["classes"]
    assert slo["drift"]["signals"] == ["p99", "certify_rate"]


# --------------------------------------------------------------------------
# device-occupancy sampler (tentpole 3)
# --------------------------------------------------------------------------


def test_sampler_overhead_budget_and_duty_cycle():
    """The acceptance budget, measured: per-tick cost far under the
    <1%-at-1Hz envelope (10 ms/tick == 1%); the duty cycle derives
    from the flight duty accumulator; stop() is clean."""
    s = osampler.DeviceSampler()
    s.configure(50.0)
    try:
        deadline = time.time() + 10
        while s.snapshot()["samples_total"] < 5 \
                and time.time() < deadline:
            time.sleep(0.05)
        # land a record claiming heavy device time: the next ticks'
        # duty-cycle delta must pick it up
        oflight.record({
            "ts": time.time(), "kind": "solve", "wall_s": 2.0,
            "split": {"compile_s": 0.0, "device_s": 1.5,
                      "dispatch_s": 0.1, "host_s": 0.4},
            "quality": {"feasible": True, "certified": True},
        })
        deadline = time.time() + 10
        while s.snapshot()["duty_cycle"] == 0.0 \
                and time.time() < deadline:
            time.sleep(0.05)
        snap = s.snapshot()
    finally:
        s.stop()
    assert snap["enabled"] == 1
    assert snap["samples_total"] >= 5
    assert snap["avg_sample_s"] < 0.010, snap  # 10 ms/tick == 1% @ 1Hz
    assert snap["duty_cycle"] > 0.0
    assert snap["hz"] == 50.0
    # roofline summary: the record above lands in a bucket row
    assert any(row["device_frac"] > 0
               for row in snap["roofline"].values())
    assert osampler.SAMPLER.snapshot()["enabled"] == 0  # global: off


def test_sampler_disabled_is_inert_and_healthz_has_devices_section():
    from kafka_assignment_optimizer_tpu import serve as srv

    snap = osampler.SAMPLER.snapshot()
    assert snap["enabled"] == 0 and snap["hz"] == 0.0
    h = srv.handle_healthz()
    assert "devices" in h
    assert h["devices"]["enabled"] == 0
    assert "duty_cycle" in h["devices"]
    # the fleet identity rides /healthz observability
    assert h["observability"]["worker"]["host"]
    assert h["observability"]["fleet_peers"] == []
