"""Vectorized-constructor parity pins (ISSUE 10, docs/CONSTRUCTOR.md).

The host constructor path — greedy placement, the aggregated-MILP
disaggregation, the flow/LP bounds assembly, and the exact leader
reseat — was rewritten from per-partition Python loops into vectorized
numpy behind the swappable implementation registry
(``solvers.tpu.constructor``). The legacy path stays in the tree as the
ORACLE; these tests pin the vectorized default against it on the demo,
decommission, growth (rf_change), and adversarial fixtures:

- greedy seeds are the SAME PLAN bit-for-bit (the vectorized repair
  makes identical decisions by construction — same donor order, same
  recipient lexsort, same BFS scan order);
- the aggregated disaggregation realizes the same kept counts at the
  same preservation weight (class partitions are exchangeable, so the
  realizations may differ per partition but never in rank);
- flow bounds are bit-equal across implementations;
- the legacy path remains selectable (env + setter) and the solve
  stats say which implementation served.
"""

from __future__ import annotations

import numpy as np
import pytest

from kafka_assignment_optimizer_tpu.models.cluster import (
    demo_assignment,
    demo_broker_list,
    demo_topology,
)
from kafka_assignment_optimizer_tpu.models.instance import build_instance
from kafka_assignment_optimizer_tpu.solvers.tpu import constructor
from kafka_assignment_optimizer_tpu.solvers.tpu.seed import greedy_seed
from kafka_assignment_optimizer_tpu.utils import gen

FIXTURES = ("decommission", "rf_change", "adversarial", "scale_out",
            "leader_only", "adv50k")


def _fixture(name: str):
    if name == "demo":
        return build_instance(
            demo_assignment(), demo_broker_list(), demo_topology()
        )
    sc = gen.SCENARIOS[name](**gen.SMOKE_KWARGS[name])
    return build_instance(
        sc.current, sc.broker_list, sc.topology,
        target_rf=sc.kwargs.get("target_rf"),
    )


@pytest.fixture(autouse=True)
def _restore_impl():
    prev = constructor.active()
    yield
    constructor.set_impl(prev)


# ------------------------------------------------------------- registry


def test_registry_default_and_setter():
    assert constructor.active() in constructor.IMPLS
    prev = constructor.set_impl("legacy")
    assert constructor.active() == "legacy"
    constructor.set_impl(prev)
    with pytest.raises(ValueError):
        constructor.set_impl("typo")


def test_solve_stats_name_the_implementation(demo):
    from kafka_assignment_optimizer_tpu import optimize

    current, brokers, topo = demo
    res = optimize(current, brokers, topo, solver="tpu")
    assert res.solve.stats["constructor_impl"] == constructor.active()


# ---------------------------------------------------------- greedy seed


@pytest.mark.parametrize("name", ("demo",) + FIXTURES)
def test_greedy_seed_parity(name):
    """Vectorized greedy == legacy greedy, plan-for-plan, on every
    fixture family (same plan is the strongest rank tie) — and the
    plan is oracle-verified feasible wherever the legacy one is."""
    inst_l = _fixture(name)
    inst_v = _fixture(name)
    a_legacy = greedy_seed(inst_l, impl="legacy")
    a_vec = greedy_seed(inst_v, impl="vec")
    assert np.array_equal(a_legacy, a_vec), name
    if inst_l.is_feasible(a_legacy):
        assert inst_v.is_feasible(a_vec)


def test_greedy_seed_parity_scrambled_growth(rng):
    """A shuffled mixed-RF cluster under an RF bump: nulls, diversity,
    band and leader repairs all fire — the adversarial composition for
    the repair machinery — and the implementations still agree."""
    sc = gen.adversarial(n_brokers=32, n_racks=4, n_topics_low=6,
                         n_topics_high=6, parts_per_topic=10, seed=1)
    kw = dict(target_rf=4)
    inst_l = build_instance(sc.current, sc.broker_list, sc.topology, **kw)
    inst_v = build_instance(sc.current, sc.broker_list, sc.topology, **kw)
    a_legacy = greedy_seed(inst_l, impl="legacy")
    a_vec = greedy_seed(inst_v, impl="vec")
    assert np.array_equal(a_legacy, a_vec)


# ------------------------------------------------------- disaggregation


def test_disaggregate_parity_same_counts_and_weight():
    """Both realizations of the aggregated MILP counts keep the same
    number of slots at the same preservation weight (partitions within
    a class are exchangeable, so per-partition choices may differ but
    totals may not)."""
    from kafka_assignment_optimizer_tpu.solvers.lp_round import (
        _disaggregate,
    )

    inst = _fixture("decommission")
    agg = inst._kept_weight_agg(integer=True, return_solution=True)
    assert isinstance(agg, dict), "fixture no longer yields an aggregate"
    out = {}
    for impl in ("legacy", "vec"):
        constructor.set_impl(impl)
        d = _disaggregate(inst, agg)
        assert d is not None
        mr, mc = d["mrows"], d["mcols"]
        wl = inst.w_leader[mr, mc]
        wf = np.maximum(inst.w_follower[mr, mc], 0)
        out[impl] = (
            int(d["x"].sum()), int(d["y"].sum()),
            int((wf * d["x"]).sum() + (wl * d["y"]).sum()),
        )
        # structural sanity: at most one kept leader per partition,
        # never a member kept in both roles
        assert not (d["x"] & d["y"]).any()
        assert np.bincount(mr[d["y"]], minlength=inst.num_parts).max() <= 1
    assert out["legacy"] == out["vec"]


@pytest.mark.parametrize("name", ("scale_out", "leader_only",
                                  "rf_change", "decommission"))
def test_construct_parity_end_to_end(name):
    """``lp_round.construct`` under both implementations: same
    feasibility, same preservation weight, same move count — the
    constructor-rank parity the engine's final selection relies on."""
    from kafka_assignment_optimizer_tpu.solvers.lp_round import construct

    out = {}
    for impl in ("legacy", "vec"):
        constructor.set_impl(impl)
        inst = _fixture(name)  # fresh: no cross-impl memo sharing
        plan = construct(inst)
        assert plan is not None, (name, impl)
        out[impl] = (
            inst.is_feasible(plan),
            inst.preservation_weight(plan),
            inst.move_count(plan),
            getattr(inst, "_agg_weight_ub", None),
        )
    assert out["legacy"] == out["vec"], name


def test_lossless_lp_vertex_records_weight_bound():
    """A losslessly realized kept-replica LP vertex records its weight
    as a certificate bound (the ``_agg_weight_ub`` convention the
    aggregated MILP already used) so certify_optimal needs no second
    kept-LP solve — the ISSUE 10 duplicated-LP fix — and the recorded
    bound really is an upper bound: certification still holds."""
    from kafka_assignment_optimizer_tpu.solvers.lp_round import construct

    inst = _fixture("scale_out")
    plan = construct(inst)
    assert plan is not None
    ub = getattr(inst, "_agg_weight_ub", None)
    assert ub is not None
    assert inst.preservation_weight(plan) == ub
    assert inst.certify_optimal(plan, allow_tight=False)


# ---------------------------------------------------------- flow bounds


@pytest.mark.parametrize("name", ("decommission", "scale_out",
                                  "leader_only", "adversarial"))
def test_flow_bounds_bit_equal_across_impls(name):
    """The move/weight bound ladder is implementation-independent:
    bit-equal integers whichever constructor impl is active (the
    vectorized bounds assembly changed representation, not values)."""
    vals = {}
    for impl in ("legacy", "vec"):
        constructor.set_impl(impl)
        inst = _fixture(name)
        vals[impl] = (
            int(inst.move_lower_bound()),
            int(inst.move_lower_bound_exact()),
            int(inst.weight_upper_bound(level=0)),
            int(inst.weight_upper_bound(level=1)),
            int(inst.weight_upper_bound(level=2)),
        )
    assert vals["legacy"] == vals["vec"], name


# --------------------------------------------------------------- reseat


def test_reseat_racer_matches_lp_oracle():
    """The reseat racer's exact leader assignment (cycle canceller)
    still reaches the transportation-LP optimum on a scrambled-leader
    plan — the reseat half of the constructor parity pin."""
    inst = _fixture("leader_only")
    a = greedy_seed(inst)
    # scramble leaders: rotate each partition's slots so leader counts
    # leave the band and the repair phase must run
    rng = np.random.default_rng(3)
    a = a.copy()
    for p in range(inst.num_parts):
        r = int(inst.rf[p])
        if r > 1 and rng.random() < 0.5:
            a[p, :r] = np.roll(a[p, :r], 1)
    fast = inst.best_leader_assignment(a)
    oracle = inst._best_leader_lp(a)
    assert oracle is not None
    assert inst.preservation_weight(fast) == \
        inst.preservation_weight(oracle)
    # the reseat permutes slots only: replica sets untouched
    assert np.array_equal(np.sort(fast, axis=1), np.sort(a, axis=1))


# ------------------------------------------------------------ env wiring


def test_env_selects_legacy(monkeypatch):
    """KAO_CONSTRUCTOR=legacy selects the oracle implementation in a
    fresh process — the operator's no-redeploy fallback rung."""
    import subprocess
    import sys

    code = (
        "from kafka_assignment_optimizer_tpu.solvers.tpu import "
        "constructor as c; print(c.active())"
    )
    env = {"KAO_CONSTRUCTOR": "legacy", "JAX_PLATFORMS": "cpu",
           "PATH": "/usr/bin:/bin:/usr/local/bin"}
    import os
    env = {**os.environ, **env}
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-500:]
    assert out.stdout.strip() == "legacy"
