"""Optimality bounds + certificates (move lower bounds, weight upper
bounds, exact leader reseat) — the machinery behind the TPU engine's
``proved_optimal`` / early-stop (SURVEY.md §7 hard part 1: "matching
lp_solve's optimality").

Oracle: the exact MILP backend (``solvers/milp.py``), which solves the
same 0-1 model the reference hands to lp_solve
(``/root/reference/README.md:106-185``).
"""

from __future__ import annotations

import numpy as np
import pytest

from kafka_assignment_optimizer_tpu.api import optimize
from kafka_assignment_optimizer_tpu.models.instance import build_instance
from kafka_assignment_optimizer_tpu.utils import gen


def _inst(name, smoke=True):
    kw = gen.SMOKE_KWARGS[name] if smoke else {}
    sc = gen.SCENARIOS[name](**kw)
    return sc, build_instance(
        sc.current, sc.broker_list, sc.topology, target_rf=sc.target_rf
    )


@pytest.mark.parametrize("name", list(gen.SCENARIOS))
def test_move_lower_bound_matches_scenario_bounds(name):
    """The generic counting bound reproduces every hand-derived
    per-scenario bound of utils/gen.py at full size."""
    sc, inst = _inst(name, smoke=False)
    lb = inst.move_lower_bound()
    assert lb >= sc.min_moves_lb
    if sc.lb_tight:
        # the scenario bound is known achievable, so a stronger generic
        # bound would be unsound
        assert lb == sc.min_moves_lb


@pytest.mark.parametrize("name", ["demo", "decommission", "leader_only",
                                  "scale_out", "rf_change"])
def test_weight_upper_bound_vs_exact_milp(name):
    """Tiered weight UBs are valid (>= MILP optimum) and the tight tier
    is exact on every smoke BASELINE scenario."""
    sc, inst = _inst(name)
    r = optimize(solver="milp", **sc.kwargs)
    opt = r.solve.objective
    assert r.solve.optimal
    t0 = inst.weight_upper_bound()
    t1 = inst.weight_upper_bound(tight=True)
    assert t0 >= t1 >= opt
    assert t1 == opt, f"tight weight UB not exact on {name}"


@pytest.mark.parametrize("name", ["demo", "decommission", "scale_out"])
def test_move_lower_bound_exact_valid(name):
    """The max-flow bound never exceeds the moves of the exact
    weight-optimal plan (which, on these scenarios, is move-optimal)."""
    sc, inst = _inst(name)
    r = optimize(solver="milp", **sc.kwargs)
    assert inst.move_lower_bound_exact() <= r.replica_moves
    assert inst.move_lower_bound_exact() >= inst.move_lower_bound()


def test_certify_optimal_on_milp_solution():
    """The certificate recognizes an exact solver's plan as optimal on a
    scenario where both bounds are tight."""
    sc, inst = _inst("decommission")
    r = optimize(solver="milp", **sc.kwargs)
    assert inst.certify_optimal(r.solve.a)


def test_certificate_rejects_suboptimal():
    """A feasible but clearly suboptimal plan must NOT certify."""
    sc, inst = _inst("leader_only")
    # the identity plan is feasible for leader_only? — no: leadership is
    # skewed, so leader bands are violated; use the MILP plan but break
    # its weight by demoting every leader to a follower slot
    r = optimize(solver="milp", **sc.kwargs)
    a = np.asarray(r.solve.a).copy()
    a[:, [0, 1]] = a[:, [1, 0]]  # swap leader with first follower
    assert not inst.certify_optimal(a)


def test_best_leader_assignment_exact_on_leader_only():
    """With replica sets fixed, the transportation reseat reaches the
    exact optimum (this scenario's optimum moves no replicas at all)."""
    sc, inst = _inst("leader_only")
    r = optimize(solver="milp", **sc.kwargs)
    opt = r.solve.objective
    # start from the skewed CURRENT assignment (feasible replica sets,
    # infeasible/suboptimal leadership) and reseat exactly
    fixed = inst.best_leader_assignment(inst.a0)
    assert inst.is_feasible(fixed)
    assert inst.preservation_weight(fixed) == opt
    assert inst.move_count(fixed) == 0


def test_best_leader_assignment_never_regresses():
    """Reseat output is always >= input weight and preserves
    feasibility, on every smoke scenario's TPU plan."""
    for name in gen.SCENARIOS:
        sc, inst = _inst(name)
        r = optimize(solver="tpu", seed=1, **sc.kwargs)
        a = np.asarray(r.solve.a)
        out = inst.best_leader_assignment(a)
        assert inst.preservation_weight(out) >= inst.preservation_weight(a)
        if inst.is_feasible(a):
            assert inst.is_feasible(out)
        # a reseat permutes within partitions: replica SETS unchanged
        assert all(
            set(row_a[inst.slot_valid[p]]) == set(row_o[inst.slot_valid[p]])
            for p, (row_a, row_o) in enumerate(zip(a, out))
        )


def test_reseat_cycle_cancel_matches_lp():
    """The negative-cycle-canceling fast path of the exact reseat must
    land on the SAME optimum as the transportation LP on every input —
    including adversarially scrambled leadership (random in-partition
    leader swaps), where multi-arc cancel cycles are actually
    exercised, and OUT-OF-BAND leadership counts, where the r4
    band-repair phase runs before canceling (the LP repairs optimally,
    so the canceller must too). Measured r4: the canceller replaced a
    58 s LP solve on the adv50k certification path, so its exactness
    is certificate-critical."""
    rng = np.random.default_rng(3)
    for name in ("decommission", "adversarial", "leader_only"):
        sc, inst = _inst(name)
        r = optimize(solver="tpu", seed=1, **sc.kwargs)
        base = np.asarray(r.solve.a).astype(np.int32)
        B = inst.num_brokers
        for trial in range(4):
            a = base.copy()
            if trial:  # scramble: random in-partition leader swaps
                # (out-of-band results are kept: they exercise repair)
                for p in rng.choice(
                    inst.num_parts, size=min(inst.num_parts, 40),
                    replace=False,
                ):
                    live = np.flatnonzero(
                        inst.slot_valid[p] & (a[p] < B)
                    )
                    if live.size >= 2:
                        s = int(rng.choice(live[1:]))
                        a[p, 0], a[p, s] = a[p, s], a[p, 0]
            fast = inst._reseat_cycle_cancel(a.copy())
            lp = inst._best_leader_lp(a.copy())
            assert fast is not None, f"{name} trial {trial} declined"
            assert lp is not None
            assert (
                inst.preservation_weight(fast)
                == inst.preservation_weight(lp)
            ), f"{name} trial {trial}"
            # replica sets unchanged, feasibility preserved
            assert inst.move_count(fast) == inst.move_count(a)
            if inst.is_feasible(a):
                assert inst.is_feasible(fast)


def test_engine_proves_optimality():
    """On scenarios with tight bounds the sweep engine's final plan
    carries the optimality certificate."""
    sc, _ = _inst("decommission")
    r = optimize(solver="tpu", seed=0, **sc.kwargs)
    s = r.solve.stats
    assert s["feasible"]
    assert s["proved_optimal"]
    assert r.solve.optimal
    assert s["moves"] == s["moves_lb"]


def test_engine_early_stops_with_proof(monkeypatch):
    """With the bounds already memoized (prewarmed), the boundary
    certificate fires deterministically and the engine stops early. (In
    production the bounds prefetch races the ladder — the non-blocking
    check just makes early-stop opportunistic.) The plan CONSTRUCTOR is
    neutralized: if it wins the race the ladder never starts and this
    test would pass vacuously without exercising the boundary check."""
    from kafka_assignment_optimizer_tpu.solvers.tpu import engine as eng

    monkeypatch.setattr(
        eng, "_construct_worker", lambda *a, **k: (None, False, False)
    )
    sc, inst = _inst("decommission")
    inst.move_lower_bound_exact()
    inst.weight_upper_bound()
    # the sweep engine (the TPU default) is the chunked/stateful one —
    # the chain engine runs one uncut ladder unless a deadline forces
    # chunking. cert_min_savings_s=0 disables the "is stopping early
    # even worth it" economics so the check is deterministic.
    res = eng.solve_tpu(inst, seed=0, engine="sweep",
                        cert_min_savings_s=0.0)
    s = res.stats
    assert s["feasible"]
    assert s["proved_optimal"]
    assert s["early_stopped"]
    assert s["rounds_run"] < s["rounds"]
    assert s["moves"] == s["moves_lb"]


def test_leader_cap_flow_matches_lp_oracle(rng):
    """The native-flow fast path of the cap-only leader bound equals
    the scipy transportation LP on random clusters. The flow IS the
    level-0 certificate bound (r4 rewrite: 5.3 s of HiGHS IPM ->
    ~0.2 s at 50k partitions), so a silent divergence would produce
    false certificates — pin it to the LP oracle it replaced."""
    from kafka_assignment_optimizer_tpu.models.cluster import (
        Assignment,
        PartitionAssignment,
        Topology,
    )
    from kafka_assignment_optimizer_tpu.models.instance import (
        ProblemInstance,
        build_instance,
    )

    # a broken native build would silently turn this into LP-vs-LP —
    # exactly the vacuous pass the docstring warns about
    from kafka_assignment_optimizer_tpu.native import mcmf

    assert callable(mcmf)

    checked = 0
    for trial in range(12):
        n_b = int(rng.integers(4, 16))
        n_racks = int(rng.integers(1, 4))
        n_p = int(rng.integers(3, 40))
        rf = int(rng.integers(1, min(4, n_b)))
        topo = Topology.from_dict(
            {str(b): f"r{b % n_racks}" for b in range(n_b)}
        )
        parts = [
            PartitionAssignment(
                topic="t", partition=p,
                replicas=rng.choice(n_b, size=rf, replace=False).tolist(),
            )
            for p in range(n_p)
        ]
        drop = int(rng.integers(0, n_b)) if rng.random() < 0.5 else None
        brokers = [b for b in range(n_b) if b != drop]
        inst = build_instance(
            Assignment(partitions=parts), brokers, topo
        )
        flow0 = inst._leader_cap_lp(with_lower=False)
        flow1 = inst._leader_cap_lp(with_lower=True)
        # force the scipy path by disabling the flow fast paths
        orig0 = ProblemInstance._leader_cap_flow
        orig1 = ProblemInstance._leader_cap_flow_lower
        ProblemInstance._leader_cap_flow = lambda self, *a, **k: None
        ProblemInstance._leader_cap_flow_lower = (
            lambda self, *a, **k: None
        )
        try:
            inst2 = build_instance(
                Assignment(partitions=parts), brokers, topo
            )
            lp0 = inst2._leader_cap_lp(with_lower=False)
            lp1 = inst2._leader_cap_lp(with_lower=True)
        finally:
            ProblemInstance._leader_cap_flow = orig0
            ProblemInstance._leader_cap_flow_lower = orig1
        assert flow0 == lp0, (trial, flow0, lp0)
        # level 1: the flow is the exact polytope optimum; the LP path
        # reports max(primal, repaired dual), which is sound but can
        # sit slightly ABOVE the optimum — so the flow must never
        # exceed it, and must stay within the repair slack of it
        assert lp1 is not None and flow1 is not None, trial
        assert flow1 <= lp1, (trial, flow1, lp1)
        assert lp1 - flow1 <= 2, (trial, flow1, lp1)
        checked += 1
    assert checked == 12


def test_proof_claims_sound_on_random_clusters(rng):
    """A claimed certificate must NEVER be wrong: on random adversarial
    clusters, every proved_optimal plan's objective equals the exact
    MILP optimum (and moves don't exceed the MILP's). The single most
    important property of the whole bounds stack."""
    from kafka_assignment_optimizer_tpu.models.cluster import (
        Assignment,
        PartitionAssignment,
        Topology,
    )

    proved = 0
    for trial in range(6):
        n_b = int(rng.integers(5, 14))
        n_racks = int(rng.integers(1, 4))
        n_p = int(rng.integers(4, 30))
        rf = int(rng.integers(1, min(4, n_b)))
        topo = Topology.from_dict(
            {str(b): f"r{b % n_racks}" for b in range(n_b)}
        )
        parts = [
            PartitionAssignment(
                topic="t", partition=p,
                replicas=rng.choice(n_b, size=rf, replace=False).tolist(),
            )
            for p in range(n_p)
        ]
        drop = int(rng.integers(0, n_b)) if rng.random() < 0.5 else None
        brokers = [b for b in range(n_b) if b != drop]
        kw = dict(
            current=Assignment(partitions=parts),
            broker_list=brokers, topology=topo,
        )
        r = optimize(solver="tpu", seed=trial, rounds=32, **kw)
        s = r.solve.stats
        assert s["feasible"]
        if s["proved_optimal"]:
            proved += 1
            ex = optimize(solver="milp", **kw)
            assert ex.solve.optimal  # the oracle itself must be exact
            assert r.solve.objective == ex.solve.objective, trial
            assert r.replica_moves <= ex.replica_moves, trial
    # the bounds are tight often enough that a silent "never proves
    # anything" regression would also be caught
    assert proved >= 1


def test_engine_unprovable_still_solves():
    """Where the relaxation has a gap (smoke jumbo), the engine must run
    the full ladder and still return a feasible plan, with
    proved_optimal honestly False."""
    sc, _ = _inst("jumbo")
    r = optimize(solver="tpu", seed=0, **sc.kwargs)
    s = r.solve.stats
    assert s["feasible"]
    assert s["rounds_run"] == s["rounds"]
    # jumbo smoke's true optimum (27 moves, MILP-verified) sits above the
    # relaxation bound (25) — the engine must not claim a proof there
    assert not s["proved_optimal"] or s["moves"] == s["moves_lb"]
