"""Golden end-to-end tests (SURVEY.md §4.1): the reference's worked demo
must solve to exactly one replica move — partition 1 ``[8,19] -> [8,1]``
or a same-cost AZ-balanced symmetric answer (README.md:83-91)."""

import numpy as np

from kafka_assignment_optimizer_tpu import build_instance, move_diff, optimize
from kafka_assignment_optimizer_tpu.solvers.milp import build_milp


def test_demo_golden_one_move(demo):
    current, brokers, topo = demo
    res = optimize(current, brokers, topo, solver="milp")
    rep = res.report()
    assert rep["feasible"], rep
    assert res.replica_moves == 1, res.assignment.to_json(indent=1)
    # only partition 1 (which held removed broker 19) changes replicas
    changed = {k.partition for k in res.moves.changed}
    assert 1 in changed
    p1 = res.assignment.by_key()[[k for k in res.assignment.by_key()
                                  if k.partition == 1][0]]
    assert p1.leader == 8  # leader preserved
    assert 19 not in p1.replicas
    # replacement broker keeps AZ balance: 19 was odd/AZ b -> new is odd
    new_b = [b for b in p1.replicas if b != 8][0]
    assert new_b % 2 == 1
    assert res.solve.optimal


def test_demo_objective_is_max_minus_follower_loss(demo):
    current, brokers, topo = demo
    res = optimize(current, brokers, topo, solver="milp")
    inst = res.instance
    # optimum keeps everything except one follower slot of partition 1
    # whose broker (19) was removed and carries no weight
    assert res.solve.objective == inst.preservation_weight(res.solve.a)
    assert res.solve.objective == inst.max_weight()


def test_milp_row_counts_match_reference_structure(demo):
    # SURVEY.md §3.3: P + P + B + B + B*P + K + P*K constraint rows
    # (bands are interval constraints = one row each here, two in LP text)
    current, brokers, topo = demo
    inst = build_instance(current, brokers, topo)
    _, constraint, integrality = build_milp(inst)
    P, B, K = inst.num_parts, inst.num_brokers, inst.num_racks
    assert constraint.A.shape[0] == P + P + B + B + B * P + K + P * K
    assert constraint.A.shape[1] == 2 * B * P == len(integrality)


def test_no_change_needed_is_zero_moves(demo):
    current, _, topo = demo
    # keep all 20 brokers: current assignment is already optimal
    res = optimize(current, list(range(20)), topo, solver="milp")
    assert res.replica_moves == 0
    assert res.moves.leader_changes == 0
    assert res.assignment.to_dict() == current.to_dict()


def test_scale_out_rebalance_small():
    # add brokers to a loaded cluster; plan must be feasible and move few
    import itertools

    from kafka_assignment_optimizer_tpu.models.cluster import (
        Assignment,
        PartitionAssignment,
        Topology,
    )

    rng = np.random.default_rng(7)
    B0, P = 6, 12
    parts = []
    cycle = itertools.cycle(range(B0))
    for p in range(P):
        a = next(cycle)
        b = (a + 1) % B0
        parts.append(PartitionAssignment("t", p, [a, b]))
    current = Assignment(partitions=parts)
    topo = Topology.even_odd(range(8))
    res = optimize(current, list(range(8)), topo, solver="milp")
    rep = res.report()
    assert rep["feasible"], rep
    # 24 replicas over 8 brokers -> exactly 3 each; moving >8 replicas is
    # never needed to rebalance 2 new brokers to band
    assert res.replica_moves <= 8


def test_rf_increase_adds_replicas_without_moving_existing(demo):
    current, _, topo = demo
    res = optimize(current, list(range(20)), topo, target_rf=3, solver="milp")
    rep = res.report()
    assert rep["feasible"], rep
    old = current.by_key()
    for key, p in res.assignment.by_key().items():
        assert len(p.replicas) == 3
        # existing replicas kept (optimal: only additions)
        assert set(old[key].replicas) <= set(p.replicas)
        assert p.leader == old[key].leader
    # 10 new replicas = 10 "moves" (data copies), the unavoidable minimum
    assert res.replica_moves == 10
