"""The continuous-performance observatory (ISSUE 9,
docs/OBSERVABILITY.md): flight recorder, SLO engine burn-rate math,
Chrome trace export (golden-pinned), the noise-aware bench comparator,
the byte-bounded report ring, and the end-to-end exemplar chain —
metrics -> exemplar trace ID -> /debug/solves -> Chrome trace."""

import json
import subprocess
import sys
import threading
import urllib.request
from pathlib import Path

import pytest

from kafka_assignment_optimizer_tpu.obs import chrome as ochrome
from kafka_assignment_optimizer_tpu.obs import flight as oflight
from kafka_assignment_optimizer_tpu.obs import regress as oregress
from kafka_assignment_optimizer_tpu.obs import slo as oslo
from kafka_assignment_optimizer_tpu.obs import trace as otrace

GOLDEN = Path(__file__).parent / "golden" / "chrome_trace.json"
REPO = Path(__file__).resolve().parent.parent


# --------------------------------------------------------------------------
# flight recorder
# --------------------------------------------------------------------------


def test_flight_jsonl_rotation_and_roundtrip(tmp_path):
    rec = oflight.FlightRecorder()
    rec.configure(str(tmp_path), max_bytes=4096, max_files=2)
    for i in range(200):
        rec.write({"ts": i, "kind": "solve", "wall_s": 0.1,
                   "pad": "x" * 80, "i": i})
    snap = rec.snapshot()
    assert snap["records_total"] == 200
    assert snap["rotations_total"] >= 1
    assert snap["write_errors_total"] == 0
    # archives pruned to the cap; live file still present
    archives = [p for p in tmp_path.iterdir()
                if p.name.startswith("flight-")]
    assert len(archives) <= 2
    assert (tmp_path / "flight.jsonl").exists()
    got = list(oflight.iter_records(str(tmp_path)))
    # older records fell off with pruned archives, but the retained
    # tail reads back in order and intact
    assert got, "no records survived rotation"
    idx = [r["i"] for r in got]
    assert idx == sorted(idx)
    assert idx[-1] == 199


def test_flight_reader_tolerates_torn_tail(tmp_path):
    rec = oflight.FlightRecorder()
    rec.configure(str(tmp_path))
    rec.write({"i": 1})
    rec.write({"i": 2})
    with open(tmp_path / "flight.jsonl", "a") as f:
        f.write('{"i": 3, "tor')  # the kill -9 tail
    got = list(oflight.iter_records(str(tmp_path / "flight.jsonl")))
    assert [r["i"] for r in got] == [1, 2]


def test_flight_write_failure_counts_never_raises(tmp_path):
    rec = oflight.FlightRecorder()
    rec.configure(str(tmp_path))
    rec.write({"i": 1})
    # yank the directory out from under the live handle
    (tmp_path / "flight.jsonl").unlink()
    tmp_path.rmdir()
    rec._fh = None  # force a reopen attempt against the dead dir
    rec.write({"i": 2})  # must not raise
    assert rec.snapshot()["write_errors_total"] >= 1


def test_solve_histogram_exemplar_worst_recent():
    oflight.reset_solve_stats()
    oflight.observe_solve("solve", 0.3, trace_id="small")
    oflight.observe_solve("solve", 0.45, trace_id="big")
    oflight.observe_solve("solve", 0.31, trace_id="later-small")
    ex = {(e["class"], e["le"]): e for e in oflight.solve_exemplars()}
    # 0.3/0.45/0.31 all land in the le=0.5 containment bucket; the
    # WORST recent one owns the exemplar
    assert ex[("solve", "0.5")]["trace_id"] == "big"
    snap = oflight.solve_snapshot()["solve"]
    assert snap["count"] == 3
    # cumulative: every bucket >= 0.5 saw all three
    assert dict(snap["buckets"])["0.5"] == 3


# --------------------------------------------------------------------------
# SLO engine: burn-rate window math at the boundaries
# --------------------------------------------------------------------------


def test_slo_window_boundary_and_burn_math():
    eng = oslo.SLOEngine(objectives={
        "solve": {"latency_s": 1.0, "target": 0.99},
    })
    # one breach + one ok inside the 5m window
    eng.observe("solve", 10.0, True, trace_id="t-slow", now=1000.0)
    eng.observe("solve", 0.1, True, trace_id="t-fast", now=1100.0)
    s = eng.snapshot(now=1299.9)  # breach is 299.9s old: IN (age < 300)
    w5 = s["classes"]["solve"]["windows"]["5m"]
    assert w5["events"] == 2 and w5["latency_breaches"] == 1
    # burn = (1 bad / 2 events) / (1 - 0.99) = 50
    assert w5["burn_rate"] == pytest.approx(50.0)
    assert s["classes"]["solve"]["status"] == "fast_burn"  # 1h burns too
    # at age EXACTLY 300 the breach falls OUT of the 5m window
    s2 = eng.snapshot(now=1300.0)
    w5 = s2["classes"]["solve"]["windows"]["5m"]
    assert w5["events"] == 1 and w5["latency_breaches"] == 0
    assert w5["burn_rate"] == 0.0
    # ...but stays in the 1h window until age 3600
    assert s2["classes"]["solve"]["windows"]["1h"]["events"] == 2
    s3 = eng.snapshot(now=1000.0 + 3600.0)
    assert s3["classes"]["solve"]["windows"]["1h"]["events"] == 1
    # cumulative counters never rewind
    assert s3["classes"]["solve"]["events_total"] == 2
    assert s3["classes"]["solve"]["latency_breaches_total"] == 1


def test_slo_quality_breach_and_worst_exemplar():
    eng = oslo.SLOEngine(objectives={
        "delta": {"latency_s": 5.0, "target": 0.9},
    })
    eng.observe_record({"kind": "delta", "wall_s": 0.2, "ts": 100.0,
                        "trace_id": "q1",
                        "quality": {"feasible": False}})
    eng.observe_record({"kind": "delta", "wall_s": 0.9, "ts": 101.0,
                        "trace_id": "q2",
                        "quality": {"feasible": True}})
    s = eng.snapshot(now=102.0)
    c = s["classes"]["delta"]
    assert c["quality_breaches_total"] == 1
    assert c["windows"]["5m"]["quality_breaches"] == 1
    # burn = (1/2) / 0.1 = 5 on both windows -> fast burn
    assert c["windows"]["5m"]["burn_rate"] == pytest.approx(5.0)
    assert c["status"] == "fast_burn"
    # worst recent observation carries ITS trace id (the 0.9 s one)
    assert c["worst_recent"]["trace_id"] == "q2"


def test_slo_worst_recent_expires_at_read_time():
    """A quiet class must not advertise a trace the report ring
    evicted: worst_recent drops out of snapshots past the longest
    window, same read-time rule as the histogram exemplars."""
    eng = oslo.SLOEngine()
    eng.observe("solve", 3.0, True, trace_id="w", now=0.0)
    assert eng.snapshot(now=100.0)["classes"]["solve"][
        "worst_recent"]["trace_id"] == "w"
    assert "worst_recent" not in eng.snapshot(
        now=3601.0)["classes"]["solve"]


def test_slo_spec_parser_is_loud():
    ok = oslo.parse_spec("solve:5:0.99,delta:2")
    assert ok["solve"] == {"latency_s": 5.0, "target": 0.99}
    assert ok["delta"]["target"] == 0.99  # default
    for bad in ("solve", "solve:0:0.9", "solve:5:1.5", "solve:x",
                "bad name:5", ""):
        with pytest.raises(ValueError):
            oslo.parse_spec(bad)


# --------------------------------------------------------------------------
# Chrome trace export (golden-pinned)
# --------------------------------------------------------------------------

_CHROME_REPORT = {
    "trace_id": "deadbeef00000001",
    "name": "request",
    "started_unix": 1754300000.0,
    "wall_s": 1.25,
    "phases": {"bounds": 0.4, "ladder": 0.8},
    "annealing": {"engine": "sweep", "rounds": 8},
    "spans": {
        "name": "request", "start_s": 0.0, "wall_s": 1.25,
        "attrs": {"solver": "tpu", "feasible": True},
        "spans": [
            {"name": "bounds", "start_s": 0.01, "wall_s": 0.4},
            {"name": "constructor", "start_s": 0.02, "wall_s": None},
            {"name": "seed", "start_s": 0.42, "wall_s": 0.01},
            {"name": "ladder", "start_s": 0.43, "wall_s": 0.8,
             "attrs": {"engine": "sweep", "pipelined": True},
             "spans": [
                 {"name": "chunk", "start_s": 0.44, "wall_s": 0.3,
                  "attrs": {"index": 0},
                  "spans": [{"name": "compile", "start_s": 0.45,
                             "wall_s": 0.2},
                            {"name": "dispatch", "start_s": 0.66,
                             "wall_s": 0.05,
                             "attrs": {"cache": "miss"}}]},
                 {"name": "chunk", "start_s": 0.75, "wall_s": 0.4,
                  "attrs": {"index": 1}},
                 {"name": "degrade", "start_s": 0.8, "wall_s": 0,
                  "attrs": {"rung": "pallas_to_xla"}},
             ]},
            {"name": "polish", "start_s": 1.23, "wall_s": 0,
             "attrs": {"skipped": True}},
            {"name": "verify", "start_s": 1.24, "wall_s": 0.01},
        ],
    },
}


def test_chrome_export_matches_golden():
    got = ochrome.to_chrome(_CHROME_REPORT)
    want = json.loads(GOLDEN.read_text())
    assert got == want, (
        "Chrome export drifted from tests/golden/chrome_trace.json — "
        "if the change is intentional, regenerate the golden file"
    )


def test_chrome_export_invariants():
    out = ochrome.to_chrome(_CHROME_REPORT)
    evs = [e for e in out["traceEvents"] if e["ph"] != "M"]
    # stable field set per phase kind
    for e in evs:
        base = {"name", "ph", "ts", "pid", "tid", "cat"}
        assert base <= set(e), e
        if e["ph"] == "X":
            assert "dur" in e and e["dur"] >= 0
        else:
            assert e["ph"] == "i" and e["s"] == "t" and "dur" not in e
    # monotonic ts
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts)
    # parent/child nesting preserved: every event's interval sits
    # inside the root's, and same-tid X events properly nest (no
    # partial overlap)
    root = evs[0]
    assert root["name"] == "request" and root["tid"] == 0
    for e in evs[1:]:
        assert e["ts"] >= root["ts"]
        assert e["ts"] + e.get("dur", 0) <= root["ts"] + root["dur"]
    xs = [e for e in evs if e["ph"] == "X"]
    for i, a in enumerate(xs):
        for b in xs[i + 1:]:
            if a["tid"] != b["tid"]:
                continue
            a0, a1 = a["ts"], a["ts"] + a["dur"]
            b0, b1 = b["ts"], b["ts"] + b["dur"]
            overlap = max(a0, b0) < min(a1, b1)
            nested = (a0 <= b0 and b1 <= a1) or (b0 <= a0 and a1 <= b1)
            assert not overlap or nested, (a, b)
    # the in-flight worker span landed off the main lane, flagged
    cons = next(e for e in evs if e["name"] == "constructor")
    assert cons["tid"] != 0 and cons["args"]["in_flight"] is True
    # root carries the trace id
    assert evs[0]["args"]["trace_id"] == "deadbeef00000001"


def test_kao_trace_convert_cli(tmp_path):
    from kafka_assignment_optimizer_tpu.obs.trace_cli import main

    rep = tmp_path / "report.json"
    # the CLI --trace wrapper shape: solve_report nested in the report
    rep.write_text(json.dumps({"feasible": True,
                               "solve_report": _CHROME_REPORT}))
    out = tmp_path / "chrome.json"
    assert main(["convert", str(rep), "-o", str(out)]) == 0
    doc = json.loads(out.read_text())
    assert doc == ochrome.to_chrome(_CHROME_REPORT)
    # a non-report file errors cleanly
    bad = tmp_path / "bad.json"
    bad.write_text('{"no": "spans"}')
    assert main(["convert", str(bad)]) == 2


def test_kao_trace_flight_cli(tmp_path, capsys):
    from kafka_assignment_optimizer_tpu.obs.trace_cli import main

    f = tmp_path / "flight.jsonl"
    f.write_text('{"kind": "solve", "i": 1}\n'
                 '{"kind": "delta", "i": 2}\n'
                 '{"torn')
    assert main(["flight", str(f), "--kind", "delta"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1 and json.loads(out[0])["i"] == 2


# --------------------------------------------------------------------------
# byte-bounded solve-report ring (ISSUE 9 satellite)
# --------------------------------------------------------------------------


def _deep_report(tid: str, depth: int, fanout: int) -> dict:
    def span(d):
        s = {"name": f"lvl{d}", "start_s": 0.0, "wall_s": 1.0,
             "attrs": {"pad": "x" * 40}}
        if d < depth:
            s["spans"] = [span(d + 1) for _ in range(fanout)]
        return s

    return {"trace_id": tid, "name": "solve", "started_unix": 0.0,
            "wall_s": 1.0, "phases": {}, "spans": span(0)}


def test_report_ring_truncates_deepest_first():
    ring = otrace.ReportRing(capacity=8, max_report_bytes=4096,
                             max_total_bytes=64 << 10)
    ring.put(_deep_report("big1", depth=6, fanout=4))
    rep = ring.get("big1")
    assert rep["truncated"] is True
    assert len(json.dumps(rep)) <= 4096

    def depth_of(s):
        return 1 + max((depth_of(c) for c in s.get("spans") or ()),
                       default=0)

    def dropped(s):
        return (s.get("spans_dropped", 0)
                + sum(dropped(c) for c in s.get("spans") or ()))

    # the shallow skeleton survives; the deepest levels were pruned
    # and accounted for
    assert depth_of(rep["spans"]) < 7
    assert dropped(rep["spans"]) > 0
    assert ring.stats()["truncated_total"] == 1


def test_report_ring_bounds_total_bytes():
    ring = otrace.ReportRing(capacity=100, max_report_bytes=8 << 10,
                             max_total_bytes=20 << 10)
    for i in range(10):
        ring.put(_deep_report(f"r{i}", depth=4, fanout=3))
    st = ring.stats()
    assert st["bytes"] <= 20 << 10
    assert st["reports"] < 10  # oldest evicted on byte pressure
    ids = ring.ids()
    assert ids[0] == "r9"  # newest always retained
    assert ring.get("r0") is None


def test_small_reports_pass_through_untouched():
    ring = otrace.ReportRing(capacity=4)
    rep = {"trace_id": "t", "spans": {"name": "s", "start_s": 0.0,
                                      "wall_s": 0.1}}
    ring.put(rep)
    assert "truncated" not in ring.get("t")
    # untruncated puts store the SAME object (no copy cost)
    assert ring.get("t") is rep


# --------------------------------------------------------------------------
# noise-aware perf-regression gate (obs/regress.py)
# --------------------------------------------------------------------------


def _artifact(**over) -> dict:
    import bench as bench_mod

    art = {
        "metric": "decommission_255b_10000p_warm_wall_clock",
        "value": 1.0, "unit": "s", "vs_baseline": 5.0,
        "platform": "cpu", "cold_wall_clock_s": 2.0,
        "cold_cached_wall_clock_s": 1.8,
        "moves": 117, "min_moves_lb": 117, "feasible": True,
        "proved_optimal": True, "engine": "construct",
        "env": {"git_sha": "aaaa000000", "platform": "cpu",
                "devices": 8, "xla_flags": ""},
        "rows_schema": bench_mod.ROW_SCHEMA,
        "scenarios": [
            ["decommission", 1.0, 2.0, 117, 117, 1, 1, 1, "construct",
             "agg", 1.0, 1, 2, [0.1, 0, 0, 0.5, 0, 0.1], None],
            ["adversarial", 3.6, 26.0, 117, 117, 1, 1, 0, "sweep", "",
             22.4, 2, 4, [0.1, 0, 0, 3.0, 0, 0.1], 1.2],
        ],
        "jumbo_cold_runs": [10.0, 11.0, 9.0],
        "search_cold_runs": {"adversarial": [26.0, 7.0, 7.1]},
        "replay_day": {"warm_p50_s": 0.1, "warm_p99_s": 0.4,
                       "cold_p50_s": 0.2, "cold_p99_s": 0.8,
                       "quality_ok": True, "storm_dropped": 0},
        "batch_throughput": {"b1": 1.0, "b2": 1.8, "b4": 3.0,
                             "b8": 5.0, "lanes_feasible": True,
                             "moves_at_bound": True},
        "decompose": {"ultra_parts": 200_000,
                      "ultra_jumbo_cold_s": 42.0, "sub_problems": 4,
                      "bound_gap": 160, "certified": False,
                      "stitched_feasible": True, "gap_ok": True,
                      "decompose_speedup": 3.5},
    }
    art.update(over)
    return art


def test_regress_identical_self_compare_is_ok():
    art = _artifact()
    v = oregress.compare(art, json.loads(json.dumps(art)))
    assert v["comparable"] and v["verdict"] == "ok"
    assert not v["latency"]["confirmed"] and not v["latency"]["suspect"]
    assert not v["quality_regressions"]
    assert v["checked"] > 5


def test_regress_flags_seeded_2x_slowdown():
    art = _artifact()
    slow = oregress.seed_slowdown(art, 2.0)
    # quality untouched by the fixture
    assert slow["feasible"] is True
    assert slow["scenarios"][0][3] == art["scenarios"][0][3]  # moves
    v = oregress.compare(art, slow)
    assert v["verdict"] == "regression", v
    # every latency metric doubled: a full suspect quorum (2.0 < hard)
    assert len(v["latency"]["suspect"]) >= v["suspect_quorum"]
    # and the reverse direction reads as an improvement
    v2 = oregress.compare(slow, art)
    assert v2["verdict"] == "ok" and v2["latency"]["improved"]


def test_regress_single_metric_jitter_does_not_trip():
    art = _artifact()
    noisy = json.loads(json.dumps(art))
    noisy["scenarios"][1][1] = 6.5  # adversarial warm 3.6 -> 1.8x
    v = oregress.compare(art, noisy)
    assert v["verdict"] == "ok"
    assert len(v["latency"]["suspect"]) == 1
    # but a single CONFIRMED (>hard_ratio) metric trips alone
    noisy["scenarios"][1][1] = 10.0  # 2.8x
    v = oregress.compare(art, noisy)
    assert v["verdict"] == "regression"
    assert v["latency"]["confirmed"]


def test_regress_headline_not_double_counted_with_rows():
    """With scenario rows present, the top-level headline fields are
    the headline row's numbers verbatim — they must not enter the
    check set twice (one jittery draw would fill the suspect quorum
    by itself)."""
    art = _artifact()
    names = [n for n, _, _ in oregress._latency_pairs(art, art)]
    assert "headline_warm_s" not in names
    assert "decommission.warm_s" in names
    # headline-only artifacts still use the top-level fields
    bare = {k: v for k, v in art.items()
            if k not in ("scenarios", "rows_schema")}
    names = [n for n, _, _ in oregress._latency_pairs(bare, bare)]
    assert "headline_warm_s" in names


def test_regress_decompose_keys():
    """PR 16 satellite: the decompose artifact block participates in
    the gate — ultra-jumbo cold wall as latency, decomposed-vs-flat
    speedup as throughput, stitched_feasible/gap_ok as deterministic
    quality trips."""
    art = _artifact()
    lat = [n for n, _, _ in oregress._latency_pairs(art, art)]
    assert "decompose.ultra_jumbo_cold_s" in lat
    thr = [n for n, _, _ in oregress._throughput_pairs(art, art)]
    assert "decompose.speedup" in thr
    # seed_slowdown scales both, in opposite directions
    slow = oregress.seed_slowdown(art, 2.0)
    assert slow["decompose"]["ultra_jumbo_cold_s"] == 84.0
    assert slow["decompose"]["decompose_speedup"] == 1.75
    # a verdict flip is a confirmed quality regression
    bad = json.loads(json.dumps(art))
    bad["decompose"]["stitched_feasible"] = False
    v = oregress.compare(art, bad)
    assert v["verdict"] == "regression"
    assert any(r["metric"] == "decompose.stitched_feasible"
               for r in v["quality_regressions"])
    bad2 = json.loads(json.dumps(art))
    bad2["decompose"]["gap_ok"] = False
    v2 = oregress.compare(art, bad2)
    assert any(r["metric"] == "decompose.gap_ok"
               for r in v2["quality_regressions"])


def test_regress_quality_regression_is_noise_free():
    art = _artifact()
    bad = json.loads(json.dumps(art))
    bad["scenarios"][1][5] = 0  # adversarial feasible 1 -> 0
    v = oregress.compare(art, bad)
    assert v["verdict"] == "regression"
    assert any("feasible" in r["metric"]
               for r in v["quality_regressions"])
    # moves past a previously-met tight bound is also quality
    bad2 = json.loads(json.dumps(art))
    bad2["scenarios"][1][3] = 140  # moves 117 -> 140 past lb 117
    v2 = oregress.compare(art, bad2)
    assert any("moves_vs_bound" in r["metric"]
               for r in v2["quality_regressions"])


def test_regress_refuses_incomparable_environments():
    art = _artifact()
    other = _artifact()
    other["env"]["devices"] = 1
    v = oregress.compare(art, other)
    assert v["verdict"] == "incomparable" and not v["comparable"]
    # --force overrides
    v2 = oregress.compare(art, other, force=True)
    assert v2["comparable"]
    # unstamped artifacts refuse too (old BENCH_r0x files)
    unstamped = _artifact()
    del unstamped["env"]
    assert oregress.compare(art, unstamped)["verdict"] == "incomparable"


def test_regress_sub_floor_baseline_blowup_is_caught():
    """The noise floor gates on the LARGER side of a pair: a 15 ms
    warm-certify baseline degrading to seconds must stay visible even
    though 15 ms alone sits under the floor."""
    art = _artifact()
    art["replay_day"]["warm_p50_s"] = 0.015
    blow = json.loads(json.dumps(art))
    blow["replay_day"]["warm_p50_s"] = 3.0
    v = oregress.compare(art, blow)
    assert v["verdict"] == "regression"
    assert any(r["metric"] == "replay_day.warm_p50_s"
               for r in v["latency"]["confirmed"])
    # tiny-vs-tiny stays ignored (both under the floor)
    quiet = json.loads(json.dumps(art))
    quiet["replay_day"]["warm_p50_s"] = 0.019
    v2 = oregress.compare(art, quiet)
    names = [r["metric"] for r in v2["latency"]["suspect"]
             + v2["latency"]["confirmed"]]
    assert "replay_day.warm_p50_s" not in names


def test_exemplar_ttl_drops_stale_links_at_read_time():
    """An exemplar past the TTL is dropped from snapshots entirely — a
    quiet bucket must not advertise a trace the report ring evicted."""
    import time as _time

    h = otrace.ExemplarHistogram((1.0,), ttl_s=0.05)
    h.observe("solve", 2.0, trace_id="stale-soon")
    assert h.exemplars("class")
    _time.sleep(0.08)
    assert h.exemplars("class") == []
    # the histogram counts themselves never expire
    assert h.snapshot()["solve"]["count"] == 1


def test_regress_refuses_errored_and_empty_artifacts():
    """A bench run that failed outright (or artifacts sharing no
    metrics) must read as incomparable, never as a green gate."""
    art = _artifact()
    errored = {"metric": "replay_day", "error": "backend init blew up",
               "env": dict(art["env"])}
    v = oregress.compare(art, errored)
    assert v["verdict"] == "incomparable"
    assert "bench failure" in v["reason"]
    bare = {"metric": "x", "env": dict(art["env"])}
    v2 = oregress.compare(bare, bare)
    assert v2["verdict"] == "incomparable"
    assert "no comparable metrics" in v2["reason"]


def test_regress_median_of_n_resists_one_outlier():
    art = _artifact()
    noisy = json.loads(json.dumps(art))
    # one wild cold draw; the median barely moves
    noisy["jumbo_cold_runs"] = [10.0, 30.0, 9.0]
    v = oregress.compare(art, noisy)
    names = [r["metric"] for r in
             v["latency"]["suspect"] + v["latency"]["confirmed"]]
    assert "jumbo_cold_median_s" not in names


def test_bench_compare_cli_wiring(tmp_path):
    """bench.py --compare prints the verdict JSON first and returns
    the gate exit code (0 ok / 3 regression) — the CI contract."""
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(_artifact()))
    b.write_text(json.dumps(oregress.seed_slowdown(_artifact(), 2.0)))
    ok = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--compare",
         str(a), str(a)],
        capture_output=True, text=True, timeout=120, cwd=str(REPO),
    )
    assert ok.returncode == 0, ok.stderr
    assert json.loads(ok.stdout)["verdict"] == "ok"
    trip = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--compare",
         str(a), str(b)],
        capture_output=True, text=True, timeout=120, cwd=str(REPO),
    )
    assert trip.returncode == 3, (trip.stdout, trip.stderr)
    assert json.loads(trip.stdout)["verdict"] == "regression"


# --------------------------------------------------------------------------
# engine + watch integration: records per solve/delta
# --------------------------------------------------------------------------


def _demo_instance():
    from kafka_assignment_optimizer_tpu import build_instance
    from kafka_assignment_optimizer_tpu.models.cluster import (
        demo_assignment, demo_broker_list, demo_topology,
    )

    return build_instance(demo_assignment(), demo_broker_list(),
                          demo_topology())


def test_engine_solve_lands_one_flight_record(tmp_path):
    from kafka_assignment_optimizer_tpu.solvers.tpu.engine import (
        solve_tpu,
    )

    oflight.configure(str(tmp_path))
    oflight.reset_recent()
    try:
        res = solve_tpu(_demo_instance(), seed=0, batch=4, rounds=4,
                        steps_per_round=40, trace=True)
    finally:
        oflight.configure(None)
    recs = oflight.recent(kind="solve")
    # exactly ONE record: the sweep->chain retry and any nested solve
    # feed the outer record instead of landing their own
    assert len(recs) == 1, [r["kind"] for r in oflight.recent()]
    rec = recs[0]
    assert rec["trace_id"] == res.stats["trace_id"]
    assert rec["quality"]["feasible"] is True
    assert rec["quality"]["moves"] == res.stats["moves"]
    assert set(rec["split"]) == {"compile_s", "device_s", "dispatch_s",
                                "host_s", "dispatches", "duty_cycle"}
    assert "bounds" in rec["phases"] and "ladder" in rec["phases"]
    assert rec["bucket"][0] == 19  # demo brokers
    # the record also hit the durable JSONL
    disk = list(oflight.iter_records(str(tmp_path)))
    assert [r["trace_id"] for r in disk] == [rec["trace_id"]]
    # and the solve-seconds histogram + SLO engine saw it
    assert oflight.solve_snapshot()["solve"]["count"] >= 1


def test_failed_solve_lands_failure_record(monkeypatch):
    """A solve that RAISES must still burn the SLO quality budget —
    a total outage of the solve path must not read as zero burn."""
    import kafka_assignment_optimizer_tpu.solvers.tpu.engine as eng

    def boom(*a, **k):
        raise RuntimeError("synthetic solve failure")

    monkeypatch.setattr(eng, "_solve_tpu_traced", boom)
    oflight.reset_recent()
    with pytest.raises(RuntimeError):
        eng.solve_tpu(_demo_instance(), seed=0)
    recs = oflight.recent(kind="solve")
    assert len(recs) == 1
    rec = recs[0]
    assert rec["quality"]["feasible"] is False
    assert "synthetic solve failure" in rec["error"]
    assert rec["bucket"][0] == 19
    # the SLO engine counted it as a quality breach
    eng2 = oslo.SLOEngine()
    eng2.observe_record(rec)
    s = eng2.snapshot(now=rec["ts"] + 1)
    assert s["classes"]["solve"]["quality_breaches_total"] == 1


def test_exact_solver_optimize_lands_reduced_record():
    """Small instances route 'auto' to the exact oracles, which have
    no engine-level recorder — api.optimize lands the reduced record
    so exact-solver traffic is not an SLO blind spot."""
    from kafka_assignment_optimizer_tpu.api import optimize
    from kafka_assignment_optimizer_tpu.models.cluster import (
        demo_assignment, demo_broker_list, demo_topology,
    )

    oflight.reset_recent()
    optimize(demo_assignment(), demo_broker_list(), demo_topology(),
             solver="milp")
    recs = oflight.recent(kind="solve")
    assert len(recs) == 1
    rec = recs[0]
    assert rec["engine"] == "milp"
    assert rec["quality"]["feasible"] is True
    assert rec["quality"]["certified"] is True
    assert rec["quality"]["moves"] == 1  # the golden demo answer
    assert rec["warm"]["warm_path"] is True  # exact solvers never compile


def test_watch_delta_events_each_land_a_flight_record():
    """Acceptance (ISSUE 9): replayed watch events each produce one
    kind="delta" flight record carrying the cluster/epoch identity —
    the same ambient tagging bench.py --replay-day rides."""
    from kafka_assignment_optimizer_tpu.api import optimize_delta
    from kafka_assignment_optimizer_tpu.models.cluster import (
        demo_assignment, demo_topology,
    )
    from kafka_assignment_optimizer_tpu.watch.manager import (
        WatchRegistry,
    )

    def solve_fn(state, prev_plan, budget):
        res = optimize_delta(
            state.assignment, state.brokers, state.topology,
            target_rf=state.rf, prev_plan=prev_plan, solver="tpu",
            seed=0, batch=4, rounds=4, steps_per_round=40,
        )
        return res.assignment.to_dict(), res.report()

    reg = WatchRegistry(solve_fn, None, window_s=0.0)
    oflight.reset_recent()
    topo = demo_topology()
    reg.handle_event("obs-e2e", {
        "type": "bootstrap", "epoch": 1,
        "assignment": demo_assignment().to_dict(),
        "brokers": list(range(19)), "topology": topo.to_dict(),
    })
    reg.handle_event("obs-e2e", {
        "type": "broker_drain", "epoch": 2, "brokers": [18],
    })
    recs = oflight.recent(kind="delta")
    assert len(recs) == 2
    assert [r["epoch"] for r in recs] == [1, 2]
    assert all(r["cluster"] == "obs-e2e" for r in recs)
    # the drain delta warm-started from the bootstrap plan
    assert recs[1]["warm"]["warm_started"] is True


# --------------------------------------------------------------------------
# end-to-end exemplar chain over real HTTP (ISSUE 9 acceptance)
# --------------------------------------------------------------------------


@pytest.fixture()
def obs_server():
    from kafka_assignment_optimizer_tpu.serve import make_server

    srv = make_server(port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()
    srv.server_close()


def test_exemplar_chain_metrics_to_chrome_trace(obs_server):
    """The p99-spike walkthrough, mechanised: solve -> scrape the
    kao_solve_seconds exemplar -> its trace ID resolves on
    /debug/solves/<id> -> ?format=chrome exports a valid trace whose
    root carries the same ID -> /debug/slo saw the record."""
    from kafka_assignment_optimizer_tpu.models.cluster import (
        demo_assignment,
    )

    oflight.reset_solve_stats()
    payload = {
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
        "topology": "even-odd",
        "solver": "tpu",
        "options": {"seed": 0, "batch": 4, "rounds": 4,
                    "steps_per_round": 40},
    }
    req = urllib.request.Request(
        obs_server + "/submit", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=300) as r:
        body = json.loads(r.read())
    tid = body["trace_id"]

    with urllib.request.urlopen(obs_server + "/metrics",
                                timeout=30) as r:
        metrics = r.read().decode()
    ex_lines = [ln for ln in metrics.splitlines()
                if ln.startswith("kao_solve_seconds_exemplar{")]
    assert ex_lines, metrics[-2000:]
    line = next(ln for ln in ex_lines if 'class="solve"' in ln)
    ex_tid = line.split('trace_id="', 1)[1].split('"', 1)[0]
    assert ex_tid == tid  # the only solve since reset IS the worst

    with urllib.request.urlopen(
        f"{obs_server}/debug/solves/{ex_tid}", timeout=30
    ) as r:
        rep = json.loads(r.read())
    assert rep["trace_id"] == ex_tid and "spans" in rep

    with urllib.request.urlopen(
        f"{obs_server}/debug/solves/{ex_tid}?format=chrome", timeout=30
    ) as r:
        ct = json.loads(r.read())
    evs = [e for e in ct["traceEvents"] if e["ph"] != "M"]
    assert evs and evs[0]["args"]["trace_id"] == ex_tid
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts)
    assert ct["otherData"]["trace_id"] == ex_tid

    with urllib.request.urlopen(obs_server + "/debug/slo",
                                timeout=30) as r:
        slo = json.loads(r.read())
    assert slo["slo"]["classes"]["solve"]["events_total"] >= 1
    assert any(rec.get("trace_id") == tid
               for rec in slo["recent_records"])

    # /healthz carries the compact slo section
    with urllib.request.urlopen(obs_server + "/healthz",
                                timeout=30) as r:
        hz = json.loads(r.read())
    assert "slo" in hz and "status" in hz["slo"]
    # no --flight-dir on this server: the recorder is disabled, but
    # the record STREAM (ring + SLO + histograms) saw the solve
    assert hz["observability"]["flight"]["stream_records_total"] >= 1
    assert hz["observability"]["flight"]["enabled"] == 0


# --------------------------------------------------------------------------
# sharded-mesh comparator keys (ISSUE 19, docs/MESH.md)
# --------------------------------------------------------------------------


def test_regress_mesh_bench_keys():
    """The --mesh-bench block participates in the gate: best-split
    lanes/s as throughput (quorum honesty: a single-core box's flat
    curve must not read as regression by itself) and parity_ok as a
    deterministic quality trip."""
    art = _artifact()
    art["mesh_bench"] = {"parity_ok": True, "best_spec": "8x1",
                         "best_lanes_per_s": 5.0, "lane_scaling": 1.0}
    thr = [n for n, _, _ in oregress._throughput_pairs(art, art)]
    assert "mesh_bench.best_lanes_per_s" in thr
    # a parity flip is a confirmed quality regression — the soak A/B
    # self-compare turns a sharding bit-parity break into exit 3
    bad = json.loads(json.dumps(art))
    bad["mesh_bench"]["parity_ok"] = False
    v = oregress.compare(art, bad)
    assert v["verdict"] == "regression"
    assert any(r["metric"] == "mesh_bench.parity_ok"
               for r in v["quality_regressions"])
    # artifacts without the block stay comparable (the key set is
    # presence-gated, like every other block)
    v2 = oregress.compare(_artifact(), _artifact())
    assert v2["comparable"] and v2["verdict"] == "ok"


def test_regress_refuses_topology_mismatch():
    """Process/mesh topology is an env-stamp comparability axis: a
    1-process artifact never silently diffs against a 2-process one,
    and a different chains×lanes split is likewise incomparable —
    but artifacts predating the stamp (no topology keys) still
    compare."""
    art = _artifact()
    art["env"]["n_processes"] = 1
    art["env"]["mesh_axes"] = {"chains": 8, "lanes": 1}
    other = json.loads(json.dumps(art))
    other["env"]["n_processes"] = 2
    v = oregress.compare(art, other)
    assert v["verdict"] == "incomparable" and not v["comparable"]
    split = json.loads(json.dumps(art))
    split["env"]["mesh_axes"] = {"chains": 4, "lanes": 2}
    assert oregress.compare(art, split)["verdict"] == "incomparable"
    # --force overrides, as with every other env mismatch
    assert oregress.compare(art, split, force=True)["comparable"]
    # a pre-stamp artifact (no topology keys) is not punished
    legacy = json.loads(json.dumps(art))
    del legacy["env"]["n_processes"], legacy["env"]["mesh_axes"]
    assert oregress.compare(art, legacy)["comparable"]
