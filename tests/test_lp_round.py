"""LP-rounding constructor (``solvers/lp_round.py``): decoding the
kept-replica LP vertex into a full plan must yield either None or a
feasible plan, and a certified plan must equal the exact MILP optimum.
"""

from __future__ import annotations

import numpy as np
import pytest

from kafka_assignment_optimizer_tpu.api import optimize
from kafka_assignment_optimizer_tpu.models.cluster import (
    Assignment,
    PartitionAssignment,
    Topology,
)
from kafka_assignment_optimizer_tpu.models.instance import build_instance
from kafka_assignment_optimizer_tpu.solvers.lp_round import construct
from kafka_assignment_optimizer_tpu.utils import gen


def _inst(name):
    sc = gen.SCENARIOS[name](**gen.SMOKE_KWARGS[name])
    return sc, build_instance(
        sc.current, sc.broker_list, sc.topology, target_rf=sc.target_rf
    )


@pytest.mark.parametrize(
    "name", ["demo", "scale_out", "decommission", "leader_only",
             "rf_change"]
)
def test_construct_is_exact_on_baseline_scenarios(name):
    """On every BASELINE smoke scenario the constructor produces a
    feasible plan matching the exact MILP optimum, with a certificate."""
    sc, inst = _inst(name)
    a = construct(inst)
    assert a is not None
    assert inst.is_feasible(a)
    assert inst.certify_optimal(a)
    exact = optimize(solver="milp", **sc.kwargs)
    assert inst.preservation_weight(a) == exact.solve.objective
    assert inst.move_count(a) <= exact.replica_moves


def test_construct_never_infeasible_fuzz(rng):
    """Random lopsided clusters: construct returns None or a feasible
    plan — never a band-violating one."""
    for trial in range(6):
        n_b = int(rng.integers(6, 14))
        n_p = int(rng.integers(8, 30))
        rf = int(rng.integers(1, 3))
        topo = Topology.from_dict(
            {str(b): f"r{b % int(rng.integers(2, 4))}" for b in range(n_b)}
        )
        parts = []
        for p in range(n_p):
            reps = rng.choice(n_b, size=rf, replace=False).tolist()
            parts.append(
                PartitionAssignment(topic="t", partition=p, replicas=reps)
            )
        drop = int(rng.integers(0, n_b))
        brokers = [b for b in range(n_b) if b != drop]
        inst = build_instance(
            Assignment(partitions=parts), brokers, topo
        )
        a = construct(inst)
        if a is not None:
            assert inst.is_feasible(a), (trial, inst.violations(a))


@pytest.mark.parametrize("name", ["scale_out", "leader_only"])
def test_construct_reseats_without_lp_fallback(name):
    """The slot-0 pre-seat (kept leaders + the completion's
    lead-channel placements) must leave the exact reseat's fast
    cycle-canceller an in-band input, so the constructor never needs
    the full transportation LP — the r4 fix that took the jumbo's
    realization from 7.2 s to 0.5 s. A regression (canceller declines,
    LP path hit) fails loudly here instead of silently costing
    seconds per constructed solve."""
    from kafka_assignment_optimizer_tpu.models.instance import (
        ProblemInstance,
    )

    sc, inst = _inst(name)
    calls = []
    orig = ProblemInstance._best_leader_lp

    def _spy(self, a):
        calls.append(1)
        return orig(self, a)

    ProblemInstance._best_leader_lp = _spy
    try:
        a = construct(inst)
    finally:
        ProblemInstance._best_leader_lp = orig
    assert a is not None
    assert inst.is_feasible(a)
    assert inst.certify_optimal(a)
    assert not calls, (
        "constructed plan fell back to the reseat LP: the slot-0 "
        "pre-seat left out-of-band leader counts"
    )


def test_mcmf_completion_survives_binding_lead_gates():
    """Plain placements must not consume lead quota: two leaderless
    vacancies forced onto one broker with lead_quota 1 must still all
    place (one through the rewarded lead channel, one through the
    cost-0 bypass) instead of aborting at max flow 1."""
    from types import SimpleNamespace

    from kafka_assignment_optimizer_tpu.solvers.lp_round import (
        _complete_mcmf,
    )

    B = 2
    inst = SimpleNamespace(
        num_brokers=B,
        num_racks=1,
        rack_of_broker=np.zeros(B + 1, dtype=np.int32),
        broker_hi=np.array([2, 0]),
        broker_lo=np.array([0, 0]),
        rack_hi=np.array([2]),
        rack_lo=np.array([0]),
        part_rack_hi=np.array([2, 2]),
    )
    a = np.full((2, 1), B, dtype=np.int32)  # both slots vacant
    out = _complete_mcmf(
        inst, a,
        vac=np.array([1, 1]),
        leaderless=np.array([True, True]),
        lead_quota=np.array([1, 0]),
    )
    assert out is not None
    ap, ab, alead = out  # flat assignment arrays (ISSUE 10)
    assert sorted(zip(ap.tolist(), ab.tolist())) == [(0, 0), (1, 0)]
    # exactly one went through the rewarded lead channel; the other
    # took the cost-0 bypass (lead_quota[0] is 1)
    assert int(alead.sum()) == 1


def test_engine_uses_constructed_plan():
    """solve_tpu on a caps-bind scenario returns the constructed
    certified plan without running any annealing rounds. Bounds are
    prewarmed so the 5-second fast-path join is deterministic even on a
    loaded machine."""
    from kafka_assignment_optimizer_tpu.solvers.tpu.engine import solve_tpu

    sc, inst = _inst("scale_out")
    inst.move_lower_bound_exact()
    inst.weight_upper_bound(level=2)
    res = solve_tpu(inst, seed=0)
    s = res.stats
    assert s["constructed"]
    assert s["proved_optimal"]
    assert res.optimal
    assert s["rounds_run"] == 0
    assert s["feasible"]


def test_no_signal_keeps_annealing_path(monkeypatch):
    """A plain demo decommission has slack caps — the LP constructor
    worker is not launched and the annealer solves it (still to proven
    optimality). The tiny-instance exact-MILP race is disabled here:
    this test pins the LP constructor's GATING, and the annealer path
    must retain CI coverage."""
    from kafka_assignment_optimizer_tpu.solvers.tpu import engine as eng

    monkeypatch.setattr(eng, "_EXACT_RACE_PARTS", 0)
    monkeypatch.setattr(eng, "_RESEAT_RACE", False)
    sc = gen.SCENARIOS["demo"]()
    inst = build_instance(sc.current, sc.broker_list, sc.topology)
    assert not eng._caps_bind(inst)
    r = optimize(solver="tpu", seed=0, **sc.kwargs)
    assert not r.solve.stats["constructed"]
    assert r.solve.stats["proved_optimal"]


def test_tiny_default_solve_races_exact_milp():
    """A DEFAULTED demo-sized solve (no engine/budget knobs) wins the
    exact-MILP race instead: certified optimum, zero device work —
    the cold-start fast path for the flagship golden case."""
    sc = gen.SCENARIOS["demo"]()
    r = optimize(solver="tpu", seed=0, **sc.kwargs)
    s = r.solve.stats
    assert s["constructed"]
    assert s["construct_path"] == "milp"
    assert s["engine"] == "construct"
    assert s["proved_optimal"]
    assert s["rounds_run"] == 0
    assert r.replica_moves == 1  # the golden 1-move optimum
    # explicit knobs opt OUT of the race: the search engine runs
    r2 = optimize(solver="tpu", seed=0, engine="sweep", **sc.kwargs)
    assert not r2.solve.stats["constructed"]


@pytest.mark.soak
def test_big_asymmetric_skips_futile_constructor_race(monkeypatch):
    """Past the unaggregated-LP size, an instance the aggregated
    formulation would refuse (``agg_construct_viable`` False) has NO
    viable constructor path — the race must not launch (it would delay
    the annealer by the big-instance wait while a ~900 s LP grinds)."""
    from kafka_assignment_optimizer_tpu.models import (
        instance as inst_mod,
    )
    from kafka_assignment_optimizer_tpu.models.instance import (
        ProblemInstance,
    )
    from kafka_assignment_optimizer_tpu.solvers.tpu import engine as eng

    # the predicate itself, at FULL scale (cheap — no solve): ~1.02x
    # class collapse over 29,883 members is far below the 4x floor
    sc_full = gen.SCENARIOS["adversarial"]()
    inst_full = build_instance(sc_full.current, sc_full.broker_list,
                               sc_full.topology)
    assert not inst_full.agg_construct_viable()
    assert inst_full.agg_effective() is False

    # the worker wiring, at smoke scale: a big + non-viable instance
    # must return from the constructor worker at once — before the
    # bounds join and before any LP work
    import kafka_assignment_optimizer_tpu.solvers.lp_round as lp_round

    sc = gen.SCENARIOS["adversarial"](**gen.SMOKE_KWARGS["adversarial"])
    inst = build_instance(sc.current, sc.broker_list, sc.topology,
                          target_rf=sc.target_rf)
    monkeypatch.setattr(inst_mod, "AGG_MEMBER_THRESHOLD", 100)
    monkeypatch.setattr(
        ProblemInstance, "agg_construct_viable", lambda self: False
    )
    calls = []
    monkeypatch.setattr(
        lp_round, "construct",
        lambda i: calls.append(1) or None,
    )
    r = eng.solve_tpu(inst, seed=0, engine="sweep")
    assert r.stats["feasible"]
    assert not r.stats["constructed"]
    assert not calls, "futile construction was attempted"
