"""The lp_solve subprocess path, executed for real (VERDICT r1 item 6).

The reference's entire L5 is "lp_solve is used behind the scene"
(``/root/reference/README.md:135-137``). Upstream lp_solve 5.5 cannot be
fetched here (no egress), so the repo bundles a work-alike CLI
(``native/lp_cli.cpp``): a separate binary that parses the emitted
LP-format text and solves the 0-1 program exactly. These tests run the
full emit -> exec -> parse -S4 output -> decode pipeline against that
binary (or the system ``lp_solve`` when one exists — same adapter), and
pin the SURVEY §4.4 cross-solver parity: the TPU engine's move count
must never exceed the LP oracle's.
"""

from __future__ import annotations

import numpy as np
import pytest

from kafka_assignment_optimizer_tpu import build_instance, optimize
from kafka_assignment_optimizer_tpu.solvers.lp import (
    lp_solve_available,
    solve_lp_solve,
)

from tests.test_tpu_engine import random_cluster

pytestmark = pytest.mark.skipif(
    not lp_solve_available(),
    reason="no lp_solve binary and bundled lp_cli failed to build",
)


def _system_lp_solve() -> bool:
    import shutil

    return shutil.which("lp_solve") is not None


@pytest.mark.skipif(
    not _system_lp_solve(),
    reason="genuine lp_solve 5.5 binary not on PATH (the Docker image "
           "installs it; this environment has no package egress)",
)
def test_real_lp_solve_binary_parity(demo, rng):
    """VERDICT r4 item 4: when the GENUINE lp_solve 5.5 binary is
    present (the Dockerfile installs Debian's lp-solve), the reference
    path must run it end to end — golden demo at the known 1-move
    optimum, and move-count parity with the exact in-process MILP on a
    fuzz cluster. The adapter prefers a system binary over the bundled
    work-alike, so stats must say backend == system."""
    current, brokers, topo = demo
    res = optimize(current, brokers, topo, solver="lp_solve")
    assert res.solve.stats["backend"] == "system"
    assert res.report()["feasible"]
    assert res.replica_moves == 1  # README.md:85-91 optimum

    fz_current, fz_brokers, fz_topo = random_cluster(rng, 9, 10, 2, 3,
                                                     drop=1)
    lp = optimize(fz_current, fz_brokers, fz_topo, solver="lp_solve")
    exact = optimize(fz_current, fz_brokers, fz_topo, solver="milp")
    assert lp.report()["feasible"]
    assert lp.replica_moves == exact.replica_moves
    assert lp.solve.objective == exact.solve.objective


def test_demo_golden_via_lp_solve(demo):
    current, brokers, topo = demo
    res = optimize(current, brokers, topo, solver="lp_solve")
    rep = res.report()
    assert rep["feasible"], rep
    assert rep["proven_optimal"] is True
    assert res.replica_moves == 1  # README.md:85-91 optimum
    assert res.solve.stats["backend"] in ("system", "bundled_lp_cli")


def test_tpu_moves_never_exceed_lp_solve(rng):
    """North-star quality metric (BASELINE.json): tpu <= lp_solve."""
    for nb, npart, rf, nr, drop in ((8, 12, 2, 2, 1), (12, 10, 2, 3, 2)):
        current, brokers, topo = random_cluster(rng, nb, npart, rf, nr,
                                                drop=drop)
        lp = optimize(current, brokers, topo, solver="lp_solve")
        tpu = optimize(current, brokers, topo, solver="tpu",
                       batch=16, seed=0)
        assert lp.report()["feasible"]
        assert tpu.report()["feasible"]
        assert tpu.replica_moves <= lp.replica_moves


def test_lp_solve_matches_milp_objective(rng):
    """The bundled CLI is exact: same optimal objective as HiGHS."""
    current, brokers, topo = random_cluster(rng, 9, 8, 3, 3, drop=1)
    inst = build_instance(current, brokers, topo)
    lp = solve_lp_solve(inst, time_limit_s=90.0)
    from kafka_assignment_optimizer_tpu.solvers.milp import solve_milp

    exact = solve_milp(inst)
    assert inst.is_feasible(lp.a)
    if lp.optimal:  # a timeout (rc=1) may return a proven-feasible incumbent
        assert lp.objective == exact.objective
    else:
        assert lp.objective <= exact.objective


def test_timeout_returns_feasible_incumbent(rng):
    """-timeout: the CLI prints its best-so-far (rc=1) and the adapter
    surfaces it as a non-optimal but feasible SolveResult."""
    current, brokers, topo = random_cluster(rng, 16, 24, 3, 4, drop=1)
    inst = build_instance(current, brokers, topo)
    res = solve_lp_solve(inst, time_limit_s=2.0)
    assert inst.is_feasible(res.a)
    # large RF=3 instance in 2s: the bundled B&B cannot prove optimality
    # (a system lp_solve might — accept either, but the plan must be real)
    assert res.objective <= inst.max_weight()
