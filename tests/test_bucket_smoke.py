"""Tier-1-safe bucket-ladder smoke (CI/tooling satellite): walk the low
rungs of the partition ladder on the CPU test mesh with tiny shapes and
assert that same-bucket instances never duplicate compilation. Drives
``parallel.mesh.solve_on_mesh`` directly — no engine races, no bound
LPs — so the whole walk stays seconds-cheap inside the ``not slow``
gate while still executing the exact dispatch path (shard_map solver ->
AOT executable LRU) production solves take."""

import jax.numpy as jnp
import numpy as np
import pytest

from kafka_assignment_optimizer_tpu import build_instance
from kafka_assignment_optimizer_tpu.models.cluster import (
    Assignment,
    PartitionAssignment,
    Topology,
)
from kafka_assignment_optimizer_tpu.parallel import mesh
from kafka_assignment_optimizer_tpu.solvers.tpu import arrays, bucket
from kafka_assignment_optimizer_tpu.solvers.tpu.arrays import (
    geometric_temps,
)
from kafka_assignment_optimizer_tpu.solvers.tpu.seed import greedy_seed


def _tiny_instance(rng, n_parts, n_brokers=8, rf=2, n_racks=2):
    parts = [
        PartitionAssignment(
            "t", p, rng.choice(n_brokers, size=rf, replace=False).tolist()
        )
        for p in range(n_parts)
    ]
    topo = Topology(
        rack_of={b: f"r{b % n_racks}" for b in range(n_brokers)}
    )
    return build_instance(
        Assignment(partitions=parts), list(range(n_brokers)), topo
    )


@pytest.mark.soak
@pytest.mark.slow  # ~22 s; nightly. Tier-1 keeps warm-reuse pins at
# the decompose (test_second_decomposed_solve_compiles_nothing) and
# sharded-mesh (test_sharded_warm_resolve_compiles_nothing) layers.
def test_ladder_walk_no_duplicate_compiles(rng, monkeypatch):
    """For each of the first rungs: two instances with different
    partition counts in the bucket run the sweep solver; the second
    must add zero compiles, and both results must verify against the
    numpy oracle (padded rows inert end to end)."""
    compiles: list = []
    real = mesh._lower_and_compile

    def counting(fn, args):
        compiles.append(mesh._arg_signature(args))
        return real(fn, args)

    monkeypatch.setattr(mesh, "_lower_and_compile", counting)
    msh = mesh.make_mesh()
    temps = geometric_temps(2.0, 0.02, 8)
    import jax

    for rung in bucket.ladder(4):  # 32..112: tiny, seconds-cheap
        for i, n_parts in enumerate((rung - 5, rung - 2)):
            inst = _tiny_instance(rng, n_parts)
            assert bucket.part_bucket(inst.num_parts) == rung
            m = arrays.from_instance(
                inst, num_parts=rung, max_rf=bucket.rf_bucket(inst.max_rf)
            )
            seed = jnp.asarray(
                arrays.pad_candidate(greedy_seed(inst), m), jnp.int32
            )
            before = len(compiles)
            _state, pop_a, _pop_k, _curve = mesh.solve_on_mesh(
                m, seed, jax.random.PRNGKey(0), msh,
                chains_per_device=1, rounds=8, steps_per_round=1,
                engine="sweep", temps=temps,
            )
            if i == 1:
                assert len(compiles) == before, (
                    f"rung {rung}: same-bucket instance recompiled "
                    f"{compiles[before:]}"
                )
            pa = np.asarray(mesh.fetch_global(pop_a))
            # padded rows stayed null; real rows verify on the oracle
            assert (pa[:, inst.num_parts:, :] == inst.num_brokers).all()
            for shard in pa:
                real_a = shard[: inst.num_parts, : inst.max_rf]
                v = inst.violations(real_a)
                assert v["duplicate_in_partition"] == 0
                assert v["null_in_valid_slot"] == 0
                assert v["slot_out_of_range"] == 0
