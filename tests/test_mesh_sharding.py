"""Sharded solve mesh: per-bucket (chains × lanes) splits (ISSUE 19).

Pins the docs/MESH.md contracts:

- **bit-parity replay**: any ``(dc, dl)`` split of the same bucket
  reproduces the default chains-only trajectory BIT FOR BIT — the
  logical chain-shard count is always the device count, a lane split
  only re-tiles which physical device hosts which (shard, lane) block,
  and the in-shard ``cblk`` vmap axis composes with the mesh chain axis
  so every collective sees the identical participant set in the
  identical order. Pinned for the sync chunked path, the fused
  megachunk path, the Pallas-interpret scorer (the code path TPU
  compiles via Mosaic), and the engine-level batch dispatch under
  ``KAO_MESH_SHARDING``.
- **spec-invariant global layout**: ``init_lane_state`` and the solve
  outputs keep the same global ``[C, L, ...]`` shapes under every
  split, so callers never see the sharding.
- **never-guess chooser**: explicit env spec > ``off`` > evidence; the
  default split wins until a challenger AND the default both carry
  ``MESH_MIN_SOLVES`` observations and the challenger wins on
  throughput; multi-controller always takes the default (per-process
  evidence must not fork the SPMD executable).
- **warm cache**: each split is its own AOT executable
  (``lanes@{dc}x{dl}`` tag); a warm re-solve at the same split
  compiles nothing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafka_assignment_optimizer_tpu import build_instance
from kafka_assignment_optimizer_tpu.parallel import mesh as pm
from kafka_assignment_optimizer_tpu.solvers.tpu import arrays
from kafka_assignment_optimizer_tpu.solvers.tpu.engine import solve_tpu_batch
from kafka_assignment_optimizer_tpu.solvers.tpu.seed import greedy_seed
from kafka_assignment_optimizer_tpu.utils import gen

N_DEV = 8  # conftest forces --xla_force_host_platform_device_count=8


def _adv_instance(seed: int):
    sc = gen.adversarial(n_brokers=32, n_topics_low=3, n_topics_high=3,
                         parts_per_topic=10, seed=seed)
    return build_instance(sc.current, sc.broker_list, sc.topology)


@pytest.fixture
def lane_problem():
    """One 4-lane stacked problem (same bucket), shared per test."""
    insts = [_adv_instance(s) for s in (7, 8, 9, 10)]
    models = [arrays.from_instance(i) for i in insts]
    ms = arrays.stack_models(models)
    lane_seeds = np.stack(
        [np.asarray(greedy_seed(i), np.int32) for i in insts]
    )
    keys = jnp.stack([jax.random.PRNGKey(s) for s in range(4)])
    temps = arrays.geometric_temps(2.0, 0.02, 8)
    return ms, lane_seeds, keys, temps


@pytest.fixture(autouse=True)
def _fresh_evidence():
    pm.reset_mesh_adapt()
    yield
    pm.reset_mesh_adapt()


def _lane_solve(spec_dl, lane_problem, scorer="xla"):
    ms, lane_seeds, keys, temps = lane_problem
    mesh = pm.make_mesh(N_DEV, lane_devices=spec_dl)
    state = pm.init_lane_state(ms, lane_seeds, keys, mesh, 2)
    return pm.solve_lanes(ms, mesh, 2, temps, state=state, scorer=scorer)


# ------------------------------------------------------------ unit layer

def test_parse_mesh_sharding_grammar():
    assert pm.parse_mesh_sharding("auto") == ("auto", None)
    assert pm.parse_mesh_sharding("") == ("auto", None)
    assert pm.parse_mesh_sharding("off") == ("off", None)
    assert pm.parse_mesh_sharding("4x2") == ("spec", (4, 2))
    assert pm.parse_mesh_sharding(" 8X1 ") == ("spec", (8, 1))
    # typos degrade, never crash a solve
    assert pm.parse_mesh_sharding("4by2")[0] == "invalid"
    assert pm.parse_mesh_sharding("0x8")[0] == "invalid"


def test_candidate_shardings_divisibility():
    # dl must divide BOTH the device count and the lane count; the
    # default chains-only split always leads
    assert pm.candidate_shardings(8, 4) == [(8, 1), (4, 2), (2, 4)]
    assert pm.candidate_shardings(8, 6) == [(8, 1), (4, 2)]
    assert pm.candidate_shardings(8, 1) == [(8, 1)]
    assert pm.candidate_shardings(1, 4) == [(1, 1)]


def test_mesh_spec_roundtrip_and_validation():
    mesh = pm.make_mesh(N_DEV, lane_devices=2)
    assert pm.mesh_spec(mesh) == (4, 2)
    assert mesh.axis_names == (pm.AXIS, pm.AXIS_LANES)
    with pytest.raises(ValueError, match="does not divide"):
        pm.make_mesh(N_DEV, lane_devices=3)
    # default mesh is layout-identical to the historical chains split
    assert pm.mesh_spec(pm.make_mesh(N_DEV)) == (N_DEV, 1)


def test_choose_sharding_never_guesses(monkeypatch):
    bkt = (32, 8, 90, 3)
    monkeypatch.delenv(pm.MESH_ENV, raising=False)
    # no evidence → default
    assert pm.choose_sharding(bkt, 8, 4) == (8, 1)
    # a qualified challenger alone is NOT enough: the default itself
    # must have quorum before the chooser trusts the comparison
    for _ in range(pm.MESH_MIN_SOLVES):
        pm.note_sharding_evidence(bkt, (4, 2), lanes=4, solves=1,
                                  device_s=0.5)
    assert pm.choose_sharding(bkt, 8, 4) == (8, 1)
    for _ in range(pm.MESH_MIN_SOLVES):
        pm.note_sharding_evidence(bkt, (8, 1), lanes=4, solves=1,
                                  device_s=1.0)
    # both qualified, challenger 2x faster → challenger
    assert pm.choose_sharding(bkt, 8, 4) == (4, 2)
    # multi-controller SPMD must not fork the executable per process
    assert pm.choose_sharding(bkt, 8, 4, multi=True) == (8, 1)
    # env pin beats evidence; off and invalid degrade to default
    monkeypatch.setenv(pm.MESH_ENV, "2x4")
    assert pm.choose_sharding(bkt, 8, 4) == (2, 4)
    monkeypatch.setenv(pm.MESH_ENV, "off")
    assert pm.choose_sharding(bkt, 8, 4) == (8, 1)
    monkeypatch.setenv(pm.MESH_ENV, "3x3")  # does not fit 8 devices
    assert pm.choose_sharding(bkt, 8, 4) == (8, 1)


def test_mesh_snapshot_shape(monkeypatch):
    monkeypatch.delenv(pm.MESH_ENV, raising=False)
    bkt = (32, 8, 90, 3)
    pm.note_sharding_evidence(bkt, (4, 2), lanes=4, solves=2,
                              device_s=1.0)
    pm.make_mesh(N_DEV, lane_devices=2)
    snap = pm.mesh_snapshot()
    assert snap["axes"] == {pm.AXIS: 4, pm.AXIS_LANES: 2}
    assert snap["sharding_mode"] == "auto"
    assert snap["min_solves"] == pm.MESH_MIN_SOLVES
    (bucket_row,) = snap["buckets"].values()
    assert bucket_row["evidence"]["4x2"]["solves"] == 2
    assert set(snap["counters"]) == {"search_evals", "reshard_bytes"}


# ---------------------------------------------------------- parity layer

def test_sharded_lane_solve_bit_parity(lane_problem):
    """THE acceptance pin: every (dc, dl) split of an 8-device bucket
    replays the default split's sync chunked trajectory bit-for-bit,
    with identical global output shapes."""
    base = _lane_solve(1, lane_problem)
    for dl in (2, 4):
        out = _lane_solve(dl, lane_problem)
        for name, a, b in zip(("state", "best_a", "best_k", "curve"),
                              base, out):
            if name == "state":
                for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
                    assert la.shape == lb.shape
                    assert np.array_equal(np.asarray(la), np.asarray(lb))
                continue
            assert np.asarray(a).shape == np.asarray(b).shape
            assert np.array_equal(np.asarray(a), np.asarray(b)), (
                f"{name} diverged at split {N_DEV // dl}x{dl}"
            )


def test_sharded_interpret_scorer_bit_parity(lane_problem):
    """The Pallas-interpret scorer (the Mosaic code path) under a lane
    split matches the unsharded interpret run bit-for-bit."""
    base = _lane_solve(1, lane_problem, scorer="pallas-interpret")
    out = _lane_solve(2, lane_problem, scorer="pallas-interpret")
    assert np.array_equal(np.asarray(base[1]), np.asarray(out[1]))
    assert np.array_equal(np.asarray(base[2]), np.asarray(out[2]))
    assert np.array_equal(np.asarray(base[3]), np.asarray(out[3]))


def test_sharded_megachunk_bit_parity(lane_problem):
    """The fused K-chunk scan under a lane split replays the unsharded
    megachunk dispatch bit-for-bit (certs disarmed: independent lanes
    must not share an early exit)."""
    ms, lane_seeds, keys, temps = lane_problem
    temps_stack = jnp.stack([temps, temps])  # K=2 fused chunks
    outs = []
    for dl in (1, 2):
        mesh = pm.make_mesh(N_DEV, lane_devices=dl)
        state = pm.init_lane_state(ms, lane_seeds, keys, mesh, 2)
        outs.append(pm.solve_lanes_megachunk(
            ms, mesh, 2, temps_stack, state,
        ))
    for i, (a, b) in enumerate(zip(*outs)):
        for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            assert la.shape == lb.shape
            assert np.array_equal(np.asarray(la), np.asarray(lb)), (
                f"megachunk output {i} diverged under the 4x2 split"
            )


def test_sharded_warm_resolve_compiles_nothing(lane_problem, monkeypatch):
    """Each split is its own AOT executable: the second solve at the
    same (bucket, split) must reuse it — zero fresh compiles — and the
    donation round-trip leaves the answer unchanged."""
    ms, lane_seeds, keys, temps = lane_problem
    compiles: list = []
    real = pm._lower_and_compile

    def counting(fn, args):
        compiles.append(pm._arg_signature(args))
        return real(fn, args)

    monkeypatch.setattr(pm, "_lower_and_compile", counting)
    mesh = pm.make_mesh(N_DEV, lane_devices=2)
    state = pm.init_lane_state(ms, lane_seeds, keys, mesh, 2)
    r1 = pm.solve_lanes(ms, mesh, 2, temps, state=state)
    after_first = len(compiles)
    state = pm.init_lane_state(ms, lane_seeds, keys, mesh, 2)
    r2 = pm.solve_lanes(ms, mesh, 2, temps, state=state)
    assert len(compiles) == after_first, (
        f"warm sharded re-solve recompiled: {compiles[after_first:]}"
    )
    assert np.array_equal(np.asarray(r1[2]), np.asarray(r2[2]))


def test_sharding_search_files_evidence(lane_problem, monkeypatch):
    """The active search runs every candidate through the real dispatch
    path, proves parity against the default, and lands its timings in
    the same evidence table production solves feed."""
    monkeypatch.delenv(pm.MESH_ENV, raising=False)
    ms, lane_seeds, keys, temps = lane_problem
    bkt = (32, 8, 90, 3)
    results = pm.run_sharding_search(
        ms, lane_seeds, keys, temps, n_devices=N_DEV,
        chains_per_device=2, bucket_key=bkt, repeats=1,
    )
    assert [r["spec"] for r in results] == ["8x1", "4x2", "2x4"]
    assert all(r["parity_vs_default"] for r in results)
    assert all(r["warm_s"] > 0 for r in results)
    assert pm.mesh_counters()["search_evals"] == 3
    snap = pm.mesh_snapshot()
    (bucket_row,) = snap["buckets"].values()
    assert set(bucket_row["evidence"]) == {"8x1", "4x2", "2x4"}


# ---------------------------------------------------------- engine layer

def test_engine_batch_parity_under_forced_split(monkeypatch):
    """Engine-level acceptance: ``solve_tpu_batch`` under a forced
    ``KAO_MESH_SHARDING=4x2`` returns the byte-identical plans of the
    default split — the env pin changes placement, never results — and
    the dispatch filed sharding evidence for the bucket."""
    insts = [_adv_instance(s) for s in (7, 8, 9, 10)]
    monkeypatch.delenv(pm.MESH_ENV, raising=False)
    base = solve_tpu_batch(insts, seeds=0, engine="sweep", batch=8,
                           rounds=8)
    monkeypatch.setenv(pm.MESH_ENV, "4x2")
    sharded = solve_tpu_batch(insts, seeds=0, engine="sweep", batch=8,
                              rounds=8)
    for i, (rb, rs) in enumerate(zip(base, sharded)):
        assert np.array_equal(rb.a, rs.a), f"lane {i} diverged"
        assert rb.objective == rs.objective
    snap = pm.mesh_snapshot()
    specs = {s for row in snap["buckets"].values()
             for s in row["evidence"]}
    assert "4x2" in specs


def test_mesh_counters_reset_semantics():
    """reset_mesh_adapt drops BOTH the evidence table and the running
    counters — a maintenance reset can never leave a stale choice
    backed by zeroed evidence."""
    bkt = (32, 8, 90, 3)
    pm.note_sharding_evidence(bkt, (4, 2), lanes=4, solves=2,
                              device_s=1.0)
    with pm._MESH_LOCK:
        pm._MESH_COUNTERS["search_evals"] += 3
    assert pm.mesh_counters()["search_evals"] == 3
    assert pm.mesh_snapshot()["buckets"]
    pm.reset_mesh_adapt()
    assert pm.mesh_counters() == {"search_evals": 0, "reshard_bytes": 0}
    assert pm.mesh_snapshot()["buckets"] == {}


def test_make_solve_mesh_gating(monkeypatch):
    """The engine-facing factory only ever lane-splits a multi-lane
    sweep dispatch; chain engines, single-lane sites, and 1-device
    runs always get the historical chains-only mesh."""
    monkeypatch.delenv(pm.MESH_ENV, raising=False)
    bkt = (32, 8, 90, 3)
    assert pm.mesh_spec(pm.make_solve_mesh(N_DEV)) == (N_DEV, 1)
    assert pm.mesh_spec(
        pm.make_solve_mesh(N_DEV, lanes=4, engine="chain")
    ) == (N_DEV, 1)
    assert pm.mesh_spec(pm.make_solve_mesh(1, lanes=4)) == (1, 1)
    # with qualified evidence on both sides, the sweep dispatch follows
    # the per-bucket winner
    for _ in range(pm.MESH_MIN_SOLVES):
        pm.note_sharding_evidence(bkt, (8, 1), lanes=4, solves=1,
                                  device_s=1.0)
        pm.note_sharding_evidence(bkt, (4, 2), lanes=4, solves=1,
                                  device_s=0.5)
    assert pm.mesh_spec(
        pm.make_solve_mesh(N_DEV, lanes=4, bucket_key=bkt)
    ) == (4, 2)
    # multi-controller SPMD must not fork the executable per process
    assert pm.mesh_spec(
        pm.make_solve_mesh(N_DEV, lanes=4, bucket_key=bkt, multi=True)
    ) == (N_DEV, 1)
