"""Map-reduce decomposition (the PR-16 tentpole, docs/DECOMPOSE.md).

Pins the contracts the decomposed rung rests on:

- the splitter's global-band inheritance makes the stitched plan
  feasible for the ORIGINAL flat instance (the oracle check is a
  redundant proof, and the engine runs it anyway);
- the result always carries a certificate or an honest bound gap —
  never silence about decomposition loss;
- the sub-instances stack as lanes of ONE lane-padded executable, so
  a second decomposed solve in the same process compiles nothing;
- any reduce-phase fault degrades via the ``decompose_to_flat``
  ladder rung on all three views (counter, stats, log) and the flat
  path still lands a feasible plan;
- triggering is explicit or auto-by-size, and never engages on
  precompile/warm-start/checkpoint flows.
"""

import numpy as np
import pytest

from kafka_assignment_optimizer_tpu import build_instance
from kafka_assignment_optimizer_tpu.decompose import (
    STATS as DSTATS,
    maybe_decompose,
    should_decompose,
)
from kafka_assignment_optimizer_tpu.decompose.split import (
    infer_groups,
    split,
)
from kafka_assignment_optimizer_tpu.decompose.stitch import stitch
from kafka_assignment_optimizer_tpu.obs import flight
from kafka_assignment_optimizer_tpu.resilience import chaos, ladder
from kafka_assignment_optimizer_tpu.solvers.tpu import bucket
from kafka_assignment_optimizer_tpu.solvers.tpu.engine import solve_tpu
from kafka_assignment_optimizer_tpu.utils import gen


def _smoke_instance(seed=0):
    sc = gen.ultra_jumbo(seed=seed, **gen.SMOKE_KWARGS["ultra_jumbo"])
    return build_instance(**sc.kwargs)


@pytest.fixture(scope="module")
def smoke_inst():
    return _smoke_instance()


@pytest.fixture(scope="module")
def decomposed(smoke_inst):
    """One forced decomposed solve shared by the read-only pins."""
    res = solve_tpu(smoke_inst, seed=0, decompose=True, rounds=6)
    return smoke_inst, res


# ------------------------------------------------------------- split


def test_infer_groups_requires_az_prefixes():
    inst = _smoke_instance()
    got = infer_groups(inst)
    assert got is not None
    names, g_rack = got
    assert names == ["az0", "az1", "az2"]
    assert g_rack.shape == (inst.num_racks,)
    # a flat topology (no '-' prefix grouping) is not decomposable
    flat = build_instance(**gen.decommission(n_brokers=32, n_topics=4, parts_per_topic=50).kwargs)
    assert infer_groups(flat) is None
    assert split(flat) is None


def test_split_partitions_axes_and_inherits_bands(smoke_inst):
    sp = split(smoke_inst)
    assert sp is not None
    assert sp.n_groups == 3
    # brokers and racks are PARTITIONED: every index in exactly one
    # group, no group empty
    all_b = np.concatenate(sp.broker_idx)
    assert sorted(all_b.tolist()) == list(range(smoke_inst.num_brokers))
    all_p = np.concatenate(sp.part_idx)
    assert sorted(all_p.tolist()) == list(range(smoke_inst.num_parts))
    assert sp.uniform_shape  # the stacking invariant
    for g, sub in enumerate(sp.subs):
        # global scalar bands inherited verbatim; rack arrays sliced
        assert sub.broker_lo == smoke_inst.broker_lo
        assert sub.broker_hi == smoke_inst.broker_hi
        assert sub.leader_lo == smoke_inst.leader_lo
        assert sub.leader_hi == smoke_inst.leader_hi
        racks_g = np.nonzero(sp.group_of_rack == g)[0]
        np.testing.assert_array_equal(sub.rack_lo,
                                      smoke_inst.rack_lo[racks_g])
        np.testing.assert_array_equal(
            sub.part_rack_hi, smoke_inst.part_rack_hi[sp.part_idx[g]])
        # weights travel with their (partition, broker) pairs
        cols = np.append(sp.broker_idx[g], smoke_inst.num_brokers)
        np.testing.assert_array_equal(
            sub.w_leader,
            smoke_inst.w_leader[np.ix_(sp.part_idx[g], cols)])


def test_stitch_translates_lane_plans_to_global_ids(smoke_inst):
    sp = split(smoke_inst)
    # a fake per-lane plan: every partition's slot 0 on local broker 0,
    # rest null — the stitch must translate to each group's first
    # GLOBAL broker and leave nulls null
    R = smoke_inst.a0.shape[1]
    plans = []
    for sub in sp.subs:
        a = np.full((sub.num_parts, R), sub.num_brokers, np.int32)
        a[:, 0] = 0
        plans.append(a)
    a = stitch(smoke_inst, sp, plans)
    B = smoke_inst.num_brokers
    for g in range(sp.n_groups):
        np.testing.assert_array_equal(
            a[sp.part_idx[g], 0], sp.broker_idx[g][0])
    assert (a[:, 1:] == B).all()


# ------------------------------------------- the decomposed solve


def test_decomposed_solve_feasible_with_provenance(decomposed):
    inst, res = decomposed
    assert res.stats["engine"] == "decomposed"
    assert res.stats["feasible"]
    # the oracle proof on the ORIGINAL flat instance, re-run here
    assert sum(inst.violations(res.a).values()) == 0
    d = res.stats["decompose"]
    assert d["subproblems"] == 3
    assert d["groups"] == ["az0", "az1", "az2"]
    assert d["uniform_shape"] is True
    assert d["sub_shape"]["lane_bucket"] >= d["subproblems"]
    assert res.stats["bucket_parts"] == d["sub_shape"]["bucket_parts"]


def test_certificate_or_gap_always_reported(decomposed):
    _, res = decomposed
    d = res.stats["decompose"]
    assert isinstance(d["certified"], bool)
    if not d["certified"]:
        # an honest non-negative gap against the FLAT upper bound
        assert isinstance(d["bound_gap"], int)
        assert d["bound_gap"] >= 0
    else:
        assert res.stats["proved_optimal"]


def test_flight_record_carries_decompose_block(smoke_inst):
    res = solve_tpu(smoke_inst, seed=3, decompose=True, rounds=6)
    recs = [r for r in flight.recent(20, kind="solve")
            if r.get("decompose")]
    assert recs, "no solve record with a decompose block"
    rec = recs[-1]
    d = res.stats["decompose"]
    assert rec["decompose"]["subproblems"] == d["subproblems"]
    assert rec["decompose"]["certified"] == d["certified"]
    assert rec["decompose"]["bound_gap"] == d["bound_gap"]
    # ONE record for the whole solve: the map lanes are suppressed
    assert rec["engine"] == "decomposed"


def test_second_decomposed_solve_compiles_nothing(smoke_inst):
    # the fixture (or a prior test) already warmed the lane executable
    solve_tpu(smoke_inst, seed=1, decompose=True, rounds=6)
    before = bucket.STATS.snapshot()
    res = solve_tpu(smoke_inst, seed=2, decompose=True, rounds=6)
    after = bucket.STATS.snapshot()
    assert res.stats["engine"] == "decomposed"
    assert after["compiles_total"] == before["compiles_total"], (
        before, after)


# ------------------------------------------------- degradation


def test_reduce_fault_degrades_to_flat_three_views(smoke_inst):
    before_rung = ladder.snapshot().get("decompose_to_flat", 0)
    before_fb = DSTATS.snapshot()["counters"]["fallback"]
    chaos.arm("decompose_reduce")
    try:
        res = solve_tpu(smoke_inst, seed=0, decompose=True, rounds=6)
    finally:
        chaos.disarm()
    # the flat path landed a feasible plan anyway
    assert res.stats.get("engine") != "decomposed"
    assert sum(smoke_inst.violations(res.a).values()) == 0
    # three-view agreement: ladder counter, ambient stats, decompose
    # counters (the log line rides note_rung)
    assert ladder.snapshot()["decompose_to_flat"] == before_rung + 1
    assert "decompose_to_flat" in res.stats.get("degradations", [])
    assert DSTATS.snapshot()["counters"]["fallback"] == before_fb + 1


def test_unsplittable_instance_falls_through_to_flat():
    before = DSTATS.snapshot()["counters"]["unsplittable"]
    inst = build_instance(**gen.decommission(n_brokers=32, n_topics=4, parts_per_topic=50).kwargs)
    res = solve_tpu(inst, seed=0, decompose=True)
    assert res.stats.get("engine") != "decomposed"
    assert res.stats["feasible"]
    assert DSTATS.snapshot()["counters"]["unsplittable"] == before + 1


# ------------------------------------------------- triggering


def test_should_decompose_kwarg_env_auto(monkeypatch):
    inst = _smoke_instance()
    # explicit kwarg wins over everything
    assert should_decompose(inst, True) is True
    assert should_decompose(inst, False) is False
    # env force
    monkeypatch.setenv("KAO_DECOMPOSE", "1")
    assert should_decompose(inst, None) is True
    monkeypatch.setenv("KAO_DECOMPOSE", "0")
    assert should_decompose(inst, None) is False
    # auto: below the default 150k threshold the smoke case stays flat
    monkeypatch.delenv("KAO_DECOMPOSE", raising=False)
    assert should_decompose(inst, None) is False
    monkeypatch.setenv("KAO_DECOMPOSE_AUTO_PARTS",
                       str(inst.num_parts))
    assert should_decompose(inst, None) is True


@pytest.mark.soak
@pytest.mark.slow  # ~17 s; nightly. Tier-1 keeps the decompose gate
# pins (should_decompose env/auto) and the warm-reuse pin
# (test_second_decomposed_solve_compiles_nothing).
def test_warm_start_and_precompile_skip_decompose(smoke_inst,
                                                 monkeypatch):
    # even force-on, the engine's gate keeps adapted-plan warm starts
    # and precompile passes on the flat path
    monkeypatch.setenv("KAO_DECOMPOSE", "1")
    before = DSTATS.snapshot()["counters"]["solves"]
    res = solve_tpu(smoke_inst, seed=0, precompile=True)
    assert res.stats.get("engine") != "decomposed"
    assert DSTATS.snapshot()["counters"]["solves"] == before


def test_maybe_decompose_returns_none_on_flat_topology():
    inst = build_instance(**gen.decommission(n_brokers=32, n_topics=4, parts_per_topic=50).kwargs)
    assert maybe_decompose(inst, seed=0) is None
