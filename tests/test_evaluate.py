"""Plan auditing (``api.evaluate`` / CLI ``--evaluate``): score an
EXISTING plan — the reference's worked demo is exactly this comparison
(Kafka's own tool proposes a near-total reshuffle where one move
suffices, ``/root/reference/README.md:65-91``).
"""

from __future__ import annotations

import json
import subprocess
import sys

import pytest

from kafka_assignment_optimizer_tpu.api import evaluate, optimize
from kafka_assignment_optimizer_tpu.models.cluster import (
    demo_assignment,
    demo_broker_list,
    demo_topology,
)


@pytest.fixture(scope="module")
def demo_solved():
    return optimize(
        demo_assignment(), demo_broker_list(), demo_topology(),
        solver="milp",
    )


def test_evaluate_certifies_the_optimal_plan(demo_solved):
    rep = evaluate(
        demo_assignment(), demo_broker_list(),
        demo_solved.assignment, demo_topology(),
    )
    assert rep["feasible"]
    assert rep["replica_moves"] == 1 == rep["min_moves_lower_bound"]
    assert rep["objective_weight"] == rep["objective_upper_bound"]
    assert rep["proven_optimal"]


def test_evaluate_flags_the_current_assignment_infeasible():
    """The unmodified current assignment still references the
    decommissioned broker 19 — the audit must flag it, not crash."""
    rep = evaluate(
        demo_assignment(), demo_broker_list(),
        demo_assignment(), demo_topology(),
    )
    assert not rep["feasible"]
    assert rep["violations"]["null_in_valid_slot"] > 0
    assert not rep["proven_optimal"]


def test_evaluate_scores_a_wasteful_reshuffle(demo_solved):
    """A feasible plan that moves more than necessary: feasible but not
    optimal, with the move gap quantified (the reference's critique of
    kafka-reassign-partitions, README.md:13-15)."""
    plan = json.loads(demo_solved.assignment.to_json())
    # swap two partitions' replica sets: still feasible (same multiset
    # of placements) but 4 extra moves
    p2 = next(p for p in plan["partitions"] if p["partition"] == 2)
    p5 = next(p for p in plan["partitions"] if p["partition"] == 5)
    p2["replicas"], p5["replicas"] = p5["replicas"], p2["replicas"]
    rep = evaluate(
        demo_assignment(), demo_broker_list(), plan, demo_topology()
    )
    assert rep["feasible"]
    assert rep["replica_moves"] > rep["min_moves_lower_bound"]
    assert not rep["proven_optimal"]


def test_evaluate_rejects_mismatched_plan():
    plan = json.loads(demo_assignment().to_json())
    plan["partitions"] = plan["partitions"][:-1]  # drop one partition
    with pytest.raises(ValueError, match="missing partition"):
        evaluate(
            demo_assignment(), demo_broker_list(), plan, demo_topology()
        )


def test_evaluate_rejects_over_replicated_plan(demo_solved):
    """An over-replicated plan cannot be silently truncated into a
    'feasible' audit — the index space cannot represent the extras."""
    plan = json.loads(demo_solved.assignment.to_json())
    for p in plan["partitions"]:
        extra = next(
            b for b in range(19) if b not in p["replicas"]
        )
        p["replicas"] = p["replicas"] + [extra]
    with pytest.raises(ValueError, match="target RF"):
        evaluate(
            demo_assignment(), demo_broker_list(), plan, demo_topology()
        )


def test_evaluate_reports_duplicate_brokers_as_violation(demo_solved):
    """A duplicated broker in a replica list is an infeasibility to
    REPORT (duplicate_in_partition), not a parse error."""
    plan = json.loads(demo_solved.assignment.to_json())
    p1 = next(p for p in plan["partitions"] if p["partition"] == 1)
    p1["replicas"] = [p1["replicas"][0], p1["replicas"][0]]
    rep = evaluate(
        demo_assignment(), demo_broker_list(), plan, demo_topology()
    )
    assert not rep["feasible"]
    assert rep["violations"]["duplicate_in_partition"] > 0


def test_cli_evaluate_roundtrip(tmp_path, demo_solved):
    cur = tmp_path / "current.json"
    cur.write_text(demo_assignment().to_json())
    plan = tmp_path / "plan.json"
    plan.write_text(demo_solved.assignment.to_json())
    r = subprocess.run(
        [sys.executable, "-m", "kafka_assignment_optimizer_tpu",
         "--input", str(cur), "--broker-list", "0-18",
         "--topology", "even-odd", "--evaluate", str(plan)],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr
    rep = json.loads(r.stdout)
    assert rep["proven_optimal"] and rep["replica_moves"] == 1

    # infeasible plan -> exit 3
    r = subprocess.run(
        [sys.executable, "-m", "kafka_assignment_optimizer_tpu",
         "--input", str(cur), "--broker-list", "0-18",
         "--topology", "even-odd", "--evaluate", str(cur)],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 3, r.stderr
    assert not json.loads(r.stdout)["feasible"]


def test_evaluate_rejects_duplicated_partition_in_plan(demo_solved):
    """A plan listing the same (topic, partition) twice — possibly with
    conflicting replica lists — is a structural mismatch, not something
    to silently dedupe last-wins (ADVICE r2)."""
    plan = json.loads(demo_solved.assignment.to_json())
    dup = dict(plan["partitions"][1])
    dup["replicas"] = list(reversed(dup["replicas"]))
    plan["partitions"].append(dup)
    with pytest.raises(ValueError, match="more than once"):
        evaluate(
            demo_assignment(), demo_broker_list(), plan, demo_topology()
        )


def test_evaluate_time_budget_degrades_not_blocks(demo_solved):
    """An (absurdly) tight time budget must not crash or hang the audit:
    expired bound tiers degrade to cheaper bounds; feasibility and the
    move diff are still exact."""
    import time

    t0 = time.perf_counter()
    rep = evaluate(
        demo_assignment(), demo_broker_list(),
        demo_solved.assignment, demo_topology(),
        time_budget_s=1e-9,
    )
    assert time.perf_counter() - t0 < 30
    assert rep["feasible"] and rep["replica_moves"] == 1
    # with a real budget the audit certifies as before
    rep = evaluate(
        demo_assignment(), demo_broker_list(),
        demo_solved.assignment, demo_topology(),
        time_budget_s=60,
    )
    assert rep["proven_optimal"]
