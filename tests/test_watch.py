"""Cluster-watch delta API (ISSUE 7, docs/WATCH.md): typed events,
epoch fencing, the durable plan store, storm coalescing/backpressure,
the warm-start adaptation, and the serve-layer delta endpoints —
including the two acceptance proofs: a fenced epoch provably triggers
no solve (metrics + trace assert), and the plan store survives a
``kill -9`` + restart with the stream resuming at the correct epoch."""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from kafka_assignment_optimizer_tpu import serve as srv
from kafka_assignment_optimizer_tpu.models.cluster import (
    Assignment,
    Topology,
    demo_assignment,
)
from kafka_assignment_optimizer_tpu.models.instance import build_instance
from kafka_assignment_optimizer_tpu.obs import trace as otrace
from kafka_assignment_optimizer_tpu.resilience.budget import Budget
from kafka_assignment_optimizer_tpu.watch import adapt as wadapt
from kafka_assignment_optimizer_tpu.watch import events as wev
from kafka_assignment_optimizer_tpu.watch import manager as wman
from kafka_assignment_optimizer_tpu.watch import store as wstore


def _assign(P=8, B=4, rf=2):
    return {
        "version": 1,
        "partitions": [
            {"topic": "t", "partition": p,
             "replicas": [(p + i) % B for i in range(rf)]}
            for p in range(P)
        ],
    }


def _bootstrap(epoch=1, B=4, **extra):
    return {
        "type": "bootstrap", "epoch": epoch,
        "assignment": _assign(B=B), "brokers": list(range(B)),
        "topology": "even-odd", **extra,
    }


# --------------------------------------------------------------------------
# events: grammar + pure transitions
# --------------------------------------------------------------------------


@pytest.mark.parametrize("bad", [
    "not an object",
    {"type": "nope", "epoch": 1},
    {"type": "broker_drain"},                       # no epoch
    {"type": "broker_drain", "epoch": -1, "brokers": [1]},
    {"type": "broker_drain", "epoch": True, "brokers": [1]},
    {"type": "broker_drain", "epoch": 1, "brokers": []},
    {"type": "broker_drain", "epoch": 1, "brokers": [1.5]},
    {"type": "rack_fail", "epoch": 1},              # no rack
    {"type": "partition_growth", "epoch": 1, "topic": "t"},  # no add
    {"type": "partition_growth", "epoch": 1, "topic": "t", "add": 0},
    {"type": "rf_change", "epoch": 1},              # no rf
    {"type": "rf_change", "epoch": 1, "rf": "three"},
    {"type": "bootstrap", "epoch": 1},              # no assignment
])
def test_validate_event_rejects_malformed(bad):
    with pytest.raises(wev.EventError):
        wev.validate_event(bad)


def test_first_event_must_be_bootstrap():
    with pytest.raises(wev.EventError, match="bootstrap"):
        wev.apply_event(None, "c", {"type": "broker_drain", "epoch": 1,
                                    "brokers": [1]})


def test_apply_event_day_of_transitions():
    st = wev.apply_event(None, "c", _bootstrap(B=6))
    assert st.epoch == 1 and st.brokers == [0, 1, 2, 3, 4, 5]
    assert st.topology is not None

    st = wev.apply_event(st, "c", {"type": "broker_drain", "epoch": 2,
                                   "brokers": [5]})
    assert st.brokers == [0, 1, 2, 3, 4] and st.drained == [5]
    # drained brokers stay racked (they may come back)
    assert 5 in st.topology.rack_of

    st = wev.apply_event(st, "c", {"type": "broker_remove", "epoch": 3,
                                   "brokers": [5]})
    assert st.drained == [] and 5 not in st.topology.rack_of

    st = wev.apply_event(st, "c", {"type": "partition_growth", "epoch": 4,
                                   "topic": "t", "add": 3})
    grown = [p for p in st.assignment.partitions if p.topic == "t"]
    assert len(grown) == 8 + 3
    # new partitions are EMPTY (placing them costs honest moves) and
    # their RF must be pinned explicitly in state.rf
    empties = [p for p in grown if not p.replicas]
    assert len(empties) == 3
    assert st.rf is not None

    st = wev.apply_event(st, "c", {"type": "rf_change", "epoch": 5,
                                   "rf": 3})
    assert st.rf == 3

    st = wev.apply_event(st, "c", {"type": "broker_add", "epoch": 6,
                                   "brokers": [7], "rack": "z"})
    assert 7 in st.brokers and st.topology.rack(7) == "z"

    rack = st.topology.rack(0)
    st2 = wev.apply_event(st, "c", {"type": "rack_fail", "epoch": 7,
                                    "rack": rack})
    assert all(st2.topology.rack(b) != rack for b in st2.brokers)
    assert st2.epoch == 7

    # round-trips through the persistence dict form
    assert wev.ClusterState.from_dict(st2.to_dict()).to_dict() \
        == st2.to_dict()


def test_transitions_guard_impossible_states():
    st = wev.apply_event(None, "c", _bootstrap())
    with pytest.raises(wev.EventError, match="zero eligible"):
        wev.apply_event(st, "c", {"type": "broker_drain", "epoch": 2,
                                  "brokers": [0, 1, 2, 3]})
    with pytest.raises(wev.EventError, match="unknown broker"):
        wev.apply_event(st, "c", {"type": "broker_drain", "epoch": 2,
                                  "brokers": [99]})
    with pytest.raises(wev.EventError, match="already eligible"):
        wev.apply_event(st, "c", {"type": "broker_add", "epoch": 2,
                                  "brokers": [0]})
    # a racked topology demands a rack for a genuinely new broker
    with pytest.raises(wev.EventError, match="rack"):
        wev.apply_event(st, "c", {"type": "broker_add", "epoch": 2,
                                  "brokers": [9]})
    with pytest.raises(wev.EventError, match="needs an explicit"):
        wev.apply_event(st, "c", {"type": "partition_growth", "epoch": 2,
                                  "topic": "brand-new", "add": 1})


# --------------------------------------------------------------------------
# store: atomic write-rename + fingerprint-verified load
# --------------------------------------------------------------------------


def test_store_roundtrip_and_corruption(tmp_path):
    store = wstore.PlanStore(tmp_path)
    st = wev.apply_event(None, "c1", _bootstrap())
    store.save(wstore.StoreRecord(st, plan=_assign(), plan_epoch=1,
                                  plan_report={"replica_moves": 0}))
    rec = store.load("c1")
    assert rec is not None
    assert rec.state.epoch == 1 and rec.plan_epoch == 1
    assert rec.state.to_dict() == st.to_dict()
    assert store.list_clusters() == ["c1"]
    assert store.load("nope") is None

    # a tampered record (bit rot, hand edit) fails the fingerprint and
    # is treated as ABSENT, never trusted — fencing from a corrupt
    # epoch would reject a healthy client stream
    path = tmp_path / "c1.json"
    doc = json.loads(path.read_text())
    doc["state"]["epoch"] = 999
    path.write_text(json.dumps(doc))
    assert store.load("c1") is None

    # a torn half-write (the failure os.replace prevents, simulated)
    path.write_text('{"version": 1, "sta')
    assert store.load("c1") is None

    with pytest.raises(ValueError):
        store.save(wstore.StoreRecord(wev.ClusterState(
            cluster_id="../evil", epoch=1,
            assignment=Assignment.from_dict(_assign()), brokers=[0],
        )))


# --------------------------------------------------------------------------
# adapt: previous plan -> warm candidate for the post-event instance
# --------------------------------------------------------------------------


def test_adapt_keeps_survivors_and_evicts_dead():
    B, P, rf = 8, 24, 3
    cur = Assignment.from_dict(_assign(P=P, B=B, rf=rf))
    topo = Topology.even_odd(list(range(B)))
    inst = build_instance(cur, list(range(B - 2)), topo, None)
    a, reason = wadapt.adapt_plan(inst, cur)
    assert a is not None, reason
    # structural families hold by construction
    viol = inst.violations(a)
    assert viol["slot_out_of_range"] == 0
    assert viol["null_in_valid_slot"] == 0
    assert viol["duplicate_in_partition"] == 0
    # every surviving replica stays in its slot; the dead brokers are
    # gone everywhere
    idx_of = {int(b): i for i, b in enumerate(inst.broker_ids)}
    plan = inst.decode(a)
    by_key = plan.by_key()
    for p in cur.partitions:
        new = by_key[p.key].replicas
        assert B - 1 not in new and B - 2 not in new
        surv = [b for b in p.replicas if b in idx_of]
        assert new[: len(surv)] == surv

    # a partition the previous plan never saw (growth) fills greedily
    grown = Assignment.from_dict(_assign(P=P + 4, B=B, rf=rf))
    inst2 = build_instance(grown, list(range(B - 2)), topo, None)
    a2, reason2 = wadapt.adapt_plan(inst2, cur)
    assert a2 is not None, reason2
    assert inst2.violations(a2)["null_in_valid_slot"] == 0


def test_adapt_band_repair_after_recovery():
    """A recovery event (brokers come back) leaves no holes, so the
    adapted candidate is the previous plan verbatim — pass 3 must
    repair the bands the restored brokers re-tightened with EXACTLY the
    forced number of moves, never breaking a hard family."""
    B, P, rf = 8, 24, 3
    topo = Topology.even_odd(list(range(B)))
    # previous plan lives entirely on brokers 0..5; 6 and 7 come back
    prev = Assignment.from_dict(_assign(P=P, B=6, rf=rf))
    inst = build_instance(prev, list(range(B)), topo, None)
    a, reason = wadapt.adapt_plan(inst, prev)
    assert a is not None, reason
    assert "rebalanced=" in reason
    viol = inst.violations(a)
    # every band except the leader band (the engine's exact reseat
    # repairs that one at admission) is satisfied
    assert all(
        v == 0 for k, v in viol.items() if k != "leader_balance"
    ), viol
    # move-minimal: r_tot=72 over 8 brokers pins broker_lo=9, so the
    # two restored brokers force exactly 2*9 incoming moves and the
    # repair must not move anything else
    assert int(inst.move_count(a)) == 2 * int(inst.broker_lo)


def test_engine_warm_starts_leader_violating_candidate():
    """A candidate whose ONLY violation is the leader band must be
    reseated at admission and WIN the seed rank — not be outranked by
    the greedy seed over a violation the engine repairs exactly."""
    from kafka_assignment_optimizer_tpu.solvers.tpu.engine import (
        _validate_warm_start,
    )

    B, P, rf = 8, 24, 3
    topo = Topology.even_odd(list(range(B)))
    prev = Assignment.from_dict(_assign(P=P, B=6, rf=rf))
    inst = build_instance(prev, list(range(B)), topo, None)
    a, reason = wadapt.adapt_plan(inst, prev)
    assert a is not None, reason
    assert inst.violations(a)["leader_balance"] > 0
    out = _validate_warm_start(inst, a)
    assert out is not None
    assert sum(inst.violations(out).values()) == 0, inst.violations(out)
    # the reseat is metadata-only: replica sets untouched
    for p in range(inst.num_parts):
        assert (
            sorted(map(int, out[p][out[p] < inst.num_brokers]))
            == sorted(map(int, a[p][a[p] < inst.num_brokers]))
        ), p


def test_engine_rejects_invalid_warm_start_onto_ladder():
    from kafka_assignment_optimizer_tpu.solvers.tpu.engine import solve_tpu

    cur = Assignment.from_dict(_assign(P=12, B=6, rf=2))
    inst = build_instance(cur, list(range(6)),
                          Topology.even_odd(list(range(6))), None)
    # duplicate broker 0 in every slot: a structural violation the
    # annealer's move set preserves — must be REJECTED onto the ladder
    bad = np.zeros((inst.num_parts, inst.max_rf), dtype=np.int32)
    res = solve_tpu(inst, seed=0, time_limit_s=30, warm_start=bad)
    assert res.stats["feasible"]
    assert not res.stats["warm_started"]
    assert "warm_start_rejected" in (res.stats.get("degradations") or [])


# --------------------------------------------------------------------------
# manager: fencing, coalescing, backpressure, durability
# --------------------------------------------------------------------------


def _stub_registry(store=None, solve_s=0.0, **kw):
    calls = []

    def solve_fn(state, prev_plan, budget):
        calls.append(state.epoch)
        if solve_s:
            time.sleep(solve_s)
        return state.assignment.to_dict(), {
            "replica_moves": 0, "feasible": True,
            "solver_warm_started": prev_plan is not None,
        }

    reg = wman.WatchRegistry(solve_fn, store, window_s=0.0, **kw)
    return reg, calls


def test_epoch_fencing_rejects_without_solving():
    reg, calls = _stub_registry()
    reg.handle_event("c", _bootstrap(epoch=5))
    assert calls == [5]
    # replayed AND stale epochs fence BEFORE any state change or solve
    for got in (5, 4, 0):
        with pytest.raises(wman.FencedEpoch) as e:
            reg.handle_event("c", {"type": "broker_drain", "epoch": got,
                                   "brokers": [3]})
        assert e.value.got == got and e.value.current == 5
    snap = reg.snapshot()
    assert snap["fenced_total"] == 3
    assert snap["solves_total"] == 1 and calls == [5]
    # the cluster state did not move
    assert reg.get_cluster("c")["epoch"] == 5
    assert reg.get_cluster("c")["brokers"] == [0, 1, 2, 3]


def test_bad_cluster_ids_rejected():
    reg, _ = _stub_registry()
    for cid in ("", "a/b", ".hidden", "x" * 65, "sp ace"):
        with pytest.raises(wev.EventError):
            reg.handle_event(cid, _bootstrap())


def test_storm_coalesces_to_one_resolve_and_cancels_superseded():
    reg, calls = _stub_registry(solve_s=0.4)
    reg.window_s = 0.01
    out = {}
    t = threading.Thread(
        target=lambda: out.update(first=reg.handle_event("c", _bootstrap()))
    )
    t.start()
    time.sleep(0.1)  # the bootstrap solve is now in flight
    acks = [
        reg.handle_event("c", {"type": "broker_drain", "epoch": 2,
                               "brokers": [3]}),
        reg.handle_event("c", {"type": "broker_add", "epoch": 3,
                               "brokers": [3]}),
        reg.handle_event("c", {"type": "broker_drain", "epoch": 4,
                               "brokers": [2]}),
    ]
    t.join()
    assert all(a["status"] == "accepted" for a in acks)
    assert [a["epoch"] for a in acks] == [2, 3, 4]
    # ONE coalesced re-solve of the LATEST state, not three
    deadline = time.time() + 10
    while time.time() < deadline:
        info = reg.get_cluster("c")
        if not info["solving"] and info["pending_events"] == 0:
            break
        time.sleep(0.02)
    assert info["plan_epoch"] == 4
    snap = reg.snapshot()
    assert snap["coalesced_total"] == 3
    assert snap["solves_total"] == 2          # bootstrap + one drain
    assert snap["superseded_total"] == 1      # the in-flight cancel
    assert calls == [1, 4]


def test_drain_solve_failure_retries_then_releases_role():
    """Events acked 202 behind a failing re-solve must not strand: the
    drain thread retries with backoff (DRAIN_RETRIES), and even after
    giving up, the durable state is intact and the NEXT admitted event
    re-solves the latest state."""
    calls = []
    fail = {"n": 2}  # first drain attempt(s) blow up, then recover

    def solve_fn(state, prev_plan, budget):
        calls.append(state.epoch)
        if state.epoch > 1 and fail["n"] > 0:
            fail["n"] -= 1
            time.sleep(0.05)
            raise RuntimeError("transient solver fault")
        if state.epoch == 1:
            time.sleep(0.3)  # keep the bootstrap in flight
        return state.assignment.to_dict(), {
            "replica_moves": 0, "feasible": True,
        }

    reg = wman.WatchRegistry(solve_fn, None, window_s=0.01)
    t = threading.Thread(target=reg.handle_event,
                         args=("c", _bootstrap()))
    t.start()
    time.sleep(0.1)
    ack = reg.handle_event("c", {"type": "broker_drain", "epoch": 2,
                                 "brokers": [3]})
    assert ack["status"] == "accepted"
    t.join()
    deadline = time.time() + 15
    while time.time() < deadline:
        info = reg.get_cluster("c")
        if not info["solving"] and info["plan_epoch"] == 2:
            break
        time.sleep(0.02)
    # the drain retried past the two transient faults and committed
    assert info["plan_epoch"] == 2
    snap = reg.snapshot()
    assert snap["solve_errors_total"] == 2
    assert calls.count(2) == 3  # two failures + the committed retry


def test_rebootstrap_coalesced_mid_solve_is_not_clobbered():
    """A re-bootstrap (operator re-declares the whole assignment) that
    coalesces behind an in-flight solve bumps the state's generation;
    the solve's commit must NOT merge its old-world plan over the
    re-declared assignment — the drain re-solve plans against the new
    ground truth instead."""
    def solve_fn(state, prev_plan, budget):
        if state.generation == 0:
            time.sleep(0.3)  # hold the gen-0 solve in flight
            plan = state.assignment.to_dict()
            # a recognizably old-world plan: every replica list reversed
            for p in plan["partitions"]:
                p["replicas"] = list(reversed(p["replicas"]))
            return plan, {"replica_moves": 1, "feasible": True}
        return state.assignment.to_dict(), {
            "replica_moves": 0, "feasible": True,
        }

    reg = wman.WatchRegistry(solve_fn, None, window_s=0.0)
    t = threading.Thread(target=reg.handle_event,
                         args=("c", _bootstrap()))
    t.start()
    time.sleep(0.1)
    ack = reg.handle_event("c", _bootstrap(epoch=2))
    assert ack["status"] == "accepted"
    t.join()
    deadline = time.time() + 10
    while time.time() < deadline:
        info = reg.get_cluster("c")
        if not info["solving"] and info["pending_events"] == 0:
            break
        time.sleep(0.02)
    # the re-declared assignment won: plan_epoch reflects the drain
    # re-solve of the NEW generation, and no partition carries the
    # old-world reversed replica lists
    assert info["epoch"] == 2 and info["plan_epoch"] == 2
    declared = {
        (p["topic"], p["partition"]): p["replicas"]
        for p in _bootstrap()["assignment"]["partitions"]
    }
    for p in info["plan"]["partitions"]:
        assert p["replicas"] == declared[(p["topic"], p["partition"])]


def test_broker_add_rejects_unparseable_racks_keys():
    """JSON object keys are strings; a racks key that cannot parse as a
    broker id must fail VALIDATION (a 400-class EventError), not leak a
    raw ValueError out of apply_event mid-replay."""
    st = wev.apply_event(None, "c", _bootstrap())
    with pytest.raises(wev.EventError, match="racks"):
        wev.apply_event(st, "c", {
            "type": "broker_add", "epoch": 2, "brokers": [9],
            "racks": {"broker-9": "r1"},
        })


def test_storm_backpressure_sheds_past_backlog():
    reg, _ = _stub_registry(solve_s=0.6, max_backlog=1)
    t = threading.Thread(target=reg.handle_event,
                         args=("c", _bootstrap()))
    t.start()
    time.sleep(0.1)
    reg.handle_event("c", {"type": "broker_drain", "epoch": 2,
                           "brokers": [3]})  # fills the backlog
    with pytest.raises(wman.StormShed) as e:
        reg.handle_event("c", {"type": "broker_add", "epoch": 3,
                               "brokers": [3]})
    assert e.value.retry_after_s > 0
    t.join()
    assert reg.snapshot()["storm_sheds_total"] == 1
    # nothing admitted was dropped: epoch 2 was applied, epoch 3 never
    deadline = time.time() + 10
    while time.time() < deadline:
        info = reg.get_cluster("c")
        if not info["solving"]:
            break
        time.sleep(0.02)
    assert info["epoch"] == 2


def test_cancelled_budget_retires_ladder_with_deadline_truncated():
    """A superseded watch solve is reclaimed through the EXISTING
    deadline machinery: Budget.cancel() from another thread moves the
    effective deadline into the past, so the very next boundary gate
    retires the ladder with its best-so-far plan and the
    ``deadline_truncated`` rung — no new cancellation protocol."""
    from kafka_assignment_optimizer_tpu.solvers.tpu.engine import solve_tpu
    from kafka_assignment_optimizer_tpu.utils import gen

    sc = gen.adversarial(**gen.SMOKE_KWARGS["adversarial"])
    inst = build_instance(sc.current, sc.broker_list, sc.topology)
    b = Budget(None)
    b.cancel()
    # cert_min_savings_s keeps the boundary certifier out of the way:
    # this smoke instance certifies at the first boundary, which would
    # end the ladder before the cancellation gate can be observed
    res = solve_tpu(inst, seed=0, engine="sweep", batch=8, rounds=64,
                    steps_per_round=1, budget=b, cert_min_savings_s=1e9)
    assert res.stats["timed_out"]
    assert "deadline_truncated" in res.stats["degradations"]
    assert res.stats["rounds_run"] < 64
    assert res.stats["feasible"]


def test_budget_cancel_collapses_remaining():
    b = Budget(None)
    assert b.remaining() is None and not b.expired()
    b.cancel()
    assert b.remaining() == 0.0 and b.expired()
    b2 = Budget(100.0)
    assert b2.remaining() > 90
    b2.cancel()
    assert b2.remaining() == 0.0


def test_registry_restart_resumes_at_persisted_epoch(tmp_path):
    store = wstore.PlanStore(tmp_path)
    reg, calls = _stub_registry(store=store)
    reg.handle_event("c", _bootstrap())
    reg.handle_event("c", {"type": "broker_drain", "epoch": 2,
                           "brokers": [3]})
    # a fresh registry over the same store (process restart): state,
    # plan, and the fence resume exactly where the old process left off
    reg2, calls2 = _stub_registry(store=store)
    info = reg2.get_cluster("c")
    assert info["epoch"] == 2 and info["plan_epoch"] == 2
    assert info["brokers"] == [0, 1, 2]
    with pytest.raises(wman.FencedEpoch):
        reg2.handle_event("c", {"type": "broker_drain", "epoch": 2,
                                "brokers": [2]})
    out = reg2.handle_event("c", {"type": "broker_add", "epoch": 3,
                                  "brokers": [3]})
    assert out["status"] == "planned" and out["epoch"] == 3
    assert calls2 == [3]
    assert reg2.list_clusters() == ["c"]


# --------------------------------------------------------------------------
# serve layer: the delta endpoints, fencing proof, storm 503, metrics
# --------------------------------------------------------------------------


@pytest.fixture
def watch_env(tmp_path, monkeypatch):
    monkeypatch.setitem(srv.WATCH, "dir", str(tmp_path / "watch"))
    monkeypatch.setitem(srv.WATCH, "registry", None)
    monkeypatch.setitem(srv.WATCH, "window_s", 0.0)
    monkeypatch.setitem(srv.WATCH, "max_backlog", 256)
    yield tmp_path
    srv.WATCH["registry"] = None


def _counter(text: str, name: str) -> float:
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[-1])
    raise AssertionError(f"{name} not in /metrics")


def test_delta_api_end_to_end_with_fencing_proof(watch_env):
    st, body = srv.handle_cluster_event("prod", _bootstrap(B=6))
    assert st == 200 and body["status"] == "planned"
    assert body["plan_epoch"] == 1
    assert body["report"]["feasible"]

    st, body = srv.handle_cluster_event(
        "prod", {"type": "broker_drain", "epoch": 2, "brokers": [5]},
    )
    assert st == 200
    plan = body["assignment"]
    assert all(5 not in p["replicas"] for p in plan["partitions"])

    # THE fencing proof: a replayed epoch returns a structured 409 and
    # provably runs no solve — the fence counter moves, the solve
    # counters do not, and no new trace is born
    m0 = srv.render_metrics()
    ids0 = list(otrace.RECENT.ids())
    with pytest.raises(srv.ApiError) as e:
        srv.handle_cluster_event(
            "prod", {"type": "broker_drain", "epoch": 2, "brokers": [4]},
        )
    assert e.value.status == 409
    assert e.value.body_extra["reason"] == "stale_epoch"
    assert e.value.body_extra["current_epoch"] == 2
    assert e.value.body_extra["expected_min_epoch"] == 3
    m1 = srv.render_metrics()
    assert _counter(m1, "kao_watch_fenced_total") \
        == _counter(m0, "kao_watch_fenced_total") + 1
    assert _counter(m1, "kao_watch_solves_total") \
        == _counter(m0, "kao_watch_solves_total")
    assert _counter(m1, "kao_solves_total") \
        == _counter(m0, "kao_solves_total")
    assert list(otrace.RECENT.ids()) == ids0

    # idempotence: the fenced event changed nothing, the stream
    # continues at the correct epoch
    info = srv.handle_clusters_get("prod")
    assert info["epoch"] == 2 and info["plan_epoch"] == 2
    st, _ = srv.handle_cluster_event(
        "prod", {"type": "broker_add", "epoch": 3, "brokers": [5]},
    )
    assert st == 200

    listing = srv.handle_clusters_get()
    assert "prod" in listing["clusters"]
    assert listing["watch"]["fenced_total"] >= 1


def test_delta_api_maps_errors(watch_env):
    with pytest.raises(srv.ApiError) as e:
        srv.handle_cluster_event("prod", {"type": "nope", "epoch": 1})
    assert e.value.status == 400
    with pytest.raises(srv.ApiError) as e:
        srv.handle_cluster_event("x/../y", _bootstrap())
    assert e.value.status == 400
    with pytest.raises(srv.ApiError) as e:
        srv.handle_clusters_get("never-bootstrapped")
    assert e.value.status == 404


def test_event_storm_503_has_retry_after_and_predeclared_reason(
        watch_env):
    """The satellite pin: ``event_storm`` is pre-declared in the
    kao_shed_total family (the PR 6 removed-but-referenced KeyError
    class of bug) and its 503 carries a Retry-After derived from the
    coalescing window."""
    assert "event_storm" in srv._SHED_REASON_NAMES
    baseline = srv.render_metrics()
    assert 'kao_shed_total{reason="event_storm"}' in baseline

    srv.WATCH["window_s"] = 0.25
    srv.WATCH["max_backlog"] = 1
    ev = threading.Event()

    def slow_solve(state, prev_plan, budget):
        ev.set()
        time.sleep(0.5)
        return state.assignment.to_dict(), {"feasible": True,
                                            "replica_moves": 0}

    srv.WATCH["registry"] = wman.WatchRegistry(
        slow_solve, None, window_s=0.25, max_backlog=1)
    t = threading.Thread(target=srv.handle_cluster_event,
                         args=("c", _bootstrap()))
    t.start()
    assert ev.wait(5)
    srv.handle_cluster_event(
        "c", {"type": "broker_drain", "epoch": 2, "brokers": [3]})
    with pytest.raises(srv.ApiError) as e:
        srv.handle_cluster_event(
            "c", {"type": "broker_add", "epoch": 3, "brokers": [3]})
    t.join()
    assert e.value.status == 503
    assert e.value.body_extra["reason"] == "event_storm"
    # Retry-After derives from the coalescing window, never zero
    assert e.value.retry_after_s >= 0.5
    assert e.value.body_extra["retry_after_s"] >= 0.5
    after = srv.render_metrics()
    assert _counter(after, 'kao_shed_total{reason="event_storm"}') \
        == _counter(baseline, 'kao_shed_total{reason="event_storm"}') + 1
    from tests.test_metrics_format import validate_prometheus

    validate_prometheus(after)


def test_healthz_and_metrics_carry_watch_state(watch_env):
    h = srv.handle_healthz()
    assert h["watch"]["dir"] == srv.WATCH["dir"]
    assert "events_total" in h["watch"]
    assert "checkpoint_files" in h["resilience"]
    text = srv.render_metrics()
    for fam in ("kao_watch_events_total", "kao_watch_fenced_total",
                "kao_watch_coalesced_total", "kao_watch_clusters",
                "kao_checkpoint_files"):
        assert fam in text


# --------------------------------------------------------------------------
# checkpoint-dir hygiene (satellite): GC on the maintenance path
# --------------------------------------------------------------------------


def test_checkpoint_gc_age_and_count_caps(tmp_path, monkeypatch):
    monkeypatch.setitem(srv.RESILIENCE, "checkpoint_dir", str(tmp_path))
    monkeypatch.setitem(srv.RESILIENCE, "checkpoint_max_files", 3)
    monkeypatch.setitem(srv.RESILIENCE, "checkpoint_max_age_s", 3600.0)
    now = time.time()
    for i in range(6):
        p = tmp_path / f"ck{i}.npz"
        p.write_bytes(b"x")
        # files 0-1 are ancient (age GC); 2-5 are fresh but over the
        # count cap, so the oldest fresh one goes too
        age = 7200 if i < 2 else 60 + i
        os.utime(p, (now - age, now - age))
    removed = srv._gc_checkpoints()
    assert removed == 3
    left = sorted(f.name for f in tmp_path.glob("*.npz"))
    assert left == ["ck2.npz", "ck3.npz", "ck4.npz"] or \
        left == ["ck3.npz", "ck4.npz", "ck5.npz"]
    assert len(left) == 3
    assert _counter(srv.render_metrics(), "kao_checkpoint_files") == 3
    # GC is inert when the feature is off, and never fatal on a
    # vanished dir
    monkeypatch.setitem(srv.RESILIENCE, "checkpoint_dir", None)
    assert srv._gc_checkpoints() == 0
    monkeypatch.setitem(srv.RESILIENCE, "checkpoint_dir",
                        str(tmp_path / "gone"))
    assert srv._gc_checkpoints() == 0


# --------------------------------------------------------------------------
# full-server kill -9 + restart (satellite + acceptance proof):
# real HTTP, real SIGKILL — the plan store and the solve checkpoint
# both survive and resume
# --------------------------------------------------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _http(method, url, payload=None, timeout=60):
    import urllib.error
    import urllib.request

    data = None if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"},
        method=method,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _start_server(port, ckpt_dir, watch_dir, timeout=120):
    proc = subprocess.Popen(
        [sys.executable, "-m", "kafka_assignment_optimizer_tpu.serve",
         "--port", str(port), "--checkpoint-dir", str(ckpt_dir),
         "--watch-dir", str(watch_dir), "--workers", "1",
         "--max-solve-s", "300"],
        cwd="/root/repo",
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    deadline = time.time() + timeout
    url = f"http://127.0.0.1:{port}"
    while time.time() < deadline:
        if proc.poll() is not None:
            raise AssertionError(f"server died rc={proc.returncode}")
        try:
            status, _ = _http("GET", url + "/healthz", timeout=5)
            if status == 200:
                return proc, url
        except Exception:
            time.sleep(0.2)
    proc.kill()
    raise AssertionError("server never became healthy")


@pytest.mark.soak
@pytest.mark.slow  # ~26 s: two server spawns (jax import + demo-bucket
# compile each) around a real SIGKILL. The nightly soak job runs it;
# tier-1 sits at ~800 s of an 870 s budget on a noisy container and
# cannot afford it. The durable-store restart semantics it exercises
# stay tier-1-covered by test_registry_restart_resumes_at_persisted_epoch
# (in-process) — this test adds the real-process kill -9 + HTTP layer.
def test_sigkill_restart_resumes_checkpoint_and_plan_store(tmp_path):
    """Start serve with --checkpoint-dir and --watch-dir, bootstrap a
    watched cluster, SIGKILL the process mid-solve, restart on the same
    dirs: the re-requested solve resumes from the checkpoint
    (complementing PR 6's worker-crash-only coverage) and the event
    stream resumes at the persisted epoch — a stale epoch still 409s
    across the restart."""
    port = _free_port()
    ckpt = tmp_path / "ckpt"
    watch = tmp_path / "watch"
    proc, url = _start_server(port, ckpt, watch)
    try:
        # 1) durable watch state before the crash (fast milp solve)
        status, body = _http(
            "POST", url + "/clusters/prod/events", _bootstrap(B=6))
        assert status == 200 and body["plan_epoch"] == 1
        status, body = _http(
            "POST", url + "/clusters/prod/events",
            {"type": "broker_drain", "epoch": 2, "brokers": [5]})
        assert status == 200

        # 2) a long annealing solve that will be killed mid-flight; the
        # engine checkpoints at every chunk boundary
        slow = {
            "assignment": demo_assignment().to_dict(),
            "brokers": "0-18", "topology": "even-odd", "solver": "tpu",
            "options": {"engine": "sweep", "rounds": 6000, "batch": 8,
                        "time_limit_s": 240},
        }
        t = threading.Thread(
            target=lambda: _http("POST", url + "/submit", slow,
                                 timeout=300),
            daemon=True,
        )
        t.start()
        deadline = time.time() + 90
        while time.time() < deadline:
            if list(ckpt.glob("*.npz")):
                break
            time.sleep(0.02)
        files = list(ckpt.glob("*.npz"))
        assert files, "no checkpoint appeared before the kill"
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()

    # 3) restart on the SAME dirs (a FRESH port: the killed listener's
    # socket can linger and durability lives in the dirs, not the port)
    proc, url = _start_server(_free_port(), ckpt, watch)
    try:
        # the plan store survived: state + plan at the persisted epoch,
        # the fence still holds, and the stream continues at epoch 3
        status, info = _http("GET", url + "/clusters/prod")
        assert status == 200
        assert info["epoch"] == 2 and info["plan_epoch"] == 2
        status, body = _http(
            "POST", url + "/clusters/prod/events",
            {"type": "broker_drain", "epoch": 2, "brokers": [4]})
        assert status == 409 and body["reason"] == "stale_epoch"
        status, body = _http(
            "POST", url + "/clusters/prod/events",
            {"type": "broker_add", "epoch": 3, "brokers": [5]})
        assert status == 200 and body["plan_epoch"] == 3

        # the solve checkpoint survived: the re-requested cluster
        # resumes from it instead of starting over
        fast = {
            "assignment": demo_assignment().to_dict(),
            "brokers": "0-18", "topology": "even-odd", "solver": "tpu",
            "options": {"engine": "sweep", "rounds": 4, "batch": 8,
                        "time_limit_s": 120},
        }
        status, body = _http("POST", url + "/submit", fast, timeout=300)
        assert status == 200
        assert body["report"]["solver_resumed_from_checkpoint"] is True
        assert body["report"]["feasible"]
    finally:
        proc.kill()
        proc.wait(timeout=30)


# --------------------------------------------------------------------------
# the event-day replay bench (soak tier; the nightly smoke gate)
# --------------------------------------------------------------------------


@pytest.mark.soak
@pytest.mark.slow  # ~3-4 min of subprocess solves: the nightly soak
# job runs it (-m soak selects on the soak marker); the tier-1 gate
# (-m 'not slow') must not pay for a bench re-run it already covers
# with the unit/e2e tests above
def test_replay_day_smoke_bench():
    """``bench.py --replay-day --smoke``, seeded: the warm column must
    be at-least-as-good at every paired event (quality_ok), the storm
    segment must coalesce with zero dropped events, and at least one
    delta solve must actually warm-start."""
    proc = subprocess.run(
        [sys.executable, "bench.py", "--replay-day", "--smoke",
         "--seed", "0"],
        capture_output=True, text=True, timeout=1200, cwd="/root/repo",
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["metric"] == "replay_day"
    assert "error" not in line, line
    assert line["quality_ok"] is True
    assert line["storm_dropped"] == 0
    assert line["storm_coalesced"] >= 1
    assert line["warm_solves"] >= 1
    assert line["warm_p50_s"] is not None


# --------------------------------------------------------------------------
# CLI --events replay
# --------------------------------------------------------------------------


def test_cli_events_replay_and_durable_resume(tmp_path):
    events = {
        "cluster_id": "cli",
        "events": [
            _bootstrap(B=6),
            {"type": "broker_drain", "epoch": 2, "brokers": [5]},
        ],
    }
    f = tmp_path / "events.json"
    f.write_text(json.dumps(events))
    wdir = tmp_path / "store"

    def run(*extra):
        return subprocess.run(
            [sys.executable, "-m", "kafka_assignment_optimizer_tpu",
             "--events", str(f), "--watch-dir", str(wdir),
             "--solver", "milp", *extra],
            capture_output=True, text=True, timeout=300,
            cwd="/root/repo",
        )

    proc = run()
    assert proc.returncode == 0, proc.stderr
    plan = json.loads(proc.stdout)
    assert all(5 not in p["replicas"] for p in plan["partitions"])
    assert "status=planned" in proc.stderr

    # replaying the SAME file against the durable store: every epoch is
    # stale now — all fenced, nothing re-solved, rc=3
    proc2 = run()
    assert proc2.returncode == 3
    assert proc2.stderr.count("FENCED") == 2
