"""TPU annealing-engine tests (CPU backend, 8 virtual devices).

Covers SURVEY.md §4: golden demo via the tpu solver, incremental-vs-full
score consistency (the engine's O(1) deltas against the XLA scorer and the
numpy oracle), feasibility property tests on random clusters, and
cross-solver parity with the exact MILP backend."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafka_assignment_optimizer_tpu import build_instance, optimize
from kafka_assignment_optimizer_tpu.models.cluster import (
    Assignment,
    PartitionAssignment,
    Topology,
)
from kafka_assignment_optimizer_tpu.ops.score import score_batch, score_one
from kafka_assignment_optimizer_tpu.solvers.tpu import arrays
from kafka_assignment_optimizer_tpu.solvers.tpu.anneal import (
    best_key,
    init_chain,
    make_round_runner,
)
from kafka_assignment_optimizer_tpu.solvers.tpu.seed import greedy_seed


def random_cluster(rng, n_brokers, n_parts, rf, n_racks, drop=0):
    parts = []
    for p in range(n_parts):
        reps = rng.choice(n_brokers, size=rf, replace=False).tolist()
        parts.append(PartitionAssignment("t", p, [int(b) for b in reps]))
    topo = Topology(rack_of={b: f"r{b % n_racks}" for b in range(n_brokers)})
    brokers = list(range(n_brokers - drop))
    return Assignment(partitions=parts), brokers, topo


def test_seed_feasible_and_minimal_on_demo(demo):
    current, brokers, topo = demo
    inst = build_instance(current, brokers, topo)
    a = greedy_seed(inst)
    assert inst.is_feasible(a)
    assert inst.move_count(a) == 1  # greedy already finds the optimum here


def test_xla_scorer_matches_numpy_oracle(rng):
    current, brokers, topo = random_cluster(rng, 12, 20, 3, 3, drop=1)
    inst = build_instance(current, brokers, topo)
    m = arrays.from_instance(inst)
    for _ in range(5):
        a = rng.integers(0, inst.num_brokers, size=inst.a0.shape).astype(np.int32)
        s = score_one(jnp.asarray(a), m)
        v = inst.violations(a)
        assert int(s.pen_broker) == v["broker_balance"]
        assert int(s.pen_leader) == v["leader_balance"]
        assert int(s.pen_rack) == v["rack_balance"]
        assert int(s.pen_part_rack) == v["part_rack_diversity"]
        assert int(s.weight) == inst.preservation_weight(a)


@pytest.mark.soak
def test_incremental_deltas_track_full_score(rng):
    """After thousands of accepted moves of all three types, the chain's
    running (w, pen, counts) must equal a from-scratch rescoring."""
    current, brokers, topo = random_cluster(rng, 10, 16, 3, 2, drop=1)
    inst = build_instance(current, brokers, topo)
    m = arrays.from_instance(inst)
    seed = jnp.asarray(greedy_seed(inst), jnp.int32)

    run_round = make_round_runner(steps_per_round=500, axis_name=None)
    n = 8
    keys = jax.random.split(jax.random.PRNGKey(42), n)
    state = jax.vmap(lambda k: init_chain(m, seed, k))(keys)
    bk = jnp.full((n,), jnp.iinfo(jnp.int32).min, jnp.int32)
    ba = jnp.broadcast_to(seed, (n, *seed.shape))
    for temp in [3.0, 1.0, 0.3]:  # high temp: plenty of accepted moves
        state, bk, ba = jax.jit(run_round)(m, state, bk, ba, jnp.float32(temp))

    full = score_batch(state.a, m)
    np.testing.assert_array_equal(np.asarray(state.w), np.asarray(full.weight))
    np.testing.assert_array_equal(np.asarray(state.pen), np.asarray(full.penalty))
    np.testing.assert_array_equal(np.asarray(state.cnt), np.asarray(full.cnt))
    np.testing.assert_array_equal(np.asarray(state.lcnt), np.asarray(full.lcnt))
    np.testing.assert_array_equal(np.asarray(state.rcnt), np.asarray(full.rcnt))
    # every chain keeps partitions duplicate-free (hard-encoded C8)
    for i in range(n):
        v = inst.violations(np.asarray(state.a[i]))
        assert v["duplicate_in_partition"] == 0
        assert v["null_in_valid_slot"] == 0
    # best snapshots rank correctly
    assert (np.asarray(bk) >= np.asarray(best_key(state)).min()).all()


def test_tpu_solver_demo_golden(demo):
    current, brokers, topo = demo
    res = optimize(current, brokers, topo, solver="tpu",
                   batch=16, rounds=6, steps_per_round=200)
    rep = res.report()
    assert rep["feasible"], rep
    assert res.replica_moves == 1
    assert res.solve.objective == res.instance.max_weight()


@pytest.mark.parametrize("case", [
    dict(n_brokers=8, n_parts=12, rf=2, n_racks=2, drop=1),
    dict(n_brokers=9, n_parts=10, rf=3, n_racks=3, drop=0),
    dict(n_brokers=12, n_parts=18, rf=2, n_racks=4, drop=2),
])
def test_property_feasible_plans_random_clusters(case, rng):
    current, brokers, topo = random_cluster(rng, **case)
    res = optimize(current, brokers, topo, solver="tpu",
                   batch=16, rounds=8, steps_per_round=300)
    rep = res.report()
    assert rep["feasible"], rep
    # replica lists well-formed: right RF, unique brokers, eligible only
    for p in res.assignment.partitions:
        assert len(p.replicas) == len(set(p.replicas))
        assert set(p.replicas) <= set(brokers)


def test_cross_solver_parity_small(rng):
    """North-star quality gate (SURVEY.md §4.4): on exactly solvable
    instances the search must reach the ILP optimum."""
    current, brokers, topo = random_cluster(rng, 8, 10, 2, 2, drop=1)
    exact = optimize(current, brokers, topo, solver="milp")
    search = optimize(current, brokers, topo, solver="tpu",
                      batch=24, rounds=10, steps_per_round=400)
    assert search.report()["feasible"]
    assert search.replica_moves <= exact.replica_moves
    assert search.solve.objective == exact.solve.objective


def test_leader_only_rebalance_zero_replica_moves():
    """BASELINE.json config 5: skewed leadership, balanced replicas —
    the optimizer must fix leader skew with zero replica moves."""
    # 6 brokers, 12 partitions RF=2, all leaders piled on brokers 0..2
    parts = []
    for p in range(12):
        lead = p % 3
        foll = 3 + (p % 3)
        parts.append(PartitionAssignment("t", p, [lead, foll]))
    current = Assignment(partitions=parts)
    topo = Topology.single_rack(range(6))
    res = optimize(current, list(range(6)), topo, solver="tpu",
                   batch=16, rounds=8, steps_per_round=300)
    rep = res.report()
    assert rep["feasible"], rep
    assert res.replica_moves == 0
    assert res.moves.leader_changes > 0  # skew actually fixed


@pytest.mark.soak
@pytest.mark.slow  # ~25 s; inherently wall-clock bound (warm-up
# compile + timed re-solve). Nightly; tier-1 keeps the deterministic
# deadline rung pin (test_cancelled_budget_retires_ladder...).
def test_time_limit_is_honored(rng):
    """VERDICT r1 item 4: --time-limit must cap the solve. The schedule
    runs in equal clock-checked chunks; after a warm-up compile, a tight
    budget must cut the sweep count short and still return a feasible
    best-so-far plan with a timed_out stat."""
    current, brokers, topo = random_cluster(rng, 16, 60, 3, 4, drop=1)
    kw = dict(solver="tpu", engine="sweep", batch=8, seed=0)
    # warm-up: compiles the chunked executable for this shape
    optimize(current=current, broker_list=brokers, topology=topo,
             sweeps=4000, time_limit_s=600.0, **kw)
    t0 = __import__("time").perf_counter()
    res = optimize(current=current, broker_list=brokers, topology=topo,
                   sweeps=4000, time_limit_s=0.5, **kw)
    wall = __import__("time").perf_counter() - t0
    st = res.solve.stats
    assert st["timed_out"] is True
    assert st["rounds_run"] < 4000
    assert res.report()["feasible"] is True
    # warm, the overshoot is at most ~one chunk + polish; be generous to
    # CI noise but still catch "limit ignored" (which would run all 400)
    assert wall < 6.0, wall


@pytest.mark.soak
def test_no_time_limit_runs_all_rounds(rng):
    current, brokers, topo = random_cluster(rng, 12, 24, 2, 2, drop=1)
    res = optimize(current=current, broker_list=brokers, topology=topo,
                   solver="tpu", engine="chain", batch=8, rounds=6, seed=0)
    st = res.solve.stats
    assert st["timed_out"] is False
    assert st["rounds_run"] == 6
    assert st["steps_per_round_ignored"] is False


def test_mesh_size_invariance(rng):
    """SURVEY.md §7 hard part 5 / VERDICT r1 item 8: the same instance +
    seed solved over n_devices ∈ {1, 2, 8} must produce a feasible plan
    of equivalent quality on every mesh size (no crash, no sharding bug,
    no quality cliff). Trajectories legitimately differ — per-device RNG
    streams depend on the mesh — so the pin is exact quality, not bytes:
    this instance is exactly solvable, and every mesh size must reach
    the ILP optimum."""
    current, brokers, topo = random_cluster(rng, 8, 12, 2, 2, drop=1)
    exact = optimize(current, brokers, topo, solver="milp")
    for n_dev in (1, 2, 8):
        res = optimize(current, brokers, topo, solver="tpu", seed=11,
                       batch=24, rounds=10, steps_per_round=400,
                       n_devices=n_dev)
        rep = res.report()
        assert rep["feasible"], (n_dev, rep)
        assert res.replica_moves <= exact.replica_moves, (n_dev, rep)
        assert res.solve.objective == exact.solve.objective, (n_dev, rep)


@pytest.mark.soak
@pytest.mark.slow  # ~40 s; nightly. Tier-1 keeps the chain-engine
# exactness pin above plus the 8-device split-parity pins in
# test_mesh_sharding.py (ISSUE 19 re-tier).
def test_mesh_size_invariance_sweep_engine(rng):
    """Same pin for the sweep engine (the at-scale path): forced
    engine='sweep' across mesh sizes stays feasible and within one move
    / one weight unit of the ILP optimum. Exactness is NOT pinned here:
    a stochastic engine sized for 10k-partition instances can park in a
    1-move local optimum on a 14-partition toy, and which mesh size does
    so is a seed artifact, not a sharding bug (the chain-engine test
    above pins exactness on the small-instance default path)."""
    current, brokers, topo = random_cluster(rng, 10, 14, 2, 2, drop=1)
    exact = optimize(current, brokers, topo, solver="milp")
    for n_dev in (1, 2, 8):
        res = optimize(current, brokers, topo, solver="tpu", seed=5,
                       engine="sweep", batch=32, rounds=96,
                       n_devices=n_dev)
        rep = res.report()
        assert rep["feasible"], (n_dev, rep)
        assert res.replica_moves <= exact.replica_moves + 1, (n_dev, rep)
        assert res.solve.objective >= exact.solve.objective - 1, (n_dev, rep)


@pytest.mark.soak
def test_sweep_infeasible_falls_back_to_chain(monkeypatch):
    """Ultra-tight instance (exact rack bands + per-partition diversity
    1 at RF=4 over 5 racks) that defeats the sweep engine's parallel
    moves: a DEFAULTED sweep that ends infeasible must retry with the
    chain engine and return a feasible plan (regression for a fuzz
    find). On CPU the defaulted engine would be chain (the branch under
    test would never run), so TPU's engine choice is simulated by
    patching _defaults — exactly what a real TPU run does."""
    import numpy as np

    from kafka_assignment_optimizer_tpu.api import optimize
    from kafka_assignment_optimizer_tpu.models.cluster import (
        Assignment,
        PartitionAssignment,
        Topology,
    )
    from kafka_assignment_optimizer_tpu.solvers.tpu import engine as eng

    orig_defaults = eng._defaults

    def tpu_like_defaults(inst, platform, engine):
        if engine is None:  # default choice: sweep, as on TPU
            d = orig_defaults(inst, platform, "sweep")
            d["rounds"] = 64
            return d
        return orig_defaults(inst, platform, engine)

    monkeypatch.setattr(eng, "_defaults", tpu_like_defaults)

    # the fuzz-found instance: 12 brokers over 5 racks (sizes 3/3/2/2/2),
    # RF=4 -> every partition needs 4 DISTINCT racks and the rack bands
    # are near-exact
    rng = np.random.default_rng(20260730)
    n_b, n_racks, n_p, rf = 12, 5, 61, 4
    topo = Topology.from_dict(
        {str(b): f"r{b % n_racks}" for b in range(n_b)}
    )
    parts = [
        PartitionAssignment(
            topic="t", partition=p,
            replicas=rng.choice(n_b, size=rf, replace=False).tolist(),
        )
        for p in range(n_p)
    ]
    r = optimize(
        Assignment(partitions=parts), list(range(n_b)), topo,
        solver="tpu", seed=0,
    )
    s = r.solve.stats
    assert s["feasible"], s
    # either the (patched-default) sweep solved it, or the net fired and
    # the chain engine rescued it — both end feasible; the fallback must
    # be recorded when the final engine is not the defaulted sweep
    if s["engine"] == "chain":
        assert s["engine_fallback"]


def test_adversarial_scenario_is_constructor_proof():
    """VERDICT r3 item 2: the adversarial scenario (shuffled mixed-RF
    decommission) must defeat every constructor shortcut — caps slack
    (no LP race), aggregation refused (every partition its own class) —
    and still be solved AND proven optimal by the sweep annealer
    itself, matching the exact MILP oracle."""
    from kafka_assignment_optimizer_tpu.utils import gen

    sc = gen.SCENARIOS["adversarial"](**gen.SMOKE_KWARGS["adversarial"])
    inst = build_instance(sc.current, sc.broker_list, sc.topology,
                          target_rf=sc.target_rf)
    assert not inst.caps_bind()
    assert not inst.agg_effective()
    # the shuffle really did break symmetry: nearly one class per member
    members = inst._members()[0].size
    n_cm = inst._member_classes()[3].size
    assert n_cm * 8 > members
    # pin the sweep engine: it is the TPU default at every size (the
    # bench row this test backs runs it), but pytest's pinned-CPU env
    # would default the 200-partition smoke shape to the chain engine
    r = optimize(solver="tpu", seed=0, engine="sweep", **sc.kwargs)
    s = r.solve.stats
    assert s["engine"] == "sweep"
    assert not s["constructed"]
    assert s["feasible"]
    assert s["proved_optimal"]
    assert s["moves"] == sc.min_moves_lb
    ex = optimize(solver="milp", **sc.kwargs)
    assert r.solve.objective == ex.solve.objective


@pytest.mark.soak
def test_adversarial_full_scale_gates():
    """The FULL-SIZE adversarial instance (256 brokers / 10k
    partitions) keeps the same gate profile — no solve here, just the
    instance-level facts the benchmark row's meaning rests on."""
    from kafka_assignment_optimizer_tpu.utils import gen

    sc = gen.SCENARIOS["adversarial"]()
    inst = build_instance(sc.current, sc.broker_list, sc.topology,
                          target_rf=sc.target_rf)
    assert inst.num_parts == 10_000
    assert inst.num_brokers == 255
    assert not inst.caps_bind()
    assert not inst.agg_effective()
    assert sc.min_moves_lb == inst.move_lower_bound()


@pytest.mark.soak
def test_adv50k_full_scale_gates():
    """The FULL-SIZE adv50k instance (512 brokers / 50k partitions,
    149,600 replica slots) keeps the constructor-proof gate profile at
    5x the headline scale — instance-level facts only, no solve."""
    from kafka_assignment_optimizer_tpu.utils import gen

    sc = gen.SCENARIOS["adv50k"]()
    inst = build_instance(sc.current, sc.broker_list, sc.topology,
                          target_rf=sc.target_rf)
    assert inst.num_parts == 50_000
    assert inst.num_brokers == 511
    assert inst.total_replicas == 149_600
    assert not inst.caps_bind()
    assert not inst.agg_effective()
    # big + barely-collapsing: the aggregated constructor must refuse
    # outright rather than race a futile MILP
    assert not inst.agg_construct_viable()
    assert sc.min_moves_lb == inst.move_lower_bound()


@pytest.mark.soak
def test_adv50k_full_scale_default_certifies_via_reseat():
    """The FULL-SIZE adv50k default path: the greedy+reseat racer
    alone produces the certified optimum of the 50k-partition shuffled
    mixed-RF decommission — host CPU only, no device, a few seconds
    (the README's 6.4-8.6 s default-path claim rests on this)."""
    from kafka_assignment_optimizer_tpu.solvers.tpu.engine import (
        _BoundsTask,
        _construct_worker,
    )
    from kafka_assignment_optimizer_tpu.utils import gen

    sc = gen.SCENARIOS["adv50k"]()
    inst = build_instance(sc.current, sc.broker_list, sc.topology,
                          target_rf=sc.target_rf)
    bounds = _BoundsTask(
        lambda: (inst.move_lower_bound_exact(), inst.weight_upper_bound())
    )
    # the route solve_tpu actually takes for adv50k: past the
    # aggregation threshold into _construct_worker, whose agg-refusal
    # fallback dispatches the reseat racer. Guard the precondition —
    # if generator drift ever makes aggregation viable here, the call
    # below would grind the aggregated MILP for minutes; fail fast
    # with a diagnosis instead
    assert not inst.agg_construct_viable(), (
        "adv50k generator drift: aggregation became viable, the "
        "reseat-fallback route is no longer exercised"
    )
    plan, ok, *_rest = _construct_worker(inst, bounds,
                                         reseat_fallback=True)
    assert ok, "reseat racer failed to certify the full-size adv50k"
    assert inst._construct_path == "reseat"
    assert inst.is_feasible(plan)
    assert inst.move_count(plan) == sc.min_moves_lb


def test_adv50k_smoke_solves_proven():
    """The shrunk adv50k config (bench --smoke) keeps the generator
    invariants and is solved feasible + proven by the sweep engine —
    the same contract the full-size bench row rests on."""
    from kafka_assignment_optimizer_tpu.utils import gen

    sc = gen.SCENARIOS["adv50k"](**gen.SMOKE_KWARGS["adv50k"])
    inst = build_instance(sc.current, sc.broker_list, sc.topology,
                          target_rf=sc.target_rf)
    assert not inst.caps_bind()
    r = optimize(solver="tpu", seed=0, engine="sweep", **sc.kwargs)
    s = r.solve.stats
    assert s["engine"] == "sweep"
    assert s["feasible"]
    assert s["proved_optimal"]
    assert s["moves"] == sc.min_moves_lb


def test_adversarial_default_certifies_via_reseat_race():
    """A DEFAULTED solve of the adversarial class (slack caps, no
    symmetry, too big for the exact MILP) wins the greedy+reseat race:
    certified optimum, zero device work, no compile (r4 — the default
    adv50k solve drops from ~12 s warm / ~80 s cold to ~5 s)."""
    from kafka_assignment_optimizer_tpu.utils import gen

    sc = gen.SCENARIOS["adversarial"](**gen.SMOKE_KWARGS["adversarial"])
    r = optimize(solver="tpu", seed=0, **sc.kwargs)
    s = r.solve.stats
    assert s["constructed"]
    assert s["construct_path"] == "reseat"
    assert s["engine"] == "construct"
    assert s["proved_optimal"]
    assert s["rounds_run"] == 0
    assert s["moves"] == sc.min_moves_lb


def test_adversarial_engine_knob_opts_out_of_reseat_race():
    """An explicit engine knob means the caller wants the search: the
    same instance anneals on the sweep engine (still to proven
    optimality) — the contract the bench's at-scale search rows rest
    on (engine: "sweep", constructed: false)."""
    from kafka_assignment_optimizer_tpu.utils import gen

    sc = gen.SCENARIOS["adversarial"](**gen.SMOKE_KWARGS["adversarial"])
    r = optimize(solver="tpu", seed=0, engine="sweep", **sc.kwargs)
    s = r.solve.stats
    assert s["engine"] == "sweep"
    assert not s["constructed"]
    assert s["proved_optimal"]


def test_certified_solve_skips_polish(monkeypatch):
    """Certify-first final selection: a sweep solve whose champion
    (plus at most one exact leader reseat) meets both bounds must never
    EXECUTE the steepest-descent polish — at 50k partitions that
    execution is ~a minute of dead weight on a proven optimum (the
    measured r4 cost of polishing the already-optimal adv50k champion).
    The AOT compile thread may still run; only __call__ is the waste."""
    from kafka_assignment_optimizer_tpu.solvers.tpu import polish as pol_mod
    from kafka_assignment_optimizer_tpu.utils import gen

    calls = []
    real = pol_mod.polish_jit

    class Spy:
        def __call__(self, *a, **k):
            calls.append("run")
            return real(*a, **k)

        def lower(self, *a, **k):
            # poison the AOT path: the engine's overlapped compile then
            # fails and any polish EXECUTION must fall back to
            # __call__ above — so a regressed certify-first (polish
            # running on a certified solve) cannot slip through the
            # compiled executable unseen
            raise RuntimeError("AOT polish disabled by test")

    monkeypatch.setattr(pol_mod, "polish_jit", Spy())
    sc = gen.SCENARIOS["adversarial"](**gen.SMOKE_KWARGS["adversarial"])
    r = optimize(solver="tpu", seed=0, engine="sweep", **sc.kwargs)
    assert r.solve.stats["proved_optimal"]
    assert calls == []


@pytest.mark.parametrize("seed", [7, 11, 23, 101])
def test_adversarial_generator_invariants(seed):
    """The adversarial generator's gate profile must hold for ANY seed,
    not just the shipped default: exact per-broker balance inside the
    post-removal bands (caps slack), leader counts in band, rack-diverse
    partitions, and enough symmetry classes that aggregation refuses."""
    from kafka_assignment_optimizer_tpu.utils import gen

    sc = gen.adversarial(seed=seed, **gen.SMOKE_KWARGS["adversarial"])
    inst = build_instance(sc.current, sc.broker_list, sc.topology,
                          target_rf=sc.target_rf)
    assert not inst.caps_bind()
    assert not inst.agg_effective()
    # the current assignment itself is a feasible steady state of the
    # PRE-removal cluster: every partition rack-diverse, no duplicates
    for p in sc.current.partitions:
        assert len(p.replicas) == len(set(p.replicas))
        racks = [sc.topology.rack(b) for b in p.replicas]
        assert len(racks) == len(set(racks))
    # leader counts sit inside the band valid before AND after the
    # removal (the docstring's claim, asserted directly)
    from collections import Counter

    n_p = len(sc.current.partitions)
    B = len(sc.broker_list) + 1
    lo_t = n_p // (B - 1) if (n_p // (B - 1)) * B <= n_p else n_p // B
    hi_t = max(-(-n_p // B), lo_t)
    lcnt = Counter(p.replicas[0] for p in sc.current.partitions)
    assert all(lo_t <= lcnt.get(b, 0) <= hi_t
               for b in range(B)), dict(lcnt)
    # the removal's move lower bound equals the dropped broker's load
    assert sc.min_moves_lb == inst.move_lower_bound()
