"""CLI end-to-end: demo JSON on stdin -> optimal plan on stdout
(the reference's batch UX, README.md:35-48)."""

import json
import subprocess
import sys

from kafka_assignment_optimizer_tpu.models.cluster import demo_assignment


def run_cli(args, stdin_text):
    return subprocess.run(
        [sys.executable, "-m", "kafka_assignment_optimizer_tpu", *args],
        input=stdin_text,
        capture_output=True,
        text=True,
        timeout=300,
        cwd="/root/repo",
    )


def test_cli_demo_golden(tmp_path):
    proc = run_cli(
        [
            "--broker-list", "0-18",
            "--topology", "even-odd",
            "--solver", "milp",
            "--report",
            "--emit-lp", str(tmp_path / "model.lp"),
        ],
        demo_assignment().to_json(),
    )
    assert proc.returncode == 0, proc.stderr
    plan = json.loads(proc.stdout)
    by_part = {p["partition"]: p["replicas"] for p in plan["partitions"]}
    assert by_part[1][0] == 8 and 19 not in by_part[1]
    report = json.loads(proc.stderr)
    assert report["replica_moves"] == 1
    assert report["feasible"] is True
    lp_text = (tmp_path / "model.lp").read_text()
    assert lp_text.startswith("// Optimization function")


def test_cli_infeasible_inputs_error():
    proc = run_cli(["--broker-list", "0"], demo_assignment().to_json())
    assert proc.returncode != 0


def test_cli_per_topic_rf():
    """--rf accepts a topic->RF JSON object: only the listed topic
    grows, others keep their current RF."""
    current = {
        "version": 1,
        "partitions": [
            {"topic": "logs", "partition": 0, "replicas": [0, 1]},
            {"topic": "logs", "partition": 1, "replicas": [2, 3]},
            {"topic": "metrics", "partition": 0, "replicas": [4, 5]},
        ],
    }
    proc = run_cli(
        ["--broker-list", "0-7", "--solver", "milp",
         "--rf", '{"logs": 3}'],
        json.dumps(current),
    )
    assert proc.returncode == 0, proc.stderr
    plan = json.loads(proc.stdout)
    by_key = {(p["topic"], p["partition"]): p["replicas"]
              for p in plan["partitions"]}
    assert len(by_key[("logs", 0)]) == 3
    assert len(by_key[("logs", 1)]) == 3
    assert len(by_key[("metrics", 0)]) == 2

    # malformed --rf -> clean error, exit 2
    proc = run_cli(
        ["--broker-list", "0-7", "--rf", '{"logs": "three"}'],
        json.dumps(current),
    )
    assert proc.returncode == 2
    assert "topic->int" in proc.stderr


def test_cli_rf_error_paths():
    current = {
        "version": 1,
        "partitions": [{"topic": "logs", "partition": 0, "replicas": [0, 1]}],
    }
    # typo'd topic must fail loudly, not silently no-op
    proc = run_cli(
        ["--broker-list", "0-7", "--rf", '{"lgs": 3}'],
        json.dumps(current),
    )
    assert proc.returncode == 2
    assert "unknown topic" in proc.stderr
    # a mistyped file path must name --rf in the error
    proc = run_cli(
        ["--broker-list", "0-7", "--rf", "rf.jsonn"],
        json.dumps(current),
    )
    assert proc.returncode == 2
    assert "--rf" in proc.stderr
